"""Resident-scene cache: compiled scenes + their jit closures, LRU by
HBM footprint.

The paper's master keeps ONE scene loaded per worker process; a serving
master multiplexing many renders must instead keep the HOT scenes
resident — a scene compile (BVH build, material/texture baking,
device upload) plus the first jit trace of its chunk program costs
orders of magnitude more than rendering one chunk, so a repeat submit of
a warm scene must pay ZERO of either. Residency here means three
coupled things:

- the `CompiledScene` (whose `dev` dict is the HBM-resident geometry /
  material / texture tables),
- the integrator instance bound to it — the single-slot jit-closure
  cache (`WavefrontIntegrator._jit_cache`, the PR 2 `_cache_size` audit
  contract) lives ON the integrator, so keeping the pair together is
  what makes a warm resubmit report 0 jit recompiles,
- the accounting to evict cold entries when the footprint budget is
  exceeded (LRU by a monotonic touch counter — never wall clock, so
  eviction order is deterministic and replayable).

Entries are keyed by the scene SOURCE (file path + mtime/size, or a
content hash for inline text): that key is known before compiling, which
is what lets a hit skip the compile entirely. The render-config
fingerprint (`parallel/checkpoint.render_fingerprint`) of every plan
built against the entry is indexed alongside, so jobs, checkpoints and
cache entries all speak the same identity.

Pinning: a scene referenced by a live (queued/active/parked) job cannot
be evicted — eviction only reclaims unpinned entries, and an over-budget
cache of pinned scenes stays over budget (loudly, via stats) rather
than corrupting a running job.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from tpu_pbrt.obs.metrics import METRICS
from tpu_pbrt.utils.clock import WALL

#: film-accumulator bytes per pixel: FilmState rgb + weight + splat,
#: all f32. hbmcheck's HC-ACCT cross-checks this against the LIVE
#: FilmState layout — a new film plane that forgets to bump it would
#: make the LRU evict on wrong numbers
FILM_BYTES_PER_PIXEL = 4 * (3 + 1 + 3)


def scene_hbm_bytes(scene) -> int:
    """Device-resident footprint of a compiled scene: every array leaf
    of the `dev` pytree (geometry, BVH stream tables, materials,
    texture atlas, light tables) plus one film-state allocation (the
    accumulator a job of this scene will hold)."""
    total = 0
    for leaf in jax.tree.leaves(scene.dev):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = int(np.size(leaf))
            nbytes = size * getattr(
                getattr(leaf, "dtype", np.float32), "itemsize", 4
            )
        total += int(nbytes)
    rx, ry = scene.film.full_resolution
    total += rx * ry * FILM_BYTES_PER_PIXEL
    return total


def scene_source_key(
    path: Optional[str] = None, text: Optional[str] = None,
    extra: Tuple = (),
) -> str:
    """Residency key computable BEFORE compiling: file identity
    (abspath + mtime_ns + size — a rewritten file is a different scene)
    or a content hash for inline text, plus `extra` (render-affecting
    option overrides like crop/quick, which change the compiled film)."""
    h = hashlib.sha1()
    if path is not None:
        p = os.path.abspath(path)
        st = os.stat(p)
        h.update(f"file:{p}:{st.st_mtime_ns}:{st.st_size}".encode())
    elif text is not None:
        h.update(b"text:")
        h.update(text.encode())
    else:
        raise ValueError("scene_source_key needs a path or text")
    for item in extra:
        h.update(f":{item}".encode())
    return h.hexdigest()[:16]


@dataclass
class ResidentScene:
    """One cache entry: the compiled pair + accounting."""

    key: str
    scene: Any
    integrator: Any
    hbm_bytes: int
    compile_seconds: float
    pins: int = 0
    last_used: int = 0  # monotonic touch counter (deterministic LRU)
    hits: int = 0
    #: render_fingerprints of plans built against this entry (grows as
    #: jobs with different slice widths schedule on it)
    fingerprints: set = field(default_factory=set)


class ResidencyCache:
    """LRU-by-HBM-footprint cache of ResidentScene entries."""

    def __init__(self, max_bytes: Optional[int] = None, clock=None):
        self.max_bytes = max_bytes
        #: time source for compile-duration measurement only. The LRU
        #: order below runs on `_clock`, the integer touch counter —
        #: never this — so virtual-time harness runs and wall-clock
        #: serving evict in the same order.
        self.clock = clock if clock is not None else WALL
        self._entries: Dict[str, ResidentScene] = {}
        self._clock = 0
        self.scene_compiles = 0
        self.hits = 0
        self.evictions = 0

    # -- core --------------------------------------------------------------
    def _touch(self, ent: ResidentScene) -> None:
        self._clock += 1
        ent.last_used = self._clock

    def get(self, key: str) -> Optional[ResidentScene]:
        ent = self._entries.get(key)
        if ent is not None:
            self._touch(ent)
        return ent

    def get_or_compile(
        self, key: str, builder: Callable[[], Tuple[Any, Any]],
    ) -> ResidentScene:
        """The submit path: a hit costs a dict lookup; a miss runs
        `builder() -> (scene, integrator)` (parse + compile + upload),
        inserts, and evicts cold unpinned entries past the budget."""
        ent = self._entries.get(key)
        if ent is not None:
            ent.hits += 1
            self.hits += 1
            METRICS.counter(
                "residency_hits_total",
                "submits served from a resident compiled scene",
            ).inc()
            self._touch(ent)
            return ent
        t0 = self.clock.monotonic()
        scene, integ = builder()
        self.scene_compiles += 1
        METRICS.counter(
            "residency_misses_total",
            "submits that paid a scene compile",
        ).inc()
        ent = ResidentScene(
            key=key, scene=scene, integrator=integ,
            hbm_bytes=scene_hbm_bytes(scene),
            compile_seconds=self.clock.monotonic() - t0,
        )
        self._entries[key] = ent
        self._touch(ent)
        # the entry being handed back must survive this call's eviction
        # even when it alone exceeds the budget (the caller is about to
        # pin and use it; evicting it here would dangle the reference)
        ent.pins += 1
        try:
            self.evict_over_budget()
        finally:
            ent.pins -= 1
        return ent

    def find_by_fingerprint(self, fingerprint: str) -> Optional[ResidentScene]:
        """Entry whose compiled plans include this render fingerprint
        (`parallel/checkpoint.render_fingerprint`) — the lookup that
        lets a checkpoint written by another process resume onto an
        already-resident scene without recompiling."""
        for ent in self._entries.values():
            if fingerprint in ent.fingerprints:
                self._touch(ent)
                return ent
        return None

    # -- pinning / eviction ------------------------------------------------
    def pin(self, key: str) -> None:
        self._entries[key].pins += 1

    def unpin(self, key: str) -> None:
        ent = self._entries.get(key)
        if ent is not None and ent.pins > 0:
            ent.pins -= 1

    def total_bytes(self) -> int:
        return sum(e.hbm_bytes for e in self._entries.values())

    def evict_over_budget(self) -> int:
        """Evict least-recently-used UNPINNED entries until the total
        footprint fits max_bytes (no-op when unbudgeted). Returns the
        number of entries evicted. Dropping the entry releases the last
        strong refs to scene.dev and the integrator's jit closure — jax
        frees the device buffers when the arrays are collected."""
        self._footprint_gauges()
        if self.max_bytes is None:
            return 0
        n = 0
        while self.total_bytes() > self.max_bytes:
            victims = [
                e for e in self._entries.values() if e.pins == 0
            ]
            if not victims:
                break  # everything pinned: stay over budget, loudly
            coldest = min(victims, key=lambda e: e.last_used)
            del self._entries[coldest.key]
            self.evictions += 1
            METRICS.counter(
                "residency_evicted_bytes_total",
                "HBM bytes reclaimed by LRU scene eviction",
            ).inc(coldest.hbm_bytes)
            n += 1
        if n:
            self._footprint_gauges()
        return n

    def _footprint_gauges(self) -> None:
        if not METRICS.enabled:
            return
        METRICS.gauge(
            "residency_resident_bytes",
            "HBM footprint of the resident compiled scenes",
        ).set(self.total_bytes())
        METRICS.gauge(
            "residency_entries", "resident compiled scenes"
        ).set(len(self._entries))

    def release(self, key: str) -> bool:
        """Drop an entry outright regardless of LRU order (explicit
        invalidation); refuses while pinned. Returns whether dropped."""
        ent = self._entries.get(key)
        if ent is None or ent.pins > 0:
            return False
        del self._entries[key]
        self.evictions += 1
        METRICS.counter(
            "residency_evicted_bytes_total",
            "HBM bytes reclaimed by LRU scene eviction",
        ).inc(ent.hbm_bytes)
        self._footprint_gauges()
        return True

    # -- introspection -----------------------------------------------------
    def pin_counts(self) -> Dict[str, int]:
        """key -> live pin count. The protocol checker's pin-balance
        invariant reads this after every decision: each key's pins must
        equal the number of non-terminal jobs holding it, and every
        count must be zero once all jobs are terminal (a leak here is a
        scene the LRU can never evict)."""
        return {k: e.pins for k, e in self._entries.items()}

    def stats(self) -> Dict[str, Any]:
        return {
            "entries": len(self._entries),
            "resident_bytes": self.total_bytes(),
            "max_bytes": self.max_bytes,
            "scene_compiles": self.scene_compiles,
            "hits": self.hits,
            "evictions": self.evictions,
            "scenes": {
                e.key: {
                    "hbm_bytes": e.hbm_bytes,
                    "pins": e.pins,
                    "hits": e.hits,
                    "compile_seconds": round(e.compile_seconds, 3),
                }
                for e in sorted(
                    self._entries.values(), key=lambda e: -e.last_used
                )
            },
        }
