"""tpu_pbrt — a TPU-native physically based renderer.

A from-scratch reimplementation of the capabilities of pbrt-v3 plus the
distributed master/worker tile renderer of jirenz/pbrt-v3-distributed,
designed TPU-first: scenes are compiled to flat SoA arrays in HBM and
rendered by JAX/XLA wavefront kernels, distributed over a device mesh via
shard_map with collective film merge.

Layer map (cf. SURVEY.md §1; upstream reference paths in module docstrings):
  scene/    — .pbrt front-end: lexer, parser, pbrt* API, ParamSet, factories
  core/     — math: transforms, spectrum, sampling, RNG, filters
  shapes/   — shape plugins tessellated/compiled to triangle SoA
  accel/    — SAH/LBVH build (host) + LinearBVHNode traversal (device)
  integrators/ — direct, path, volpath, bdpt, sppm, whitted, ao, mlt
  parallel/ — mesh/shard_map tile scheduler, film merge, checkpoint/resume
  ops/      — Pallas TPU kernels for the hot ops
  utils/    — image I/O (EXR/PNG/PFM), stats, progress, logging
"""

__version__ = "0.1.0"

from tpu_pbrt.scene.api import (  # noqa: F401
    pbrt_init,
    pbrt_cleanup,
    parse_file,
    parse_string,
    render_file,
)
