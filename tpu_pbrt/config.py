"""Centralized runtime configuration — the ONLY sanctioned os.environ
reader inside tpu_pbrt/ (enforced by jaxlint rule JL-ENV).

Every TPU_PBRT_* knob the renderer honors is read ONCE, here, at import
time into the module-level `cfg` singleton. Hot modules import `cfg` and
read plain attributes — no scattered `os.environ.get` calls inside
jit-reachable code, no per-call string parsing, and one place to see the
whole knob surface.

Tests that need to flip a knob mid-process set the env var and call
`reload()` (see tests/conftest.py's `tpu_pbrt_env` helper); production
code must never call reload() — the snapshot taken at import is the
contract.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional


_FALSY = frozenset({"0", "false", "no", "off"})
_TRUTHY = frozenset({"1", "true", "yes", "on"})


def _flag(name: str, default: bool) -> bool:
    """Explicit falsy/truthy spellings only; unset, empty, or anything
    unrecognized keeps the default. `export KNOB=` or `KNOB=false` in a
    wrapper script must never count as enabled — TPU_PBRT_ALLOW_DROPS
    silently flipping on would downgrade the capacity-overflow error to
    a warning (silent false misses)."""
    v = os.environ.get(name)
    if v is None:
        return default
    v = v.strip().lower()
    if v in _FALSY:
        return False
    if v in _TRUTHY:
        return True
    return default


def _int(name: str, default: Optional[int]) -> Optional[int]:
    v = os.environ.get(name)
    return default if v in (None, "") else int(v)


def _float(name: str, default: Optional[float]) -> Optional[float]:
    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


def _triflag(name: str) -> Optional[bool]:
    """Three-state knob: explicit truthy/falsy forces the value, unset or
    unrecognized means 'auto' (None) — the caller picks the default."""
    v = os.environ.get(name)
    if v is None:
        return None
    v = v.strip().lower()
    if v in _FALSY:
        return False
    if v in _TRUTHY:
        return True
    return None


class Config:
    """Snapshot of every environment knob. Attributes only — no methods
    touch os.environ after _load()."""

    __slots__ = (
        "bvh",
        "leaf_tris",
        "pallas",
        "fused",
        "fused_max_rays",
        "fused_max_nodes",
        "onehot",
        "slab",
        "headroom",
        "native",
        "progress_frequency",
        "coordinator_address",
        "regen",
        "mipfilter",
        "chunk",
        "pool",
        "deposit_seg",
        "serve_chunk",
        "serve_resident_mb",
        "pipeline",
        "serve_prefetch",
        "audit_drops",
        "allow_drops",
        "shard_native_check",
        "telemetry",
        "metrics",
        "metrics_path",
        "trace_path",
        "flight_path",
        "flight_max_mb",
        "metrics_exemplars",
        "health_wedge_steps",
        "serve_slo_depth",
        "serve_slo_wait_s",
        "faults",
        "nonfinite",
        "retry_max",
        "retry_backoff",
        "retry_backoff_cap",
        "retry_deadline",
    )

    def _load(self) -> "Config":
        #: acceleration structure: stream (default) | packet | wide | binary
        self.bvh: str = os.environ.get("TPU_PBRT_BVH", "stream")
        #: triangles per stream-path treelet leaf (None -> STREAM_LEAF_TRIS)
        self.leaf_tris: Optional[int] = _int("TPU_PBRT_LEAF_TRIS", None)
        #: Pallas kernels allowed at all (0 = the jnp/XLA escape hatch,
        #: overriding TPU_PBRT_FUSED)
        self.pallas: bool = _flag("TPU_PBRT_PALLAS", True)
        #: fused Pallas wavefront kernel (accel/fusedwave.py): flush
        #: phase (phi build + treelet DMA + MT matmul + closest-hit
        #: merge) and node expansion in single Pallas grids. Tri-state:
        #: 1 forces it on (interpret mode on CPU — the testing story),
        #: 0 forces the jnp path, unset = auto (on for TPU backends,
        #: off on CPU)
        self.fused: Optional[bool] = _triflag("TPU_PBRT_FUSED")
        #: wave-size ceiling for the fused kernels: the per-ray tables
        #: ((8, R) rayF + the (R,) winner accumulators) must be
        #: VMEM-resident, so waves past this fall back to the jnp path
        #: (see README "Accel kernels" for the budget math)
        self.fused_max_rays: int = _int("TPU_PBRT_FUSED_MAX_RAYS", 1 << 18)
        #: top-tree node ceiling for the fused EXPAND kernel (the
        #: (48, N) box table must be VMEM-resident); flush fusion is
        #: independent of this
        self.fused_max_nodes: int = _int("TPU_PBRT_FUSED_MAX_NODES", 1 << 14)
        # TPU_PBRT_PREFETCH (the standalone scalar-prefetch leaf kernel
        # of PRs <= 8) is retired: the fused wavefront kernel owns the
        # same DMA schedule plus everything around it. The knob aliases
        # to TPU_PBRT_FUSED=1 so old launch scripts keep working.
        if _flag("TPU_PBRT_PREFETCH", False):
            warnings.warn(
                "TPU_PBRT_PREFETCH is deprecated: the scalar-prefetch "
                "leaf kernel was subsumed by the fused wavefront kernel "
                "(accel/fusedwave.py). Treating it as TPU_PBRT_FUSED=1; "
                "set TPU_PBRT_FUSED explicitly.",
                DeprecationWarning,
                stacklevel=2,
            )
            if self.fused is None:
                self.fused = True
        #: one-hot MXU matmul for small-table gathers in EXPAND
        self.onehot: bool = _flag("TPU_PBRT_ONEHOT", True)
        #: stream worklist slab cap (pairs per EXPAND step)
        self.slab: int = _int("TPU_PBRT_SLAB", 1 << 17)
        #: worklist headroom scale (the overflow regression test shrinks it)
        self.headroom: float = _float("TPU_PBRT_HEADROOM", 1.0)
        #: native C++ scene-compile helpers (0 forces the numpy builders)
        self.native: bool = _flag("TPU_PBRT_NATIVE", True)
        #: progress-bar min update interval in seconds (pbrt's knob name)
        self.progress_frequency: Optional[float] = _float(
            "PBRT_PROGRESS_FREQUENCY", None
        )
        #: multi-host coordinator snapshot; prefer coordinator_address()
        #: (call-time) — drivers commonly export the variable AFTER
        #: import, once cluster discovery has run
        self.coordinator_address: Optional[str] = os.environ.get(
            "JAX_COORDINATOR_ADDRESS"
        )
        #: persistent-wavefront compaction+regeneration (0 -> fixed batch)
        self.regen: bool = _flag("TPU_PBRT_REGEN", True)
        #: trilinear mip selection from camera-ray differentials
        self.mipfilter: bool = _flag("TPU_PBRT_MIPFILTER", True)
        #: camera rays per dispatch (None -> platform default)
        self.chunk: Optional[int] = _int("TPU_PBRT_CHUNK", None)
        #: path-pool slots (0 -> per_dev/4 heuristic)
        self.pool: int = _int("TPU_PBRT_POOL", 0)
        #: segmented pool film deposit: width of the per-wave deposit
        #: window (terminated lanes are sorted to a contiguous prefix and
        #: only the window is scattered — the full-pool-width scatter was
        #: the ROADMAP "pool deposit path" carried item). 0 = auto
        #: (pool/4 once the pool is big enough to amortize the extra
        #: sort); >= pool or negative = full-width (the exact pre-segment
        #: program)
        self.deposit_seg: int = _int("TPU_PBRT_DEPOSIT_SEG", 0)
        #: in-flight dispatch window (ISSUE 13): how many chunk-slices
        #: the drain loops keep launched ahead of the host. JAX dispatch
        #: is async, so depth N lets every piece of host-side work
        #: (deposit bookkeeping, preview develop, checkpoint
        #: serialization, scheduling, metrics/flight recording) run
        #: UNDER the device compute of the slices still in flight; 1 is
        #: the strictly synchronous dispatch/block/host-work loop (the
        #: A/B baseline for host_overlap_fraction). Bit-identity is
        #: depth-independent by construction — the window only moves
        #: sync points, never the dispatched programs. The strict
        #: non-finite firewall modes force depth 1 (their per-chunk
        #: scrub-count sync cannot be pipelined away); see
        #: parallel/mesh.resolve_pipeline_depth
        self.pipeline: int = _int("TPU_PBRT_PIPELINE", 2)
        #: render-service dispatch lookahead: while the current job's
        #: slice is in flight, pre-activate the NEXT scheduled job
        #: (plan build + checkpoint film load host->HBM + residency LRU
        #: touch) so its first dispatch is not serialized behind its
        #: activation. Never preempts, never changes the schedule
        self.serve_prefetch: bool = _flag("TPU_PBRT_SERVE_PREFETCH", True)
        #: render-service slice width (camera rays per submit/step
        #: quantum — the preemption granularity; None = platform chunk)
        self.serve_chunk: Optional[int] = _int("TPU_PBRT_SERVE_CHUNK", None)
        #: render-service resident-scene HBM budget in MB (LRU eviction
        #: above it; None = unbounded). The default is a checked
        #: consequence of hbmcheck's serve HBM model (HC-CAP): the
        #: largest 1024-aligned budget that, together with the
        #: worst-case job load, fits the smallest platform's HBM with
        #: headroom — `python -m tpu_pbrt.analysis.hbmcheck
        #: --derive-hbm-caps` reproduces it
        self.serve_resident_mb: Optional[float] = _float(
            "TPU_PBRT_SERVE_RESIDENT_MB", 12288.0
        )
        #: pre-render stream-capacity audit (overflows fail loudly)
        self.audit_drops: bool = _flag("TPU_PBRT_AUDIT_DROPS", True)
        #: downgrade a detected capacity overflow to a warning
        self.allow_drops: bool = _flag("TPU_PBRT_ALLOW_DROPS", False)
        #: force jax's native shard_map replication check on (True) or
        #: off (False); None = auto by jax version (parallel/mesh.py
        #: resolve_shard_map_nocheck)
        self.shard_native_check: Optional[bool] = _triflag(
            "TPU_PBRT_SHARD_NATIVE_CHECK"
        )
        #: runtime telemetry (tpu_pbrt/obs): device-side wave counters in
        #: the pool drain, host-side trace spans and flight heartbeats.
        #: 0 is the kill switch — the drain compiles to the exact
        #: pre-telemetry program (the counter carry is a None pytree leaf)
        self.telemetry: bool = _flag("TPU_PBRT_TELEMETRY", True)
        #: host-side metrics registry (tpu_pbrt/obs/metrics.py):
        #: counters/gauges/histograms over the serve path and the render
        #: drain loop, Prometheus exposition, SLO load-shedding inputs.
        #: 0 is the kill switch — every record call is a no-op and render
        #: stats / serve responses are byte-identical to a build without
        #: the registry (host-side only; the compiled programs never see
        #: it either way)
        self.metrics: bool = _flag("TPU_PBRT_METRICS", True)
        #: Prometheus text snapshot file the registry exports to (also
        #: settable per-run via --metrics-path on main.py / serve)
        self.metrics_path: Optional[str] = os.environ.get(
            "TPU_PBRT_METRICS_PATH"
        ) or None
        #: Chrome-trace/Perfetto JSON output path for the span recorder
        #: (also settable per-run via --trace on main.py / bench.py)
        self.trace_path: Optional[str] = os.environ.get(
            "TPU_PBRT_TRACE_PATH"
        ) or None
        #: append-only JSONL flight-recorder path (phase heartbeats +
        #: counter snapshots; bench.py defaults this when unset)
        self.flight_path: Optional[str] = os.environ.get(
            "TPU_PBRT_FLIGHT_PATH"
        ) or None
        #: flight-recorder growth cap in MB: at a flush boundary past the
        #: cap the file rotates ONCE to `<path>.1` (previous rotation
        #: overwritten) — a long-lived serve daemon must not grow its
        #: append-only JSONL without bound. None/0 = unbounded
        self.flight_max_mb: Optional[float] = _float(
            "TPU_PBRT_FLIGHT_MAX_MB", None
        )
        #: exemplars retained per histogram series (tpu-scope): the
        #: top-K observations by value, each carrying the trace/span ids
        #: the caller attached — the join key from a slow percentile to
        #: the exact trace span that produced it. 0 disables retention
        self.metrics_exemplars: int = _int("TPU_PBRT_METRICS_EXEMPLARS", 4)
        #: health watchdog wedge threshold: the service is flagged
        #: wedged when runnable jobs exist but no chunk-slice has been
        #: dispatched OR retired across this many consecutive step()
        #: calls (obs/health.py)
        self.health_wedge_steps: int = _int("TPU_PBRT_HEALTH_WEDGE_STEPS", 12)
        #: serve SLO admission control (ISSUE 10 / ROADMAP #2 load
        #: shedding): per-priority-class queue-DEPTH targets — a submit
        #: that would push the class's runnable-job count past its target
        #: is answered with a deterministic `shed` instead of queued.
        #: Spec grammar: "8" (every class) or "0=4,5=32" (per class int,
        #: `default=` for the rest); empty = no depth shedding
        self.serve_slo_depth: str = os.environ.get(
            "TPU_PBRT_SERVE_SLO_DEPTH", ""
        ).strip()
        #: ... and per-class queue-WAIT targets in seconds: shed while
        #: the class has queued work AND its recent p90 queue wait (a
        #: bounded in-service window — deliberately NOT the registry's
        #: lifetime histogram, whose p90 could never recover once
        #: elevated) exceeds the target. Same spec grammar
        self.serve_slo_wait_s: str = os.environ.get(
            "TPU_PBRT_SERVE_SLO_WAIT_S", ""
        ).strip()
        #: declarative fault-injection plan (tpu_pbrt/chaos grammar, e.g.
        #: "dispatch:poison@chunk=3,ckpt:torn@write=2"); empty = no chaos.
        #: Installed into the CHAOS registry once at chaos-package import
        #: (snapshot contract — reload() does not re-install)
        self.faults: str = os.environ.get("TPU_PBRT_FAULTS", "").strip()
        #: non-finite film firewall mode: "scrub" (default — NaN/Inf
        #: deposits zeroed + counted in nonfinite_deposits), "raise"
        #: (abort the render on the first scrubbed chunk), "retry"
        #: (treat the chunk as state-poisoned and re-dispatch it exactly;
        #: raise/retry pay a per-chunk device sync for the check and
        #: REQUIRE the telemetry counters — render() rejects the
        #: combination with TPU_PBRT_TELEMETRY=0 rather than silently
        #: degrading to scrub)
        nf = os.environ.get("TPU_PBRT_NONFINITE", "").strip().lower()
        self.nonfinite: str = nf if nf in ("scrub", "raise", "retry") else "scrub"
        #: re-dispatch attempts per chunk before the render gives up
        #: (writes an emergency checkpoint first when one is configured)
        self.retry_max: int = _int("TPU_PBRT_RETRY_MAX", 8)
        #: exponential re-dispatch backoff: base seconds ...
        self.retry_backoff: float = _float("TPU_PBRT_RETRY_BACKOFF", 0.25)
        #: ... and ceiling seconds (attempt k sleeps
        #: min(base * 2^(k-1), cap) * deterministic-jitter[0.5, 1.0])
        self.retry_backoff_cap: float = _float(
            "TPU_PBRT_RETRY_BACKOFF_CAP", 30.0
        )
        #: wall-clock seconds spent retrying before giving up regardless
        #: of the attempt budget — the BENCH_r04/r05 hang shape, where a
        #: tight retry loop burned the whole capture (0 disables)
        self.retry_deadline: float = _float(
            "TPU_PBRT_RETRY_DEADLINE_S", 600.0
        )
        return self


#: the process-wide snapshot, read once at import
cfg = Config()._load()


def reload() -> Config:
    """Re-read the environment into the existing `cfg` object (same
    identity, so `from tpu_pbrt.config import cfg` holders see the new
    values). Test-only seam."""
    return cfg._load()


def coordinator_address() -> Optional[str]:
    """JAX_COORDINATOR_ADDRESS at CALL time. Unlike the TPU_PBRT_*
    knobs, this standard JAX cluster variable is routinely exported by
    launch drivers after import (post cluster discovery), so the
    import-time snapshot contract does not apply to it."""
    return os.environ.get("JAX_COORDINATOR_ADDRESS") or cfg.coordinator_address
