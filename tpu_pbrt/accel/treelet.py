"""Two-level acceleration structure: treelets + top-level wide BVH (host build).

Capability match for pbrt-v3 src/accelerators/bvh.cpp BVHAccel (same hit
semantics), re-shaped for the TPU memory system. The reference's
LinearBVHNode[] walk gathers one 32-byte node per ray per step — on TPU
that per-lane gather pattern is row-latency-bound and catastrophically
slow (measured ~0.05us PER ROW regardless of row size). The TPU-shaped
layout instead:

- cuts the binary SAH/Morton tree (accel/build.py) into TREELETS —
  subtrees of <= LEAF_TRIS triangles, contiguous in leaf order — and
  precomputes each treelet's 16 x 4L Möller–Trumbore feature matrix
  (accel/mxu.py), so a leaf visit is one fat contiguous row fetch + one
  MXU matmul instead of L scattered scalar tests;
- builds a small top-level BVH over treelet AABBs and collapses it 8-wide
  (accel/wide.py build_wide), so interior traversal touches ~100x fewer
  nodes than the triangle-level tree;
- is traversed per PACKET (accel/packet.py): 128 rays share one traversal
  stack, so node fetches are per-packet rows, not per-ray rows.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.build import BVHArrays, build_bvh
from tpu_pbrt.accel.mxu import tri_feature_weights_raw
from tpu_pbrt.accel.wide import _LEAF_STRIDE, WideBVH, build_wide

#: triangles per treelet (feature-matrix columns = 4x this). 64 keeps the
#: treelet feature row at 16 KB — one efficient contiguous fetch.
LEAF_TRIS = 64


class TreeletPack(NamedTuple):
    """Device arrays for the two-level traversal (all jnp — every field is
    a pytree leaf so the pack passes through jit; static metadata like
    leaf_tris is derived from shapes: feat.shape == (C, 4*leaf_tris, 16)).

    The feature layout is TRANSPOSED relative to accel/mxu.py's standalone
    (16, 4T) weights: rows are output columns, so a leaf block feeds the
    MXU as dot(featT (4L,16), phiT (16,128)) with the 128 rays on the lane
    dimension — the shape the fused wavefront flush kernel
    (accel/fusedwave.py _flush_kernel) consumes without a transpose, and
    the same contraction the jnp einsum runs. Only this one layout is
    stored: it is
    the scene's largest array (~0.5 GB for crown-class), so keeping a
    second transposed copy for the packet walker would double device
    residency; the packet walker transposes per-leaf instead."""

    top: WideBVH  # 8-wide top tree; leaf codes encode treelet ids
    featT: jnp.ndarray  # (C, 16, 4*LEAF_TRIS) f32 MT feature matrices
    center: jnp.ndarray  # (C, 3) f32 re-centering point per treelet
    offset: jnp.ndarray  # (C,) i32 first leaf-order triangle id
    count: jnp.ndarray  # (C,) i32 triangles in treelet

    @property
    def leaf_tris(self) -> int:
        return self.featT.shape[2] // 4

    @property
    def n_features(self) -> int:
        """16 static, 64 with motion-blur time features."""
        return self.featT.shape[1]

    @property
    def n_treelets(self) -> int:
        return self.featT.shape[0]


def _subtree_ranges(bvh: BVHArrays):
    """Per-node (first leaf-order prim, prim count) via a reverse DFS pass.

    DFS layout: children of interior node i are i+1 and second_child[i],
    both with larger ids, so a reverse iteration sees children first.
    Morton padding leaves (n_prims == 0, no forward second-child) count 0.
    """
    n = bvh.n_nodes
    second = bvh.second_child
    n_prims = bvh.n_prims
    count = np.zeros(n, np.int64)
    first = np.zeros(n, np.int64)
    for i in range(n - 1, -1, -1):
        if n_prims[i] > 0:
            count[i] = n_prims[i]
            first[i] = bvh.prim_offset[i]
        elif second[i] > i:
            count[i] = count[i + 1] + count[second[i]]
            first[i] = first[i + 1]
    return first, count


def cut_treelets(bvh: BVHArrays, leaf_tris: int = LEAF_TRIS):
    """Top-down cut of the binary tree into subtrees of <= leaf_tris prims.

    Returns (offsets, counts, bmin, bmax) numpy arrays, one row per
    treelet. Subtree prims are contiguous in leaf order, so a treelet is
    just a range [offset, offset+count) of the leaf-order triangle array.
    """
    first, count = _subtree_ranges(bvh)
    offsets, counts, bmins, bmaxs = [], [], [], []
    stack = [0]
    while stack:
        i = stack.pop()
        if count[i] == 0:
            continue  # Morton padding
        if count[i] <= leaf_tris:
            offsets.append(first[i])
            counts.append(count[i])
            bmins.append(bvh.bounds_min[i])
            bmaxs.append(bvh.bounds_max[i])
        else:
            stack.append(int(bvh.second_child[i]))
            stack.append(i + 1)
    return (
        np.asarray(offsets, np.int64),
        np.asarray(counts, np.int64),
        np.asarray(bmins, np.float32),
        np.asarray(bmaxs, np.float32),
    )


def decode_top_leaf(code):
    """Top-tree wide leaf code -> treelet id (inverse of build_wide's
    leaf encoding with one 'primitive' — a treelet — per leaf)."""
    return (-(code + 1)) // _LEAF_STRIDE


def build_treelet_pack(
    tri_verts_leaf_order: np.ndarray, bvh: BVHArrays,
    leaf_tris: int = LEAF_TRIS, tri_verts1: np.ndarray = None,
) -> TreeletPack:
    """Cut + features + top tree. tri_verts_leaf_order: (T,3,3) float32 in
    the SAME leaf order the BVH's prim_offset indexes (the scene compiler's
    permuted triangle array, unpadded). tri_verts1 (same order): the
    shutter-end keyframe — features become the 64-row cubic-in-time
    tables of accel/mxu.py tri_feature_weights_motion, and the caller's
    bvh must be built over union bounds."""
    off, cnt, bmin, bmax = cut_treelets(bvh, leaf_tris)
    c = len(off)

    # top tree over treelet AABBs, one treelet per leaf; its prim_order
    # permutes treelets, so reorder the treelet arrays to match
    top_bin = build_bvh(bmin, bmax, method="sah" if c <= 262144 else "hlbvh",
                        max_leaf_prims=1)
    order = top_bin.prim_order
    off, cnt = off[order], cnt[order]
    top = build_wide(top_bin)

    # Vectorized padded gather of every treelet's triangles + per-treelet
    # feature build (crown-class scenes have ~50k treelets; a Python loop
    # here would dominate scene compile on a single host core).
    verts = np.asarray(tri_verts_leaf_order, np.float32)
    t_total = len(verts)
    gidx = off[:, None] + np.arange(leaf_tris)[None, :]  # (C, L)
    valid = np.arange(leaf_tris)[None, :] < cnt[:, None]
    tv = verts[np.clip(gidx, 0, t_total - 1)]  # (C, L, 3, 3)
    tv[~valid] = 0.0  # zero pad: det == 0, never hits
    if tri_verts1 is not None:
        tv1 = np.asarray(tri_verts1, np.float32)[np.clip(gidx, 0, t_total - 1)]
        tv1[~valid] = 0.0
        both = np.concatenate([tv, tv1], axis=1)
        vmin = np.where(
            np.tile(valid, (1, 2))[..., None], both.min(axis=2), np.inf
        ).min(axis=1)
        vmax = np.where(
            np.tile(valid, (1, 2))[..., None], both.max(axis=2), -np.inf
        ).max(axis=1)
    else:
        vmin = np.where(valid[..., None], tv.min(axis=2), np.inf).min(axis=1)
        vmax = np.where(valid[..., None], tv.max(axis=2), -np.inf).max(axis=1)
    center = (0.5 * (vmin + vmax)).astype(np.float32)  # (C, 3)
    if tri_verts1 is not None:
        from tpu_pbrt.accel.mxu import tri_feature_weights_motion

        W = tri_feature_weights_motion(
            tv.reshape(c * leaf_tris, 3, 3),
            tv1.reshape(c * leaf_tris, 3, 3),
            np.repeat(center, leaf_tris, axis=0)[:, None, :],
            raw=True,
        ).reshape(c, leaf_tris, 64, 4)
        feat = np.ascontiguousarray(
            W.transpose(0, 3, 1, 2).reshape(c, 4 * leaf_tris, 64)
        )
    else:
        W = tri_feature_weights_raw(
            tv.reshape(c * leaf_tris, 3, 3),
            np.repeat(center, leaf_tris, axis=0)[:, None, :],
        ).reshape(c, leaf_tris, 16, 4)
        # (C, L, 16, 4) -> (C, 4, L, 16) -> (C, 4L, 16): rows grouped
        # [det(L) | u*det(L) | v*det(L) | t*det(L)], matching
        # decode_outputs' column order after the (...,f) x (k,f) contraction
        feat = np.ascontiguousarray(
            W.transpose(0, 3, 1, 2).reshape(c, 4 * leaf_tris, 16)
        )

    return TreeletPack(
        top=top,
        featT=jnp.asarray(np.ascontiguousarray(feat.transpose(0, 2, 1))),
        center=jnp.asarray(center),
        offset=jnp.asarray(off, jnp.int32),
        count=jnp.asarray(cnt, jnp.int32),
    )
