"""Packet traversal of the two-level treelet BVH — the fast trace path.

Capability match for pbrt-v3 src/accelerators/bvh.cpp
BVHAccel::Intersect/IntersectP (same closest-hit/any-hit semantics over
the same tree), re-architected for TPU memory behavior. Why not the
reference's per-ray stack walk: on TPU a gather costs ~constant time PER
ROW (latency-bound), so R rays each fetching one node row per step costs
R rows * steps — measured 5 orders of magnitude off target in round 2.

The packet design divides the R-ray batch into packets of LANE=128 rays
that share ONE traversal stack (classic CPU-SIMD packet tracing, mapped
to the VPU lane dimension):

- node fetches are per-PACKET rows (R/128 of them per step, not R);
- all per-lane work is dense (P, 128, 8) vector math — no per-lane
  gathers, no per-lane stacks, no argsort;
- a popped top-level node expands 8 children at once (slab tests against
  every lane); children hit by ANY lane are pushed with their packet-min
  entry distance, and a pop whose entry distance exceeds the packet-max
  current hit t is discarded (front-to-back culling at packet grain);
- treelet leaves are queued per packet, sorted by entry distance, and
  intersected with one MXU feature matmul per (packet, treelet) pair
  (accel/mxu.py) — 64 watertight-equivalent triangle tests per lane in
  one contiguous 16 KB row fetch + (128,16)@(16,256) matmul;
- the leaf queue is bounded: when it fills mid-walk the traversal flushes
  (tests queued treelets, tightening per-lane t), then resumes — so
  arbitrarily divergent packets stay correct with fixed memory.

Coherence determines the packet-union overhead: camera rays from adjacent
pixels traverse near-identical node sets; integrators keep bounce rays in
their parent packets (spatial coherence) — see integrators/common.py.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_pbrt.accel.mxu import decode_outputs, ray_features
from tpu_pbrt.accel.traverse import Hit
from tpu_pbrt.accel.treelet import TreeletPack
from tpu_pbrt.accel.wide import _EMPTY, MAX_STACK

LANE = 128
LEAF_QUEUE = 64
_FLUSH_AT = LEAF_QUEUE - 8  # a pop can append up to 8 leaves


class _State(NamedTuple):
    sp: jnp.ndarray  # (P,) stack depth
    stk_c: jnp.ndarray  # (P,S) i32 interior node codes
    stk_t: jnp.ndarray  # (P,S) f32 packet-min entry distance
    nleaf: jnp.ndarray  # (P,) queued leaf count
    leaf_id: jnp.ndarray  # (P,Q) i32 treelet ids
    leaf_tn: jnp.ndarray  # (P,Q) f32 entry distances
    t: jnp.ndarray  # (P,LANE) current closest hit (or t_max)
    prim: jnp.ndarray  # (P,LANE) i32 global leaf-order triangle id, -1 miss
    b0: jnp.ndarray  # (P,LANE)
    b1: jnp.ndarray  # (P,LANE)
    n_pop: jnp.ndarray  # (P,) stat: interior pops (BVHAccel nodes-visited)
    n_tl: jnp.ndarray  # (P,) stat: treelet (leaf matmul) tests


def _packet_done(s: _State, dead, any_hit: bool):
    if not any_hit:
        return jnp.zeros(s.sp.shape, bool)
    return jnp.all((s.prim >= 0) | dead, axis=-1)


def _traverse(tp: TreeletPack, o, d, t_max, any_hit: bool):
    """o,d: (P,LANE,3); t_max: (P,LANE). Returns final _State."""
    P = o.shape[0]
    L = tp.leaf_tris
    inv_d = 1.0 / d
    dead = t_max <= 0.0
    p_idx = jnp.arange(P, dtype=jnp.int32)

    top = tp.top
    from tpu_pbrt.accel.treelet import decode_top_leaf

    def interior_step(s: _State):
        active = (s.sp > 0) & (s.nleaf <= _FLUSH_AT) & ~_packet_done(s, dead, any_hit)
        sp1 = jnp.maximum(s.sp - 1, 0)
        code = s.stk_c[p_idx, sp1]
        tn_top = s.stk_t[p_idx, sp1]
        sp_new = jnp.where(active, sp1, s.sp)
        t_pkt = jnp.max(s.t, axis=-1)  # packet-max current hit distance
        expand = active & (tn_top <= t_pkt)

        node = jnp.where(expand, code, 0)
        nmin = top.child_bmin[node]  # (P,8,3)
        nmax = top.child_bmax[node]
        cids = top.child_idx[node]  # (P,8)

        # slab test: every lane vs all 8 children, far plane clamped by the
        # lane's current t (adaptive front-to-back culling)
        from tpu_pbrt.accel.wide import slab_test

        tn, _, lane_hit = slab_test(
            nmin[:, None], nmax[:, None], o[:, :, None, :],
            inv_d[:, :, None, :], s.t[:, :, None],
        )  # (P,LANE,8)
        hit8 = jnp.any(lane_hit, axis=1) & (cids != _EMPTY) & expand[:, None]
        tn_pkt = jnp.min(jnp.where(lane_hit, tn, jnp.inf), axis=1)  # (P,8)

        is_int = hit8 & (cids >= 0)
        is_leaf = hit8 & (cids < 0)

        # push interior children (one scatter; unpushed slots -> OOB drop)
        npush = jnp.cumsum(is_int, axis=-1)
        pos = jnp.where(is_int, sp_new[:, None] + npush - 1, MAX_STACK + 7)
        stk_c = s.stk_c.at[p_idx[:, None], pos].set(cids, mode="drop")
        stk_t = s.stk_t.at[p_idx[:, None], pos].set(tn_pkt, mode="drop")
        sp_out = sp_new + npush[:, -1]

        # queue leaf children (treelet ids)
        tids = decode_top_leaf(cids)
        nq = jnp.cumsum(is_leaf, axis=-1)
        qpos = jnp.where(is_leaf, s.nleaf[:, None] + nq - 1, LEAF_QUEUE + 7)
        leaf_id = s.leaf_id.at[p_idx[:, None], qpos].set(tids, mode="drop")
        leaf_tn = s.leaf_tn.at[p_idx[:, None], qpos].set(tn_pkt, mode="drop")
        nleaf = s.nleaf + nq[:, -1]

        return s._replace(
            sp=sp_out, stk_c=stk_c, stk_t=stk_t,
            nleaf=nleaf, leaf_id=leaf_id, leaf_tn=leaf_tn,
            n_pop=s.n_pop + active.astype(jnp.int32),
        )

    def leaf_step(c):
        k, s = c
        valid = (k < s.nleaf) & ~_packet_done(s, dead, any_hit)
        t_pkt = jnp.max(s.t, axis=-1)
        tid = jnp.where(valid, s.leaf_id[:, k], 0)
        # queue is tn-sorted: once the packet's next treelet is farther
        # than its farthest lane hit, every later one is too
        live = valid & (s.leaf_tn[:, k] <= t_pkt) & (tid >= 0)

        WT = tp.featT[jnp.where(live, tid, 0)]  # (P,16,4L)
        ctr = tp.center[jnp.where(live, tid, 0)]  # (P,3)
        off = tp.offset[jnp.where(live, tid, 0)]  # (P,)
        phi = ray_features(o - ctr[:, None, :], d)  # (P,LANE,16)
        out = jnp.einsum(
            "plf,pfc->plc", phi, WT, precision=jax.lax.Precision.HIGHEST
        )
        t_new, k_loc, b0, b1 = decode_outputs(out, L, s.t)
        better = live[:, None] & jnp.isfinite(t_new) & (t_new < s.t)
        return k + 1, s._replace(
            t=jnp.where(better, t_new, s.t),
            prim=jnp.where(better, off[:, None] + k_loc.astype(jnp.int32), s.prim),
            b0=jnp.where(better, b0, s.b0),
            b1=jnp.where(better, b1, s.b1),
            n_tl=s.n_tl + live.astype(jnp.int32),
        )

    def flush(s: _State):
        """Sort the leaf queue by entry distance, intersect front-to-back."""
        key = jnp.where(
            jnp.arange(LEAF_QUEUE, dtype=jnp.int32)[None, :] < s.nleaf[:, None],
            s.leaf_tn, jnp.inf
        )
        key_s, id_s = jax.lax.sort([key, s.leaf_id], num_keys=1)
        s = s._replace(leaf_tn=key_s, leaf_id=id_s)

        def cond(c):
            k, ss = c
            t_pkt = jnp.max(ss.t, axis=-1)
            live = (
                (k < ss.nleaf)
                & (ss.leaf_tn[:, jnp.minimum(k, LEAF_QUEUE - 1)] <= t_pkt)
                & ~_packet_done(ss, dead, any_hit)
            )
            return (k < LEAF_QUEUE) & jnp.any(live)

        _, s = jax.lax.while_loop(cond, leaf_step, (jnp.int32(0), s))
        return s._replace(nleaf=jnp.zeros_like(s.nleaf))

    def outer_cond(s: _State):
        alive = ((s.sp > 0) | (s.nleaf > 0)) & ~_packet_done(s, dead, any_hit)
        return jnp.any(alive)

    def outer_body(s: _State):
        def a_cond(ss: _State):
            active = (
                (ss.sp > 0) & (ss.nleaf <= _FLUSH_AT)
                & ~_packet_done(ss, dead, any_hit)
            )
            return jnp.any(active)

        s = jax.lax.while_loop(a_cond, interior_step, s)
        return flush(s)

    init = _State(
        sp=jnp.ones((P,), jnp.int32),
        stk_c=jnp.zeros((P, MAX_STACK), jnp.int32),  # stack[0] = root
        stk_t=jnp.zeros((P, MAX_STACK), jnp.float32),
        nleaf=jnp.zeros((P,), jnp.int32),
        leaf_id=jnp.full((P, LEAF_QUEUE), -1, jnp.int32),
        leaf_tn=jnp.full((P, LEAF_QUEUE), jnp.inf, jnp.float32),
        t=t_max,
        prim=jnp.full((P, LANE), -1, jnp.int32),
        b0=jnp.zeros((P, LANE), jnp.float32),
        b1=jnp.zeros((P, LANE), jnp.float32),
        n_pop=jnp.zeros((P,), jnp.int32),
        n_tl=jnp.zeros((P,), jnp.int32),
    )
    return jax.lax.while_loop(outer_cond, outer_body, init)


@partial(jax.jit, static_argnames=("any_hit",))
def packet_traverse_stats(tp: TreeletPack, o, d, t_max, any_hit: bool = False):
    """Per-packet traversal statistics (interior pops, treelet matmul
    tests) for the stats subsystem and perf analysis."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    op, dp, tm, _ = _to_packets(o, d, t_max)
    s = _traverse(tp, op, dp, tm, any_hit)
    return s.n_pop, s.n_tl


def _to_packets(o, d, t_max):
    R = o.shape[0]
    P = (R + LANE - 1) // LANE
    pad = P * LANE - R
    if pad:
        o = jnp.concatenate([o, jnp.zeros((pad, 3), o.dtype)])
        d = jnp.concatenate([d, jnp.full((pad, 3), 1.0, d.dtype)])
        t_max = jnp.concatenate([t_max, jnp.full((pad,), -1.0, t_max.dtype)])
    return (
        o.reshape(P, LANE, 3),
        d.reshape(P, LANE, 3),
        t_max.reshape(P, LANE),
        R,
    )


@partial(jax.jit, static_argnames=("any_hit",))
def packet_intersect(tp: TreeletPack, o, d, t_max, any_hit: bool = False):
    """Closest hit (or any-hit predicate source) for a flat ray batch.

    o,d: (R,3); t_max scalar or (R,). Returns Hit with global leaf-order
    triangle ids, API-compatible with bvh_intersect/wide_intersect.
    """
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    op, dp, tp_, R = _to_packets(o, d, t_max)
    s = _traverse(tp, op, dp, tp_, any_hit)
    flat = lambda a: a.reshape(-1)[:R]  # noqa: E731
    t = flat(s.t)
    prim = flat(s.prim)
    t = jnp.where(prim >= 0, t, jnp.inf)
    return Hit(t, prim, flat(s.b0), flat(s.b1))


def packet_intersect_p(tp: TreeletPack, o, d, t_max):
    """Any-hit (shadow) predicate -> bool (R,)."""
    hit = packet_intersect(tp, o, d, t_max, any_hit=True)
    return hit.prim >= 0
