"""Pallas TPU kernel: fused treelet-block triangle intersection.

Capability match for pbrt-v3 src/shapes/triangle.cpp Triangle::Intersect
over a leaf's triangle list (bvh.cpp's leaf loop), as the fused form of
accel/mxu.py's feature matmul + decode_outputs.

Why a kernel: the XLA path materializes the (blocks, 128, 4L) matmul
output in HBM and then re-reads it several times through decode (slices,
divisions, compares, argmin, take_along_axis) — measured ~4-6 ms per
512-block chunk, the dominant cost of the stream tracer's flush phase.
This kernel keeps the (4L, 128) product of each block entirely in VMEM,
reduces it to the per-ray closest hit in-register, and writes only the
(128,) winners: per-block HBM traffic drops from ~1.5 MB to ~74 KB
(feature row + ray features + two output rows).

Per grid step (one leaf block = one treelet x 128 rays):
    out4 (4L, 128) = dot(featT (4L, 16), phiT (16, 128))   [MXU, f32]
    u, v, t        = Moller-Trumbore ratios from out4 rows  [VPU]
    hit            = barycentric bounds (EDGE_EPS band) & 0 < t < t_max
    t_best, k      = masked min + argmin over the L triangles
The b0/b1 barycentrics of the winner are NOT produced here — the stream
tracer recomputes them once per ray from (ray, prim) at the end, which is
cheaper than carrying them through every block merge.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_pbrt.accel.mxu import EDGE_EPS


def _leaf_kernel(feat_ref, phi_ref, tb_ref, t_out_ref, k_out_ref, *, L: int):
    featT = feat_ref[0]  # (16, 4L): features on the contraction dim
    phiT = phi_ref[0]  # (16, 128)
    out4 = jax.lax.dot_general(
        featT, phiT,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # (4L, 128)
    det = out4[0 * L : 1 * L]
    udet = out4[1 * L : 2 * L]
    vdet = out4[2 * L : 3 * L]
    tdet = out4[3 * L : 4 * L]
    inv = 1.0 / jnp.where(det == 0.0, 1.0, det)
    u = udet * inv
    v = vdet * inv
    t = tdet * inv
    tb = tb_ref[0]  # (1, 128) current per-ray t_max
    hit = (
        (det != 0.0)
        & (u >= -EDGE_EPS)
        & (v >= -EDGE_EPS)
        & (u + v <= 1.0 + EDGE_EPS)
        & (t > 0.0)
        & (t < tb)
    )
    tm = jnp.where(hit, t, jnp.inf)  # (L, 128)
    t_out_ref[0] = jnp.min(tm, axis=0, keepdims=True)
    k_out_ref[0] = jnp.argmin(tm, axis=0, keepdims=True).astype(jnp.int32)


def _leaf_kernel_sp(tids_ref, feat_ref, phi_ref, tb_ref, t_out_ref, k_out_ref,
                    *, L: int):
    # scalar-prefetch ref arrives first; the index_maps consumed it already
    _leaf_kernel(feat_ref, phi_ref, tb_ref, t_out_ref, k_out_ref, L=L)


@partial(jax.jit, static_argnames=())
def leaf_blocks_intersect_prefetch(feat_table, tids, phi, t_b):
    """Scalar-prefetch variant: takes the FULL treelet feature table
    (C, 4L, 16) resident in HBM plus per-block treelet ids (B,) and lets
    the grid's index_map select each step's feature block — Pallas DMAs
    exactly feat_table[tids[i]] HBM->VMEM per step, overlapped with the
    previous step's compute. This removes the materialized
    `feat_table[tids]` gather (the flush phase's largest HBM cost: the
    same treelet row was re-fetched for every one of its ~dozens of
    blocks AND round-tripped through a (B, 4L, 16) HBM temporary)."""
    B = tids.shape[0]
    _, _, fourL = feat_table.shape  # (C, 16, 4L)
    L = fourL // 4
    phiT = phi  # caller builds (B, 16, 128) directly
    tb2 = t_b[:, None, :]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 16, fourL), lambda i, tids_ref: (tids_ref[i], 0, 0)),
            pl.BlockSpec((1, 16, 128), lambda i, tids_ref: (i, 0, 0)),
            pl.BlockSpec((1, 1, 128), lambda i, tids_ref: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 128), lambda i, tids_ref: (i, 0, 0)),
            pl.BlockSpec((1, 1, 128), lambda i, tids_ref: (i, 0, 0)),
        ],
    )
    t_loc, k_loc = pl.pallas_call(
        partial(_leaf_kernel_sp, L=L),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, 1, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, 1, 128), jnp.int32),
        ],
    )(tids, feat_table, phiT, tb2)
    return t_loc[:, 0, :], k_loc[:, 0, :]


@partial(jax.jit, static_argnames=())
def leaf_blocks_intersect(feat_b, phi, t_b):
    """feat_b: (B, 16, 4L) gathered TRANSPOSED treelet features; phi:
    (B, 16, 128) transposed ray features (re-centered); t_b: (B, 128).
    Returns (t_loc, k_loc): (B, 128) closest-hit distance (inf = miss,
    always < t_b on hit) and LOCAL triangle index within the treelet —
    the same contract as mxu.decode_outputs' first two outputs."""
    B, _, fourL = feat_b.shape  # (B, 16, 4L)
    L = fourL // 4
    phiT = phi  # caller builds (B, 16, 128) directly (rays on lanes)
    tb2 = t_b[:, None, :]  # (B, 1, 128)
    t_loc, k_loc = pl.pallas_call(
        partial(_leaf_kernel, L=L),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 16, fourL), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 16, 128), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 128), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 128), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 128), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, 1, 128), jnp.int32),
        ],
    )(feat_b, phiT, tb2)
    return t_loc[:, 0, :], k_loc[:, 0, :]
