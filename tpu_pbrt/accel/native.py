"""ctypes bridge to the native C++ scene-compile runtime (native/).

The reference's build/runtime layer is C++ (bvh.cpp's builders run inside
the C++ process); ours mirrors that: hot host-side compile steps live in
native/*.cpp, compiled once into .native/libtpupbrt.so by the local g++
and loaded here through ctypes (no pybind11 in this environment — plain C
ABI with caller-allocated numpy buffers).

Graceful degradation: if g++ or the compile is unavailable the callers
fall back to the pure-numpy implementations (TPU_PBRT_NATIVE=0 forces
this; tests cover both paths and assert they agree)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "bvh_builder.cpp")
_OUT_DIR = os.path.join(_REPO, ".native")
_LIB = os.path.join(_OUT_DIR, "libtpupbrt.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _compile() -> bool:
    os.makedirs(_OUT_DIR, exist_ok=True)
    # rebuild when the source is newer than the cached .so
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return True
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if r.returncode != 0:
        from tpu_pbrt.utils.error import Warning as _W

        _W(f"native build failed ({r.stderr.decode()[:200]}); using numpy builders")
        return False
    return True


def get_lib():
    """The loaded native library, or None (numpy fallback)."""
    global _lib, _tried
    from tpu_pbrt.config import cfg

    if not cfg.native:
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC) or not _compile():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.build_sah_bvh.restype = ctypes.c_int64
        lib.build_sah_bvh.argtypes = [
            ctypes.POINTER(ctypes.c_double),  # bmin
            ctypes.POINTER(ctypes.c_double),  # bmax
            ctypes.c_int64,  # n
            ctypes.c_int32,  # max_leaf
            ctypes.POINTER(ctypes.c_float),  # out_min
            ctypes.POINTER(ctypes.c_float),  # out_max
            ctypes.POINTER(ctypes.c_int32),  # out_prim_off
            ctypes.POINTER(ctypes.c_int32),  # out_nprims
            ctypes.POINTER(ctypes.c_int32),  # out_second
            ctypes.POINTER(ctypes.c_int32),  # out_axis
            ctypes.POINTER(ctypes.c_int64),  # out_order
        ]
        _lib = lib
        return _lib


def _ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def native_build_sah(bmin: np.ndarray, bmax: np.ndarray, max_leaf: int):
    """Run the native SAH build; returns BVHArrays or None if the native
    library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    from tpu_pbrt.accel.build import BVHArrays

    n = len(bmin)
    bmin = np.ascontiguousarray(bmin, np.float64)
    bmax = np.ascontiguousarray(bmax, np.float64)
    cap = 2 * n + 1
    out_min = np.empty((cap, 3), np.float32)
    out_max = np.empty((cap, 3), np.float32)
    out_prim_off = np.zeros(cap, np.int32)
    out_nprims = np.zeros(cap, np.int32)
    out_second = np.zeros(cap, np.int32)
    out_axis = np.zeros(cap, np.int32)
    out_order = np.empty(n, np.int64)
    m = lib.build_sah_bvh(
        _ptr(bmin, ctypes.c_double),
        _ptr(bmax, ctypes.c_double),
        ctypes.c_int64(n),
        ctypes.c_int32(max_leaf),
        _ptr(out_min, ctypes.c_float),
        _ptr(out_max, ctypes.c_float),
        _ptr(out_prim_off, ctypes.c_int32),
        _ptr(out_nprims, ctypes.c_int32),
        _ptr(out_second, ctypes.c_int32),
        _ptr(out_axis, ctypes.c_int32),
        _ptr(out_order, ctypes.c_int64),
    )
    if m <= 0:
        return None
    return BVHArrays(
        bounds_min=out_min[:m].copy(),
        bounds_max=out_max[:m].copy(),
        prim_offset=out_prim_off[:m].copy(),
        n_prims=out_nprims[:m].copy(),
        second_child=out_second[:m].copy(),
        axis=out_axis[:m].copy(),
        prim_order=out_order,
    )
