"""Triangle intersection as matrix multiply — the MXU leaf test.

Capability match for pbrt-v3 src/shapes/triangle.cpp Triangle::Intersect
(same hit set and barycentrics up to f32 rounding), re-derived for the
TPU's systolic array. The key observation: every quantity the
Möller–Trumbore test needs is a BILINEAR form in (ray, triangle). With
e1 = v1-v0, e2 = v2-v0, s = o-v0, p = d x e2, q = s x e1:

    det   = p . e1 = d . (e2 x e1)                    (linear in d)
    u*det = p . s  = sum_ij o_i d_j [eps_ijk e2_k] - d . (e2 x v0)
    v*det = q . d  = sum_ij o_i d_j [-eps_ijk e1_k] - d . (v0 x e1)
    t*det = q . e2 = o . n - v0 . n,   n = e1 x e2    (linear in o)

so with the 16-dim ray feature vector

    phi(o, d) = [o_i d_j (9, i-major), d (3), o (3), 1]

all four outputs for T triangles are one matmul phi @ W with per-triangle
weights W in R^{16 x 4T} — exactly the (rays, 16) @ (16, 4T) shape the MXU
wants. Intersecting a 64-triangle treelet against a 128-ray packet costs
one small matmul instead of 64 gathered scalar tests.

f32 precision: the o_i d_j features lose ~eps*|o||d| per term, so rays and
vertices are RE-CENTERED per treelet (o' = o - c, v0' = v0 - c), bounding
the cancellation by the treelet diameter instead of the scene diameter.
The matmul runs at Precision.HIGHEST (3-pass f32 on TPU) — bf16 features
would visibly crack edges. Edge behavior: unlike the shear-based
watertight test (accel/traverse.py intersect_triangle, which this module
does NOT replace for oracle/unit-test use), the barycentric comparisons
here use a small epsilon band, so shared-edge rays may hit BOTH adjacent
triangles (closest-t wins — harmless) but never leak through.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.traverse import Hit

#: relative barycentric tolerance: widens each triangle by ~1e-6 so shared
#: edges cannot crack open under f32 rounding (double hits resolve by t)
EDGE_EPS = 1e-6

#: scenes at or below this triangle count skip the treelet hierarchy and
#: brute-force every triangle in one feature matmul (Cornell-class scenes)
BRUTE_MAX_TRIS = 256


def tri_feature_weights_raw(verts: np.ndarray, center) -> np.ndarray:
    """(T,3,3) triangle vertices + re-centering point(s) -> (T, 16, 4)
    per-triangle weights (outputs: det, u*det, v*det, t*det).

    `center` broadcasts against (T,3,3) — pass (3,) for a shared center or
    (T,1,3) for per-triangle centers. Degenerate (zero-area) triangles —
    including padding rows — produce all-zero weights, so det == 0 and
    they can never hit.
    """
    v = np.asarray(verts, np.float64) - np.asarray(center, np.float64)
    v0, v1, v2 = v[:, 0], v[:, 1], v[:, 2]
    e1 = v1 - v0
    e2 = v2 - v0
    n = np.cross(e1, e2)  # (T,3)
    T = len(v)

    eps = np.zeros((3, 3, 3))
    eps[0, 1, 2] = eps[1, 2, 0] = eps[2, 0, 1] = 1.0
    eps[0, 2, 1] = eps[2, 1, 0] = eps[1, 0, 2] = -1.0

    W = np.zeros((T, 16, 4), np.float64)
    # det = d . (e2 x e1) = -d . n
    W[:, 9:12, 0] = -n
    # u*det = sum o'_i d_j eps_ijk e2_k  -  d . (e2 x v0')
    W[:, :9, 1] = np.einsum("ijk,tk->tij", eps, e2).reshape(T, 9)
    W[:, 9:12, 1] = -np.cross(e2, v0)
    # v*det = sum o'_i d_j (-eps_ijk e1_k)  -  d . (v0' x e1)
    W[:, :9, 2] = -np.einsum("ijk,tk->tij", eps, e1).reshape(T, 9)
    W[:, 9:12, 2] = -np.cross(v0, e1)
    # t*det = o' . n - v0' . n
    W[:, 12:15, 3] = n
    W[:, 15, 3] = -np.sum(v0 * n, axis=-1)
    return W.astype(np.float32)


def tri_feature_weights_motion(v0: np.ndarray, v1: np.ndarray, center,
                               raw: bool = False) -> np.ndarray:
    """Motion-blur feature weights: vertices lerp linearly over the
    shutter, so every Moller-Trumbore output is a CUBIC in the ray time
    t (det and u/v*det are quadratic, t_hit*det cubic via v0(t).n(t)).
    The per-triangle weights become 4 monomial coefficient blocks
    W(t) = W_0 + t W_1 + t^2 W_2 + t^3 W_3, fit EXACTLY by evaluating
    the static weights at 4 nodes and applying the inverse Vandermonde
    (float64). The matmul consumes the extended 64-dim ray feature
    phi(o, d) (x) [1, t, t^2, t^3].

    raw=False -> (64, 4T) matmul table; raw=True -> (T, 64, 4)."""
    nodes = np.array([0.0, 1.0 / 3.0, 2.0 / 3.0, 1.0])
    vand_inv = np.linalg.inv(np.vander(nodes, 4, increasing=True))  # (4,4)
    ws = []
    for t in nodes:
        vt = (1.0 - t) * np.asarray(v0, np.float64) + t * np.asarray(v1, np.float64)
        ws.append(tri_feature_weights_raw(vt, center).astype(np.float64))
    wstack = np.stack(ws, axis=0)  # (4, T, 16, 4) values at nodes
    coeffs = np.einsum("kn,ntfo->ktfo", vand_inv, wstack)  # (4, T, 16, 4)
    # rows: [W0(16) | W1(16) | W2(16) | W3(16)] -> (T, 64, 4)
    wt = np.concatenate([coeffs[k] for k in range(4)], axis=1)
    if raw:
        return wt.astype(np.float32)
    T = len(wt)
    return np.ascontiguousarray(
        wt.transpose(1, 2, 0).reshape(64, 4 * T)
    ).astype(np.float32)


def ray_features_motion(o_c, d, t):
    """phi(o, d) (x) [1, t, t^2, t^3] -> (..., 64)."""
    phi = ray_features(o_c, d)
    tp = jnp.stack(
        [jnp.ones_like(t), t, t * t, t * t * t], axis=-1
    )  # (..., 4)
    return (tp[..., :, None] * phi[..., None, :]).reshape(
        phi.shape[:-1] + (64,)
    )


def tri_feature_weights(verts: np.ndarray, center) -> np.ndarray:
    """(T,3,3) + shared center -> (16, 4T) matmul weights with column
    layout [det (T) | u*det (T) | v*det (T) | t*det (T)]."""
    W = tri_feature_weights_raw(verts, center)
    T = len(W)
    return np.ascontiguousarray(W.transpose(1, 2, 0).reshape(16, 4 * T))


def ray_features(o_c, d):
    """Re-centered origins (...,3) + directions (...,3) -> phi (...,16)."""
    od = o_c[..., :, None] * d[..., None, :]  # (...,3,3) i-major
    one = jnp.ones(o_c.shape[:-1] + (1,), o_c.dtype)
    return jnp.concatenate(
        [od.reshape(od.shape[:-2] + (9,)), d, o_c, one], axis=-1
    )


def decode_outputs(out, n_tris: int, t_max):
    """Matmul output (..., 4T) -> per-ray closest hit over the T columns.

    Returns (t, k, b0, b1) where k is the LOCAL triangle index in [0, T)
    (or arbitrary when t == +inf => miss) and b0/b1 follow the Hit
    convention (b0 = 1-u-v weight of v0, b1 = u weight of v1).
    """
    T = n_tris
    det = out[..., 0 * T : 1 * T]
    udet = out[..., 1 * T : 2 * T]
    vdet = out[..., 2 * T : 3 * T]
    tdet = out[..., 3 * T : 4 * T]
    inv = 1.0 / jnp.where(det == 0.0, 1.0, det)
    u = udet * inv
    v = vdet * inv
    t = tdet * inv
    tm = t_max[..., None] if jnp.ndim(t_max) else t_max
    hit = (
        (det != 0.0)
        & (u >= -EDGE_EPS)
        & (v >= -EDGE_EPS)
        & (u + v <= 1.0 + EDGE_EPS)
        & (t > 0.0)
        & (t < tm)
    )
    t = jnp.where(hit, t, jnp.inf)
    k = jnp.argmin(t, axis=-1)
    t_best = jnp.take_along_axis(t, k[..., None], axis=-1)[..., 0]
    u_best = jnp.take_along_axis(u, k[..., None], axis=-1)[..., 0]
    v_best = jnp.take_along_axis(v, k[..., None], axis=-1)[..., 0]
    b0 = 1.0 - u_best - v_best
    b1 = u_best
    return t_best, k, b0, b1


def brute_feature_intersect(feat, center, n_tris: int, o, d, t_max,
                            chunk=32768, time=None):
    """Closest hit of rays (R,3) against ALL n_tris triangles via one
    feature matmul per ray slab (the small-scene acceleration path:
    Cornell-class scenes need no hierarchy at all on the MXU). A
    64-row feat table (motion blur) consumes the extended time
    features; `time` is the per-ray shutter time in [0,1]."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    R = o.shape[0]
    motion = feat.shape[0] == 64
    if time is None:
        time = jnp.zeros_like(t_max)
    time = jnp.broadcast_to(jnp.asarray(time, jnp.float32), o.shape[:-1])
    n_slabs = max(1, (R + chunk - 1) // chunk)
    pad = n_slabs * chunk - R
    if pad:
        o = jnp.concatenate([o, jnp.zeros((pad, 3), o.dtype)])
        d = jnp.concatenate([d, jnp.ones((pad, 3), d.dtype)])
        t_max = jnp.concatenate([t_max, jnp.full((pad,), -1.0, t_max.dtype)])
        time = jnp.concatenate([time, jnp.zeros((pad,), time.dtype)])

    def slab(args):
        oo, dd, tt, tm = args
        if motion:
            phi = ray_features_motion(oo - center, dd, tm)
        else:
            phi = ray_features(oo - center, dd)
        out = jnp.matmul(phi, feat, precision=jax.lax.Precision.HIGHEST)
        t, k, b0, b1 = decode_outputs(out, n_tris, tt)
        prim = jnp.where(jnp.isfinite(t), k.astype(jnp.int32), -1)
        return t, prim, b0, b1

    t, prim, b0, b1 = jax.lax.map(
        slab,
        (
            o.reshape(n_slabs, chunk, 3),
            d.reshape(n_slabs, chunk, 3),
            t_max.reshape(n_slabs, chunk),
            time.reshape(n_slabs, chunk),
        ),
    )
    flat = lambda a: a.reshape(-1)[:R]  # noqa: E731
    return Hit(flat(t), flat(prim), flat(b0), flat(b1))
