"""Host-side BVH construction -> flattened LinearBVHNode SoA.

Capability match for pbrt-v3 src/accelerators/bvh.{h,cpp} BVHAccel: binned
SAH build (12 buckets, pbrt's leaf/split cost model), plus a Morton-ordered
build standing in for HLBVH, plus 'middle' and 'equal' split methods; the
result is the depth-first flattened LinearBVHNode layout (first child
adjacent, second-child offset, split axis for front-to-back traversal).

TPU-first design: the builder is numpy on the host (scene compile step); the
flattened SoA arrays are uploaded once to HBM and traversed by the device
kernel in accel/traverse.py. The Morton path is fully vectorized (no
per-primitive Python) so multi-million-triangle scenes (crown: ~3.5M) build
in seconds, mirroring HLBVH's role upstream.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_N_BUCKETS = 12
_TRAVERSAL_COST = 0.125  # relative cost: pbrt uses 1/8 node traversal vs isect

# Hard cap on primitives per leaf: the device traversal unrolls exactly this
# many masked triangle tests per leaf visit, so every builder must respect it.
MAX_LEAF_PRIMS = 4


@dataclass
class BVHArrays:
    """Flattened BVH, structure-of-arrays (the LinearBVHNode[] equivalent)."""

    bounds_min: np.ndarray  # (M,3) f32
    bounds_max: np.ndarray  # (M,3) f32
    prim_offset: np.ndarray  # (M,) i32 — first primitive if leaf
    n_prims: np.ndarray  # (M,) i32 — 0 for interior nodes
    second_child: np.ndarray  # (M,) i32 — offset of far child if interior
    axis: np.ndarray  # (M,) i32 — split axis if interior
    prim_order: np.ndarray  # (T,) i64 — permutation old->leaf order

    @property
    def n_nodes(self):
        return len(self.n_prims)


def build_bvh(
    bmin: np.ndarray,
    bmax: np.ndarray,
    method: str = "auto",
    max_leaf_prims: int = 4,
    sah_threshold: int = 262144,
) -> BVHArrays:
    """Build over per-primitive AABBs (T,3)+(T,3).

    method: 'sah' | 'hlbvh' (morton) | 'middle' | 'equal' | 'auto'
    (auto = sah below sah_threshold prims, morton above, matching pbrt's
    guidance that HLBVH trades quality for build speed on huge scenes).
    """
    n = len(bmin)
    assert n > 0, "BVH over zero primitives"
    max_leaf_prims = min(max_leaf_prims, MAX_LEAF_PRIMS)
    bmin = np.asarray(bmin, dtype=np.float64)
    bmax = np.asarray(bmax, dtype=np.float64)
    if method == "auto":
        # with the native builder available, SAH is fast enough for every
        # scene size (crown-class included); only the pure-Python SAH needs
        # the Morton escape hatch above the threshold
        from tpu_pbrt.accel.native import get_lib

        if get_lib() is not None:
            method = "sah"
        else:
            method = "sah" if n <= sah_threshold else "hlbvh"
    if method in ("hlbvh", "lbvh", "morton"):
        return _build_morton(bmin, bmax, max_leaf_prims)
    if method == "sah":
        from tpu_pbrt.accel.native import native_build_sah

        out = native_build_sah(bmin, bmax, max_leaf_prims)
        if out is not None:
            return out
    if method in ("sah", "middle", "equal", "equalcounts"):
        return _build_recursive(bmin, bmax, max_leaf_prims, method)
    raise ValueError(f"unknown BVH split method {method!r}")


# -------------------------------------------------------------------------
# Recursive binned-SAH / middle / equal builder (pbrt recursiveBuild),
# emitting nodes directly in depth-first flattened order.
# -------------------------------------------------------------------------

def _build_recursive(bmin, bmax, max_leaf, method) -> BVHArrays:
    n = len(bmin)
    centroids = 0.5 * (bmin + bmax)

    cap = 2 * n + 1
    out_min = np.empty((cap, 3), dtype=np.float32)
    out_max = np.empty((cap, 3), dtype=np.float32)
    out_prim_off = np.zeros(cap, dtype=np.int32)
    out_nprims = np.zeros(cap, dtype=np.int32)
    out_second = np.zeros(cap, dtype=np.int32)
    out_axis = np.zeros(cap, dtype=np.int32)
    order: list = []
    slot = 0

    # explicit stack of (prim index array, parent_slot or -1 meaning no patch)
    # pushing right-then-left yields pbrt's DFS layout: left child at parent+1
    stack = [(np.arange(n), -1)]
    while stack:
        idx, patch_parent = stack.pop()
        my_slot = slot
        slot += 1
        if patch_parent >= 0:
            out_second[patch_parent] = my_slot
        nb_min = bmin[idx].min(axis=0)
        nb_max = bmax[idx].max(axis=0)
        out_min[my_slot] = nb_min
        out_max[my_slot] = nb_max

        def make_leaf():
            out_prim_off[my_slot] = len(order)
            out_nprims[my_slot] = len(idx)
            order.extend(idx.tolist())

        if len(idx) == 1:
            make_leaf()
            continue
        c = centroids[idx]
        cb_min, cb_max = c.min(axis=0), c.max(axis=0)
        ext = cb_max - cb_min
        dim = int(np.argmax(ext))
        if ext[dim] <= 0:
            # degenerate centroid cluster: leaf if it fits, else force an
            # equal split so no leaf ever exceeds max_leaf (the traversal
            # unrolls exactly that many prim tests)
            if len(idx) <= max_leaf:
                make_leaf()
                continue
            mid = len(idx) // 2
            out_axis[my_slot] = dim
            out_nprims[my_slot] = 0
            stack.append((idx[mid:], my_slot))
            stack.append((idx[:mid], -1))
            continue

        mid = None
        if method == "middle":
            pmid = 0.5 * (cb_min[dim] + cb_max[dim])
            left = c[:, dim] < pmid
            mid = int(left.sum())
            if mid == 0 or mid == len(idx):
                mid = None  # fall through to equal
        if method in ("equal", "equalcounts") or (method == "middle" and mid is None):
            mid = len(idx) // 2
            part = np.argpartition(c[:, dim], mid)
            idx = idx[part]
        elif method == "middle":
            ordr = np.argsort(left)[::-1]  # lefts first
            idx = idx[ordr]
        else:  # SAH
            if len(idx) <= 2:
                mid = len(idx) // 2
                part = np.argpartition(c[:, dim], mid)
                idx = idx[part]
            else:
                t = (c[:, dim] - cb_min[dim]) / ext[dim]
                b = np.minimum((_N_BUCKETS * t).astype(np.int32), _N_BUCKETS - 1)
                # per-bucket counts and bounds
                counts = np.bincount(b, minlength=_N_BUCKETS)
                bk_min = np.full((_N_BUCKETS, 3), np.inf)
                bk_max = np.full((_N_BUCKETS, 3), -np.inf)
                np.minimum.at(bk_min, b, bmin[idx])
                np.maximum.at(bk_max, b, bmax[idx])
                # prefix/suffix accumulation of bounds+counts
                cmin_f = np.minimum.accumulate(bk_min, axis=0)
                cmax_f = np.maximum.accumulate(bk_max, axis=0)
                cnt_f = np.cumsum(counts)
                cmin_b = np.minimum.accumulate(bk_min[::-1], axis=0)[::-1]
                cmax_b = np.maximum.accumulate(bk_max[::-1], axis=0)[::-1]
                cnt_b = np.cumsum(counts[::-1])[::-1]

                def area(mn, mx):
                    d = np.maximum(mx - mn, 0)
                    return 2 * (d[..., 0] * d[..., 1] + d[..., 0] * d[..., 2] + d[..., 1] * d[..., 2])

                a0 = area(cmin_f[:-1], cmax_f[:-1])
                a1 = area(cmin_b[1:], cmax_b[1:])
                total_area = max(area(nb_min, nb_max), 1e-30)
                cost = _TRAVERSAL_COST + (cnt_f[:-1] * a0 + cnt_b[1:] * a1) / total_area
                valid = (cnt_f[:-1] > 0) & (cnt_b[1:] > 0)
                cost = np.where(valid, cost, np.inf)
                best = int(np.argmin(cost))
                leaf_cost = float(len(idx))
                if len(idx) > max_leaf or cost[best] < leaf_cost:
                    if not valid.any():
                        mid = len(idx) // 2
                        part = np.argpartition(c[:, dim], mid)
                        idx = idx[part]
                    else:
                        left = b <= best
                        mid = int(left.sum())
                        idx = idx[np.argsort(~left, kind="stable")]
                else:
                    make_leaf()
                    continue
        out_axis[my_slot] = dim
        out_nprims[my_slot] = 0
        stack.append((idx[mid:], my_slot))  # right (far) — patched later
        stack.append((idx[:mid], -1))  # left — next slot
    return BVHArrays(
        bounds_min=out_min[:slot].copy(),
        bounds_max=out_max[:slot].copy(),
        prim_offset=out_prim_off[:slot].copy(),
        n_prims=out_nprims[:slot].copy(),
        second_child=out_second[:slot].copy(),
        axis=out_axis[:slot].copy(),
        prim_order=np.asarray(order, dtype=np.int64),
    )


# -------------------------------------------------------------------------
# Morton build (HLBVH stand-in): sort by 30-bit Morton code, complete
# binary tree over equal-count runs, bounds by level reduction, DFS
# numbering computed level-by-level — all vectorized.
# -------------------------------------------------------------------------

def _expand_bits(v: np.ndarray) -> np.ndarray:
    """Spread 10 bits to every 3rd position (pbrt LeftShift3)."""
    v = v.astype(np.uint64)
    v = (v | (v << 16)) & np.uint64(0x30000FF)
    v = (v | (v << 8)) & np.uint64(0x300F00F)
    v = (v | (v << 4)) & np.uint64(0x30C30C3)
    v = (v | (v << 2)) & np.uint64(0x9249249)
    return v


def morton_codes(points: np.ndarray, scene_min, scene_max) -> np.ndarray:
    """30-bit 3D Morton codes of points within [scene_min, scene_max]."""
    ext = np.maximum(np.asarray(scene_max) - np.asarray(scene_min), 1e-30)
    q = np.clip((points - scene_min) / ext * 1024.0, 0, 1023).astype(np.uint32)
    return (
        (_expand_bits(q[:, 2]) << np.uint64(2))
        | (_expand_bits(q[:, 1]) << np.uint64(1))
        | _expand_bits(q[:, 0])
    )


def _build_morton(bmin, bmax, max_leaf) -> BVHArrays:
    n = len(bmin)
    centroids = 0.5 * (bmin + bmax)
    codes = morton_codes(centroids, bmin.min(axis=0), bmax.max(axis=0))
    order = np.argsort(codes, kind="stable").astype(np.int64)

    # leaves: contiguous runs of max_leaf prims in morton order
    n_leaves = (n + max_leaf - 1) // max_leaf
    depth = max(1, int(np.ceil(np.log2(max(n_leaves, 2)))))
    full = 1 << depth  # complete tree with `full` leaf slots

    # pad: empty leaf slots get degenerate bounds and 0 prims
    leaf_starts = np.arange(n_leaves) * max_leaf
    leaf_counts = np.minimum(max_leaf, n - leaf_starts).astype(np.int32)

    sm = bmin[order].astype(np.float32)
    sx = bmax[order].astype(np.float32)
    # per-leaf bounds via reduceat
    lmin = np.minimum.reduceat(sm, leaf_starts, axis=0)
    lmax = np.maximum.reduceat(sx, leaf_starts, axis=0)

    pad = full - n_leaves
    if pad:
        lmin = np.vstack([lmin, np.full((pad, 3), np.inf, np.float32)])
        lmax = np.vstack([lmax, np.full((pad, 3), -np.inf, np.float32)])
        leaf_starts = np.concatenate([leaf_starts, np.full(pad, n)])
        leaf_counts = np.concatenate([leaf_counts, np.zeros(pad, np.int32)])

    # level bounds bottom-up: levels[d] has 2^d nodes
    lv_min = [lmin]
    lv_max = [lmax]
    for _ in range(depth):
        lv_min.append(np.minimum(lv_min[-1][0::2], lv_min[-1][1::2]))
        lv_max.append(np.maximum(lv_max[-1][0::2], lv_max[-1][1::2]))
    lv_min.reverse()
    lv_max.reverse()  # lv_min[0] = root level (1 node) ... lv_min[depth] = leaves

    # DFS numbering: every interior node has subtree size 2*half_leaves-1 where
    # the tree below is complete; dfs(left)=dfs(v)+1, dfs(right)=dfs(v)+1+size(left)
    m_total = 2 * full - 1
    dfs = [np.zeros(1, dtype=np.int64)]
    for d in range(depth):
        size_child = (1 << (depth - d)) - 1  # subtree size of each child
        child = np.empty(2 << d, dtype=np.int64)
        child[0::2] = dfs[d] + 1
        child[1::2] = dfs[d] + 1 + size_child
        dfs.append(child)

    out_min = np.empty((m_total, 3), np.float32)
    out_max = np.empty((m_total, 3), np.float32)
    out_prim_off = np.zeros(m_total, np.int32)
    out_nprims = np.zeros(m_total, np.int32)
    out_second = np.zeros(m_total, np.int32)
    out_axis = np.zeros(m_total, np.int32)
    for d in range(depth + 1):
        ids = dfs[d]
        out_min[ids] = lv_min[d]
        out_max[ids] = lv_max[d]
        if d < depth:
            out_second[ids] = dfs[d + 1][1::2]
            # split axis: largest extent of the node bounds (approximation;
            # morton splits cycle xyz but extent ordering works for traversal)
            out_axis[ids] = np.argmax(lv_max[d] - lv_min[d], axis=1)
        else:
            out_prim_off[ids] = leaf_starts
            out_nprims[ids] = leaf_counts
    # empty padded leaves keep inf/-inf bounds -> never hit by slab test
    return BVHArrays(out_min, out_max, out_prim_off, out_nprims, out_second, out_axis, order)


def triangle_bounds(verts: np.ndarray):
    """(T,3,3) world-space triangle vertices -> AABB arrays (T,3),(T,3)."""
    return verts.min(axis=1), verts.max(axis=1)
