"""Wide (8-ary) BVH: the TPU-shaped acceleration structure.

Capability match for pbrt-v3 src/accelerators/bvh.cpp BVHAccel::Intersect /
IntersectP — same watertight leaf tests, same closest-hit semantics — but
re-designed for the hardware (SURVEY.md §7 "the hard parts" #1/#2):

- The binary LinearBVHNode walk visits thousands of nodes per ray worst
  case, and a vmapped lockstep while_loop makes EVERY lane pay the worst
  lane's iteration count, with 4-byte scattered gathers each step. On TPU
  that is catastrophic (measured ~30 s per 16k-ray path chunk).
- The wide BVH collapses the binary tree into nodes of up to 8 children.
  One iteration pops a node and slab-tests all 8 child AABBs at once from
  ONE contiguous 48-float row (XLA lowers the row gather to efficient
  vector loads), cutting max iterations by ~4-8x and turning memory traffic
  from scattered scalars into dense rows. Children are pushed far-to-near
  (8-element argsort) so near subtrees pop first, preserving the binary
  version's front-to-back early-out behavior.
- Leaf triangle data is fetched as one contiguous (MAX_LEAF_PRIMS*9)-float
  dynamic slice per leaf pop instead of per-step unrolled gathers.

Build: host-side collapse of the flattened binary BVH (accel/build.py)
by repeatedly expanding the largest-surface-area child until 8 slots fill.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.build import MAX_LEAF_PRIMS, BVHArrays
from tpu_pbrt.accel.traverse import Hit, intersect_triangle
from tpu_pbrt.core.vecmath import gamma

WIDTH = 8
# worst-case occupancy is (WIDTH-1)*depth + 1, checked loudly in build_wide;
# 128 covers depth 18 (~8^18 nodes) at 512 B/lane of while_loop state
MAX_STACK = 128
_BOX_EPS = 1.0 + 2.0 * gamma(3)
# wide-leaf encoding in child_idx: >= 0 interior node id;
# < 0 leaf: -(1 + prim_offset * (MAX_LEAF_PRIMS+1) + n_prims)
_LEAF_STRIDE = MAX_LEAF_PRIMS + 1
_EMPTY = np.int32(2**30)  # empty slot: bounds are +inf/-inf, never hit


def slab_test(nmin, nmax, o, inv_d, t_far):
    """Conservative watertight ray/AABB slab test, shared by every walker
    (wide/packet/stream) so the epsilon and NaN semantics cannot diverge.

    nmin/nmax: (..., 3) child bounds; o/inv_d: (..., 3) broadcastable ray;
    t_far: (...) far clip (current closest hit). Returns (t_near, t_far,
    hit) with t_near >= 0 and the 0*inf NaN treated as inside-slab (pbrt's
    conservative ordering: bvh.cpp IntersectP's gamma-widened slabs)."""
    lo = jnp.where(inv_d < 0, nmax, nmin)
    hi = jnp.where(inv_d < 0, nmin, nmax)
    t0 = (lo - o) * inv_d
    t1 = (hi - o) * inv_d * _BOX_EPS
    t0 = jnp.where(jnp.isnan(t0), -jnp.inf, t0)
    t1 = jnp.where(jnp.isnan(t1), jnp.inf, t1)
    tn = jnp.maximum(jnp.max(t0, axis=-1), 0.0)
    tf = jnp.minimum(jnp.min(t1, axis=-1), t_far)
    return tn, tf, tn <= tf


def slab_test_lane_major(b_lo, b_hi, o_c, inv_c):
    """Per-AXIS half of slab_test for lane-major layouts (the stream
    walker's (8, S) arrays): returns this axis's (t0, t1) with the SAME
    _BOX_EPS widening and NaN rules as slab_test above — one source for
    the watertightness semantics, two layouts. Callers combine the three
    axes with explicit min/max chains (no axis reductions) and clamp
    t_near to 0 / t_far to the ray's current hit themselves."""
    lo = jnp.where(inv_c < 0, b_hi, b_lo)
    hi = jnp.where(inv_c < 0, b_lo, b_hi)
    t0 = (lo - o_c) * inv_c
    t1 = (hi - o_c) * inv_c * _BOX_EPS
    t0 = jnp.where(jnp.isnan(t0), -jnp.inf, t0)
    t1 = jnp.where(jnp.isnan(t1), jnp.inf, t1)
    return t0, t1


class WideBVH(NamedTuple):
    child_bmin: jnp.ndarray  # (N, 8, 3)
    child_bmax: jnp.ndarray  # (N, 8, 3)
    child_idx: jnp.ndarray  # (N, 8) encoded


def _area(bmin, bmax):
    d = np.maximum(bmax - bmin, 0)
    return 2 * (d[0] * d[1] + d[0] * d[2] + d[1] * d[2])


def build_wide(bvh: BVHArrays) -> WideBVH:
    """Collapse the flattened binary BVH into 8-wide nodes (host).

    Leaf triangle data is NOT duplicated here: traversal slices the shared
    leaf-order triangle array (`pad_tri_verts` of it) that the scene
    compiler uploads once for both traversal and interaction lookup."""
    n_prims_b = bvh.n_prims
    second = bvh.second_child
    bmin_b = bvh.bounds_min
    bmax_b = bvh.bounds_max
    off_b = bvh.prim_offset

    def leaf_code(b):
        return -(1 + int(off_b[b]) * _LEAF_STRIDE + int(n_prims_b[b]))

    def is_interior(b):
        # the Morton builder pads its complete tree with empty leaves
        # (n_prims == 0, second == 0, inf/-inf bounds); only a forward
        # second-child pointer marks a real interior node
        return n_prims_b[b] == 0 and int(second[b]) > b

    def is_empty_leaf(b):
        return n_prims_b[b] == 0 and int(second[b]) <= b

    wide_nodes = []  # each: list of (binary node id or leaf-code, bmin, bmax)
    # map binary node id -> wide node id (filled as we emit)
    emit_queue = [0]
    wide_id_of: dict = {}

    if n_prims_b[0] > 0:
        # degenerate single-leaf tree
        children = [(leaf_code(0), bmin_b[0], bmax_b[0])]
        wide_nodes.append(children)
    else:
        wide_id_of[0] = 0
        wide_nodes.append(None)  # placeholder
        queue = [0]
        while queue:
            b = queue.pop()
            # expand b's children until 8 slots: keep a worklist of binary
            # subtree roots, split the largest-area interior one each step
            slots = [b + 1, int(second[b])]
            while len(slots) < WIDTH:
                best = -1
                best_a = -1.0
                for i, sb in enumerate(slots):
                    if is_interior(sb):
                        a = _area(bmin_b[sb], bmax_b[sb])
                        if a > best_a:
                            best_a = a
                            best = i
                if best < 0:
                    break
                sb = slots.pop(best)
                slots.append(sb + 1)
                slots.append(int(second[sb]))
            children = []
            for sb in slots:
                if is_empty_leaf(sb):
                    continue  # unhittable padding: no slot at all
                if n_prims_b[sb] > 0:
                    children.append((leaf_code(sb), bmin_b[sb], bmax_b[sb]))
                else:
                    wid = wide_id_of.get(sb)
                    if wid is None:
                        wid = len(wide_nodes)
                        wide_id_of[sb] = wid
                        wide_nodes.append(None)
                        queue.append(sb)
                    children.append((wid, bmin_b[sb], bmax_b[sb]))
            wide_nodes[wide_id_of[b]] = children

    n = len(wide_nodes)
    cmin = np.full((n, WIDTH, 3), np.inf, np.float32)
    cmax = np.full((n, WIDTH, 3), -np.inf, np.float32)
    cidx = np.full((n, WIDTH), _EMPTY, np.int32)
    for i, children in enumerate(wide_nodes):
        for k, (code, bmn, bmx) in enumerate(children):
            cidx[i, k] = code
            cmin[i, k] = bmn
            cmax[i, k] = bmx

    # Loud stack check (replaces a silent top-slot clamp): children always
    # get larger wide ids than their parent, so a reverse pass computes
    # interior depth; each interior pop frees 1 slot and pushes <= WIDTH,
    # giving worst-case occupancy (WIDTH-1)*depth + 1.
    depth = np.ones(n, np.int64)
    for i in range(n - 1, -1, -1):
        for code, _, _ in wide_nodes[i]:
            if code >= 0:
                depth[i] = max(depth[i], 1 + depth[code])
    worst = (WIDTH - 1) * int(depth[0]) + 1
    if worst > MAX_STACK:
        raise ValueError(
            f"wide BVH depth {int(depth[0])} needs stack {worst} > MAX_STACK="
            f"{MAX_STACK}; raise MAX_STACK in accel/wide.py"
        )

    return WideBVH(
        child_bmin=jnp.asarray(cmin),
        child_bmax=jnp.asarray(cmax),
        child_idx=jnp.asarray(cidx),
    )


def pad_tri_verts(tri_verts_leaf_order: np.ndarray) -> np.ndarray:
    """Pad the leaf-order (T,3,3) vertex array with MAX_LEAF_PRIMS zero rows
    so the fixed-size leaf dynamic_slice never reads past the end. The
    padded rows are degenerate triangles (det == 0 -> never hit), so the
    same array safely serves brute-force oracles and interaction gathers."""
    tv = np.ascontiguousarray(tri_verts_leaf_order, dtype=np.float32)
    return np.concatenate([tv, np.zeros((MAX_LEAF_PRIMS, 3, 3), np.float32)], axis=0)


# -------------------------------------------------------------------------
# Device traversal
# -------------------------------------------------------------------------

class _WState(NamedTuple):
    sp: jnp.ndarray
    stack: jnp.ndarray
    t: jnp.ndarray
    prim: jnp.ndarray
    b0: jnp.ndarray
    b1: jnp.ndarray
    iters: jnp.ndarray


_MAX_ITERS = 16384  # safety bound; real traversals finish in hundreds


def _ray_traverse_wide(w: WideBVH, tri_flat, o, d, t_max, any_hit: bool):
    inv_d = 1.0 / d

    def cond(s: _WState):
        return (s.sp > 0) & (s.iters < _MAX_ITERS)

    def body(s: _WState):
        sp = s.sp - 1
        code = s.stack[sp]
        is_leaf = code < 0

        # ---- leaf: contiguous triangle block test -----------------------
        leaf_dec = -(code + 1)
        off = jnp.where(is_leaf, leaf_dec // _LEAF_STRIDE, 0)
        cnt = jnp.where(is_leaf, leaf_dec % _LEAF_STRIDE, 0)
        tri_block = jax.lax.dynamic_slice(
            tri_flat, (off * 9,), (MAX_LEAF_PRIMS * 9,)
        ).reshape(MAX_LEAF_PRIMS, 3, 3)
        h, th, b0h, b1h = intersect_triangle(
            o, d, tri_block[:, 0], tri_block[:, 1], tri_block[:, 2], s.t
        )
        take = is_leaf & (jnp.arange(MAX_LEAF_PRIMS, dtype=jnp.int32) < cnt) & h
        th_m = jnp.where(take, th, jnp.inf)
        k = jnp.argmin(th_m)
        better = th_m[k] < s.t
        t_new = jnp.where(better, th_m[k], s.t)
        prim_new = jnp.where(better, off + k, s.prim)
        b0_new = jnp.where(better, b0h[k], s.b0)
        b1_new = jnp.where(better, b1h[k], s.b1)

        # ---- interior: 8-wide slab test + ordered push ------------------
        node = jnp.where(is_leaf, 0, code)
        nmin = w.child_bmin[node]  # (8,3) one contiguous row
        nmax = w.child_bmax[node]
        cids = w.child_idx[node]
        tn, _, in_slab = slab_test(nmin, nmax, o, inv_d, t_new)
        hit8 = (~is_leaf) & in_slab & (cids != _EMPTY)

        # push far-to-near so near children pop first
        key = jnp.where(hit8, tn, -jnp.inf)
        order = jnp.argsort(key)  # misses (-inf) first, then near..far
        # stack depth is validated loudly at build time (build_wide), so the
        # push needs no runtime clamp
        stack = s.stack
        sp_new = sp
        for j in range(WIDTH - 1, -1, -1):  # far .. near
            c = order[j]
            do = hit8[c]
            stack = jnp.where(do, stack.at[sp_new].set(cids[c]), stack)
            sp_new = jnp.where(do, sp_new + 1, sp_new)

        done_early = jnp.where(any_hit & (prim_new >= 0), jnp.int32(0), sp_new)
        return _WState(done_early, stack, t_new, prim_new, b0_new, b1_new, s.iters + 1)

    init = _WState(
        sp=jnp.int32(1),
        stack=jnp.zeros((MAX_STACK,), jnp.int32),  # stack[0] = root node 0
        t=jnp.asarray(t_max, jnp.float32),
        prim=jnp.int32(-1),
        b0=jnp.float32(0),
        b1=jnp.float32(0),
        iters=jnp.int32(0),
    )
    out = jax.lax.while_loop(cond, body, init)
    return Hit(out.t, out.prim, out.b0, out.b1)


@jax.jit
def wide_intersect(w: WideBVH, tri_verts, o, d, t_max) -> Hit:
    """Closest-hit over a ray batch against the wide BVH. tri_verts is the
    shared padded leaf-order vertex array (see pad_tri_verts)."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    tri_flat = tri_verts.reshape(-1)
    return jax.vmap(lambda oo, dd, tt: _ray_traverse_wide(w, tri_flat, oo, dd, tt, False))(o, d, t_max)


@jax.jit
def wide_intersect_p(w: WideBVH, tri_verts, o, d, t_max) -> jnp.ndarray:
    """Any-hit (shadow) predicate over a ray batch."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    tri_flat = tri_verts.reshape(-1)
    hit = jax.vmap(lambda oo, dd, tt: _ray_traverse_wide(w, tri_flat, oo, dd, tt, True))(o, d, t_max)
    return hit.prim >= 0
