"""Stream (sort/compaction wavefront) BVH traversal — the fast trace path.

Capability match for pbrt-v3 src/accelerators/bvh.cpp
BVHAccel::Intersect/IntersectP (same closest-hit/any-hit semantics over the
same SAH tree), re-architected a third time for TPU execution behavior.

Why not the packet walk (accel/packet.py): packets amortize node fetches
only while the 128 rays in a packet agree on a traversal path. Bounce rays
(cosine-sampled hemispheres) disagree almost immediately, the packet's
union frustum covers the whole scene, and every lane pays for every node
any lane wants — measured 4 orders of magnitude slower than coherent
camera rays on the same kernel.

Why not a per-ray stack walk (accel/wide.py): a vmapped while_loop makes
every ray pay the worst ray's iteration count, and each iteration moves a
few hundred bytes per ray — far below the row sizes TPU memory wants.

The stream design has NO per-ray control flow at all. Traversal state is
one flat LIFO worklist of (ray, node, t_entry) pairs shared by the whole
wave, processed in large dense slabs. Primitive costs measured on this
v5e (distinct inputs per dispatch, host-fetch timing — the tunnel
memoizes repeats) dictate the shape of every step:

- jax.lax.sort hits a FAST radix-like path only for INT32 keys with at
  most 3 operand arrays (~1 ms / 1M elements); a float key or a 4th
  array falls back to a comparator sort (~7 ms / 1M). Every sort in this
  file therefore uses a single packed-i32 key and <= 3 arrays.
- random gathers cost ~10-30 ns per INDEX (layout-insensitive), but
  nearly-sorted indices approach ~1 ns/element; scatters are worst of
  all. Gathers from SMALL tables are instead computed on the MXU as a
  one-hot matmul (~0.4 ms for 131k lookups of a 48-float row vs ~8 ms
  for the native gather).

EXPAND pops a slab of SLAB pairs at once (one contiguous dynamic_slice),
culls pairs whose recorded entry distance already exceeds their ray's
current hit, slab-tests each pair's ray against its node's 8 child boxes
in one dense (8, SLAB) lane-major test. The node's 8 child boxes AND the
8 child codes (as two exact 16-bit halves) ride ONE one-hot matmul:
(64, N) static table @ (N, S) one-hot at Precision.HIGHEST — exact for
the integer rows, and within 1 ulp for the box rows, absorbed by the
slab test's _BOX_EPS widening. The 8*SLAB child candidates are then
compacted with ONE 2-array int-key sort whose packed key is

    leaf:     ray                                  (sorts first)
    interior: 2^30 + (ray << TN_BITS) + ~quant(t_entry)
    dead:     INT32_MAX

so leaves compact to the front (appended to the leaf buffer with one
contiguous write), interiors land grouped BY RAY with each ray's nearest
children pushed on top of the LIFO stack (per-ray front-to-back order —
stronger culling than any global distance order, because only a ray's
OWN near leaves can tighten its t), and the ray-major order makes every
downstream per-ray gather (o/inv_d/t) nearly sorted. The entry distance
lives ONLY in the key's low quantized bits: the pop-side cull rebuilds
a conservative underestimate from them (mantissa tail zero-filled), so
dropping the exact f32 plane costs a fraction of a percent of extra
pairs but removes a third sort array and a whole stack plane.

FLUSH runs when the leaf buffer is nearly full (or the stack empties):
it sorts the buffered (ray, treelet) pairs by a packed (treelet << RAY_
BITS | ray) key, so each treelet's rays form one contiguous, ray-sorted
run; block starts are recovered with a second single-array int sort
(position-of-k-th-set-bit via sort — searchsorted is ~100x slower on
TPU), and each 128-ray block is intersected against its treelet's
triangles in one MXU feature matmul (accel/mxu.py): (128, 16) ray
features x (16, 4L) per-treelet Moller-Trumbore weights. Closest hits
merge per chunk by sorting the chunk's candidates on a packed
(ray, t-bits) key pair and scattering only each ray-run's HEAD (its
argmin): two small mostly-dropped scatters at sorted unique indices
replace the per-slot scatter-min + equality-select pair that dominated
the round-3 profile.

Sequential depth per wave is ~(total pairs / SLAB) big dense steps, and
leaf work lands on the MXU in (128, 16) @ (16, 4L) tiles regardless of
ray order. Ray coherence changes only the pair COUNT, never the
execution shape. Dead lanes (t_max <= 0) are sorted out of the initial
stack, so bounce/shadow waves cost ~(live rays), not R.

The acceleration structure is the same two-level TreeletPack as the
packet walk (accel/treelet.py) with fatter leaves (STREAM_LEAF_TRIS):
the MXU makes triangle tests nearly free, so trading deeper trees for
fatter matmuls moves work from the latency-bound worklist to the
compute units.

TPU_PBRT_FUSED selects between two compilations of the SAME algorithm
(bit-identical by contract): the jnp path above, and the fused Pallas
wavefront kernels (accel/fusedwave.py) that run each flush chunk and
each expansion's dense middle as one grid with the ray tables, winner
accumulators and node table VMEM-resident — only the sort-based
compactions stay at jnp level. See _use_fused / the fusedwave module
doc for the gates and the VMEM budget math.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.mxu import decode_outputs
from tpu_pbrt.accel.traverse import Hit
from tpu_pbrt.config import cfg
from tpu_pbrt.accel.treelet import TreeletPack, decode_top_leaf
from tpu_pbrt.accel.wide import _EMPTY, slab_test_lane_major

#: triangles per treelet for the stream path (feature row = 4*this
#: columns). Swept on the v5e bench: 256 -> 0.61 Mray/s, 512 -> 0.73
#: (fewer worklist pairs; the fatter matmul is nearly free on the MXU),
#: 1024 -> 0.36 (matmul cost finally dominates).
STREAM_LEAF_TRIS = 512
#: rays per leaf block — the MXU matmul's row dimension
BLOCK = 128
#: leaf blocks processed per flush chunk (bounds transient memory: the
#: chunk's matmul output is CHUNK*BLOCK*4L floats)
CHUNK = 512
#: safety bound on while_loop iterations (real waves take tens to hundreds)
_MAX_ITERS = 1 << 16
#: above this top-node count the one-hot box matmul's N dimension costs
#: more than the native gather it replaces — and its materialized (N, S)
#: one-hot operand (N * 131072 * 4 bytes per EXPAND) starts to threaten
#: HBM. 512 is the largest measured-good size (~268 MB operand).
_ONEHOT_MAX_NODES = 512

_I32_MAX = np.int32(2**31 - 1)


def _use_fused(R: int) -> bool:
    """Static (trace-time) switch for the fused Pallas wavefront kernels
    (accel/fusedwave.py): TPU_PBRT_FUSED=1 forces them on (interpret
    mode on CPU — the testing story), =0 forces the jnp path, unset
    means auto (on for TPU backends). TPU_PBRT_PALLAS=0 remains the
    global escape hatch. Waves past TPU_PBRT_FUSED_MAX_RAYS fall back
    to the jnp path: the fused kernels keep the (8, R) ray table and
    the (R,) winner accumulators VMEM-resident (budget math in the
    fusedwave module doc / README)."""
    if not cfg.pallas:
        return False
    f = cfg.fused
    if f is None:
        f = jax.default_backend() not in ("cpu",)
    if not f:
        return False
    return R <= int(cfg.fused_max_rays)


def _fused_interpret() -> bool:
    """Pallas interpret mode off-TPU: same sequential grid semantics,
    pure-XLA execution — how tier-1 tests and the chaos matrix exercise
    the fused kernels on CPU."""
    return jax.default_backend() in ("cpu",)


def tracer_mode(R: int = 1 << 16) -> str:
    """Static tracer attribution for telemetry/bench: which leaf/flush
    path a wave of R rays would compile to ('fused' | 'jnp')."""
    return "fused" if _use_fused(R) else "jnp"


def flush_geometry(R: int, n_treelets: int) -> dict:
    """Static flush-phase shape for a wave of R rays: worklist sizes
    and the per-flush block capacity (bench.py records
    blocks_per_flush as `fused_blocks_per_flush` so live captures can
    attribute the roofline ratio to the right kernel)."""
    slab, w, lb = _sizes(R)
    b_cap = lb // BLOCK + n_treelets + 2
    return {
        "slab": slab,
        "worklist": w,
        "leaf_buffer": lb,
        "blocks_per_flush": b_cap,
        "chunk": min(CHUNK, b_cap),
        "tracer_mode": tracer_mode(R),
    }


def clear_traverse_caches() -> None:
    """Drop the jit caches of every module-level traversal entry point.

    These cache by aval shape alone, so any trace-time mode flip with
    unchanged shapes (a TPU_PBRT_FUSED reload, audit's forced_tracer,
    tests flipping knobs) MUST call this or a later trace — even from a
    brand-new integrator — inlines a stale inner jaxpr. One definition
    here so stage two adding an entry point updates every caller."""
    for f in (stream_intersect, stream_intersect_split, _traverse_p,
              stream_traverse_stats):
        f.clear_cache()


def _use_onehot(n_nodes: int) -> bool:
    if not cfg.onehot:
        return False
    return n_nodes <= _ONEHOT_MAX_NODES


class _SState(NamedTuple):
    # Lane-major per-ray tables. Multi-row takes on this v5e cost
    # ~2 ns per fetched ELEMENT (not per index), so each consumer gets
    # its own 8-row table holding exactly what it reads, fetched in ONE
    # take: rayE for EXPAND [o(0:3) inv_d(3:6) t(6) pad], rayF for FLUSH
    # [o(0:3) d(3:6) t(6) pad]. Row 6 (the ray's current closest hit) is
    # kept identical in both: the merge updates it once via a 1D scatter
    # and writes it back with two contiguous dynamic_update_slices
    # (carrying a separate (R,) t array instead made XLA re-lay-out the
    # tables every iteration, ~130 ms/wave).
    rayE: jnp.ndarray  # (8, R) f32
    rayF: jnp.ndarray  # (8, R) f32
    prim: jnp.ndarray  # (R,) i32 global leaf-order triangle id, -1 miss
    stk_key: jnp.ndarray  # (W + headroom,) i32 packed (2^30 | ray<<TN | ~qtn)
    stk_code: jnp.ndarray  # (W + headroom,) i32 top-tree node id
    n_stk: jnp.ndarray  # i32
    lf_ray: jnp.ndarray  # (LB + headroom,) i32 ray ids (= leaf sort keys)
    lf_tid: jnp.ndarray  # (LB + headroom,) i32 treelet ids
    n_lf: jnp.ndarray  # i32
    n_drop: jnp.ndarray  # i32 pairs lost to capacity (tests assert 0)
    n_exp: jnp.ndarray  # i32 stat: pairs expanded
    n_tl: jnp.ndarray  # i32 stat: (ray, treelet) block-slot tests
    iters: jnp.ndarray  # i32


def _sizes(R: int):
    """Static worklist sizes for a wave of R rays.

    Slab-size tradeoff, measured on this v5e (1M-ray camera wave):
    bigger slabs amortize per-step dispatch cost but DELAY flushes, so
    per-ray closest-t stays loose longer and the wave expands more
    pairs. The default keeps the tighter-culling small slab;
    TPU_PBRT_SLAB overrides for experiments."""
    cap = int(cfg.slab)
    slab = int(min(max(R // 4, 4096), cap))
    # TPU_PBRT_HEADROOM scales the worklist headroom (default 1.0);
    # the capacity-overflow regression test shrinks it to force drops.
    # Floors: the stack must hold at least one push burst, and the leaf
    # buffer must exceed the 8*slab flush threshold or _traverse would
    # flush empty buffers forever.
    head = float(cfg.headroom)
    w = R + max(int(24 * slab * head), slab // 2)
    lb = max(int(12 * slab * head), 9 * slab)
    return slab, w, lb


def _bits(x):
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _unbits(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


def _ray_bits(R: int) -> int:
    rb = max(1, int(np.ceil(np.log2(max(R, 2)))))
    if rb > 29:
        raise ValueError(
            f"stream tracer waves are capped at 2^29 rays (got {R}); "
            "chunk the wave at the integrator level"
        )
    return rb


def _tn_bits(R: int) -> int:
    # interior keys live in [2^30, 2^30 + 2^(rb+tn)) which must stay
    # below INT32_MAX; rb + tn <= 29 guarantees it with room to spare
    return max(0, min(12, 29 - _ray_bits(R)))


def _node_table(boxT, cidT):
    """(64, N) f32 one-hot-matmul table: rows 0..47 the 8 child boxes
    (component-major, flattened from the caller's (6, 8, N) boxT so the
    two fetch paths share one layout), rows 48..55 / 56..63 the child
    codes' low/high 16-bit halves (exact in f32; reassembled bitwise).
    +-inf box bounds are clamped to +-3e38: inf * 0.0 in the matmul
    would poison the one-hot sum with NaN."""
    N = boxT.shape[2]
    box48 = jnp.clip(boxT.reshape(48, N), -3e38, 3e38)
    lo = (cidT & 0xFFFF).astype(jnp.float32)
    hi = ((cidT >> 16) & 0xFFFF).astype(jnp.float32)
    return jnp.concatenate([box48, lo, hi], axis=0)  # (64, N)


def _fetch_children(tab64, boxT, cidT, node, use_onehot: bool):
    """Per-pair child boxes (6, 8, S) + child codes (8, S) for node ids
    (S,). Small top trees ride the MXU (one-hot matmul); big ones fall
    back to native gathers."""
    S = node.shape[0]
    N = boxT.shape[2]
    if use_onehot:
        oh = (node[None, :] == jnp.arange(N, dtype=jnp.int32)[:, None]).astype(
            jnp.float32
        )  # (N, S)
        out = jax.lax.dot(
            tab64, oh, precision=jax.lax.Precision.HIGHEST
        )  # (64, S)
        nb = out[:48].reshape(6, 8, S)
        lo = jnp.round(out[48:56]).astype(jnp.int32)
        hi = jnp.round(out[56:64]).astype(jnp.int32)
        cids = (hi << 16) | lo
    else:
        nb = jnp.take(boxT, node, axis=2)  # (6, 8, S)
        cids = jnp.take(cidT, node, axis=1)  # (8, S)
    return nb, cids


def _expand(tp: TreeletPack, tab64, boxT, cidT, s: _SState, slab: int,
            w: int, lb: int, any_hit: bool, use_onehot: bool,
            use_fused: bool = False):
    R = s.rayE.shape[1]
    rb = _ray_bits(R)
    tb = _tn_bits(R)
    start = jnp.maximum(s.n_stk - slab, 0)
    k = jnp.arange(slab, dtype=jnp.int32)
    valid = k < (s.n_stk - start)
    key_in = jnp.where(
        valid, jax.lax.dynamic_slice(s.stk_key, (start,), (slab,)), _I32_MAX
    )
    node = jnp.where(valid, jax.lax.dynamic_slice(s.stk_code, (start,), (slab,)), 0)
    if use_fused:
        # the dense middle of the expansion — ray fetch, child fetch,
        # slab tests, push-key build — runs as ONE Pallas grid with the
        # popped slab and the node table resident in VMEM
        # (accel/fusedwave.py; bit-identical by construction). Only the
        # (8, S) key/candidate planes come back to HBM for the
        # compaction sort below — lax.sort stays at jnp level, where
        # the int-key radix fast path lives. The kernel may pad S up to
        # its grid tile; pad lanes are dead keys the sort drops.
        from tpu_pbrt.accel.fusedwave import fused_expand

        key8, cand8, live_i = fused_expand(
            key_in, node, s.rayE, s.prim,
            tab64 if use_onehot else None,
            None if use_onehot else boxT.reshape(48, -1),
            None if use_onehot else cidT,
            tb=tb, use_onehot=use_onehot, any_hit=any_hit,
            interpret=_fused_interpret(),
        )
        key = key8.reshape(-1)
        cand_code = cand8.reshape(-1)
        n_leaf = jnp.sum(key < (1 << 30), dtype=jnp.int32)
        n_int = jnp.sum(
            (key >= (1 << 30)) & (key != _I32_MAX), dtype=jnp.int32
        )
        key_s, code_s = jax.lax.sort([key, cand_code], num_keys=1)
        s8 = 8 * slab
        return _expand_push(
            s, key_s, code_s, n_leaf, n_int, live_i, start, w, lb, s8
        )
    # stack entries are always interiors: ray id sits at key bits
    # [tb, tb+rb); the low tb bits hold the complemented quantized entry
    # distance, reconstructed here by zero-filling the mantissa tail —
    # a value <= the true t_entry, so the pop cull stays conservative
    # (carrying the exact f32 cost a third sort array + stack plane)
    rid = jnp.clip((key_in - (1 << 30)) >> tb, 0, R - 1)
    if tb:
        comp = (key_in - (1 << 30)) & ((1 << tb) - 1)
        tn_in = _unbits(((1 << tb) - 1 - comp) << (31 - tb))
    else:
        tn_in = jnp.zeros_like(key_in, jnp.float32)
    tn_in = jnp.where(valid & (key_in != _I32_MAX), tn_in, jnp.inf)
    # ONE lane-axis take covers o, inv_d AND the ray's current t
    # (per-element gather cost rules here — see rayE/rayF note)
    rows = jnp.take(s.rayE, rid, axis=1)  # (8, S)
    t_r = rows[6]
    live = valid & (key_in != _I32_MAX) & (tn_in <= t_r)
    if any_hit:
        live = live & (s.prim[rid] < 0)

    # ---- lane-major slab tests ------------------------------------------
    # Layout is everything here (profiled): all arrays keep the SLAB
    # dimension minor so every elementwise op and min/max chain runs on
    # (8, S) with full lanes and no reductions.
    nb, cids = _fetch_children(tab64, boxT, cidT, node, use_onehot)
    ray6 = rows[0:6]  # (6, S) o + inv_d

    tx0, tx1 = slab_test_lane_major(nb[0], nb[3], ray6[0][None, :], ray6[3][None, :])
    ty0, ty1 = slab_test_lane_major(nb[1], nb[4], ray6[1][None, :], ray6[4][None, :])
    tz0, tz1 = slab_test_lane_major(nb[2], nb[5], ray6[2][None, :], ray6[5][None, :])
    tn8 = jnp.maximum(jnp.maximum(tx0, ty0), jnp.maximum(tz0, 0.0))  # (8,S)
    tf8 = jnp.minimum(jnp.minimum(tx1, ty1), jnp.minimum(tz1, t_r[None, :]))
    in_slab = tn8 <= tf8

    hit8 = live[None, :] & in_slab & (cids != _EMPTY)
    is_int = hit8 & (cids >= 0)
    is_leaf = hit8 & (cids < 0)

    # ---- sort-based compaction of the 8S child candidates ---------------
    # packed i32 key (3-array int sort = the fast path; see module doc):
    # leaves first keyed by ray alone, then interiors keyed by
    # (ray, ~quantized t_entry) so each ray's nearest children end up on
    # top of the LIFO stack, dead last
    rid8 = jnp.broadcast_to(rid[None, :], cids.shape)
    # monotone 10-bit-ish quantization of the non-negative f32 tn: its
    # raw bits are order-preserving; keep the top tb bits (exponent +
    # leading mantissa). These key bits are ALL that survives: the next
    # pop's cull dequantizes them back to a conservative lower bound.
    qtn = jax.lax.shift_right_logical(_bits(tn8), 31 - tb) if tb else 0
    key_leaf = rid8
    key_int = (1 << 30) + (rid8 << tb) + (((1 << tb) - 1) - qtn)
    key = jnp.where(
        is_leaf, key_leaf, jnp.where(is_int, key_int, _I32_MAX)
    ).reshape(-1)
    cand_code = jnp.where(is_leaf, decode_top_leaf(cids), cids).reshape(-1)
    key_s, code_s = jax.lax.sort([key, cand_code], num_keys=1)
    n_leaf = jnp.sum(is_leaf, dtype=jnp.int32)
    n_int = jnp.sum(is_int, dtype=jnp.int32)
    s8 = 8 * slab
    return _expand_push(
        s, key_s, code_s, n_leaf, n_int, live, start, w, lb, s8
    )


def _expand_push(s: _SState, key_s, code_s, n_leaf, n_int, live,
                 start, w: int, lb: int, s8: int):
    """Shared tail of EXPAND (jnp and fused front halves): append the
    sorted leaf prefix to the leaf buffer, push the interior span onto
    the stack, roll the counters. `live` is the per-pair live mask
    (jnp: (S,) bool; fused: the kernel's (Sp,) i32 row) — summed HERE,
    after the buffer writes, so the jnp program's equation order (and
    with it the persistent-compile-cache hash of every render program)
    is byte-identical to the pre-fusedwave trace."""

    # append the leaf prefix to the leaf buffer (contiguous write; for
    # leaves the sort key IS the ray id). Garbage entries past n_leaf
    # land in headroom and are overwritten or masked by n_lf.
    lf_ray = jax.lax.dynamic_update_slice(s.lf_ray, key_s, (s.n_lf,))
    lf_tid = jax.lax.dynamic_update_slice(s.lf_tid, code_s, (s.n_lf,))
    n_lf_new = s.n_lf + n_leaf
    dropped = jnp.maximum(n_lf_new - lb, 0)
    n_lf_new = jnp.minimum(n_lf_new, lb)

    # push the interior span [n_leaf, n_leaf + n_int) onto the stack: slice
    # it out of the (padded to 16S) sorted arrays at the dynamic offset,
    # then one contiguous write at the stack top
    pad = jnp.full((s8,), _I32_MAX, jnp.int32)
    int_key = jax.lax.dynamic_slice(
        jnp.concatenate([key_s, pad]), (n_leaf,), (s8,)
    )
    int_code = jax.lax.dynamic_slice(
        jnp.concatenate([code_s, pad]), (n_leaf,), (s8,)
    )
    stk_key = jax.lax.dynamic_update_slice(s.stk_key, int_key, (start,))
    stk_code = jax.lax.dynamic_update_slice(s.stk_code, int_code, (start,))
    n_stk_new = start + n_int
    dropped = dropped + jnp.maximum(n_stk_new - w, 0)
    n_stk_new = jnp.minimum(n_stk_new, w)

    return s._replace(
        stk_key=stk_key, stk_code=stk_code, n_stk=n_stk_new,
        lf_ray=lf_ray, lf_tid=lf_tid, n_lf=n_lf_new,
        n_drop=s.n_drop + dropped,
        n_exp=s.n_exp + jnp.sum(live, dtype=jnp.int32),
        iters=s.iters + 1,
    )


def _merge_chunk(rayE, rayF, prim, rid, t_loc, k_loc, off, won, R):
    """Fold a chunk's (ray, t, prim) candidates into the per-ray best.

    Sort the candidates on a (ray, t-bits) key pair — positive-f32 bits
    are order-preserving, so two i32 keys + the i32 payload stay on the
    int-sort fast path — then scatter only each ray-run's HEAD (its
    argmin). A few mostly-dropped scatters at sorted, unique indices
    replace the per-slot scatter-min + equality-select pair that
    dominated the round-3 profile (~12x on this v5e). The updated t row
    goes back into BOTH ray tables with contiguous
    dynamic_update_slices."""
    prim_cand = (off[:, None] + k_loc.astype(jnp.int32)).reshape(-1)
    key_ray = jnp.where(won, rid, R).reshape(-1)
    key_t = _bits(jnp.where(won, t_loc, jnp.inf)).reshape(-1)
    r_s, t_s, p_s = jax.lax.sort([key_ray, key_t, prim_cand], num_keys=2)
    head = jnp.concatenate(
        [jnp.ones((1,), bool), r_s[1:] != r_s[:-1]]
    ) & (r_s < R)
    sel = jnp.where(head, r_s, R)
    tv = _unbits(t_s)
    t_row = rayF[6]
    # ray-run head beats the stored t iff it beats the PRE-update value
    old = t_row[jnp.clip(r_s, 0, R - 1)]
    win = head & (tv < old)
    t_row2 = t_row.at[sel].min(tv, mode="drop")
    rayE2 = jax.lax.dynamic_update_slice(rayE, t_row2[None, :], (6, 0))
    rayF2 = jax.lax.dynamic_update_slice(rayF, t_row2[None, :], (6, 0))
    prim2 = prim.at[jnp.where(win, r_s, R)].set(p_s, mode="drop")
    return rayE2, rayF2, prim2


def _slice_rows(a, starts, width):
    """(CH,) starts -> (CH, width) contiguous slices of 1-D a, as ONE
    lax.gather with slice_sizes=(width,): the TPU lowers this as batched
    row copies (~bandwidth), where a vmapped dynamic_slice unrolls into
    a sequential per-row loop (~0.8 us each, profiled)."""
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(1,), collapsed_slice_dims=(), start_index_map=(0,)
    )
    return jax.lax.gather(
        a, starts[:, None], dnums, slice_sizes=(width,),
        mode=jax.lax.GatherScatterMode.CLIP,
    )


def _flush(tp: TreeletPack, featT_tab, s: _SState, lb: int,
           any_hit: bool):
    R = s.rayE.shape[1]
    rb = _ray_bits(R)
    C = tp.n_treelets
    L = tp.leaf_tris
    # n_lf <= lb always, so the sort/scan pipeline works on the (lb,)
    # prefix — the append headroom past lb never holds countable pairs
    lb_v = min(lb, s.lf_tid.shape[0])
    b_cap = lb_v // BLOCK + C + 2
    motion = tp.n_features == 64
    use_fused = _use_fused(R)
    chunk = min(CHUNK, b_cap)
    # pack (treelet, ray) into one i32 sort key when the id ranges allow
    # (common case) -> single-array fast sort + ray-sorted runs; else a
    # 2-array (tid, ray) sort
    packed_key = C < (1 << max(31 - rb, 0))

    idx = jnp.arange(lb_v, dtype=jnp.int32)
    ray_c = jnp.clip(s.lf_ray[:lb_v], 0, R - 1)
    # no flush-time t-based re-cull: it cost a (lb,)-sized random gather
    # (~40 ms/flush, the single most expensive op of the round-3 design)
    # and pruned nothing the chunk loop's per-slot t_b bound would not
    # reject anyway. Shadow waves still prune pairs whose ray has its
    # occlusion answer (one i32 gather; those pairs are pure waste).
    live = (idx < s.n_lf) & (s.lf_tid[:lb_v] >= 0)
    if any_hit:
        live = live & (s.prim[ray_c] < 0)
    if packed_key:
        key = jnp.where(
            live, (s.lf_tid[:lb_v] << rb) + ray_c, jnp.int32(C) << rb
        )
        (key_s,) = jax.lax.sort([key], num_keys=1)
        tid_s = key_s >> rb
        rid_s = key_s & ((1 << rb) - 1)
    else:
        key = jnp.where(live, s.lf_tid[:lb_v], C)
        tid_s, rid_s = jax.lax.sort([key, ray_c], num_keys=1)
    valid_s = tid_s < C
    prev = jnp.concatenate([jnp.full((1,), -1, tid_s.dtype), tid_s[:-1]])
    newrun = valid_s & (tid_s != prev)
    # block breaks at run starts OR 128-aligned positions: every block
    # stays within one treelet run and spans at most BLOCK pairs, without
    # needing a rank-within-run scan — the in_blk mask in the chunk loop
    # already handles blocks that end early
    brk = newrun | (valid_s & (idx % BLOCK == 0))
    blk_of = jnp.cumsum(brk.astype(jnp.int32)) - 1  # sorted ascending
    n_blocks = jnp.max(jnp.where(valid_s, blk_of, -1)) + 1
    # block b's pairs start at the position of the b-th set bit of brk:
    # one single-array int sort compacts those positions to the front
    # (searchsorted over the 1.5M-row blk_of was ~100x slower here)
    (start_sorted,) = jax.lax.sort(
        [jnp.where(brk, idx, _I32_MAX)], num_keys=1
    )
    block_start = start_sorted[:b_cap]

    def chunk_cond(c):
        return c[0] < n_blocks

    def _block_tables(cstart):
        """Shared per-chunk block tables, all derived from the sorted
        buffer with batched row copies (sort-derived, near-bandwidth)."""
        bids = cstart + jnp.arange(chunk, dtype=jnp.int32)  # (CH,)
        # gather (not dynamic_slice): a slice's clamped start would
        # misalign starts against bids on the last chunk when n_blocks
        # approaches b_cap, silently dropping or misbinding trailing blocks
        starts = block_start[jnp.minimum(bids, b_cap - 1)]
        # the slice window is clamped to stay in bounds (slots outside
        # the block are masked by in_blk), but the treelet id MUST be
        # read at the true start: a block beginning within BLOCK of the
        # buffer end would otherwise bind to the preceding run's treelet
        starts_w = jnp.minimum(starts, lb_v - BLOCK)
        # each block's slots are a CONTIGUOUS 128-run of the sorted
        # buffer: fetch them as sliced-row gathers (batched row copies)
        # — a flat gather of the same 65k positions costs ~21 ns/INDEX
        # (2 x 1.4 ms per chunk, profiled)
        blk_row = _slice_rows(blk_of, starts_w, BLOCK)  # (CH, BLOCK)
        rid_row = _slice_rows(rid_s, starts_w, BLOCK)  # (CH, BLOCK)
        in_blk = blk_row == bids[:, None]  # masks run ends + overflow
        rows = jnp.where(in_blk, rid_row, -1)  # (CH, BLOCK) ray ids
        tids = jnp.where(
            bids < n_blocks, tid_s[jnp.minimum(starts, lb_v - 1)], 0
        )
        tids = jnp.clip(tids, 0, C - 1)
        return bids, rows, tids

    if use_fused:
        # fused wavefront flush (accel/fusedwave.py): ONE Pallas grid
        # per chunk covers the phi build (in-kernel gather from the
        # VMEM-resident ray table), the treelet feature DMA (scalar-
        # prefetch index_map — the schedule the retired TPU_PBRT_
        # PREFETCH kernel introduced), the MT matmul + decode, and the
        # per-ray closest-hit merge against VMEM accumulators. The only
        # HBM round trip per chunk is the (R,) t/prim winner pair — the
        # (CH, F, BLOCK) phi tensor, the (CH, F, 4L) gathered features
        # and the (CH, BLOCK, 4L) matmul product of the jnp path below
        # never exist.
        from tpu_pbrt.accel.fusedwave import fused_flush_chunk

        interp = _fused_interpret()
        center_bits = _bits(tp.center)  # (C, 3) f32 bits ride i32 meta

        def chunk_body_fused(c):
            cstart, t_row, prim, n_tl = c
            bids, rows, tids = _block_tables(cstart)
            meta = jnp.stack(
                [
                    tids,
                    tp.offset[tids],
                    center_bits[tids, 0],
                    center_bits[tids, 1],
                    center_bits[tids, 2],
                    (bids < n_blocks).astype(jnp.int32),
                    jnp.zeros_like(tids),
                    jnp.zeros_like(tids),
                ],
                axis=1,
            )  # (CH, 8) per-block scalars for the kernel
            t_row2, prim2 = fused_flush_chunk(
                featT_tab, meta, rows, s.rayF, t_row, prim,
                interpret=interp,
            )
            return (
                cstart + chunk, t_row2, prim2,
                n_tl + jnp.sum(rows >= 0, dtype=jnp.int32),
            )

        init = (jnp.int32(0), s.rayF[6], s.prim, s.n_tl)
        _, t_row, prim, n_tl = jax.lax.while_loop(
            chunk_cond, chunk_body_fused, init
        )
        # the winner t row goes back into BOTH ray tables once per
        # flush (the kernel never reads row 6 — the merge's strict <
        # carries the bound), keeping the tables layout-stable
        rayE = jax.lax.dynamic_update_slice(s.rayE, t_row[None, :], (6, 0))
        rayF = jax.lax.dynamic_update_slice(s.rayF, t_row[None, :], (6, 0))
        return s._replace(
            rayE=rayE, rayF=rayF, prim=prim,
            n_lf=jnp.int32(0), n_tl=n_tl, iters=s.iters + 1,
        )

    def chunk_body(c):
        cstart, rayE, rayF, prim, n_tl = c
        bids, rows, tids = _block_tables(cstart)
        has_ray = rows >= 0
        rid = jnp.where(has_ray, rows, 0)
        ctr = tp.center[tids]  # (CH, 3)
        off = tp.offset[tids]  # (CH,)
        # ONE lane-axis take covers o, d AND t (see rayE/rayF note),
        # then a TRANSPOSED feature build: phi rows on axis 1, the 128
        # rays on lanes — (CH, BLOCK, 16) would put 16 on lanes (the
        # profiled layout sin of the old path)
        rr = jnp.take(rayF, rid.reshape(-1), axis=1)  # (8, CH*BLOCK)
        rrows = jnp.swapaxes(
            rr.reshape(8, chunk, BLOCK), 0, 1
        )  # (CH, 8, BLOCK)
        t_b = jnp.where(has_ray, rrows[:, 6], -jnp.inf)  # dead: t<tm fails
        oc = [rrows[:, i] - ctr[:, i][:, None] for i in range(3)]
        dc = [rrows[:, 3 + i] for i in range(3)]
        phiT = jnp.stack(
            [oc[i] * dc[j] for i in range(3) for j in range(3)]
            + dc + oc + [jnp.ones_like(oc[0])],
            axis=1,
        )  # (CH, 16, BLOCK)
        if motion:
            # motion packs carry 64-row cubic-in-time features: extend
            # phi with the per-ray shutter time powers (rayF row 7)
            tm_r = rrows[:, 7]  # (CH, BLOCK)
            phiT = jnp.concatenate(
                [phiT, phiT * tm_r[:, None, :],
                 phiT * (tm_r * tm_r)[:, None, :],
                 phiT * (tm_r * tm_r * tm_r)[:, None, :]],
                axis=1,
            )  # (CH, 64, BLOCK)
        featT = featT_tab[tids]  # (CH, F, 4L)
        out = jnp.einsum(
            "cfb,cfk->cbk", phiT, featT,
            precision=jax.lax.Precision.HIGHEST,
        )
        t_loc, k_loc, _, _ = decode_outputs(out, L, t_b)
        won = has_ray & jnp.isfinite(t_loc)  # t_loc < t[ray] by decode
        rayE2, rayF2, prim2 = _merge_chunk(
            rayE, rayF, prim, rid, t_loc, k_loc, off, won, R
        )
        return (
            cstart + chunk, rayE2, rayF2, prim2,
            n_tl + jnp.sum(has_ray, dtype=jnp.int32),
        )

    init = (jnp.int32(0), s.rayE, s.rayF, s.prim, s.n_tl)
    _, rayE, rayF, prim, n_tl = jax.lax.while_loop(
        chunk_cond, chunk_body, init
    )
    return s._replace(
        rayE=rayE, rayF=rayF, prim=prim,
        n_lf=jnp.int32(0), n_tl=n_tl, iters=s.iters + 1,
    )


def _traverse(tp: TreeletPack, o, d, t_max, any_hit: bool,
              time=None) -> _SState:
    R = o.shape[0]
    rb = _ray_bits(R)
    tb = _tn_bits(R)
    slab, w, lb = _sizes(R)
    s8 = 8 * slab
    inv_d = 1.0 / d
    boxT = jnp.transpose(
        jnp.concatenate([tp.top.child_bmin, tp.top.child_bmax], axis=-1),
        (2, 1, 0),
    )  # (6, 8, N)
    cidT = tp.top.child_idx.T  # (8, N)
    use_onehot = _use_onehot(int(boxT.shape[2]))
    tab64 = _node_table(boxT, cidT) if use_onehot else None
    # the fused EXPAND kernel additionally needs the node table VMEM-
    # resident, so it gates on top-tree size; the fused FLUSH does not
    use_fused_exp = _use_fused(R) and int(boxT.shape[2]) <= int(
        cfg.fused_max_nodes
    )
    featT_tab = tp.featT  # (C, 16, 4L), stored at build

    t_max = jnp.asarray(t_max, jnp.float32)
    # the consolidated lane-major per-ray tables (see _SState.rayE/rayF);
    # rayF row 7 carries the per-ray shutter time for motion packs
    trow = (
        jnp.zeros((1, R), jnp.float32) if time is None
        else jnp.broadcast_to(
            jnp.asarray(time, jnp.float32), (R,)
        )[None, :]
    )
    pad1 = jnp.zeros((1, R), jnp.float32)
    rayE = jnp.concatenate([o.T, inv_d.T, t_max[None, :], pad1], axis=0)
    rayF = jnp.concatenate([o.T, d.T, t_max[None, :], trow], axis=0)
    alive0 = t_max > 0.0
    rid0 = jnp.arange(R, dtype=jnp.int32)
    # seed: one root pair per LIVE ray, packed exactly like _expand's
    # interior keys (tn = 0 -> qtn complement = max). Dead lanes sort to
    # the back and are excluded from n_stk — a mostly-dead bounce wave
    # pops only its live rays.
    key0 = jnp.where(
        alive0, (1 << 30) + (rid0 << tb) + ((1 << tb) - 1), _I32_MAX
    )
    (key0_s,) = jax.lax.sort([key0], num_keys=1)
    n_live = jnp.sum(alive0, dtype=jnp.int32)
    init = _SState(
        rayE=rayE,
        rayF=rayF,
        prim=jnp.full((R,), -1, jnp.int32),
        stk_key=jnp.full((w + s8,), _I32_MAX, jnp.int32).at[:R].set(key0_s),
        stk_code=jnp.zeros((w + s8,), jnp.int32),  # root everywhere
        n_stk=n_live,
        lf_ray=jnp.zeros((lb + s8,), jnp.int32),
        lf_tid=jnp.full((lb + s8,), -1, jnp.int32),
        n_lf=jnp.int32(0),
        n_drop=jnp.int32(0), n_exp=jnp.int32(0), n_tl=jnp.int32(0),
        iters=jnp.int32(0),
    )

    dead = t_max <= 0.0

    def cond(s: _SState):
        go = ((s.n_stk > 0) | (s.n_lf > 0)) & (s.iters < _MAX_ITERS)
        if any_hit:
            # shadow waves stop as soon as every live ray has its hit
            go = go & ~jnp.all((s.prim >= 0) | dead)
        return go

    def body(s: _SState):
        do_flush = (s.n_lf > lb - s8) | (s.n_stk == 0)
        return jax.lax.cond(
            do_flush,
            lambda ss: _flush(tp, featT_tab, ss, lb, any_hit),
            lambda ss: _expand(tp, tab64, boxT, cidT, ss, slab, w,
                               lb, any_hit, use_onehot, use_fused_exp),
            s,
        )

    return jax.lax.while_loop(cond, body, init)


def _finalize_hits(tri_verts, o, d, t_raw, prim, time=None,
                   tri_verts1=None, tv9T=None, tv9T1=None) -> Hit:
    """(t, prim) -> full Hit: ONE tri_verts row fetch per ray recovers
    the winner's barycentrics (beats scattering b0/b1 per tested block
    slot during the merge), and the fetched vertices ride along in
    Hit.tv so shading never re-gathers them. Motion scenes lerp the
    two keyframes at the ray's time."""
    hit = prim >= 0
    t = jnp.where(hit, t_raw, jnp.inf)
    # take from a lane-major (9, T) view: the native (T, 3, 3) layout
    # gathers at ~33 ns per fetched element on this v5e, a lane-major
    # axis-1 take at ~2.6. The scene compiler bakes the (9, T) table
    # once (dev["tri_verts9T"]) — recomputing it here cost a
    # whole-triangle-table relayout copy EVERY wave
    # (JC-CHURN's sibling finding JC-RELAYOUT:stream_intersect:
    # "transpose of (T, 9) buffer"); the fallback below keeps direct
    # callers (tests, tools) working without a compiled scene.
    T = tri_verts.shape[0]
    if tv9T is None:
        tv9T = tri_verts.reshape(T, 9).T  # (9, T)
    tv = jnp.take(tv9T, jnp.maximum(prim, 0), axis=1).T.reshape(
        -1, 3, 3
    )  # (R, 3, 3)
    if tri_verts1 is not None and time is not None:
        if tv9T1 is None:
            tv9T1 = tri_verts1.reshape(T, 9).T
        tv1 = jnp.take(tv9T1, jnp.maximum(prim, 0), axis=1).T.reshape(-1, 3, 3)
        tm = jnp.asarray(time, jnp.float32).reshape(-1, 1, 1)
        tv = (1.0 - tm) * tv + tm * tv1
    v0, v1, v2 = tv[:, 0], tv[:, 1], tv[:, 2]
    e1 = v1 - v0
    e2 = v2 - v0
    pvec = jnp.cross(d, e2)
    det = jnp.sum(e1 * pvec, axis=-1)
    inv = 1.0 / jnp.where(det == 0.0, 1.0, det)
    sv = o - v0
    u = jnp.sum(sv * pvec, axis=-1) * inv
    qvec = jnp.cross(sv, e1)
    v = jnp.sum(d * qvec, axis=-1) * inv
    b0 = jnp.where(hit, 1.0 - u - v, 0.0)
    b1 = jnp.where(hit, u, 0.0)
    return Hit(t, prim, b0, b1, tv)


@jax.jit
def stream_intersect(tp: TreeletPack, tri_verts, o, d, t_max,
                     time=None, tri_verts1=None, tv9T=None,
                     tv9T1=None) -> Hit:
    """Closest hit for a flat ray batch. o, d: (R, 3); t_max scalar or
    (R,). Returns Hit with global leaf-order triangle ids (and the hit
    vertices in Hit.tv) — API-compatible with bvh_intersect /
    wide_intersect / packet_intersect. time/tri_verts1: motion blur
    (see _traverse/_finalize_hits). tv9T/tv9T1: the compile-time
    lane-major (9, T) vertex tables (dev["tri_verts9T"]); omitted, the
    relayout is recomputed per wave."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    s = _traverse(tp, o, d, t_max, False, time=time)
    return _finalize_hits(
        tri_verts, o, d, s.rayF[6], s.prim, time=time,
        tri_verts1=tri_verts1, tv9T=tv9T, tv9T1=tv9T1,
    )


@partial(jax.jit, static_argnames=("n_finalize",))
def stream_intersect_split(tp: TreeletPack, tri_verts, o, d, t_max,
                           n_finalize: int, time=None, tri_verts1=None,
                           tv9T=None, tv9T1=None):
    """Fused-wave closest hit: traverse ALL rays, but build the full Hit
    (barycentric refetch) only for the first n_finalize — the tail (the
    integrator's queued shadow rays) needs just prim>=0, and skipping
    its per-ray tri_verts row fetch saves ~9 gathered elements/ray."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    s = _traverse(tp, o, d, t_max, False, time=time)
    n = n_finalize
    hit = _finalize_hits(
        tri_verts, o[:n], d[:n], s.rayF[6][:n], s.prim[:n],
        time=None if time is None else time[:n],
        tri_verts1=tri_verts1, tv9T=tv9T, tv9T1=tv9T1,
    )
    return hit, s.prim[n:]


def stream_intersect_p(tp: TreeletPack, o, d, t_max, time=None):
    """Any-hit (shadow) predicate -> bool (R,)."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    return _traverse_p(tp, o, d, t_max, time)


@jax.jit
def _traverse_p(tp: TreeletPack, o, d, t_max, time=None):
    return _traverse(tp, o, d, t_max, True, time=time).prim >= 0


@partial(jax.jit, static_argnames=("any_hit",))
def stream_traverse_stats(tp: TreeletPack, o, d, t_max, any_hit: bool = False):
    """(pairs expanded, leaf block-slot tests, pairs dropped, loop iters)
    for the stats subsystem, perf analysis, and the capacity-overflow
    regression test."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    s = _traverse(tp, o, d, t_max, any_hit)
    return s.n_exp, s.n_tl, s.n_drop, s.iters
