"""Stream (sort/compaction wavefront) BVH traversal — the fast trace path.

Capability match for pbrt-v3 src/accelerators/bvh.cpp
BVHAccel::Intersect/IntersectP (same closest-hit/any-hit semantics over the
same SAH tree), re-architected a second time for TPU execution behavior.

Why not the packet walk (accel/packet.py): packets amortize node fetches
only while the 128 rays in a packet agree on a traversal path. Bounce rays
(cosine-sampled hemispheres) disagree almost immediately, the packet's
union frustum covers the whole scene, and every lane pays for every node
any lane wants — measured 4 orders of magnitude slower than coherent
camera rays on the same kernel.

Why not a per-ray stack walk (accel/wide.py): a vmapped while_loop makes
every ray pay the worst ray's iteration count, and each iteration moves a
few hundred bytes per ray — far below the row sizes TPU memory wants.

The stream design has NO per-ray control flow at all. Traversal state is
one flat LIFO worklist of (ray, node, t_entry) pairs shared by the whole
wave, processed in large dense slabs. The primitive costs measured on this
v5e (in-jit repetition, amortizing the ~100 ms tunnel round-trip) dictate
the shape of every step: scatters ~10-35 ms per 512k elements, sorts ~2 ms
per 512k keys, row gathers ~8 ns/row, contiguous dynamic slices and dense
vector/MXU math effectively free. So the design is SORT-BASED and
scatter-free everywhere a sort can stand in for a scatter:

- EXPAND pops a slab of SLAB pairs at once (one contiguous dynamic_slice),
  culls pairs whose recorded entry distance already exceeds their ray's
  current hit, slab-tests each pair's ray against its node's 8 child boxes
  in one dense (SLAB, 8) test — one packed (8,6)-float box row and one
  packed (6,)-float ray row per pair — then compacts the 8*SLAB child
  candidates with ONE sort on a single f32 key: hit leaves sort to the
  front (key -inf), hit interior children next ordered far-to-near (key
  -t_entry), everything else to the back (key +inf). The sorted prefix is
  appended to the leaf buffer and the interior span is pushed onto the
  stack with two contiguous dynamic_update_slices — no scatter, and the
  global far-to-near order means the next pop takes the wave's nearest
  subtrees first (stronger front-to-back culling than per-node child
  ordering).
- FLUSH runs when the leaf buffer is nearly full (or the stack empties):
  it sorts the buffered (ray, treelet) pairs by treelet id, so each
  treelet's rays form a contiguous run; block starts come from a
  searchsorted over the run ids (binary search, not scatter), and each
  128-ray block is intersected against its treelet's triangles in one MXU
  feature matmul (accel/mxu.py): (128, 16) ray features x (16, 4L)
  per-treelet Moller-Trumbore weights. Closest hits merge into per-ray
  state by scatter-min (+ an equality-select scatter for the payload, the
  standard two-pass argmin trick) — the one place a scatter is
  unavoidable, paid per tested block slot.

Sequential depth per wave is therefore ~(total pairs / SLAB) big dense
steps instead of per-ray tree depth times worst-lane divergence, and leaf
work lands on the MXU in (128, 16) @ (16, 4L) tiles regardless of ray
order. Ray coherence changes only the pair COUNT (coherent rays produce
fewer pairs), never the execution shape — the design goal for a wavefront
path tracer whose bounce waves are inherently incoherent.

The acceleration structure is the same two-level TreeletPack as the packet
walk (accel/treelet.py) with fatter leaves (STREAM_LEAF_TRIS): the
MXU makes triangle tests nearly free, so trading deeper trees for fatter
matmuls moves work from the latency-bound worklist to the compute units.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.mxu import decode_outputs, ray_features
from tpu_pbrt.accel.traverse import Hit
from tpu_pbrt.accel.treelet import TreeletPack, decode_top_leaf
from tpu_pbrt.accel.wide import _EMPTY, slab_test_lane_major

#: triangles per treelet for the stream path (feature row = 4*this
#: columns). Swept on the v5e bench: 256 -> 0.61 Mray/s, 512 -> 0.73
#: (fewer worklist pairs; the fatter matmul is nearly free on the MXU),
#: 1024 -> 0.36 (matmul cost finally dominates).
STREAM_LEAF_TRIS = 512
#: rays per leaf block — the MXU matmul's row dimension
BLOCK = 128
#: leaf blocks processed per flush chunk (bounds transient memory: the
#: chunk's matmul output is CHUNK*BLOCK*4L floats)
CHUNK = 512
#: safety bound on while_loop iterations (real waves take tens to hundreds)
_MAX_ITERS = 1 << 16


def _use_pallas() -> bool:
    """Static (trace-time) switch: the fused Pallas leaf kernel runs on
    real TPUs; CPU (tests, virtual meshes) uses the XLA einsum fallback.
    TPU_PBRT_PALLAS=0 forces the fallback for A/B comparison."""
    import os

    if os.environ.get("TPU_PBRT_PALLAS", "1") == "0":
        return False
    return jax.default_backend() not in ("cpu",)


def _use_prefetch() -> bool:
    """Opt-in scalar-prefetch leaf kernel (TPU_PBRT_PREFETCH=1): DMAs
    treelet rows in-kernel instead of a materialized gather. Verified
    bit-compatible; currently ~15% slower end-to-end (see _flush)."""
    import os

    return os.environ.get("TPU_PBRT_PREFETCH", "0") == "1"


class _SState(NamedTuple):
    t: jnp.ndarray  # (R,) current closest hit (or t_max)
    prim: jnp.ndarray  # (R,) i32 global leaf-order triangle id, -1 miss
    stk_node: jnp.ndarray  # (W + headroom,) i32 top-tree node / treelet code
    stk_ray: jnp.ndarray  # (W + headroom,) i32 ray ids
    stk_tn: jnp.ndarray  # (W + headroom,) i32 bitcast f32 entry distance
    n_stk: jnp.ndarray  # i32
    lf_tid: jnp.ndarray  # (LB + headroom,) i32 treelet ids
    lf_ray: jnp.ndarray  # (LB + headroom,) i32
    lf_tn: jnp.ndarray  # (LB + headroom,) i32 bitcast f32
    n_lf: jnp.ndarray  # i32
    n_drop: jnp.ndarray  # i32 pairs lost to capacity (tests assert 0)
    n_exp: jnp.ndarray  # i32 stat: pairs expanded
    n_tl: jnp.ndarray  # i32 stat: (ray, treelet) block-slot tests
    iters: jnp.ndarray  # i32


def _sizes(R: int):
    """Static worklist sizes for a wave of R rays.

    Slab-size tradeoff, measured on this v5e (1M-ray camera wave):
    bigger slabs amortize sort dispatch cost (128k-key sort 3.6 ms vs
    1M-key 5.1 ms) but DELAY flushes, so per-ray closest-t stays loose
    longer and the wave expands more pairs (131k slab: 6.7M pairs,
    1.29 s; 512k slab: 7.3M pairs, 1.53 s). The default keeps the
    tighter-culling small slab; TPU_PBRT_SLAB overrides for experiments."""
    import os

    cap = int(os.environ.get("TPU_PBRT_SLAB", 1 << 17))
    slab = int(min(max(R // 4, 4096), cap))
    w = R + 24 * slab
    lb = 12 * slab
    return slab, w, lb


def _bits(x):
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _unbits(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


def _expand(tp: TreeletPack, boxT, cidT, o_invT, s: _SState, slab: int,
            w: int, lb: int, any_hit: bool):
    start = jnp.maximum(s.n_stk - slab, 0)
    k = jnp.arange(slab, dtype=jnp.int32)
    valid = k < (s.n_stk - start)
    node = jnp.where(valid, jax.lax.dynamic_slice(s.stk_node, (start,), (slab,)), 0)
    rid = jnp.where(valid, jax.lax.dynamic_slice(s.stk_ray, (start,), (slab,)), 0)
    tn_in = jnp.where(
        valid, _unbits(jax.lax.dynamic_slice(s.stk_tn, (start,), (slab,))), jnp.inf
    )
    t_r = s.t[rid]
    live = valid & (tn_in <= t_r)
    if any_hit:
        live = live & (s.prim[rid] < 0)

    # ---- lane-major slab tests ------------------------------------------
    # Layout is everything here (profiled): (S, 8, 3)-shaped math puts 3
    # on the TPU lane dimension (3/128 utilization) and its axis reductions
    # + tiny-row gathers were ~38% of the wave. All arrays below keep the
    # SLAB dimension minor: tables are pre-transposed to (6, 8, N)/(8, N)/
    # (6, R) and gathered along their LAST axis, so every elementwise op
    # and min/max chain runs on (8, S) with full lanes and no reductions.
    nb = jnp.take(boxT, node, axis=2)  # (6, 8, S)
    cids = jnp.take(cidT, node, axis=1)  # (8, S)
    ray6 = jnp.take(o_invT, rid, axis=1)  # (6, S)

    tx0, tx1 = slab_test_lane_major(nb[0], nb[3], ray6[0][None, :], ray6[3][None, :])
    ty0, ty1 = slab_test_lane_major(nb[1], nb[4], ray6[1][None, :], ray6[4][None, :])
    tz0, tz1 = slab_test_lane_major(nb[2], nb[5], ray6[2][None, :], ray6[5][None, :])
    tn8 = jnp.maximum(jnp.maximum(tx0, ty0), jnp.maximum(tz0, 0.0))  # (8,S)
    tf8 = jnp.minimum(jnp.minimum(tx1, ty1), jnp.minimum(tz1, t_r[None, :]))
    in_slab = tn8 <= tf8

    hit8 = live[None, :] & in_slab & (cids != _EMPTY)
    is_int = hit8 & (cids >= 0)
    is_leaf = hit8 & (cids < 0)

    # ---- sort-based compaction of the 8S child candidates ---------------
    # key: leaves first (-inf), interiors far-to-near (-t_entry: the wave's
    # NEAREST subtrees end up on top of the LIFO stack), dead last (+inf)
    key = jnp.where(
        is_leaf, -jnp.inf, jnp.where(is_int, -tn8, jnp.inf)
    ).reshape(-1)
    cand_code = jnp.where(is_leaf, decode_top_leaf(cids), cids).reshape(-1)
    cand_ray = jnp.broadcast_to(rid[None, :], cids.shape).reshape(-1)
    cand_tn = _bits(tn8).reshape(-1)
    _, code_s, ray_s, tn_s = jax.lax.sort(
        [key, cand_code, cand_ray, cand_tn], num_keys=1
    )
    n_leaf = jnp.sum(is_leaf, dtype=jnp.int32)
    n_int = jnp.sum(is_int, dtype=jnp.int32)
    s8 = 8 * slab

    # append the leaf prefix to the leaf buffer (contiguous write; the up
    # to 8S garbage entries past n_leaf land in headroom/garbage region and
    # are overwritten by the next append or masked by n_lf)
    lf_tid = jax.lax.dynamic_update_slice(s.lf_tid, code_s, (s.n_lf,))
    lf_ray = jax.lax.dynamic_update_slice(s.lf_ray, ray_s, (s.n_lf,))
    lf_tn = jax.lax.dynamic_update_slice(s.lf_tn, tn_s, (s.n_lf,))
    n_lf_new = s.n_lf + n_leaf
    dropped = jnp.maximum(n_lf_new - lb, 0)
    n_lf_new = jnp.minimum(n_lf_new, lb)

    # push the interior span [n_leaf, n_leaf + n_int) onto the stack: slice
    # it out of the (padded to 16S) sorted arrays at the dynamic offset,
    # then one contiguous write at the stack top
    pad = jnp.full((s8,), _EMPTY, jnp.int32)
    int_code = jax.lax.dynamic_slice(
        jnp.concatenate([code_s, pad]), (n_leaf,), (s8,)
    )
    int_ray = jax.lax.dynamic_slice(
        jnp.concatenate([ray_s, pad]), (n_leaf,), (s8,)
    )
    int_tn = jax.lax.dynamic_slice(
        jnp.concatenate([tn_s, pad]), (n_leaf,), (s8,)
    )
    stk_node = jax.lax.dynamic_update_slice(s.stk_node, int_code, (start,))
    stk_ray = jax.lax.dynamic_update_slice(s.stk_ray, int_ray, (start,))
    stk_tn = jax.lax.dynamic_update_slice(s.stk_tn, int_tn, (start,))
    n_stk_new = start + n_int
    dropped = dropped + jnp.maximum(n_stk_new - w, 0)
    n_stk_new = jnp.minimum(n_stk_new, w)

    return s._replace(
        stk_node=stk_node, stk_ray=stk_ray, stk_tn=stk_tn, n_stk=n_stk_new,
        lf_tid=lf_tid, lf_ray=lf_ray, lf_tn=lf_tn, n_lf=n_lf_new,
        n_drop=s.n_drop + dropped,
        n_exp=s.n_exp + jnp.sum(live, dtype=jnp.int32),
        iters=s.iters + 1,
    )


def _flush(tp: TreeletPack, featT_tab, oT, dT, s: _SState, lb: int,
           any_hit: bool):
    R = s.t.shape[0]
    C = tp.n_treelets
    L = tp.leaf_tris
    # n_lf <= lb always, so the sort/scan pipeline works on the (lb,)
    # prefix — the append headroom past lb never holds countable pairs
    lb_v = min(lb, s.lf_tid.shape[0])
    b_cap = lb_v // BLOCK + C + 2
    # the Pallas prefetch kernel materializes no (chunk, 128, 4L) matmul
    # output, so its chunks can be 8x larger — fewer merge scatters and
    # searchsorted dispatches per flush. Measured on this v5e it is ~15%
    # SLOWER end-to-end than the gathered kernel (the one-block-per-step
    # DMA pipeline loses to XLA's batched gather), so it stays opt-in.
    use_pallas = _use_pallas()
    use_prefetch = use_pallas and _use_prefetch()
    chunk = min(CHUNK * 8 if use_prefetch else CHUNK, b_cap)

    idx = jnp.arange(lb_v, dtype=jnp.int32)
    tn0 = _unbits(s.lf_tn[:lb_v])
    ray_c = jnp.clip(s.lf_ray[:lb_v], 0, R - 1)
    live = (idx < s.n_lf) & (s.lf_tid[:lb_v] >= 0) & (tn0 <= s.t[ray_c])
    if any_hit:
        live = live & (s.prim[ray_c] < 0)
    key = jnp.where(live, s.lf_tid[:lb_v], C)
    key_s, rid_s = jax.lax.sort([key, ray_c], num_keys=1)
    valid_s = key_s < C
    prev = jnp.concatenate([jnp.full((1,), -1, key_s.dtype), key_s[:-1]])
    newrun = valid_s & (key_s != prev)
    # block breaks at run starts OR 128-aligned positions: every block
    # stays within one treelet run and spans at most BLOCK pairs, without
    # needing a rank-within-run scan — the in_blk mask in the chunk loop
    # already handles blocks that end early
    brk = newrun | (valid_s & (idx % BLOCK == 0))
    blk_of = jnp.cumsum(brk.astype(jnp.int32)) - 1  # sorted ascending
    n_blocks = jnp.max(jnp.where(valid_s, blk_of, -1)) + 1
    # block b's pairs start at the first sorted position with blk_of == b:
    # a binary search over the monotone blk_of (scatter-free)
    block_start = jnp.searchsorted(
        blk_of, jnp.arange(b_cap, dtype=jnp.int32), side="left"
    ).astype(jnp.int32)

    def chunk_cond(c):
        return c[0] < n_blocks

    def chunk_body(c):
        cstart, t, prim, n_tl = c
        bids = cstart + jnp.arange(chunk, dtype=jnp.int32)  # (CH,)
        # gather (not dynamic_slice): a slice's clamped start would
        # misalign starts against bids on the last chunk when n_blocks
        # approaches b_cap, silently dropping or misbinding trailing blocks
        starts = block_start[jnp.minimum(bids, b_cap - 1)]
        pos = jnp.minimum(starts[:, None] + jnp.arange(BLOCK), lb_v - 1)
        in_blk = blk_of[pos] == bids[:, None]  # masks run ends + overflow
        rows = jnp.where(in_blk, rid_s[pos], -1)  # (CH, BLOCK) ray ids
        tids = jnp.where(bids < n_blocks, key_s[jnp.minimum(starts, lb_v - 1)], 0)
        tids = jnp.clip(tids, 0, C - 1)
        has_ray = rows >= 0
        rid = jnp.where(has_ray, rows, 0)
        t_b = jnp.where(has_ray, t[rid], -jnp.inf)  # dead slots: t<tm fails
        ctr = tp.center[tids]  # (CH, 3)
        off = tp.offset[tids]  # (CH,)
        # component-wise ray fetch + TRANSPOSED feature build: phi rows on
        # axis 1, the 128 rays on lanes — (CH, BLOCK, 16) would put 16 on
        # lanes (the profiled layout sin of the old path)
        oc = [jnp.take(oT[i], rid) - ctr[:, i][:, None] for i in range(3)]
        dc = [jnp.take(dT[i], rid) for i in range(3)]
        phiT = jnp.stack(
            [oc[i] * dc[j] for i in range(3) for j in range(3)]
            + dc + oc + [jnp.ones_like(oc[0])],
            axis=1,
        )  # (CH, 16, BLOCK)
        if use_prefetch:
            # full feature table stays in HBM; the kernel's scalar-prefetch
            # index_map DMAs each block's treelet row directly (no
            # materialized (CH, 16, 4L) gather)
            from tpu_pbrt.accel.leafkernel import leaf_blocks_intersect_prefetch

            t_loc, k_loc = leaf_blocks_intersect_prefetch(featT_tab, tids, phiT, t_b)
        elif use_pallas:
            from tpu_pbrt.accel.leafkernel import leaf_blocks_intersect

            featT = featT_tab[tids]  # (CH, 16, 4L)
            t_loc, k_loc = leaf_blocks_intersect(featT, phiT, t_b)
        else:
            featT = featT_tab[tids]  # (CH, 16, 4L)
            out = jnp.einsum(
                "cfb,cfk->cbk", phiT, featT,
                precision=jax.lax.Precision.HIGHEST,
            )
            t_loc, k_loc, _, _ = decode_outputs(out, L, t_b)
        won = has_ray & jnp.isfinite(t_loc)  # t_loc < t[ray] by decode
        flat_rid = jnp.where(won, rid, R).reshape(-1)
        t2 = t.at[flat_rid].min(t_loc.reshape(-1), mode="drop")
        # equality-select second pass: pairs matching the post-min value
        # write the payload (ties pick an arbitrary winner, as in any
        # closest-hit tie)
        win2 = won & (t_loc == t2[rid])
        sel = jnp.where(win2, rid, R).reshape(-1)
        prim2 = prim.at[sel].set(
            (off[:, None] + k_loc.astype(jnp.int32)).reshape(-1), mode="drop"
        )
        return (
            cstart + chunk, t2, prim2,
            n_tl + jnp.sum(has_ray, dtype=jnp.int32),
        )

    init = (jnp.int32(0), s.t, s.prim, s.n_tl)
    _, t, prim, n_tl = jax.lax.while_loop(chunk_cond, chunk_body, init)
    return s._replace(
        t=t, prim=prim,
        n_lf=jnp.int32(0), n_tl=n_tl, iters=s.iters + 1,
    )


def _traverse(tp: TreeletPack, o, d, t_max, any_hit: bool) -> _SState:
    R = o.shape[0]
    slab, w, lb = _sizes(R)
    s8 = 8 * slab
    inv_d = 1.0 / d
    # lane-major tables, transposed ONCE per wave (see _expand's layout
    # note): gathers index the LAST axis so their outputs keep the big
    # dimension on TPU lanes
    o_invT = jnp.concatenate([o, inv_d], axis=-1).T  # (6, R)
    boxT = jnp.transpose(
        jnp.concatenate([tp.top.child_bmin, tp.top.child_bmax], axis=-1),
        (2, 1, 0),
    )  # (6, 8, N)
    cidT = tp.top.child_idx.T  # (8, N)
    featT_tab = tp.featT  # (C, 16, 4L), stored at build
    oT = o.T  # (3, R)
    dT = d.T

    rid0 = jnp.arange(R, dtype=jnp.int32)
    tn0 = _bits(jnp.where(t_max > 0.0, 0.0, jnp.inf).astype(jnp.float32))
    init = _SState(
        t=jnp.asarray(t_max, jnp.float32),
        prim=jnp.full((R,), -1, jnp.int32),
        stk_node=jnp.zeros((w + s8,), jnp.int32),  # [0:R] = root
        stk_ray=jnp.zeros((w + s8,), jnp.int32).at[:R].set(rid0),
        stk_tn=jnp.full((w + s8,), _bits(jnp.float32(jnp.inf)), jnp.int32)
        .at[:R]
        .set(tn0),
        n_stk=jnp.int32(R),
        lf_tid=jnp.full((lb + s8,), -1, jnp.int32),
        lf_ray=jnp.zeros((lb + s8,), jnp.int32),
        lf_tn=jnp.zeros((lb + s8,), jnp.int32),
        n_lf=jnp.int32(0),
        n_drop=jnp.int32(0), n_exp=jnp.int32(0), n_tl=jnp.int32(0),
        iters=jnp.int32(0),
    )

    dead = jnp.asarray(t_max, jnp.float32) <= 0.0

    def cond(s: _SState):
        go = ((s.n_stk > 0) | (s.n_lf > 0)) & (s.iters < _MAX_ITERS)
        if any_hit:
            # shadow waves stop as soon as every live ray has its hit
            go = go & ~jnp.all((s.prim >= 0) | dead)
        return go

    def body(s: _SState):
        do_flush = (s.n_lf > lb - s8) | (s.n_stk == 0)
        return jax.lax.cond(
            do_flush,
            lambda ss: _flush(tp, featT_tab, oT, dT, ss, lb, any_hit),
            lambda ss: _expand(tp, boxT, cidT, o_invT, ss, slab, w, lb, any_hit),
            s,
        )

    return jax.lax.while_loop(cond, body, init)


@jax.jit
def stream_intersect(tp: TreeletPack, tri_verts, o, d, t_max) -> Hit:
    """Closest hit (or first-hit source for the any-hit predicate) for a
    flat ray batch. o, d: (R, 3); t_max scalar or (R,); tri_verts the
    shared leaf-order (T, 3, 3) vertex array the winner's barycentrics are
    recomputed from (ONE row fetch per ray beats scattering b0/b1 per
    tested block slot during the merge). Returns Hit with global
    leaf-order triangle ids — API-compatible with bvh_intersect /
    wide_intersect / packet_intersect."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    s = _traverse(tp, o, d, t_max, False)
    hit = s.prim >= 0
    t = jnp.where(hit, s.t, jnp.inf)
    tv = tri_verts[jnp.maximum(s.prim, 0)]  # (R, 3, 3)
    v0, v1, v2 = tv[:, 0], tv[:, 1], tv[:, 2]
    e1 = v1 - v0
    e2 = v2 - v0
    pvec = jnp.cross(d, e2)
    det = jnp.sum(e1 * pvec, axis=-1)
    inv = 1.0 / jnp.where(det == 0.0, 1.0, det)
    sv = o - v0
    u = jnp.sum(sv * pvec, axis=-1) * inv
    qvec = jnp.cross(sv, e1)
    v = jnp.sum(d * qvec, axis=-1) * inv
    b0 = jnp.where(hit, 1.0 - u - v, 0.0)
    b1 = jnp.where(hit, u, 0.0)
    return Hit(t, s.prim, b0, b1)


def stream_intersect_p(tp: TreeletPack, o, d, t_max):
    """Any-hit (shadow) predicate -> bool (R,)."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    return _traverse_p(tp, o, d, t_max)


@jax.jit
def _traverse_p(tp: TreeletPack, o, d, t_max):
    return _traverse(tp, o, d, t_max, True).prim >= 0


@partial(jax.jit, static_argnames=("any_hit",))
def stream_traverse_stats(tp: TreeletPack, o, d, t_max, any_hit: bool = False):
    """(pairs expanded, leaf block-slot tests, pairs dropped, loop iters)
    for the stats subsystem, perf analysis, and the capacity-overflow
    regression test."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    s = _traverse(tp, o, d, t_max, any_hit)
    return s.n_exp, s.n_tl, s.n_drop, s.iters
