"""Device-side ray-scene intersection: watertight triangles + BVH walk.

Capability match for pbrt-v3:
- src/shapes/triangle.cpp Triangle::Intersect/IntersectP — the watertight
  Woop-style shear intersection (translate, permute max-|d| axis to z,
  shear, signed edge functions, scaled depth test).
- src/accelerators/bvh.cpp BVHAccel::Intersect/IntersectP — iterative
  LinearBVHNode traversal with a 64-entry stack, precomputed invDir and
  dir-sign near/far child ordering.

TPU-first design: the single-ray traversal is scalar JAX code vmapped over
the ray batch — under vmap the while_loop runs all lanes in lockstep with
masking, which XLA vectorizes over the VPU. Leaf processing unrolls
MAX_LEAF_PRIMS masked triangle tests. The Pallas fused-trace kernel
(ops/) replaces this on the hot path; this module is the semantic
reference and the CPU/testing path.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_pbrt.core.vecmath import gamma

from tpu_pbrt.accel.build import MAX_LEAF_PRIMS

MAX_STACK = 64
_BOX_EPS = 1.0 + 2.0 * gamma(3)

# Per-dispatch ray-batch cap. Empirically (2026-07, v5e via the axon tunnel)
# vmapped while_loop traversal faults the TPU somewhere between 2^18 and 2^19
# lanes; integrators must chunk ray batches to at most this many rays per
# device dispatch (they want bounded tile x spp chunks anyway for film
# accumulation and checkpointing).
MAX_RAYS_PER_DISPATCH = 1 << 18


class Hit(NamedTuple):
    """SoA hit record; prim == -1 means miss. b0/b1 are barycentrics of
    vertices 0/1 (b2 = 1-b0-b1). tv optionally carries the hit
    triangle's (…, 3, 3) vertices when the tracer already fetched them —
    per-element gather costs dominate on TPU, so consumers
    (make_interaction) reuse this instead of re-gathering tri_verts."""

    t: jnp.ndarray
    prim: jnp.ndarray
    b0: jnp.ndarray
    b1: jnp.ndarray
    tv: jnp.ndarray | None = None


def intersect_triangle(o, d, p0, p1, p2, t_max):
    """Watertight ray-triangle test; broadcasts over leading axes.

    Returns (hit_mask, t, b0, b1). Follows Triangle::Intersect's shear
    formulation so edge-on rays hit exactly one of two adjacent triangles.
    """
    # translate to ray origin
    p0t = p0 - o
    p1t = p1 - o
    p2t = p2 - o
    # permute so |d| is largest along z; perm derives from d alone, so it
    # must broadcast against each operand's (possibly wider) batch shape —
    # e.g. a single ray (3,) tested against a leaf block (M,3)
    kz = jnp.argmax(jnp.abs(d), axis=-1)
    kx = (kz + 1) % 3
    ky = (kx + 1) % 3
    perm = jnp.stack([kx, ky, kz], axis=-1)

    def permute(a):
        shp = jnp.broadcast_shapes(a.shape, perm.shape)
        return jnp.take_along_axis(
            jnp.broadcast_to(a, shp), jnp.broadcast_to(perm, shp), axis=-1
        )

    dp = permute(d)
    p0t = permute(p0t)
    p1t = permute(p1t)
    p2t = permute(p2t)
    # shear to align ray with +z
    inv_dz = 1.0 / dp[..., 2]
    sx = -dp[..., 0] * inv_dz
    sy = -dp[..., 1] * inv_dz
    x0 = p0t[..., 0] + sx * p0t[..., 2]
    y0 = p0t[..., 1] + sy * p0t[..., 2]
    x1 = p1t[..., 0] + sx * p1t[..., 2]
    y1 = p1t[..., 1] + sy * p1t[..., 2]
    x2 = p2t[..., 0] + sx * p2t[..., 2]
    y2 = p2t[..., 1] + sy * p2t[..., 2]
    # signed edge functions
    e0 = x1 * y2 - y1 * x2
    e1 = x2 * y0 - y2 * x0
    e2 = x0 * y1 - y0 * x1
    det = e0 + e1 + e2
    same_sign = ((e0 >= 0) & (e1 >= 0) & (e2 >= 0)) | ((e0 <= 0) & (e1 <= 0) & (e2 <= 0))
    # scaled depth
    z0 = inv_dz * p0t[..., 2]
    z1 = inv_dz * p1t[..., 2]
    z2 = inv_dz * p2t[..., 2]
    t_scaled = e0 * z0 + e1 * z1 + e2 * z2
    in_range = jnp.where(
        det < 0,
        (t_scaled < 0) & (t_scaled >= t_max * det),
        (t_scaled > 0) & (t_scaled <= t_max * det),
    )
    hit = same_sign & (det != 0) & in_range
    inv_det = 1.0 / jnp.where(det == 0, 1.0, det)
    t = t_scaled * inv_det
    b0 = e0 * inv_det
    b1 = e1 * inv_det
    return hit, t, b0, b1


def brute_force_intersect(tri_verts, o, d, t_max, chunk=4096):
    """Oracle: closest hit over all triangles (SURVEY.md §7 stage 1 oracle).
    o,d: (R,3); tri_verts: (T,3,3). Chunked over T to bound memory."""
    n_tris = tri_verts.shape[0]
    r = o.shape[0]

    def chunk_body(c, state):
        t_best, prim_best, b0_best, b1_best = state
        start = c * chunk
        tv = jax.lax.dynamic_slice(tri_verts, (start, 0, 0), (chunk, 3, 3))
        hit, t, b0, b1 = intersect_triangle(
            o[:, None, :], d[:, None, :], tv[None, :, 0], tv[None, :, 1], tv[None, :, 2], t_best[:, None]
        )
        tri_ids = start + jnp.arange(chunk, dtype=jnp.int32)
        valid = hit & (tri_ids[None, :] < n_tris)
        t = jnp.where(valid, t, jnp.inf)
        k = jnp.argmin(t, axis=1)
        rr = jnp.arange(r, dtype=jnp.int32)
        better = t[rr, k] < t_best
        return (
            jnp.where(better, t[rr, k], t_best),
            jnp.where(better, tri_ids[k], prim_best),
            jnp.where(better, b0[rr, k], b0_best),
            jnp.where(better, b1[rr, k], b1_best),
        )

    n_chunks = (n_tris + chunk - 1) // chunk
    pad = n_chunks * chunk - n_tris
    if pad:
        tri_verts = jnp.concatenate([tri_verts, jnp.zeros((pad, 3, 3), tri_verts.dtype)], axis=0)
    init = (
        jnp.full((r,), t_max, jnp.float32) if jnp.ndim(t_max) == 0 else t_max,
        jnp.full((r,), -1, jnp.int32),
        jnp.zeros((r,), jnp.float32),
        jnp.zeros((r,), jnp.float32),
    )
    t, prim, b0, b1 = jax.lax.fori_loop(0, n_chunks, chunk_body, init)
    return Hit(t, prim, b0, b1)


class _TravState(NamedTuple):
    node: jnp.ndarray
    sp: jnp.ndarray
    stack: jnp.ndarray
    t: jnp.ndarray
    prim: jnp.ndarray
    b0: jnp.ndarray
    b1: jnp.ndarray
    done: jnp.ndarray


def _slab_test(o, inv_d, dir_neg, nmin, nmax, t_cur):
    lo = jnp.where(dir_neg, nmax, nmin)
    hi = jnp.where(dir_neg, nmin, nmax)
    t0 = (lo - o) * inv_d
    t1 = (hi - o) * inv_d * _BOX_EPS
    # 0 * inf (d[axis]==0 with origin exactly on a slab plane) yields NaN;
    # pbrt's comparison ordering treats that conservatively as "inside the
    # slab" — mirror that by mapping NaN to the permissive bound.
    t0 = jnp.where(jnp.isnan(t0), -jnp.inf, t0)
    t1 = jnp.where(jnp.isnan(t1), jnp.inf, t1)
    tn = jnp.maximum(jnp.max(t0), 0.0)
    tf = jnp.minimum(jnp.min(t1), t_cur)
    return tn <= tf


def _ray_traverse(bvh, tri_verts, o, d, t_max, any_hit: bool):
    """Single-ray BVH walk (scalars + fixed stack); vmapped by callers."""
    inv_d = 1.0 / d
    dir_neg = inv_d < 0

    def cond(s: _TravState):
        return ~s.done

    def body(s: _TravState):
        node = s.node
        hit_box = _slab_test(o, inv_d, dir_neg, bvh["bounds_min"][node], bvh["bounds_max"][node], s.t)
        n_prims = bvh["n_prims"][node]
        is_leaf = n_prims > 0
        test_leaf = hit_box & is_leaf

        # unrolled masked leaf tests; clamp the gather index — the final
        # leaf's off+k can run past the triangle array (masked out by
        # k < n_prims, but the gather itself must stay in bounds on TPU)
        t_new, prim_new, b0_new, b1_new = s.t, s.prim, s.b0, s.b1
        off = bvh["prim_offset"][node]
        n_tris = tri_verts.shape[0]
        for k in range(MAX_LEAF_PRIMS):
            pidx = jnp.minimum(off + k, n_tris - 1)
            tri = tri_verts[pidx]
            h, th, b0h, b1h = intersect_triangle(o, d, tri[0], tri[1], tri[2], t_new)
            take = test_leaf & (k < n_prims) & h
            t_new = jnp.where(take, th, t_new)
            prim_new = jnp.where(take, pidx, prim_new)
            b0_new = jnp.where(take, b0h, b0_new)
            b1_new = jnp.where(take, b1h, b1_new)

        # descend interior front-to-back, else pop
        go_down = hit_box & ~is_leaf
        ax = bvh["axis"][node]
        neg = dir_neg[ax]
        first = jnp.where(neg, bvh["second_child"][node], node + 1)
        second = jnp.where(neg, node + 1, bvh["second_child"][node])
        stack = jnp.where(go_down, s.stack.at[s.sp].set(second), s.stack)
        sp_push = jnp.where(go_down, s.sp + 1, s.sp)
        # pop path
        exhausted = sp_push == 0
        sp_pop = jnp.maximum(sp_push - 1, 0)
        popped = stack[sp_pop]
        next_node = jnp.where(go_down, first, popped)
        next_sp = jnp.where(go_down, sp_push, sp_pop)
        done = jnp.where(go_down, False, exhausted)
        if any_hit:
            done = done | (prim_new >= 0)
        return _TravState(next_node, next_sp, stack, t_new, prim_new, b0_new, b1_new, done)

    init = _TravState(
        node=jnp.int32(0),
        sp=jnp.int32(0),
        stack=jnp.zeros((MAX_STACK,), jnp.int32),
        t=jnp.asarray(t_max, jnp.float32),
        prim=jnp.int32(-1),
        b0=jnp.float32(0),
        b1=jnp.float32(0),
        done=jnp.bool_(False),
    )
    out = jax.lax.while_loop(cond, body, init)
    return Hit(out.t, out.prim, out.b0, out.b1)


@partial(jax.jit, static_argnames=())
def bvh_intersect(bvh, tri_verts, o, d, t_max) -> Hit:
    """Closest-hit for a ray batch. bvh: dict of SoA arrays; o,d: (R,3);
    t_max: scalar or (R,)."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    return jax.vmap(lambda oo, dd, tt: _ray_traverse(bvh, tri_verts, oo, dd, tt, False))(o, d, t_max)


@partial(jax.jit, static_argnames=())
def bvh_intersect_p(bvh, tri_verts, o, d, t_max) -> jnp.ndarray:
    """Any-hit (shadow ray) predicate for a ray batch -> bool (R,)."""
    t_max = jnp.broadcast_to(jnp.asarray(t_max, jnp.float32), o.shape[:-1])
    hit = jax.vmap(lambda oo, dd, tt: _ray_traverse(bvh, tri_verts, oo, dd, tt, True))(o, d, t_max)
    return hit.prim >= 0


def bvh_as_device_dict(bvh_arrays) -> dict:
    """BVHArrays (numpy) -> device dict consumed by the traversal kernels.
    Fails loudly if the tree is deeper than the fixed traversal stack."""
    import numpy as _np

    n_prims = _np.asarray(bvh_arrays.n_prims)
    second = _np.asarray(bvh_arrays.second_child)
    n = n_prims.shape[0]
    depth = _np.ones(n, _np.int64)
    # DFS layout: children have larger ids. Interior nodes are n_prims == 0
    # with a forward second-child pointer; the Morton build also emits empty
    # padded leaves (n_prims == 0, second == 0, inf/-inf bounds) which the
    # traversal never descends — skip them here the same way.
    for i in range(n - 1, -1, -1):
        if n_prims[i] == 0 and second[i] > i and i + 1 < n:
            depth[i] = 1 + max(depth[i + 1], depth[second[i]])
    if int(depth[0]) > MAX_STACK:
        raise ValueError(
            f"binary BVH depth {int(depth[0])} exceeds MAX_STACK={MAX_STACK}; "
            "raise MAX_STACK in accel/traverse.py"
        )
    return {
        "bounds_min": jnp.asarray(bvh_arrays.bounds_min, jnp.float32),
        "bounds_max": jnp.asarray(bvh_arrays.bounds_max, jnp.float32),
        "prim_offset": jnp.asarray(bvh_arrays.prim_offset, jnp.int32),
        "n_prims": jnp.asarray(bvh_arrays.n_prims, jnp.int32),
        "second_child": jnp.asarray(bvh_arrays.second_child, jnp.int32),
        "axis": jnp.asarray(bvh_arrays.axis, jnp.int32),
    }
