"""Pallas TPU wavefront kernels: the fused flush + expand stages.

Stage one of the bounce megakernel (ROADMAP direction #1 / PAPER.md's
"one fused Pallas wavefront kernel"). The stream tracer's two dense
phases each become ONE Pallas grid:

FLUSH (`fused_flush_chunk`): the whole leaf-intersection pipeline for a
chunk of 128-ray treelet blocks — per-block ray-feature gather +
re-center (the phi build), the treelet feature row DMA'd HBM->VMEM by a
scalar-prefetch index_map (the schedule the retired TPU_PBRT_PREFETCH
kernel pioneered), the Möller–Trumbore MXU product, the per-lane
closest-hit decode, AND the cross-block per-ray merge against
VMEM-resident (R,) winner accumulators. The jnp path materializes the
(CH, 16, 128) phi tensor, a (CH, 16, 4L) gathered feature copy and the
(CH, 128, 4L) matmul product in HBM and re-reads them through decode and
`_merge_chunk`'s sort; the kernel's only HBM traffic is the feature rows
(once per block), the (CH, 128) block tables, the (8, R) ray table
(fetched once per chunk) and the final (R,) t/prim winners.

EXPAND (`fused_expand`): the dense middle of the traversal step — the
per-pair ray fetch, the 8-child node fetch (the one-hot MXU matmul for
small top trees, exactly `stream._fetch_children`'s table so culling
stays bit-identical, or the native take for big ones), the lane-major
slab tests and the packed push-key build — with the popped stack slab
resident in VMEM for the whole grid. The sort-based compaction stays at
jnp level: lax.sort has no Pallas lowering and XLA's int-key radix path
is already the measured-fast primitive (accel/stream.py module doc).

Bit-identity contract (pinned by tests/test_fusedwave.py in interpret
mode): identical EDGE_EPS band, identical argmin tiebreak (lowest local
triangle index), and a merge whose final (t, prim) equals the jnp
`_merge_chunk` sort exactly. Two structural arguments make the simpler
in-kernel forms safe:

- the kernel drops the per-block `t < t_max` pre-cull: removing the
  upper bound only ADDS candidates with t >= the ray's current best,
  and the merge's strict `<` rejects every one of them, so the final
  winner (and its tie-break) cannot change;
- the sequential strict-`<` merge in grid order equals the chunked
  stable-sort merge: lax.sort is stable, so among equal-(ray, t)
  candidates the jnp path keeps buffer order — exactly the grid order —
  and `<` keeps the first winner, `.at[].min` + strict-`<` prim update
  keep it too.

TPU grid steps execute sequentially, which is what makes the
accumulator outputs (constant index_map -> block revisiting keeps them
in VMEM across the whole grid) and the ordered merge sound. Both
pallas_calls DECLARE that requirement (`dimension_semantics =
("arbitrary",)` below): a dim flipped to "parallel" would let megacore
interleave grid steps across cores and silently race the accumulator
merge — pallascheck's PC-RACE rule fails the repo gate on exactly that
flip, and PC-INIT pins the `@pl.when(b == 0)` accumulator seed.
Interpret mode (`interpret=True` on CPU backends) preserves the same
sequential semantics — that is the CPU testing story.

VMEM budgets are no longer hand-derived here: the per-grid-step
footprint of every kernel (double-buffered moving blocks + resident
accumulators + flat scratch) is computed statically by
`tpu_pbrt/analysis/pallascheck.py`, gated against the committed
`analysis/vmem_budgets.json`, and INVERTED to derive the maximal safe
caps — `python -m tpu_pbrt.analysis.pallascheck --derive-caps` prints
the maximal TPU_PBRT_FUSED_MAX_RAYS / MAX_NODES per platform VMEM
size; the config.py defaults (2^18 rays, 2^14 nodes) are a checked
consequence of that model (PC-CAPS), not folklore.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_pbrt.accel.mxu import EDGE_EPS
from tpu_pbrt.accel.treelet import decode_top_leaf
from tpu_pbrt.accel.wide import _EMPTY, slab_test_lane_major

#: rays per leaf block (the MXU matmul row dim — mirrors stream.BLOCK)
BLOCK = 128
#: lanes per fused-expand grid step
EXPAND_TILE = 1024

_I32_MAX = np.int32(2**31 - 1)

#: Mosaic dimension semantics for the two 1-D grids. "arbitrary" =
#: sequential execution in grid order — the property BOTH correctness
#: proofs above rest on (the ordered closest-hit merge and the b == 0
#: accumulator seed). Declared explicitly (not left to the Mosaic
#: default) so pallascheck's PC-RACE rule verifies it per kernel;
#: flipping either to ("parallel",) fails `python -m tpu_pbrt.analysis`.
FLUSH_DIM_SEMANTICS = ("arbitrary",)
EXPAND_DIM_SEMANTICS = ("arbitrary",)


# --------------------------------------------------------------------------
# FLUSH: phi build + treelet DMA + MT matmul + decode + closest-hit merge
# --------------------------------------------------------------------------


def _seed_accumulators(t_in_ref, p_in_ref, t_out_ref, p_out_ref):
    """Seed the VMEM-resident winner accumulators from the wave's
    current (t, prim) — must run on grid step 0, before any merge reads
    them (pallascheck PC-INIT fails the repo gate if this goes missing);
    they are written back to HBM only once, after the last grid step."""
    t_out_ref[...] = t_in_ref[...]
    p_out_ref[...] = p_in_ref[...]


def _flush_kernel(meta_ref, feat_ref, rid_ref, rayF_ref, t_in_ref,
                  p_in_ref, t_out_ref, p_out_ref, t_scr, p_scr,
                  *, L: int, motion: bool):
    """One grid step = one leaf block (one treelet x 128 rays).

    meta row (8,) i32: [treelet id, prim offset, center xyz (f32 bits),
    block live flag, 0, 0]. The treelet id drove the scalar-prefetch
    index_map that DMA'd feat_ref before this body ran."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        _seed_accumulators(t_in_ref, p_in_ref, t_out_ref, p_out_ref)

    @pl.when(meta_ref[b, 5] > 0)
    def _():
        rid = rid_ref[0]  # (128,) i32, -1 = empty slot
        ridc = jnp.maximum(rid, 0)
        # the block-build gather: 128 ray columns (o, d, t, time) pulled
        # from the VMEM-resident lane-major ray table — the jnp path's
        # (8, CH*BLOCK) HBM gather + (CH, 8, BLOCK) swap, fused away
        rr = jnp.take(rayF_ref[...], ridc, axis=1)  # (8, 128)
        ctr = jnp.stack([
            jax.lax.bitcast_convert_type(meta_ref[b, 2 + i], jnp.float32)
            for i in range(3)
        ])  # (3,) treelet re-center point
        oc = [rr[i] - ctr[i] for i in range(3)]
        dc = [rr[3 + i] for i in range(3)]
        phiT = jnp.stack(
            [oc[i] * dc[j] for i in range(3) for j in range(3)]
            + dc + oc + [jnp.ones_like(oc[0])],
        )  # (16, 128) — same row order as stream._flush's jnp build
        if motion:
            tm_r = rr[7]
            phiT = jnp.concatenate(
                [phiT, phiT * tm_r[None, :],
                 phiT * (tm_r * tm_r)[None, :],
                 phiT * (tm_r * tm_r * tm_r)[None, :]],
                axis=0,
            )  # (64, 128) cubic-in-time features
        featT = feat_ref[0]  # (F, 4L), F features on the contraction dim
        out4 = jax.lax.dot_general(
            featT, phiT,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (4L, 128)
        det = out4[0 * L: 1 * L]
        udet = out4[1 * L: 2 * L]
        vdet = out4[2 * L: 3 * L]
        tdet = out4[3 * L: 4 * L]
        inv = 1.0 / jnp.where(det == 0.0, 1.0, det)
        u = udet * inv
        v = vdet * inv
        t = tdet * inv
        # same EDGE_EPS band as mxu.decode_outputs; the t < t_max bound
        # is enforced by the merge's strict `<` below (see module doc)
        hit = (
            (det != 0.0)
            & (u >= -EDGE_EPS)
            & (v >= -EDGE_EPS)
            & (u + v <= 1.0 + EDGE_EPS)
            & (t > 0.0)
        )
        tm = jnp.where(hit, t, jnp.inf)  # (L, 128)
        # argmin = the lowest local index among equal-t hits — the
        # pinned tiebreak, identical to decode_outputs
        t_scr[...] = jnp.min(tm, axis=0, keepdims=True)
        k = jnp.argmin(tm, axis=0, keepdims=True).astype(jnp.int32)
        p_scr[...] = meta_ref[b, 1] + k  # global leaf-order prim id

        def lane(i, carry):
            r = rid_ref[0, i]
            # clamp BOTH ends: ray ids are < R by construction (and the
            # store is r >= 0 guarded), so the clip is value-identical —
            # it exists so pallascheck's PC-OOB interval proof closes on
            # the meta-driven accumulator indexing below
            rc = jnp.clip(r, 0, t_out_ref.shape[1] - 1)
            tc = t_scr[0, i]
            cur = t_out_ref[0, rc]

            @pl.when((r >= 0) & (tc < cur))
            def _():
                # Pallas REF stores (mutable by contract), reached via
                # fori_loop so the AST walk cannot see the pallas_call
                # boundary above them
                t_out_ref[0, rc] = tc  # jaxlint: disable=JL-MUT
                p_out_ref[0, rc] = p_scr[0, i]  # jaxlint: disable=JL-MUT

            return carry

        # sequential per-lane scatter-min: ray ids within a block are
        # unique (a ray reaches a treelet leaf at most once per wave),
        # so lane order inside the loop is immaterial; grid order
        # supplies the buffer order the stable-sort merge would use
        jax.lax.fori_loop(0, BLOCK, lane, 0)


@partial(jax.jit, static_argnames=("interpret",))
def fused_flush_chunk(feat_table, meta, rid_rows, rayF, t_row, prim,
                      interpret: bool = False):
    """Fold one chunk of leaf blocks into the per-ray best (t, prim).

    feat_table: (C, F, 4L) full treelet feature table, resident in HBM —
    the grid's scalar-prefetch index_map DMAs exactly row meta[b, 0] per
    step. meta: (CH, 8) i32 per-block scalars (see _flush_kernel).
    rid_rows: (CH, 128) i32 ray ids, -1 = empty slot. rayF: (8, R)
    lane-major ray table [o | d | t | time]. t_row/prim: (R,) current
    winners. Returns the updated (t_row, prim) — the ONLY per-chunk HBM
    writes."""
    CH = meta.shape[0]
    _, F, fourL = feat_table.shape
    L = fourL // 4
    R = rayF.shape[1]
    t2 = t_row[None, :]
    p2 = prim[None, :]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(CH,),
        in_specs=[
            pl.BlockSpec((1, F, fourL), lambda i, m: (m[i, 0], 0, 0)),
            pl.BlockSpec((1, BLOCK), lambda i, m: (i, 0)),
            pl.BlockSpec((8, R), lambda i, m: (0, 0)),
            pl.BlockSpec((1, R), lambda i, m: (0, 0)),
            pl.BlockSpec((1, R), lambda i, m: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, R), lambda i, m: (0, 0)),
            pl.BlockSpec((1, R), lambda i, m: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, BLOCK), jnp.float32),
            pltpu.VMEM((1, BLOCK), jnp.int32),
        ],
    )
    t_out, p_out = pl.pallas_call(
        partial(_flush_kernel, L=L, motion=(F == 64)),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((1, R), jnp.float32),
            jax.ShapeDtypeStruct((1, R), jnp.int32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=FLUSH_DIM_SEMANTICS,
        ),
        interpret=interpret,
    )(meta, feat_table, rid_rows, rayF, t2, p2)
    return t_out[0], p_out[0]


# --------------------------------------------------------------------------
# EXPAND: ray fetch + child fetch + slab tests + push-key build
# --------------------------------------------------------------------------


def _expand_kernel(key_ref, node_ref, rayE_ref, *refs,
                   tb: int, R: int, use_onehot: bool, any_hit: bool):
    """One grid step = EXPAND_TILE popped (ray, node) pairs: everything
    stream._expand does between the stack pop and the compaction sort.
    refs order: [prim (any_hit)] + ([tab64] if use_onehot else
    [box48, cid]) + [key_out, cand_out, live_out]."""
    refs = list(refs)
    prim_ref = refs.pop(0) if any_hit else None
    if use_onehot:
        tab_ref = refs.pop(0)
    else:
        box_ref = refs.pop(0)
        cid_ref = refs.pop(0)
    key_out_ref, cand_out_ref, live_out_ref = refs

    key_in = key_ref[0]  # (T,) i32; invalid/pad lanes carry I32_MAX
    node = node_ref[0]  # (T,) i32
    T = key_in.shape[0]
    rid = jnp.clip((key_in - (1 << 30)) >> tb, 0, R - 1)
    if tb:
        comp = (key_in - (1 << 30)) & ((1 << tb) - 1)
        tn_in = jax.lax.bitcast_convert_type(
            ((1 << tb) - 1 - comp) << (31 - tb), jnp.float32
        )
    else:
        tn_in = jnp.zeros_like(key_in, jnp.float32)
    tn_in = jnp.where(key_in != _I32_MAX, tn_in, jnp.inf)
    rows = jnp.take(rayE_ref[...], rid, axis=1)  # (8, T)
    t_r = rows[6]
    live = (key_in != _I32_MAX) & (tn_in <= t_r)
    if any_hit:
        live = live & (jnp.take(prim_ref[0], rid) < 0)

    if use_onehot:
        # the SAME clamped 64-row table + rounding reassembly as
        # stream._fetch_children: culling decisions (1-ulp box wobble
        # absorbed by _BOX_EPS) stay bit-identical to the jnp path
        tab64 = tab_ref[...]  # (64, N)
        N = tab64.shape[1]
        oh = (
            node[None, :] == jax.lax.broadcasted_iota(jnp.int32, (N, T), 0)
        ).astype(jnp.float32)
        out = jax.lax.dot(
            tab64, oh, precision=jax.lax.Precision.HIGHEST
        )  # (64, T)
        nb = out[:48].reshape(6, 8, T)
        lo = jnp.round(out[48:56]).astype(jnp.int32)
        hi = jnp.round(out[56:64]).astype(jnp.int32)
        cids = (hi << 16) | lo
    else:
        nb = jnp.take(box_ref[...], node, axis=1).reshape(6, 8, T)
        cids = jnp.take(cid_ref[...], node, axis=1)  # (8, T)

    ray6 = rows[0:6]
    tx0, tx1 = slab_test_lane_major(nb[0], nb[3], ray6[0][None, :], ray6[3][None, :])
    ty0, ty1 = slab_test_lane_major(nb[1], nb[4], ray6[1][None, :], ray6[4][None, :])
    tz0, tz1 = slab_test_lane_major(nb[2], nb[5], ray6[2][None, :], ray6[5][None, :])
    tn8 = jnp.maximum(jnp.maximum(tx0, ty0), jnp.maximum(tz0, 0.0))
    tf8 = jnp.minimum(jnp.minimum(tx1, ty1), jnp.minimum(tz1, t_r[None, :]))
    in_slab = tn8 <= tf8

    hit8 = live[None, :] & in_slab & (cids != _EMPTY)
    is_int = hit8 & (cids >= 0)
    is_leaf = hit8 & (cids < 0)
    rid8 = jnp.broadcast_to(rid[None, :], cids.shape)
    if tb:
        qtn = jax.lax.shift_right_logical(
            jax.lax.bitcast_convert_type(tn8, jnp.int32), 31 - tb
        )
    else:
        qtn = 0
    key_leaf = rid8
    key_int = (1 << 30) + (rid8 << tb) + (((1 << tb) - 1) - qtn)
    key_out_ref[...] = jnp.where(
        is_leaf, key_leaf, jnp.where(is_int, key_int, _I32_MAX)
    )
    cand_out_ref[...] = jnp.where(is_leaf, decode_top_leaf(cids), cids)
    live_out_ref[...] = live.astype(jnp.int32)[None, :]


@partial(jax.jit, static_argnames=("tb", "use_onehot", "any_hit", "interpret"))
def fused_expand(key_in, node, rayE, prim, tab64, box48, cid,
                 tb: int, use_onehot: bool, any_hit: bool,
                 interpret: bool = False):
    """Child candidates for a popped stack slab, in one Pallas grid.

    key_in/node: (S,) packed interior keys + node ids (invalid lanes
    already masked to I32_MAX / 0 by the caller — they produce dead
    output keys). rayE: (8, R) lane-major [o | inv_d | t]. prim: (R,)
    current hit ids (read only under any_hit; pass anything otherwise).
    tab64 OR box48+cid: the node table in the SAME representation the
    jnp `_fetch_children` would use for this top tree. Returns
    (key8, cand8, live) of shapes ((8, Sp), (8, Sp), (Sp,)) where
    Sp >= S is S rounded up to the grid tile; the pad lanes are dead
    (key = I32_MAX) and the caller's compaction sort drops them."""
    S = key_in.shape[0]
    R = rayE.shape[1]
    tile = min(EXPAND_TILE, S)
    n_tiles = -(-S // tile)
    sp = n_tiles * tile
    if sp != S:
        key_in = jnp.concatenate(
            [key_in, jnp.full((sp - S,), _I32_MAX, jnp.int32)]
        )
        node = jnp.concatenate([node, jnp.zeros((sp - S,), jnp.int32)])

    in_specs = [
        pl.BlockSpec((1, tile), lambda i: (0, i)),
        pl.BlockSpec((1, tile), lambda i: (0, i)),
        pl.BlockSpec((8, R), lambda i: (0, 0)),
    ]
    args = [key_in[None, :], node[None, :], rayE]
    if any_hit:
        in_specs.append(pl.BlockSpec((1, R), lambda i: (0, 0)))
        args.append(prim[None, :])
    if use_onehot:
        N = tab64.shape[1]
        in_specs.append(pl.BlockSpec((64, N), lambda i: (0, 0)))
        args.append(tab64)
    else:
        N = box48.shape[1]
        in_specs.append(pl.BlockSpec((48, N), lambda i: (0, 0)))
        in_specs.append(pl.BlockSpec((8, N), lambda i: (0, 0)))
        args.extend([box48, cid])

    key8, cand8, live = pl.pallas_call(
        partial(_expand_kernel, tb=tb, R=R, use_onehot=use_onehot,
                any_hit=any_hit),
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((8, tile), lambda i: (0, i)),
            pl.BlockSpec((8, tile), lambda i: (0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, sp), jnp.int32),
            jax.ShapeDtypeStruct((8, sp), jnp.int32),
            jax.ShapeDtypeStruct((1, sp), jnp.int32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=EXPAND_DIM_SEMANTICS,
        ),
        interpret=interpret,
    )(*args)
    return key8, cand8, live[0]
