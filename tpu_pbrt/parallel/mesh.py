"""Distribution layer: the tile scheduler over a TPU device mesh.

Capability match for the reference's distributed layer (SURVEY.md §2e/§3.4)
and for src/core/parallel.{h,cpp}:
- ParallelFor2D's tile decomposition -> the flat work-index space is split
  across mesh devices inside a shard_map (static round-robin tile
  assignment: the fork's master/worker tile protocol collapsed into SPMD).
- Worker->master FilmTile return + Film::MergeFilmTile -> a `psum` over the
  mesh axis: film accumulation is associative, so the distributed film
  merge is ONE ICI all-reduce per chunk (the north star's "distributed film
  merge becomes an ICI all-reduce into a sharded framebuffer").
- The thread pool / work queue / mutex / AtomicFloat machinery has no
  equivalent here because the SPMD program replaces it: races are designed
  out (SURVEY.md §5.2).
- Multi-host: the same shard_map spans hosts under jax.distributed; the
  host-side spp-chunk loop is the dynamic re-dispatch seam for
  straggler/failure handling (chunks are idempotent pure functions of
  (scene, work range), SURVEY.md §5.3).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # shard_map moved out of experimental in jax 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

import inspect


def _jax_version() -> tuple:
    """(major, minor, patch) of the running jax, zeros on parse failure
    (dev builds) so the conservative branch wins."""
    parts = []
    for tok in jax.__version__.split(".")[:3]:
        digits = "".join(c for c in tok if c.isdigit())
        parts.append(int(digits) if digits else 0)
    while len(parts) < 3:
        parts.append(0)
    return tuple(parts)


def resolve_shard_map_nocheck() -> dict:
    """kwargs for shard_map's replication/varying-manual-axes check,
    gated on jax version (ISSUE 3 satellite).

    On jax 0.4.x-0.6.x the native `check_rep` rejects our programs: the
    BVH/drain while_loops carry values that start replicated and become
    varying over the tile axis, and pre-0.7 check_rep has no pvary
    plumbing for loop carries — PR 1 measured three test_distributed
    failures from it, so those versions get `check_rep=False`. From the
    0.7 varying-manual-axes rework on, the native check is EXPECTED to
    understand loop-carry transitions; keep it enabled there so jax
    cross-validates what analysis/shardcheck.py verifies statically (two
    independent checkers watching the same invariant). That expectation
    is untestable on the pinned container jax (0.4.37) — if a given
    0.7+ release still rejects our carries (e.g. demands explicit
    jax.lax.pvary), every mesh render fails at trace time with jax's
    own diagnostic: set TPU_PBRT_SHARD_NATIVE_CHECK=0 and file the
    version here. The kwarg is `check_vma` in new jax and `check_rep`
    before; resolve against the live signature.

    TPU_PBRT_SHARD_NATIVE_CHECK=1/0 overrides the version gate both ways
    (escape hatch for a jax release where the auto choice is wrong)."""
    from tpu_pbrt.config import cfg

    kwarg = (
        "check_vma"
        if "check_vma" in inspect.signature(shard_map).parameters
        else "check_rep"
    )
    native_ok = cfg.shard_native_check
    if native_ok is None:
        native_ok = _jax_version() >= (0, 7, 0)
    return {} if native_ok else {kwarg: False}


#: resolved once at import (config snapshot contract); empty on versions
#: where jax's own check is trusted, `{check_rep/check_vma: False}` where
#: it is known-broken for our loop-carry programs
SHARD_MAP_NOCHECK = resolve_shard_map_nocheck()

TILE_AXIS = "tiles"


def maybe_init_distributed(options=None) -> bool:
    """Multi-host seam: bring up the JAX distributed runtime (DCN
    coordination; the multi-host analog of the fork's master/worker
    socket channel). Activates when the standard cluster-environment
    variables are present (JAX_COORDINATOR_ADDRESS / auto-detected TPU
    pod env) or options.multihost is set. Idempotent; returns whether the
    distributed runtime is live. After this, jax.devices() spans all
    hosts and the same shard_map program runs pod-wide."""
    from tpu_pbrt.config import coordinator_address

    want = bool(getattr(options, "multihost", False)) or bool(
        coordinator_address()
    )
    if not want:
        return False
    try:
        import time as _time

        from tpu_pbrt.obs.metrics import METRICS

        t0 = _time.perf_counter()
        jax.distributed.initialize()
        # DCN coordination cost is a render-startup phase a fleet
        # monitor wants attributed like any other (host-side registry;
        # no-op under TPU_PBRT_METRICS=0)
        METRICS.gauge(
            "distributed_init_seconds",
            "wall seconds jax.distributed.initialize took",
        ).set(_time.perf_counter() - t0)
        return True
    except (RuntimeError, ValueError) as e:
        # already initialized counts as success
        if "already" in str(e).lower():
            return True
        from tpu_pbrt.utils.error import Warning as _W

        _W(f"jax.distributed.initialize failed: {e}; running single-host")
        return False


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D device mesh over the tile axis (a renderer's parallel axis is
    image/sample space — SURVEY.md §2f maps it to data-parallel)."""
    devs = devices if devices is not None else jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (TILE_AXIS,))


def resolve_mesh(mesh_shape) -> Optional[Mesh]:
    """Options.mesh_shape -> Mesh (or None for single-device): the CLI's
    '--mesh 2,4' spelling resolved against the live device set. Shared
    by the run-to-completion render loop and the render service so both
    frontends mean the same thing by the same flag. A request for more
    devices than exist degrades to single-device (matching the render
    loop's historical behavior) rather than erroring — the scene still
    renders, just not sharded."""
    from tpu_pbrt.obs.metrics import METRICS

    mesh = None
    if mesh_shape:
        n_req = int(np.prod(tuple(mesh_shape)))
        if n_req > 1 and len(jax.devices()) >= n_req:
            mesh = make_mesh(n_req)
    # the mesh width every drain in this process fans over — the
    # denominator a monitor needs next to the per-device wave-spread
    # telemetry (1 = single-device, incl. a degraded fallback)
    METRICS.gauge(
        "mesh_devices", "devices in the resolved render mesh"
    ).set(1 if mesh is None else mesh.devices.size)
    return mesh


def resolve_pipeline_depth(mesh: Optional[Mesh] = None) -> int:
    """Effective in-flight dispatch window for the drain loops (ISSUE
    13): how many chunk-slices stay launched ahead of the host.
    TPU_PBRT_PIPELINE (default 2), clamped to >= 1 — depth 1 is the
    strictly synchronous dispatch/block/host-work loop, the A/B
    baseline the host_overlap_fraction acceptance compares against.

    The strict non-finite firewall modes (TPU_PBRT_NONFINITE=raise|
    retry) force depth 1: they read each chunk's scrub count before the
    NEXT dispatch may trust the accumulator — a per-chunk device sync
    pipelining cannot hide, and eager checking keeps the failure
    attributed to the exact chunk that scrubbed.

    A mesh does not widen the window: every dispatch spans the whole
    mesh (one SPMD program per chunk), so the in-flight slices are in
    program order regardless of device count. `mesh` is accepted for
    call-site symmetry and future per-topology tuning."""
    from tpu_pbrt.config import cfg

    if cfg.nonfinite != "scrub":
        return 1
    return max(1, int(cfg.pipeline))


def device_spread(value, n_dev: int, axis: str = TILE_AXIS):
    """One-hot scatter of a per-device scalar into an (n_dev,) vector:
    device i contributes `value` at slot i, zeros elsewhere, so the
    drain's EXISTING aux psum reconstructs the full per-device vector on
    every device — an all_gather's result without adding a collective
    (sharded_pool_renderer's no-new-collectives contract and the
    shardcheck SC-LOOP-COLLECTIVE analysis both stay untouched).

    This is how the ROADMAP multi-chip metric — the per-device
    wave-count spread of the independent pool drains — leaves the mesh
    step (obs/counters.spread_stats turns the vector into min/max/
    rel_spread on the host). Call only inside a shard_map body."""
    import jax.numpy as jnp

    i = jax.lax.axis_index(axis)
    return jnp.zeros((n_dev,), jnp.int32).at[i].set(
        jnp.asarray(value, jnp.int32)
    )


def sharded_chunk_renderer(mesh: Mesh, per_device_fn):
    """Wrap a per-device chunk body into an SPMD step with film all-reduce.

    per_device_fn(dev, start_scalar) -> (film_contrib pytree, aux pytree):
    the film contribution of that device's work-items plus scalar
    accounting (nrays, and the firewall's non-finite scrub count when
    telemetry is on). The wrapped function takes (dev, starts (n_dev,))
    with starts sharded over the mesh and returns the psum-merged
    (film_contrib, aux), replicated — ready to add into the accumulated
    film state.

    Failure model (ISSUE 5): there is no per-device recovery INSIDE the
    SPMD step — a lost device fails the whole dispatch (the host sees a
    JaxRuntimeError), and the render loop's recovery ladder handles it
    as a state-poisoning chunk failure: rollback to the last durable
    checkpoint (or restart) + capped-backoff re-dispatch. Chunks are
    idempotent, so the re-run on the surviving mesh is exact. The chaos
    plan's `mesh:lost@chunk=N` injects exactly this shape on the CPU
    mesh; true degraded-mesh continuation (re-forming a smaller mesh
    without a restart) is a ROADMAP open item pending live hardware."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(TILE_AXIS)),
        out_specs=(P(), P()),
        **SHARD_MAP_NOCHECK,
    )
    def step(dev, starts):
        contrib, aux = per_device_fn(dev, starts)
        contrib = jax.tree.map(lambda x: jax.lax.psum(x, TILE_AXIS), contrib)
        aux = jax.tree.map(lambda x: jax.lax.psum(x, TILE_AXIS), aux)
        return contrib, aux

    return step


def sharded_pool_renderer(mesh: Mesh, per_device_drain):
    """Persistent-wavefront (compaction+regeneration) analog of
    sharded_chunk_renderer: each device DRAINS its own flat work slice
    through a resident path pool driven by a per-device work counter,
    instead of advancing one static batch in lockstep.

    per_device_drain(dev, start_pair) -> (film_contrib pytree, aux pytree)
    runs the whole drain loop for that device's slice. There are NO
    collectives inside the drain, so the SPMD while_loops are free to run
    different iteration counts per device — a device whose paths die
    early regenerates new pixels from its counter and finishes its slice
    in fewer waves rather than idling on the longest path; the film psum
    after the drain is the only sync point. aux (ray/occupancy counters)
    is psum-reduced alongside the film."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(TILE_AXIS)),
        out_specs=(P(), P()),
        **SHARD_MAP_NOCHECK,
    )
    def step(dev, starts):
        contrib, aux = per_device_drain(dev, starts)
        contrib = jax.tree.map(lambda x: jax.lax.psum(x, TILE_AXIS), contrib)
        aux = jax.tree.map(lambda x: jax.lax.psum(x, TILE_AXIS), aux)
        return contrib, aux

    return step
