"""Checkpoint / resume for in-progress renders.

Capability the reference lacks (SURVEY.md §5.4 flags it as the TPU build's
cheap win): because film accumulation is associative and every chunk is an
idempotent pure function of (scene, work range), a checkpoint is just the
accumulated film pytree plus the chunk cursor. The counter-based RNG keyed
on (pixel, sample, dimension) makes a resumed render bit-identical to an
uninterrupted one.

Durability (ISSUE 5 hardening). A write is tmp + fsync(tmp) + fsync(dir)
+ rename: without the fsyncs, a crash AFTER the rename could still leave
a zero-length "durable" checkpoint (the rename is atomic in the namespace
but the data may not have reached the platter). Format v4 adds a CRC32
content checksum over the film arrays + metadata, and every write rotates
the previous good file to `<path>.prev` — `load_checkpoint` detects a
corrupt/torn current file (checksum mismatch, truncated zip, short read)
and falls back to `.prev` instead of crashing the resume. Corruption is
distinct from misconfiguration: a version/fingerprint mismatch still
raises immediately (falling back would silently resume the wrong render).

Format history: v2 = film + cursor + fingerprint; v3 added the cumulative
telemetry-counter snapshot (obs/counters host dict, JSON-encoded) so a
resumed render reports END-TO-END totals; v4 added the content checksum.
v2/v3 files still load (no checksum to verify, empty counters for v2).

Chaos seams (tpu_pbrt/chaos): `ckpt:torn|crash|bitflip@write=N` faults
are applied here — a torn final file, a simulated crash between the tmp
write and the rename, and a seeded bit-flip — so the recovery path above
is continuously testable on CPU.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Optional

import numpy as np

from tpu_pbrt.chaos import CHAOS
from tpu_pbrt.core.film import FilmState

_FORMAT_VERSION = 4
#: versions load_checkpoint still understands
_COMPAT_VERSIONS = (2, 3, 4)

#: write observers: fn(path, next_chunk, rays) called whenever a VALID
#: checkpoint is durably published (after the rename; never for the
#: simulated crash/torn chaos outcomes, which publish nothing usable).
#: The protocol checker (analysis layer 6) hooks here to verify
#: deferred-write linearity — per path the published cursor must be
#: monotone nondecreasing, so a superseded cadence write replayed after
#: a park shows up as a cursor regression — without monkeypatching the
#: writer it is auditing.
_WRITE_OBSERVERS: list = []


def register_write_observer(fn) -> None:
    _WRITE_OBSERVERS.append(fn)


def unregister_write_observer(fn) -> None:
    try:
        _WRITE_OBSERVERS.remove(fn)
    except ValueError:
        pass


class CorruptCheckpointError(ValueError):
    """The checkpoint file cannot be trusted (torn/short/bit-flipped —
    checksum mismatch or unparseable archive). Distinct from the plain
    ValueError raised for version/fingerprint MISconfiguration:
    corruption triggers the `.prev` fallback, misconfiguration never
    does."""


def _content_checksum(
    rgb: np.ndarray, weight: np.ndarray, splat: np.ndarray,
    next_chunk: int, rays: int, fingerprint: str, counters_json: str,
) -> int:
    crc = 0
    for a in (rgb, weight, splat):
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    meta = f"{int(next_chunk)}|{int(rays)}|{fingerprint}|{counters_json}"
    return zlib.crc32(meta.encode(), crc) & 0xFFFFFFFF


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """fsync the containing directory so the rename itself is durable;
    best-effort — some filesystems refuse O_RDONLY on directories and a
    telemetry-grade durability upgrade must not kill the render."""
    try:
        _fsync_path(os.path.dirname(os.path.abspath(path)) or ".")
    except OSError:
        pass


def _rotate_prev(path: str) -> None:
    """Rotate the current checkpoint to `<path>.prev` WITHOUT a window
    where no file exists at `path`: hardlink the current inode to .prev
    and let the caller's later os.replace atomically swap the new data
    in. A rename-based rotate would un-publish the current file until
    the replace lands — a crash in that window leaves a resume that
    silently restarts from chunk 0 despite a good .prev on disk. Falls
    back to the rename on filesystems without hardlinks (the
    checkpoint_exists()/load fallback still recovers there)."""
    if not os.path.exists(path):
        return
    prev = path + ".prev"
    try:
        os.remove(prev)
    except FileNotFoundError:
        pass
    try:
        os.link(path, prev)
    except OSError:
        os.replace(path, prev)


def begin_host_copy(state: FilmState) -> None:
    """Start the device->host DMA for a film state EARLY, best-effort.

    The pipelined drain loops (ISSUE 13) call this when they defer a
    cadence checkpoint write: the write runs only once the slice it
    covers has retired, so starting the copy at enqueue time means the
    transfer streams out under device compute and `save_checkpoint`'s
    np.asarray fetch becomes a wait on an already-moving DMA instead of
    a fresh round trip. Safe only because a deferred write holds an
    UN-DONATED accumulator (pipeline depth > 1 compiles donation out of
    the chunk closure — see ChunkPlan.pipeline_depth); a donated buffer
    must never be touched after dispatch. Advisory: arrays without the
    async-copy API (or backends that refuse it) fall through to the
    blocking fetch at write time."""
    for leaf in (state.rgb, state.weight, state.splat):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a failed prefetch only
                pass  # costs the blocking fetch the write always paid


def checkpoint_exists(path: str) -> bool:
    """True when `path` OR its `.prev` rotation holds a resumable file.
    Resume/rollback sites must use this rather than a bare exists(path):
    after a crash inside a (hardlink-less) rotation, or a deleted
    current file, load_checkpoint still recovers via .prev — a bare
    check would silently restart from scratch instead."""
    return os.path.exists(path) or os.path.exists(path + ".prev")


def save_checkpoint(
    path: str,
    state: FilmState,
    next_chunk: int,
    rays_so_far: int,
    fingerprint: str = "",
    counters: Optional[Dict[str, Any]] = None,
):
    """fingerprint encodes everything the chunk cursor's meaning depends on
    (chunk size, spp, work total, scene/film identity — see
    render_fingerprint); load_checkpoint refuses a mismatch rather than
    silently misinterpreting the cursor (ADVICE r1). counters is the
    cumulative telemetry snapshot (may be None/{} with telemetry killed)."""
    rgb = np.asarray(state.rgb)
    weight = np.asarray(state.weight)
    splat = np.asarray(state.splat)
    counters_json = json.dumps(counters or {})
    checksum = _content_checksum(
        rgb, weight, splat, next_chunk, rays_so_far, fingerprint,
        counters_json,
    )
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp,
        version=_FORMAT_VERSION,
        rgb=rgb,
        weight=weight,
        splat=splat,
        next_chunk=next_chunk,
        rays=rays_so_far,
        fingerprint=np.array(fingerprint),
        counters=np.array(counters_json),
        checksum=checksum,
    )
    # np.savez appends .npz when missing
    actual_tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"

    fault = CHAOS.checkpoint_fault()
    if fault == "bitflip":
        # seeded single-byte corruption of the payload — the checksum
        # (or the zip parse) must catch it at load time
        with open(actual_tmp, "r+b") as f:
            size = os.path.getsize(actual_tmp)
            off = CHAOS.bitflip_offset(size)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))

    # durability: the data must be on disk BEFORE the rename publishes it
    # — a crash after rename would otherwise leave a zero-length
    # "durable" checkpoint (the ISSUE 5 satellite fix)
    _fsync_path(actual_tmp)

    if fault == "crash":
        # simulated process death between the tmp write and the rename:
        # the tmp file is left behind (like a real crash would) and the
        # previous checkpoint stays the current one
        return

    if fault == "torn":
        # simulated torn write: rotate the good previous file, then
        # publish a TRUNCATED current — load must fall back to .prev.
        # The truncated bytes go through their own tmp + replace (never
        # an in-place truncate of `path`: after the hardlink rotation
        # .prev shares that inode and would be torn too)
        with open(actual_tmp, "rb") as f:
            data = f.read()
        _rotate_prev(path)
        torn_tmp = actual_tmp + ".torn"
        with open(torn_tmp, "wb") as f:
            f.write(data[: max(len(data) // 3, 1)])
        os.replace(torn_tmp, path)
        os.remove(actual_tmp)
        _fsync_dir(path)
        return

    # rotate: keep the previous good checkpoint as the corruption
    # fallback (hardlinked — `path` never goes missing), then atomically
    # publish the new one
    _rotate_prev(path)
    os.replace(actual_tmp, path)
    _fsync_dir(path)
    for obs in _WRITE_OBSERVERS:
        obs(path, int(next_chunk), int(rays_so_far))


def delete_checkpoint(path: str) -> None:
    """Remove a checkpoint and every sibling artifact the writer can
    leave behind (`.prev` rotation, orphaned `.tmp.npz` from a crash
    between write and rename). The render service calls this when a
    cancelled/finished job releases its spool slot — a stale file would
    otherwise resume into the NEXT job that reuses the path (the
    fingerprint guard would refuse, but refusing loudly at submit time
    is worse than never seeing the corpse)."""
    for p in (path, path + ".prev", path + ".tmp", path + ".tmp.npz"):
        try:
            os.remove(p)
        except FileNotFoundError:
            pass


def render_fingerprint(*, chunk: int, spp: int, total: int, scene) -> str:
    """The resume-compatibility key: chunk size depends on TPU_PBRT_CHUNK
    and device count, spp/total on the scene spec, and the film arrays on
    resolution — any of these changing invalidates the chunk cursor."""
    film = scene.film
    return (
        f"chunk={chunk};spp={spp};total={total};tris={scene.n_tris};"
        f"film={film.full_resolution[0]}x{film.full_resolution[1]};"
        f"crop={film.sample_bounds()}"
    )


def _load_one(path: str, fingerprint: str = ""):
    """Load and verify ONE checkpoint file. Raises CorruptCheckpointError
    for anything that smells like torn/flipped bytes, plain ValueError
    for version/fingerprint misconfiguration."""
    import zipfile

    import jax.numpy as jnp

    try:
        with np.load(path) as z:
            version = int(z["version"])
            raw = {
                k: np.asarray(z[k])
                for k in ("rgb", "weight", "splat")
            }
            next_chunk = int(z["next_chunk"])
            rays = int(z["rays"])
            saved_fp = str(z["fingerprint"].item()) if "fingerprint" in z else ""
            counters_json = (
                str(z["counters"].item()) if "counters" in z else "{}"
            )
            saved_crc = int(z["checksum"]) if "checksum" in z else None
    except (OSError, EOFError, KeyError, zipfile.BadZipFile, zlib.error) as e:
        raise CorruptCheckpointError(f"unreadable checkpoint {path}: {e}") from e
    except ValueError as e:
        # np internals raise ValueError on mangled headers/arrays
        raise CorruptCheckpointError(f"unparseable checkpoint {path}: {e}") from e

    if version not in _COMPAT_VERSIONS:
        raise ValueError(f"checkpoint {path}: unsupported version {version}")
    # an empty saved fingerprint (hand-written or pre-metadata file)
    # is accepted; only a conflicting one is an error
    if fingerprint and saved_fp and saved_fp != fingerprint:
        raise ValueError(
            f"checkpoint {path} was written for a different render "
            f"configuration (saved {saved_fp!r}, current {fingerprint!r}); "
            "delete it or restore the original settings to resume"
        )
    if saved_crc is not None:
        crc = _content_checksum(
            raw["rgb"], raw["weight"], raw["splat"], next_chunk, rays,
            saved_fp, counters_json,
        )
        if crc != saved_crc:
            raise CorruptCheckpointError(
                f"checkpoint {path}: content checksum mismatch "
                f"(saved {saved_crc:#010x}, computed {crc:#010x}) — "
                "torn or bit-flipped write"
            )
    counters: Dict[str, Any] = {}
    try:
        counters = json.loads(counters_json) or {}
    except ValueError:
        # a mangled snapshot must not block the film resume —
        # the counters are telemetry, the film is the render
        counters = {}
    # jnp.array(copy=True): the render loop DONATES the film state
    # into its jitted chunk dispatch, so the device arrays must own
    # their buffers — a zero-copy alias of the numpy arrays here
    # (jax on CPU aliases host memory) gets freed/overwritten by the
    # donation and corrupts the heap (flaky resume-test aborts)
    state = FilmState(
        rgb=jnp.array(raw["rgb"], copy=True),
        weight=jnp.array(raw["weight"], copy=True),
        splat=jnp.array(raw["splat"], copy=True),
    )
    return state, next_chunk, rays, counters


def load_checkpoint(path: str, fingerprint: str = ""):
    """-> (FilmState, next_chunk, rays_so_far, counters). Raises
    ValueError when the checkpoint was written under a different render
    configuration. counters is {} for v2 files (pre-telemetry).

    A corrupt/torn CURRENT file falls back to the rotated `<path>.prev`
    (the previous good write) instead of crashing the resume; only when
    both are unusable does the corruption propagate."""
    try:
        return _load_one(path, fingerprint)
    except CorruptCheckpointError as e:
        prev = path + ".prev"
        if os.path.exists(prev):
            from tpu_pbrt.utils.error import Warning as _W

            _W(
                f"checkpoint {path} is corrupt ({e}); falling back to the "
                f"previous good checkpoint {prev}"
            )
            return _load_one(prev, fingerprint)
        raise CorruptCheckpointError(
            f"checkpoint {path} is corrupt and no {prev} fallback exists: {e}"
        ) from e
