"""Checkpoint / resume for in-progress renders.

Capability the reference lacks (SURVEY.md §5.4 flags it as the TPU build's
cheap win): because film accumulation is associative and every chunk is an
idempotent pure function of (scene, work range), a checkpoint is just the
accumulated film pytree plus the chunk cursor. The counter-based RNG keyed
on (pixel, sample, dimension) makes a resumed render bit-identical to an
uninterrupted one. Written atomically (tmp + rename) so a crash mid-write
leaves the previous checkpoint intact."""

from __future__ import annotations

import os

import numpy as np

from tpu_pbrt.core.film import FilmState

_FORMAT_VERSION = 1


def save_checkpoint(path: str, state: FilmState, next_chunk: int, rays_so_far: int):
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp if tmp.endswith(".npz") else tmp,
        version=_FORMAT_VERSION,
        rgb=np.asarray(state.rgb),
        weight=np.asarray(state.weight),
        splat=np.asarray(state.splat),
        next_chunk=next_chunk,
        rays=rays_so_far,
    )
    # np.savez appends .npz when missing
    actual_tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"
    os.replace(actual_tmp, path)


def load_checkpoint(path: str):
    """-> (FilmState, next_chunk, rays_so_far)."""
    import jax.numpy as jnp

    with np.load(path) as z:
        if int(z["version"]) != _FORMAT_VERSION:
            raise ValueError(f"checkpoint {path}: unsupported version {z['version']}")
        state = FilmState(
            rgb=jnp.asarray(z["rgb"]),
            weight=jnp.asarray(z["weight"]),
            splat=jnp.asarray(z["splat"]),
        )
        return state, int(z["next_chunk"]), int(z["rays"])
