"""Checkpoint / resume for in-progress renders.

Capability the reference lacks (SURVEY.md §5.4 flags it as the TPU build's
cheap win): because film accumulation is associative and every chunk is an
idempotent pure function of (scene, work range), a checkpoint is just the
accumulated film pytree plus the chunk cursor. The counter-based RNG keyed
on (pixel, sample, dimension) makes a resumed render bit-identical to an
uninterrupted one. Written atomically (tmp + rename) so a crash mid-write
leaves the previous checkpoint intact.

Format v3 adds the cumulative telemetry-counter snapshot (obs/counters
host dict, JSON-encoded) so a resumed render reports END-TO-END totals —
rays/regenerations/deposits across every process that touched the film,
not just the last one. v2 files (no counter field) still load, with an
empty snapshot."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from tpu_pbrt.core.film import FilmState

_FORMAT_VERSION = 3
#: versions load_checkpoint still understands
_COMPAT_VERSIONS = (2, 3)


def save_checkpoint(
    path: str,
    state: FilmState,
    next_chunk: int,
    rays_so_far: int,
    fingerprint: str = "",
    counters: Optional[Dict[str, Any]] = None,
):
    """fingerprint encodes everything the chunk cursor's meaning depends on
    (chunk size, spp, work total, scene/film identity — see
    render_fingerprint); load_checkpoint refuses a mismatch rather than
    silently misinterpreting the cursor (ADVICE r1). counters is the
    cumulative telemetry snapshot (may be None/{} with telemetry killed)."""
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp if tmp.endswith(".npz") else tmp,
        version=_FORMAT_VERSION,
        rgb=np.asarray(state.rgb),
        weight=np.asarray(state.weight),
        splat=np.asarray(state.splat),
        next_chunk=next_chunk,
        rays=rays_so_far,
        fingerprint=np.array(fingerprint),
        counters=np.array(json.dumps(counters or {})),
    )
    # np.savez appends .npz when missing
    actual_tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"
    os.replace(actual_tmp, path)


def render_fingerprint(*, chunk: int, spp: int, total: int, scene) -> str:
    """The resume-compatibility key: chunk size depends on TPU_PBRT_CHUNK
    and device count, spp/total on the scene spec, and the film arrays on
    resolution — any of these changing invalidates the chunk cursor."""
    film = scene.film
    return (
        f"chunk={chunk};spp={spp};total={total};tris={scene.n_tris};"
        f"film={film.full_resolution[0]}x{film.full_resolution[1]};"
        f"crop={film.sample_bounds()}"
    )


def load_checkpoint(path: str, fingerprint: str = ""):
    """-> (FilmState, next_chunk, rays_so_far, counters). Raises
    ValueError when the checkpoint was written under a different render
    configuration. counters is {} for v2 files (pre-telemetry)."""
    import jax.numpy as jnp

    with np.load(path) as z:
        if int(z["version"]) not in _COMPAT_VERSIONS:
            raise ValueError(f"checkpoint {path}: unsupported version {z['version']}")
        saved_fp = str(z["fingerprint"].item()) if "fingerprint" in z else ""
        # an empty saved fingerprint (hand-written or pre-metadata file)
        # is accepted; only a conflicting one is an error
        if fingerprint and saved_fp and saved_fp != fingerprint:
            raise ValueError(
                f"checkpoint {path} was written for a different render "
                f"configuration (saved {saved_fp!r}, current {fingerprint!r}); "
                "delete it or restore the original settings to resume"
            )
        counters: Dict[str, Any] = {}
        if "counters" in z:
            try:
                counters = json.loads(str(z["counters"].item())) or {}
            except ValueError:
                # a mangled snapshot must not block the film resume —
                # the counters are telemetry, the film is the render
                counters = {}
        # jnp.array(copy=True): the render loop DONATES the film state
        # into its jitted chunk dispatch, so the device arrays must own
        # their buffers — a zero-copy alias of the numpy arrays here
        # (jax on CPU aliases host memory) gets freed/overwritten by the
        # donation and corrupts the heap (flaky resume-test aborts)
        state = FilmState(
            rgb=jnp.array(z["rgb"], copy=True),
            weight=jnp.array(z["weight"], copy=True),
            splat=jnp.array(z["splat"], copy=True),
        )
        return state, int(z["next_chunk"]), int(z["rays"]), counters
