"""Command-line entry point.

Capability match for pbrt-v3 src/main/pbrt.cpp: flag parsing into Options
(--nthreads, --outfile, --quick, --quiet, --cropwindow, ...) plus the
TPU-specific runtime tier (--mesh for the device mesh shape, --spp-chunk
for sample chunking) per SURVEY.md §5.6's two-tier config system.
"""

from __future__ import annotations

import argparse
import sys

from tpu_pbrt.scene.api import Options, render_file
from tpu_pbrt.utils.error import PbrtError


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-pbrt",
        description="TPU-native physically based renderer (pbrt-v3 scene compatible)",
    )
    p.add_argument("scenes", nargs="*", help=".pbrt scene file(s) to render")
    p.add_argument(
        "--serve",
        action="store_true",
        help="run as a persistent render service: scenes given on the "
        "command line are submitted as initial jobs, then a stdin/JSONL "
        "daemon accepts submit/poll/preempt/cancel ops (protocol: "
        "python -m tpu_pbrt.serve --help, README 'Render service')",
    )
    p.add_argument("--outfile", "-o", default="", help="output image filename (overrides scene Film)")
    p.add_argument("--quick", action="store_true", help="reduce samples/resolution for a fast preview")
    p.add_argument("--quiet", action="store_true", help="suppress progress/warning messages")
    p.add_argument("--verbose", "-v", action="store_true", help="verbose logging")
    p.add_argument(
        "--cropwindow",
        nargs=4,
        type=float,
        metavar=("X0", "X1", "Y0", "Y1"),
        help="render only this fraction of the image",
    )
    p.add_argument("--nthreads", type=int, default=0, help="host threads for scene compile (0 = all)")
    p.add_argument("--mesh", default="", help="TPU device mesh shape, e.g. '8' or '2,4' (default: all devices)")
    p.add_argument("--spp-chunk", type=int, default=0, help="samples per render chunk (0 = auto)")
    p.add_argument("--checkpoint", default="", help="checkpoint file: resume from it if present, write to it while rendering")
    p.add_argument("--checkpoint-every", type=int, default=16, help="chunks between checkpoint writes")
    p.add_argument(
        "--multihost",
        action="store_true",
        help="initialize jax.distributed (multi-host pod rendering over DCN; "
        "also auto-enabled by JAX_COORDINATOR_ADDRESS)",
    )
    p.add_argument(
        "--trace",
        default="",
        metavar="OUT.json",
        help="export a Chrome-trace/Perfetto span timeline of the render "
        "phases (also settable via TPU_PBRT_TRACE_PATH); view at "
        "ui.perfetto.dev",
    )
    p.add_argument(
        "--metrics-path",
        default="",
        metavar="OUT.prom",
        help="write a Prometheus text snapshot of the host metrics "
        "registry (phase-time histograms etc.) on exit; also settable "
        "via TPU_PBRT_METRICS_PATH (TPU_PBRT_METRICS=0 disables)",
    )
    p.add_argument(
        "--faults",
        default="",
        metavar="PLAN",
        help="chaos fault-injection plan (tpu_pbrt.chaos grammar, e.g. "
        "'dispatch:poison@chunk=3,ckpt:torn@write=2'); also settable via "
        "TPU_PBRT_FAULTS — see `python -m tpu_pbrt.chaos --list`",
    )
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if not args.scenes and not args.serve:
        print("tpu-pbrt: no scene files (and no --serve)", file=sys.stderr)
        return 1
    opts = Options(
        n_threads=args.nthreads,
        quick_render=args.quick,
        quiet=args.quiet,
        verbose=args.verbose,
        image_file=args.outfile,
        crop_window=tuple(args.cropwindow) if args.cropwindow else None,
        mesh_shape=tuple(int(x) for x in args.mesh.split(",")) if args.mesh else None,
        spp_chunk=args.spp_chunk,
        checkpoint_path=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        multihost=args.multihost,
    )
    from tpu_pbrt.obs.metrics import METRICS
    from tpu_pbrt.obs.trace import TRACE
    from tpu_pbrt.parallel.mesh import maybe_init_distributed

    # chaos BEFORE the telemetry arm-up: a fault plan that targets the
    # very first dispatch (or the trace exporter itself) must already be
    # installed when instrumentation comes online — and both before
    # jax.distributed, whose init is a dispatch-bearing phase
    if args.faults:
        from tpu_pbrt.chaos import CHAOS

        CHAOS.install(args.faults)
    if args.trace:
        TRACE.configure(args.trace)
    if args.metrics_path:
        METRICS.configure(args.metrics_path)
    maybe_init_distributed(opts)
    if args.serve:
        from tpu_pbrt.parallel.mesh import resolve_mesh
        from tpu_pbrt.serve import RenderService
        from tpu_pbrt.serve.__main__ import run_daemon

        service = RenderService(
            mesh=resolve_mesh(opts.mesh_shape), quiet=args.quiet,
        )
        for i, scene in enumerate(args.scenes):
            # one --checkpoint path cannot be shared by several jobs
            # (interleaved writes would clobber each other and the
            # fingerprint guard would fail the second resume): key it
            # per scene when more than one is submitted
            ckpt = args.checkpoint
            if ckpt and len(args.scenes) > 1:
                ckpt = f"{ckpt}.{i}"
            job = service.submit(
                scene, options=opts,
                checkpoint_path=ckpt,
                checkpoint_every=args.checkpoint_every,
                outfile=args.outfile,
            )
            if not args.quiet:
                print(f"tpu-pbrt: submitted {scene} as {job}", file=sys.stderr)
        try:
            return run_daemon(service)
        finally:
            TRACE.maybe_export()
            METRICS.maybe_export()
    try:
        for scene in args.scenes:
            try:
                with TRACE.span("main/render_file", scene=scene):
                    render_file(scene, opts)
            except PbrtError as e:
                print(f"tpu-pbrt: {e}", file=sys.stderr)
                return 1
        return 0
    finally:
        # render() exports incrementally; this export catches the outer
        # main/render_file spans — and runs on the FAILURE path too,
        # where the trace matters most
        TRACE.maybe_export()
        METRICS.maybe_export()


if __name__ == "__main__":
    sys.exit(main())
