"""Built-in test/benchmark scenes.

Stand-ins for the pbrt-v3-scenes distribution (killeroo-simple, cornell
box, ...; SURVEY.md 'Workload configs'), which is not shipped in this
environment: a classic Cornell box in .pbrt text form, and a procedural
killeroo-class mesh (comparable triangle count and shading mix) built
through the pbrt API so the benchmark exercises the same code path as real
scene files — parser -> API state machine -> scene compiler -> wavefront.
"""

from __future__ import annotations

import numpy as np

from tpu_pbrt.scene.api import Options, PbrtAPI, parse_string, pbrt_init
from tpu_pbrt.scene.paramset import ParamSet


def cornell_box_text(res=256, spp=16, integrator="directlighting", maxdepth=5, filename="", sampler="zerotwosequence"):
    """The cornell-box config (SURVEY.md: DirectLightingIntegrator, area
    light + Lambertian). Classic Cornell geometry, meters scaled to [0,1]."""
    return f'''
Integrator "{integrator}" "integer maxdepth" [{maxdepth}]
Sampler "{sampler}" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}] "string filename" ["{filename}"]
LookAt 0.5 0.5 -1.4  0.5 0.5 0  0 1 0
Camera "perspective" "float fov" [40]
WorldBegin
# floor (normal +y)
Material "matte" "rgb Kd" [0.73 0.73 0.73]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [0 0 0  0 0 1  1 0 1  1 0 0]
# ceiling (normal -y)
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [0 1 0  1 1 0  1 1 1  0 1 1]
# back wall (normal -z)
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [0 0 1  0 1 1  1 1 1  1 0 1]
# left wall, red (normal +x)
Material "matte" "rgb Kd" [0.65 0.05 0.05]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [0 0 0  0 1 0  0 1 1  0 0 1]
# right wall, green (normal -x)
Material "matte" "rgb Kd" [0.12 0.45 0.15]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [1 0 0  1 0 1  1 1 1  1 1 0]
# short block
Material "matte" "rgb Kd" [0.73 0.73 0.73]
AttributeBegin
Translate 0.65 0.15 0.3
Rotate -18 0 1 0
Scale 0.15 0.15 0.15
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3  4 6 5 4 7 6  0 4 1 1 4 5  2 6 3 3 6 7  1 5 2 2 5 6  0 3 7 0 7 4]
  "point P" [-1 -1 -1  1 -1 -1  1 -1 1  -1 -1 1  -1 1 -1  1 1 -1  1 1 1  -1 1 1]
AttributeEnd
# tall block
AttributeBegin
Translate 0.3 0.3 0.65
Rotate 15 0 1 0
Scale 0.15 0.3 0.15
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3  4 6 5 4 7 6  0 4 1 1 4 5  2 6 3 3 6 7  1 5 2 2 5 6  0 3 7 0 7 4]
  "point P" [-1 -1 -1  1 -1 -1  1 -1 1  -1 -1 1  -1 1 -1  1 1 -1  1 1 1  -1 1 1]
AttributeEnd
# light (faces -y, just below ceiling)
AttributeBegin
AreaLightSource "diffuse" "rgb L" [15 11 5]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [0.35 0.998 0.35  0.65 0.998 0.35  0.65 0.998 0.65  0.35 0.998 0.65]
AttributeEnd
WorldEnd
'''


def compile_api(api: PbrtAPI):
    """Compile the world accumulated so far (WorldEnd's compile step without
    the render or the state reset) -> (CompiledScene, integrator)."""
    from tpu_pbrt.integrators import make_integrator
    from tpu_pbrt.scene.compiler import compile_scene

    scene = compile_scene(api)
    integ = make_integrator(
        api.render_options.integrator_name, api.render_options.integrator_params, scene, api.options
    )
    return scene, integ


def _crown_envmap_path():
    """Procedural HDR sky (gradient + sun disk) written once under
    refimg/ — the crown-class bench's environment light."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "refimg", "crown_env.pfm")
    if os.path.exists(path):
        return path
    h, w = 64, 128
    th = np.linspace(0, np.pi, h)[:, None]
    ph = np.linspace(0, 2 * np.pi, w)[None, :]
    sky = np.stack(
        [
            0.35 + 0.25 * np.cos(th) * np.ones_like(ph),
            0.45 + 0.30 * np.cos(th) * np.ones_like(ph),
            0.75 + 0.25 * np.cos(th) * np.ones_like(ph),
        ],
        axis=-1,
    ).astype(np.float32)
    # warm sun disk
    sun_dir = (0.45 * np.pi, 0.3 * np.pi)
    d2 = (th - sun_dir[0]) ** 2 + (ph - sun_dir[1]) ** 2
    sun = np.exp(-d2 / 0.004)[..., None] * np.asarray([60.0, 50.0, 35.0])
    img = (sky + sun).astype(np.float32)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    from tpu_pbrt.utils.imageio import write_image

    write_image(path, img)
    return path


def make_crown_like(res=512, spp=64, maxdepth=5, options=None,
                    n_theta=500, n_phi=1000) -> PbrtAPI:
    """crown-class stand-in (BASELINE.md crown rows): >=1M-triangle
    displaced mesh in GLASS, two metal-GGX side pieces, matte ground,
    HDR environment light with 2D-CDF importance sampling — the
    feature set of pbrt-v3-scenes/crown at a procedural geometry
    budget (the PLYs are unavailable in this environment)."""
    api = pbrt_init(options or Options(quiet=True))
    env = _crown_envmap_path()
    parse_string(
        f"""
Integrator "path" "integer maxdepth" [{maxdepth}]
Sampler "zerotwosequence" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}] "string filename" [""]
LookAt 0 1.4 -3.6  0 0.4 0  0 1 0
Camera "perspective" "float fov" [39]
WorldBegin
LightSource "infinite" "string mapname" ["{env}"]
Material "matte" "rgb Kd" [0.45 0.42 0.38]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-8 -0.75 -8  -8 -0.75 8  8 -0.75 8  8 -0.75 -8]
Material "glass" "float eta" [1.5] "rgb Kr" [1 1 1] "rgb Kt" [1 1 1]
""",
        api,
        render=False,
    )
    V, F, N = _displaced_sphere(n_theta, n_phi)
    ps = ParamSet()
    ps.add("integer indices", F.reshape(-1).tolist())
    ps.add("point P", V.reshape(-1).tolist())
    ps.add("normal N", N.reshape(-1).tolist())
    api.shape("trianglemesh", ps)
    # two metal-GGX side pieces (rough + brushed)
    parse_string(
        """
AttributeBegin
Material "metal" "float roughness" [0.05]
Translate -1.7 -0.15 0.4
Scale 0.55 0.55 0.55
""",
        api,
        render=False,
    )
    V2, F2, N2 = _displaced_sphere(140, 280, seed=11)
    ps2 = ParamSet()
    ps2.add("integer indices", F2.reshape(-1).tolist())
    ps2.add("point P", V2.reshape(-1).tolist())
    ps2.add("normal N", N2.reshape(-1).tolist())
    api.shape("trianglemesh", ps2)
    parse_string(
        """
AttributeEnd
AttributeBegin
Material "metal" "float roughness" [0.18] "float uroughness" [0.3] "float vroughness" [0.05]
Translate 1.7 -0.1 0.6
Scale 0.6 0.6 0.6
""",
        api,
        render=False,
    )
    V3, F3, N3 = _displaced_sphere(140, 280, seed=23)
    ps3 = ParamSet()
    ps3.add("integer indices", F3.reshape(-1).tolist())
    ps3.add("point P", V3.reshape(-1).tolist())
    ps3.add("normal N", N3.reshape(-1).tolist())
    api.shape("trianglemesh", ps3)
    parse_string("AttributeEnd\n", api, render=False)
    return api


def make_cornell(res=256, spp=16, integrator="directlighting", maxdepth=5, options=None, sampler="zerotwosequence") -> PbrtAPI:
    """Parse the Cornell box up to (not including) WorldEnd, so the caller
    controls compilation/rendering via compile_api()."""
    api = pbrt_init(options or Options(quiet=True))
    text = cornell_box_text(res, spp, integrator, maxdepth, sampler=sampler)
    text = text.rsplit("WorldEnd", 1)[0]
    parse_string(text, api, render=False)
    return api


def _displaced_sphere(n_theta=180, n_phi=360, seed=7):
    """Procedural blobby mesh, ~(n_theta-1)*n_phi*2 triangles, with shading
    normals — a killeroo-class triangle count with curvature everywhere."""
    rng = np.random.default_rng(seed)
    amps = rng.uniform(0.02, 0.08, size=6)
    freqs = rng.integers(2, 9, size=(6, 2))
    th = np.linspace(1e-3, np.pi - 1e-3, n_theta)
    ph = np.linspace(0.0, 2 * np.pi, n_phi, endpoint=False)
    T, P = np.meshgrid(th, ph, indexing="ij")
    r = np.ones_like(T)
    for a, (f1, f2) in zip(amps, freqs):
        r = r + a * np.sin(f1 * T) * np.cos(f2 * P)
    x = r * np.sin(T) * np.cos(P)
    y = r * np.cos(T)
    z = r * np.sin(T) * np.sin(P)
    V = np.stack([x, y, z], axis=-1).reshape(-1, 3)

    def vid(i, j):
        return i * n_phi + (j % n_phi)

    idx = []
    for i in range(n_theta - 1):
        for j in range(n_phi):
            idx.append((vid(i, j), vid(i + 1, j), vid(i + 1, j + 1)))
            idx.append((vid(i, j), vid(i + 1, j + 1), vid(i, j + 1)))
    F = np.asarray(idx, np.int64)
    # smooth vertex normals
    fn = np.cross(V[F[:, 1]] - V[F[:, 0]], V[F[:, 2]] - V[F[:, 0]])
    N = np.zeros_like(V)
    for k in range(3):
        np.add.at(N, F[:, k], fn)
    N /= np.maximum(np.linalg.norm(N, axis=-1, keepdims=True), 1e-20)
    return V, F, N


def make_killeroo_like(res=512, spp=64, integrator="path", maxdepth=5,
                       n_theta=180, n_phi=360, options=None) -> PbrtAPI:
    """killeroo-simple stand-in: one ~128k-triangle matte mesh over a ground
    plane, one area light + point fill, path integrator (the [D]
    killeroo-simple config: PathIntegrator, matte BSDF, trimesh)."""
    api = pbrt_init(options or Options(quiet=True))
    parse_string(
        f'''
Integrator "{integrator}" "integer maxdepth" [{maxdepth}]
Sampler "zerotwosequence" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [{res}] "integer yresolution" [{res}] "string filename" [""]
LookAt 0 1.2 -3.4  0 0.3 0  0 1 0
Camera "perspective" "float fov" [38]
WorldBegin
AttributeBegin
AreaLightSource "diffuse" "rgb L" [18 17 15]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-1 2.98 -1  1 2.98 -1  1 2.98 1  -1 2.98 1]
AttributeEnd
LightSource "point" "rgb I" [4 4 5] "point from" [2.5 2 -2.5]
Material "matte" "rgb Kd" [0.82 0.78 0.75]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-6 -0.72 -6  -6 -0.72 6  6 -0.72 6  6 -0.72 -6]
Material "matte" "rgb Kd" [0.35 0.30 0.25]
''',
        api,
        render=False,
    )
    V, F, N = _displaced_sphere(n_theta, n_phi)
    ps = ParamSet()
    ps.add("integer indices", F.reshape(-1).tolist())
    ps.add("point P", V.reshape(-1).tolist())
    ps.add("normal N", N.reshape(-1).tolist())
    api.shape("trianglemesh", ps)
    # WorldEnd handled by caller via api.world_end(render=...)
    return api
