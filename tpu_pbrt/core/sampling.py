"""Device-side sampling: counter-based RNG, warps, distributions, MIS.

Capability match for pbrt-v3:
- src/core/rng.h RNG (PCG32): replaced TPU-first by a *stateless*
  counter-based generator — every random number is a pure hash of
  (pixel_index, sample_index, dimension) — so a wavefront of a million rays
  draws its samples with no per-lane mutable state, renders are bit-exact
  reproducible, and checkpoint/resume only needs the sample-range cursor
  (SURVEY.md §5.4).
- src/core/sampling.{h,cpp}: ConcentricSampleDisk, CosineSampleHemisphere,
  UniformSample{Sphere,Hemisphere,Triangle,Cone}, Distribution1D/2D,
  Balance/PowerHeuristic, StratifiedSample via index permutation.
- src/core/lowdiscrepancy.h RadicalInverse / scrambled variants (the
  Halton/(0,2)-sequence samplers in samplers/ build on these).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

ONE_MINUS_EPSILON = np.float32(0.99999994)


# -------------------------------------------------------------------------
# Stateless RNG. pcg-style integer hash over a mixed 32-bit counter.
# -------------------------------------------------------------------------

def _mix(h, v):
    """One round of bob-jenkins-style avalanche combine (uint32)."""
    h = (h ^ v) * jnp.uint32(0x9E3779B1)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    return h ^ (h >> 13)


def hash_u32(*parts) -> jnp.ndarray:
    """Hash any number of integer parts to uint32 (broadcasts)."""
    h = jnp.uint32(0x2545F491)
    for p in parts:
        h = _mix(h, jnp.asarray(p).astype(jnp.uint32))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def uniform_float(*parts) -> jnp.ndarray:
    """U[0,1) from hashed parts; strictly < 1 (pbrt OneMinusEpsilon clamp)."""
    u = hash_u32(*parts)
    f = (u >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
    return jnp.minimum(f, ONE_MINUS_EPSILON)


def uniform_2d(*parts):
    """Two independent U[0,1) streams distinguished by a trailing salt."""
    return uniform_float(*parts, 0x5B3C), uniform_float(*parts, 0xA7E9)


# -------------------------------------------------------------------------
# Warps (pbrt sampling.cpp)
# -------------------------------------------------------------------------

def concentric_sample_disk(u1, u2):
    """Shirley–Chiu concentric map; returns (x, y)."""
    ox = 2.0 * u1 - 1.0
    oy = 2.0 * u2 - 1.0
    degenerate = (ox == 0.0) & (oy == 0.0)
    use_x = jnp.abs(ox) > jnp.abs(oy)
    r = jnp.where(use_x, ox, oy)
    theta = jnp.where(
        use_x,
        (jnp.pi / 4.0) * (oy / jnp.where(ox == 0.0, 1.0, ox)),
        (jnp.pi / 2.0) - (jnp.pi / 4.0) * (ox / jnp.where(oy == 0.0, 1.0, oy)),
    )
    x = jnp.where(degenerate, 0.0, r * jnp.cos(theta))
    y = jnp.where(degenerate, 0.0, r * jnp.sin(theta))
    return x, y


def cosine_sample_hemisphere(u1, u2):
    """Malley's method; returns direction (...,3) in local frame, z up."""
    x, y = concentric_sample_disk(u1, u2)
    z = jnp.sqrt(jnp.maximum(0.0, 1.0 - x * x - y * y))
    return jnp.stack([x, y, z], axis=-1)


def cosine_hemisphere_pdf(cos_theta):
    return cos_theta * (1.0 / jnp.pi)


def uniform_sample_hemisphere(u1, u2):
    z = u1
    r = jnp.sqrt(jnp.maximum(0.0, 1.0 - z * z))
    phi = 2.0 * jnp.pi * u2
    return jnp.stack([r * jnp.cos(phi), r * jnp.sin(phi), z], axis=-1)


UNIFORM_HEMISPHERE_PDF = 1.0 / (2.0 * np.pi)
UNIFORM_SPHERE_PDF = 1.0 / (4.0 * np.pi)


def uniform_sample_sphere(u1, u2):
    z = 1.0 - 2.0 * u1
    r = jnp.sqrt(jnp.maximum(0.0, 1.0 - z * z))
    phi = 2.0 * jnp.pi * u2
    return jnp.stack([r * jnp.cos(phi), r * jnp.sin(phi), z], axis=-1)


def uniform_sample_triangle(u1, u2):
    """Returns barycentrics (b0, b1) (sqrt warp)."""
    su0 = jnp.sqrt(u1)
    return 1.0 - su0, u2 * su0


def uniform_sample_cone(u1, u2, cos_theta_max):
    cos_theta = (1.0 - u1) + u1 * cos_theta_max
    sin_theta = jnp.sqrt(jnp.maximum(0.0, 1.0 - cos_theta * cos_theta))
    phi = 2.0 * jnp.pi * u2
    return jnp.stack(
        [sin_theta * jnp.cos(phi), sin_theta * jnp.sin(phi), cos_theta], axis=-1
    )


def uniform_cone_pdf(cos_theta_max):
    return 1.0 / (2.0 * jnp.pi * jnp.maximum(1.0 - cos_theta_max, 1e-9))


# -------------------------------------------------------------------------
# MIS heuristics (pbrt sampling.h)
# -------------------------------------------------------------------------

def balance_heuristic(nf, f_pdf, ng, g_pdf):
    return (nf * f_pdf) / jnp.maximum(nf * f_pdf + ng * g_pdf, 1e-20)


def power_heuristic(nf, f_pdf, ng, g_pdf):
    f = nf * f_pdf
    g = ng * g_pdf
    return (f * f) / jnp.maximum(f * f + g * g, 1e-20)


# -------------------------------------------------------------------------
# Stratification on a counter-based stream. A wavefront renderer cannot
# carry pbrt's per-pixel sample arrays, so stratified dimensions are formed
# directly from the sample index: for spp = sx*sy, sample s of pixel p gets
# cell perm_p(s) of an sx×sy grid, jittered. perm_p is a per-pixel
# Feistel-style permutation so cross-dimension correlation is broken
# (pbrt's Shuffle equivalent, but stateless).
# -------------------------------------------------------------------------

def permutation_element(i, n, seed):
    """Stateless random permutation of [0,n): Kensler's hash permutation
    (Correlated Multi-Jittered Sampling, also pbrt-v4 PermutationElement) —
    an invertible mix cycle-walked on the next power of two. The unbounded
    do-while becomes 16 fixed masked rounds (miss probability < 2^-16 per
    element; each round rejects with p < 1/2)."""
    n = jnp.asarray(n, jnp.uint32)
    i = jnp.asarray(i, jnp.uint32)
    p = jnp.asarray(seed, jnp.uint32)
    w = n - 1
    w = w | (w >> 1)
    w = w | (w >> 2)
    w = w | (w >> 4)
    w = w | (w >> 8)
    w = w | (w >> 16)

    def mix(i):
        i = i ^ p
        i = i * jnp.uint32(0xE170893D)
        i = i ^ (p >> 16)
        i = i ^ ((i & w) >> 4)
        i = i ^ (p >> 8)
        i = i * jnp.uint32(0x0929EB3F)
        i = i ^ (p >> 23)
        i = i ^ ((i & w) >> 1)
        i = i * (jnp.uint32(1) | (p >> 27))
        i = i * jnp.uint32(0x6935FA69)
        i = i ^ ((i & w) >> 11)
        i = i * jnp.uint32(0x74DCCA23)
        i = i ^ (p >> 2)
        i = i * jnp.uint32(0x9E501CC3)
        i = i ^ ((i & w) >> 2)
        i = i * jnp.uint32(0xC860A3DF)
        i = i & w
        return i ^ (i >> 5)

    y = mix(i)
    for _ in range(15):
        y = jnp.where(y >= n, mix(y), y)
    return (jnp.minimum(y, n - 1) + p) % n


def stratified_1d(sample_index, n_strata, *key_parts):
    """Jittered stratified sample: cell = perm(sample_index), jitter inside."""
    seed = hash_u32(*key_parts, 0x517A)
    cell = permutation_element(sample_index, n_strata, seed).astype(jnp.float32)
    u = uniform_float(*key_parts, 0x11D7)
    return jnp.minimum((cell + u) / n_strata, ONE_MINUS_EPSILON)


def stratified_2d(sample_index, sx, sy, *key_parts):
    """Jittered 2D stratification over an sx×sy grid."""
    seed = hash_u32(*key_parts, 0x2F83)
    cell = permutation_element(sample_index, sx * sy, seed)
    cx = (cell % jnp.uint32(sx)).astype(jnp.float32)
    cy = (cell // jnp.uint32(sx)).astype(jnp.float32)
    u1 = uniform_float(*key_parts, 0x9E01)
    u2 = uniform_float(*key_parts, 0xC6A3)
    return (
        jnp.minimum((cx + u1) / sx, ONE_MINUS_EPSILON),
        jnp.minimum((cy + u2) / sy, ONE_MINUS_EPSILON),
    )


# -------------------------------------------------------------------------
# Radical inverse / scrambling (pbrt lowdiscrepancy.h) — bases 2 and 3
# device-side; arbitrary-base host-side for Halton tables.
# -------------------------------------------------------------------------

def reverse_bits_32(n):
    n = jnp.asarray(n, jnp.uint32)
    n = (n << 16) | (n >> 16)
    n = ((n & jnp.uint32(0x00FF00FF)) << 8) | ((n & jnp.uint32(0xFF00FF00)) >> 8)
    n = ((n & jnp.uint32(0x0F0F0F0F)) << 4) | ((n & jnp.uint32(0xF0F0F0F0)) >> 4)
    n = ((n & jnp.uint32(0x33333333)) << 2) | ((n & jnp.uint32(0xCCCCCCCC)) >> 2)
    n = ((n & jnp.uint32(0x55555555)) << 1) | ((n & jnp.uint32(0xAAAAAAAA)) >> 1)
    return n


def radical_inverse_base2(n, scramble=0):
    """Van der Corput, with optional XOR scramble (uint32)."""
    bits = reverse_bits_32(n) ^ jnp.asarray(scramble, jnp.uint32)
    return jnp.minimum(
        bits.astype(jnp.float32) * jnp.float32(2.3283064365386963e-10), ONE_MINUS_EPSILON
    )


def sobol_2d(n, scramble_x=0, scramble_y=0):
    """First two dimensions of the Sobol' sequence ((0,2)-sequence), as used
    by pbrt's ZeroTwoSequenceSampler (gray-code matrices for dim 2)."""
    x = reverse_bits_32(n) ^ jnp.asarray(scramble_x, jnp.uint32)

    # dimension 2: Sobol' direction numbers for the second dimension
    v = jnp.uint32(1 << 31)
    n = jnp.asarray(n, jnp.uint32)
    y = jnp.zeros_like(n)
    for i in range(32):
        y = jnp.where((n >> i) & 1, y ^ v, y)
        v = v ^ (v >> 1)
    y = y ^ jnp.asarray(scramble_y, jnp.uint32)
    to_f = jnp.float32(2.3283064365386963e-10)
    return (
        jnp.minimum(x.astype(jnp.float32) * to_f, ONE_MINUS_EPSILON),
        jnp.minimum(y.astype(jnp.float32) * to_f, ONE_MINUS_EPSILON),
    )


def _primes(n):
    out, c = [], 2
    while len(out) < n:
        if all(c % p for p in out):
            out.append(c)
        c += 1
    return out


#: prime bases for the Halton sampler's dimensions (primes.cpp equivalent,
#: generated instead of tabulated)
PRIMES = _primes(64)


def radical_inverse_prime(base: int, n, scramble_seed=None):
    """ScrambledRadicalInverse (lowdiscrepancy.h) for a STATIC prime base:
    digit reversal in the given base with an optional per-stream
    multiplicative digit permutation (seeded; digit 0 maps to 0 only under
    the identity — the (a*d + c) mod b permutation keeps sequences
    collision-free per digit while decorrelating streams)."""
    if base == 2:
        scr = 0 if scramble_seed is None else scramble_seed
        return radical_inverse_base2(n, scr)
    n = jnp.asarray(n, jnp.uint32)
    digits = int(np.ceil(32 / np.log2(base)))
    inv_base = np.float32(1.0 / base)
    if scramble_seed is not None:
        seed = jnp.asarray(scramble_seed, jnp.uint32)
        a = (seed % jnp.uint32(base - 1)) + jnp.uint32(1)  # coprime to prime b
        c = (seed >> 8) % jnp.uint32(base)
    out = jnp.zeros(jnp.shape(n), jnp.float32)
    factor = np.float32(1.0)
    for _ in range(digits):
        d = n % jnp.uint32(base)
        if scramble_seed is not None:
            d = (a * d + c) % jnp.uint32(base)
        factor = factor * inv_base
        out = out + d.astype(jnp.float32) * factor
        n = n // jnp.uint32(base)
    return jnp.minimum(out, ONE_MINUS_EPSILON)




# -------------------------------------------------------------------------
# True Sobol' sampler (samplers/sobol.cpp + core/sobolmatrices.cpp
# capability; VERDICT r4 #7). pbrt ships Joe-Kuo generator matrices as a
# 1024-dim table; this build GENERATES its own direction numbers at
# import (first-primitive-polynomial-per-degree over GF(2), hash-seeded
# odd initial m values) and compensates the unoptimized initialization
# with per-dimension fast-Owen scrambling (Laine-Karras) — randomized
# QMC keeps every dimension a base-2 (0,1)-sequence regardless of the
# m choice, which is what the stratification tests pin. The SobolSampler
# global index remap (SobolIntervalToIndex) is reproduced exactly, with
# the van-der-Corput inverse matrices computed from THESE matrices so
# the remap is self-consistent: sample `frame` of pixel (px, py) gets
# the unique global index whose first two dimensions land in that pixel.
# -------------------------------------------------------------------------

N_SOBOL_DIMS = 64
_SOBOL_BITS = 32


def _pascal_matrix():
    """MSB-aligned direction numbers of the Pascal (binomial mod 2)
    matrix — the classical Sobol dimension 2, whose pairing with the
    van der Corput identity is an exact (0,2)-sequence."""
    v = np.zeros(_SOBOL_BITS, np.uint64)
    m = 1
    ms = [1]
    for i in range(1, _SOBOL_BITS):
        m = ms[-1] ^ (ms[-1] << 1)  # x+1 recurrence => Pascal columns
        ms.append(m & ((1 << (i + 1)) - 1))
    for k in range(_SOBOL_BITS):
        v[k] = np.uint64(ms[k]) << np.uint64(31 - k)
    return v


def _lower_tri_scramble(v_cols, seed):
    """Apply a hash-seeded unit-lower-triangular (MSB-first) linear
    scramble L to a 32-column direction matrix: a LINEAR Owen scramble,
    which preserves every (t,m,s)-net property of the sequence while
    decorrelating it from other scrambled copies."""
    rows = np.zeros(_SOBOL_BITS, np.uint64)
    state = np.uint64(seed * 2654435761 % (1 << 32))
    for p in range(_SOBOL_BITS):
        state = np.uint64((int(state) * 6364136223846793005 + 1442695040888963407) % (1 << 64))
        rand_low = int(state >> np.uint64(33)) & ((1 << (31 - p)) - 1)
        rows[p] = (np.uint64(1) << np.uint64(31 - p)) | np.uint64(rand_low)
    out = np.zeros_like(v_cols)
    for k in range(_SOBOL_BITS):
        acc = np.uint64(0)
        col = int(v_cols[k])
        for p in range(_SOBOL_BITS):
            if (col >> (31 - p)) & 1:
                acc ^= rows[p]
        out[k] = acc
    return out


def _build_sobol_matrices():
    """(N_SOBOL_DIMS, 32) uint32 direction-number table, MSB-aligned.

    dims 0/1: van der Corput + Pascal (the exact (0,2) pair the global
    pixel remap inverts). Every later CONSUMED-TOGETHER pair
    (2k, 2k+1) is an independently linear-Owen-scrambled copy of that
    same pair, so each 2D decision drawn through sample_2d keeps the
    exact (0,2)-sequence property while distinct decisions decorrelate
    (pbrt's Joe-Kuo table achieves pairwise quality by optimized
    initialization; the scrambled-copy construction achieves it by
    inheritance)."""
    v = np.zeros((N_SOBOL_DIMS, _SOBOL_BITS), np.uint64)
    for k in range(_SOBOL_BITS):
        v[0, k] = np.uint64(1) << np.uint64(31 - k)
    v[1] = _pascal_matrix()
    for pair in range(1, N_SOBOL_DIMS // 2):
        v[2 * pair] = _lower_tri_scramble(v[0], 2 * pair + 17)
        v[2 * pair + 1] = _lower_tri_scramble(v[1], 2 * pair + 18)
    return v.astype(np.uint32)


_SOBOL_V = _build_sobol_matrices()
_SOBOL_V_I32 = _SOBOL_V.view(np.int32)


def _sobol_dev():
    # numpy -> fresh constant per trace (a cached device array would
    # leak across jit traces)
    return jnp.asarray(_SOBOL_V_I32)


def _gf2_inv(mat):
    """Invert a binary matrix (lists of row bitmasks) over GF(2)."""
    n = len(mat)
    a = list(mat)
    inv = [1 << i for i in range(n)]
    for col in range(n):
        piv = next(r for r in range(col, n) if (a[r] >> col) & 1)
        a[col], a[piv] = a[piv], a[col]
        inv[col], inv[piv] = inv[piv], inv[col]
        for r in range(n):
            if r != col and ((a[r] >> col) & 1):
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


class _RemapTables:
    """Per-resolution (m = log2) tables for SobolIntervalToIndex."""

    cache: dict = {}

    @classmethod
    def get(cls, m):  # jaxlint: disable=JL-SYNC,JL-MUT — host table bake
        if m in cls.cache:
            return cls.cache[m]
        # rows: for each low index bit c < 2m, the (x|y) bits it produces
        # through dims 0/1 (x from dim 0, y from dim 1), packed y-low.
        # Output bit layout: b = (px << m) | py.
        fwd = []
        for c in range(2 * m):
            xv = int(_SOBOL_V[0, c]) >> (32 - m)  # top m bits
            yv = int(_SOBOL_V[1, c]) >> (32 - m)
            fwd.append((xv << m) | yv)
        inv = _gf2_inv(fwd)  # maps target (x|y) bits -> low index bits
        # delta rows: contribution of frame bit c (index bits >= 2m)
        # to the pixel bits
        hi = []
        for c in range(_SOBOL_BITS - 2 * m):
            xv = int(_SOBOL_V[0, c + 2 * m]) >> (32 - m)
            yv = int(_SOBOL_V[1, c + 2 * m]) >> (32 - m)
            hi.append((xv << m) | yv)
        # cache NUMPY tables: device arrays created inside a jit trace
        # would leak tracers into later traces
        tabs = (
            np.asarray(hi, np.int64).astype(np.int32),
            np.asarray(inv, np.int64).astype(np.int32),
        )
        cls.cache[m] = tabs
        return tabs


def sobol_interval_to_index(m: int, frame, px, py):
    """SobolSampler's global index remap (sobolmatrices' VdCSobolMatrices
    path, rebuilt from this module's matrices): the index whose dims 0/1
    land sample `frame` in pixel (px, py) of the 2^m x 2^m grid."""
    if m == 0:
        return frame
    hi, inv = _RemapTables.get(m)
    m2 = 2 * m
    index = frame << m2
    delta = jnp.zeros_like(px)
    for c in range(hi.shape[0]):
        delta = delta ^ jnp.where((frame >> c) & 1 != 0, int(hi[c]), 0)
    b = ((px << m) | py) ^ delta
    for c in range(m2):
        index = index ^ jnp.where((b >> c) & 1 != 0, int(inv[c]), 0)
    return index


def _sobol_raw_bits(index, dim):
    """32-bit Sobol value of `index` (i32, global) in dimension `dim`,
    before scrambling. `dim` may be a static int, a traced scalar, or a
    PER-LANE array (the persistent-wavefront pool mixes path depths in
    one wave, so each lane salts its own dimension)."""
    dim = jnp.asarray(dim, jnp.int32) % N_SOBOL_DIMS
    if dim.ndim == 0:
        row = jax.lax.dynamic_slice(
            _sobol_dev(), (dim, 0), (1, _SOBOL_BITS)
        )[0]
        cols = [row[k] for k in range(_SOBOL_BITS)]
    else:
        rows = jnp.take(_sobol_dev(), dim, axis=0)  # (..., 32)
        cols = [rows[..., k] for k in range(_SOBOL_BITS)]
    out = jnp.zeros_like(index)
    for k in range(_SOBOL_BITS):
        out = out ^ jnp.where((index >> k) & 1 != 0, cols[k], 0)
    return out


def _fast_owen(bits, seed):
    """Laine-Karras hash-based nested scramble on MSB-aligned bits."""
    v = reverse_bits_32(bits)
    v = v + seed.astype(jnp.uint32)
    v = v ^ (v * jnp.uint32(0x6C50B47C))
    v = v ^ (v * jnp.uint32(0xB82F1E52))
    v = v ^ (v * jnp.uint32(0xC7AFE638))
    v = v ^ (v * jnp.uint32(0x8D22F6E6))
    return reverse_bits_32(v)


def sobol_sample(index, dim, scramble_seed=None):
    """U[0,1) Sobol' sample of global `index` in dimension `dim`, with
    per-dimension fast-Owen scrambling when a seed is given."""
    bits = _sobol_raw_bits(index, dim).astype(jnp.uint32)
    if scramble_seed is not None:
        bits = _fast_owen(bits, scramble_seed)
    return jnp.minimum(
        bits.astype(jnp.float32) * jnp.float32(2.3283064365386963e-10),
        jnp.float32(1.0 - 1e-7),
    )


# -------------------------------------------------------------------------
# Sampler plugin dispatch (samplers/{random,stratified,zerotwosequence,
# sobol,halton,maxmin}.cpp; VERDICT r3 #7). The wavefront redesign keeps
# every draw a pure function of (px, py, sample index, dimension salt);
# what the plugin selects is the STRUCTURE of each dimension's stream:
#
# - random:      the counter-hash (rng.h equivalent)
# - stratified:  jittered strata over the spp range, shuffled per
#                (pixel, dimension) so dimensions pair independently
# - 02sequence/lowdiscrepancy/sobol/maxmindist: xor-scrambled (0,2)
#   Sobol' pairs, sample order shuffled per (pixel, dimension) — pbrt's
#   ZeroTwoSequenceSampler decorrelates dimensions exactly this way
#   (shuffled independently per dimension request). maxmindist's bespoke
#   generator matrix is approximated by the (0,2) sequence (documented).
# - halton:      per-pixel scrambled Halton — dimension pairs use prime
#   bases (2,3),(5,7),(11,13),... at the SAME index (jointly LD), with
#   per-pixel digit scrambles replacing pbrt's global pixel stride walk
#   (lowdiscrepancy.cpp: equivalent stratification, no 2^k image tiling).
# -------------------------------------------------------------------------

#: joint 2D bases for halton pair-dimensions — LOW primes only (base-b
#: stratification is only perfect at b^k samples, so large bases stratify
#: poorly at render spp; pair reuse is decorrelated by the per-dimension
#: sample-order shuffle)
_HALTON_PAIRS = [(2, 3), (5, 7), (3, 5), (7, 2), (2, 5), (3, 7)]


def sobol_resolution_log2(res_xy) -> int:
    """The SobolSampler's pixel grid: the smallest 2^m x 2^m grid
    covering the film (sobol.cpp's resolution rounding). Returns m —
    callers hold it (it is static per scene) and pass it into the traced
    film-dimension remap explicitly; module-global trace-time state here
    would silently bake a stale grid into any new jit closure (ADVICE
    r4)."""
    m = 0
    while (1 << m) < max(int(res_xy[0]), int(res_xy[1])):
        m += 1
    return m


def _sobol_dim_draw(px, py, s, salt, which, spp):
    """Decision-dimension Sobol draw: the consumed-together pair
    (2k, 2k+1) for dimension-salt k — an exact (0,2)-sequence by
    construction — indexed by the PER-PIXEL sample rank (shuffled per
    pixel+salt) with per-pixel fast-Owen scrambles. This is the padded
    construction (pbrt-v4's PaddedSobolSampler): a pixel's spp draws
    stratify perfectly in every 2D decision, and pixels decorrelate.
    pbrt-v3's global-index consumption of Joe-Kuo dims needs table
    quality this build's generated matrices cannot promise jointly
    with the pixel dims; only the FILM dims ride the global remap
    (sobol_interval_to_index), which is where the global sequence has
    provable structure here."""
    n_pairs = N_SOBOL_DIMS // 2 - 1
    sp = permutation_element(s, spp, hash_u32(px, py, salt, 0x5A11))
    if isinstance(salt, (int, np.integer)):
        dim = 2 + 2 * (int(salt) % n_pairs) + which
    else:
        dim = 2 + 2 * (jnp.asarray(salt, jnp.int32) % n_pairs) + which
    seed = hash_u32(px, py, salt, 0x193 + 0x7FEB * which).astype(jnp.uint32)
    return sobol_sample(sp, dim, seed)


def sample_1d(kind: str, spp: int, px, py, s, salt):
    """One U[0,1) draw for dimension `salt` under sampler `kind`."""
    if kind == "random" or spp <= 1:
        return uniform_float(px, py, s, salt)
    if kind == "sobol":
        return _sobol_dim_draw(px, py, s, salt, 0, spp)
    if kind == "stratified":
        return stratified_1d(s, spp, px, py, salt)
    if kind == "halton":
        # 1D dimensions use the base-2 sequence with a per-dimension
        # sample-order shuffle + XOR scramble: base 2 stratifies perfectly
        # at the power-of-two spp renders use (a base-b sequence only
        # stratifies at b^k samples, and a digit scramble turns a partial
        # prefix into a random stratum subset), while the shuffle
        # decorrelates dimensions (the padded-sampler construction).
        # Halton's distinguishing JOINT low-discrepancy lives in the
        # prime-base pairs of sample_2d.
        sp = permutation_element(s, spp, hash_u32(px, py, salt, 0x6E5))
        return radical_inverse_base2(sp, hash_u32(px, py, salt, 0x4A1))
    # (0,2)-family: shuffled + scrambled van der Corput
    sp = permutation_element(s, spp, hash_u32(px, py, salt, 0x7F2))
    return radical_inverse_base2(sp, hash_u32(px, py, salt, 0x9D3))


def sample_2d(kind: str, spp: int, px, py, s, salt):
    """A consumed-together 2D pair for dimension pair `salt`."""
    if kind == "random" or spp <= 1:
        return (
            uniform_float(px, py, s, salt),
            uniform_float(px, py, s, salt + 0x151),
        )
    if kind == "sobol":
        return (
            _sobol_dim_draw(px, py, s, salt, 0, spp),
            _sobol_dim_draw(px, py, s, salt, 1, spp),
        )
    if kind == "stratified":
        sx = max(int(np.sqrt(spp)), 1)
        sy = (spp + sx - 1) // sx  # sx*sy >= spp: permutation stays a bijection
        return stratified_2d(s, sx, sy, px, py, salt)
    if kind == "halton":
        # joint (b1, b2) pair at a SHARED shuffled index: the pair keeps
        # its joint 2D low discrepancy (same point set, reordered) and
        # different pair-dimensions decorrelate through the shuffle
        seed = hash_u32(px, py, salt, 0x62B)
        sp = permutation_element(s, spp, hash_u32(px, py, salt, 0xD47))

        def pair(b1, b2):
            return lambda: jnp.stack(
                [
                    radical_inverse_prime(b1, sp, seed),
                    radical_inverse_prime(b2, sp, seed >> 7),
                ],
                axis=0,
            )

        if isinstance(salt, (int, np.integer)):
            uv = pair(*_HALTON_PAIRS[salt % len(_HALTON_PAIRS)])()
        else:
            import jax as _jax

            uv = _jax.lax.switch(
                jnp.asarray(salt % len(_HALTON_PAIRS), jnp.int32),
                [pair(b1, b2) for b1, b2 in _HALTON_PAIRS],
            )
        return uv[0], uv[1]
    sp = permutation_element(s, spp, hash_u32(px, py, salt, 0x3C5))
    return sobol_2d(
        sp, hash_u32(px, py, salt, 0x8E7), hash_u32(px, py, salt, 0xB19)
    )


def normalize_sampler_name(name: str) -> str:
    """Scene-file sampler name -> dispatch kind (api.cpp MakeSampler)."""
    n = (name or "").lower()
    if n in ("random",):
        return "random"
    if n in ("stratified",):
        return "stratified"
    if n in ("halton",):
        return "halton"
    if n in ("sobol",):
        return "sobol"
    if n in ("lowdiscrepancy", "02sequence", "zerotwosequence"):
        return "02"
    from tpu_pbrt.utils.error import Warning as _W

    if n == "maxmindist":
        _W(
            'sampler "maxmindist" has no bespoke generator matrix in this '
            "build; SUBSTITUTING the (0,2)-sequence sampler"
        )
        return "02"
    _W(f'sampler "{name}" unknown; using the (0,2)-sequence sampler')
    return "02"


# -------------------------------------------------------------------------
# Distribution1D / Distribution2D (pbrt sampling.h) — piecewise-constant
# CDF importance sampling. Build host-side (numpy), sample device-side.
# -------------------------------------------------------------------------

class Distribution1D(NamedTuple):
    """func: (N,), cdf: (N+1,), integral: scalar — all device arrays."""

    func: jnp.ndarray
    cdf: jnp.ndarray
    func_int: jnp.ndarray

    @staticmethod
    def build(f) -> "Distribution1D":
        f = np.asarray(f, dtype=np.float64)
        n = len(f)
        cdf = np.zeros(n + 1)
        cdf[1:] = np.cumsum(f) / n
        func_int = cdf[-1]
        if func_int == 0:
            cdf[1:] = np.arange(1, n + 1) / n
        else:
            cdf[1:] /= func_int
        return Distribution1D(
            jnp.asarray(f, jnp.float32), jnp.asarray(cdf, jnp.float32), jnp.float32(func_int)
        )

    @property
    def count(self):
        return self.func.shape[0]

    def sample_continuous(self, u):
        """Returns (x in [0,1), pdf, offset)."""
        offset = jnp.clip(
            jnp.searchsorted(self.cdf, u, side="right") - 1, 0, self.count - 1
        )
        c0 = self.cdf[offset]
        c1 = self.cdf[offset + 1]
        du = jnp.where(c1 > c0, (u - c0) / jnp.maximum(c1 - c0, 1e-20), 0.0)
        pdf = jnp.where(
            self.func_int > 0, self.func[offset] / jnp.maximum(self.func_int, 1e-20), 0.0
        )
        x = (offset.astype(jnp.float32) + du) / self.count
        return x, pdf, offset

    def sample_discrete(self, u):
        """Returns (offset, pmf)."""
        offset = jnp.clip(
            jnp.searchsorted(self.cdf, u, side="right") - 1, 0, self.count - 1
        )
        pmf = jnp.where(
            self.func_int > 0,
            self.func[offset] / jnp.maximum(self.func_int * self.count, 1e-20),
            0.0,
        )
        return offset, pmf

    def discrete_pdf(self, index):
        return self.func[index] / jnp.maximum(self.func_int * self.count, 1e-20)


class Distribution2D(NamedTuple):
    """Conditional rows + marginal over rows, flattened to fixed arrays.

    cond_func/cond_cdf: (H, W)/(H, W+1); marg over row integrals."""

    cond_func: jnp.ndarray
    cond_cdf: jnp.ndarray
    cond_int: jnp.ndarray  # (H,)
    marg_func: jnp.ndarray  # (H,)
    marg_cdf: jnp.ndarray  # (H+1,)
    marg_int: jnp.ndarray  # scalar

    @staticmethod
    def build(f) -> "Distribution2D":
        f = np.asarray(f, dtype=np.float64)
        h, w = f.shape
        cond_cdf = np.zeros((h, w + 1))
        cond_cdf[:, 1:] = np.cumsum(f, axis=1) / w
        cond_int = cond_cdf[:, -1].copy()
        safe = np.where(cond_int == 0, 1.0, cond_int)
        cond_cdf[:, 1:] = np.where(
            cond_int[:, None] == 0,
            np.arange(1, w + 1)[None, :] / w,
            cond_cdf[:, 1:] / safe[:, None],
        )
        marg = Distribution1D.build(cond_int)
        return Distribution2D(
            jnp.asarray(f, jnp.float32),
            jnp.asarray(cond_cdf, jnp.float32),
            jnp.asarray(cond_int, jnp.float32),
            marg.func,
            marg.cdf,
            marg.func_int,
        )

    def sample_continuous(self, u1, u2):
        """Returns ((u, v), pdf)."""
        h, w = self.cond_func.shape
        # marginal (rows)
        row = jnp.clip(jnp.searchsorted(self.marg_cdf, u2, side="right") - 1, 0, h - 1)
        mc0 = self.marg_cdf[row]
        mc1 = self.marg_cdf[row + 1]
        dv = jnp.where(mc1 > mc0, (u2 - mc0) / jnp.maximum(mc1 - mc0, 1e-20), 0.0)
        pdf_v = jnp.where(
            self.marg_int > 0, self.marg_func[row] / jnp.maximum(self.marg_int, 1e-20), 0.0
        )
        v = (row.astype(jnp.float32) + dv) / h
        # conditional (cols within row) — count-based search so it batches
        cdf_row = self.cond_cdf[row]  # (..., W+1)
        u1e = jnp.asarray(u1)[..., None]
        col = jnp.clip(jnp.sum(cdf_row <= u1e, axis=-1) - 1, 0, w - 1)
        cc0 = jnp.take_along_axis(cdf_row, col[..., None], axis=-1)[..., 0]
        cc1 = jnp.take_along_axis(cdf_row, col[..., None] + 1, axis=-1)[..., 0]
        du = jnp.where(cc1 > cc0, (u1 - cc0) / jnp.maximum(cc1 - cc0, 1e-20), 0.0)
        ci = self.cond_int[row]
        fval = jnp.take_along_axis(self.cond_func[row], col[..., None], axis=-1)[..., 0]
        pdf_u = jnp.where(ci > 0, fval / jnp.maximum(ci, 1e-20), 0.0)
        uu = (col.astype(jnp.float32) + du) / w
        return (uu, v), pdf_u * pdf_v

    def pdf(self, u, v):
        """Pdf of (u,v) in [0,1)^2 (pbrt Distribution2D::Pdf)."""
        h, w = self.cond_func.shape
        iu = jnp.clip((u * w).astype(jnp.int32), 0, w - 1)
        iv = jnp.clip((v * h).astype(jnp.int32), 0, h - 1)
        return self.cond_func[iv, iu] / jnp.maximum(self.marg_int, 1e-20)
