"""Device texture evaluation (VERDICT r3 #6).

Capability match for pbrt-v3 src/core/texture.{h,cpp} (Texture::Evaluate,
the 2D/3D mappings, Noise/FBm/Turbulence), src/core/mipmap.h (MIPMap
pyramid + trilinear lookup), and src/textures/* evaluation semantics
(imagemap, checkerboard, dots, scale, mix, bilerp, uv, fbm, wrinkled,
windy, marble).

TPU-first design: textures are COMPILED, not interpreted. The scene
compiler hands the (small, static) set of non-constant texture nodes to
`build_texture_table`, which
- packs every imagemap's full mip pyramid into ONE flat (T, 3) f32 atlas
  buffer (level offsets/extents are Python constants baked into each
  texture's generated closure — no metadata table, no indirection), and
- generates one jitted evaluator closure per texture node tree by
  recursive composition; per-lane texture selection is a masked sum over
  the (few) per-scene textures rather than lax.switch, because the ids
  are per-lane, not scalar.

Lookups use bilinear filtering at an explicit mip level (default 0 —
pbrt's no-ray-differentials path collapses to the finest level the same
way). When the caller supplies the (..., 4) [dudx, dvdx, dudy, dvdy]
uv-footprint (camera hits through ray differentials), imagemaps run the
EWA-class anisotropic filter: mip level from the minor ellipse axis,
EWA_TAPS Gaussian-weighted trilinear taps along the major axis,
eccentricity clamped to MAX_ANISO (mipmap.h MIPMap::EWA semantics,
realized as fixed-tap footprint assembly — a TPU-static formulation of
the same ellipse integral; the data-dependent ellipse-bbox loop of the
reference would defeat XLA). A legacy scalar lod takes one trilinear
tap. Gamma decode (sRGB->linear) happens once at load, as in
imagemap.cpp's ConvertIn(gamma).

The procedural noise is a hash-based lattice gradient noise with pbrt's
quintic smoothstep weights and FBm/Turbulence octave accumulation
(omega gain, 1.99 lacunarity). pbrt seeds gradients from a fixed
permutation table; ours come from an integer hash — statistically
equivalent, not bit-identical (documented deviation).
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax.numpy as jnp
import numpy as np

#: EWA eccentricity clamp (pbrt ImageTexture maxanisotropy default)
MAX_ANISO = 8.0
#: fixed Gaussian tap count along the major axis (static cost per lane;
#: 4 matches common hardware aniso quality at 8:1 eccentricity)
EWA_TAPS = 4

# -------------------------------------------------------------------------
# noise (texture.cpp Noise/FBm/Turbulence)
# -------------------------------------------------------------------------


def _hash3(xi, yi, zi):
    h = (
        xi.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
        ^ yi.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
        ^ zi.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    )
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x2C1B3C6D)
    h = h ^ (h >> 12)
    return h


def _grad(xi, yi, zi, dx, dy, dz):
    """Gradient dot product from one of 16 lattice directions (the
    classic Perlin gradient set, selected by hash instead of pbrt's
    permutation table)."""
    h = _hash3(xi, yi, zi) & 15
    u = jnp.where(h < 8, dx, dy)
    v = jnp.where(h < 4, dy, jnp.where((h == 12) | (h == 14), dx, dz))
    return jnp.where(h & 1 == 0, u, -u) + jnp.where(h & 2 == 0, v, -v)


def noise3(p):
    """Perlin-style gradient noise in [-1, 1], p: (..., 3)."""
    pi = jnp.floor(p)
    d = p - pi
    xi = pi[..., 0].astype(jnp.int32)
    yi = pi[..., 1].astype(jnp.int32)
    zi = pi[..., 2].astype(jnp.int32)
    dx, dy, dz = d[..., 0], d[..., 1], d[..., 2]
    # quintic smoothstep (NoiseWeight in texture.cpp)
    w = d * d * d * (d * (d * 6.0 - 15.0) + 10.0)
    wx, wy, wz = w[..., 0], w[..., 1], w[..., 2]

    def g(ox, oy, oz):
        return _grad(xi + ox, yi + oy, zi + oz, dx - ox, dy - oy, dz - oz)

    def lerp(t, a, b):
        return a + t * (b - a)

    x00 = lerp(wx, g(0, 0, 0), g(1, 0, 0))
    x10 = lerp(wx, g(0, 1, 0), g(1, 1, 0))
    x01 = lerp(wx, g(0, 0, 1), g(1, 0, 1))
    x11 = lerp(wx, g(0, 1, 1), g(1, 1, 1))
    y0 = lerp(wy, x00, x10)
    y1 = lerp(wy, x01, x11)
    return lerp(wz, y0, y1)


def fbm(p, omega: float, octaves: int):
    """texture.cpp FBm (no ray-differential octave clamp: explicit count)."""
    out = 0.0
    lam, o = 1.0, 1.0
    for _ in range(max(int(octaves), 1)):
        out = out + o * noise3(p * lam)
        lam *= 1.99
        o *= omega
    return out


def turbulence(p, omega: float, octaves: int):
    out = 0.0
    lam, o = 1.0, 1.0
    for _ in range(max(int(octaves), 1)):
        out = out + o * jnp.abs(noise3(p * lam))
        lam *= 1.99
        o *= omega
    return out


# -------------------------------------------------------------------------
# mappings (texture.cpp TextureMapping2D/3D)
# -------------------------------------------------------------------------


def _map2d(m: dict, uv, p):
    kind = m.get("type", "uv")
    if kind == "uv":
        u = m["su"] * uv[..., 0] + m["du"]
        v = m["sv"] * uv[..., 1] + m["dv"]
        return u, v
    if kind == "planar":
        v1 = jnp.asarray(m["v1"], jnp.float32)
        v2 = jnp.asarray(m["v2"], jnp.float32)
        return (
            jnp.sum(p * v1, -1) + m["du"],
            jnp.sum(p * v2, -1) + m["dv"],
        )
    w2t = np.asarray(m["world_to_texture"].m, np.float32)
    pt = p @ w2t[:3, :3].T + w2t[:3, 3]
    if kind == "spherical":
        r = jnp.linalg.norm(pt, axis=-1)
        theta = jnp.arccos(jnp.clip(pt[..., 2] / jnp.maximum(r, 1e-20), -1, 1))
        phi = jnp.arctan2(pt[..., 1], pt[..., 0])
        phi = jnp.where(phi < 0, phi + 2 * np.pi, phi)
        return theta / np.pi, phi / (2 * np.pi)
    # cylindrical
    phi = jnp.arctan2(pt[..., 1], pt[..., 0])
    phi = jnp.where(phi < 0, phi + 2 * np.pi, phi)
    return phi / (2 * np.pi), pt[..., 2]


def _map3d(m: dict, p):
    w2t = np.asarray(m["world_to_texture"].m, np.float32)
    return p @ w2t[:3, :3].T + w2t[:3, 3]


# -------------------------------------------------------------------------
# imagemap atlas
# -------------------------------------------------------------------------


def _srgb_to_linear(x):
    return np.where(x <= 0.04045, x / 12.92, ((x + 0.055) / 1.055) ** 2.4)


def _build_pyramid(img: np.ndarray) -> List[np.ndarray]:
    """Box-filtered mip chain (mipmap.h resampleWeights simplified to the
    power-of-two box reduction; non-pow2 levels use edge-clamped halving)."""
    levels = [img.astype(np.float32)]
    cur = levels[0]
    while max(cur.shape[0], cur.shape[1]) > 1:
        h, w = cur.shape[:2]
        h2, w2 = max(h // 2, 1), max(w // 2, 1)
        pad = cur[: h2 * 2, : w2 * 2]
        if pad.shape[0] < 2 * h2 or pad.shape[1] < 2 * w2:
            pad = np.pad(
                cur,
                ((0, 2 * h2 - h), (0, 2 * w2 - w), (0, 0)),
                mode="edge",
            )[: 2 * h2, : 2 * w2]
        nxt = 0.25 * (
            pad[0::2, 0::2] + pad[1::2, 0::2] + pad[0::2, 1::2] + pad[1::2, 1::2]
        )
        levels.append(nxt.astype(np.float32))
        cur = nxt
    return levels


def _bilinear(atlas, off: int, w: int, h: int, u, v, wrap: str):
    """One bilinear tap from a level stored row-major at atlas[off:off+w*h]."""
    x = u * w - 0.5
    y = v * h - 0.5
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    fx = x - x0
    fy = y - y0

    def wrapc(i, n):
        i = i.astype(jnp.int32)
        if wrap == "repeat":
            return jnp.mod(i, n)
        return jnp.clip(i, 0, n - 1)

    inside = jnp.ones(u.shape, bool)
    if wrap == "black":
        inside = (u >= 0.0) & (u < 1.0) & (v >= 0.0) & (v < 1.0)

    def tap(ix, iy):
        idx = off + wrapc(iy, h) * w + wrapc(ix, w)
        return atlas[idx]

    c = (
        tap(x0, y0) * ((1 - fx) * (1 - fy))[..., None]
        + tap(x0 + 1, y0) * (fx * (1 - fy))[..., None]
        + tap(x0, y0 + 1) * ((1 - fx) * fy)[..., None]
        + tap(x0 + 1, y0 + 1) * (fx * fy)[..., None]
    )
    return jnp.where(inside[..., None], c, 0.0)


# -------------------------------------------------------------------------
# node compilation
# -------------------------------------------------------------------------


class _AtlasBuilder:
    def __init__(self):
        self.chunks: List[np.ndarray] = []
        self.size = 0
        self._cache = {}

    def add_image(self, path: str, gamma: bool, scale: float):
        """Returns [(offset, w, h)] per mip level."""
        key = (path, bool(gamma), float(scale))
        if key in self._cache:
            return self._cache[key]
        from tpu_pbrt.utils.imageio import read_image

        img = np.asarray(read_image(path), np.float32)
        if img.ndim == 2:
            img = img[..., None]
        if img.shape[-1] == 1:
            img = np.repeat(img, 3, -1)
        img = img[..., :3]
        if gamma:
            img = _srgb_to_linear(img)
        img = img * scale
        levels = []
        for lv in _build_pyramid(img):
            h, w = lv.shape[:2]
            levels.append((self.size, w, h))
            self.chunks.append(lv.reshape(-1, 3))
            self.size += w * h
        self._cache[key] = levels
        return levels

    def finish(self) -> np.ndarray:
        if not self.chunks:
            return np.zeros((1, 3), np.float32)
        return np.concatenate(self.chunks, 0)


def _compile_node(node, atlas: _AtlasBuilder) -> Callable:
    """node -> fn(atlas_buf, uv, p, lod) -> (..., 3). Constants and float
    scalars broadcast; recursion composes sub-textures (scale/mix/checker
    arms are themselves texture nodes)."""
    if node is None:
        return lambda a, uv, p, lod: jnp.zeros(uv.shape[:-1] + (3,), jnp.float32)
    if isinstance(node, (int, float)):
        c = float(node)
        return lambda a, uv, p, lod: jnp.full(uv.shape[:-1] + (3,), c, jnp.float32)
    if isinstance(node, np.ndarray) or (
        isinstance(node, (list, tuple)) and node and isinstance(node[0], (int, float))
    ):
        c = np.asarray(node, np.float32).reshape(-1)
        c3 = np.full(3, c[0]) if c.size == 1 else c[:3]
        return lambda a, uv, p, lod: jnp.broadcast_to(
            jnp.asarray(c3), uv.shape[:-1] + (3,)
        )
    kind = node[0]
    if kind in ("const", "constf"):
        return _compile_node(node[1], atlas)
    if kind == "scale":
        f1 = _compile_node(node[1], atlas)
        f2 = _compile_node(node[2], atlas)
        return lambda a, uv, p, lod: f1(a, uv, p, lod) * f2(a, uv, p, lod)
    if kind == "mix":
        f1 = _compile_node(node[1], atlas)
        f2 = _compile_node(node[2], atlas)
        fa = _compile_node(node[3], atlas)
        return lambda a, uv, p, lod: (
            lambda t: (1.0 - t) * f1(a, uv, p, lod) + t * f2(a, uv, p, lod)
        )(fa(a, uv, p, lod))
    if kind == "bilerp":
        d = node[1]
        f00 = _compile_node(d["v00"], atlas)
        f01 = _compile_node(d["v01"], atlas)
        f10 = _compile_node(d["v10"], atlas)
        f11 = _compile_node(d["v11"], atlas)
        m = d["mapping"]

        def ev_bilerp(a, uv, p, lod):
            u, v = _map2d(m, uv, p)
            return (
                (1 - u)[..., None] * (1 - v)[..., None] * f00(a, uv, p, lod)
                + (1 - u)[..., None] * v[..., None] * f01(a, uv, p, lod)
                + u[..., None] * (1 - v)[..., None] * f10(a, uv, p, lod)
                + u[..., None] * v[..., None] * f11(a, uv, p, lod)
            )

        return ev_bilerp
    if kind == "imagemap":
        d = node[1]
        levels = atlas.add_image(d["filename"], d["gamma"], d["scale"])
        m = d["mapping"]
        wrap = d.get("wrap", "repeat")
        n_levels = len(levels)

        def trilerp(a, u, v, lodc):
            """One trilinear tap: bilinear at floor/ceil level, lerped."""
            l0 = jnp.floor(lodc).astype(jnp.int32)
            fl = lodc - l0.astype(jnp.float32)
            out0 = jnp.zeros(u.shape + (3,), jnp.float32)
            out1 = jnp.zeros(u.shape + (3,), jnp.float32)
            for li, (off, w, h) in enumerate(levels):
                tapv = _bilinear(a, off, w, h, u, v, wrap)
                out0 = jnp.where((l0 == li)[..., None], tapv, out0)
                out1 = jnp.where(
                    (jnp.minimum(l0 + 1, n_levels - 1) == li)[..., None],
                    tapv, out1,
                )
            return out0 * (1.0 - fl)[..., None] + out1 * fl[..., None]

        def ev_image(a, uv, p, lod):
            u, v = _map2d(m, uv, p)
            if lod is None:
                off, w, h = levels[0]
                return _bilinear(a, off, w, h, u, v, wrap)
            # `lod` is the (..., 4) [dudx, dvdx, dudy, dvdy] SURFACE-uv
            # footprint; the uv mapping's su/sv scale it into texture
            # space exactly as UVMapping2D::Map scales dstdx/dstdy
            # before MIPMap::Lookup (other mappings approximate with
            # scale 1). A legacy scalar `lod` (isotropic width) still
            # takes the single-tap trilinear path.
            if lod.ndim == u.ndim + 1:
                # ---- EWA-class anisotropic filtering (mipmap.h EWA,
                # realized as footprint assembly): pick the mip level
                # from the MINOR ellipse axis and place EWA_TAPS
                # Gaussian-weighted trilinear taps along the MAJOR
                # axis. Fixed tap count keeps the cost static (TPU:
                # no data-dependent ellipse-bbox loop); eccentricity
                # clamped to MAX_ANISO exactly as pbrt widens the
                # minor axis.
                if m.get("type", "uv") == "uv":
                    su = abs(float(m.get("su", 1.0)))
                    sv = abs(float(m.get("sv", 1.0)))
                else:
                    su = sv = 1.0
                dux, dvx = lod[..., 0] * su, lod[..., 1] * sv
                duy, dvy = lod[..., 2] * su, lod[..., 3] * sv
                l2x = dux * dux + dvx * dvx
                l2y = duy * duy + dvy * dvy
                x_major = l2x >= l2y
                major = jnp.sqrt(jnp.maximum(jnp.maximum(l2x, l2y), 1e-16))
                minor = jnp.sqrt(jnp.maximum(jnp.minimum(l2x, l2y), 0.0))
                minor = jnp.maximum(minor, major / MAX_ANISO)
                mu = jnp.where(x_major, dux, duy)
                mv = jnp.where(x_major, dvx, dvy)
                lodc = jnp.clip(
                    (n_levels - 1)
                    + jnp.log2(jnp.maximum(minor, 1e-8)),
                    0.0, n_levels - 1.0,
                )
                acc = jnp.zeros(u.shape + (3,), jnp.float32)
                wsum = 0.0
                for t in range(EWA_TAPS):
                    f = (t + 0.5) / EWA_TAPS - 0.5  # (-0.5, 0.5)
                    # pbrt's EWA Gaussian falloff (alpha = 2) over the
                    # normalized ellipse coordinate r = 2f
                    wgt = float(np.exp(-2.0 * (2.0 * f) ** 2))
                    acc = acc + wgt * trilerp(
                        a, u + f * mu, v + f * mv, lodc
                    )
                    wsum += wgt
                return acc / wsum
            map_scale = max(
                abs(float(m.get("su", 1.0))), abs(float(m.get("sv", 1.0)))
            ) if m.get("type", "uv") == "uv" else 1.0
            lvl = (n_levels - 1) + jnp.log2(
                jnp.maximum(lod * map_scale, 1e-8)
            )
            return trilerp(a, u, v, jnp.clip(lvl, 0.0, n_levels - 1.0))

        return ev_image
    if kind == "uv":
        m = node[1]["mapping"]

        def ev_uv(a, uv, p, lod):
            u, v = _map2d(m, uv, p)
            return jnp.stack([u - jnp.floor(u), v - jnp.floor(v), jnp.zeros_like(u)], -1)

        return ev_uv
    if kind == "checkerboard":
        d = node[1]
        f1 = _compile_node(d["tex1"], atlas)
        f2 = _compile_node(d["tex2"], atlas)
        m = d["mapping"]
        if d["dim"] == 2:

            def ev_check(a, uv, p, lod):
                u, v = _map2d(m, uv, p)
                sel = (jnp.floor(u) + jnp.floor(v)).astype(jnp.int32) % 2 == 0
                return jnp.where(sel[..., None], f1(a, uv, p, lod), f2(a, uv, p, lod))

            return ev_check

        def ev_check3(a, uv, p, lod):
            pt = _map3d(m, p)
            s = jnp.sum(jnp.floor(pt).astype(jnp.int32), -1)
            return jnp.where((s % 2 == 0)[..., None], f1(a, uv, p, lod), f2(a, uv, p, lod))

        return ev_check3
    if kind == "dots":
        d = node[1]
        fi = _compile_node(d["inside"], atlas)
        fo = _compile_node(d["outside"], atlas)
        m = d["mapping"]

        def ev_dots(a, uv, p, lod):
            u, v = _map2d(m, uv, p)
            sc, tc = jnp.floor(u + 0.5), jnp.floor(v + 0.5)
            cell = jnp.stack([sc, tc, jnp.zeros_like(sc)], -1)
            has_dot = noise3(cell + 0.5) > 0.0
            rad = 0.35
            maxshift = 0.5 - rad
            cx = sc + maxshift * noise3(cell * 1.5 + 10.0)
            cy = tc + maxshift * noise3(cell * 2.5 + 20.0)
            d2 = (u - cx) ** 2 + (v - cy) ** 2
            sel = has_dot & (d2 < rad * rad)
            return jnp.where(sel[..., None], fi(a, uv, p, lod), fo(a, uv, p, lod))

        return ev_dots
    if kind in ("fbm", "wrinkled", "windy", "marble"):
        d = node[1]
        m = d["mapping"]
        octaves = int(d.get("octaves", 8))
        omega = float(d.get("roughness", 0.5))
        if kind == "fbm":

            def ev_noise(a, uv, p, lod):
                return fbm(_map3d(m, p), omega, octaves)[..., None] * jnp.ones(3)

            return ev_noise
        if kind == "wrinkled":

            def ev_wri(a, uv, p, lod):
                return turbulence(_map3d(m, p), omega, octaves)[..., None] * jnp.ones(3)

            return ev_wri
        if kind == "windy":

            def ev_windy(a, uv, p, lod):
                pt = _map3d(m, p)
                strength = jnp.abs(fbm(0.1 * pt, 0.5, 3))
                height = fbm(pt, 0.5, 6)
                return (strength * jnp.abs(height))[..., None] * jnp.ones(3)

            return ev_windy
        scale = float(d.get("scale", 1.0))
        variation = float(d.get("variation", 0.2))
        # marble.cpp: sin curve displaced by turbulence, spline through
        # the marble color ramp (colors approximated by the ramp below)
        _MARBLE = np.asarray(
            [
                [0.58, 0.58, 0.6],
                [0.58, 0.58, 0.6],
                [0.58, 0.58, 0.6],
                [0.5, 0.5, 0.5],
                [0.6, 0.59, 0.58],
                [0.58, 0.58, 0.6],
                [0.58, 0.58, 0.6],
                [0.2, 0.2, 0.33],
                [0.58, 0.58, 0.6],
            ],
            np.float32,
        )

        def ev_marble(a, uv, p, lod):
            pt = _map3d(m, p) * scale
            marble = pt[..., 1] + variation * fbm(pt, omega, octaves)
            t = 0.5 + 0.5 * jnp.sin(marble)
            nseg = _MARBLE.shape[0] - 3
            fi = jnp.clip(t * nseg, 0.0, nseg - 1e-4)
            i0 = fi.astype(jnp.int32)
            ft = (fi - i0)[..., None]
            ramp = jnp.asarray(_MARBLE)
            c0 = ramp[i0 + 1]
            c1 = ramp[i0 + 2]
            return (1 - ft) * c0 + ft * c1

        return ev_marble
    # unknown node: mid gray (textures.py already warned at parse)
    return lambda a, uv, p, lod: jnp.full(uv.shape[:-1] + (3,), 0.5, jnp.float32)


def build_texture_table(nodes: List[Any]) -> Tuple[np.ndarray, Callable]:
    """deferred texture nodes -> (atlas ndarray, eval fn).

    eval(atlas_buf, tid (R,), uv (R,2), p (R,3), lod=None) -> (R,3);
    tid < 0 lanes return 0 (callers keep the constant-folded parameter).
    Selection is a masked sum over the per-scene texture set."""
    atlas = _AtlasBuilder()
    fns = [_compile_node(n, atlas) for n in nodes]
    buf = atlas.finish()

    def evaluate(atlas_buf, tid, uv, p, lod=None):
        out = jnp.zeros(uv.shape[:-1] + (3,), jnp.float32)
        for i, fn in enumerate(fns):
            val = fn(atlas_buf, uv, p, lod)
            if val.ndim == out.ndim - 1:
                val = val[..., None] * jnp.ones((3,), jnp.float32)
            out = jnp.where((tid == i)[..., None], val, out)
        return out

    return buf, evaluate
