"""Device-side light sampling: the NEE half of the light transport.

Capability match for pbrt-v3:
- src/lights/point.cpp, spot.cpp, distant.cpp, diffuse.cpp (area),
  infinite.cpp — each light type's Sample_Li / Pdf_Li / Le, lowered to a
  tagged-union SoA row per light (area lights are one row per emissive
  triangle, mirroring pbrt's one-DiffuseAreaLight-per-Triangle).
- src/core/integrator.cpp UniformSampleOneLight light selection (uniform or
  power-weighted via lightdistrib.cpp PowerLightDistribution).
- src/core/light.h VisibilityTester: the caller traces the returned shadow
  ray with bvh_intersect_p.

All functions are batched over rays; light-type dispatch is masked select
(few types, cheap formulas — the expensive part, the shadow ray, is shared).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from tpu_pbrt.core.sampling import Distribution2D, uniform_sample_triangle
from tpu_pbrt.core.smalltab import small_take, small_take_along
from tpu_pbrt.core.vecmath import dot, normalize
from tpu_pbrt.scene.compiler import (
    LIGHT_AREA,
    LIGHT_DISTANT,
    LIGHT_GONIO,
    LIGHT_INFINITE,
    LIGHT_POINT,
    LIGHT_PROJECTION,
    LIGHT_SPOT,
)


class LightSample(NamedTuple):
    li: jnp.ndarray  # (R,3) incident radiance (pre-visibility)
    wi: jnp.ndarray  # (R,3) world direction to light
    pdf: jnp.ndarray  # (R,) solid-angle pdf x light-pick pmf
    dist: jnp.ndarray  # (R,) shadow-ray length
    is_delta: jnp.ndarray  # (R,) delta light (no MIS vs BSDF)
    li_idx: jnp.ndarray = None  # (R,) sampled light row (BDPT MIS needs it)


def _spot_falloff(cos_w, cos_falloff_start, cos_total_width):
    d = jnp.clip(
        (cos_w - cos_total_width) / jnp.maximum(cos_falloff_start - cos_total_width, 1e-9),
        0.0,
        1.0,
    )
    return jnp.where(cos_w < cos_total_width, 0.0, jnp.where(cos_w > cos_falloff_start, 1.0, d * d * d * d))


def env_lookup(dev, d_world):
    """InfiniteAreaLight::Le for directions (bilinear lat-long lookup)."""
    env = dev["envmap"]
    h, w = env.shape[:2]
    wl = d_world @ dev["env_w2l"].T
    wl = normalize(wl)
    phi = jnp.arctan2(wl[..., 1], wl[..., 0])
    phi = jnp.where(phi < 0.0, phi + 2.0 * jnp.pi, phi)
    theta = jnp.arccos(jnp.clip(wl[..., 2], -1.0, 1.0))
    u = phi * (0.5 / jnp.pi)
    v = theta / jnp.pi
    x = u * w - 0.5
    y = v * h - 0.5
    x0 = jnp.floor(x).astype(jnp.int32)
    y0 = jnp.floor(y).astype(jnp.int32)
    fx = x - x0
    fy = y - y0
    x0w = jnp.mod(x0, w)
    x1w = jnp.mod(x0 + 1, w)
    y0c = jnp.clip(y0, 0, h - 1)
    y1c = jnp.clip(y0 + 1, 0, h - 1)
    c00 = env[y0c, x0w]
    c10 = env[y0c, x1w]
    c01 = env[y1c, x0w]
    c11 = env[y1c, x1w]
    fx = fx[..., None]
    fy = fy[..., None]
    return (c00 * (1 - fx) + c10 * fx) * (1 - fy) + (c01 * (1 - fx) + c11 * fx) * fy


def env_pdf(dev, d_world):
    """Solid-angle pdf of sampling d via the env importance map."""
    distr: Distribution2D = dev["env_distr"]
    wl = normalize(d_world @ dev["env_w2l"].T)
    phi = jnp.arctan2(wl[..., 1], wl[..., 0])
    phi = jnp.where(phi < 0.0, phi + 2.0 * jnp.pi, phi)
    theta = jnp.arccos(jnp.clip(wl[..., 2], -1.0, 1.0))
    sin_t = jnp.sin(theta)
    p_uv = distr.pdf(phi * (0.5 / jnp.pi), theta / jnp.pi)
    return jnp.where(sin_t > 1e-7, p_uv / (2.0 * jnp.pi * jnp.pi * jnp.maximum(sin_t, 1e-9)), 0.0)


def _env_sample(dev, u1, u2):
    """Sample direction from the env map distribution. Returns (wi, pdf, li)."""
    distr: Distribution2D = dev["env_distr"]
    (u, v), pdf_uv = distr.sample_continuous(u1, u2)
    theta = v * jnp.pi
    phi = u * 2.0 * jnp.pi
    sin_t = jnp.sin(theta)
    wl = jnp.stack([sin_t * jnp.cos(phi), sin_t * jnp.sin(phi), jnp.cos(theta)], axis=-1)
    # light-to-world: env_w2l is world->light rotation, transpose back
    wi = wl @ dev["env_w2l"]
    pdf = jnp.where(sin_t > 1e-7, pdf_uv / (2.0 * jnp.pi * jnp.pi * jnp.maximum(sin_t, 1e-9)), 0.0)
    li = env_lookup(dev, wi)
    return wi, pdf, li


def sample_triangle_point(tv, u1, u2):
    """Uniform point + geometric normal on (…,3,3) triangles — shared by
    Sample_Li, Sample_Le and BDPT's resample bookkeeping so the pdfs stay
    bit-identical across estimators."""
    b0, b1 = uniform_sample_triangle(u1, u2)
    p = (
        b0[..., None] * tv[..., 0, :]
        + b1[..., None] * tv[..., 1, :]
        + (1.0 - b0 - b1)[..., None] * tv[..., 2, :]
    )
    n = jnp.cross(tv[..., 1, :] - tv[..., 0, :], tv[..., 2, :] - tv[..., 0, :])
    n = n / jnp.maximum(jnp.linalg.norm(n, axis=-1, keepdims=True), 1e-20)
    return p, n


def triangle_normal(tv):
    """Geometric normal of (…,3,3) triangles (shared helper)."""
    n = jnp.cross(tv[..., 1, :] - tv[..., 0, :], tv[..., 2, :] - tv[..., 0, :])
    return n / jnp.maximum(jnp.linalg.norm(n, axis=-1, keepdims=True), 1e-20)


def _light_map_scale(dev, lt, li_idx, w_from_light, is_gonio, is_proj):
    """Image-modulated angular intensity of goniometric/projection lights
    (goniometric.h Scale, projection.cpp Projection). w_from_light is the
    world direction FROM the light toward the shading point; each row
    carries its world-to-light rotation and its (offset, w, h) window into
    the shared light atlas. Clamp-filtered bilinear lookup with per-row
    traced extents."""
    atlas = dev["light_atlas"]
    w2l = small_take(lt["w2l"], li_idx).reshape(li_idx.shape + (3, 3))
    img = small_take(lt["img"], li_idx)  # (..., 3): offset, width, height
    off, iw, ih = img[..., 0], img[..., 1], img[..., 2]
    dl = jnp.einsum("...ij,...j->...i", w2l, w_from_light)
    dl = normalize(dl)

    # goniometric: lat-long about the Y axis — pbrt goniometric.h Scale()
    # swaps y/z before SphericalTheta/Phi, so theta comes from the
    # light-space Y component and phi from (x, z)
    theta = jnp.arccos(jnp.clip(dl[..., 1], -1.0, 1.0))
    phi = jnp.arctan2(dl[..., 2], dl[..., 0])
    phi = jnp.where(phi < 0, phi + 2 * jnp.pi, phi)
    u_g = phi / (2 * jnp.pi)
    v_g = theta / jnp.pi

    # projection: perspective divide into the fov screen window
    tan_half = small_take(lt["cos0"], li_idx)
    aspect = small_take(lt["cos1"], li_idx)
    z = dl[..., 2]
    inside_z = z > 1e-3
    zs = jnp.where(inside_z, z, 1.0)
    sx = dl[..., 0] / (zs * jnp.maximum(tan_half, 1e-6))
    sy = dl[..., 1] / (zs * jnp.maximum(tan_half, 1e-6))
    u_p = (sx / jnp.maximum(aspect, 1.0) + 1.0) * 0.5
    v_p = (sy * jnp.minimum(aspect, 1.0) + 1.0) * 0.5
    in_win = inside_z & (u_p >= 0) & (u_p < 1) & (v_p >= 0) & (v_p < 1)

    u = jnp.where(is_proj, u_p, u_g)
    v = jnp.where(is_proj, v_p, v_g)

    x = u * iw - 0.5
    y = v * ih - 0.5
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    fx = x - x0
    fy = y - y0

    def tap(ix, iy):
        ix = jnp.clip(ix.astype(jnp.int32), 0, jnp.maximum(iw - 1, 0))
        iy = jnp.clip(iy.astype(jnp.int32), 0, jnp.maximum(ih - 1, 0))
        return atlas[jnp.maximum(off, 0) + iy * iw + ix]

    c = (
        tap(x0, y0) * ((1 - fx) * (1 - fy))[..., None]
        + tap(x0 + 1, y0) * (fx * (1 - fy))[..., None]
        + tap(x0, y0 + 1) * ((1 - fx) * fy)[..., None]
        + tap(x0 + 1, y0 + 1) * (fx * fy)[..., None]
    )
    use = (is_gonio | (is_proj & in_win)) & (off >= 0)
    return jnp.where(use[..., None], c, jnp.where(is_proj[..., None], 0.0, 1.0))


def sample_light_rows(dev, li_idx, ref_p, u1, u2) -> LightSample:
    """Sample_Li for explicit light rows li_idx (R,) — no pick pmf folded."""
    lt = dev["light"]
    ltype = small_take(lt["type"], li_idx)
    lp = small_take(lt["p"], li_idx)
    lL = small_take(lt["L"], li_idx)
    ldir = small_take(lt["dir"], li_idx)
    cos0 = small_take(lt["cos0"], li_idx)
    cos1 = small_take(lt["cos1"], li_idx)
    tri = small_take(lt["tri"], li_idx)
    twosided = small_take(lt["twosided"], li_idx)
    area = small_take(lt["area"], li_idx)
    wr = dev["world_radius"]

    # -- point / spot -----------------------------------------------------
    to_l = lp - ref_p
    d2 = jnp.maximum(jnp.sum(to_l * to_l, axis=-1), 1e-20)
    dist_pt = jnp.sqrt(d2)
    wi_pt = to_l / dist_pt[..., None]
    li_pt = lL / d2[..., None]
    fall = _spot_falloff(dot(-wi_pt, ldir), cos0, cos1)
    li_spot = li_pt * fall[..., None]

    # -- distant ----------------------------------------------------------
    wi_dist = ldir
    li_dist = lL
    dist_dist = jnp.full_like(dist_pt, 2.0) * wr

    # -- area (triangle) --------------------------------------------------
    if "tri_v" in lt:
        tv = small_take(lt["tri_v"], li_idx)  # (R,3,3) dense select
    else:
        tv = dev["tri_verts"][jnp.maximum(tri, 0)]
    p_l, n_l = sample_triangle_point(tv, u1, u2)
    to_a = p_l - ref_p
    d2a = jnp.maximum(jnp.sum(to_a * to_a, axis=-1), 1e-12)
    dist_a = jnp.sqrt(d2a)
    wi_a = to_a / dist_a[..., None]
    cos_l = dot(n_l, -wi_a)
    emits = (cos_l > 0.0) | (twosided > 0)
    li_a = jnp.where(emits[..., None], lL, 0.0)
    # area pdf -> solid angle
    pdf_a = d2a / jnp.maximum(jnp.abs(cos_l) * area, 1e-12)

    # -- infinite ---------------------------------------------------------
    if "envmap" in dev:
        wi_env, pdf_env, li_env = _env_sample(dev, u1, u2)
        dist_env = jnp.full_like(dist_pt, 2.0) * wr
    else:
        wi_env = wi_dist
        pdf_env = jnp.zeros_like(dist_pt)
        li_env = jnp.zeros_like(lL)
        dist_env = dist_dist

    # -- goniometric / projection (image-modulated point intensity) -------
    is_gonio = ltype == LIGHT_GONIO
    is_proj = ltype == LIGHT_PROJECTION
    if "light_atlas" in dev:
        scale_img = _light_map_scale(dev, lt, li_idx, -wi_pt, is_gonio, is_proj)
        li_gonio = li_pt * scale_img
    else:
        li_gonio = li_pt

    # -- select by type ---------------------------------------------------
    is_pt = ltype == LIGHT_POINT
    is_spot = ltype == LIGHT_SPOT
    is_distant = ltype == LIGHT_DISTANT
    is_area = ltype == LIGHT_AREA
    is_env = ltype == LIGHT_INFINITE

    wi = jnp.where(is_area[..., None], wi_a, wi_pt)
    wi = jnp.where(is_distant[..., None], wi_dist, wi)
    wi = jnp.where(is_env[..., None], wi_env, wi)
    li = jnp.where(is_area[..., None], li_a, li_pt)
    li = jnp.where(is_spot[..., None], li_spot, li)
    li = jnp.where((is_gonio | is_proj)[..., None], li_gonio, li)
    li = jnp.where(is_distant[..., None], li_dist, li)
    li = jnp.where(is_env[..., None], li_env, li)
    pdf = jnp.where(is_area, pdf_a, 1.0)
    pdf = jnp.where(is_env, pdf_env, pdf)
    dist = jnp.where(is_area, dist_a, dist_pt)
    dist = jnp.where(is_distant | is_env, dist_env, dist)
    is_delta = is_pt | is_spot | is_distant | is_gonio | is_proj

    li = jnp.where((pdf > 0.0)[..., None], li, 0.0)
    return LightSample(li, wi, pdf, dist, is_delta, li_idx)


class SpatialLightDistribution(NamedTuple):
    """lightdistrib.cpp SpatialLightDistribution, precomputed dense.

    pbrt voxelizes the scene and builds a per-voxel light Distribution1D
    LAZILY in a lock-free hash (64-entry packed keys); the TPU-shaped
    equivalent precomputes every voxel's CDF at scene compile into one
    dense (V, L) table — selection is then a single row gather plus a
    masked scan, no hashing and no laziness. The per-voxel importance is
    estimated at the voxel center (pbrt Monte-Carlos 128 points per
    voxel; documented simplification)."""

    cdf: jnp.ndarray  # (V, L) inclusive per-voxel CDF
    mean_pmf: jnp.ndarray  # (L,) scene-wide marginal (positionless fallback)
    lo: jnp.ndarray  # (3,)
    inv_cs: jnp.ndarray  # (3,)
    res: tuple  # STATIC (nx, ny, nz)

    def _voxel(self, p):
        nx, ny, nz = self.res
        v = jnp.floor((p - self.lo) * self.inv_cs).astype(jnp.int32)
        v = jnp.clip(v, 0, jnp.asarray([nx - 1, ny - 1, nz - 1], jnp.int32))
        return v[..., 0] + nx * (v[..., 1] + ny * v[..., 2])

    def sample_discrete_at(self, u, p):
        row = self.cdf[self._voxel(p)]  # (..., L)
        idx = jnp.sum((u[..., None] >= row).astype(jnp.int32), axis=-1)
        idx = jnp.minimum(idx, row.shape[-1] - 1)
        prev = jnp.where(
            idx > 0, small_take_along(row, jnp.maximum(idx - 1, 0)), 0.0
        )
        pmf = small_take_along(row, idx) - prev
        return idx, jnp.maximum(pmf, 1e-12)

    def discrete_pdf_at(self, idx, p):
        row = self.cdf[self._voxel(p)]
        idx = jnp.clip(idx, 0, row.shape[-1] - 1)
        prev = jnp.where(
            idx > 0, small_take_along(row, jnp.maximum(idx - 1, 0)), 0.0
        )
        return jnp.maximum(small_take_along(row, idx) - prev, 1e-12)


def sample_one_light(dev, light_distr, ref_p, u_pick, u1, u2) -> LightSample:
    """UniformSampleOneLight's light-selection + Sample_Li, batched.

    light_distr: None for uniform pick, a Distribution1D (power), or a
    SpatialLightDistribution (position-dependent pick).
    Returns pdf already including the pick pmf (contribution / pdf is then
    the single-light estimator of the sum over lights)."""
    lt = dev["light"]
    n = lt["type"].shape[0]
    if light_distr is None:
        li_idx = jnp.minimum((u_pick * n).astype(jnp.int32), n - 1)
        pick_pmf = jnp.full(u_pick.shape, 1.0 / n, jnp.float32)
    elif isinstance(light_distr, SpatialLightDistribution):
        li_idx, pick_pmf = light_distr.sample_discrete_at(u_pick, ref_p)
    else:
        li_idx, pick_pmf = light_distr.sample_discrete(u_pick)
    ls = sample_light_rows(dev, li_idx, ref_p, u1, u2)
    return LightSample(ls.li, ls.wi, ls.pdf * pick_pmf, ls.dist, ls.is_delta, li_idx)


def emitted_pdf(dev, light_distr, ref_p, hit_p, light_idx, n_l):
    """Solid-angle pdf (incl. pick pmf) of light-sampling the point hit_p on
    area light `light_idx` from ref_p."""
    lt = dev["light"]
    n = lt["type"].shape[0]
    area = small_take(lt["area"], jnp.maximum(light_idx, 0))
    to_h = hit_p - ref_p
    d2 = jnp.maximum(jnp.sum(to_h * to_h, axis=-1), 1e-12)
    wi = to_h / jnp.sqrt(d2)[..., None]
    cos_l = jnp.abs(dot(n_l, -wi))
    pdf_sa = d2 / jnp.maximum(cos_l * area, 1e-12)
    if light_distr is None:
        pmf = 1.0 / n
    elif isinstance(light_distr, SpatialLightDistribution):
        pmf = light_distr.discrete_pdf_at(jnp.maximum(light_idx, 0), ref_p)
    else:
        pmf = light_distr.discrete_pdf(jnp.maximum(light_idx, 0))
    return pdf_sa * pmf


def infinite_pdf(dev, light_distr, wi, ref_p=None):
    """Pdf_Li x pick pmf for escaped (BSDF-sampled) rays toward the env.
    ref_p: scattering position (needed for the spatial strategy's pick
    pmf; None falls back to the scene-wide marginal)."""
    lt = dev["light"]
    n = lt["type"].shape[0]
    if "envmap" not in dev:
        return jnp.zeros(wi.shape[:-1], jnp.float32)
    p = env_pdf(dev, wi)
    is_env = lt["type"] == LIGHT_INFINITE
    if light_distr is None:
        pmf = jnp.sum(is_env.astype(jnp.float32)) / n
    elif isinstance(light_distr, SpatialLightDistribution):
        idx = jnp.argmax(is_env)
        if ref_p is None:
            pmf = light_distr.mean_pmf[idx]
        else:
            pmf = light_distr.discrete_pdf_at(
                jnp.broadcast_to(idx, wi.shape[:-1]), ref_p
            )
    else:
        idx = jnp.argmax(is_env)
        pmf = light_distr.discrete_pdf(idx)
    return p * pmf


class LeSample(NamedTuple):
    """One sampled emission ray per lane (Light::Sample_Le, light.h)."""

    li_idx: jnp.ndarray  # (R,) light row
    pmf: jnp.ndarray  # (R,) pick pmf
    p: jnp.ndarray  # (R,3) emission origin
    n: jnp.ndarray  # (R,3) emission normal (light forward dir for deltas)
    d: jnp.ndarray  # (R,3) emission direction
    le: jnp.ndarray  # (R,3) emitted radiance/intensity
    pdf_pos: jnp.ndarray  # (R,) area-measure position pdf (1 for deltas)
    pdf_dir: jnp.ndarray  # (R,) solid-angle direction pdf
    is_delta: jnp.ndarray  # (R,) delta-position light (point/spot)
    supported: jnp.ndarray  # (R,) light type has a BDPT emission model


def sample_le(dev, light_distr, u_pick, up1, up2, ud1, ud2) -> LeSample:
    """Light::Sample_Le for BDPT/SPPM light subpaths (point.cpp:169,
    spot.cpp:94, diffuse.cpp:124, distant.cpp:59, infinite.cpp:129
    Sample_Le), batched with masked type dispatch. Distant/infinite
    lights emit from the scene-spanning disk behind their direction
    (VERDICT r4 #10)."""
    from tpu_pbrt.core.sampling import (
        concentric_sample_disk,
        cosine_sample_hemisphere,
        uniform_sample_sphere,
    )
    from tpu_pbrt.core.vecmath import coordinate_system

    lt = dev["light"]
    n_lights = lt["type"].shape[0]
    if light_distr is None:
        li_idx = jnp.minimum((u_pick * n_lights).astype(jnp.int32), n_lights - 1)
        pmf = jnp.full(u_pick.shape, 1.0 / n_lights, jnp.float32)
    elif isinstance(light_distr, SpatialLightDistribution):
        # emission has no receiver position; pick by the scene marginal
        cdf = jnp.cumsum(light_distr.mean_pmf)
        li_idx = jnp.minimum(
            jnp.sum((u_pick[..., None] >= cdf).astype(jnp.int32), -1), n_lights - 1
        )
        pmf = jnp.maximum(small_take(light_distr.mean_pmf, li_idx), 1e-12)
    else:
        li_idx, pmf = light_distr.sample_discrete(u_pick)
    ltype = small_take(lt["type"], li_idx)
    lp = small_take(lt["p"], li_idx)
    lL = small_take(lt["L"], li_idx)
    ldir = small_take(lt["dir"], li_idx)
    cos0 = small_take(lt["cos0"], li_idx)
    cos1 = small_take(lt["cos1"], li_idx)
    tri = small_take(lt["tri"], li_idx)
    twosided = small_take(lt["twosided"], li_idx)
    area = small_take(lt["area"], li_idx)

    # -- point: uniform sphere -------------------------------------------
    d_pt = uniform_sample_sphere(ud1, ud2)
    pdf_dir_pt = jnp.full_like(ud1, 1.0 / (4.0 * jnp.pi))

    # -- spot: uniform cone of the total width (spot.cpp Sample_Le) ------
    from tpu_pbrt.core.sampling import uniform_cone_pdf, uniform_sample_cone

    d_cone = uniform_sample_cone(ud1, ud2, cos1)  # local frame, +z axis
    s1, s2 = coordinate_system(ldir)
    d_spot = d_cone[..., 0:1] * s1 + d_cone[..., 1:2] * s2 + d_cone[..., 2:3] * ldir
    pdf_dir_spot = uniform_cone_pdf(cos1)
    fall = _spot_falloff(d_cone[..., 2], cos0, cos1)
    le_spot = lL * fall[..., None]

    # -- area: uniform point on the triangle + cosine hemisphere ---------
    # twosided lights pick the emission side with a remapped ud1 and halve
    # the direction pdf (diffuse.cpp Sample_Le / Pdf_Le)
    if "tri_v" in lt:
        tv = small_take(lt["tri_v"], li_idx)
    else:
        tv = dev["tri_verts"][jnp.maximum(tri, 0)]
    p_a, n_front = sample_triangle_point(tv, up1, up2)
    two = twosided > 0
    flip = two & (ud1 >= 0.5)
    ud1_a = jnp.where(two, jnp.minimum(ud1 * 2.0 % 1.0, 0.999999), ud1)
    n_a = jnp.where(flip[..., None], -n_front, n_front)
    d_loc = cosine_sample_hemisphere(ud1_a, ud2)
    t1, t2 = coordinate_system(n_a)
    d_a = d_loc[..., 0:1] * t1 + d_loc[..., 1:2] * t2 + d_loc[..., 2:3] * n_a
    pdf_dir_a = jnp.abs(d_loc[..., 2]) / jnp.pi
    pdf_dir_a = jnp.where(two, pdf_dir_a * 0.5, pdf_dir_a)
    pdf_pos_a = 1.0 / jnp.maximum(area, 1e-20)

    is_pt = ltype == LIGHT_POINT
    is_spot = ltype == LIGHT_SPOT
    is_area = ltype == LIGHT_AREA
    # goniometric/projection photons: point-position emission over the
    # sphere with the image-modulated intensity (goniometric.cpp /
    # projection.cpp Sample_Le; projection directions outside the fov
    # window carry zero and are wasted, as in the reference's cone)
    is_img = (ltype == LIGHT_GONIO) | (ltype == LIGHT_PROJECTION)
    is_distant = ltype == LIGHT_DISTANT
    is_env = ltype == LIGHT_INFINITE

    # -- distant (distant.cpp Sample_Le): ldir points TOWARD the light
    # (compiler stores from - to), so photons travel along -ldir from a
    # world-spanning disk offset a radius toward the light;
    # pdf_pos = 1/(pi r^2), pdf_dir = 1 (delta direction)
    wr = dev["world_radius"]
    wc = dev["world_center"]
    dx_d, dy_d = concentric_sample_disk(up1, up2)
    v1d, v2d = coordinate_system(ldir)
    p_disk = wc + wr * (dx_d[..., None] * v1d + dy_d[..., None] * v2d)
    p_dist = p_disk + ldir * wr
    pdf_pos_dist = 1.0 / (jnp.pi * wr * wr)

    # -- infinite (infinite.cpp Sample_Le): direction from the envmap
    # importance distribution (PHOTONS travel -wi), origin on the
    # tangent disk behind that direction
    if "envmap" in dev:
        wi_e, pdf_e, le_e = _env_sample(dev, ud1, ud2)
        d_env = -wi_e
        dx_e, dy_e = concentric_sample_disk(up1, up2)
        v1e, v2e = coordinate_system(d_env)
        p_env = (
            wc
            + wr * (dx_e[..., None] * v1e + dy_e[..., None] * v2e)
            - d_env * wr
        )
        pdf_dir_env = pdf_e
        le_env_s = le_e
    else:
        # unreachable: the compiler builds an envmap for every
        # LIGHT_INFINITE row; keep is_env lanes inert if it ever isn't
        d_env = d_pt
        p_env = jnp.broadcast_to(wc, d_pt.shape)
        pdf_dir_env = jnp.zeros_like(ud1)
        le_env_s = jnp.zeros_like(lL)
    supported = is_pt | is_spot | is_area | is_img | is_distant | is_env

    p = jnp.where(is_area[..., None], p_a, lp)
    p = jnp.where(is_distant[..., None], p_dist, p)
    p = jnp.where(is_env[..., None], p_env, p)
    n = jnp.where(is_area[..., None], n_a, ldir)
    n = jnp.where(is_distant[..., None], -ldir, n)
    n = jnp.where(is_env[..., None], d_env, n)
    d = jnp.where(is_area[..., None], d_a, d_pt)
    d = jnp.where(is_spot[..., None], d_spot, d)
    d = jnp.where(is_distant[..., None], -ldir, d)
    d = jnp.where(is_env[..., None], d_env, d)
    le = jnp.where(is_spot[..., None], le_spot, lL)
    le = jnp.where(is_env[..., None], le_env_s, le)
    if "light_atlas" in dev:
        le_img = lL * _light_map_scale(
            dev, lt, li_idx, d, ltype == LIGHT_GONIO, ltype == LIGHT_PROJECTION
        )
        le = jnp.where(is_img[..., None], le_img, le)
    pdf_pos = jnp.where(is_area, pdf_pos_a, 1.0)
    pdf_pos = jnp.where(is_distant | is_env, pdf_pos_dist, pdf_pos)
    pdf_dir = jnp.where(is_area, pdf_dir_a, pdf_dir_pt)
    pdf_dir = jnp.where(is_spot, pdf_dir_spot, pdf_dir)
    pdf_dir = jnp.where(is_distant, 1.0, pdf_dir)
    pdf_dir = jnp.where(is_env, pdf_dir_env, pdf_dir)
    is_delta = is_pt | is_spot | is_img | is_distant
    le = jnp.where(supported[..., None], le, 0.0)
    return LeSample(li_idx, pmf, p, n, d, le, pdf_pos, pdf_dir, is_delta, supported)


def le_pdfs(dev, li_idx, n_emit, w):
    """Light::Pdf_Le for an emission configuration: position pdf (area
    measure) and direction pdf (solid angle) of emitting along w from a
    light-row li_idx whose surface normal is n_emit. Used by BDPT MIS.
    Twosided area lights emit from either face at half the one-sided
    cosine pdf (diffuse.cpp Pdf_Le)."""
    from tpu_pbrt.core.sampling import uniform_cone_pdf

    lt = dev["light"]
    ltype = lt["type"][li_idx]
    cos1 = lt["cos1"][li_idx]
    area = lt["area"][li_idx]
    two = lt["twosided"][li_idx] > 0
    is_pt = ltype == LIGHT_POINT
    is_spot = ltype == LIGHT_SPOT
    is_area = ltype == LIGHT_AREA
    cos_l = dot(n_emit, w)
    pdf_area = jnp.where(
        two, 0.5 * jnp.abs(cos_l) / jnp.pi, jnp.maximum(cos_l, 0.0) / jnp.pi
    )
    pdf_dir = jnp.where(is_pt, 1.0 / (4.0 * jnp.pi), 0.0)
    pdf_dir = jnp.where(is_spot, uniform_cone_pdf(cos1), pdf_dir)
    pdf_dir = jnp.where(is_area, pdf_area, pdf_dir)
    pdf_pos = jnp.where(is_area, 1.0 / jnp.maximum(area, 1e-20), 1.0)
    # distant/infinite (distant.cpp/infinite.cpp Pdf_Le): position over
    # the scene-spanning disk; direction delta (distant) or the env
    # importance pdf (infinite)
    is_distant = ltype == LIGHT_DISTANT
    is_env = ltype == LIGHT_INFINITE
    wr = dev["world_radius"]
    disk_pdf = 1.0 / (jnp.pi * wr * wr)
    pdf_pos = jnp.where(is_distant | is_env, disk_pdf, pdf_pos)
    # distant.cpp Pdf_Le: the direction is a DELTA — pdf 0, which the
    # BDPT MIS ratio walk remaps exactly like other delta junctions
    pdf_dir = jnp.where(is_distant, 0.0, pdf_dir)
    if "envmap" in dev:
        pdf_dir = jnp.where(is_env, env_pdf(dev, -w), pdf_dir)
    return pdf_pos, pdf_dir


def light_pick_pmf(dev, light_distr, li_idx, ref_p=None):
    """Pick pmf of light row li_idx under the integrator's distribution."""
    n = dev["light"]["type"].shape[0]
    if light_distr is None:
        return jnp.full(jnp.shape(li_idx), 1.0 / n, jnp.float32)
    if isinstance(light_distr, SpatialLightDistribution):
        if ref_p is None:
            return jnp.maximum(light_distr.mean_pmf[jnp.maximum(li_idx, 0)], 1e-12)
        return light_distr.discrete_pdf_at(jnp.maximum(li_idx, 0), ref_p)
    return light_distr.discrete_pdf(jnp.maximum(li_idx, 0))


def emitted_radiance(dev, tri_light, wo_world, n_g):
    """L_e of an intersected emissive triangle (diffuse.cpp
    DiffuseAreaLight::L): emits from the front side unless twosided."""
    lt = dev["light"]
    idx = jnp.maximum(tri_light, 0)
    lL = small_take(lt["L"], idx)
    two = small_take(lt["twosided"], idx)
    front = dot(n_g, wo_world) > 0.0
    emit = (tri_light >= 0) & (front | (two > 0))
    return jnp.where(emit[..., None], lL, 0.0)
