"""BSSRDF subsurface transport: photon-beam-diffusion tables + the
separable sampling machinery.

Capability match for pbrt-v3 src/core/bssrdf.{h,cpp} (SeparableBSSRDF /
TabulatedBSSRDF / ComputeBeamDiffusionBSSRDF / SubsurfaceFromDiffuse)
and src/materials/subsurface.cpp + kdsubsurface.cpp. The numerical
model is the published photon-beam-diffusion estimate (Habel, Christensen
& Jarosz 2013) with the classical-dipole grosjean diffusion coefficient
and Fresnel boundary moments — the same physics pbrt tabulates.

TPU-first redesign:
- pbrt interpolates a (rho, radius) CatmullRom2D table per lookup
  because its albedo can be textured. Here sigma_a/sigma_s are
  per-material compile-time constants (textured sigma_s warns and takes
  the constant fallback), so the compiler bakes ONE radial profile per
  (subsurface material, RGB channel): a (64,) r-grid with profile,
  normalized CDF, and pdf rows. Device lookups are 1-D linear interps
  on a lane-major (rows, 64) table — no 2-D spline walk, no
  data-dependent iteration.
- radius sampling inverts the baked CDF with a vectorized
  searchsorted-free interval walk (the grid is 64 wide: a dense
  compare+sum finds the interval as one (R, 64) op on the VPU).
- the probe-ray machinery (Sample_Sp's axis/channel MIS, chord
  construction, Pdf_Sp) lives in integrators/path.py as masked dense
  waves; this module is pure per-lane math.

Verification: tests/test_bssrdf.py pins rho_eff monotonicity, the
diffusion profile's normalization (integral 2*pi*r*Sr dr == rho_eff),
CDF inversion round-trips, and the white-furnace-style energy bound of
the end-to-end subsurface render.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

#: radial samples per profile (bssrdf.cpp uses 64)
N_RADII = 64
#: depth samples of the beam integration (bssrdf.cpp nSamples = 100)
_N_DEPTH = 100


def fresnel_moment1(eta: float) -> float:
    """First angular moment of the Fresnel reflectance (bssrdf.cpp
    FresnelMoment1 — the d'Eon & Irving 2011 polynomial fits)."""
    e2, e3 = eta * eta, eta * eta * eta
    e4, e5 = e2 * e2, e2 * e3
    if eta < 1.0:
        return (
            0.45966 - 1.73965 * eta + 3.37668 * e2 - 3.904945 * e3
            + 2.49277 * e4 - 0.68441 * e5
        )
    return (
        -4.61686 + 11.1136 * eta - 10.4646 * e2 + 5.11455 * e3
        - 1.27198 * e4 + 0.12746 * e5
    )


def fresnel_moment2(eta: float) -> float:
    """Second Fresnel moment (bssrdf.cpp FresnelMoment2)."""
    e2, e3 = eta * eta, eta * eta * eta
    e4, e5 = e2 * e2, e2 * e3
    if eta < 1.0:
        return (
            0.27614 - 0.87350 * eta + 1.12077 * e2 - 0.65095 * e3
            - 0.07883 * e4 + 0.04860 * e5
        )
    r_1 = -547.033 + 45.3087 / e3 - 218.725 / e2 + 458.843 / eta
    r_1 += 404.557 * eta - 189.519 * e2 + 54.9327 * e3 - 9.00603 * e4
    r_1 += 0.63942 * e5
    return r_1


def _fr_dielectric(cos_i: np.ndarray, eta: float) -> np.ndarray:
    """Unpolarized Fresnel reflectance, numpy (host tables)."""
    cos_i = np.clip(cos_i, -1.0, 1.0)
    entering = cos_i > 0
    eta_i = np.where(entering, 1.0, eta)
    eta_t = np.where(entering, eta, 1.0)
    ci = np.abs(cos_i)
    sin_t2 = (eta_i / eta_t) ** 2 * np.maximum(0.0, 1.0 - ci * ci)
    tir = sin_t2 >= 1.0
    ct = np.sqrt(np.maximum(0.0, 1.0 - sin_t2))
    r_par = (eta_t * ci - eta_i * ct) / np.maximum(eta_t * ci + eta_i * ct, 1e-12)
    r_perp = (eta_i * ci - eta_t * ct) / np.maximum(eta_i * ci + eta_t * ct, 1e-12)
    return np.where(tir, 1.0, 0.5 * (r_par**2 + r_perp**2))


def beam_diffusion_ms(sigma_s: float, sigma_a: float, g: float, eta: float,
                      r: np.ndarray) -> np.ndarray:
    """Multiple-scattering radial profile Sr_ms(r) by photon-beam
    diffusion (bssrdf.cpp BeamDiffusionMS; Habel et al. 2013 eq. 5/11):
    average the classical-dipole diffusion response over _N_DEPTH
    exponentially-distributed beam depths, with Grosjean's
    non-classical diffusion coefficient and the extrapolated boundary
    from the Fresnel moments."""
    r = np.asarray(r, np.float64)
    sigma_t = sigma_a + sigma_s
    if sigma_t <= 0.0:
        return np.zeros_like(r)
    # similarity-reduced coefficients
    sigmap_s = sigma_s * (1.0 - g)
    sigmap_t = sigma_a + sigmap_s
    rhop = sigmap_s / sigmap_t
    # Grosjean's effective diffusion coefficient (non-classical)
    d_g = (2.0 * sigma_a + sigmap_s) / (3.0 * sigmap_t**2)
    sigma_tr = math.sqrt(sigma_a / d_g)
    # linear-extrapolation boundary depth from the Fresnel moments
    fm1, fm2 = fresnel_moment1(eta), fresnel_moment2(eta)
    ze = -2.0 * d_g * (1.0 + 3.0 * fm2) / (1.0 - 2.0 * fm1)
    # exitance scale factors (d'Eon & Irving's hybrid flux+fluence)
    c_phi = 0.25 * (1.0 - 2.0 * fm1)
    c_e = 0.5 * (1.0 - 3.0 * fm2)
    out = np.zeros_like(r)
    for i in range(_N_DEPTH):
        # real source depth sampled from the beam's transmittance
        zr = -math.log(1.0 - (i + 0.5) / _N_DEPTH) / sigmap_t
        # virtual source mirrored across the extrapolated boundary
        zv = -zr + 2.0 * ze
        dr = np.sqrt(r * r + zr * zr)
        dv = np.sqrt(r * r + zv * zv)
        phi_d = (np.exp(-sigma_tr * dr) / np.maximum(dr, 1e-9)
                 - np.exp(-sigma_tr * dv) / np.maximum(dv, 1e-9)) / (
            4.0 * math.pi * d_g
        )
        e_dn = (
            zr * (1.0 + sigma_tr * dr) * np.exp(-sigma_tr * dr)
            / np.maximum(dr, 1e-9) ** 3
            - zv * (1.0 + sigma_tr * dv) * np.exp(-sigma_tr * dv)
            / np.maximum(dv, 1e-9) ** 3
        ) / (4.0 * math.pi)
        # pbrt's source weighting: rhop^2 (one albedo factor for the
        # scattering event creating the source, one for the exitance
        # response) times the kappa correction of Habel et al. eq. 18
        # (suppresses the dipole's overestimate at source depths the
        # beam has not yet reached). Without both, the effective albedo
        # saturates near 0.5 instead of approaching 1 as rho' -> 1.
        kappa = 1.0 - np.exp(-2.0 * sigmap_t * (dr + zr))
        out += (c_phi * phi_d + c_e * e_dn) * kappa * (
            rhop * rhop / _N_DEPTH
        )
    return np.maximum(out, 0.0)


def beam_diffusion_ss(sigma_s: float, sigma_a: float, g: float, eta: float,
                      r: np.ndarray) -> np.ndarray:
    """Single-scattering radial profile (bssrdf.cpp BeamDiffusionSS):
    integrate the one-bounce HG response along the refracted beam,
    sampled at _N_DEPTH transmittance-distributed depths."""
    r = np.asarray(r, np.float64)
    sigma_t = sigma_a + sigma_s
    if sigma_t <= 0.0:
        return np.zeros_like(r)
    rho = sigma_s / sigma_t
    # critical depth: beyond t_crit the exit angle suffers TIR
    t_crit = r * math.sqrt(max(eta * eta - 1.0, 0.0))
    out = np.zeros_like(r)
    for i in range(_N_DEPTH):
        ti = t_crit - math.log(1.0 - (i + 0.5) / _N_DEPTH) / sigma_t
        d = np.sqrt(r * r + ti * ti)
        cos_o = ti / np.maximum(d, 1e-9)
        # HG phase at the single-scatter vertex (deflection from
        # straight-down beam to the exit direction)
        g2 = g * g
        denom = 1.0 + g2 + 2.0 * g * (-cos_o)
        phase = (1.0 - g2) / (4.0 * math.pi * np.maximum(denom, 1e-9) ** 1.5)
        # exit Fresnel at the inside-to-outside crossing: pbrt's
        # BeamDiffusionSS uses FrDielectric(-cosThetaO, 1, eta) — the
        # NEGATIVE cosine selects the eta->1 (exiting) branch. The
        # entering-side convention (+cos_o) overestimates transmission
        # near the critical angle (advisor finding, ISSUE 2 satellite)
        fr_exit = 1.0 - _fr_dielectric(-cos_o, eta)
        out += (
            rho
            * np.exp(-sigma_t * (d + t_crit))
            / np.maximum(d * d, 1e-12)
            * phase
            * fr_exit
            * cos_o
        ) / _N_DEPTH
    return np.maximum(out, 0.0)


class BakedBSSRDF(NamedTuple):
    """Per-scene device tables: one row per (subsurface material id,
    channel). Rows for non-subsurface materials are zeros."""

    radii: jnp.ndarray     # (M, 3, N_RADII) radius grid (per-channel scale)
    profile: jnp.ndarray   # (M, 3, N_RADII) Sr(r) (area density)
    cdf: jnp.ndarray       # (M, 3, N_RADII) normalized radial CDF
    rho_eff: jnp.ndarray   # (M, 3) total diffuse albedo of the profile
    r_max: jnp.ndarray     # (M, 3) 0.999-quantile sampling radius
    eta: jnp.ndarray       # (M,)


def radial_grid(sigma_t: float) -> np.ndarray:
    """bssrdf.cpp's radius samples (0, 2.5e-3, *1.2 geometric), scaled
    into physical units by the mean free path 1/sigma_t."""
    r = np.zeros(N_RADII)
    r[1] = 2.5e-3
    for i in range(2, N_RADII):
        r[i] = r[i - 1] * 1.2
    return r / max(sigma_t, 1e-9)


def bake_profile(sigma_s: float, sigma_a: float, g: float, eta: float):
    """One channel's (radii, profile, cdf, rho_eff, r_max). Profile is
    Sr(r) (per-area); the CDF integrates 2*pi*r*Sr piecewise linearly
    (trapezoid — documented deviation from pbrt's spline-exact
    IntegrateCatmullRom; the grid is geometric and dense where Sr
    varies, measured <1% albedo error on the test media)."""
    sigma_t = sigma_s + sigma_a
    radii = radial_grid(sigma_t)
    prof = beam_diffusion_ms(sigma_s, sigma_a, g, eta, radii) + \
        beam_diffusion_ss(sigma_s, sigma_a, g, eta, radii)
    integrand = 2.0 * math.pi * radii * prof
    seg = 0.5 * (integrand[1:] + integrand[:-1]) * np.diff(radii)
    cdf = np.concatenate([[0.0], np.cumsum(seg)])
    rho_eff = float(cdf[-1])
    if rho_eff > 0:
        cdf_n = cdf / rho_eff
    else:
        cdf_n = np.linspace(0.0, 1.0, N_RADII)
    r_max = float(np.interp(0.999, cdf_n, radii))
    return radii, prof, cdf_n, rho_eff, r_max


def effective_albedo_curve(g: float, eta: float, n: int = 48):
    """(rho_single[], rho_eff[]) for SubsurfaceFromDiffuse inversion:
    rho_eff is monotone in the single-scattering albedo. The rho grid
    uses pbrt's exponential spacing (bssrdf.cpp
    ComputeBeamDiffusionBSSRDF): coarse near 0 where the curve is flat,
    dense near 1 where it rises steeply toward rho_eff ~ 1 — a uniform
    grid there makes the linear inversion land ~0.1 off for bright
    diffuse colors."""
    i = np.arange(n, dtype=np.float64)
    rho_s = (1.0 - np.exp(-8.0 * i / (n - 1))) / (1.0 - math.exp(-8.0))
    rho_s = np.clip(rho_s, 1e-4, 0.9999)
    rho_e = np.empty(n)
    for k, rs in enumerate(rho_s):
        # unit sigma_t: profiles scale with mfp, albedo does not
        _, _, _, re, _ = bake_profile(rs, 1.0 - rs, g, eta)
        rho_e[k] = re
    return rho_s, np.maximum.accumulate(rho_e)


def subsurface_from_diffuse(kd: np.ndarray, mfp: np.ndarray, g: float,
                            eta: float):
    """kdsubsurface.cpp: invert the effective-albedo curve so the
    medium's diffusion profile integrates to the given diffuse color,
    with mean free path mfp per channel. Returns (sigma_s, sigma_a)."""
    rho_s_grid, rho_e_grid = effective_albedo_curve(g, eta)
    kd = np.clip(np.asarray(kd, np.float64), 0.0, 0.995)
    rho = np.interp(kd, rho_e_grid, rho_s_grid)
    sigma_t = 1.0 / np.maximum(np.asarray(mfp, np.float64), 1e-6)
    return rho * sigma_t, (1.0 - rho) * sigma_t


# -- device-side lookups ---------------------------------------------------


def _interp_row(radii, values, r):
    """Linear interp values(r) on a per-lane (…, N_RADII) grid pair."""
    idx = jnp.sum((r[..., None] >= radii).astype(jnp.int32), axis=-1) - 1
    i0 = jnp.clip(idx, 0, N_RADII - 2)
    r0 = jnp.take_along_axis(radii, i0[..., None], axis=-1)[..., 0]
    r1 = jnp.take_along_axis(radii, (i0 + 1)[..., None], axis=-1)[..., 0]
    v0 = jnp.take_along_axis(values, i0[..., None], axis=-1)[..., 0]
    v1 = jnp.take_along_axis(values, (i0 + 1)[..., None], axis=-1)[..., 0]
    t = jnp.clip((r - r0) / jnp.maximum(r1 - r0, 1e-20), 0.0, 1.0)
    v = v0 + t * (v1 - v0)
    inside = (r >= radii[..., 0]) & (r <= radii[..., -1])
    return jnp.where(inside, v, 0.0)


def sr_eval(tab: BakedBSSRDF, mid, r):
    """Sp(r): (R, 3) profile at distance r (R,) for material rows mid."""
    radii = tab.radii[mid]   # (R, 3, N)
    prof = tab.profile[mid]
    return jnp.stack(
        [_interp_row(radii[:, c], prof[:, c], r) for c in range(3)], axis=-1
    )


def sample_sr(tab: BakedBSSRDF, mid, ch, u):
    """Invert the radial CDF of channel ch: u (R,) -> radius (R,).
    Dense interval search: one (R, N_RADII) compare+sum (the grid is
    tiny; a gather chain would be slower on TPU)."""
    radii = jnp.take_along_axis(
        tab.radii[mid], ch[..., None, None], axis=-2
    )[..., 0, :]  # (R, N)
    cdf = jnp.take_along_axis(
        tab.cdf[mid], ch[..., None, None], axis=-2
    )[..., 0, :]
    idx = jnp.sum((u[..., None] >= cdf).astype(jnp.int32), axis=-1) - 1
    i0 = jnp.clip(idx, 0, N_RADII - 2)
    c0 = jnp.take_along_axis(cdf, i0[..., None], axis=-1)[..., 0]
    c1 = jnp.take_along_axis(cdf, (i0 + 1)[..., None], axis=-1)[..., 0]
    r0 = jnp.take_along_axis(radii, i0[..., None], axis=-1)[..., 0]
    r1 = jnp.take_along_axis(radii, (i0 + 1)[..., None], axis=-1)[..., 0]
    t = jnp.clip((u - c0) / jnp.maximum(c1 - c0, 1e-20), 0.0, 1.0)
    return r0 + t * (r1 - r0)


def pdf_sr(tab: BakedBSSRDF, mid, ch, r):
    """Radial sampling pdf (per unit area) of channel ch at radius r:
    2*pi*r*Sr(r)/rho_eff is the density in r; the AREA density the MIS
    weights need is Sr(r)/rho_eff (bssrdf.cpp Pdf_Sr per-area form)."""
    radii = jnp.take_along_axis(
        tab.radii[mid], ch[..., None, None], axis=-2
    )[..., 0, :]
    prof = jnp.take_along_axis(
        tab.profile[mid], ch[..., None, None], axis=-2
    )[..., 0, :]
    rho = jnp.take_along_axis(tab.rho_eff[mid], ch[..., None], axis=-1)[..., 0]
    sr = _interp_row(radii, prof, r)
    return sr / jnp.maximum(rho, 1e-9)


def sw_eval(eta, cos_w):
    """Directional term Sw (bssrdf.h SeparableBSSRDF::Sw): the
    normalized Fresnel transmittance of the exit crossing, with pbrt's
    c = 1 - 2*FresnelMoment1(1/eta) normalization — by the moment
    identity this makes the hemispherical integral of Sw*cos exactly 1
    (pinned by tests/test_bssrdf.py::test_sw_normalization). The eta^2
    radiance-mode factor of pbrt's SeparableBSSRDFAdapter::f is NOT
    part of Sw; the integrator applies it once at the exit vertex."""
    from tpu_pbrt.core.bxdf import fresnel_dielectric

    c = 1.0 - 2.0 * fresnel_moment1_jnp(1.0 / eta)
    fr = fresnel_dielectric(
        jnp.abs(cos_w), jnp.ones_like(jnp.asarray(eta)), eta
    )
    return (1.0 - fr) / (c * jnp.pi)


def fresnel_moment1_jnp(eta):
    e2, e3 = eta * eta, eta * eta * eta
    e4, e5 = e2 * e2, e2 * e3
    lo = (0.45966 - 1.73965 * eta + 3.37668 * e2 - 3.904945 * e3
          + 2.49277 * e4 - 0.68441 * e5)
    hi = (-4.61686 + 11.1136 * eta - 10.4646 * e2 + 5.11455 * e3
          - 1.27198 * e4 + 0.12746 * e5)
    return jnp.where(eta < 1.0, lo, hi)
