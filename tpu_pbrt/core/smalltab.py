"""Dense selects for tiny per-scene tables (lights, materials).

Random gathers on this TPU cost ~10-30 ns per fetched ELEMENT regardless
of table size (profiled: the light/material row fetches in the path
integrator's shading phase were ~1.1 s of a 6 s render window on a
3-light scene). For a table with few rows, a where-sum over a one-hot
row mask is pure dense vector math — bandwidth-bound, orders of
magnitude cheaper — and bit-exact (the sum has one nonzero term).

Capability note: this replaces the implicit `Scene::lights[i]` /
material-pointer indirection of pbrt-v3 (src/core/scene.h,
src/core/primitive.cpp GetMaterial) for the SoA tables; semantics are
identical to `table[idx]`.
"""

from __future__ import annotations

import jax.numpy as jnp

#: tables at or below this many rows use the dense select; above it the
#: native gather wins (dense cost grows linearly with row count)
MAX_DENSE_ROWS = 16


def small_take(table, idx, max_rows: int = MAX_DENSE_ROWS):
    """`table[idx]` with a dense one-hot select when the leading dim is
    tiny. idx may have any shape; trailing table dims broadcast.

    Out-of-range idx is CLAMPED to [0, n-1], matching the native
    `table[idx]` gather's clamp mode on both paths (the one-hot compare
    would otherwise silently return zeros for e.g. -1 sentinels)."""
    n = table.shape[0]
    if n > max_rows:
        return table[idx]
    idx = jnp.clip(jnp.asarray(idx), 0, n - 1)
    oh = idx[..., None] == jnp.arange(n, dtype=idx.dtype)  # (..., n)
    ohx = oh.reshape(oh.shape + (1,) * (table.ndim - 1))
    t = table.reshape((1,) * idx.ndim + table.shape)
    out = jnp.sum(jnp.where(ohx, t, 0), axis=idx.ndim)
    return out.astype(table.dtype)


def small_take_along(row, idx, max_cols: int = MAX_DENSE_ROWS * 2):
    """`take_along_axis(row, idx[..., None], -1)[..., 0]` as a dense
    select over a small LAST axis (e.g. per-voxel light-pick CDF rows)."""
    L = row.shape[-1]
    if L > max_cols:
        return jnp.take_along_axis(row, idx[..., None], axis=-1)[..., 0]
    oh = idx[..., None] == jnp.arange(L, dtype=idx.dtype)
    return jnp.sum(jnp.where(oh, row, 0), axis=-1).astype(row.dtype)
