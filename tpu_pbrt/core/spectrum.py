"""Color/spectrum handling.

Capability match for pbrt-v3 src/core/spectrum.{h,cpp}. The device color
representation is linear RGB float32 (pbrt's default RGBSpectrum; its
compile-time SampledSpectrum<60> variant is subsumed by host-side spectral
conversion: arbitrary SPDs, XYZ and blackbody inputs are integrated against
CIE matching curves at scene-compile time, which is where pbrt itself
converts for RGB rendering).

CIE matching functions use the Wyman–Sloan–Shirley multi-lobe Gaussian fits
(JCGT 2013) — within ~1% of the tabulated CIE 1931 curves, which is well
inside rendering tolerance and keeps tables out of the repo.
"""

from __future__ import annotations

import numpy as np

# sRGB/Rec709 primaries, D65 white (matches pbrt's RGB<->XYZ matrices)
_XYZ_TO_RGB = np.array(
    [
        [3.240479, -1.537150, -0.498535],
        [-0.969256, 1.875991, 0.041556],
        [0.055648, -0.204043, 1.057311],
    ]
)
_RGB_TO_XYZ = np.array(
    [
        [0.412453, 0.357580, 0.180423],
        [0.212671, 0.715160, 0.072169],
        [0.019334, 0.119193, 0.950227],
    ]
)

CIE_Y_INTEGRAL = 106.856895


def xyz_to_rgb(xyz) -> np.ndarray:
    return _XYZ_TO_RGB @ np.asarray(xyz, dtype=np.float64)


def rgb_to_xyz(rgb) -> np.ndarray:
    return _RGB_TO_XYZ @ np.asarray(rgb, dtype=np.float64)


def luminance(rgb):
    """Rec.709 luminance (pbrt RGBSpectrum::y). Backend-agnostic: works on
    numpy and traced jax arrays; returns an array of rgb's batch shape."""
    return 0.212671 * rgb[..., 0] + 0.715160 * rgb[..., 1] + 0.072169 * rgb[..., 2]


def _gauss(x, alpha, mu, s1, s2):
    s = np.where(x < mu, s1, s2)
    return alpha * np.exp(-((x - mu) ** 2) / (2 * s * s))


def cie_x(lam):
    lam = np.asarray(lam, dtype=np.float64)
    return _gauss(lam, 1.056, 599.8, 37.9, 31.0) + _gauss(lam, 0.362, 442.0, 16.0, 26.7) + _gauss(
        lam, -0.065, 501.1, 20.4, 26.2
    )


def cie_y(lam):
    lam = np.asarray(lam, dtype=np.float64)
    return _gauss(lam, 0.821, 568.8, 46.9, 40.5) + _gauss(lam, 0.286, 530.9, 16.3, 31.1)


def cie_z(lam):
    lam = np.asarray(lam, dtype=np.float64)
    return _gauss(lam, 1.217, 437.0, 11.8, 36.0) + _gauss(lam, 0.681, 459.0, 26.0, 13.8)


def spd_to_xyz(lam: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Integrate a piecewise-linear SPD (sorted by wavelength, nm) against the
    CIE curves (pbrt SampledSpectrum::FromSampled -> ToXYZ)."""
    order = np.argsort(lam)
    lam, vals = np.asarray(lam, dtype=np.float64)[order], np.asarray(vals, dtype=np.float64)[order]
    grid = np.arange(360.0, 831.0, 1.0)
    v = np.interp(grid, lam, vals, left=vals[0], right=vals[-1])
    x = np.trapezoid(v * cie_x(grid), grid)
    y = np.trapezoid(v * cie_y(grid), grid)
    z = np.trapezoid(v * cie_z(grid), grid)
    return np.array([x, y, z]) / CIE_Y_INTEGRAL


def spd_to_rgb(lam: np.ndarray, vals: np.ndarray) -> np.ndarray:
    return xyz_to_rgb(spd_to_xyz(lam, vals))


def blackbody(lam_nm: np.ndarray, t_kelvin: float) -> np.ndarray:
    """Planck's law, spectral radiance (pbrt Blackbody, W/(m^2 sr m))."""
    lam = np.asarray(lam_nm, dtype=np.float64) * 1e-9
    c = 299792458.0
    h = 6.62606957e-34
    kb = 1.3806488e-23
    return (2 * h * c * c) / (lam**5 * (np.expm1(h * c / (lam * kb * t_kelvin))))


def blackbody_rgb_normalized(t_kelvin: float) -> np.ndarray:
    """pbrt BlackbodyNormalized: scaled so peak wavelength has value 1, then
    converted to RGB."""
    grid = np.arange(360.0, 831.0, 1.0)
    le = blackbody(grid, t_kelvin)
    lam_max = 2.8977721e-3 / t_kelvin * 1e9
    max_l = blackbody(np.array([lam_max]), t_kelvin)[0]
    return spd_to_rgb(grid, le / max_l)


# Named metal spectra (pbrt ships .spd files for these under
# scenes' spds/ and embeds Cu/CuK as the MetalMaterial default).
# RGB values below were produced by integrating the tabulated
# refractiveindex.info data against the CIE fits above.
NAMED_SPECTRA_RGB = {
    "metal-cu-eta": np.array([0.2004, 0.9240, 1.1022]),
    "metal-cu-k": np.array([3.9129, 2.4528, 2.1421]),
    "metal-au-eta": np.array([0.1431, 0.3749, 1.4424]),
    "metal-au-k": np.array([3.9831, 2.3857, 1.6032]),
    "metal-ag-eta": np.array([0.1553, 0.1163, 0.1380]),
    "metal-ag-k": np.array([4.8283, 3.1222, 2.1469]),
    "metal-al-eta": np.array([1.3456, 0.9654, 0.6172]),
    "metal-al-k": np.array([7.4746, 6.3995, 5.3031]),
    "glass-bk7": np.array([1.5131, 1.5191, 1.5253]),
}
