"""FourierBSDF: tabulated measured/simulated BSDFs.

Capability match for pbrt-v3 src/core/reflection.{h,cpp} FourierBSDF +
FourierBSDFTable::Read (the binary .bsdf format produced by layerlab /
Jakob-Hanika 2014). The table stores, per (muI, muO) knot pair, a
variable-length cosine series a_k such that

    f(muI, muO, phi) * |muI| = sum_k a_k cos(k phi)

with 1 (luminance) or 3 (Y, R, B) channels; G is reconstructed with
pbrt's constants. Evaluation blends the 16 neighbouring knot pairs'
series with Catmull-Rom weights (core/interpolation.py) and runs the
cosine recurrence on the blended coefficients.

TPU-first notes: the variable-length coefficient runs are gathered as
fixed mMax windows from the flat coefficient array and masked per-run
(dense math instead of pointer-chased runs). Sampling DEVIATES from
pbrt's SampleFourier Newton inversion: wi is drawn from a two-sided
cosine distribution and weighted by the exact f/pdf — unbiased, with
somewhat higher variance on strongly specular tables (documented; the
eval/pdf pair is exact so MIS stays correct).
"""

from __future__ import annotations

import struct
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core.interpolation import catmull_rom_weights, fourier
from tpu_pbrt.utils.error import Error


class FourierTable:
    """Device arrays for one .bsdf table (shared by every fourier
    material in the scene that names the same file). Registered as a
    custom pytree so eta/n_channels/m_max stay STATIC across jit (m_max
    bounds the coefficient gather loop at trace time)."""

    def __init__(self, mu, cdf, a, offset, m, eta, n_channels, m_max):
        self.mu = mu  # (nMu,) zenith cosine knots, ascending in [-1,1]
        self.cdf = cdf  # (nMu, nMu) marginal CDFs (pdf normalization)
        self.a = a  # (nCoeffs,) flat coefficient array
        self.offset = offset  # (nMu*nMu,) i32 run starts into a
        self.m = m  # (nMu*nMu,) i32 run orders (per channel stride)
        self.eta = float(eta)
        self.n_channels = int(n_channels)
        self.m_max = int(m_max)

    def tree_flatten(self):
        return (
            (self.mu, self.cdf, self.a, self.offset, self.m),
            (self.eta, self.n_channels, self.m_max),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


import jax  # noqa: E402

jax.tree_util.register_pytree_node(
    FourierTable,
    lambda t: t.tree_flatten(),
    FourierTable.tree_unflatten,
)


def read_bsdf_file(path: str) -> FourierTable:
    """FourierBSDFTable::Read (reflection.cpp): little-endian binary."""
    with open(path, "rb") as fh:
        data = fh.read()
    if data[:8] != b"SCATFUN\x01":
        Error(f'"{path}": not a valid .bsdf (SCATFUN v1) file')
    ints = struct.unpack_from("<9i", data, 8)
    flags, n_mu, n_coeffs, m_max, n_channels, n_bases = ints[:6]
    (eta,) = struct.unpack_from("<f", data, 8 + 36)
    # 4 reserved int32s follow eta
    off = 8 + 36 + 4 + 16
    if flags != 1 or n_bases != 1 or n_channels not in (1, 3):
        Error(f'"{path}": unsupported .bsdf layout '
              f"(flags={flags} bases={n_bases} channels={n_channels})")
    mu = np.frombuffer(data, "<f4", n_mu, off)
    off += 4 * n_mu
    cdf = np.frombuffer(data, "<f4", n_mu * n_mu, off).reshape(n_mu, n_mu)
    off += 4 * n_mu * n_mu
    ol = np.frombuffer(data, "<i4", 2 * n_mu * n_mu, off).reshape(-1, 2)
    off += 8 * n_mu * n_mu
    a = np.frombuffer(data, "<f4", n_coeffs, off)
    return FourierTable(
        mu=jnp.asarray(mu),
        cdf=jnp.asarray(cdf),
        a=jnp.asarray(a),
        offset=jnp.asarray(ol[:, 0].copy(), jnp.int32),
        m=jnp.asarray(ol[:, 1].copy(), jnp.int32),
        eta=float(eta),
        n_channels=int(n_channels),
        m_max=int(ol[:, 1].max()) if len(ol) else 1,
    )


def make_table(mu, values, eta=1.0):
    """Build a 1-coefficient-per-pair (phi-constant) table directly —
    the synthetic-table path used by tests (a Lambertian or other
    azimuthally symmetric BSDF needs only a_0)."""
    mu = np.asarray(mu, np.float32)
    n = len(mu)
    vals = np.asarray(values, np.float32).reshape(n, n)
    a = vals.reshape(-1)
    offset = np.arange(n * n, dtype=np.int32)
    m = np.where(np.abs(a) > 0, 1, 0).astype(np.int32)
    # marginal "cdf" rows: cumulative integral of a_0 over muI per muO
    # column, matching pbrt's normalization use in Pdf()
    cdf = np.zeros((n, n), np.float32)
    for o in range(n):
        acc = 0.0
        for i in range(1, n):
            acc += 0.5 * (vals[o, i] + vals[o, i - 1]) * (mu[i] - mu[i - 1])
            cdf[o, i] = acc
    return FourierTable(
        mu=jnp.asarray(mu),
        cdf=jnp.asarray(cdf),
        a=jnp.asarray(a),
        offset=jnp.asarray(offset),
        m=jnp.asarray(m),
        eta=float(eta),
        n_channels=1,
        m_max=1,
    )


def _cos_dphi(wa, wb):
    """CosDPhi (geometry.h): cosine of the azimuth difference."""
    waxy = wa[..., 0] * wb[..., 0] + wa[..., 1] * wb[..., 1]
    la = wa[..., 0] ** 2 + wa[..., 1] ** 2
    lb = wb[..., 0] ** 2 + wb[..., 1] ** 2
    denom = jnp.sqrt(jnp.maximum(la * lb, 1e-20))
    return jnp.clip(jnp.where(denom > 1e-10, waxy / denom, 1.0), -1.0, 1.0)


def _blend_coeffs(tab: FourierTable, mu_i, mu_o):
    """Catmull-Rom blend of the 16 neighbouring coefficient runs ->
    (R, n_channels, m_max) dense coefficient rows + validity."""
    n_mu = tab.mu.shape[0]
    ii, *wis = catmull_rom_weights(tab.mu, mu_i)
    io, *wos = catmull_rom_weights(tab.mu, mu_o)
    mmax = tab.m_max
    nc = tab.n_channels
    ak = jnp.zeros(mu_i.shape + (nc, mmax), jnp.float32)
    k = jnp.arange(mmax, dtype=jnp.int32)
    for a_ in range(4):
        for b in range(4):
            # weight slot a applies to knot (interval - 1 + a)
            w = wos[b] * wis[a_]
            idx = jnp.clip(
                (io - 1 + b) * n_mu + (ii - 1 + a_), 0, n_mu * n_mu - 1
            )
            start = tab.offset[idx]
            mlen = tab.m[idx]
            for c in range(nc):
                pos = jnp.clip(
                    start[..., None] + c * mlen[..., None] + k,
                    0, tab.a.shape[0] - 1,
                )
                run = jnp.where(k < mlen[..., None], tab.a[pos], 0.0)
                ak = ak.at[..., c, :].add(w[..., None] * run)
    return ak


def fourier_f_pdf(tab: FourierTable, wo, wi):
    """FourierBSDF::f and ::Pdf (reflection.cpp) for a batch of local
    directions. Returns (f (R,3), pdf (R,))."""
    mu_i = -wi[..., 2]
    mu_o = wo[..., 2]
    cos_phi = _cos_dphi(-wi, wo)
    ak = _blend_coeffs(tab, mu_i, mu_o)
    mmax = tab.m_max
    y = jnp.maximum(fourier(ak[..., 0, :], cos_phi, mmax), 0.0)
    scale = jnp.where(
        jnp.abs(mu_i) > 1e-6, 1.0 / jnp.maximum(jnp.abs(mu_i), 1e-6), 0.0
    )
    # radiance transport: scale transmission by 1/eta^2 of the side
    trans = mu_i * mu_o > 0.0  # pbrt muI = cos(-wi): same-sign = trans
    eta_d = jnp.where(mu_i > 0.0, 1.0 / tab.eta, tab.eta)
    scale = scale * jnp.where(trans, eta_d * eta_d, 1.0)
    if tab.n_channels == 1:
        f = jnp.stack([y, y, y], axis=-1) * scale[..., None]
    else:
        r = fourier(ak[..., 1, :], cos_phi, mmax)
        b = fourier(ak[..., 2, :], cos_phi, mmax)
        g = 1.39829 * y - 0.100913 * b - 0.297375 * r
        f = (
            jnp.stack([r, g, b], axis=-1)
            * scale[..., None]
        )
    f = jnp.maximum(f, 0.0)

    # pdf of the two-sided cosine sampler this module uses (NOT pbrt's
    # SampleFourier pdf): |cos|/pi split across hemispheres
    pdf = jnp.abs(wi[..., 2]) / jnp.pi * 0.5
    return f, pdf


def fourier_sample_wi(wo, u_lobe, u1, u2):
    """Two-sided cosine draw (see module docstring deviation note)."""
    from tpu_pbrt.core.sampling import cosine_sample_hemisphere

    wi = cosine_sample_hemisphere(u1, u2)
    flip = u_lobe < 0.5
    wi = jnp.where(flip[..., None], wi * jnp.asarray([1.0, 1.0, -1.0]), wi)
    # keep wi on a side independent of wo's (both hemispheres covered)
    return wi
