"""Participating media: transmittance, distance sampling, phase functions.

Capability match for pbrt-v3:
- src/core/medium.{h,cpp}: Medium::Tr/Sample interfaces, HenyeyGreenstein
  phase function (p(cos), Sample_p), and the measured subsurface medium
  presets (GetMediumScatteringProperties — the ~60 entries reduce to the
  handful the target scenes use; others fall back with a warning).
- src/media/homogeneous.cpp: closed-form Beer-Lambert Tr, spectral channel
  distance sampling with the 1/n channel-average pdf.
- src/media/grid.cpp GridDensityMedium: trilinearly interpolated density,
  ratio-tracking Tr and delta-tracking distance sampling, lowered to
  bounded lax.while_loop (the TPU equivalent of the reference's
  unbounded while loops).

Media are a SoA table (type enum + sigma_a/sigma_s/g) plus an optional
density grid; rays carry a current-medium id (-1 = vacuum).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core.sampling import uniform_float
from tpu_pbrt.core.vecmath import coordinate_system, dot, normalize
from tpu_pbrt.utils.error import Warning

MEDIUM_NONE = -1
MEDIUM_HOMOGENEOUS = 0
MEDIUM_GRID = 1

# pbrt medium.cpp SubsurfaceParameterTable (sigma_prime_s, sigma_a) —
# the entries plausibly used by the target configs
MEDIUM_PRESETS = {
    "milk": (np.array([2.55, 3.21, 3.77]), np.array([0.0011, 0.0024, 0.014])),
    "skimmilk": (np.array([0.70, 1.22, 1.90]), np.array([0.0014, 0.0025, 0.0142])),
    "wholemilk": (np.array([2.55, 3.21, 3.77]), np.array([0.0011, 0.0024, 0.014])),
    "skin1": (np.array([0.74, 0.88, 1.01]), np.array([0.032, 0.17, 0.48])),
    "skin2": (np.array([1.09, 1.59, 1.79]), np.array([0.013, 0.070, 0.145])),
    "marble": (np.array([2.19, 2.62, 3.00]), np.array([0.0021, 0.0041, 0.0071])),
    "cream": (np.array([7.38, 5.47, 3.15]), np.array([0.0002, 0.0028, 0.0163])),
    "ketchup": (np.array([0.18, 0.07, 0.03]), np.array([0.061, 0.97, 1.45])),
    "coke": (np.array([0.01, 0.01, 0.01]), np.array([0.10014, 0.16503, 0.2468])),
}


class MediumTable(NamedTuple):
    """Device SoA of media rows; grids stored side-band (single grid slot —
    target configs use one heterogeneous medium per scene; extendable to an
    atlas)."""

    mtype: jnp.ndarray  # (M,)
    sigma_a: jnp.ndarray  # (M,3)
    sigma_s: jnp.ndarray  # (M,3)
    g: jnp.ndarray  # (M,)
    # grid medium support
    grid_id: jnp.ndarray  # (M,) -1 or 0
    density: jnp.ndarray  # (D,H,W) or (1,1,1) placeholder
    world_to_medium: jnp.ndarray  # (4,4)
    sigma_t_max: jnp.ndarray  # scalar: majorant for delta tracking


def empty_medium_table() -> MediumTable:
    return MediumTable(
        mtype=jnp.zeros((1,), jnp.int32),
        sigma_a=jnp.zeros((1, 3), jnp.float32),
        sigma_s=jnp.zeros((1, 3), jnp.float32),
        g=jnp.zeros((1,), jnp.float32),
        grid_id=jnp.full((1,), -1, jnp.int32),
        density=jnp.zeros((1, 1, 1), jnp.float32),
        world_to_medium=jnp.eye(4, dtype=jnp.float32),
        sigma_t_max=jnp.float32(0.0),
    )


# -------------------------------------------------------------------------
# Henyey-Greenstein (medium.cpp)
# -------------------------------------------------------------------------

def hg_p(cos_theta, g):
    denom = 1.0 + g * g + 2.0 * g * cos_theta
    return (1.0 / (4.0 * jnp.pi)) * (1.0 - g * g) / (denom * jnp.sqrt(jnp.maximum(denom, 1e-9)))


def hg_sample(wo, g, u1, u2):
    """HenyeyGreenstein::Sample_p: returns (wi, pdf=p)."""
    g_safe = jnp.where(jnp.abs(g) < 1e-3, jnp.where(g < 0, -1e-3, 1e-3), g)
    sq = (1.0 - g_safe * g_safe) / (1.0 + g_safe - 2.0 * g_safe * u1)
    cos_theta_hg = -(1.0 + g_safe * g_safe - sq * sq) / (2.0 * g_safe)
    cos_theta = jnp.where(jnp.abs(g) < 1e-3, 1.0 - 2.0 * u1, cos_theta_hg)
    sin_theta = jnp.sqrt(jnp.maximum(0.0, 1.0 - cos_theta * cos_theta))
    phi = 2.0 * jnp.pi * u2
    # build frame around wo (pbrt samples w.r.t. wo direction)
    v1, v2 = coordinate_system(wo)
    wi = (
        sin_theta[..., None] * jnp.cos(phi)[..., None] * v1
        + sin_theta[..., None] * jnp.sin(phi)[..., None] * v2
        + cos_theta[..., None] * wo
    )
    return wi, hg_p(cos_theta, g)


# -------------------------------------------------------------------------
# Grid density lookup (media/grid.cpp GridDensityMedium::Density)
# -------------------------------------------------------------------------

def grid_density(mt: MediumTable, p_world):
    """Trilinear density at world points (vectorized)."""
    m = mt.world_to_medium
    p = p_world @ m[:3, :3].T + m[:3, 3]
    d, h, w = mt.density.shape
    # medium space is [0,1]^3 over the grid
    gx = p[..., 0] * w - 0.5
    gy = p[..., 1] * h - 0.5
    gz = p[..., 2] * d - 0.5
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    z0 = jnp.floor(gz).astype(jnp.int32)
    fx, fy, fz = gx - x0, gy - y0, gz - z0

    def tap(xi, yi, zi):
        inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h) & (zi >= 0) & (zi < d)
        v = mt.density[jnp.clip(zi, 0, d - 1), jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
        return jnp.where(inb, v, 0.0)

    d00 = tap(x0, y0, z0) * (1 - fx) + tap(x0 + 1, y0, z0) * fx
    d10 = tap(x0, y0 + 1, z0) * (1 - fx) + tap(x0 + 1, y0 + 1, z0) * fx
    d01 = tap(x0, y0, z0 + 1) * (1 - fx) + tap(x0 + 1, y0, z0 + 1) * fx
    d11 = tap(x0, y0 + 1, z0 + 1) * (1 - fx) + tap(x0 + 1, y0 + 1, z0 + 1) * fx
    d0 = d00 * (1 - fy) + d10 * fy
    d1 = d01 * (1 - fy) + d11 * fy
    inside = (p[..., 0] >= 0) & (p[..., 0] <= 1) & (p[..., 1] >= 0) & (p[..., 1] <= 1) & (
        p[..., 2] >= 0
    ) & (p[..., 2] <= 1)
    return jnp.where(inside, d0 * (1 - fz) + d1 * fz, 0.0)


_MAX_TRACKING_STEPS = 256


def medium_tr(mt: MediumTable, med_id, o, d, t_max, px, py, s, salt):
    """Medium::Tr along [0, t_max] for each ray's current medium.

    Homogeneous: exp(-sigma_t * t). Grid: ratio tracking with the grid
    majorant (grid.cpp GridDensityMedium::Tr), bounded steps."""
    active = med_id >= 0
    idx = jnp.maximum(med_id, 0)
    sig_t = mt.sigma_a[idx] + mt.sigma_s[idx]
    t_cl = jnp.minimum(t_max, 1e7)  # avoid inf * 0
    tr_homog = jnp.exp(-sig_t * t_cl[..., None])

    if int(mt.density.size) > 1:
        inv_max = 1.0 / jnp.maximum(mt.sigma_t_max, 1e-9)
        sig_t1 = sig_t[..., 0]  # grid media are monochromatic-sigma in pbrt

        def body(i, carry):
            t, tr = carry
            u = uniform_float(px, py, s, salt + 3000 + i)
            t = t - jnp.log(1.0 - u) * inv_max
            dens = grid_density(mt, o + t[..., None] * d)
            live = t < t_max
            tr = jnp.where(live, tr * (1.0 - jnp.maximum(0.0, dens * sig_t1 * inv_max)), tr)
            return t, tr

        t0 = jnp.zeros_like(t_cl)
        tr0 = jnp.ones_like(t_cl)
        _, tr_grid = jax.lax.fori_loop(0, _MAX_TRACKING_STEPS, body, (t0, tr0))
        is_grid = mt.mtype[idx] == MEDIUM_GRID
        tr = jnp.where(is_grid[..., None], tr_grid[..., None], tr_homog)
    else:
        tr = tr_homog
    return jnp.where(active[..., None], tr, 1.0)


class MediumSample(NamedTuple):
    sampled_medium: jnp.ndarray  # (R,) bool — interaction inside the medium
    t: jnp.ndarray  # (R,) interaction distance
    weight: jnp.ndarray  # (R,3) beta multiplier (Tr*sigma_s/pdf or Tr/pdf)


def medium_sample(mt: MediumTable, med_id, o, d, t_hit, px, py, s, salt) -> MediumSample:
    """Medium::Sample along a ray segment ending at the surface hit t_hit.

    Homogeneous (homogeneous.cpp): pick a spectral channel uniformly,
    sample an exponential distance, weight by Tr*sigma_s/pdf (medium) or
    Tr/pdf (surface). Grid (grid.cpp): delta tracking against the majorant."""
    active = med_id >= 0
    idx = jnp.maximum(med_id, 0)
    sig_a = mt.sigma_a[idx]
    sig_s = mt.sigma_s[idx]
    sig_t = sig_a + sig_s
    t_end = jnp.minimum(t_hit, 1e7)

    # ---- homogeneous ----------------------------------------------------
    uc = uniform_float(px, py, s, salt)
    ud = uniform_float(px, py, s, salt + 1)
    ch = jnp.minimum((uc * 3).astype(jnp.int32), 2)
    sig_ch = jnp.take_along_axis(sig_t, ch[..., None], axis=-1)[..., 0]
    t_s = -jnp.log(jnp.maximum(1.0 - ud, 1e-20)) / jnp.maximum(sig_ch, 1e-20)
    in_medium_h = (t_s < t_end) & (sig_ch > 0)
    t_m = jnp.minimum(t_s, t_end)
    tr = jnp.exp(-sig_t * t_m[..., None])
    # pdf: average over channels
    pdf_m = jnp.mean(sig_t * tr, axis=-1)
    pdf_surf = jnp.mean(tr, axis=-1)
    w_medium = tr * sig_s / jnp.maximum(pdf_m, 1e-20)[..., None]
    w_surface = tr / jnp.maximum(pdf_surf, 1e-20)[..., None]
    weight_h = jnp.where(in_medium_h[..., None], w_medium, w_surface)

    if int(mt.density.size) > 1:
        # ---- grid: delta tracking --------------------------------------
        inv_max = 1.0 / jnp.maximum(mt.sigma_t_max, 1e-9)
        sig_t1 = sig_t[..., 0]
        albedo = sig_s[..., 0] / jnp.maximum(sig_t1, 1e-20)

        def body(i, carry):
            t, done, hit_med = carry
            u1 = uniform_float(px, py, s, salt + 5000 + 2 * i)
            u2 = uniform_float(px, py, s, salt + 5001 + 2 * i)
            t_new = t - jnp.log(1.0 - u1) * inv_max
            esc = t_new >= t_end
            dens = grid_density(mt, o + t_new[..., None] * d)
            real = u2 < dens * sig_t1 * inv_max
            newly_done = ~done & (esc | real)
            hit_med = jnp.where(~done & real & ~esc, True, hit_med)
            t = jnp.where(done, t, t_new)
            return t, done | newly_done, hit_med

        t0 = jnp.zeros_like(t_end)
        f0 = jnp.zeros_like(t_end, dtype=bool)
        t_g, _, hit_med_g = jax.lax.fori_loop(0, _MAX_TRACKING_STEPS, body, (t0, f0, f0))
        is_grid = mt.mtype[idx] == MEDIUM_GRID
        in_medium = jnp.where(is_grid, hit_med_g, in_medium_h)
        t_m = jnp.where(is_grid, jnp.minimum(t_g, t_end), t_m)
        # delta tracking weight: sigma_s/sigma_t on real collision, 1 on escape
        w_grid = jnp.where(hit_med_g[..., None], albedo[..., None].repeat(3, -1), 1.0)
        weight = jnp.where(is_grid[..., None], w_grid, weight_h)
    else:
        in_medium = in_medium_h
        weight = weight_h

    in_medium = in_medium & active
    weight = jnp.where(active[..., None], weight, 1.0)
    return MediumSample(in_medium, t_m, weight)
