"""Reconstruction filters.

Capability match for pbrt-v3 src/filters/ (box, triangle, gaussian,
mitchell, sinc) and src/core/filter.h. Filters are evaluated exactly
(pbrt's 16x16 lookup table is a CPU-cache optimization; on TPU the exact
evaluation fuses into the film scatter and is both faster and more
accurate). A filter is a (name, radius_x, radius_y, params) spec whose
evaluate() is jit-traceable.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp

from tpu_pbrt.utils.error import Warning


class FilterSpec(NamedTuple):
    name: str  # static — selects the evaluate path at trace time
    xwidth: float
    ywidth: float
    p0: float  # gaussian alpha | mitchell B | sinc tau
    p1: float  # mitchell C

    def evaluate(self, dx, dy):
        """Filter value at offset (dx, dy) from the filter center; batched."""
        ax, ay = jnp.abs(dx), jnp.abs(dy)
        inside = (ax <= self.xwidth) & (ay <= self.ywidth)
        if self.name == "box":
            val = jnp.ones_like(dx)
        elif self.name == "triangle":
            val = jnp.maximum(0.0, self.xwidth - ax) * jnp.maximum(0.0, self.ywidth - ay)
        elif self.name == "gaussian":
            alpha = self.p0

            def g(d, r):
                expv = math.exp(-alpha * r * r)
                return jnp.maximum(0.0, jnp.exp(-alpha * d * d) - expv)

            val = g(dx, self.xwidth) * g(dy, self.ywidth)
        elif self.name == "mitchell":
            b, c = self.p0, self.p1

            def m1d(x):
                x = jnp.abs(2.0 * x)
                near = (
                    (12.0 - 9.0 * b - 6.0 * c) * x**3
                    + (-18.0 + 12.0 * b + 6.0 * c) * x**2
                    + (6.0 - 2.0 * b)
                ) * (1.0 / 6.0)
                far = (
                    (-b - 6.0 * c) * x**3
                    + (6.0 * b + 30.0 * c) * x**2
                    + (-12.0 * b - 48.0 * c) * x
                    + (8.0 * b + 24.0 * c)
                ) * (1.0 / 6.0)
                return jnp.where(x > 1.0, jnp.where(x < 2.0, far, 0.0), near)

            val = m1d(dx / self.xwidth) * m1d(dy / self.ywidth)
        elif self.name == "sinc":
            tau = self.p0

            def ws(x, radius):
                x = jnp.abs(x)

                def sinc(v):
                    v = jnp.abs(v)
                    return jnp.where(v < 1e-5, 1.0, jnp.sin(jnp.pi * v) / (jnp.pi * v))

                lanczos = sinc(x / tau)
                return jnp.where(x > radius, 0.0, sinc(x) * lanczos)

            val = ws(dx, self.xwidth) * ws(dy, self.ywidth)
        else:
            val = jnp.ones_like(dx)
        return jnp.where(inside, val, 0.0)


def make_filter(name: str, params) -> FilterSpec:
    """api.cpp MakeFilter (string-dispatched Create*Filter factories)."""
    if name == "box":
        return FilterSpec(
            "box",
            params.find_one_float("xwidth", 0.5),
            params.find_one_float("ywidth", 0.5),
            0.0,
            0.0,
        )
    if name == "triangle":
        return FilterSpec(
            "triangle",
            params.find_one_float("xwidth", 2.0),
            params.find_one_float("ywidth", 2.0),
            0.0,
            0.0,
        )
    if name == "gaussian":
        return FilterSpec(
            "gaussian",
            params.find_one_float("xwidth", 2.0),
            params.find_one_float("ywidth", 2.0),
            params.find_one_float("alpha", 2.0),
            0.0,
        )
    if name == "mitchell":
        return FilterSpec(
            "mitchell",
            params.find_one_float("xwidth", 2.0),
            params.find_one_float("ywidth", 2.0),
            params.find_one_float("B", 1.0 / 3.0),
            params.find_one_float("C", 1.0 / 3.0),
        )
    if name in ("sinc", "lanczossinc", "lanczos"):
        return FilterSpec(
            "sinc",
            params.find_one_float("xwidth", 4.0),
            params.find_one_float("ywidth", 4.0),
            params.find_one_float("tau", 3.0),
            0.0,
        )
    Warning(f'Filter "{name}" unknown; using box.')
    return FilterSpec("box", 0.5, 0.5, 0.0, 0.0)
