"""Device-side vector math on SoA jnp arrays.

Capability match for pbrt-v3 src/core/geometry.h's vector/point/normal
operations, re-expressed TPU-first: no Vector3 classes — everything is a
float32 array whose last axis is xyz, so all ops vectorize over ray batches.
Also carries the robust-offset machinery standing in for src/core/efloat.h
(conservative fixed epsilons instead of running error intervals; see
offset_ray_origin).
"""

from __future__ import annotations

import jax.numpy as jnp

# float32 machine epsilon / 2 (pbrt MachineEpsilon)
MACHINE_EPS = 5.960464477539063e-08
ONE_MINUS_EPSILON = 0.99999994  # largest float32 < 1
INF = jnp.inf


def gamma(n: int) -> float:
    """pbrt gamma(n): bound on accumulated fp rounding error."""
    return (n * MACHINE_EPS) / (1 - n * MACHINE_EPS)


def dot(a, b):
    return jnp.sum(a * b, axis=-1)


def absdot(a, b):
    return jnp.abs(dot(a, b))


def cross(a, b):
    return jnp.cross(a, b)


def length_squared(v):
    return jnp.sum(v * v, axis=-1)


def length(v):
    return jnp.sqrt(length_squared(v))


def normalize(v):
    return v / jnp.maximum(length(v)[..., None], 1e-20)


def distance(a, b):
    return length(a - b)


def lerp(t, a, b):
    return (1.0 - t) * a + t * b


def face_forward(n, v):
    """Flip n to lie in the hemisphere of v (pbrt Faceforward)."""
    return jnp.where(dot(n, v)[..., None] < 0.0, -n, n)


def coordinate_system(v):
    """Branchless orthonormal basis (Duff et al. 2017), replacing pbrt's
    CoordinateSystem. v must be normalized. Returns (t, b)."""
    z = v[..., 2]
    sign = jnp.where(z >= 0.0, 1.0, -1.0)
    a = -1.0 / (sign + z)
    b = v[..., 0] * v[..., 1] * a
    t1 = jnp.stack(
        [1.0 + sign * v[..., 0] * v[..., 0] * a, sign * b, -sign * v[..., 0]], axis=-1
    )
    t2 = jnp.stack([b, sign + v[..., 1] * v[..., 1] * a, -v[..., 1]], axis=-1)
    return t1, t2


def spherical_direction(sin_theta, cos_theta, phi):
    return jnp.stack(
        [sin_theta * jnp.cos(phi), sin_theta * jnp.sin(phi), cos_theta], axis=-1
    )


def spherical_theta(v):
    return jnp.arccos(jnp.clip(v[..., 2], -1.0, 1.0))


def spherical_phi(v):
    p = jnp.arctan2(v[..., 1], v[..., 0])
    return jnp.where(p < 0.0, p + 2.0 * jnp.pi, p)


def to_local(v, t, b, n):
    """World -> shading frame (pbrt BSDF::WorldToLocal)."""
    return jnp.stack([dot(v, t), dot(v, b), dot(v, n)], axis=-1)


def to_world(v, t, b, n):
    return (
        v[..., 0:1] * t + v[..., 1:2] * b + v[..., 2:3] * n
    )


def reflect(wo, n):
    """pbrt Reflect: mirror wo about n (both pointing away from surface)."""
    return -wo + 2.0 * dot(wo, n)[..., None] * n


def refract(wi, n, eta):
    """pbrt Refract. Returns (refracted_dir, total_internal_reflection_mask).
    eta = eta_i/eta_t (scalar or batched); n on same side as wi."""
    eta = jnp.asarray(eta)
    cos_theta_i = dot(n, wi)
    sin2_theta_i = jnp.maximum(0.0, 1.0 - cos_theta_i * cos_theta_i)
    sin2_theta_t = eta * eta * sin2_theta_i
    tir = sin2_theta_t >= 1.0
    cos_theta_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin2_theta_t))
    wt = eta[..., None] * -wi + (eta * cos_theta_i - cos_theta_t)[..., None] * n
    return wt, tir


def offset_ray_origin(p, n, d):
    """Robust shadow/secondary ray origin.

    pbrt's OffsetRayOrigin uses per-intersection error bounds from EFloat;
    the TPU build uses a conservative scale-adaptive epsilon (SURVEY.md §7
    'efloat machinery becomes fixed conservative epsilons'): offset along the
    geometric normal proportional to |p|, in the hemisphere of d."""
    eps = 1e-4 * jnp.maximum(1.0, jnp.max(jnp.abs(p), axis=-1))
    sign = jnp.where(dot(n, d) >= 0.0, 1.0, -1.0)
    return p + (sign * eps)[..., None] * n


# -- shading-frame trig (pbrt reflection.h inline helpers) ---------------
# all operate on directions in the local frame where n = (0,0,1)

def cos_theta(w):
    return w[..., 2]


def cos2_theta(w):
    return w[..., 2] * w[..., 2]


def abs_cos_theta(w):
    return jnp.abs(w[..., 2])


def sin2_theta(w):
    return jnp.maximum(0.0, 1.0 - cos2_theta(w))


def sin_theta(w):
    return jnp.sqrt(sin2_theta(w))


def tan_theta(w):
    return sin_theta(w) / jnp.where(jnp.abs(cos_theta(w)) < 1e-8, 1e-8, cos_theta(w))


def tan2_theta(w):
    c2 = cos2_theta(w)
    return sin2_theta(w) / jnp.maximum(c2, 1e-12)


def cos_phi(w):
    s = sin_theta(w)
    return jnp.where(s == 0.0, 1.0, jnp.clip(w[..., 0] / jnp.maximum(s, 1e-12), -1.0, 1.0))


def sin_phi(w):
    s = sin_theta(w)
    return jnp.where(s == 0.0, 0.0, jnp.clip(w[..., 1] / jnp.maximum(s, 1e-12), -1.0, 1.0))


def same_hemisphere(w, wp):
    return w[..., 2] * wp[..., 2] > 0.0
