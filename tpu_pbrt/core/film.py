"""Film: filter-weighted sample accumulation into a framebuffer.

Capability match for pbrt-v3 src/core/film.{h,cpp}: Film (full-res pixel
array with crop window, filter-weighted xyz + filterWeightSum + splat
planes, scale / maxsampleluminance, diagonal), FilmTile/MergeFilmTile and
AddSplat.

TPU-first redesign: there are no tiles-as-objects and no mutexes/atomics.
The film is a functional pytree (rgb, weight, splat arrays); a batch of
samples lands via a statically-unrolled footprint of masked scatter-adds
(XLA lowers `at[].add` to deterministic scatter), and "merge" is just `+`
(or a psum across devices) because accumulation is associative. FilmTile
semantics (crop-window restriction) fall out of rendering only a tile's
pixel batch. This replaces the mutex-guarded Film::MergeFilmTile and the
AtomicFloat splats (SURVEY.md §5.2).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core.filters import FilterSpec, make_filter
from tpu_pbrt.core.spectrum import luminance
from tpu_pbrt.utils.error import Error, Warning


class FilmState(NamedTuple):
    """The accumulation buffers — a pure pytree; merging two states is
    elementwise addition (associative, so psum-able across a mesh)."""

    rgb: jnp.ndarray  # (H, W, 3) filter-weighted radiance sums
    weight: jnp.ndarray  # (H, W) filter weight sums
    splat: jnp.ndarray  # (H, W, 3) unweighted splats (BDPT/MLT/SPPM)


def merge_film(a: FilmState, b: FilmState) -> FilmState:
    """Film::MergeFilmTile, functional form."""
    return FilmState(a.rgb + b.rgb, a.weight + b.weight, a.splat + b.splat)


def nonfinite_mask(L) -> jnp.ndarray:
    """Rows of a (..., 3) radiance batch carrying any NaN/Inf component.

    The non-finite FIREWALL's shared predicate (ISSUE 5): every deposit
    path zeroes these rows before accumulation (the scrub half — pbrt's
    AddSample NaN drop, extended to Inf), and callers that carry a
    telemetry block count the same mask into the `nonfinite_deposits`
    counter — one predicate, so the scrub and the count can never
    disagree. One contaminated wave therefore cannot poison the film
    (NaN + x = NaN would otherwise spread to every later checkpoint),
    and the contamination is visible instead of silent."""
    return jnp.any(~jnp.isfinite(jnp.asarray(L, jnp.float32)), axis=-1)


@partial(jax.jit, static_argnums=(0, 1))
def _init_state_jit(ry: int, rx: int) -> FilmState:
    return FilmState(
        rgb=jnp.zeros((ry, rx, 3), jnp.float32),
        weight=jnp.zeros((ry, rx), jnp.float32),
        splat=jnp.zeros((ry, rx, 3), jnp.float32),
    )


class Film:
    """Host-side film configuration + the jit-traceable accumulation ops."""

    def __init__(
        self,
        resolution=(1280, 720),
        crop_window=(0.0, 1.0, 0.0, 1.0),
        filt: Optional[FilterSpec] = None,
        diagonal_mm: float = 35.0,
        filename: str = "pbrt.exr",
        scale: float = 1.0,
        max_sample_luminance: float = float("inf"),
    ):
        self.full_resolution = (int(resolution[0]), int(resolution[1]))
        self.filter = filt or FilterSpec("box", 0.5, 0.5, 0.0, 0.0)
        self.diagonal = diagonal_mm * 0.001
        self.filename = filename
        self.scale = scale
        self.max_sample_luminance = max_sample_luminance
        x0, x1, y0, y1 = crop_window
        rx, ry = self.full_resolution
        # pbrt Film ctor: croppedPixelBounds from the crop window
        self.cropped_pixel_bounds = (
            int(math.ceil(rx * x0)),
            int(math.ceil(rx * x1)),
            int(math.ceil(ry * y0)),
            int(math.ceil(ry * y1)),
        )
        if (
            self.cropped_pixel_bounds[1] <= self.cropped_pixel_bounds[0]
            or self.cropped_pixel_bounds[3] <= self.cropped_pixel_bounds[2]
        ):
            Error("Degenerate crop window")

    # -- sample bounds (Film::GetSampleBounds) ----------------------------
    def sample_bounds(self):
        """Pixel-area bounds that samples must cover so the filter is fed
        at the crop edges."""
        fx, fy = self.filter.xwidth, self.filter.ywidth
        x0, x1, y0, y1 = self.cropped_pixel_bounds
        return (
            int(math.floor(x0 + 0.5 - fx)),
            int(math.ceil(x1 - 0.5 + fx)),
            int(math.floor(y0 + 0.5 - fy)),
            int(math.ceil(y1 - 0.5 + fy)),
        )

    def physical_extent(self):
        """Film::GetPhysicalExtent (meters), for RealisticCamera/light We."""
        rx, ry = self.full_resolution
        aspect = ry / rx
        x = math.sqrt(self.diagonal * self.diagonal / (1 + aspect * aspect))
        y = aspect * x
        return (-x / 2, x / 2, -y / 2, y / 2)

    # -- device ops -------------------------------------------------------
    def init_state(self) -> FilmState:
        rx, ry = self.full_resolution
        # jitted creation: eager jnp.zeros stages an implicit
        # host->device scalar transfer, which the jaxpr audit's
        # transfer_guard("disallow") smoke render treats as an error;
        # inside jit the zeros are compile-time constants
        return _init_state_jit(ry, rx)

    def add_samples(self, state: FilmState, p_film, L, ray_weight=None) -> FilmState:
        """FilmTile::AddSample over a batch. p_film: (R,2) raster coords,
        L: (R,3). Static filter footprint of masked scatter-adds."""
        f = self.filter
        L = jnp.asarray(L, jnp.float32)
        # pbrt: drop NaNs, clamp to maxSampleLuminance
        bad = nonfinite_mask(L)
        L = jnp.where(bad[..., None], 0.0, L)
        if np.isfinite(self.max_sample_luminance):
            y = luminance(L)
            s = jnp.where(
                y > self.max_sample_luminance, self.max_sample_luminance / jnp.maximum(y, 1e-20), 1.0
            )
            L = L * s[..., None]
        if ray_weight is not None:
            L = L * jnp.asarray(ray_weight, jnp.float32)[..., None]

        # discrete coords: pixel (x,y) has its sample center at x+0.5.
        # x0f/y0f stay f32 next to their int32 twins: ceil() is exact on
        # integer-valued f32, so feeding the filter from the float copy
        # is bit-identical to re-converting the ints — and deletes the
        # f32->i32->f32 round trip the cost pass flagged
        # (JC-CHURN:film.add_samples: two convert passes per footprint tap)
        dx = p_film[..., 0] - 0.5
        dy = p_film[..., 1] - 0.5
        x0f = jnp.ceil(dx - f.xwidth)
        y0f = jnp.ceil(dy - f.ywidth)
        x0 = x0f.astype(jnp.int32)
        y0 = y0f.astype(jnp.int32)
        nx = int(math.floor(2 * f.xwidth)) + 1
        ny = int(math.floor(2 * f.ywidth)) + 1
        rx, ryres = self.full_resolution
        cx0, cx1, cy0, cy1 = self.cropped_pixel_bounds

        rgb, wsum = state.rgb, state.weight
        for oy in range(ny):
            for ox in range(nx):
                px = x0 + ox
                py = y0 + oy
                fw = f.evaluate((x0f + ox) - dx, (y0f + oy) - dy)
                inb = (px >= cx0) & (px < cx1) & (py >= cy0) & (py < cy1)
                fw = jnp.where(inb, fw, 0.0)
                pxc = jnp.clip(px, 0, rx - 1)
                pyc = jnp.clip(py, 0, ryres - 1)
                rgb = rgb.at[pyc, pxc].add(fw[..., None] * L)
                wsum = wsum.at[pyc, pxc].add(fw)
        return FilmState(rgb, wsum, state.splat)

    def aligned_chunk_pixels(self, chunk: int, spp: int) -> int:
        """Static gate for add_samples_aligned: returns the pixels per
        chunk when the fast path applies (the default box(0.5) filter —
        a one-pixel deposit — full-frame crop, whole-pixel chunks tiling
        the frame exactly), else 0."""
        rx, ry = self.full_resolution
        if not self.pixel_deposit_ok() or spp <= 0 or chunk % spp:
            return 0
        npc = chunk // spp
        return npc if (rx * ry) % npc == 0 else 0

    def add_samples_aligned(
        self, state: FilmState, start_pix, spp: int, p_film, L,
        ray_weight=None,
    ) -> FilmState:
        """add_samples for a chunk of `chunk//spp` CONSECUTIVE pixels
        with spp consecutive samples each (the render loop's layout):
        the per-pixel filter sums become one reshape + axis-sum and the
        film update two contiguous slice-adds — no scatter. Scatter-adds
        of the general path cost ~90 ms per 1M-sample chunk on this
        v5e; this is ~2 ms. Caller must have checked
        aligned_chunk_pixels() != 0 (box(0.5) only).

        Documented deviation: a jitter of EXACTLY 0.0 lands on a pixel
        boundary, where the general path's box filter deposits the
        sample into BOTH adjacent pixels with weight 1; this path
        deposits into the sample's own pixel only. The double deposit
        raises rgb and weight together, so the developed (weighted-mean)
        image is unchanged up to rounding — and the event has ~2^-23
        probability per sample."""
        f = self.filter
        L = jnp.asarray(L, jnp.float32)
        bad = nonfinite_mask(L)
        L = jnp.where(bad[..., None], 0.0, L)
        if np.isfinite(self.max_sample_luminance):
            y = luminance(L)
            s = jnp.where(
                y > self.max_sample_luminance,
                self.max_sample_luminance / jnp.maximum(y, 1e-20), 1.0,
            )
            L = L * s[..., None]
        if ray_weight is not None:
            L = L * jnp.asarray(ray_weight, jnp.float32)[..., None]
        del f  # box(0.5): in-pixel weight is identically 1
        n = L.shape[0]
        npc = n // spp
        contrib = L.reshape(npc, spp, 3).sum(axis=1)
        wadd = jnp.full((npc,), spp, dtype=jnp.float32)
        rx, ry = self.full_resolution
        rgb_flat = state.rgb.reshape(rx * ry, 3)
        w_flat = state.weight.reshape(rx * ry)
        cur = jax.lax.dynamic_slice(rgb_flat, (start_pix, 0), (npc, 3))
        rgb_flat = jax.lax.dynamic_update_slice(
            rgb_flat, cur + contrib, (start_pix, 0)
        )
        curw = jax.lax.dynamic_slice(w_flat, (start_pix,), (npc,))
        w_flat = jax.lax.dynamic_update_slice(
            w_flat, curw + wadd, (start_pix,)
        )
        return FilmState(
            rgb_flat.reshape(ry, rx, 3), w_flat.reshape(ry, rx), state.splat
        )

    def pixel_deposit_ok(self) -> bool:
        """Static gate for add_samples_pixel: box(0.5) filter (one-pixel
        deposit) over the full frame."""
        f = self.filter
        rx, ry = self.full_resolution
        return (
            f.name == "box" and f.xwidth == 0.5 and f.ywidth == 0.5
            and self.cropped_pixel_bounds == (0, rx, 0, ry)
        )

    def add_samples_pixel(
        self, state: FilmState, px, py, L, mask, ray_weight=None
    ) -> FilmState:
        """add_samples for the box(0.5)/full-frame case with KNOWN integer
        pixel coordinates: each masked sample deposits into its own pixel
        with filter weight 1 — two masked scatter-adds instead of the
        general path's filter footprint. Used by the persistent-wavefront
        pool, whose terminated lanes deposit mid-loop and already carry
        (px, py). Shares add_samples_aligned's documented deviation: a
        jitter of exactly 0.0 deposits into the sample's own pixel only,
        where the general footprint path would also hit the boundary
        neighbor (the fixed-batch single-device render takes the aligned
        path, so pool and fixed-batch images stay identical).
        Caller must have checked pixel_deposit_ok()."""
        L = jnp.asarray(L, jnp.float32)
        bad = nonfinite_mask(L)
        L = jnp.where(bad[..., None], 0.0, L)
        if np.isfinite(self.max_sample_luminance):
            y = luminance(L)
            s = jnp.where(
                y > self.max_sample_luminance,
                self.max_sample_luminance / jnp.maximum(y, 1e-20), 1.0,
            )
            L = L * s[..., None]
        if ray_weight is not None:
            L = L * jnp.asarray(ray_weight, jnp.float32)[..., None]
        rx, ryres = self.full_resolution
        pxc = jnp.clip(px, 0, rx - 1)
        pyc = jnp.clip(py, 0, ryres - 1)
        rgb = state.rgb.at[pyc, pxc].add(
            jnp.where(mask[..., None], L, 0.0)
        )
        wsum = state.weight.at[pyc, pxc].add(
            jnp.where(mask, 1.0, 0.0)
        )
        return FilmState(rgb, wsum, state.splat)

    def add_splats(self, state: FilmState, p_film, v) -> FilmState:
        """Film::AddSplat over a batch (no filtering; box deposit)."""
        v = jnp.asarray(v, jnp.float32)
        bad = nonfinite_mask(v)
        v = jnp.where(bad[..., None], 0.0, v)
        if np.isfinite(self.max_sample_luminance):
            y = luminance(v)
            s = jnp.where(
                y > self.max_sample_luminance, self.max_sample_luminance / jnp.maximum(y, 1e-20), 1.0
            )
            v = v * s[..., None]
        px = jnp.floor(p_film[..., 0]).astype(jnp.int32)
        py = jnp.floor(p_film[..., 1]).astype(jnp.int32)
        cx0, cx1, cy0, cy1 = self.cropped_pixel_bounds
        inb = (px >= cx0) & (px < cx1) & (py >= cy0) & (py < cy1)
        v = jnp.where(inb[..., None], v, 0.0)
        rx, ryres = self.full_resolution
        pxc = jnp.clip(px, 0, rx - 1)
        pyc = jnp.clip(py, 0, ryres - 1)
        return FilmState(state.rgb, state.weight, state.splat.at[pyc, pxc].add(v))

    def develop(self, state: FilmState, splat_scale: float = 1.0) -> np.ndarray:
        """Film::WriteImage math: rgb/filterWeightSum + splatScale*splat,
        then `scale`. Returns the cropped (h, w, 3) float32 image."""
        # explicit device_get: develop() runs inside the render loop's
        # jax.transfer_guard("disallow") audit, where an implicit D2H
        # (np.asarray on a device buffer) is a hard error
        rgb = np.asarray(jax.device_get(state.rgb), np.float64)
        w = np.asarray(jax.device_get(state.weight), np.float64)
        splat = np.asarray(jax.device_get(state.splat), np.float64)
        img = rgb / np.maximum(w, 1e-20)[..., None]
        img = np.where(w[..., None] > 0, img, 0.0)
        img = img + splat_scale * splat
        img = img * self.scale
        x0, x1, y0, y1 = self.cropped_pixel_bounds
        return img[y0:y1, x0:x1].astype(np.float32)

    def write_image(self, state: FilmState, splat_scale: float = 1.0, filename: str = ""):
        from tpu_pbrt.utils import imageio

        img = self.develop(state, splat_scale)
        imageio.write_image(filename or self.filename, img)
        return img


def make_film(name: str, params, filt: FilterSpec, options=None) -> Film:
    """api.cpp MakeFilm -> CreateFilm."""
    if name != "image":
        Warning(f'Film "{name}" unknown; using "image".')
    xres = params.find_one_int("xresolution", 1280)
    yres = params.find_one_int("yresolution", 720)
    if options is not None and getattr(options, "quick_render", False):
        xres = max(1, xres // 4)
        yres = max(1, yres // 4)
    crop = (0.0, 1.0, 0.0, 1.0)
    cr = params.find_float("cropwindow")
    if cr is not None and len(cr) == 4:
        crop = (
            min(cr[0], cr[1]), max(cr[0], cr[1]),
            min(cr[2], cr[3]), max(cr[2], cr[3]),
        )
    elif cr is not None:
        Error(f"{len(cr)} values supplied for \"cropwindow\". Expected 4.")
    if options is not None and getattr(options, "crop_window", None):
        c = options.crop_window
        crop = (c[0], c[1], c[2], c[3])
    filename = params.find_one_string("filename", "")
    if options is not None and getattr(options, "image_file", ""):
        if filename:
            Warning(
                f'Output filename supplied on command line, "{options.image_file}" '
                f'is overriding filename provided in scene description file, "{filename}".'
            )
        filename = options.image_file
    if not filename:
        filename = "pbrt.exr"
    return Film(
        resolution=(xres, yres),
        crop_window=crop,
        filt=filt,
        diagonal_mm=params.find_one_float("diagonal", 35.0),
        filename=filename,
        scale=params.find_one_float("scale", 1.0),
        max_sample_luminance=params.find_one_float("maxsampleluminance", float("inf")),
    )
