"""Host-side affine transforms for scene construction.

Covers the capabilities of pbrt-v3 src/core/transform.{h,cpp} and
quaternion.{h,cpp}: Matrix4x4, Transform (with cached inverse),
Translate/Scale/Rotate/LookAt/Perspective/Orthographic constructors, and
AnimatedTransform (matrix decomposition + quaternion slerp for motion blur).

Design note (TPU-first): transforms only exist on the host during scene
compilation. Everything that reaches the device is already in world space
(triangle vertices) or baked into small matrices (camera raster->world).
float64 is used on the host to keep the compile path precise; arrays are
cast to float32 at scene-compile time.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np


def _as_mat(m) -> np.ndarray:
    a = np.asarray(m, dtype=np.float64)
    if a.shape != (4, 4):
        raise ValueError(f"expected 4x4 matrix, got {a.shape}")
    return a


class Transform:
    """An invertible affine transform: a 4x4 matrix and its inverse."""

    __slots__ = ("m", "m_inv")

    def __init__(self, m=None, m_inv=None):
        if m is None:
            self.m = np.eye(4)
            self.m_inv = np.eye(4)
        else:
            self.m = _as_mat(m)
            self.m_inv = _as_mat(m_inv) if m_inv is not None else np.linalg.inv(self.m)

    # -- composition ------------------------------------------------------
    def __mul__(self, other: "Transform") -> "Transform":
        return Transform(self.m @ other.m, other.m_inv @ self.m_inv)

    def inverse(self) -> "Transform":
        return Transform(self.m_inv, self.m)

    def transpose(self) -> "Transform":
        return Transform(self.m.T, self.m_inv.T)

    def is_identity(self) -> bool:
        return np.allclose(self.m, np.eye(4))

    def __eq__(self, other):
        return isinstance(other, Transform) and np.array_equal(self.m, other.m)

    def __repr__(self):
        return f"Transform({self.m.tolist()})"

    def swaps_handedness(self) -> bool:
        return np.linalg.det(self.m[:3, :3]) < 0

    # -- application (host, numpy; vectorized over leading axes) ----------
    def apply_point(self, p) -> np.ndarray:
        p = np.asarray(p, dtype=np.float64)
        r = p @ self.m[:3, :3].T + self.m[:3, 3]
        w = p @ self.m[3, :3].T + self.m[3, 3]
        w = np.where(w == 0, 1.0, w)
        return r / w[..., None] if np.ndim(w) else (r / w)

    def apply_vector(self, v) -> np.ndarray:
        v = np.asarray(v, dtype=np.float64)
        return v @ self.m[:3, :3].T

    def apply_normal(self, n) -> np.ndarray:
        n = np.asarray(n, dtype=np.float64)
        return n @ self.m_inv[:3, :3]


# -- constructors (pbrt-v3 transform.cpp API surface) ---------------------

def translate(delta) -> Transform:
    d = np.asarray(delta, dtype=np.float64)
    m = np.eye(4)
    m[:3, 3] = d
    mi = np.eye(4)
    mi[:3, 3] = -d
    return Transform(m, mi)


def scale(sx, sy, sz) -> Transform:
    m = np.diag([sx, sy, sz, 1.0])
    mi = np.diag([1.0 / sx, 1.0 / sy, 1.0 / sz, 1.0])
    return Transform(m, mi)


def rotate_x(deg) -> Transform:
    s, c = math.sin(math.radians(deg)), math.cos(math.radians(deg))
    m = np.eye(4)
    m[1, 1], m[1, 2], m[2, 1], m[2, 2] = c, -s, s, c
    return Transform(m, m.T)


def rotate_y(deg) -> Transform:
    s, c = math.sin(math.radians(deg)), math.cos(math.radians(deg))
    m = np.eye(4)
    m[0, 0], m[0, 2], m[2, 0], m[2, 2] = c, s, -s, c
    return Transform(m, m.T)


def rotate_z(deg) -> Transform:
    s, c = math.sin(math.radians(deg)), math.cos(math.radians(deg))
    m = np.eye(4)
    m[0, 0], m[0, 1], m[1, 0], m[1, 1] = c, -s, s, c
    return Transform(m, m.T)


def rotate(deg, axis) -> Transform:
    a = np.asarray(axis, dtype=np.float64)
    a = a / np.linalg.norm(a)
    s, c = math.sin(math.radians(deg)), math.cos(math.radians(deg))
    m = np.eye(4)
    m[0, 0] = a[0] * a[0] + (1 - a[0] * a[0]) * c
    m[0, 1] = a[0] * a[1] * (1 - c) - a[2] * s
    m[0, 2] = a[0] * a[2] * (1 - c) + a[1] * s
    m[1, 0] = a[0] * a[1] * (1 - c) + a[2] * s
    m[1, 1] = a[1] * a[1] + (1 - a[1] * a[1]) * c
    m[1, 2] = a[1] * a[2] * (1 - c) - a[0] * s
    m[2, 0] = a[0] * a[2] * (1 - c) - a[1] * s
    m[2, 1] = a[1] * a[2] * (1 - c) + a[0] * s
    m[2, 2] = a[2] * a[2] + (1 - a[2] * a[2]) * c
    return Transform(m, m.T)


def look_at(eye, look, up) -> Transform:
    """camera-to-world transform (pbrt LookAt semantics: +z toward look)."""
    eye = np.asarray(eye, dtype=np.float64)
    look = np.asarray(look, dtype=np.float64)
    up = np.asarray(up, dtype=np.float64)
    dirv = look - eye
    dirv = dirv / np.linalg.norm(dirv)
    right = np.cross(up / np.linalg.norm(up), dirv)
    nr = np.linalg.norm(right)
    if nr < 1e-12:
        # up parallel to dir; pick an arbitrary perpendicular (pbrt errors here)
        tmp = np.array([1.0, 0, 0]) if abs(dirv[0]) < 0.9 else np.array([0, 1.0, 0])
        right = np.cross(tmp, dirv)
        nr = np.linalg.norm(right)
    right /= nr
    new_up = np.cross(dirv, right)
    cam_to_world = np.eye(4)
    cam_to_world[:3, 0] = right
    cam_to_world[:3, 1] = new_up
    cam_to_world[:3, 2] = dirv
    cam_to_world[:3, 3] = eye
    return Transform(cam_to_world)


def perspective(fov_deg, znear, zfar) -> Transform:
    """Projective camera->screen transform (pbrt transform.cpp Perspective)."""
    persp = np.array(
        [
            [1, 0, 0, 0],
            [0, 1, 0, 0],
            [0, 0, zfar / (zfar - znear), -zfar * znear / (zfar - znear)],
            [0, 0, 1, 0],
        ],
        dtype=np.float64,
    )
    inv_tan = 1.0 / math.tan(math.radians(fov_deg) / 2)
    return scale(inv_tan, inv_tan, 1.0) * Transform(persp)


def orthographic(znear, zfar) -> Transform:
    return scale(1.0, 1.0, 1.0 / (zfar - znear)) * translate([0, 0, -znear])


# -- AnimatedTransform ----------------------------------------------------

def _quat_from_matrix(r: np.ndarray) -> np.ndarray:
    """Rotation matrix -> quaternion (w,x,y,z), Shepperd's method."""
    t = np.trace(r)
    if t > 0:
        w = math.sqrt(t + 1.0) / 2
        s = 1.0 / (4 * w)
        return np.array([w, (r[2, 1] - r[1, 2]) * s, (r[0, 2] - r[2, 0]) * s, (r[1, 0] - r[0, 1]) * s])
    i = int(np.argmax(np.diag(r)))
    j, k = (i + 1) % 3, (i + 2) % 3
    s = math.sqrt(max(0.0, r[i, i] - r[j, j] - r[k, k] + 1.0))
    q = np.zeros(4)
    q[1 + i] = s / 2
    s = 0.5 / s if s != 0 else 0.0
    q[0] = (r[k, j] - r[j, k]) * s
    q[1 + j] = (r[j, i] + r[i, j]) * s
    q[1 + k] = (r[k, i] + r[i, k]) * s
    return q


def _quat_to_matrix(q: np.ndarray) -> np.ndarray:
    w, x, y, z = q
    return np.array(
        [
            [1 - 2 * (y * y + z * z), 2 * (x * y - z * w), 2 * (x * z + y * w)],
            [2 * (x * y + z * w), 1 - 2 * (x * x + z * z), 2 * (y * z - x * w)],
            [2 * (x * z - y * w), 2 * (y * z + x * w), 1 - 2 * (x * x + y * y)],
        ]
    )


def _slerp(t: float, q0: np.ndarray, q1: np.ndarray) -> np.ndarray:
    d = float(np.dot(q0, q1))
    if d < 0:
        q1, d = -q1, -d
    if d > 0.9995:
        q = (1 - t) * q0 + t * q1
    else:
        theta = math.acos(min(1.0, d))
        q = (math.sin((1 - t) * theta) * q0 + math.sin(t * theta) * q1) / math.sin(theta)
    return q / np.linalg.norm(q)


def _decompose(m: np.ndarray):
    """M = T R S per pbrt AnimatedTransform::Decompose (polar decomposition)."""
    t = m[:3, 3].copy()
    upper = m[:3, :3].copy()
    r = upper.copy()
    for _ in range(100):
        r_next = 0.5 * (r + np.linalg.inv(r.T))
        if np.max(np.abs(r_next - r)) < 1e-8:
            r = r_next
            break
        r = r_next
    s = np.linalg.inv(r) @ upper
    return t, _quat_from_matrix(r), s


@dataclass
class AnimatedTransform:
    """Two keyframed transforms with decompose+slerp interpolation.

    Capability match for pbrt-v3 src/core/transform.cpp AnimatedTransform.
    interpolate() is used at scene-compile time to bake per-sample-time
    geometry; motion-blurred primitives get per-time tessellation.
    """

    start: Transform
    end: Transform
    start_time: float = 0.0
    end_time: float = 1.0
    _decomp: tuple = field(init=False, default=None, repr=False)

    @property
    def actually_animated(self) -> bool:
        return not np.allclose(self.start.m, self.end.m)

    def interpolate(self, time: float) -> Transform:
        if not self.actually_animated or time <= self.start_time:
            return self.start
        if time >= self.end_time:
            return self.end
        if self._decomp is None:
            self._decomp = (_decompose(self.start.m), _decompose(self.end.m))
        (t0, q0, s0), (t1, q1, s1) = self._decomp
        dt = (time - self.start_time) / (self.end_time - self.start_time)
        t = (1 - dt) * t0 + dt * t1
        q = _slerp(dt, q0, q1)
        s = (1 - dt) * s0 + dt * s1
        m = np.eye(4)
        m[:3, :3] = _quat_to_matrix(q) @ s
        m[:3, 3] = t
        return Transform(m)


def solve_linear_system_2x2(a, b):
    """pbrt SolveLinearSystem2x2 (used by curve/quadric param solves)."""
    det = a[0][0] * a[1][1] - a[0][1] * a[1][0]
    if abs(det) < 1e-10:
        return None
    x0 = (a[1][1] * b[0] - a[0][1] * b[1]) / det
    x1 = (a[0][0] * b[1] - a[1][0] * b[0]) / det
    return x0, x1
