"""Spline and Fourier interpolation utilities.

Capability match for pbrt-v3 src/core/interpolation.{h,cpp}:
`CatmullRom`, `CatmullRomWeights`, `SampleCatmullRom`, `Fourier`,
`IntegrateCatmullRom`, `InvertCatmullRom` — the numeric machinery behind
FourierBSDF and the tabulated BSSRDF. Implemented batched over jnp arrays
(host-precomputable pieces accept numpy transparently); the find-interval
binary search is a fixed-round masked search (stateless, jit-safe).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def find_interval(xs, x):
    """pbrt FindInterval: largest i with xs[i] <= x, clamped to
    [0, len-2]. xs: (N,) sorted; x: (...,). Fixed-round binary search."""
    n = xs.shape[0]
    lo = jnp.zeros(jnp.shape(x), jnp.int32)
    hi = jnp.full(jnp.shape(x), n - 1, jnp.int32)
    rounds = max(int(np.ceil(np.log2(max(n, 2)))) + 1, 1)
    for _ in range(rounds):
        mid = (lo + hi) // 2
        go_up = xs[mid] <= x
        lo = jnp.where(go_up, mid, lo)
        hi = jnp.where(go_up, hi, mid)
    return jnp.clip(lo, 0, n - 2)


def catmull_rom_weights(xs, x):
    """CatmullRomWeights (interpolation.cpp): returns (offset, w0..w3)
    for the not-a-knot cubic through 4 neighbouring samples. Out-of-range
    x clamps to the boundary interval (weights stay a partition of unity
    for interior nodes; callers mask out-of-domain lookups)."""
    i = find_interval(xs, x)
    x0 = xs[i]
    x1 = xs[i + 1]
    t = (x - x0) / jnp.where(x1 == x0, 1.0, x1 - x0)
    t = jnp.clip(t, 0.0, 1.0)
    t2 = t * t
    t3 = t2 * t
    w1 = 2.0 * t3 - 3.0 * t2 + 1.0
    w2 = -2.0 * t3 + 3.0 * t2
    # endpoint derivative terms, exactly interpolation.cpp's assembly:
    # interior nodes spread the derivative weight onto the prev/next
    # samples; boundary intervals fold it into the one-sided difference
    n = xs.shape[0]
    has_prev = i > 0
    has_next = i + 2 < n
    x_prev = xs[jnp.maximum(i - 1, 0)]
    x_next = xs[jnp.minimum(i + 2, n - 1)]
    d0_scale = (x1 - x0) / jnp.where(has_prev, x1 - x_prev, 1.0)
    d1_scale = (x1 - x0) / jnp.where(has_next, x_next - x0, 1.0)
    w0s = t3 - 2.0 * t2 + t
    w3s = t3 - t2
    w0 = jnp.where(has_prev, -(w0s * d0_scale), 0.0)
    w1 = w1 - jnp.where(has_prev, 0.0, w0s)
    w2 = w2 + jnp.where(has_prev, w0s * d0_scale, w0s)
    w3 = jnp.where(has_next, w3s * d1_scale, 0.0)
    w1 = w1 - jnp.where(has_next, w3s * d1_scale, w3s)
    w2 = w2 + jnp.where(has_next, 0.0, w3s)
    return i, w0, w1, w2, w3


def catmull_rom(xs, fs, x):
    """CatmullRom: spline interpolation of samples fs at nodes xs."""
    i, w0, w1, w2, w3 = catmull_rom_weights(xs, x)
    n = xs.shape[0]
    f_prev = fs[jnp.maximum(i - 1, 0)]
    f0 = fs[i]
    f1 = fs[i + 1]
    f_next = fs[jnp.minimum(i + 2, n - 1)]
    return w0 * f_prev + w1 * f0 + w2 * f1 + w3 * f_next


def integrate_catmull_rom(xs, fs):
    """IntegrateCatmullRom: per-node running integral of the spline (host,
    numpy — it precomputes CDFs for SampleCatmullRom). Returns (cdf (N,),
    total)."""
    xs = np.asarray(xs, np.float64)
    fs = np.asarray(fs, np.float64)
    n = len(xs)
    cdf = np.zeros(n)
    total = 0.0
    for i in range(n - 1):
        x0, x1 = xs[i], xs[i + 1]
        f0, f1 = fs[i], fs[i + 1]
        width = x1 - x0
        # spline derivative estimates (same not-a-knot endpoints)
        if i > 0:
            d0 = width * (f1 - fs[i - 1]) / (x1 - xs[i - 1])
        else:
            d0 = f1 - f0
        if i + 2 < n:
            d1 = width * (fs[i + 2] - f0) / (xs[i + 2] - x0)
        else:
            d1 = f1 - f0
        total += ((d0 - d1) / 12.0 + (f0 + f1) * 0.5) * width
        cdf[i + 1] = total
    return cdf, total


def sample_catmull_rom(xs, fs, cdf, u):
    """SampleCatmullRom: draw x proportional to the (non-negative) spline.
    xs/fs/cdf: (N,) arrays (cdf from integrate_catmull_rom, unnormalized);
    u: (...,) uniforms. Returns (x, f(x), pdf)."""
    xs = jnp.asarray(xs, jnp.float32)
    fs = jnp.asarray(fs, jnp.float32)
    cdf = jnp.asarray(cdf, jnp.float32)
    total = cdf[-1]
    uu = u * total
    i = find_interval(cdf, uu)
    x0 = xs[i]
    x1 = xs[i + 1]
    f0 = fs[i]
    f1 = fs[i + 1]
    width = x1 - x0
    n = xs.shape[0]
    d0 = jnp.where(
        i > 0,
        width * (f1 - fs[jnp.maximum(i - 1, 0)]) / (x1 - xs[jnp.maximum(i - 1, 0)]),
        f1 - f0,
    )
    d1 = jnp.where(
        i + 2 < n,
        width * (fs[jnp.minimum(i + 2, n - 1)] - f0)
        / (xs[jnp.minimum(i + 2, n - 1)] - x0),
        f1 - f0,
    )
    # invert the definite integral with a few Newton-bisection rounds
    # (pbrt's do-while becomes fixed rounds)
    ulocal = (uu - cdf[i]) / jnp.maximum(width, 1e-20)
    t = jnp.where(f0 != f1, (f0 - jnp.sqrt(jnp.maximum(f0 * f0 + 2.0 * ulocal * (f1 - f0), 0.0))) / (f0 - f1), ulocal / jnp.maximum(f0, 1e-20))
    t = jnp.clip(t, 0.0, 1.0)
    a = jnp.zeros_like(t)
    b = jnp.ones_like(t)
    for _ in range(12):
        t2 = t * t
        t3 = t2 * t
        # cubic hermite integral F(t) and value f(t) (expanded basis)
        F = (
            f0 * t
            + d0 * t2 / 2.0
            + (-2.0 * d0 - d1 + 3.0 * (f1 - f0)) * t3 / 3.0
            + (d0 + d1 + 2.0 * (f0 - f1)) * t2 * t2 / 4.0
        )
        fval = (
            f0
            + d0 * t
            + (-2.0 * d0 - d1 + 3.0 * (f1 - f0)) * t2
            + (d0 + d1 + 2.0 * (f0 - f1)) * t3
        )
        too_big = F > ulocal
        b = jnp.where(too_big, t, b)
        a = jnp.where(too_big, a, t)
        newton = t - (F - ulocal) / jnp.where(jnp.abs(fval) < 1e-6, 1e-6, fval)
        in_bracket = (newton > a) & (newton < b)
        t = jnp.where(in_bracket, newton, 0.5 * (a + b))
    t2 = t * t
    t3 = t2 * t
    fval = (
        f0
        + d0 * t
        + (-2.0 * d0 - d1 + 3.0 * (f1 - f0)) * t2
        + (d0 + d1 + 2.0 * (f0 - f1)) * t3
    )
    x = x0 + width * t
    pdf = jnp.maximum(fval, 0.0) / jnp.maximum(total, 1e-20)
    return x, fval, pdf


def fourier(a, cos_phi, m):
    """Fourier (interpolation.cpp): sum_{k<m} a[k] cos(k phi) via the
    double-angle recurrence. a: (..., m_max) coefficient rows; cos_phi:
    (...); m: static int (number of active orders)."""
    a = jnp.asarray(a, jnp.float32)
    value = jnp.zeros(jnp.shape(cos_phi), jnp.float32)
    cos_k_minus = jnp.ones(jnp.shape(cos_phi), jnp.float32) * cos_phi  # cos(1*phi)
    cos_k = jnp.ones(jnp.shape(cos_phi), jnp.float32)  # cos(0*phi)
    for k in range(m):
        value = value + a[..., k] * cos_k
        cos_next = 2.0 * cos_phi * cos_k_minus - cos_k
        cos_k = cos_k_minus
        cos_k_minus = cos_next
    return value
