"""Device-side BSDF evaluation and sampling (tagged-union dispatch).

Capability match for pbrt-v3 src/core/reflection.{h,cpp} and
src/core/microfacet.{h,cpp}:
- Fresnel{Dielectric,Conductor,NoOp}
- LambertianReflection/Transmission, OrenNayar
- SpecularReflection/Transmission, FresnelSpecular
- MicrofacetReflection (TrowbridgeReitz/GGX with visible-normal sampling)
- FresnelBlend (substrate)
and for the per-material BxDF assembly in src/materials/*::
ComputeScatteringFunctions (matte/plastic/metal/glass/mirror/uber/
substrate/translucent/disney lowered to lobe combinations).

TPU-first design: instead of arena-allocated BxDF object stacks with
virtual dispatch, every ray carries its gathered material parameters
(SoA row) and the whole batch evaluates a fixed set of lobe formulas under
masks — a diffuse lobe and a glossy/specular lobe per material, combined
with pbrt's matching-lobe pdf averaging. All directions are in the local
shading frame (z = shading normal). Radiance-mode transport (eta^2 scaling
on specular transmission) matches pbrt's TransportMode::Radiance.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from tpu_pbrt.core.vecmath import (
    abs_cos_theta,
    cos2_theta,
    cos_phi,
    cos_theta,
    same_hemisphere,
    sin2_theta,
    sin_phi,
    tan2_theta,
    tan_theta,
)
from tpu_pbrt.core.sampling import (
    concentric_sample_disk,
    cosine_hemisphere_pdf,
    cosine_sample_hemisphere,
)
from tpu_pbrt.scene.compiler import (
    MAT_DISNEY,
    MAT_GLASS,
    MAT_MATTE,
    MAT_METAL,
    MAT_MIRROR,
    MAT_NONE,
    MAT_PLASTIC,
    MAT_SUBSTRATE,
    MAT_TRANSLUCENT,
    MAT_UBER,
    MAT_FOURIER,
    MAT_HAIR,
    MAT_SUBSURFACE,
)

_INV_PI = 1.0 / jnp.pi


# -------------------------------------------------------------------------
# Fresnel (reflection.cpp FrDielectric / FrConductor)
# -------------------------------------------------------------------------

def fresnel_dielectric(cos_i, eta_i, eta_t):
    """Unpolarized dielectric Fresnel; handles entering/exiting by sign."""
    cos_i = jnp.clip(cos_i, -1.0, 1.0)
    entering = cos_i > 0.0
    ei = jnp.where(entering, eta_i, eta_t)
    et = jnp.where(entering, eta_t, eta_i)
    ci = jnp.abs(cos_i)
    sin_t = ei / et * jnp.sqrt(jnp.maximum(0.0, 1.0 - ci * ci))
    tir = sin_t >= 1.0
    ct = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin_t * sin_t))
    r_parl = (et * ci - ei * ct) / jnp.maximum(et * ci + ei * ct, 1e-20)
    r_perp = (ei * ci - et * ct) / jnp.maximum(ei * ci + et * ct, 1e-20)
    fr = 0.5 * (r_parl * r_parl + r_perp * r_perp)
    return jnp.where(tir, 1.0, fr)


def fresnel_conductor(cos_i, eta, k):
    """reflection.cpp FrConductor (per-channel; eta,k (...,3))."""
    ci = jnp.clip(jnp.abs(cos_i), 0.0, 1.0)[..., None]
    c2 = ci * ci
    s2 = 1.0 - c2
    e2 = eta * eta
    k2 = k * k
    t0 = e2 - k2 - s2
    a2b2 = jnp.sqrt(jnp.maximum(t0 * t0 + 4.0 * e2 * k2, 0.0))
    t1 = a2b2 + c2
    a = jnp.sqrt(jnp.maximum(0.5 * (a2b2 + t0), 0.0))
    t2 = 2.0 * a * ci
    rs = (t1 - t2) / jnp.maximum(t1 + t2, 1e-20)
    t3 = c2 * a2b2 + s2 * s2
    t4 = t2 * s2
    rp = rs * (t3 - t4) / jnp.maximum(t3 + t4, 1e-20)
    return 0.5 * (rp + rs)


# -------------------------------------------------------------------------
# Trowbridge-Reitz / GGX microfacet distribution (microfacet.cpp)
# -------------------------------------------------------------------------

# -------------------------------------------------------------------------
# Beckmann distribution (microfacet.cpp BeckmannDistribution) — D, Lambda,
# and full-distribution half-vector sampling (the non-visible-normal
# Sample_wh branch, exact for isotropic and anisotropic alphas).
# -------------------------------------------------------------------------

def beckmann_d(wh, ax, ay):
    t2 = tan2_theta(wh)
    c4 = cos2_theta(wh) ** 2
    e = jnp.exp(
        -t2 * (cos_phi(wh) ** 2 / jnp.maximum(ax * ax, 1e-12)
               + sin_phi(wh) ** 2 / jnp.maximum(ay * ay, 1e-12))
    )
    d = e / (jnp.pi * ax * ay * jnp.maximum(c4, 1e-16))
    return jnp.where(jnp.isfinite(t2) & (c4 > 1e-16), d, 0.0)


def beckmann_lambda(w, ax, ay):
    abs_tan = jnp.abs(tan_theta(w))
    alpha = jnp.sqrt(cos_phi(w) ** 2 * ax * ax + sin_phi(w) ** 2 * ay * ay)
    a = 1.0 / jnp.maximum(alpha * abs_tan, 1e-12)
    lam = (1.0 - 1.259 * a + 0.396 * a * a) / (3.535 * a + 2.181 * a * a)
    return jnp.where(jnp.isfinite(abs_tan) & (a < 1.6), lam, 0.0)


def beckmann_g(wo, wi, ax, ay):
    return 1.0 / (1.0 + beckmann_lambda(wo, ax, ay) + beckmann_lambda(wi, ax, ay))


def beckmann_sample_wh(u1, u2, ax, ay):
    """Full-distribution Beckmann Sample_wh (microfacet.cpp, the
    !sampleVisibleArea branch): tan2 = -a^2 log(1-u1) with per-phi alpha
    for the anisotropic case."""
    log_u = jnp.log(jnp.maximum(1.0 - u1, 1e-12))
    phi = jnp.arctan(ay / ax * jnp.tan(2.0 * jnp.pi * u2 + 0.5 * jnp.pi))
    phi = phi + jnp.where(u2 > 0.5, jnp.pi, 0.0)
    sp, cp = jnp.sin(phi), jnp.cos(phi)
    a2 = 1.0 / jnp.maximum(cp * cp / jnp.maximum(ax * ax, 1e-12)
                           + sp * sp / jnp.maximum(ay * ay, 1e-12), 1e-12)
    tan2 = -log_u * a2
    ct = 1.0 / jnp.sqrt(1.0 + tan2)
    st = jnp.sqrt(jnp.maximum(0.0, 1.0 - ct * ct))
    return jnp.stack([st * cp, st * sp, ct], axis=-1)


def beckmann_pdf(wh, ax, ay):
    """pdf of wh under full-distribution sampling: D(wh) |cos wh|."""
    return beckmann_d(wh, ax, ay) * abs_cos_theta(wh)


def tr_roughness_to_alpha(rough):
    """TrowbridgeReitzDistribution::RoughnessToAlpha."""
    rough = jnp.maximum(rough, 1e-3)
    x = jnp.log(rough)
    return (
        1.62142
        + 0.819955 * x
        + 0.1734 * x * x
        + 0.0171201 * x * x * x
        + 0.000640711 * x * x * x * x
    )


def tr_d(wh, ax, ay):
    t2 = tan2_theta(wh)
    c4 = cos2_theta(wh) ** 2
    e = (cos_phi(wh) ** 2 / jnp.maximum(ax * ax, 1e-12) + sin_phi(wh) ** 2 / jnp.maximum(ay * ay, 1e-12)) * t2
    d = 1.0 / (jnp.pi * ax * ay * c4 * (1.0 + e) ** 2)
    return jnp.where(jnp.isfinite(t2) & (c4 > 1e-16), d, 0.0)


def tr_lambda(w, ax, ay):
    abs_tan = jnp.abs(tan_theta(w))
    alpha = jnp.sqrt(cos_phi(w) ** 2 * ax * ax + sin_phi(w) ** 2 * ay * ay)
    a2t2 = (alpha * abs_tan) ** 2
    lam = (-1.0 + jnp.sqrt(1.0 + a2t2)) / 2.0
    return jnp.where(jnp.isfinite(abs_tan), lam, 0.0)


def tr_g(wo, wi, ax, ay):
    return 1.0 / (1.0 + tr_lambda(wo, ax, ay) + tr_lambda(wi, ax, ay))


def tr_g1(w, ax, ay):
    return 1.0 / (1.0 + tr_lambda(w, ax, ay))


def _tr_sample11(cos_t, u1, u2):
    """TrowbridgeReitzSample11: slopes for visible-normal sampling."""
    # special case: normal incidence
    sin_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - cos_t * cos_t))
    tan_t = sin_t / jnp.maximum(cos_t, 1e-7)
    a = 1.0 / jnp.maximum(tan_t, 1e-12)
    g1 = 2.0 / (1.0 + jnp.sqrt(1.0 + 1.0 / jnp.maximum(a * a, 1e-20)))

    # pbrt TrowbridgeReitzSample11 verbatim: tmp = 1/(A^2-1) is NEGATIVE
    # for |A| < 1 and that sign is load-bearing — negating it (an earlier
    # "sanity" tweak) collapsed every u1 < 0.5 sample onto the horizon
    # (tr_d = 0), silently killing half of all VNDF samples
    A = 2.0 * u1 / jnp.maximum(g1, 1e-12) - 1.0
    denom = A * A - 1.0
    tmp = 1.0 / jnp.where(jnp.abs(denom) < 1e-12, jnp.where(denom < 0, -1e-12, 1e-12), denom)
    tmp = jnp.minimum(tmp, 1e10)
    B = tan_t
    D = jnp.sqrt(jnp.maximum(B * B * tmp * tmp - (A * A - B * B) * tmp, 0.0))
    slope_x_1 = B * tmp - D
    slope_x_2 = B * tmp + D
    slope_x = jnp.where((A < 0) | (slope_x_2 > 1.0 / jnp.maximum(tan_t, 1e-12)), slope_x_1, slope_x_2)

    S = jnp.where(u2 > 0.5, 1.0, -1.0)
    u2r = jnp.where(u2 > 0.5, 2.0 * (u2 - 0.5), 2.0 * (0.5 - u2))
    z = (u2r * (u2r * (u2r * 0.27385 - 0.73369) + 0.46341)) / (
        u2r * (u2r * (u2r * 0.093073 + 0.309420) - 1.000000) + 0.597999
    )
    slope_y = S * z * jnp.sqrt(1.0 + slope_x * slope_x)

    # normal incidence fallback
    r = jnp.sqrt(jnp.maximum(u1 / jnp.maximum(1.0 - u1, 1e-12), 0.0))
    phi = 6.28318530718 * u2
    ni = cos_t > 0.9999
    slope_x = jnp.where(ni, r * jnp.cos(phi), slope_x)
    slope_y = jnp.where(ni, r * jnp.sin(phi), slope_y)
    return slope_x, slope_y


def tr_sample_wh(wo, u1, u2, ax, ay):
    """Visible-normal sampling (TrowbridgeReitzDistribution::Sample_wh)."""
    flip = cos_theta(wo) < 0.0
    wo_f = jnp.where(flip[..., None], -wo, wo)
    # stretch
    wi_s = jnp.stack([ax * wo_f[..., 0], ay * wo_f[..., 1], wo_f[..., 2]], axis=-1)
    ln = jnp.sqrt(jnp.sum(wi_s * wi_s, axis=-1))
    wi_s = wi_s / jnp.maximum(ln[..., None], 1e-20)
    ct = jnp.clip(wi_s[..., 2], -1.0, 1.0)
    s_len = jnp.sqrt(jnp.maximum(0.0, 1.0 - ct * ct))
    cphi = jnp.where(s_len < 1e-7, 1.0, wi_s[..., 0] / jnp.maximum(s_len, 1e-12))
    sphi = jnp.where(s_len < 1e-7, 0.0, wi_s[..., 1] / jnp.maximum(s_len, 1e-12))
    sx, sy = _tr_sample11(ct, u1, u2)
    # rotate
    tmp = cphi * sx - sphi * sy
    sy = sphi * sx + cphi * sy
    sx = tmp
    # unstretch
    sx = sx * ax
    sy = sy * ay
    wh = jnp.stack([-sx, -sy, jnp.ones_like(sx)], axis=-1)
    wh = wh / jnp.sqrt(jnp.sum(wh * wh, axis=-1))[..., None]
    return jnp.where(flip[..., None], -wh, wh)


def tr_pdf(wo, wh, ax, ay):
    """pdf of wh under visible-normal sampling."""
    return (
        tr_d(wh, ax, ay)
        * tr_g1(wo, ax, ay)
        * jnp.abs(jnp.sum(wo * wh, axis=-1))
        / jnp.maximum(abs_cos_theta(wo), 1e-12)
    )


# -------------------------------------------------------------------------
# Material parameter gather
# -------------------------------------------------------------------------

class MatParams(NamedTuple):
    mtype: jnp.ndarray  # (R,)
    kd: jnp.ndarray  # (R,3)
    ks: jnp.ndarray
    kr: jnp.ndarray
    kt: jnp.ndarray
    eta: jnp.ndarray  # (R,3)
    k: jnp.ndarray
    ax: jnp.ndarray  # (R,) GGX alphas (post-remap)
    ay: jnp.ndarray
    sigma: jnp.ndarray  # oren-nayar sigma (degrees) / disney metallic
    opacity: jnp.ndarray
    rough_raw: jnp.ndarray  # (R,) raw (pre-remap) roughness; 0 = smooth


def gather_mat(mat: dict, mid) -> MatParams:
    from tpu_pbrt.core.smalltab import small_take

    remap = small_take(mat["remap"], mid)
    ru = small_take(mat["rough_u"], mid)
    rv = small_take(mat["rough_v"], mid)
    ax = jnp.where(remap > 0, tr_roughness_to_alpha(ru), jnp.maximum(ru, 1e-3))
    ay = jnp.where(remap > 0, tr_roughness_to_alpha(rv), jnp.maximum(rv, 1e-3))
    return MatParams(
        mtype=small_take(mat["type"], mid),
        kd=small_take(mat["kd"], mid),
        ks=small_take(mat["ks"], mid),
        kr=small_take(mat["kr"], mid),
        kt=small_take(mat["kt"], mid),
        eta=small_take(mat["eta"], mid),
        k=small_take(mat["k"], mid),
        ax=ax,
        ay=ay,
        sigma=small_take(mat["sigma"], mid),
        opacity=small_take(mat["opacity"], mid),
        # glass.cpp activates the microfacet lobes when EITHER axis is
        # rough (urough != 0 || vrough != 0)
        rough_raw=jnp.maximum(ru, rv),
    )


def _lobe_flags(mp: MatParams):
    """(has_diffuse, has_glossy, is_specular_lobe, has_transmission)."""
    t = mp.mtype
    diffuse = (
        (t == MAT_MATTE)
        | (t == MAT_PLASTIC)
        | (t == MAT_UBER)
        | (t == MAT_TRANSLUCENT)
        | (t == MAT_DISNEY)
        | (t == MAT_HAIR)
        | (t == MAT_FOURIER)
        | (t == MAT_SUBSURFACE)
    )
    glossy = (
        (t == MAT_PLASTIC) | (t == MAT_METAL) | (t == MAT_UBER) | (t == MAT_SUBSTRATE) | (t == MAT_DISNEY)
        # rough glass is a real (non-delta) microfacet BSDF: SPPM stores
        # visible points on glossy surfaces at the depth cap, and
        # bsdf_eval/bsdf_sample override rg lanes wholesale, so flagging
        # it glossy here cannot double-count lobes
        | _is_rough_glass(mp)
    )
    specular = ((t == MAT_GLASS) & ~_is_rough_glass(mp)) | (t == MAT_MIRROR)
    return diffuse, glossy, specular


# -------------------------------------------------------------------------
# Lobe formulas (batched, local frame)
# -------------------------------------------------------------------------

def _diffuse_f(mp: MatParams, wo, wi):
    """Lambertian or Oren-Nayar by sigma; reflection hemisphere only."""
    refl = same_hemisphere(wo, wi)
    sigma = jnp.radians(mp.sigma)
    s2 = sigma * sigma
    a = 1.0 - s2 / (2.0 * (s2 + 0.33))
    b = 0.45 * s2 / (s2 + 0.09)
    sin_to = jnp.sqrt(sin2_theta(wo))
    sin_ti = jnp.sqrt(sin2_theta(wi))
    # max(0, cos(phi_i - phi_o))
    cos_dphi = cos_phi(wi) * cos_phi(wo) + sin_phi(wi) * sin_phi(wo)
    max_cos = jnp.maximum(0.0, cos_dphi)
    has_sin = (sin_to > 1e-4) & (sin_ti > 1e-4)
    max_cos = jnp.where(has_sin, max_cos, 0.0)
    abs_ci = abs_cos_theta(wi)
    abs_co = abs_cos_theta(wo)
    sin_alpha = jnp.where(abs_ci > abs_co, sin_to, sin_ti)
    tan_beta = jnp.where(
        abs_ci > abs_co,
        sin_ti / jnp.maximum(abs_ci, 1e-7),
        sin_to / jnp.maximum(abs_co, 1e-7),
    )
    on = a + b * max_cos * sin_alpha * tan_beta
    is_on = mp.sigma > 0.0
    base = jnp.where(is_on, on, 1.0)
    # translucent diffuse transmission: kd*kt on the opposite hemisphere
    trans_scale = jnp.where(
        (mp.mtype == MAT_TRANSLUCENT)[..., None], mp.kt, jnp.zeros_like(mp.kt)
    )
    refl_scale = jnp.where(
        (mp.mtype == MAT_TRANSLUCENT)[..., None], mp.kr, jnp.ones_like(mp.kr)
    )
    f_refl = mp.kd * (_INV_PI * base)[..., None] * refl_scale
    f_trans = mp.kd * _INV_PI * trans_scale
    return jnp.where(refl[..., None], f_refl, f_trans)


def _diffuse_pdf(mp: MatParams, wo, wi):
    refl = same_hemisphere(wo, wi)
    pdf_r = cosine_hemisphere_pdf(abs_cos_theta(wi))
    is_transl = mp.mtype == MAT_TRANSLUCENT
    # translucent splits the cosine pdf across both hemispheres
    return jnp.where(
        refl, jnp.where(is_transl, 0.5 * pdf_r, pdf_r), jnp.where(is_transl, 0.5 * pdf_r, 0.0)
    )


def _glossy_f(mp: MatParams, wo, wi):
    """Microfacet reflection lobe (or FresnelBlend for substrate)."""
    refl = same_hemisphere(wo, wi)
    wh = wi + wo
    wh_len = jnp.sqrt(jnp.sum(wh * wh, axis=-1))
    valid = refl & (wh_len > 1e-12) & (abs_cos_theta(wi) > 1e-7) & (abs_cos_theta(wo) > 1e-7)
    wh = wh / jnp.maximum(wh_len[..., None], 1e-20)
    d = tr_d(wh, mp.ax, mp.ay)
    g = tr_g(wo, wi, mp.ax, mp.ay)
    cos_wh = jnp.sum(wi * wh, axis=-1)
    is_metal = mp.mtype == MAT_METAL
    eta_s = mp.eta[..., 0]
    f_cond = fresnel_conductor(cos_wh, mp.eta, mp.k)
    f_diel = fresnel_dielectric(cos_wh, jnp.ones_like(eta_s), eta_s)[..., None]
    F = jnp.where(is_metal[..., None], f_cond, f_diel)
    scale = jnp.where(is_metal[..., None], jnp.ones_like(mp.ks), mp.ks)
    denom = 4.0 * abs_cos_theta(wi) * abs_cos_theta(wo)
    f_mf = scale * F * (d * g / jnp.maximum(denom, 1e-12))[..., None]

    # FresnelBlend (substrate): Ashikhmin-Shirley diffuse+spec
    is_sub = mp.mtype == MAT_SUBSTRATE
    pow5 = lambda v: (v * v) * (v * v) * v  # noqa: E731
    diff = (
        (28.0 / (23.0 * jnp.pi))
        * mp.kd
        * (1.0 - mp.ks)
        * (1.0 - pow5(1.0 - 0.5 * abs_cos_theta(wi)))[..., None]
        * (1.0 - pow5(1.0 - 0.5 * abs_cos_theta(wo)))[..., None]
    )
    schlick = mp.ks + pow5(1.0 - cos_wh)[..., None] * (1.0 - mp.ks)
    spec = (
        d
        / jnp.maximum(4.0 * jnp.abs(cos_wh) * jnp.maximum(abs_cos_theta(wi), abs_cos_theta(wo)), 1e-12)
    )[..., None] * schlick
    f_sub = diff + spec

    f = jnp.where(is_sub[..., None], f_sub, f_mf)
    return jnp.where(valid[..., None], f, 0.0)


def _glossy_pdf(mp: MatParams, wo, wi):
    refl = same_hemisphere(wo, wi)
    wh = wi + wo
    wh_len = jnp.sqrt(jnp.sum(wh * wh, axis=-1))
    wh = wh / jnp.maximum(wh_len[..., None], 1e-20)
    pdf_wh = tr_pdf(wo, wh, mp.ax, mp.ay)
    pdf = pdf_wh / jnp.maximum(4.0 * jnp.sum(wo * wh, axis=-1), 1e-12)
    is_sub = mp.mtype == MAT_SUBSTRATE
    # FresnelBlend pdf: average of cosine and half-vector pdfs
    pdf_sub = 0.5 * (cosine_hemisphere_pdf(abs_cos_theta(wi)) + pdf)
    pdf = jnp.where(is_sub, pdf_sub, pdf)
    return jnp.where(refl & (wh_len > 1e-12), pdf, 0.0)


#: raw roughness above this makes glass a microfacet (non-delta) surface
#: (glass.cpp: rough glass builds MicrofacetReflection/Transmission)
ROUGH_GLASS_MIN = 1e-4


def _is_rough_glass(mp: MatParams):
    return (mp.mtype == MAT_GLASS) & (mp.rough_raw > ROUGH_GLASS_MIN)


def _refract_about(wo, wh, eta_rel):
    """Refract wo about microfacet normal wh (faced toward wo);
    eta_rel = eta_incident / eta_transmitted. Returns (wi, tir)."""
    wh_f = jnp.where((jnp.sum(wo * wh, axis=-1) < 0.0)[..., None], -wh, wh)
    ci = jnp.sum(wo * wh_f, axis=-1)
    sin2t = eta_rel * eta_rel * jnp.maximum(0.0, 1.0 - ci * ci)
    tir = sin2t >= 1.0
    ctt = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin2t))
    wi = eta_rel[..., None] * -wo + (eta_rel * ci - ctt)[..., None] * wh_f
    return wi, tir


def _mf_glass_terms(mp: MatParams, wo, wi, wh):
    """The MicrofacetReflection + MicrofacetTransmission formulas
    (reflection.cpp ::f/::Pdf) evaluated at an EXPLICIT half-vector —
    the single source both bsdf_eval (reconstructed whs) and bsdf_sample
    (the drawn wh) share, so the MIS pdfs cannot drift apart. wh is
    faceforwarded to +z internally (TIR via the signed Fresnel cosine).
    pdfs carry pbrt's uniform 2-lobe component weight (0.5 each).
    Radiance transport: transmission carries the 1/eta^2 scale.
    Returns (f_refl, pdf_refl, ok_refl, f_trans, pdf_trans, ok_trans)."""
    eta_s = mp.eta[..., 0]
    refl = same_hemisphere(wo, wi)
    ci = abs_cos_theta(wi)
    co = abs_cos_theta(wo)
    ok_angles = (ci > 1e-7) & (co > 1e-7)
    wh_z = jnp.where((wh[..., 2] < 0.0)[..., None], -wh, wh)
    do_h = jnp.sum(wo * wh_z, axis=-1)
    di_h = jnp.sum(wi * wh_z, axis=-1)
    d = tr_d(wh_z, mp.ax, mp.ay)
    g = tr_g(wo, wi, mp.ax, mp.ay)
    pdf_wh = tr_pdf(wo, wh_z, mp.ax, mp.ay)
    F = fresnel_dielectric(do_h, jnp.ones_like(eta_s), eta_s)

    f_refl = mp.kr * (d * g * F / jnp.maximum(4.0 * ci * co, 1e-12))[..., None]
    pdf_refl = 0.5 * pdf_wh / jnp.maximum(4.0 * jnp.abs(do_h), 1e-12)
    ok_refl = refl & ok_angles

    # eta = etaT/etaI of the transmitted side (MicrofacetTransmission)
    eta_t = jnp.where(cos_theta(wo) > 0.0, eta_s, 1.0 / jnp.maximum(eta_s, 1e-6))
    sqrt_denom = do_h + eta_t * di_h
    factor = 1.0 / jnp.maximum(eta_t, 1e-6)  # radiance transport scale
    f_trans = mp.kt * jnp.abs(
        d * g * eta_t * eta_t * (1.0 - F) * jnp.abs(di_h) * jnp.abs(do_h)
        * factor * factor
        / jnp.maximum(ci * co * sqrt_denom * sqrt_denom, 1e-12)
    )[..., None]
    dwh_dwi = jnp.abs(eta_t * eta_t * di_h) / jnp.maximum(
        sqrt_denom * sqrt_denom, 1e-12
    )
    pdf_trans = 0.5 * pdf_wh * dwh_dwi
    ok_trans = (~refl) & ok_angles & (do_h * di_h < 0.0)
    return f_refl, pdf_refl, ok_refl, f_trans, pdf_trans, ok_trans


def _rough_glass_f_pdf(mp: MatParams, wo, wi):
    """Eval path: reconstruct each lobe's half-vector from (wo, wi) —
    wo+wi for reflection, the generalized wo + eta*wi for transmission —
    then evaluate the shared terms at each."""
    eta_s = mp.eta[..., 0]
    wh_r = wi + wo
    whr_len = jnp.sqrt(jnp.sum(wh_r * wh_r, axis=-1))
    wh_rn = wh_r / jnp.maximum(whr_len[..., None], 1e-20)
    f_r, p_r, ok_r, _, _, _ = _mf_glass_terms(mp, wo, wi, wh_rn)
    ok_r = ok_r & (whr_len > 1e-12)

    eta_t = jnp.where(cos_theta(wo) > 0.0, eta_s, 1.0 / jnp.maximum(eta_s, 1e-6))
    wh_t = wo + wi * eta_t[..., None]
    wht_len = jnp.sqrt(jnp.sum(wh_t * wh_t, axis=-1))
    wh_tn = wh_t / jnp.maximum(wht_len[..., None], 1e-20)
    _, _, _, f_t, p_t, ok_t = _mf_glass_terms(mp, wo, wi, wh_tn)
    ok_t = ok_t & (wht_len > 1e-12)

    f = jnp.where(ok_r[..., None], f_r, 0.0) + jnp.where(ok_t[..., None], f_t, 0.0)
    pdf = jnp.where(ok_r, p_r, 0.0) + jnp.where(ok_t, p_t, 0.0)
    return f, pdf


# -------------------------------------------------------------------------
# Public API
# -------------------------------------------------------------------------

def bsdf_eval(mp: MatParams, wo, wi):
    """f(wo,wi) and pdf for non-specular lobes (pbrt BSDF::f / BSDF::Pdf
    with BSDF_ALL & ~SPECULAR: specular lobes contribute zero)."""
    has_d, has_g, is_spec = _lobe_flags(mp)
    f = jnp.zeros_like(mp.kd)
    pdf = jnp.zeros_like(mp.ax)
    fd = _diffuse_f(mp, wo, wi)
    pd = _diffuse_pdf(mp, wo, wi)
    fg = _glossy_f(mp, wo, wi)
    pg = _glossy_pdf(mp, wo, wi)
    f = jnp.where(has_d[..., None], fd, 0.0) + jnp.where(has_g[..., None], fg, 0.0)
    n_lobes = has_d.astype(jnp.float32) + has_g.astype(jnp.float32)
    pdf = (jnp.where(has_d, pd, 0.0) + jnp.where(has_g, pg, 0.0)) / jnp.maximum(n_lobes, 1.0)
    # rough (microfacet) glass is a real non-delta BSDF (glass.cpp)
    rg = _is_rough_glass(mp)
    f_rg, pdf_rg = _rough_glass_f_pdf(mp, wo, wi)
    f = jnp.where(rg[..., None], f_rg, f)
    pdf = jnp.where(rg, pdf_rg, pdf)
    dead = (is_spec & ~rg) | (mp.mtype == MAT_NONE)
    return jnp.where(dead[..., None], 0.0, f), jnp.where(dead, 0.0, pdf)


class BSDFSample(NamedTuple):
    wi: jnp.ndarray  # (R,3) local frame
    f: jnp.ndarray  # (R,3)
    pdf: jnp.ndarray  # (R,)
    is_specular: jnp.ndarray  # (R,) bool
    is_transmission: jnp.ndarray  # (R,) bool


def bsdf_sample(mp: MatParams, wo, u_lobe, u1, u2) -> BSDFSample:
    """BSDF::Sample_f over the batch. u_lobe picks among matching lobes
    (pbrt's uniform component choice); u1,u2 drive the chosen lobe."""
    has_d, has_g, is_spec = _lobe_flags(mp)
    n_lobes = has_d.astype(jnp.int32) + has_g.astype(jnp.int32)
    pick_g = has_g & ((~has_d) | (u_lobe * n_lobes.astype(jnp.float32) >= 1.0))

    # --- diffuse candidate (cosine hemisphere) ---------------------------
    # translucent: u2's low bit picks reflect/transmit, then u2 is remapped
    # to [0,1) so the decision and the disk coordinate are independent —
    # reusing raw u2 for both would cover only half the transmitted disk
    # while _diffuse_pdf claims the full hemisphere (ADVICE r1)
    is_transl = mp.mtype == MAT_TRANSLUCENT
    flip_t = is_transl & (u2 < 0.5)
    u2d = jnp.where(is_transl, jnp.where(u2 < 0.5, 2.0 * u2, 2.0 * (u2 - 0.5)), u2)
    wi_d = cosine_sample_hemisphere(u1, u2d)
    wi_d = jnp.where((cos_theta(wo) < 0.0)[..., None], wi_d * jnp.asarray([1.0, 1.0, -1.0]), wi_d)
    wi_d = jnp.where(flip_t[..., None], wi_d * jnp.asarray([1.0, 1.0, -1.0]), wi_d)

    # --- glossy candidate (VNDF half-vector) -----------------------------
    wh = tr_sample_wh(wo, u1, u2, mp.ax, mp.ay)
    wi_g = -wo + 2.0 * jnp.sum(wo * wh, axis=-1)[..., None] * wh
    # substrate: half the samples are cosine (FresnelBlend::Sample_f)
    is_sub = mp.mtype == MAT_SUBSTRATE
    use_cos = is_sub & (u_lobe < 0.5)
    wi_g = jnp.where(use_cos[..., None], wi_d, wi_g)

    wi = jnp.where(pick_g[..., None], wi_g, wi_d)

    # --- combined f/pdf over matching non-specular lobes -----------------
    f_ns, pdf_ns = bsdf_eval(mp, wo, wi)

    # --- specular materials ---------------------------------------------
    eta_s = mp.eta[..., 0]
    ct_o = cos_theta(wo)
    F = fresnel_dielectric(ct_o, jnp.ones_like(eta_s), eta_s)
    is_glass = mp.mtype == MAT_GLASS
    is_mirror = mp.mtype == MAT_MIRROR
    # mirror: perfect reflection, FresnelNoOp
    wi_mirror = jnp.stack([-wo[..., 0], -wo[..., 1], wo[..., 2]], axis=-1)
    f_mirror = mp.kr / jnp.maximum(abs_cos_theta(wi_mirror), 1e-12)[..., None]
    # glass: choose R/T by Fresnel using u_lobe
    reflect_g = u_lobe < F
    entering = ct_o > 0.0
    ei = jnp.where(entering, 1.0, eta_s)
    et = jnp.where(entering, eta_s, 1.0)
    eta_rel = ei / et
    # refract in local frame about +/- z
    n_loc = jnp.stack(
        [jnp.zeros_like(ct_o), jnp.zeros_like(ct_o), jnp.where(entering, 1.0, -1.0)], axis=-1
    )
    ci = jnp.abs(ct_o)
    sin2_t = eta_rel * eta_rel * jnp.maximum(0.0, 1.0 - ci * ci)
    ct_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin2_t))
    wi_refr = eta_rel[..., None] * -wo + (eta_rel * ci - ct_t)[..., None] * n_loc
    f_refl_g = (F / jnp.maximum(abs_cos_theta(wi_mirror), 1e-12))[..., None] * mp.kr
    # radiance transport: (ei/et)^2 factor
    f_trans_g = (
        ((1.0 - F) * (ei / et) ** 2 / jnp.maximum(jnp.abs(ct_t), 1e-12))[..., None] * mp.kt
    )
    wi_glass = jnp.where(reflect_g[..., None], wi_mirror, wi_refr)
    f_glass = jnp.where(reflect_g[..., None], f_refl_g, f_trans_g)
    pdf_glass = jnp.where(reflect_g, F, 1.0 - F)

    wi = jnp.where(is_mirror[..., None], wi_mirror, wi)
    wi = jnp.where(is_glass[..., None], wi_glass, wi)
    f = jnp.where(is_mirror[..., None], f_mirror, f_ns)
    f = jnp.where(is_glass[..., None], f_glass, f)
    pdf = jnp.where(is_mirror, 1.0, pdf_ns)
    pdf = jnp.where(is_glass, pdf_glass, pdf)

    # --- rough (microfacet) glass: override the delta-glass pick ---------
    # f/pdf come from the SAMPLED half-vector (pbrt Microfacet*::Sample_f
    # computes its pdf from the wh it drew) — reconstructing wh from wi
    # breaks down in f32 for the near-saturated slopes sample11 emits at
    # high alpha (identical degenerate whs -> D = 0 -> dropped samples)
    rg = _is_rough_glass(mp)
    wh_rg = tr_sample_wh(wo, u1, u2, mp.ax, mp.ay)
    refl_pick = u_lobe < 0.5  # pbrt BSDF uniform 2-lobe component choice
    wi_rg_r = -wo + 2.0 * jnp.sum(wo * wh_rg, axis=-1)[..., None] * wh_rg
    ct_o_rg = cos_theta(wo)
    eta_rel_rg = jnp.where(ct_o_rg > 0.0, 1.0 / jnp.maximum(eta_s, 1e-6), eta_s)
    wi_rg_t, tir_rg = _refract_about(wo, wh_rg, eta_rel_rg)
    wi_rg = jnp.where(refl_pick[..., None], wi_rg_r, wi_rg_t)

    f_r, p_r, ok_r2, f_t, p_t, ok_t2 = _mf_glass_terms(mp, wo, wi_rg, wh_rg)
    ok_rg = jnp.where(refl_pick, ok_r2, ok_t2 & ~tir_rg)
    f_rg = jnp.where(refl_pick[..., None], f_r, f_t)
    pdf_rg = jnp.where(refl_pick, p_r, p_t)
    wi = jnp.where(rg[..., None], wi_rg, wi)
    f = jnp.where((rg & ok_rg)[..., None], f_rg, jnp.where(rg[..., None], 0.0, f))
    pdf = jnp.where(rg, jnp.where(ok_rg, pdf_rg, 0.0), pdf)

    is_specular = (is_glass & ~rg) | is_mirror
    is_transmission = (is_glass & ~rg & ~reflect_g) | (flip_t & ~pick_g) | (
        rg & ~same_hemisphere(wo, wi)
    )
    dead = (mp.mtype == MAT_NONE) | (pdf <= 0.0)
    f = jnp.where(dead[..., None], 0.0, f)
    pdf = jnp.where(dead, 0.0, pdf)
    return BSDFSample(wi, f, pdf, is_specular, is_transmission)
