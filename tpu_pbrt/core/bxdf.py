"""Device-side BSDF evaluation and sampling (tagged-union dispatch).

Capability match for pbrt-v3 src/core/reflection.{h,cpp} and
src/core/microfacet.{h,cpp}:
- Fresnel{Dielectric,Conductor,NoOp}
- LambertianReflection/Transmission, OrenNayar
- SpecularReflection/Transmission, FresnelSpecular
- MicrofacetReflection (TrowbridgeReitz/GGX with visible-normal sampling)
- FresnelBlend (substrate)
and for the per-material BxDF assembly in src/materials/*::
ComputeScatteringFunctions (matte/plastic/metal/glass/mirror/uber/
substrate/translucent/disney lowered to lobe combinations).

TPU-first design: instead of arena-allocated BxDF object stacks with
virtual dispatch, every ray carries its gathered material parameters
(SoA row) and the whole batch evaluates a fixed set of lobe formulas under
masks — a diffuse lobe and a glossy/specular lobe per material, combined
with pbrt's matching-lobe pdf averaging. All directions are in the local
shading frame (z = shading normal). Radiance-mode transport (eta^2 scaling
on specular transmission) matches pbrt's TransportMode::Radiance.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from tpu_pbrt.core.vecmath import (
    abs_cos_theta,
    cos2_theta,
    cos_phi,
    cos_theta,
    same_hemisphere,
    sin2_theta,
    sin_phi,
    tan2_theta,
    tan_theta,
)
from tpu_pbrt.core.sampling import (
    concentric_sample_disk,
    cosine_hemisphere_pdf,
    cosine_sample_hemisphere,
)
from tpu_pbrt.scene.compiler import (
    MAT_DISNEY,
    MAT_GLASS,
    MAT_MATTE,
    MAT_METAL,
    MAT_MIRROR,
    MAT_NONE,
    MAT_PLASTIC,
    MAT_SUBSTRATE,
    MAT_TRANSLUCENT,
    MAT_UBER,
    MAT_FOURIER,
    MAT_HAIR,
    MAT_SUBSURFACE,
)

_INV_PI = 1.0 / jnp.pi


# -------------------------------------------------------------------------
# Fresnel (reflection.cpp FrDielectric / FrConductor)
# -------------------------------------------------------------------------

def fresnel_dielectric(cos_i, eta_i, eta_t):
    """Unpolarized dielectric Fresnel; handles entering/exiting by sign."""
    cos_i = jnp.clip(cos_i, -1.0, 1.0)
    entering = cos_i > 0.0
    ei = jnp.where(entering, eta_i, eta_t)
    et = jnp.where(entering, eta_t, eta_i)
    ci = jnp.abs(cos_i)
    sin_t = ei / et * jnp.sqrt(jnp.maximum(0.0, 1.0 - ci * ci))
    tir = sin_t >= 1.0
    ct = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin_t * sin_t))
    r_parl = (et * ci - ei * ct) / jnp.maximum(et * ci + ei * ct, 1e-20)
    r_perp = (ei * ci - et * ct) / jnp.maximum(ei * ci + et * ct, 1e-20)
    fr = 0.5 * (r_parl * r_parl + r_perp * r_perp)
    return jnp.where(tir, 1.0, fr)


def fresnel_conductor(cos_i, eta, k):
    """reflection.cpp FrConductor (per-channel; eta,k (...,3))."""
    ci = jnp.clip(jnp.abs(cos_i), 0.0, 1.0)[..., None]
    c2 = ci * ci
    s2 = 1.0 - c2
    e2 = eta * eta
    k2 = k * k
    t0 = e2 - k2 - s2
    a2b2 = jnp.sqrt(jnp.maximum(t0 * t0 + 4.0 * e2 * k2, 0.0))
    t1 = a2b2 + c2
    a = jnp.sqrt(jnp.maximum(0.5 * (a2b2 + t0), 0.0))
    t2 = 2.0 * a * ci
    rs = (t1 - t2) / jnp.maximum(t1 + t2, 1e-20)
    t3 = c2 * a2b2 + s2 * s2
    t4 = t2 * s2
    rp = rs * (t3 - t4) / jnp.maximum(t3 + t4, 1e-20)
    return 0.5 * (rp + rs)


# -------------------------------------------------------------------------
# Trowbridge-Reitz / GGX microfacet distribution (microfacet.cpp)
# -------------------------------------------------------------------------

# -------------------------------------------------------------------------
# Beckmann distribution (microfacet.cpp BeckmannDistribution) — D, Lambda,
# and full-distribution half-vector sampling (the non-visible-normal
# Sample_wh branch, exact for isotropic and anisotropic alphas).
# -------------------------------------------------------------------------

def beckmann_d(wh, ax, ay):
    t2 = tan2_theta(wh)
    c4 = cos2_theta(wh) ** 2
    e = jnp.exp(
        -t2 * (cos_phi(wh) ** 2 / jnp.maximum(ax * ax, 1e-12)
               + sin_phi(wh) ** 2 / jnp.maximum(ay * ay, 1e-12))
    )
    d = e / (jnp.pi * ax * ay * jnp.maximum(c4, 1e-16))
    return jnp.where(jnp.isfinite(t2) & (c4 > 1e-16), d, 0.0)


def beckmann_lambda(w, ax, ay):
    abs_tan = jnp.abs(tan_theta(w))
    alpha = jnp.sqrt(cos_phi(w) ** 2 * ax * ax + sin_phi(w) ** 2 * ay * ay)
    a = 1.0 / jnp.maximum(alpha * abs_tan, 1e-12)
    lam = (1.0 - 1.259 * a + 0.396 * a * a) / (3.535 * a + 2.181 * a * a)
    return jnp.where(jnp.isfinite(abs_tan) & (a < 1.6), lam, 0.0)


def beckmann_g(wo, wi, ax, ay):
    return 1.0 / (1.0 + beckmann_lambda(wo, ax, ay) + beckmann_lambda(wi, ax, ay))


def beckmann_sample_wh(u1, u2, ax, ay):
    """Full-distribution Beckmann Sample_wh (microfacet.cpp, the
    !sampleVisibleArea branch): tan2 = -a^2 log(1-u1) with per-phi alpha
    for the anisotropic case."""
    log_u = jnp.log(jnp.maximum(1.0 - u1, 1e-12))
    phi = jnp.arctan(ay / ax * jnp.tan(2.0 * jnp.pi * u2 + 0.5 * jnp.pi))
    phi = phi + jnp.where(u2 > 0.5, jnp.pi, 0.0)
    sp, cp = jnp.sin(phi), jnp.cos(phi)
    a2 = 1.0 / jnp.maximum(cp * cp / jnp.maximum(ax * ax, 1e-12)
                           + sp * sp / jnp.maximum(ay * ay, 1e-12), 1e-12)
    tan2 = -log_u * a2
    ct = 1.0 / jnp.sqrt(1.0 + tan2)
    st = jnp.sqrt(jnp.maximum(0.0, 1.0 - ct * ct))
    return jnp.stack([st * cp, st * sp, ct], axis=-1)


def beckmann_pdf(wh, ax, ay):
    """pdf of wh under full-distribution sampling: D(wh) |cos wh|."""
    return beckmann_d(wh, ax, ay) * abs_cos_theta(wh)


def tr_roughness_to_alpha(rough):
    """TrowbridgeReitzDistribution::RoughnessToAlpha."""
    rough = jnp.maximum(rough, 1e-3)
    x = jnp.log(rough)
    return (
        1.62142
        + 0.819955 * x
        + 0.1734 * x * x
        + 0.0171201 * x * x * x
        + 0.000640711 * x * x * x * x
    )


def tr_d(wh, ax, ay):
    t2 = tan2_theta(wh)
    c4 = cos2_theta(wh) ** 2
    e = (cos_phi(wh) ** 2 / jnp.maximum(ax * ax, 1e-12) + sin_phi(wh) ** 2 / jnp.maximum(ay * ay, 1e-12)) * t2
    d = 1.0 / (jnp.pi * ax * ay * c4 * (1.0 + e) ** 2)
    return jnp.where(jnp.isfinite(t2) & (c4 > 1e-16), d, 0.0)


def tr_lambda(w, ax, ay):
    abs_tan = jnp.abs(tan_theta(w))
    alpha = jnp.sqrt(cos_phi(w) ** 2 * ax * ax + sin_phi(w) ** 2 * ay * ay)
    a2t2 = (alpha * abs_tan) ** 2
    lam = (-1.0 + jnp.sqrt(1.0 + a2t2)) / 2.0
    return jnp.where(jnp.isfinite(abs_tan), lam, 0.0)


def tr_g(wo, wi, ax, ay):
    return 1.0 / (1.0 + tr_lambda(wo, ax, ay) + tr_lambda(wi, ax, ay))


def tr_g1(w, ax, ay):
    return 1.0 / (1.0 + tr_lambda(w, ax, ay))


def _tr_sample11(cos_t, u1, u2):
    """TrowbridgeReitzSample11: slopes for visible-normal sampling."""
    # special case: normal incidence
    sin_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - cos_t * cos_t))
    tan_t = sin_t / jnp.maximum(cos_t, 1e-7)
    a = 1.0 / jnp.maximum(tan_t, 1e-12)
    g1 = 2.0 / (1.0 + jnp.sqrt(1.0 + 1.0 / jnp.maximum(a * a, 1e-20)))

    # pbrt TrowbridgeReitzSample11 verbatim: tmp = 1/(A^2-1) is NEGATIVE
    # for |A| < 1 and that sign is load-bearing — negating it (an earlier
    # "sanity" tweak) collapsed every u1 < 0.5 sample onto the horizon
    # (tr_d = 0), silently killing half of all VNDF samples
    A = 2.0 * u1 / jnp.maximum(g1, 1e-12) - 1.0
    denom = A * A - 1.0
    tmp = 1.0 / jnp.where(jnp.abs(denom) < 1e-12, jnp.where(denom < 0, -1e-12, 1e-12), denom)
    tmp = jnp.minimum(tmp, 1e10)
    B = tan_t
    D = jnp.sqrt(jnp.maximum(B * B * tmp * tmp - (A * A - B * B) * tmp, 0.0))
    slope_x_1 = B * tmp - D
    slope_x_2 = B * tmp + D
    slope_x = jnp.where((A < 0) | (slope_x_2 > 1.0 / jnp.maximum(tan_t, 1e-12)), slope_x_1, slope_x_2)

    S = jnp.where(u2 > 0.5, 1.0, -1.0)
    u2r = jnp.where(u2 > 0.5, 2.0 * (u2 - 0.5), 2.0 * (0.5 - u2))
    z = (u2r * (u2r * (u2r * 0.27385 - 0.73369) + 0.46341)) / (
        u2r * (u2r * (u2r * 0.093073 + 0.309420) - 1.000000) + 0.597999
    )
    slope_y = S * z * jnp.sqrt(1.0 + slope_x * slope_x)

    # normal incidence fallback
    r = jnp.sqrt(jnp.maximum(u1 / jnp.maximum(1.0 - u1, 1e-12), 0.0))
    phi = 6.28318530718 * u2
    ni = cos_t > 0.9999
    slope_x = jnp.where(ni, r * jnp.cos(phi), slope_x)
    slope_y = jnp.where(ni, r * jnp.sin(phi), slope_y)
    return slope_x, slope_y


def tr_sample_wh(wo, u1, u2, ax, ay):
    """Visible-normal sampling (TrowbridgeReitzDistribution::Sample_wh)."""
    flip = cos_theta(wo) < 0.0
    wo_f = jnp.where(flip[..., None], -wo, wo)
    # stretch
    wi_s = jnp.stack([ax * wo_f[..., 0], ay * wo_f[..., 1], wo_f[..., 2]], axis=-1)
    ln = jnp.sqrt(jnp.sum(wi_s * wi_s, axis=-1))
    wi_s = wi_s / jnp.maximum(ln[..., None], 1e-20)
    ct = jnp.clip(wi_s[..., 2], -1.0, 1.0)
    s_len = jnp.sqrt(jnp.maximum(0.0, 1.0 - ct * ct))
    cphi = jnp.where(s_len < 1e-7, 1.0, wi_s[..., 0] / jnp.maximum(s_len, 1e-12))
    sphi = jnp.where(s_len < 1e-7, 0.0, wi_s[..., 1] / jnp.maximum(s_len, 1e-12))
    sx, sy = _tr_sample11(ct, u1, u2)
    # rotate
    tmp = cphi * sx - sphi * sy
    sy = sphi * sx + cphi * sy
    sx = tmp
    # unstretch
    sx = sx * ax
    sy = sy * ay
    wh = jnp.stack([-sx, -sy, jnp.ones_like(sx)], axis=-1)
    wh = wh / jnp.sqrt(jnp.sum(wh * wh, axis=-1))[..., None]
    return jnp.where(flip[..., None], -wh, wh)


def tr_pdf(wo, wh, ax, ay):
    """pdf of wh under visible-normal sampling."""
    return (
        tr_d(wh, ax, ay)
        * tr_g1(wo, ax, ay)
        * jnp.abs(jnp.sum(wo * wh, axis=-1))
        / jnp.maximum(abs_cos_theta(wo), 1e-12)
    )


# -------------------------------------------------------------------------
# Material parameter gather
# -------------------------------------------------------------------------

class DisneyParams(NamedTuple):
    """Per-lane Disney 2015 parameters (disney.cpp); present only when
    the scene uses the material (dz field of MatParams is None
    otherwise, and none of the Disney code is traced)."""

    metallic: jnp.ndarray  # (R,)
    spectint: jnp.ndarray
    aniso: jnp.ndarray
    sheen: jnp.ndarray
    sheentint: jnp.ndarray
    clearcoat: jnp.ndarray
    ccgloss: jnp.ndarray
    strans: jnp.ndarray
    flat: jnp.ndarray
    dtrans: jnp.ndarray
    thin: jnp.ndarray  # (R,) bool
    rough: jnp.ndarray  # (R,) raw roughness (disney does NOT remap)


class MatParams(NamedTuple):
    mtype: jnp.ndarray  # (R,)
    kd: jnp.ndarray  # (R,3)
    ks: jnp.ndarray
    kr: jnp.ndarray
    kt: jnp.ndarray
    eta: jnp.ndarray  # (R,3)
    k: jnp.ndarray
    ax: jnp.ndarray  # (R,) GGX alphas (post-remap)
    ay: jnp.ndarray
    sigma: jnp.ndarray  # oren-nayar sigma (degrees)
    opacity: jnp.ndarray
    rough_raw: jnp.ndarray  # (R,) raw (pre-remap) roughness; 0 = smooth
    dz: "DisneyParams | None" = None
    hz: "HairParams | None" = None
    fz: "object | None" = None  # FourierTable (core/fourierbsdf.py)
    sub: "jnp.ndarray | None" = None  # (R,) BSSRDF table row; -1 = none


def resolve_mix(mat: dict, mid, u):
    """MixMaterial (mixmat.cpp) resolution: map a mix-material lane to
    ONE of its sub-material rows with probability `amount` before the
    parameter gather — the one-sample estimator of pbrt's scaled BSDF
    union (f = a*f1 + (1-a)*f2). Conditioned on the pick, the lane runs
    a standard path step under the sub-BSDF, so eval/sample/pdf and the
    MIS weights stay mutually consistent and the outer expectation over
    `u` reproduces the mix exactly (for scalar `amount`; colored
    amounts select by channel mean — warned at compile).

    Static no-op for mix-free scenes (the compiler only emits the
    mix_* columns when a mix exists). Nested mixes resolve through a
    static 4-level loop; `u` is rescaled within the picked branch so
    the levels stay independent."""
    if "mix_a" not in mat or u is None:
        return mid
    from tpu_pbrt.core.smalltab import small_take

    for _ in range(4):
        ma = small_take(mat["mix_a"], mid)
        mb = small_take(mat["mix_b"], mid)
        amt = small_take(mat["mix_amt"], mid)
        is_mix = ma >= 0
        pick_a = u < amt
        mid = jnp.where(is_mix & pick_a, ma, jnp.where(is_mix, mb, mid))
        u = jnp.clip(
            jnp.where(
                pick_a,
                u / jnp.maximum(amt, 1e-8),
                (u - amt) / jnp.maximum(1.0 - amt, 1e-8),
            ),
            0.0,
            0.9999999,
        )
    return mid


def gather_mat(mat: dict, mid) -> MatParams:
    from tpu_pbrt.core.smalltab import small_take

    mtype = small_take(mat["type"], mid)
    sub = None
    if "sub_id" in mat:
        # subsurface materials: the surface BSDF is EXACTLY smooth
        # glass (Fresnel reflect + transmit — subsurface.cpp's specular
        # interface), so lanes remap to MAT_GLASS here and the BSSRDF
        # transport is keyed off `sub` (integrators/path.py probe wave)
        sub = small_take(mat["sub_id"], mid)
        mtype = jnp.where(mtype == MAT_SUBSURFACE, MAT_GLASS, mtype)
    remap = small_take(mat["remap"], mid)
    ru = small_take(mat["rough_u"], mid)
    rv = small_take(mat["rough_v"], mid)
    ax = jnp.where(remap > 0, tr_roughness_to_alpha(ru), jnp.maximum(ru, 1e-3))
    ay = jnp.where(remap > 0, tr_roughness_to_alpha(rv), jnp.maximum(rv, 1e-3))
    return MatParams(
        mtype=mtype,
        kd=small_take(mat["kd"], mid),
        ks=small_take(mat["ks"], mid),
        kr=small_take(mat["kr"], mid),
        kt=small_take(mat["kt"], mid),
        eta=small_take(mat["eta"], mid),
        k=small_take(mat["k"], mid),
        ax=ax,
        ay=ay,
        sigma=small_take(mat["sigma"], mid),
        opacity=small_take(mat["opacity"], mid),
        # glass.cpp activates the microfacet lobes when EITHER axis is
        # rough (urough != 0 || vrough != 0)
        rough_raw=jnp.maximum(ru, rv),
        dz=DisneyParams(
            metallic=small_take(mat["d_metallic"], mid),
            spectint=small_take(mat["d_spectint"], mid),
            aniso=small_take(mat["d_aniso"], mid),
            sheen=small_take(mat["d_sheen"], mid),
            sheentint=small_take(mat["d_sheentint"], mid),
            clearcoat=small_take(mat["d_clearcoat"], mid),
            ccgloss=small_take(mat["d_ccgloss"], mid),
            strans=small_take(mat["d_strans"], mid),
            flat=small_take(mat["d_flat"], mid),
            dtrans=small_take(mat["d_dtrans"], mid),
            thin=small_take(mat["d_thin"], mid) > 0,
            rough=ru,
        ) if "d_metallic" in mat else None,
        hz=HairParams(
            sigma_a=small_take(mat["h_sigma_a"], mid),
            beta_m=small_take(mat["h_beta_m"], mid),
            beta_n=small_take(mat["h_beta_n"], mid),
            alpha=small_take(mat["h_alpha"], mid),
            h=jnp.zeros_like(small_take(mat["h_beta_m"], mid)),
        ) if "h_beta_m" in mat else None,
        fz=mat.get("_fourier"),
        sub=sub,
    )


def _lobe_flags(mp: MatParams):
    """(has_diffuse, has_glossy, is_specular_lobe, has_transmission)."""
    t = mp.mtype
    diffuse = (
        (t == MAT_MATTE)
        | (t == MAT_PLASTIC)
        | (t == MAT_UBER)
        | (t == MAT_TRANSLUCENT)
        | (t == MAT_DISNEY)
        | (t == MAT_HAIR)
        | (t == MAT_FOURIER)
        | (t == MAT_SUBSURFACE)
    )
    glossy = (
        (t == MAT_PLASTIC) | (t == MAT_METAL) | (t == MAT_UBER) | (t == MAT_SUBSTRATE) | (t == MAT_DISNEY)
        # rough glass is a real (non-delta) microfacet BSDF: SPPM stores
        # visible points on glossy surfaces at the depth cap, and
        # bsdf_eval/bsdf_sample override rg lanes wholesale, so flagging
        # it glossy here cannot double-count lobes
        | _is_rough_glass(mp)
    )
    specular = ((t == MAT_GLASS) & ~_is_rough_glass(mp)) | (t == MAT_MIRROR)
    return diffuse, glossy, specular


# -------------------------------------------------------------------------
# Lobe formulas (batched, local frame)
# -------------------------------------------------------------------------

def _diffuse_f(mp: MatParams, wo, wi):
    """Lambertian or Oren-Nayar by sigma; reflection hemisphere only."""
    refl = same_hemisphere(wo, wi)
    sigma = jnp.radians(mp.sigma)
    s2 = sigma * sigma
    a = 1.0 - s2 / (2.0 * (s2 + 0.33))
    b = 0.45 * s2 / (s2 + 0.09)
    sin_to = jnp.sqrt(sin2_theta(wo))
    sin_ti = jnp.sqrt(sin2_theta(wi))
    # max(0, cos(phi_i - phi_o))
    cos_dphi = cos_phi(wi) * cos_phi(wo) + sin_phi(wi) * sin_phi(wo)
    max_cos = jnp.maximum(0.0, cos_dphi)
    has_sin = (sin_to > 1e-4) & (sin_ti > 1e-4)
    max_cos = jnp.where(has_sin, max_cos, 0.0)
    abs_ci = abs_cos_theta(wi)
    abs_co = abs_cos_theta(wo)
    sin_alpha = jnp.where(abs_ci > abs_co, sin_to, sin_ti)
    tan_beta = jnp.where(
        abs_ci > abs_co,
        sin_ti / jnp.maximum(abs_ci, 1e-7),
        sin_to / jnp.maximum(abs_co, 1e-7),
    )
    on = a + b * max_cos * sin_alpha * tan_beta
    is_on = mp.sigma > 0.0
    base = jnp.where(is_on, on, 1.0)
    # translucent diffuse transmission: kd*kt on the opposite hemisphere
    trans_scale = jnp.where(
        (mp.mtype == MAT_TRANSLUCENT)[..., None], mp.kt, jnp.zeros_like(mp.kt)
    )
    refl_scale = jnp.where(
        (mp.mtype == MAT_TRANSLUCENT)[..., None], mp.kr, jnp.ones_like(mp.kr)
    )
    f_refl = mp.kd * (_INV_PI * base)[..., None] * refl_scale
    f_trans = mp.kd * _INV_PI * trans_scale
    return jnp.where(refl[..., None], f_refl, f_trans)


def _diffuse_pdf(mp: MatParams, wo, wi):
    refl = same_hemisphere(wo, wi)
    pdf_r = cosine_hemisphere_pdf(abs_cos_theta(wi))
    is_transl = mp.mtype == MAT_TRANSLUCENT
    # translucent splits the cosine pdf across both hemispheres
    return jnp.where(
        refl, jnp.where(is_transl, 0.5 * pdf_r, pdf_r), jnp.where(is_transl, 0.5 * pdf_r, 0.0)
    )


def _glossy_f(mp: MatParams, wo, wi):
    """Microfacet reflection lobe (or FresnelBlend for substrate)."""
    refl = same_hemisphere(wo, wi)
    wh = wi + wo
    wh_len = jnp.sqrt(jnp.sum(wh * wh, axis=-1))
    valid = refl & (wh_len > 1e-12) & (abs_cos_theta(wi) > 1e-7) & (abs_cos_theta(wo) > 1e-7)
    wh = wh / jnp.maximum(wh_len[..., None], 1e-20)
    d = tr_d(wh, mp.ax, mp.ay)
    g = tr_g(wo, wi, mp.ax, mp.ay)
    cos_wh = jnp.sum(wi * wh, axis=-1)
    is_metal = mp.mtype == MAT_METAL
    eta_s = mp.eta[..., 0]
    f_cond = fresnel_conductor(cos_wh, mp.eta, mp.k)
    f_diel = fresnel_dielectric(cos_wh, jnp.ones_like(eta_s), eta_s)[..., None]
    F = jnp.where(is_metal[..., None], f_cond, f_diel)
    scale = jnp.where(is_metal[..., None], jnp.ones_like(mp.ks), mp.ks)
    denom = 4.0 * abs_cos_theta(wi) * abs_cos_theta(wo)
    f_mf = scale * F * (d * g / jnp.maximum(denom, 1e-12))[..., None]

    # FresnelBlend (substrate): Ashikhmin-Shirley diffuse+spec
    is_sub = mp.mtype == MAT_SUBSTRATE
    pow5 = lambda v: (v * v) * (v * v) * v  # noqa: E731
    diff = (
        (28.0 / (23.0 * jnp.pi))
        * mp.kd
        * (1.0 - mp.ks)
        * (1.0 - pow5(1.0 - 0.5 * abs_cos_theta(wi)))[..., None]
        * (1.0 - pow5(1.0 - 0.5 * abs_cos_theta(wo)))[..., None]
    )
    schlick = mp.ks + pow5(1.0 - cos_wh)[..., None] * (1.0 - mp.ks)
    spec = (
        d
        / jnp.maximum(4.0 * jnp.abs(cos_wh) * jnp.maximum(abs_cos_theta(wi), abs_cos_theta(wo)), 1e-12)
    )[..., None] * schlick
    f_sub = diff + spec

    f = jnp.where(is_sub[..., None], f_sub, f_mf)
    return jnp.where(valid[..., None], f, 0.0)


def _glossy_pdf(mp: MatParams, wo, wi):
    refl = same_hemisphere(wo, wi)
    wh = wi + wo
    wh_len = jnp.sqrt(jnp.sum(wh * wh, axis=-1))
    wh = wh / jnp.maximum(wh_len[..., None], 1e-20)
    pdf_wh = tr_pdf(wo, wh, mp.ax, mp.ay)
    pdf = pdf_wh / jnp.maximum(4.0 * jnp.sum(wo * wh, axis=-1), 1e-12)
    is_sub = mp.mtype == MAT_SUBSTRATE
    # FresnelBlend pdf: average of cosine and half-vector pdfs
    pdf_sub = 0.5 * (cosine_hemisphere_pdf(abs_cos_theta(wi)) + pdf)
    pdf = jnp.where(is_sub, pdf_sub, pdf)
    return jnp.where(refl & (wh_len > 1e-12), pdf, 0.0)


#: raw roughness above this makes glass a microfacet (non-delta) surface
#: (glass.cpp: rough glass builds MicrofacetReflection/Transmission)
ROUGH_GLASS_MIN = 1e-4


def _is_rough_glass(mp: MatParams):
    return (mp.mtype == MAT_GLASS) & (mp.rough_raw > ROUGH_GLASS_MIN)


def _refract_about(wo, wh, eta_rel):
    """Refract wo about microfacet normal wh (faced toward wo);
    eta_rel = eta_incident / eta_transmitted. Returns (wi, tir)."""
    wh_f = jnp.where((jnp.sum(wo * wh, axis=-1) < 0.0)[..., None], -wh, wh)
    ci = jnp.sum(wo * wh_f, axis=-1)
    sin2t = eta_rel * eta_rel * jnp.maximum(0.0, 1.0 - ci * ci)
    tir = sin2t >= 1.0
    ctt = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin2t))
    wi = eta_rel[..., None] * -wo + (eta_rel * ci - ctt)[..., None] * wh_f
    return wi, tir


def _mf_glass_terms(mp: MatParams, wo, wi, wh):
    """The MicrofacetReflection + MicrofacetTransmission formulas
    (reflection.cpp ::f/::Pdf) evaluated at an EXPLICIT half-vector —
    the single source both bsdf_eval (reconstructed whs) and bsdf_sample
    (the drawn wh) share, so the MIS pdfs cannot drift apart. wh is
    faceforwarded to +z internally (TIR via the signed Fresnel cosine).
    pdfs carry pbrt's uniform 2-lobe component weight (0.5 each).
    Radiance transport: transmission carries the 1/eta^2 scale.
    Returns (f_refl, pdf_refl, ok_refl, f_trans, pdf_trans, ok_trans)."""
    eta_s = mp.eta[..., 0]
    refl = same_hemisphere(wo, wi)
    ci = abs_cos_theta(wi)
    co = abs_cos_theta(wo)
    ok_angles = (ci > 1e-7) & (co > 1e-7)
    wh_z = jnp.where((wh[..., 2] < 0.0)[..., None], -wh, wh)
    do_h = jnp.sum(wo * wh_z, axis=-1)
    di_h = jnp.sum(wi * wh_z, axis=-1)
    d = tr_d(wh_z, mp.ax, mp.ay)
    g = tr_g(wo, wi, mp.ax, mp.ay)
    pdf_wh = tr_pdf(wo, wh_z, mp.ax, mp.ay)
    F = fresnel_dielectric(do_h, jnp.ones_like(eta_s), eta_s)

    f_refl = mp.kr * (d * g * F / jnp.maximum(4.0 * ci * co, 1e-12))[..., None]
    pdf_refl = 0.5 * pdf_wh / jnp.maximum(4.0 * jnp.abs(do_h), 1e-12)
    ok_refl = refl & ok_angles

    # eta = etaT/etaI of the transmitted side (MicrofacetTransmission)
    eta_t = jnp.where(cos_theta(wo) > 0.0, eta_s, 1.0 / jnp.maximum(eta_s, 1e-6))
    sqrt_denom = do_h + eta_t * di_h
    factor = 1.0 / jnp.maximum(eta_t, 1e-6)  # radiance transport scale
    f_trans = mp.kt * jnp.abs(
        d * g * eta_t * eta_t * (1.0 - F) * jnp.abs(di_h) * jnp.abs(do_h)
        * factor * factor
        / jnp.maximum(ci * co * sqrt_denom * sqrt_denom, 1e-12)
    )[..., None]
    dwh_dwi = jnp.abs(eta_t * eta_t * di_h) / jnp.maximum(
        sqrt_denom * sqrt_denom, 1e-12
    )
    pdf_trans = 0.5 * pdf_wh * dwh_dwi
    ok_trans = (~refl) & ok_angles & (do_h * di_h < 0.0)
    return f_refl, pdf_refl, ok_refl, f_trans, pdf_trans, ok_trans


def _rough_glass_f_pdf(mp: MatParams, wo, wi):
    """Eval path: reconstruct each lobe's half-vector from (wo, wi) —
    wo+wi for reflection, the generalized wo + eta*wi for transmission —
    then evaluate the shared terms at each."""
    eta_s = mp.eta[..., 0]
    wh_r = wi + wo
    whr_len = jnp.sqrt(jnp.sum(wh_r * wh_r, axis=-1))
    wh_rn = wh_r / jnp.maximum(whr_len[..., None], 1e-20)
    f_r, p_r, ok_r, _, _, _ = _mf_glass_terms(mp, wo, wi, wh_rn)
    ok_r = ok_r & (whr_len > 1e-12)

    eta_t = jnp.where(cos_theta(wo) > 0.0, eta_s, 1.0 / jnp.maximum(eta_s, 1e-6))
    wh_t = wo + wi * eta_t[..., None]
    wht_len = jnp.sqrt(jnp.sum(wh_t * wh_t, axis=-1))
    wh_tn = wh_t / jnp.maximum(wht_len[..., None], 1e-20)
    _, _, _, f_t, p_t, ok_t = _mf_glass_terms(mp, wo, wi, wh_tn)
    ok_t = ok_t & (wht_len > 1e-12)

    f = jnp.where(ok_r[..., None], f_r, 0.0) + jnp.where(ok_t[..., None], f_t, 0.0)
    pdf = jnp.where(ok_r, p_r, 0.0) + jnp.where(ok_t, p_t, 0.0)
    return f, pdf




# -------------------------------------------------------------------------
# Disney 2015 BSDF (materials/disney.cpp: DisneyDiffuse/FakeSS/Retro/
# Sheen, DisneyMicrofacetDistribution + DisneyFresnel, DisneyClearcoat,
# MicrofacetTransmission spec-trans, thin LambertianTransmission).
# Everything here is traced ONLY when the scene contains a disney
# material (MatParams.dz gating) — other scenes pay zero compile cost.
# -------------------------------------------------------------------------

def _sw(c):
    """SchlickWeight: (1-c)^5 clamped."""
    m = jnp.clip(1.0 - c, 0.0, 1.0)
    return (m * m) * (m * m) * m


def _gtr1_d(cos_h, alpha):
    a2 = alpha * alpha
    denom = jnp.pi * jnp.log(a2) * (1.0 + (a2 - 1.0) * cos_h * cos_h)
    return (a2 - 1.0) / jnp.where(jnp.abs(denom) < 1e-12, 1e-12, denom)


def _smith_g_sep(c, alpha):
    """Separable smith G1 with the clearcoat's fixed-alpha form
    (disney.cpp smithG_GGX)."""
    a2 = alpha * alpha
    c2 = c * c
    return 1.0 / (c + jnp.sqrt(jnp.maximum(a2 + c2 - a2 * c2, 1e-12)))


def _disney_weights(mp: MatParams):
    """Shared per-lane derived quantities."""
    from tpu_pbrt.core.spectrum import luminance

    dz = mp.dz
    c = mp.kd
    e = mp.eta[..., 0]
    metallic = dz.metallic
    strans = dz.strans
    dw = (1.0 - metallic) * (1.0 - strans)
    dt = dz.dtrans * 0.5
    lum = luminance(c)
    ctint = jnp.where((lum > 0.0)[..., None], c / jnp.maximum(lum, 1e-12)[..., None], 1.0)
    csheen = (1.0 - dz.sheentint)[..., None] + dz.sheentint[..., None] * ctint
    r0 = ((e - 1.0) / (e + 1.0)) ** 2
    cspec0 = (
        (1.0 - metallic)[..., None]
        * r0[..., None]
        * ((1.0 - dz.spectint)[..., None] + dz.spectint[..., None] * ctint)
        + metallic[..., None] * c
    )
    aspect = jnp.sqrt(jnp.maximum(1.0 - 0.9 * dz.aniso, 1e-6))
    r2 = dz.rough * dz.rough
    ax = jnp.maximum(1e-3, r2 / aspect)
    ay = jnp.maximum(1e-3, r2 * aspect)
    rscaled = (0.65 * e - 0.35) * dz.rough
    rs2 = rscaled * rscaled
    axt = jnp.where(dz.thin, jnp.maximum(1e-3, rs2 / aspect), ax)
    ayt = jnp.where(dz.thin, jnp.maximum(1e-3, rs2 * aspect), ay)
    gloss = 0.1 * (1.0 - dz.ccgloss) + 0.001 * dz.ccgloss
    return c, e, dw, dt, csheen, cspec0, ax, ay, axt, ayt, gloss


def _disney_presence(mp: MatParams):
    dz = mp.dz
    metallic = dz.metallic
    dw_pos = (1.0 - metallic) * (1.0 - dz.strans) > 0.0
    pr = [
        dw_pos,                      # 0 DisneyDiffuse
        dw_pos & dz.thin,            # 1 DisneyFakeSS
        dw_pos,                      # 2 DisneyRetro
        dw_pos & (dz.sheen > 0.0),   # 3 DisneySheen
        jnp.ones_like(dw_pos),       # 4 microfacet reflection
        dz.clearcoat > 0.0,          # 5 clearcoat
        dz.strans > 0.0,             # 6 microfacet spec transmission
        dz.thin,                     # 7 LambertianTransmission
    ]
    n = sum(p.astype(jnp.int32) for p in pr)
    return pr, n


def _disney_trans_terms(T, e, axt, ayt, wo, wi, wh):
    """MicrofacetTransmission::f/Pdf with Disney's separable G at an
    explicit half-vector (etaA=1, etaB=e, radiance transport)."""
    ci = abs_cos_theta(wi)
    co = abs_cos_theta(wo)
    ok = (ci > 1e-7) & (co > 1e-7) & ~same_hemisphere(wo, wi)
    eta_t = jnp.where(cos_theta(wo) > 0.0, e, 1.0 / jnp.maximum(e, 1e-6))
    wh_z = jnp.where((wh[..., 2] < 0.0)[..., None], -wh, wh)
    do_h = jnp.sum(wo * wh_z, axis=-1)
    di_h = jnp.sum(wi * wh_z, axis=-1)
    ok = ok & (do_h * di_h < 0.0)
    d = tr_d(wh_z, axt, ayt)
    g = tr_g1(wo, axt, ayt) * tr_g1(wi, axt, ayt)
    F = fresnel_dielectric(do_h, jnp.ones_like(e), e)
    sqrt_denom = do_h + eta_t * di_h
    factor = 1.0 / jnp.maximum(eta_t, 1e-6)
    f = T * jnp.abs(
        d * g * eta_t * eta_t * (1.0 - F) * jnp.abs(di_h) * jnp.abs(do_h)
        * factor * factor
        / jnp.maximum(ci * co * sqrt_denom * sqrt_denom, 1e-12)
    )[..., None]
    pdf_wh = tr_pdf(wo, wh_z, axt, ayt)
    dwh_dwi = jnp.abs(eta_t * eta_t * di_h) / jnp.maximum(
        sqrt_denom * sqrt_denom, 1e-12
    )
    pdf = pdf_wh * dwh_dwi
    return jnp.where(ok[..., None], f, 0.0), jnp.where(ok, pdf, 0.0), ok


def _disney_f_pdf(mp: MatParams, wo, wi):
    """f and per-lobe-averaged pdf over the full active lobe set
    (BSDF::f / BSDF::Pdf semantics over the Add()ed lobes)."""
    dz = mp.dz
    c, e, dw, dt, csheen, cspec0, ax, ay, axt, ayt, gloss = _disney_weights(mp)
    pr, n = _disney_presence(mp)
    refl = same_hemisphere(wo, wi)
    ci = abs_cos_theta(wi)
    co = abs_cos_theta(wo)
    ok_ang = (ci > 1e-7) & (co > 1e-7)

    wh = wi + wo
    wh_len = jnp.sqrt(jnp.sum(wh * wh, axis=-1))
    whn = wh / jnp.maximum(wh_len[..., None], 1e-20)
    cos_d = jnp.sum(wi * whn, axis=-1)  # cosThetaD
    FL = _sw(ci)
    FV = _sw(co)
    rough = dz.rough

    # 0: DisneyDiffuse
    f0 = (dw * (jnp.where(dz.thin, (1.0 - dz.flat) * (1.0 - dt), 1.0)))[
        ..., None
    ] * c * (_INV_PI * (1.0 - 0.5 * FL) * (1.0 - 0.5 * FV))[..., None]
    # 1: DisneyFakeSS
    fss90 = cos_d * cos_d * rough
    fss = (1.0 + (fss90 - 1.0) * FL) * (1.0 + (fss90 - 1.0) * FV)
    ss = 1.25 * (fss * (1.0 / jnp.maximum(ci + co, 1e-7) - 0.5) + 0.5)
    f1 = (dw * dz.flat * (1.0 - dt))[..., None] * c * (_INV_PI * ss)[..., None]
    # 2: DisneyRetro
    rr = 2.0 * rough * cos_d * cos_d
    f2 = dw[..., None] * c * (
        _INV_PI * rr * (FL + FV + FL * FV * (rr - 1.0))
    )[..., None]
    # 3: DisneySheen
    f3 = (dw * dz.sheen)[..., None] * csheen * _sw(cos_d)[..., None]
    # 4: microfacet reflection (GGX, Disney separable G + DisneyFresnel)
    d_mf = tr_d(whn, ax, ay)
    g_mf = tr_g1(wo, ax, ay) * tr_g1(wi, ax, ay)
    fr_diel = fresnel_dielectric(cos_d, jnp.ones_like(e), e)
    fr_schlick = cspec0 + _sw(cos_d)[..., None] * (1.0 - cspec0)
    F_mf = (1.0 - dz.metallic)[..., None] * fr_diel[..., None] + dz.metallic[
        ..., None
    ] * fr_schlick
    f4 = F_mf * (d_mf * g_mf / jnp.maximum(4.0 * ci * co, 1e-12))[..., None]
    # 5: clearcoat (GTR1)
    d_cc = _gtr1_d(jnp.abs(whn[..., 2]), gloss)
    f_cc = 0.04 + 0.96 * _sw(cos_d)
    g_cc = _smith_g_sep(ci, 0.25) * _smith_g_sep(co, 0.25)
    f5 = (0.25 * dz.clearcoat * d_cc * f_cc * g_cc)[..., None] * jnp.ones_like(c)

    refl_ok = (refl & ok_ang & (wh_len > 1e-12))[..., None]
    f_refl = (
        jnp.where(pr[0][..., None], f0, 0.0)
        + jnp.where(pr[1][..., None], f1, 0.0)
        + jnp.where(pr[2][..., None], f2, 0.0)
        + jnp.where(pr[3][..., None], f3, 0.0)
        + jnp.where(pr[4][..., None], f4, 0.0)
        + jnp.where(pr[5][..., None], f5, 0.0)
    )
    f = jnp.where(refl_ok, f_refl, 0.0)

    # 6: spec transmission (reconstruct the generalized half-vector)
    T6 = dz.strans[..., None] * jnp.sqrt(jnp.maximum(c, 0.0))
    eta_t = jnp.where(cos_theta(wo) > 0.0, e, 1.0 / jnp.maximum(e, 1e-6))
    wh_t = wo + wi * eta_t[..., None]
    wht_len = jnp.sqrt(jnp.sum(wh_t * wh_t, axis=-1))
    wh_tn = wh_t / jnp.maximum(wht_len[..., None], 1e-20)
    f6, p6, ok6 = _disney_trans_terms(T6, e, axt, ayt, wo, wi, wh_tn)
    ok6 = ok6 & (wht_len > 1e-12)
    f = f + jnp.where((pr[6] & ok6)[..., None], f6, 0.0)
    # 7: thin diffuse transmission
    f7 = (dt)[..., None] * c * _INV_PI
    f = f + jnp.where((pr[7] & ~refl & ok_ang)[..., None], f7, 0.0)

    # pdf: average over present lobes (cosine for 0-3, vndf for 4, GTR1
    # for 5, transmission jacobian for 6, flipped cosine for 7)
    pdf_cos = jnp.where(refl, cosine_hemisphere_pdf(ci), 0.0)
    n_cos = sum(p.astype(jnp.float32) for p in pr[0:4])
    pdf_mf = jnp.where(
        refl & (wh_len > 1e-12),
        tr_pdf(wo, whn, ax, ay)
        / jnp.maximum(4.0 * jnp.abs(jnp.sum(wo * whn, axis=-1)), 1e-12),
        0.0,
    )
    pdf_cc = jnp.where(
        refl & (wh_len > 1e-12),
        jnp.abs(d_cc * whn[..., 2])
        / jnp.maximum(4.0 * jnp.abs(jnp.sum(wo * whn, axis=-1)), 1e-12),
        0.0,
    )
    pdf_lt = jnp.where(~refl, cosine_hemisphere_pdf(ci), 0.0)
    pdf_sum = (
        n_cos * pdf_cos
        + jnp.where(pr[4], pdf_mf, 0.0)
        + jnp.where(pr[5], pdf_cc, 0.0)
        + jnp.where(pr[6] & ok6, p6, 0.0)
        + jnp.where(pr[7], pdf_lt, 0.0)
    )
    pdf = pdf_sum / jnp.maximum(n.astype(jnp.float32), 1.0)
    dead = ~ok_ang
    return jnp.where(dead[..., None], 0.0, f), jnp.where(dead, 0.0, pdf)


def _disney_sample_wi(mp: MatParams, wo, u_lobe, u1, u2):
    """Draw wi by picking uniformly among the PRESENT lobes (BSDF::
    Sample_f component choice); f/pdf then come from _disney_f_pdf."""
    dz = mp.dz
    c, e, dw, dt, csheen, cspec0, ax, ay, axt, ayt, gloss = _disney_weights(mp)
    pr, n = _disney_presence(mp)
    nf = n.astype(jnp.float32)
    k = jnp.minimum((u_lobe * nf).astype(jnp.int32), n - 1)
    # k-th present lobe: lobe j is chosen when cumsum(pr)[j]-1 == k
    cum = jnp.cumsum(jnp.stack([p.astype(jnp.int32) for p in pr]), axis=0)
    sel = [(cum[j] - 1 == k) & pr[j] for j in range(8)]

    sgn = jnp.where(cos_theta(wo) >= 0.0, 1.0, -1.0)
    # cosine candidates (lobes 0-3 same side, 7 flipped)
    wi_cos = cosine_sample_hemisphere(u1, u2)
    wi_cos = wi_cos * jnp.stack(
        [jnp.ones_like(sgn), jnp.ones_like(sgn), sgn], axis=-1
    )
    wi_lt = wi_cos * jnp.asarray([1.0, 1.0, -1.0])
    # microfacet reflection (vndf)
    wh_mf = tr_sample_wh(wo, u1, u2, ax, ay)
    wi_mf = -wo + 2.0 * jnp.sum(wo * wh_mf, axis=-1)[..., None] * wh_mf
    # clearcoat GTR1 half-vector (disney.cpp DisneyClearcoat::Sample_f)
    a2 = gloss * gloss
    ct_h = jnp.sqrt(
        jnp.maximum(0.0, (1.0 - jnp.power(a2, 1.0 - u1)) / (1.0 - a2))
    )
    st_h = jnp.sqrt(jnp.maximum(0.0, 1.0 - ct_h * ct_h))
    phi = 2.0 * jnp.pi * u2
    wh_cc = jnp.stack([st_h * jnp.cos(phi), st_h * jnp.sin(phi), ct_h], -1)
    wh_cc = jnp.where(same_hemisphere(wo, wh_cc)[..., None], wh_cc, -wh_cc)
    wi_cc = -wo + 2.0 * jnp.sum(wo * wh_cc, axis=-1)[..., None] * wh_cc
    # spec transmission: vndf on the (possibly thin-rescaled) dist
    wh_st = tr_sample_wh(wo, u1, u2, axt, ayt)
    eta_rel = jnp.where(
        cos_theta(wo) > 0.0, 1.0 / jnp.maximum(e, 1e-6), e
    )
    wi_st, tir_st = _refract_about(wo, wh_st, eta_rel)

    wi = wi_cos
    wi = jnp.where(sel[4][..., None], wi_mf, wi)
    wi = jnp.where(sel[5][..., None], wi_cc, wi)
    wi = jnp.where(sel[6][..., None], wi_st, wi)
    wi = jnp.where(sel[7][..., None], wi_lt, wi)
    ln = jnp.sqrt(jnp.sum(wi * wi, axis=-1))
    wi = wi / jnp.maximum(ln[..., None], 1e-20)
    bad = (sel[6] & tir_st) | (ln < 1e-12)
    return wi, bad




# -------------------------------------------------------------------------
# Hair BSDF (src/materials/hair.cpp, Chiang et al. 2016 "A Practical and
# Controllable Hair and Fur Model"): longitudinal Mp / azimuthal
# trimmed-logistic Np lobes for p = 0..3, dielectric attenuation Ap, and
# the 2-degree scale-tilt recurrences. The local frame follows pbrt's
# curve convention: x along the curve tangent, (y, z) the azimuthal
# plane; h in [-1, 1] is the across-width offset (-1 + 2 * uv.v for the
# tessellated flat ribbons). Traced only when a scene uses hair
# (MatParams.hz gating).
# -------------------------------------------------------------------------

_H_PMAX = 3
_SQRT_PI_OVER_8 = 0.626657069


class HairParams(NamedTuple):
    sigma_a: jnp.ndarray  # (R,3)
    beta_m: jnp.ndarray  # (R,)
    beta_n: jnp.ndarray
    alpha: jnp.ndarray  # degrees
    h: jnp.ndarray  # (R,) across-width offset, set from uv at shade time


def _safe_sqrt(x):
    return jnp.sqrt(jnp.maximum(x, 0.0))


def _safe_asin(x):
    return jnp.arcsin(jnp.clip(x, -1.0, 1.0))


def _i0(x):
    """Modified Bessel I0, 10-term series (hair.cpp I0)."""
    val = jnp.zeros_like(x)
    x2i = jnp.ones_like(x)
    ifact = 1.0
    i4 = 1.0
    for i in range(10):
        if i > 1:
            ifact *= i
        val = val + x2i / (i4 * ifact * ifact)
        x2i = x2i * x * x
        i4 *= 4.0
    return val


def _log_i0(x):
    big = x > 12.0
    lb = x + 0.5 * (-jnp.log(2.0 * jnp.pi) + jnp.log(1.0 / jnp.maximum(x, 1e-12)) + 1.0 / (8.0 * jnp.maximum(x, 1e-12)))
    ls = jnp.log(jnp.maximum(_i0(jnp.minimum(x, 12.0)), 1e-38))
    return jnp.where(big, lb, ls)


def _mp(cos_ti, cos_to, sin_ti, sin_to, v):
    a = cos_ti * cos_to / v
    b = sin_ti * sin_to / v
    small = v <= 0.1
    m_small = jnp.exp(
        _log_i0(a) - b - 1.0 / v + 0.6931 + jnp.log(1.0 / (2.0 * v))
    )
    vb = jnp.maximum(v, 0.05)  # keep the big-v branch finite under where
    m_big = (
        jnp.exp(-jnp.minimum(b, 80.0)) * _i0(jnp.minimum(a, 12.0))
    ) / (jnp.sinh(jnp.minimum(1.0 / vb, 80.0)) * 2.0 * vb)
    return jnp.where(small, m_small, m_big)


def _logistic(x, s):
    x = jnp.abs(x)
    e = jnp.exp(-x / s)
    return e / (s * (1.0 + e) ** 2)


def _logistic_cdf(x, s):
    return 1.0 / (1.0 + jnp.exp(-x / s))


def _trimmed_logistic(x, s):
    pi = jnp.pi
    norm = _logistic_cdf(pi, s) - _logistic_cdf(-pi, s)
    return _logistic(x, s) / jnp.maximum(norm, 1e-12)


def _sample_trimmed_logistic(u, s):
    pi = jnp.pi
    k = _logistic_cdf(pi, s) - _logistic_cdf(-pi, s)
    x = -s * jnp.log(
        1.0 / jnp.maximum(u * k + _logistic_cdf(-pi, s), 1e-12) - 1.0
    )
    return jnp.clip(x, -pi, pi)


def _hair_phi_p(p, gamma_o, gamma_t):
    return 2.0 * p * gamma_t - 2.0 * gamma_o + p * jnp.pi


def _wrap_pi(x):
    return jnp.mod(x + jnp.pi, 2.0 * jnp.pi) - jnp.pi


def _hair_setup(mp: MatParams, wo):
    """Shared per-lane terms (hair.cpp f()/Pdf() prologue)."""
    hz = mp.hz
    eta = mp.eta[..., 0]
    h = hz.h
    bm = hz.beta_m
    bn = hz.beta_n
    v0 = (0.726 * bm + 0.812 * bm * bm + 3.7 * bm ** 20) ** 2
    vs = [v0, 0.25 * v0, 4.0 * v0, 4.0 * v0]
    s = _SQRT_PI_OVER_8 * (0.265 * bn + 1.194 * bn * bn + 5.372 * bn ** 22)
    a_rad = jnp.radians(hz.alpha)
    sin2k = [jnp.sin(a_rad)]
    cos2k = [_safe_sqrt(1.0 - sin2k[0] ** 2)]
    for i in range(1, 3):
        sin2k.append(2.0 * cos2k[i - 1] * sin2k[i - 1])
        cos2k.append(cos2k[i - 1] ** 2 - sin2k[i - 1] ** 2)

    sin_to = wo[..., 0]
    cos_to = _safe_sqrt(1.0 - sin_to * sin_to)
    phi_o = jnp.arctan2(wo[..., 2], wo[..., 1])
    sin_tt = sin_to / eta
    cos_tt = _safe_sqrt(1.0 - sin_tt * sin_tt)
    etap = _safe_sqrt(eta * eta - sin_to * sin_to) / jnp.maximum(cos_to, 1e-6)
    sin_gt = h / jnp.maximum(etap, 1e-6)
    cos_gt = _safe_sqrt(1.0 - sin_gt * sin_gt)
    gamma_t = _safe_asin(sin_gt)
    gamma_o = _safe_asin(h)
    # transmittance of one internal segment
    T = jnp.exp(
        -hz.sigma_a * (2.0 * cos_gt / jnp.maximum(cos_tt, 1e-6))[..., None]
    )
    # attenuation Ap (hair.cpp Ap())
    cos_go = _safe_sqrt(1.0 - h * h)
    fr = fresnel_dielectric(cos_to * cos_go, jnp.ones_like(eta), eta)[..., None]
    ap0 = jnp.broadcast_to(fr, T.shape)
    ap1 = (1.0 - fr) ** 2 * T
    ap2 = ap1 * T * fr
    ap3 = ap2 * fr * T / jnp.maximum(1.0 - T * fr, 1e-4)
    aps = [ap0, ap1, ap2, ap3]

    # tilted longitudinal angles per p (hair.cpp "account for scales")
    tilts = []
    for p in range(3):
        if p == 0:
            st = sin_to * cos2k[1] - cos_to * sin2k[1]
            ct = cos_to * cos2k[1] + sin_to * sin2k[1]
        elif p == 1:
            st = sin_to * cos2k[0] + cos_to * sin2k[0]
            ct = cos_to * cos2k[0] - sin_to * sin2k[0]
        else:
            st = sin_to * cos2k[2] + cos_to * sin2k[2]
            ct = cos_to * cos2k[2] - sin_to * sin2k[2]
        tilts.append((st, jnp.abs(ct)))
    tilts.append((sin_to, cos_to))

    from tpu_pbrt.core.spectrum import luminance

    ap_lum = [luminance(a) for a in aps]
    tot = sum(ap_lum)
    ap_pdf = [al / jnp.maximum(tot, 1e-12) for al in ap_lum]
    return (eta, s, vs, gamma_o, gamma_t, phi_o, sin_to, cos_to, aps,
            ap_pdf, tilts)


def _hair_f_pdf(mp: MatParams, wo, wi):
    """HairBSDF::f and ::Pdf."""
    (eta, s, vs, gamma_o, gamma_t, phi_o, sin_to, cos_to, aps, ap_pdf,
     tilts) = _hair_setup(mp, wo)
    sin_ti = wi[..., 0]
    cos_ti = _safe_sqrt(1.0 - sin_ti * sin_ti)
    phi_i = jnp.arctan2(wi[..., 2], wi[..., 1])
    phi = phi_i - phi_o
    fsum = jnp.zeros_like(mp.kd)
    pdf = jnp.zeros_like(sin_to)
    for p in range(_H_PMAX):
        st, ct = tilts[p]
        m = _mp(cos_ti, ct, sin_ti, st, vs[p])
        n = _trimmed_logistic(
            _wrap_pi(phi - _hair_phi_p(p, gamma_o, gamma_t)), s
        )
        fsum = fsum + aps[p] * (m * n)[..., None]
        pdf = pdf + ap_pdf[p] * m * n
    st, ct = tilts[_H_PMAX]
    m_last = _mp(cos_ti, ct, sin_ti, st, vs[_H_PMAX])
    inv2pi = 1.0 / (2.0 * jnp.pi)
    fsum = fsum + aps[_H_PMAX] * (m_last * inv2pi)[..., None]
    pdf = pdf + ap_pdf[_H_PMAX] * m_last * inv2pi
    f = fsum / jnp.maximum(abs_cos_theta(wi), 1e-6)[..., None]
    ok = jnp.isfinite(pdf) & jnp.all(jnp.isfinite(f), axis=-1)
    return jnp.where(ok[..., None], f, 0.0), jnp.where(ok, pdf, 0.0)


def _hair_sample_wi(mp: MatParams, wo, u_lobe, u1, u2):
    """HairBSDF::Sample_f direction draw: pick p by the attenuation
    pdf, sample Mp longitudinally and the trimmed logistic azimuthally.
    u_lobe is consumed for the p choice and its remainder reused for
    the azimuthal sample (pbrt demuxes one sample the same way)."""
    (eta, s, vs, gamma_o, gamma_t, phi_o, sin_to, cos_to, aps, ap_pdf,
     tilts) = _hair_setup(mp, wo)
    c0 = ap_pdf[0]
    c1 = c0 + ap_pdf[1]
    c2 = c1 + ap_pdf[2]
    p_idx = (
        (u_lobe >= c0).astype(jnp.int32)
        + (u_lobe >= c1).astype(jnp.int32)
        + (u_lobe >= c2).astype(jnp.int32)
    )
    prev = jnp.where(
        p_idx == 0, 0.0,
        jnp.where(p_idx == 1, c0, jnp.where(p_idx == 2, c1, c2)),
    )
    width = jnp.where(
        p_idx == 0, c0,
        jnp.where(
            p_idx == 1, c1 - c0, jnp.where(p_idx == 2, c2 - c1, 1.0 - c2)
        ),
    )
    u_np = jnp.clip((u_lobe - prev) / jnp.maximum(width, 1e-9), 0.0, 0.9999)

    def sel(vals):
        out = vals[0]
        for p in range(1, 4):
            out = jnp.where(p_idx == p, vals[p], out)
        return out

    v_p = sel(vs)
    st_p = sel([t[0] for t in tilts])
    ct_p = sel([t[1] for t in tilts])
    u1c = jnp.maximum(u1, 1e-5)
    cos_theta = 1.0 + v_p * jnp.log(
        u1c
        + (1.0 - u1c)
        * jnp.exp(-jnp.minimum(2.0 / jnp.maximum(v_p, 1e-6), 80.0))
    )
    sin_theta = _safe_sqrt(1.0 - cos_theta * cos_theta)
    cos_phi_s = jnp.cos(2.0 * jnp.pi * u2)
    sin_ti = -cos_theta * st_p + sin_theta * cos_phi_s * ct_p
    cos_ti = _safe_sqrt(1.0 - sin_ti * sin_ti)
    dphi_smooth = sel(
        [_hair_phi_p(p, gamma_o, gamma_t) for p in range(4)]
    ) + _sample_trimmed_logistic(u_np, s)
    dphi = jnp.where(p_idx < _H_PMAX, dphi_smooth, 2.0 * jnp.pi * u_np)
    phi_i = phi_o + dphi
    wi = jnp.stack(
        [sin_ti, cos_ti * jnp.cos(phi_i), cos_ti * jnp.sin(phi_i)], axis=-1
    )
    return wi


# -------------------------------------------------------------------------
# Public API
# -------------------------------------------------------------------------

def bsdf_eval(mp: MatParams, wo, wi):
    """f(wo,wi) and pdf for non-specular lobes (pbrt BSDF::f / BSDF::Pdf
    with BSDF_ALL & ~SPECULAR: specular lobes contribute zero)."""
    has_d, has_g, is_spec = _lobe_flags(mp)
    f = jnp.zeros_like(mp.kd)
    pdf = jnp.zeros_like(mp.ax)
    fd = _diffuse_f(mp, wo, wi)
    pd = _diffuse_pdf(mp, wo, wi)
    fg = _glossy_f(mp, wo, wi)
    pg = _glossy_pdf(mp, wo, wi)
    f = jnp.where(has_d[..., None], fd, 0.0) + jnp.where(has_g[..., None], fg, 0.0)
    n_lobes = has_d.astype(jnp.float32) + has_g.astype(jnp.float32)
    pdf = (jnp.where(has_d, pd, 0.0) + jnp.where(has_g, pg, 0.0)) / jnp.maximum(n_lobes, 1.0)
    # rough (microfacet) glass is a real non-delta BSDF (glass.cpp)
    rg = _is_rough_glass(mp)
    f_rg, pdf_rg = _rough_glass_f_pdf(mp, wo, wi)
    f = jnp.where(rg[..., None], f_rg, f)
    pdf = jnp.where(rg, pdf_rg, pdf)
    if mp.dz is not None:
        dzl = mp.mtype == MAT_DISNEY
        f_dz, pdf_dz = _disney_f_pdf(mp, wo, wi)
        f = jnp.where(dzl[..., None], f_dz, f)
        pdf = jnp.where(dzl, pdf_dz, pdf)
    if mp.hz is not None:
        hl = mp.mtype == MAT_HAIR
        f_h, pdf_h = _hair_f_pdf(mp, wo, wi)
        f = jnp.where(hl[..., None], f_h, f)
        pdf = jnp.where(hl, pdf_h, pdf)
    if mp.fz is not None:
        from tpu_pbrt.core.fourierbsdf import fourier_f_pdf

        fl = mp.mtype == MAT_FOURIER
        f_fo, pdf_fo = fourier_f_pdf(mp.fz, wo, wi)
        f = jnp.where(fl[..., None], f_fo, f)
        pdf = jnp.where(fl, pdf_fo, pdf)
    dead = (is_spec & ~rg) | (mp.mtype == MAT_NONE)
    return jnp.where(dead[..., None], 0.0, f), jnp.where(dead, 0.0, pdf)


class BSDFSample(NamedTuple):
    wi: jnp.ndarray  # (R,3) local frame
    f: jnp.ndarray  # (R,3)
    pdf: jnp.ndarray  # (R,)
    is_specular: jnp.ndarray  # (R,) bool
    is_transmission: jnp.ndarray  # (R,) bool


def bsdf_sample(mp: MatParams, wo, u_lobe, u1, u2) -> BSDFSample:
    """BSDF::Sample_f over the batch. u_lobe picks among matching lobes
    (pbrt's uniform component choice); u1,u2 drive the chosen lobe."""
    has_d, has_g, is_spec = _lobe_flags(mp)
    n_lobes = has_d.astype(jnp.int32) + has_g.astype(jnp.int32)
    pick_g = has_g & ((~has_d) | (u_lobe * n_lobes.astype(jnp.float32) >= 1.0))

    # --- diffuse candidate (cosine hemisphere) ---------------------------
    # translucent: u2's low bit picks reflect/transmit, then u2 is remapped
    # to [0,1) so the decision and the disk coordinate are independent —
    # reusing raw u2 for both would cover only half the transmitted disk
    # while _diffuse_pdf claims the full hemisphere (ADVICE r1)
    is_transl = mp.mtype == MAT_TRANSLUCENT
    flip_t = is_transl & (u2 < 0.5)
    u2d = jnp.where(is_transl, jnp.where(u2 < 0.5, 2.0 * u2, 2.0 * (u2 - 0.5)), u2)
    wi_d = cosine_sample_hemisphere(u1, u2d)
    wi_d = jnp.where((cos_theta(wo) < 0.0)[..., None], wi_d * jnp.asarray([1.0, 1.0, -1.0]), wi_d)
    wi_d = jnp.where(flip_t[..., None], wi_d * jnp.asarray([1.0, 1.0, -1.0]), wi_d)

    # --- glossy candidate (VNDF half-vector) -----------------------------
    wh = tr_sample_wh(wo, u1, u2, mp.ax, mp.ay)
    wi_g = -wo + 2.0 * jnp.sum(wo * wh, axis=-1)[..., None] * wh
    # substrate: half the samples are cosine (FresnelBlend::Sample_f)
    is_sub = mp.mtype == MAT_SUBSTRATE
    use_cos = is_sub & (u_lobe < 0.5)
    wi_g = jnp.where(use_cos[..., None], wi_d, wi_g)

    wi = jnp.where(pick_g[..., None], wi_g, wi_d)

    dz_bad = None
    if mp.dz is not None:
        dzl = mp.mtype == MAT_DISNEY
        wi_dz, bad_dz = _disney_sample_wi(mp, wo, u_lobe, u1, u2)
        wi = jnp.where(dzl[..., None], wi_dz, wi)
        dz_bad = dzl & bad_dz
    if mp.hz is not None:
        hl = mp.mtype == MAT_HAIR
        wi_h = _hair_sample_wi(mp, wo, u_lobe, u1, u2)
        wi = jnp.where(hl[..., None], wi_h, wi)
    if mp.fz is not None:
        from tpu_pbrt.core.fourierbsdf import fourier_sample_wi

        fl = mp.mtype == MAT_FOURIER
        wi_fo = fourier_sample_wi(wo, u_lobe, u1, u2)
        wi = jnp.where(fl[..., None], wi_fo, wi)

    # --- combined f/pdf over matching non-specular lobes -----------------
    f_ns, pdf_ns = bsdf_eval(mp, wo, wi)

    # --- specular materials ---------------------------------------------
    eta_s = mp.eta[..., 0]
    ct_o = cos_theta(wo)
    F = fresnel_dielectric(ct_o, jnp.ones_like(eta_s), eta_s)
    is_glass = mp.mtype == MAT_GLASS
    is_mirror = mp.mtype == MAT_MIRROR
    # mirror: perfect reflection, FresnelNoOp
    wi_mirror = jnp.stack([-wo[..., 0], -wo[..., 1], wo[..., 2]], axis=-1)
    f_mirror = mp.kr / jnp.maximum(abs_cos_theta(wi_mirror), 1e-12)[..., None]
    # glass: choose R/T by Fresnel using u_lobe
    reflect_g = u_lobe < F
    entering = ct_o > 0.0
    ei = jnp.where(entering, 1.0, eta_s)
    et = jnp.where(entering, eta_s, 1.0)
    eta_rel = ei / et
    # refract in local frame about +/- z
    n_loc = jnp.stack(
        [jnp.zeros_like(ct_o), jnp.zeros_like(ct_o), jnp.where(entering, 1.0, -1.0)], axis=-1
    )
    ci = jnp.abs(ct_o)
    sin2_t = eta_rel * eta_rel * jnp.maximum(0.0, 1.0 - ci * ci)
    ct_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - sin2_t))
    wi_refr = eta_rel[..., None] * -wo + (eta_rel * ci - ct_t)[..., None] * n_loc
    f_refl_g = (F / jnp.maximum(abs_cos_theta(wi_mirror), 1e-12))[..., None] * mp.kr
    # radiance transport: (ei/et)^2 factor
    f_trans_g = (
        ((1.0 - F) * (ei / et) ** 2 / jnp.maximum(jnp.abs(ct_t), 1e-12))[..., None] * mp.kt
    )
    wi_glass = jnp.where(reflect_g[..., None], wi_mirror, wi_refr)
    f_glass = jnp.where(reflect_g[..., None], f_refl_g, f_trans_g)
    pdf_glass = jnp.where(reflect_g, F, 1.0 - F)

    wi = jnp.where(is_mirror[..., None], wi_mirror, wi)
    wi = jnp.where(is_glass[..., None], wi_glass, wi)
    f = jnp.where(is_mirror[..., None], f_mirror, f_ns)
    f = jnp.where(is_glass[..., None], f_glass, f)
    pdf = jnp.where(is_mirror, 1.0, pdf_ns)
    pdf = jnp.where(is_glass, pdf_glass, pdf)

    # --- rough (microfacet) glass: override the delta-glass pick ---------
    # f/pdf come from the SAMPLED half-vector (pbrt Microfacet*::Sample_f
    # computes its pdf from the wh it drew) — reconstructing wh from wi
    # breaks down in f32 for the near-saturated slopes sample11 emits at
    # high alpha (identical degenerate whs -> D = 0 -> dropped samples)
    rg = _is_rough_glass(mp)
    wh_rg = tr_sample_wh(wo, u1, u2, mp.ax, mp.ay)
    refl_pick = u_lobe < 0.5  # pbrt BSDF uniform 2-lobe component choice
    wi_rg_r = -wo + 2.0 * jnp.sum(wo * wh_rg, axis=-1)[..., None] * wh_rg
    ct_o_rg = cos_theta(wo)
    eta_rel_rg = jnp.where(ct_o_rg > 0.0, 1.0 / jnp.maximum(eta_s, 1e-6), eta_s)
    wi_rg_t, tir_rg = _refract_about(wo, wh_rg, eta_rel_rg)
    wi_rg = jnp.where(refl_pick[..., None], wi_rg_r, wi_rg_t)

    f_r, p_r, ok_r2, f_t, p_t, ok_t2 = _mf_glass_terms(mp, wo, wi_rg, wh_rg)
    ok_rg = jnp.where(refl_pick, ok_r2, ok_t2 & ~tir_rg)
    f_rg = jnp.where(refl_pick[..., None], f_r, f_t)
    pdf_rg = jnp.where(refl_pick, p_r, p_t)
    wi = jnp.where(rg[..., None], wi_rg, wi)
    f = jnp.where((rg & ok_rg)[..., None], f_rg, jnp.where(rg[..., None], 0.0, f))
    pdf = jnp.where(rg, jnp.where(ok_rg, pdf_rg, 0.0), pdf)

    is_specular = (is_glass & ~rg) | is_mirror
    is_transmission = (is_glass & ~rg & ~reflect_g) | (flip_t & ~pick_g) | (
        rg & ~same_hemisphere(wo, wi)
    )
    if dz_bad is not None:
        dzl = mp.mtype == MAT_DISNEY
        pdf = jnp.where(dz_bad, 0.0, pdf)
        is_transmission = jnp.where(
            dzl, ~same_hemisphere(wo, wi), is_transmission
        )
    if mp.hz is not None:
        # hair has no radiance-scaling transmission; leave eta_scale alone
        is_transmission = jnp.where(
            mp.mtype == MAT_HAIR, jnp.zeros_like(is_transmission),
            is_transmission,
        )
    if mp.fz is not None:
        # the two-sided fourier sampler crosses hemispheres: medium
        # interfaces must switch exactly as for any transmitted ray
        is_transmission = jnp.where(
            mp.mtype == MAT_FOURIER, ~same_hemisphere(wo, wi),
            is_transmission,
        )
    dead = (mp.mtype == MAT_NONE) | (pdf <= 0.0)
    f = jnp.where(dead[..., None], 0.0, f)
    pdf = jnp.where(dead, 0.0, pdf)
    return BSDFSample(wi, f, pdf, is_specular, is_transmission)
