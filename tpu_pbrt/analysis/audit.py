"""Layer 2: jaxpr / compile-time audit of the real render entry points.

Where the AST lint reasons about source text, this layer traces the
actual programs the renderer dispatches and asserts the TPU hot-path
invariants on what XLA will really see:

- **no f64**: every aval in the jaxpr (including sub-jaxprs of
  while/cond/scan) is <= 32-bit. A single silently-promoted f64 doubles
  HBM traffic for that buffer and falls off the MXU fast path.
- **no callbacks**: no `pure_callback` / `debug_callback` / `io_callback`
  primitives — a leftover debug print in the bounce loop is a host
  round-trip per wave.
- **donation materialized**: the film/pool chunk functions are compiled
  and the executable's `input_output_alias` table must alias EVERY film
  buffer input to an output (donate_argnums that silently fails to alias
  is how PR 1's resume path double-allocated, and donating a
  numpy-aliased buffer is how it corrupted the heap).
- **zero retraces**: two same-shape waves reuse one cached executable —
  the jit cache must not grow between chunk 1 and chunk N.
- **transfer hygiene**: a smoke render completes under
  `jax.transfer_guard("disallow")` — every host<->device crossing in the
  loop is explicit (device_put/device_get), so a new implicit sync shows
  up as a hard error, not a silent stall.

Entry points audited here: the PathIntegrator fixed-batch wave and the
persistent pool drain, stream BVH traversal, the film deposit paths, and
the sharded_pool_renderer mesh step. tests/test_jaxpr_audit.py adds the
volpath/sppm/bdpt integrators (xfail where a violation is known and
ROADMAP-tracked, so the suite documents debt instead of hiding it).

Everything is pure-trace (jax.make_jaxpr) except the donation /
recompile / transfer-guard checks, which compile tiny-scene programs.
"""

from __future__ import annotations

import contextlib
from functools import lru_cache
from typing import List

import numpy as np


@contextlib.contextmanager
def forced_tracer(fused: bool):
    """Trace-time override of the stream tracer mode (TPU_PBRT_FUSED is
    auto-off on CPU, where every audit runs): flips cfg.fused and drops
    the stream tracer's module-level jit caches on BOTH sides, so the
    fused entry points really trace the fused program and later
    default-mode entries don't inherit it via the aval-keyed caches."""
    from tpu_pbrt import config
    from tpu_pbrt.accel.stream import clear_traverse_caches

    old = config.cfg.fused
    config.cfg.fused = fused
    clear_traverse_caches()
    try:
        yield
    finally:
        config.cfg.fused = old
        clear_traverse_caches()

# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

_CALLBACK_PRIMITIVES = {
    "pure_callback",
    "debug_callback",
    "io_callback",
    "outside_call",
}


def _sub_jaxprs(v):
    from jax import core

    if isinstance(v, core.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, core.Jaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        out = []
        for item in v:
            out.extend(_sub_jaxprs(item))
        return out
    return []


def iter_jaxprs(jaxpr):
    """Yield `jaxpr` and every sub-jaxpr (while/cond/scan/pjit bodies)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_jaxprs(sub)


def find_f64(closed_jaxpr) -> List[str]:
    """Descriptions of every 64-bit value in the jaxpr (empty = clean)."""
    bad: List[str] = []
    wide = ("float64", "int64", "uint64", "complex128")
    for j in iter_jaxprs(closed_jaxpr.jaxpr):
        for v in list(j.constvars) + list(j.invars) + list(j.outvars):
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and str(dt) in wide:
                bad.append(f"var {v} : {dt}")
        for eqn in j.eqns:
            for v in eqn.outvars:
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is not None and str(dt) in wide:
                    bad.append(f"{eqn.primitive.name} -> {dt}")
    return bad


def find_callbacks(closed_jaxpr) -> List[str]:
    """Names of callback primitives present in the jaxpr (empty = clean)."""
    found: List[str] = []
    for j in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name in _CALLBACK_PRIMITIVES:
                found.append(eqn.primitive.name)
    return found


# --------------------------------------------------------------------------
# audited scenes (built once per process; tiny but real — they exercise the
# stream tracer, the area light, the matte BSDF and the box film)
# --------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _stream_scene(integrator: str = "path", spp: int = 2):
    """~2.2k-triangle killeroo-like scene — big enough for the stream
    (treelet worklist) acceleration path, small enough to trace fast."""
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(
        res=16, spp=spp, integrator=integrator, maxdepth=3,
        n_theta=24, n_phi=48,
    )
    return compile_api(api)


@lru_cache(maxsize=None)
def _cornell_scene(integrator: str, spp: int = 2):
    from tpu_pbrt.scenes import compile_api, make_cornell

    api = make_cornell(res=16, spp=spp, integrator=integrator, maxdepth=3)
    return compile_api(api)


@lru_cache(maxsize=None)
def _media_scene(spp: int = 2):
    """Homogeneous-fog scene for the volpath entry point (volpath's li
    requires a compiled MediumTable in dev)."""
    from tpu_pbrt.scene.api import Options, parse_string, pbrt_init
    from tpu_pbrt.scenes import compile_api

    api = pbrt_init(Options(quiet=True))
    parse_string(
        f"""
Integrator "volpath" "integer maxdepth" [3]
Sampler "zerotwosequence" "integer pixelsamples" [{spp}]
PixelFilter "box"
Film "image" "integer xresolution" [16] "integer yresolution" [16] "string filename" [""]
LookAt 0 0 -3  0 0 0  0 1 0
MakeNamedMedium "fog" "string type" "homogeneous" "rgb sigma_a" [0.05 0.05 0.05] "rgb sigma_s" [0.4 0.4 0.4] "float g" [0.0]
MediumInterface "" "fog"
Camera "perspective" "float fov" [50]
WorldBegin
AttributeBegin
AreaLightSource "diffuse" "rgb L" [8 8 8]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-1 2.9 -1  1 2.9 -1  1 2.9 1  -1 2.9 1]
AttributeEnd
Material "matte" "rgb Kd" [0.6 0.6 0.6]
Shape "trianglemesh" "integer indices" [0 1 2 0 2 3] "point P" [-4 -1 2  -4 3 2  4 3 2  4 -1 2]
""",
        api,
        render=False,
    )
    return compile_api(api)


def integrator_li_jaxpr(integrator: str = "path", scene_kind: str = "stream"):
    """Trace <integrator>'s fixed-batch li over a 64-ray wave and return
    the ClosedJaxpr — the object the f64/callback assertions run over."""
    import jax
    import jax.numpy as jnp

    if scene_kind == "media":
        scene, integ = _media_scene()
    elif scene_kind == "stream":
        scene, integ = _stream_scene(integrator)
    else:
        scene, integ = _cornell_scene(integrator)
    dev = scene.dev
    n = 64
    o = jnp.zeros((n, 3), jnp.float32)
    d = jnp.tile(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 1))
    px = jnp.zeros((n,), jnp.int32)
    py = jnp.zeros((n,), jnp.int32)
    s = jnp.zeros((n,), jnp.int32)
    return jax.make_jaxpr(
        lambda o, d, px, py, s: integ.li(dev, o, d, px, py, s)
    )(o, d, px, py, s)


def pool_chunk_jaxpr(fused: bool = False):
    """Trace the persistent-wavefront pool drain (compaction +
    regeneration + deposit) and return the ClosedJaxpr. fused=True
    traces the TPU_PBRT_FUSED=1 program (Pallas wavefront kernels in
    interpret mode) — the budgeted serving/TPU hot path."""
    import jax
    import jax.numpy as jnp

    scene, integ = _stream_scene("path")
    film = scene.film

    def fn(fs, start_pix, start_s):
        return integ.pool_chunk(
            scene.dev, fs, start_pix, start_s, 256, 64,
            film=film, cam=scene.camera,
        )

    with forced_tracer(fused):
        return jax.make_jaxpr(fn)(
            film.init_state(), jnp.int32(0), jnp.int32(0)
        )


def stream_traversal_jaxpr(fused: bool = False):
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.accel.stream import stream_intersect

    scene, _ = _stream_scene("path")
    dev = scene.dev
    n = 128
    o = jnp.zeros((n, 3), jnp.float32)
    d = jnp.tile(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 1))
    with forced_tracer(fused):
        return jax.make_jaxpr(
            lambda o, d: stream_intersect(
                dev["tstream"], dev["tri_verts"], o, d, jnp.inf,
                tv9T=dev.get("tri_verts9T"),
            )
        )(o, d)


def film_deposit_jaxpr(pixel_path: bool = False):
    import jax
    import jax.numpy as jnp

    scene, _ = _stream_scene("path")
    film = scene.film
    n = 64
    L = jnp.zeros((n, 3), jnp.float32)
    wt = jnp.ones((n,), jnp.float32)
    if pixel_path:
        px = jnp.zeros((n,), jnp.int32)
        done = jnp.ones((n,), bool)
        return jax.make_jaxpr(
            lambda fs, px, py, L: film.add_samples_pixel(
                fs, px, py, L, done, wt
            )
        )(film.init_state(), px, px, L)
    pf = jnp.zeros((n, 2), jnp.float32)
    return jax.make_jaxpr(
        lambda fs, pf, L: film.add_samples(fs, pf, L, wt)
    )(film.init_state(), pf, L)


def sppm_pass_jaxprs():
    """Trace SPPM's two jitted passes (camera visible-point gather and
    photon trace+deposit) and return both ClosedJaxprs."""
    import jax
    import jax.numpy as jnp

    scene, integ = _cornell_scene("sppm")
    dev = scene.dev
    n = 64
    px = jnp.zeros((n,), jnp.int32)
    py = jnp.zeros((n,), jnp.int32)
    cam = jax.make_jaxpr(
        lambda px, py: integ._camera_pass(dev, px, py, 0)
    )(px, py)
    photon = jax.make_jaxpr(
        lambda: integ._photon_pass(dev, 64, 0)
    )()
    return cam, photon


def serve_step_jaxpr():
    """Trace the render service's slice-dispatch entry point (ISSUE 6):
    the ChunkPlan closure the service schedules one chunk-slice of per
    step, at a service-shaped slice width (smaller than the batch
    chunk — the preemption quantum). This is the program every serve
    dispatch runs, so the budget gate covers the serving hot path even
    with the accelerator down."""
    import jax
    import jax.numpy as jnp

    scene, integ = _stream_scene("path")
    film = scene.film
    plan = integ.prepare_chunks(scene, chunk=256)

    def fn(fs, start_pix, start_s):
        return plan.jfn(fs, scene.dev, start_pix, start_s)

    return jax.make_jaxpr(fn)(
        film.init_state(), jnp.int32(0), jnp.int32(0)
    )


def mesh_step_jaxpr(fused: bool = False):
    """Trace the sharded_pool_renderer SPMD step over a 1..n-device CPU
    mesh (the ICI film-merge psum + per-device drain). fused=True puts
    the Pallas wavefront kernels inside the shard_map body — the
    program shardcheck must prove collective-safe for TPU_PBRT_FUSED=1
    mesh renders."""
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.core.film import merge_film
    from tpu_pbrt.parallel.mesh import (
        device_spread,
        make_mesh,
        sharded_pool_renderer,
    )

    scene, integ = _stream_scene("path")
    film = scene.film
    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)

    def per_device_fn(dev, start):
        # telemetry counters AND the one-hot wave-spread vector ride the
        # aux psum exactly as the real render loop threads them
        # (common.py per_device_fn), so the audited program IS the
        # dispatched one — a regression inside device_spread or the
        # counter carry must drift this fingerprint and fail the budget/
        # shardcheck gates; both are None (empty pytrees) under
        # TPU_PBRT_TELEMETRY=0
        fs2, nrays, live, waves, trunc, ctr = integ.pool_chunk(
            dev, film.init_state(), start[0, 0], start[0, 1], 128, 64,
            film=film, cam=scene.camera,
        )
        spread = device_spread(waves, n_dev) if ctr is not None else None
        return fs2, (nrays, live, waves, trunc, ctr, spread)

    step = sharded_pool_renderer(mesh, per_device_fn)

    def fn(fs, starts):
        contrib, aux = step(scene.dev, starts)
        return merge_film(fs, contrib), aux

    starts = jnp.zeros((n_dev, 2), jnp.int32)
    with forced_tracer(fused):
        return jax.make_jaxpr(fn)(film.init_state(), starts)


# --------------------------------------------------------------------------
# compile-time checks
# --------------------------------------------------------------------------


def donation_aliases(compiled_text: str) -> int:
    """Number of aliased inputs in a compiled HLO module. The
    `may-alias`/`must-alias` markers appear only inside the module's
    input_output_alias table, so a plain count is exact."""
    if "input_output_alias=" not in compiled_text:
        return 0
    return compiled_text.count("may-alias") + compiled_text.count(
        "must-alias"
    )


def check_film_donation(fused: bool = False) -> List[str]:
    """Compile the pool chunk function with the render loop's
    donate_argnums and assert every FilmState buffer is aliased
    input->output in the EXECUTABLE (not just requested). fused=True
    compiles the TPU_PBRT_FUSED=1 program — donation must survive the
    Pallas calls in the drain loop."""
    import jax
    import jax.numpy as jnp

    scene, integ = _stream_scene("path")
    film = scene.film

    def chunk_fn(fs, start_pix, start_s):
        out = integ.pool_chunk(
            scene.dev, fs, start_pix, start_s, 256, 64,
            film=film, cam=scene.camera,
        )
        return out[0]

    jfn = jax.jit(chunk_fn, donate_argnums=(0,))
    with forced_tracer(fused):
        txt = (
            jfn.lower(film.init_state(), jnp.int32(0), jnp.int32(0))
            .compile()
            .as_text()
        )
    n_leaves = len(jax.tree.leaves(film.init_state()))
    n_alias = donation_aliases(txt)
    if n_alias < n_leaves:
        return [
            f"film donation not materialized ({'fused' if fused else 'jnp'}"
            f" tracer): {n_alias} aliased buffers "
            f"in the executable, expected >= {n_leaves} (FilmState leaves)"
        ]
    return []


def check_recompile_guard(fused: bool = False) -> List[str]:
    """Render two same-shape waves through the real render loop and
    assert the jit cache did not grow — retraces in the chunk loop
    would pay compile time per chunk instead of per scene. fused=True
    runs the TPU_PBRT_FUSED=1 program (Pallas interpret mode on CPU):
    the fused tracer must also compile exactly once."""
    scene, integ = _stream_scene("path")
    with forced_tracer(fused):
        integ.render(scene)
        jfn = integ._jit_cache[1]
        size_after_first = jfn._cache_size()
        integ.render(scene)
        jfn2 = integ._jit_cache[1]
    fails = []
    if jfn2 is not jfn:
        fails.append("second same-shape render rebuilt the chunk closure")
    if jfn2._cache_size() > size_after_first:
        fails.append(
            f"jit cache grew across same-shape renders "
            f"({size_after_first} -> {jfn2._cache_size()})"
        )
    if size_after_first > 1:
        fails.append(
            f"first render traced {size_after_first} chunk variants "
            "(expected one executable for the whole wave loop)"
        )
    return fails


def check_transfer_guard() -> List[str]:
    """Smoke render under jax.transfer_guard('disallow'): every implicit
    host<->device transfer in the render loop is a hard error."""
    import jax

    scene, integ = _stream_scene("path", spp=1)
    try:
        with jax.transfer_guard("disallow"):
            res = integ.render(scene)
    except Exception as e:
        # only a guard trip is THIS finding; anything else (capacity
        # audit, OOM, ...) must be reported as its own crash, not as a
        # phantom host sync
        if "transfer" in str(e).lower():
            return [f"implicit transfer in the render loop: {e}"]
        raise
    img = np.asarray(res.image, np.float32)
    if not np.isfinite(img).all():
        return ["smoke render under transfer_guard produced non-finite pixels"]
    return []


# --------------------------------------------------------------------------
# suite driver
# --------------------------------------------------------------------------


def _jaxpr_invariants(name: str, closed_jaxpr) -> List[str]:
    fails = []
    f64 = find_f64(closed_jaxpr)
    if f64:
        fails.append(f"{name}: f64 in jaxpr ({f64[0]}; {len(f64)} total)")
    cbs = find_callbacks(closed_jaxpr)
    if cbs:
        fails.append(f"{name}: callback primitives {sorted(set(cbs))}")
    return fails


def run_audit(include_compile: bool = True) -> List[str]:
    """Run every audit; returns failure strings (empty = all invariants
    hold). Exceptions are reported as failures, not raised — the CLI
    must always print a complete report."""
    failures: List[str] = []
    checks = [
        ("path.li jaxpr", lambda: _jaxpr_invariants(
            "path.li", integrator_li_jaxpr("path"))),
        ("pool_chunk jaxpr", lambda: _jaxpr_invariants(
            "pool_chunk", pool_chunk_jaxpr())),
        ("stream traversal jaxpr", lambda: _jaxpr_invariants(
            "stream_intersect", stream_traversal_jaxpr())),
        # the TPU_PBRT_FUSED=1 programs (Pallas wavefront kernels,
        # interpret mode on CPU) hold the same invariants: a stray f64
        # or callback inside the kernels would sink the TPU hot path
        ("fused stream traversal jaxpr", lambda: _jaxpr_invariants(
            "stream_intersect[fused]", stream_traversal_jaxpr(fused=True))),
        ("fused pool_chunk jaxpr", lambda: _jaxpr_invariants(
            "pool_chunk[fused]", pool_chunk_jaxpr(fused=True))),
        ("film deposit jaxpr", lambda: _jaxpr_invariants(
            "film.add_samples", film_deposit_jaxpr())),
        ("film pixel-deposit jaxpr", lambda: _jaxpr_invariants(
            "film.add_samples_pixel", film_deposit_jaxpr(pixel_path=True))),
        ("mesh step jaxpr", lambda: _jaxpr_invariants(
            "sharded_pool_renderer", mesh_step_jaxpr())),
        ("serve step jaxpr", lambda: _jaxpr_invariants(
            "serve_step", serve_step_jaxpr())),
    ]
    if include_compile:
        checks += [
            ("film donation", check_film_donation),
            ("recompile guard", check_recompile_guard),
            ("fused film donation",
             lambda: check_film_donation(fused=True)),
            ("fused recompile guard",
             lambda: check_recompile_guard(fused=True)),
            ("transfer guard", check_transfer_guard),
        ]
    for label, fn in checks:
        try:
            failures.extend(fn())
        except Exception as e:  # noqa: BLE001
            failures.append(f"{label}: audit crashed: {type(e).__name__}: {e}")
    return failures
