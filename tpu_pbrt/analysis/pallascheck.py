"""pallascheck — static VMEM-budget and grid-semantics verification of
the fused Pallas kernels (analysis layer 5).

The fused wavefront kernels (accel/fusedwave.py) rest on invariants that
lived only as prose until this pass: the VMEM budget math was a module
docstring, the matching TPU_PBRT_FUSED_MAX_RAYS / MAX_NODES caps were
hand-set constants, and the bit-identity proof of the closest-hit merge
explicitly relies on sequential TPU grid order for the constant-index_map
accumulator outputs. Every stage-two megakernel (in-kernel segmented
merge, compaction scatter, BSDF shading) and the quantized-treelet node
format adds more VMEM-resident accumulators resting on the same
assumptions. This pass machine-checks them, one layer below where the
suite stopped: it walks the entry-point jaxprs (audit.py's registry),
extracts every `pallas_call` (grid, BlockSpecs/index_maps, scratch,
dimension semantics) and verifies two things.

**VMEM model.** The exact per-grid-step VMEM footprint per kernel:
operand blocks whose index_map varies across the grid are charged
double-buffered (x2 — Mosaic overlaps the next step's DMA with compute),
constant-index_map blocks stay resident across the whole grid and are
charged once, scratch is charged flat; scalar-prefetch operands live in
SMEM and are reported separately. The rollup is committed to
`tpu_pbrt/analysis/vmem_budgets.json` and gated with the same
10%-tolerance / `--update-budgets` workflow as jaxcost, plus a hard
capacity check against per-platform VMEM with headroom (PC-VMEM). On top
of the gate, `derive_caps()` inverts the model — the footprint is affine
in the wave width R (flush) and the node count N (expand) — so the
maximal safe TPU_PBRT_FUSED_MAX_RAYS / MAX_NODES are *derived* per
platform and the hand-set caps in config.py become a checked consequence
(PC-CAPS) instead of folklore. `python -m tpu_pbrt.analysis.pallascheck
--derive-caps` prints the table.

**Grid-semantics rules**, via abstract interpretation of the kernel-body
jaxpr with intervals over `program_id`:

PC-RACE   an output ref revisited across grid steps (constant index_map
          — the accumulator pattern) while its grid dim is declared
          "parallel": under megacore the two cores interleave grid
          steps and the read-modify-write merge silently races. The
          fused flush's ordered merge is EXACTLY this shape — its grid
          dim must stay "arbitrary" (sequential), which fusedwave now
          declares explicitly.
PC-INIT   a revisited output or scratch ref read before any write that
          provably executes on grid step 0 seeds it — the
          `@pl.when(b == 0)` accumulator seed in `_flush_kernel`;
          deleting it turns the repo gate red with this finding.
PC-OOB    a dynamic in-kernel ref load/store whose index interval
          cannot be proven inside the block shape (the scalar-prefetch-
          meta-driven gathers are the motivating class: their ray ids
          come from HBM, so the kernel must clamp before indexing for
          the proof to close).

Like jaxcost, everything is a pure trace: the gate works with the TPU
down. Deliberate violations go in `WAIVERS` with a written reason.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# platform model
# --------------------------------------------------------------------------

#: VMEM bytes per TensorCore (the Pallas operating target; see
#: /opt/skills guides — ~16 MB/core across current TPU generations)
VMEM_BYTES: Dict[str, int] = {
    "v4": 16 * 1024 * 1024,
    "v5e": 16 * 1024 * 1024,
    "v5p": 16 * 1024 * 1024,
}
#: fraction of VMEM the model may plan against — the rest stays free for
#: Mosaic's own temporaries (the flush kernel's phi/out4 intermediates),
#: semaphores and compiler slack
VMEM_HEADROOM = 0.85

BUDGETS_PATH = Path(__file__).resolve().parent / "vmem_budgets.json"
DEFAULT_TOLERANCE = 0.10

#: (rule, entry substring, detail substring) -> reason; waived findings
#: stay visible (severity "info") but do not fail the gate
WAIVERS: List[Tuple[str, str, str, str]] = []


def _waiver_for(rule: str, entry: str, detail: str) -> Optional[str]:
    for r, e, d, reason in WAIVERS:
        if r == rule and e in entry and d in detail:
            return reason
    return None


@dataclass(frozen=True)
class PallasFinding:
    rule: str
    entry: str
    kernel: str
    detail: str
    severity: str = "error"
    waived: Optional[str] = None

    def __str__(self) -> str:
        w = f" (waived: {self.waived})" if self.waived else ""
        return (
            f"{self.entry}: {self.rule} [{self.severity}] "
            f"kernel {self.kernel}: {self.detail}{w}"
        )


# --------------------------------------------------------------------------
# pallas_call extraction
# --------------------------------------------------------------------------


@dataclass
class Operand:
    """One kernel ref: a mapped input/output block, a scratch buffer or a
    scalar-prefetch operand."""

    kind: str  # "prefetch" | "in" | "out" | "scratch"
    name: str  # BlockMapping origin / kernel param position
    ref_shape: Tuple[int, ...]  # shape the kernel body indexes
    itemsize: int
    grid_axes: frozenset  # grid axes the index_map output depends on

    @property
    def block_bytes(self) -> int:
        n = 1
        for s in self.ref_shape:
            n *= int(s)
        return n * self.itemsize

    @property
    def bytes_per_step(self) -> int:
        """VMEM charge: double-buffered when the block moves with the
        grid, resident-once when it does not; scratch flat; prefetch is
        SMEM (charged separately)."""
        if self.kind == "prefetch":
            return 0
        if self.kind in ("in", "out") and self.grid_axes:
            return 2 * self.block_bytes
        return self.block_bytes

    @property
    def revisited(self) -> bool:
        """Same block every grid step — the VMEM-resident accumulator
        pattern the grid-semantics rules reason about."""
        return self.kind == "out" and not self.grid_axes


@dataclass
class KernelInfo:
    entry: str
    name: str
    key: str
    grid: Tuple[int, ...]
    dimension_semantics: Tuple[str, ...]
    operands: List[Operand]
    jaxpr: object = field(repr=False, default=None)  # kernel body (open)

    @property
    def grid_steps(self) -> int:
        n = 1
        for g in self.grid:
            n *= max(int(g), 1)
        return n

    @property
    def vmem_bytes(self) -> int:
        return sum(op.bytes_per_step for op in self.operands)

    @property
    def smem_bytes(self) -> int:
        return sum(
            op.block_bytes for op in self.operands if op.kind == "prefetch"
        )

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha256()
        h.update(f"{self.grid}{self.dimension_semantics}".encode())
        for op in self.operands:
            h.update(
                f"{op.kind}{op.ref_shape}{op.itemsize}"
                f"{sorted(op.grid_axes)}".encode()
            )
        return h.hexdigest()[:16]

    def to_json(self) -> Dict:
        return {
            "vmem_bytes_per_step": self.vmem_bytes,
            "smem_bytes": self.smem_bytes,
            "grid_steps": self.grid_steps,
            "fingerprint": self.fingerprint,
        }


def _index_map_grid_axes(bm, n_grid: int) -> frozenset:
    """Grid axes an operand's block index depends on: forward taint of
    the index_map jaxpr from its grid-index invars (invars past n_grid
    are scalar-prefetch operands — a block picked by `m[i, 0]` varies
    with axis i *through* the gather, which the union transfer sees)."""
    from jax import core

    closed = bm.index_map_jaxpr
    jaxpr = closed.jaxpr if isinstance(closed, core.ClosedJaxpr) else closed
    taint: Dict[int, frozenset] = {}
    for k, v in enumerate(jaxpr.invars):
        taint[id(v)] = frozenset([k]) if k < n_grid else frozenset()

    def run(j):
        for eqn in j.eqns:
            t = frozenset()
            for v in eqn.invars:
                if hasattr(v, "count"):  # Var, not Literal
                    t |= taint.get(id(v), frozenset())
            for sub in eqn.params.values():
                for s in _sub_jaxprs(sub):
                    for iv, ov in zip(eqn.invars, s.invars):
                        if hasattr(iv, "count"):
                            taint[id(ov)] = taint.get(id(iv), frozenset())
                    run(s)
                    for sv, ov in zip(s.outvars, eqn.outvars):
                        if hasattr(sv, "count"):
                            t |= taint.get(id(sv), frozenset())
            for v in eqn.outvars:
                taint[id(v)] = taint.get(id(v), frozenset()) | t

    run(jaxpr)
    out = frozenset()
    for v in jaxpr.outvars:
        if hasattr(v, "count"):
            out |= taint.get(id(v), frozenset())
    return out


def _sub_jaxprs(v):
    from tpu_pbrt.analysis.audit import _sub_jaxprs as audit_subs

    return audit_subs(v)


def _ref_shape(aval) -> Tuple[int, ...]:
    return tuple(int(s) for s in getattr(aval, "shape", ()) or ())


def _itemsize(dt) -> int:
    return int(getattr(dt, "itemsize", 4) or 4)


def _dimension_semantics(eqn, n_grid: int) -> Tuple[str, ...]:
    cp = eqn.params.get("compiler_params") or {}
    if hasattr(cp, "to_json") or not isinstance(cp, dict):  # dataclass form
        cp = getattr(cp, "__dict__", {}) or {}
    mosaic = cp.get("mosaic") or {}
    if not isinstance(mosaic, dict):
        mosaic = getattr(mosaic, "__dict__", {}) or {}
    sem = mosaic.get("dimension_semantics")
    if not sem:
        # Mosaic's default for an undeclared dim is "arbitrary"
        # (sequential); fusedwave declares it explicitly so the repo
        # relies on the declaration, not the default
        return ("arbitrary",) * n_grid
    return tuple(str(s) if s else "arbitrary" for s in sem)


def extract_kernels(closed_jaxpr, entry: str) -> List[KernelInfo]:
    """Every pallas_call under `closed_jaxpr` (including inside pjit /
    while / cond bodies) as a KernelInfo, in deterministic walk order."""
    from jax import core

    from tpu_pbrt.analysis.audit import iter_jaxprs

    infos: List[KernelInfo] = []
    seen: Dict[str, int] = {}
    for j in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name != "pallas_call":
                continue
            gm = eqn.params["grid_mapping"]
            grid = tuple(int(g) for g in (getattr(gm, "grid", ()) or ()))
            n_grid = len(grid)
            n_idx = int(getattr(gm, "num_index_operands", 0) or 0)
            n_out = int(
                getattr(gm, "num_outputs", len(eqn.outvars))
                or len(eqn.outvars)
            )
            bms = list(getattr(gm, "block_mappings", ()) or ())
            n_in = int(getattr(gm, "num_inputs", len(bms) - n_out) or 0)
            n_scr = int(getattr(gm, "num_scratch_operands", 0) or 0)
            kernel = eqn.params.get("jaxpr")
            body = kernel.jaxpr if isinstance(
                kernel, core.ClosedJaxpr
            ) else kernel
            nsi = eqn.params.get("name_and_src_info")
            name = getattr(nsi, "name", None) or str(nsi or "kernel")
            invars = list(body.invars) if body is not None else []

            operands: List[Operand] = []
            for k in range(n_idx):
                aval = getattr(invars[k], "aval", None) if k < len(
                    invars
                ) else None
                operands.append(Operand(
                    "prefetch", f"prefetch[{k}]", _ref_shape(aval),
                    _itemsize(getattr(aval, "dtype", None)), frozenset(),
                ))
            for k, bm in enumerate(bms):
                kind = "in" if k < n_in else "out"
                shape = tuple(
                    int(s) for s in bm.block_shape if s is not None
                )
                dt = getattr(bm.array_shape_dtype, "dtype", None)
                operands.append(Operand(
                    kind, str(getattr(bm, "origin", f"{kind}[{k}]")),
                    shape, _itemsize(dt),
                    _index_map_grid_axes(bm, n_grid),
                ))
            for k in range(n_scr):
                v = invars[n_idx + n_in + n_out + k] if (
                    n_idx + n_in + n_out + k < len(invars)
                ) else None
                aval = getattr(v, "aval", None)
                operands.append(Operand(
                    "scratch", f"scratch[{k}]", _ref_shape(aval),
                    _itemsize(getattr(aval, "dtype", None)), frozenset(),
                ))

            base = f"{entry}::{name}"
            n = seen.get(base, 0)
            seen[base] = n + 1
            infos.append(KernelInfo(
                entry=entry, name=name,
                key=base if n == 0 else f"{base}#{n}",
                grid=grid,
                dimension_semantics=_dimension_semantics(eqn, n_grid),
                operands=operands, jaxpr=body,
            ))
    # a second pallas_call with the same kernel name forces the suffix
    # onto the FIRST occurrence too, so keys stay stable when one is added
    for info in infos:
        if seen.get(f"{info.entry}::{info.name}", 0) > 1 and "#" not in info.key:
            info.key = f"{info.entry}::{info.name}#0"
    return infos


# --------------------------------------------------------------------------
# interval domain for the kernel-body abstract interpreter
# --------------------------------------------------------------------------

_INF = math.inf


class _Iv(tuple):
    """Closed interval [lo, hi] over reals; TOP = (-inf, inf)."""

    __slots__ = ()

    def __new__(cls, lo, hi):
        return super().__new__(cls, (float(lo), float(hi)))

    @property
    def lo(self):
        return self[0]

    @property
    def hi(self):
        return self[1]


_TOP = _Iv(-_INF, _INF)
_BOOL = _Iv(0, 1)


def _iv_join(a: _Iv, b: _Iv) -> _Iv:
    return _Iv(min(a.lo, b.lo), max(a.hi, b.hi))


def _iv_add(a: _Iv, b: _Iv) -> _Iv:
    return _Iv(a.lo + b.lo, a.hi + b.hi)


def _iv_sub(a: _Iv, b: _Iv) -> _Iv:
    return _Iv(a.lo - b.hi, a.hi - b.lo)


def _iv_mul(a: _Iv, b: _Iv) -> _Iv:
    cs = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            if (x in (-_INF, _INF) and y == 0) or (
                y in (-_INF, _INF) and x == 0
            ):
                cs.append(0.0)
            else:
                cs.append(x * y)
    return _Iv(min(cs), max(cs))


def _iv_max(a: _Iv, b: _Iv) -> _Iv:
    return _Iv(max(a.lo, b.lo), max(a.hi, b.hi))


def _iv_min(a: _Iv, b: _Iv) -> _Iv:
    return _Iv(min(a.lo, b.lo), min(a.hi, b.hi))


def _iv_lit(val) -> _Iv:
    import numpy as np

    try:
        arr = np.asarray(val)
        if arr.size == 0 or not np.issubdtype(arr.dtype, np.number):
            return _TOP
        return _Iv(float(arr.min()), float(arr.max()))
    except Exception:  # noqa: BLE001 — non-numeric literal
        return _TOP


# --------------------------------------------------------------------------
# the kernel-body walker (PC-OOB over all grid steps, PC-INIT at step 0)
# --------------------------------------------------------------------------


class _RefState:
    __slots__ = ("name", "shape", "tracked", "init")

    def __init__(self, name: str, shape: Tuple[int, ...],
                 tracked: bool, init: bool):
        self.name = name
        self.shape = shape
        self.tracked = tracked
        self.init = init


class _KernelWalk:
    """One pass over the kernel body. mode="oob": program_id spans the
    full grid and dynamic ref indices are bounds-checked. mode="init":
    program_id is pinned to grid step 0 and revisited-output/scratch
    refs are checked for read-before-seed (must-analysis: a write only
    initializes when it definitely executes and covers the full ref)."""

    def __init__(self, info: KernelInfo, mode: str):
        self.info = info
        self.mode = mode
        self.findings: List[PallasFinding] = []
        self.env: Dict[int, _Iv] = {}
        self.refs: Dict[int, _RefState] = {}
        #: outvars of a swap on a not-yet-seeded tracked ref: the
        #: RETURNED OLD VALUE is uninitialized VMEM — a write is only a
        #: read-before-seed if that value is actually consumed, so the
        #: finding fires at the first USE, not at the swap itself (the
        #: seed is itself a swap whose old value is discarded)
        self._uninit_vals: set = set()

    # -- findings ------------------------------------------------------
    def _emit(self, rule: str, detail: str) -> None:
        waived = _waiver_for(rule, self.info.entry, detail)
        f = PallasFinding(
            rule, self.info.entry, self.info.name, detail,
            severity="info" if waived else "error", waived=waived,
        )
        if f not in self.findings:
            self.findings.append(f)

    # -- env helpers ---------------------------------------------------
    def _read(self, v) -> _Iv:
        if not hasattr(v, "count"):  # Literal
            return _iv_lit(getattr(v, "val", None))
        return self.env.get(id(v), _TOP)

    def _write(self, v, iv: _Iv) -> None:
        self.env[id(v)] = iv

    def _bind_ref(self, inner_v, outer_v) -> None:
        if hasattr(outer_v, "count") and id(outer_v) in self.refs:
            self.refs[id(inner_v)] = self.refs[id(outer_v)]

    # -- the walk ------------------------------------------------------
    def run(self) -> List[PallasFinding]:
        ops = self.info.operands
        invars = list(self.info.jaxpr.invars)
        for v, op in zip(invars, ops):
            tracked = op.revisited or op.kind == "scratch"
            self.refs[id(v)] = _RefState(
                op.name, op.ref_shape, tracked,
                init=not tracked,  # inputs/prefetch arrive DMA'd
            )
        self._eval_body(self.info.jaxpr, definite=True, collect=True)
        return self.findings

    def _eval_body(self, jaxpr, definite: bool, collect: bool) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if (
                self.mode == "init" and collect and self._uninit_vals
                and any(
                    hasattr(v, "count") and id(v) in self._uninit_vals
                    for v in eqn.invars
                )
            ):
                self._emit(
                    "PC-INIT",
                    "a value swapped out of a revisited ref before any "
                    "grid-step-0 write seeds it is consumed — the old "
                    "value is uninitialized VMEM on step 0",
                )
            handler = getattr(self, f"_p_{name}", None)
            if handler is not None:
                handler(eqn, definite, collect)
            elif name in ("cond",):
                self._do_cond(eqn, definite, collect)
            elif name == "scan":
                self._do_scan(eqn, definite, collect)
            elif name == "while":
                self._do_while(eqn, definite, collect)
            elif name in _CALL_LIKE:
                self._do_call(eqn, definite, collect)
            else:
                self._transfer(eqn)

    # -- ref ops -------------------------------------------------------
    def _indexers(self, eqn, n_skip: int):
        """Reconstruct the NDIndexer tuple from the flattened dynamic
        leaves (invars past the ref [and stored value])."""
        import jax

        tree = eqn.params.get("tree")
        if tree is None:
            return None
        leaves = list(eqn.invars[n_skip:])
        try:
            return jax.tree_util.tree_unflatten(tree, leaves)
        except Exception:  # noqa: BLE001 — future indexer pytree drift
            return None

    def _check_bounds(self, st: _RefState, indexers, collect: bool) -> None:
        if self.mode != "oob" or not collect or indexers is None:
            return
        for nd in indexers:
            idx = getattr(nd, "indices", None)
            if idx is None:
                continue
            for d, ix in enumerate(idx):
                if d >= len(st.shape):
                    break
                dim = int(st.shape[d])
                start = getattr(ix, "start", None)
                if start is not None:  # a Slice
                    size = int(getattr(ix, "size", 1) or 1)
                    iv = (
                        _Iv(start, start)
                        if isinstance(start, int)
                        else self._read(start)
                    )
                    lo, hi = iv.lo, iv.hi + (size - 1)
                else:
                    iv = (
                        _Iv(ix, ix) if isinstance(ix, int)
                        else self._read(ix)
                    )
                    lo, hi = iv.lo, iv.hi
                if lo < 0 or hi > dim - 1:
                    shown = (
                        "unbounded" if (lo == -_INF or hi == _INF)
                        else f"[{int(lo)}, {int(hi)}]"
                    )
                    self._emit(
                        "PC-OOB",
                        f"ref {st.name} dim {d}: dynamic index interval "
                        f"{shown} not provably inside [0, {dim - 1}] — "
                        "clamp the index (jnp.clip) before the ref "
                        "access so the in-bounds proof closes",
                    )

    def _full_write(self, st: _RefState, indexers) -> bool:
        if indexers is None:
            return False
        for nd in indexers:
            idx = getattr(nd, "indices", None)
            if idx is None:
                return False
            for d, ix in enumerate(idx):
                dim = int(st.shape[d]) if d < len(st.shape) else 1
                start = getattr(ix, "start", None)
                if start is None:
                    if dim != 1:
                        return False
                    if isinstance(ix, int):
                        if ix != 0:
                            return False
                    else:
                        iv = self._read(ix)
                        if not (iv.lo == iv.hi == 0):
                            return False
                    continue
                size = int(getattr(ix, "size", 0) or 0)
                stride = int(getattr(ix, "stride", 1) or 1)
                if (
                    not isinstance(start, int) or start != 0
                    or size != dim or stride != 1
                ):
                    return False
        return True

    def _ref_read(self, eqn, indexers, collect) -> None:
        st = self.refs.get(id(eqn.invars[0]))
        if st is None:
            self._transfer(eqn)
            return
        self._check_bounds(st, indexers, collect)
        if self.mode == "init" and collect and st.tracked and not st.init:
            self._emit(
                "PC-INIT",
                f"ref {st.name} read before any grid-step-0 write seeds "
                "it — the block is revisited across the grid, so step 0 "
                "reads uninitialized VMEM; add a @pl.when(program_id == "
                "0) seed before the first read",
            )
        for v in eqn.outvars:
            self._write(v, _TOP)

    def _ref_write(self, eqn, indexers, definite, collect) -> None:
        st = self.refs.get(id(eqn.invars[0]))
        if st is None:
            self._transfer(eqn)
            return
        self._check_bounds(st, indexers, collect)
        if self.mode == "init" and st.tracked and not st.init:
            # the old value this swap RETURNS is uninitialized garbage;
            # flag it at its first use (see _uninit_vals)
            for v in eqn.outvars:
                self._uninit_vals.add(id(v))
            if definite and self._full_write(st, indexers):
                st.init = True
        for v in eqn.outvars:
            self._write(v, _TOP)

    def _p_get(self, eqn, definite, collect):
        self._ref_read(eqn, self._indexers(eqn, 1), collect)

    def _p_swap(self, eqn, definite, collect):
        self._ref_write(eqn, self._indexers(eqn, 2), definite, collect)

    def _masked_args(self, eqn):
        """pl.load/pl.swap lower to masked_load/masked_swap whose WHOLE
        arg list (ref, indexer tuple, [value,] mask) flattens through
        params['args_tree']."""
        import jax

        at = eqn.params.get("args_tree")
        if at is None:
            return None
        try:
            return jax.tree_util.tree_unflatten(at, list(eqn.invars))
        except Exception:  # noqa: BLE001 — future layout drift
            return None

    @staticmethod
    def _masked_idx(args):
        if args is not None and len(args) > 1 and isinstance(
            args[1], tuple
        ):
            return args[1]
        return None

    def _p_masked_load(self, eqn, definite, collect):
        args = self._masked_args(eqn)
        self._ref_read(eqn, self._masked_idx(args), collect)

    def _p_masked_swap(self, eqn, definite, collect):
        args = self._masked_args(eqn)
        # a masked store is a PARTIAL write even over full slices: only
        # unmasked lanes are seeded, so it never establishes init
        masked = args is not None and len(args) > 3 and args[3] is not None
        self._ref_write(
            eqn, self._masked_idx(args), definite and not masked, collect
        )

    def _p_addupdate(self, eqn, definite, collect):
        # accumulate = read-modify-write: counts as a read for PC-INIT
        st = self.refs.get(id(eqn.invars[0]))
        if st is None:
            return
        indexers = self._indexers(eqn, 2)
        self._check_bounds(st, indexers, collect)
        if self.mode == "init" and collect and st.tracked and not st.init:
            self._emit(
                "PC-INIT",
                f"ref {st.name} accumulated (addupdate) before any "
                "grid-step-0 write seeds it",
            )

    # -- control flow --------------------------------------------------
    def _do_cond(self, eqn, definite, collect):
        branches = eqn.params["branches"]
        pred = self._read(eqn.invars[0])
        ops = eqn.invars[1:]
        if pred.lo == pred.hi and not math.isinf(pred.lo):
            k = min(max(int(pred.lo), 0), len(branches) - 1)
            self._interp_branch(branches[k], ops, eqn, definite, collect)
            return
        # the join runs over the PRE-cond ref ids only: branch
        # interpretation adds branch-local alias ids for the same
        # _RefState objects, and an id first seen in a later branch is
        # absent from earlier snapshots — joining over it would falsely
        # clear init on a ref seeded before the cond. Every ref object
        # is reachable from its original kernel-invar id, so the
        # saved-id join covers all of them.
        saved = {vid: st.init for vid, st in self.refs.items()}
        states = []
        out_ivs = None
        for br in branches:
            for vid, init in saved.items():
                # reset to the pre-cond state for each branch
                self.refs[vid].init = init
            # a write inside a branch initializes for THAT branch's own
            # later reads (the write dominates them whenever the branch
            # runs at all); the must-join below strips it for code after
            # the cond unless every branch wrote
            ivs = self._interp_branch(br, ops, eqn, definite, collect)
            states.append({vid: self.refs[vid].init for vid in saved})
            out_ivs = ivs if out_ivs is None else [
                _iv_join(a, b) for a, b in zip(out_ivs, ivs)
            ]
        # must-analysis: initialized only if every branch initialized it
        for vid, init in saved.items():
            self.refs[vid].init = all(s.get(vid, init) for s in states)
        for v, iv in zip(eqn.outvars, out_ivs or []):
            self._write(v, iv)

    def _interp_branch(self, closed, ops, eqn, definite, collect):
        from jax import core

        j = closed.jaxpr if isinstance(closed, core.ClosedJaxpr) else closed
        for iv_var, ov in zip(ops, j.invars):
            self._write(ov, self._read(iv_var))
            self._bind_ref(ov, iv_var)
        self._eval_body(j, definite, collect)
        ivs = [self._read(v) for v in j.outvars]
        for v, iv in zip(eqn.outvars, ivs):
            self._write(v, iv)
        return ivs

    def _affine_step(self, body, i_carry: int, n_consts: int) -> Optional[float]:
        """Literal step c when carry #i_carry is `carry + c` (the
        fori_loop counter shape); 0.0 when it passes through unchanged."""
        carry_in = body.invars[n_consts + i_carry]
        out = body.outvars[i_carry]
        if out is carry_in:
            return 0.0
        for eqn in body.eqns:
            if out in eqn.outvars and eqn.primitive.name == "add":
                a, b = eqn.invars
                if a is carry_in and not hasattr(b, "count"):
                    return float(getattr(b, "val", 0))
                if b is carry_in and not hasattr(a, "count"):
                    return float(getattr(a, "val", 0))
        return None

    def _do_scan(self, eqn, definite, collect):
        from jax import core

        p = eqn.params
        closed = p["jaxpr"]
        body = closed.jaxpr if isinstance(
            closed, core.ClosedJaxpr
        ) else closed
        nc = int(p.get("num_consts", 0))
        ncar = int(p.get("num_carry", 0))
        length = max(int(p.get("length", 1) or 1), 1)
        ins = [self._read(v) for v in eqn.invars]
        for iv_var, ov in zip(eqn.invars, body.invars):
            self._bind_ref(ov, iv_var)
        carry = list(ins[nc:nc + ncar])
        # settle the carry intervals over all iterations first
        settled = [None] * ncar
        for i in range(ncar):
            step = self._affine_step(body, i, nc)
            if step is not None:
                total = step * (length - 1)
                settled[i] = _iv_join(
                    carry[i], _iv_add(carry[i], _Iv(total, total))
                )
        if any(s is None for s in settled):
            cur = list(carry)
            for _ in range(3):
                self._bind_scan_env(body, ins, nc, cur)
                self._eval_body(body, False, collect=False)
                new = [self._read(v) for v in body.outvars[:ncar]]
                joined = [_iv_join(a, b) for a, b in zip(cur, new)]
                if joined == cur:
                    break
                cur = joined
            else:
                cur = [_TOP] * ncar  # widen: no convergence in 3 passes
            for i in range(ncar):
                if settled[i] is None:
                    settled[i] = cur[i]
        # one findings pass with the settled intervals; the first
        # iteration is the PC-INIT worst case (init-state only grows)
        self._bind_scan_env(body, ins, nc, settled)
        self._eval_body(body, definite, collect)
        outs = [self._read(v) for v in body.outvars]
        for v, iv in zip(eqn.outvars, settled + outs[ncar:]):
            self._write(v, iv)

    def _bind_scan_env(self, body, ins, nc, carry):
        for k, ov in enumerate(body.invars):
            if k < nc:
                self._write(ov, ins[k])
            elif k < nc + len(carry):
                self._write(ov, carry[k - nc])
            else:
                self._write(ov, ins[k] if k < len(ins) else _TOP)

    def _do_while(self, eqn, definite, collect):
        from jax import core

        p = eqn.params
        cn = int(p.get("cond_nconsts", 0))
        bn = int(p.get("body_nconsts", 0))
        body_c = p["body_jaxpr"]
        body = body_c.jaxpr if isinstance(
            body_c, core.ClosedJaxpr
        ) else body_c
        ins = [self._read(v) for v in eqn.invars]
        carry = list(ins[cn + bn:])
        for iv_var, ov in zip(eqn.invars[cn:], body.invars):
            self._bind_ref(ov, iv_var)
        cur = list(carry)
        for _ in range(3):
            for k, ov in enumerate(body.invars):
                self._write(
                    ov, ins[cn + k] if k < bn else cur[k - bn]
                )
            self._eval_body(body, False, collect=False)
            new = [self._read(v) for v in body.outvars]
            joined = [_iv_join(a, b) for a, b in zip(cur, new)]
            if joined == cur:
                break
            cur = joined
        else:
            cur = [_TOP] * len(carry)
        for k, ov in enumerate(body.invars):
            self._write(ov, ins[cn + k] if k < bn else cur[k - bn])
        # body may run zero times: writes inside never count as seeds
        self._eval_body(body, False, collect)
        for v, iv in zip(eqn.outvars, cur):
            self._write(v, iv)

    def _do_call(self, eqn, definite, collect):
        from jax import core

        sub = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                sub = eqn.params[key]
                break
        if sub is None:
            self._transfer(eqn)
            return
        inner = sub.jaxpr if isinstance(sub, core.ClosedJaxpr) else sub
        for iv_var, ov in zip(eqn.invars, inner.invars):
            self._write(ov, self._read(iv_var))
            self._bind_ref(ov, iv_var)
        self._eval_body(inner, definite, collect)
        for sv, v in zip(inner.outvars, eqn.outvars):
            self._write(v, self._read(sv))

    # -- interval transfer ---------------------------------------------
    def _transfer(self, eqn) -> None:
        name = eqn.primitive.name
        ins = [self._read(v) for v in eqn.invars]
        out = _TOP
        if name == "program_id":
            ax = int(eqn.params.get("axis", 0))
            hi = self.info.grid[ax] - 1 if ax < len(self.info.grid) else 0
            out = _Iv(0, 0) if self.mode == "init" else _Iv(0, max(hi, 0))
        elif name == "num_programs":
            ax = int(eqn.params.get("axis", 0))
            n = self.info.grid[ax] if ax < len(self.info.grid) else 1
            out = _Iv(n, n)
        elif name == "add":
            out = _iv_add(ins[0], ins[1])
        elif name == "sub":
            out = _iv_sub(ins[0], ins[1])
        elif name == "mul":
            out = _iv_mul(ins[0], ins[1])
        elif name == "neg":
            out = _Iv(-ins[0].hi, -ins[0].lo)
        elif name == "abs":
            lo, hi = ins[0]
            out = _Iv(0 if lo <= 0 <= hi else min(abs(lo), abs(hi)),
                      max(abs(lo), abs(hi)))
        elif name == "max":
            out = _iv_max(ins[0], ins[1])
        elif name == "min":
            out = _iv_min(ins[0], ins[1])
        elif name == "clamp":  # clamp(lo, x, hi)
            out = _iv_max(ins[0], _iv_min(ins[1], ins[2]))
        elif name in ("floor", "ceil", "round"):
            lo, hi = ins[0] if ins else _TOP
            out = _Iv(
                lo if math.isinf(lo) else math.floor(lo),
                hi if math.isinf(hi) else math.ceil(hi),
            )
        elif name == "sign":
            out = _Iv(-1, 1)
        elif name in ("convert_element_type", "reduce_precision", "copy",
                      "stop_gradient"):
            out = ins[0] if ins else _TOP
        elif name in ("reshape", "transpose", "squeeze", "expand_dims",
                      "broadcast_in_dim", "slice", "rev", "reduce_max",
                      "reduce_min", "cummax", "cummin"):
            out = ins[0] if ins else _TOP
        elif name == "concatenate":
            out = ins[0]
            for iv in ins[1:]:
                out = _iv_join(out, iv)
        elif name == "select_n":
            out = ins[1] if len(ins) > 1 else _TOP
            for iv in ins[2:]:
                out = _iv_join(out, iv)
        elif name in ("eq", "ne", "lt", "le", "gt", "ge"):
            out = self._compare(name, ins[0], ins[1])
        elif name in ("and", "or", "not", "xor", "is_finite",
                      "reduce_and", "reduce_or"):
            # [0, 1] is only sound for BOOLEAN logic; the same
            # primitives on integer dtypes are bitwise and stay TOP
            dt = getattr(
                getattr(eqn.outvars[0], "aval", None), "dtype", None
            )
            out = _BOOL if str(dt) == "bool" else _TOP
        elif name in ("iota",):
            dim = int(eqn.params.get("dimension", 0))
            shape = getattr(eqn.outvars[0].aval, "shape", (1,))
            n = int(shape[dim]) if dim < len(shape) else 1
            out = _Iv(0, max(n - 1, 0))
        elif name in ("gather", "dynamic_slice", "take"):
            out = ins[0] if ins else _TOP  # values drawn from the source
        elif name == "shift_right_logical" and len(ins) == 2:
            if ins[0].lo >= 0 and ins[1].lo == ins[1].hi and not math.isinf(
                ins[1].lo
            ):
                s = int(ins[1].lo)
                hi = ins[0].hi if math.isinf(ins[0].hi) else int(
                    ins[0].hi
                ) >> s
                out = _Iv(int(ins[0].lo) >> s, hi)
        elif name == "argmin" or name == "argmax":
            aval = getattr(eqn.invars[0], "aval", None)
            n = 1
            for s in getattr(aval, "shape", ()) or ():
                n *= int(s)
            out = _Iv(0, max(n - 1, 0))
        for v in eqn.outvars:
            self._write(v, out)

    @staticmethod
    def _compare(name: str, a: _Iv, b: _Iv) -> _Iv:
        def known(t, f):  # (provably true, provably false)
            if t:
                return _Iv(1, 1)
            if f:
                return _Iv(0, 0)
            return _BOOL

        if name == "lt":
            return known(a.hi < b.lo, a.lo >= b.hi)
        if name == "le":
            return known(a.hi <= b.lo, a.lo > b.hi)
        if name == "gt":
            return known(a.lo > b.hi, a.hi <= b.lo)
        if name == "ge":
            return known(a.lo >= b.hi, a.hi < b.lo)
        if name == "eq":
            return known(
                a.lo == a.hi == b.lo == b.hi and not math.isinf(a.lo),
                a.hi < b.lo or b.hi < a.lo,
            )
        if name == "ne":
            return known(
                a.hi < b.lo or b.hi < a.lo,
                a.lo == a.hi == b.lo == b.hi and not math.isinf(a.lo),
            )
        return _BOOL


_CALL_LIKE = {"pjit", "closed_call", "core_call", "xla_call", "remat",
              "checkpoint", "custom_jvp_call", "custom_vjp_call",
              "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"}


# --------------------------------------------------------------------------
# per-kernel checks
# --------------------------------------------------------------------------


def check_kernel(info: KernelInfo) -> List[PallasFinding]:
    """PC-RACE (structural) + PC-OOB/PC-INIT (kernel-body interpretation)
    for one extracted kernel."""
    findings: List[PallasFinding] = []
    for ax, sem in enumerate(info.dimension_semantics):
        if sem != "parallel":
            continue
        for op in info.operands:
            if op.kind == "out" and ax not in op.grid_axes:
                f = PallasFinding(
                    "PC-RACE", info.entry, info.name,
                    f"output {op.name} is revisited across grid dim {ax} "
                    "(constant index_map — the VMEM accumulator pattern) "
                    "but that dim is declared \"parallel\": under "
                    "megacore both cores interleave its steps and the "
                    "read-modify-write merge races; declare the dim "
                    "\"arbitrary\"",
                )
                w = _waiver_for(f.rule, f.entry, f.detail)
                if w:
                    f = PallasFinding(
                        f.rule, f.entry, f.kernel, f.detail, "info", w
                    )
                if f not in findings:
                    findings.append(f)
    if info.jaxpr is not None:
        for mode in ("oob", "init"):
            try:
                findings.extend(_KernelWalk(info, mode).run())
            except Exception as e:  # noqa: BLE001 — report, never raise
                findings.append(PallasFinding(
                    "PC-CRASH", info.entry, info.name,
                    f"{mode} interpretation crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# entry points (audit.py's registry — the fused programs)
# --------------------------------------------------------------------------


def default_entry_points():
    """name -> () -> ClosedJaxpr for every entry point that lowers
    through Pallas: the fused stream traversal, the fused pool drain and
    the fused mesh step (flush + both expand variants each)."""
    from tpu_pbrt.analysis import audit

    return {
        "stream_intersect_fused": lambda: audit.stream_traversal_jaxpr(
            fused=True
        ),
        "pool_chunk_fused": lambda: audit.pool_chunk_jaxpr(fused=True),
        "sharded_pool_renderer_fused": lambda: audit.mesh_step_jaxpr(
            fused=True
        ),
    }


def collect_kernels(
    entries=None,
) -> Tuple[Dict[str, KernelInfo], List[PallasFinding], List[str]]:
    """Trace every entry point and extract its kernels. Crashes are
    reported, never raised (the CLI must print a full report). An entry
    with NO pallas_call is itself an error — the fused program silently
    stopped lowering through Pallas and the gate would be vacuous."""
    entries = entries if entries is not None else default_entry_points()
    kernels: Dict[str, KernelInfo] = {}
    findings: List[PallasFinding] = []
    crashes: List[str] = []
    for name, fn in entries.items():
        try:
            jx = fn()
            infos = extract_kernels(jx, name)
        except Exception as e:  # noqa: BLE001
            crashes.append(
                f"{name}: pallascheck trace crashed: {type(e).__name__}: {e}"
            )
            continue
        if not infos:
            crashes.append(
                f"{name}: no pallas_call found — the fused entry point "
                "no longer lowers through Pallas; pallascheck has "
                "nothing to verify"
            )
        for info in infos:
            kernels[info.key] = info
            findings.extend(check_kernel(info))
    return kernels, findings, crashes


# --------------------------------------------------------------------------
# the VMEM budget gate (same workflow as jaxcost's budgets.json)
# --------------------------------------------------------------------------


def load_budgets(path: Optional[Path] = None) -> Dict:
    p = Path(path) if path is not None else BUDGETS_PATH
    if not p.exists():
        return {"tolerance": DEFAULT_TOLERANCE, "entries": {}}
    return json.loads(p.read_text())


def save_budgets(
    kernels: Dict[str, KernelInfo], path: Optional[Path] = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Path:
    import jax

    p = Path(path) if path is not None else BUDGETS_PATH
    data = {
        "_comment": (
            "Per-kernel static VMEM footprints (pallascheck, ISSUE 11). "
            "bytes_per_step = double-buffered moving blocks + resident "
            "constant-index_map blocks + flat scratch. Regenerate with "
            "`python -m tpu_pbrt.analysis --update-budgets` after an "
            "INTENTIONAL kernel change; CI fails when a kernel's "
            "footprint drifts past tolerance or any kernel exceeds "
            "platform VMEM with headroom."
        ),
        "tolerance": tolerance,
        "vmem_headroom": VMEM_HEADROOM,
        "jax_version": jax.__version__,
        "entries": {k: i.to_json() for k, i in sorted(kernels.items())},
    }
    p.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return p


def check_budgets(
    kernels: Dict[str, KernelInfo], budgets: Dict
) -> Tuple[List[str], List[str]]:
    errors: List[str] = []
    warnings: List[str] = []
    tol = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    committed = budgets.get("entries", {})
    for key, info in sorted(kernels.items()):
        b = committed.get(key)
        if b is None:
            errors.append(
                f"{key}: no committed VMEM budget — run "
                "`python -m tpu_pbrt.analysis --update-budgets` and "
                "commit vmem_budgets.json"
            )
            continue
        base = int(b.get("vmem_bytes_per_step", 0))
        if base > 0:
            ratio = info.vmem_bytes / base
            if ratio > 1.0 + tol:
                errors.append(
                    f"{key}: static VMEM/step regressed {ratio:.2f}x "
                    f"({base} -> {info.vmem_bytes} B, tolerance "
                    f"{tol:.0%}) — shrink the kernel or, if intentional, "
                    "refresh with --update-budgets"
                )
            elif ratio < 1.0 - tol:
                warnings.append(
                    f"{key}: static VMEM/step improved {ratio:.2f}x "
                    f"({base} -> {info.vmem_bytes} B) — ratchet with "
                    "--update-budgets"
                )
        if b.get("fingerprint") and b["fingerprint"] != info.fingerprint:
            warnings.append(
                f"{key}: kernel structure fingerprint changed "
                f"({b['fingerprint']} -> {info.fingerprint}) — refresh "
                "vmem_budgets.json if the footprint above looks right"
            )
    for key in committed:
        if key not in kernels and not key.startswith("_"):
            warnings.append(
                f"{key}: committed VMEM budget has no live kernel — "
                "remove it with --update-budgets"
            )
    return errors, warnings


def check_capacity(
    kernels: Dict[str, KernelInfo], headroom: float = VMEM_HEADROOM,
) -> List[str]:
    """PC-VMEM: every kernel's per-step footprint must fit the smallest
    platform VMEM with headroom — statically, before any TPU sees it."""
    errors: List[str] = []
    platform, cap = min(VMEM_BYTES.items(), key=lambda kv: kv[1])
    budget = int(cap * headroom)
    for key, info in sorted(kernels.items()):
        if info.vmem_bytes > budget:
            errors.append(
                f"{key}: PC-VMEM static footprint {info.vmem_bytes} B "
                f"per grid step exceeds {budget} B "
                f"({headroom:.0%} of {platform} VMEM {cap} B) — shrink "
                "the block shapes or lower the fused caps"
            )
    return errors


# --------------------------------------------------------------------------
# cap derivation: invert the affine VMEM model for the fused kernels
# --------------------------------------------------------------------------


def _flush_kernel_info(R: int, L: Optional[int] = None,
                       motion: bool = False, CH: int = 8) -> KernelInfo:
    """Extract the fused flush kernel at wave width R via an abstract
    trace (ShapeDtypeStruct avals — no allocation, works at R = 2^22)."""
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.accel import fusedwave
    from tpu_pbrt.accel.stream import STREAM_LEAF_TRIS

    L = int(L or STREAM_LEAF_TRIS)
    F = 64 if motion else 16
    s = jax.ShapeDtypeStruct
    jx = jax.make_jaxpr(
        lambda ft, m, rr, rf, t, p: fusedwave.fused_flush_chunk(
            ft, m, rr, rf, t, p, interpret=True
        )
    )(
        s((2, F, 4 * L), jnp.float32), s((CH, 8), jnp.int32),
        s((CH, fusedwave.BLOCK), jnp.int32), s((8, R), jnp.float32),
        s((R,), jnp.float32), s((R,), jnp.int32),
    )
    return extract_kernels(jx, "derive.flush")[0]


def _expand_kernel_info(R: int, N: int, use_onehot: bool,
                        any_hit: bool) -> KernelInfo:
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.accel import fusedwave

    S = 2 * fusedwave.EXPAND_TILE
    s = jax.ShapeDtypeStruct
    tab = s((64, N), jnp.float32) if use_onehot else None
    box = None if use_onehot else s((48, N), jnp.float32)
    cid = None if use_onehot else s((8, N), jnp.int32)
    jx = jax.make_jaxpr(
        lambda k, n, re, pr, t, b, c: fusedwave.fused_expand(
            k, n, re, pr, t, b, c, tb=8, use_onehot=use_onehot,
            any_hit=any_hit, interpret=True,
        )
    )(
        s((S,), jnp.int32), s((S,), jnp.int32), s((8, R), jnp.float32),
        s((R,), jnp.int32), tab, box, cid,
    )
    return extract_kernels(jx, "derive.expand")[0]


def _affine_fit(f, x1: int, x2: int) -> Tuple[int, int]:
    """(intercept a, slope b) of the exactly-affine footprint f(x)."""
    y1, y2 = f(x1), f(x2)
    b = (y2 - y1) // (x2 - x1)
    return y1 - b * x1, b


def _pow2_floor(n: int) -> int:
    return 1 << (max(int(n), 1).bit_length() - 1)


def derive_caps(headroom: float = VMEM_HEADROOM) -> Dict:
    """Invert the VMEM model: per platform, the maximal wave width R the
    fused flush fits (worst case over motion features), then the maximal
    node count N the fused expand fits at the CONFIGURED rays cap (worst
    variant: any-hit, and the node representation the stream tracer
    would pick at that size). The hand-set config.py caps are validated
    against these (PC-CAPS) — the caps are a consequence of the model,
    not folklore."""
    from tpu_pbrt.accel.stream import _ONEHOT_MAX_NODES
    from tpu_pbrt.config import cfg

    r1, r2 = 1 << 12, 1 << 13
    fits = {}
    for motion in (False, True):
        a, b = _affine_fit(
            lambda R, m=motion: _flush_kernel_info(R, motion=m).vmem_bytes,
            r1, r2,
        )
        fits[motion] = (a, b)

    R_op = int(cfg.fused_max_rays)

    def expand_fit(use_onehot: bool, n1: int, n2: int):
        return _affine_fit(
            lambda N: _expand_kernel_info(
                R_op, N, use_onehot=use_onehot, any_hit=True
            ).vmem_bytes,
            n1, n2,
        )

    # primary fit in the box48 regime (every candidate cap above the
    # one-hot cutoff compiles the (48,N)+(8,N) tables); the one-hot
    # refit below only runs when the derived cap lands UNDER the cutoff
    ea, eb = expand_fit(False, 1 << 10, 1 << 11)
    onehot_fit = None

    out: Dict = {
        "headroom": headroom,
        "configured": {
            "fused_max_rays": R_op,
            "fused_max_nodes": int(cfg.fused_max_nodes),
        },
        "platforms": {},
    }
    for platform, cap in sorted(VMEM_BYTES.items()):
        budget = int(cap * headroom)
        rays_raw = min(
            (budget - a) // b for a, b in fits.values() if b > 0
        )
        nodes_raw = (budget - ea) // eb if eb > 0 else 0
        # a box48-regime cap at or below the one-hot cutoff means the
        # whole usable range compiles the (denser-padded) one-hot table
        # instead — re-derive there so the number matches what would
        # really compile, clamped to the cutoff where the
        # representation switches back
        if nodes_raw <= _ONEHOT_MAX_NODES and bool(cfg.onehot):
            if onehot_fit is None:
                onehot_fit = expand_fit(True, 128, 256)
            ea2, eb2 = onehot_fit
            nodes_raw = min(
                (budget - ea2) // eb2 if eb2 > 0 else 0,
                _ONEHOT_MAX_NODES,
            )
        out["platforms"][platform] = {
            "vmem_bytes": cap,
            "budget_bytes": budget,
            "max_rays": int(max(rays_raw, 0)),
            "max_rays_pow2": _pow2_floor(max(rays_raw, 1)),
            "max_nodes": int(max(nodes_raw, 0)),
            "max_nodes_pow2": _pow2_floor(max(nodes_raw, 1)),
            "flush_bytes_per_ray": int(min(b for _, b in fits.values())),
            "expand_bytes_per_node": int(eb),
        }
    return out


def check_caps(derived: Optional[Dict] = None) -> List[str]:
    """PC-CAPS: the configured TPU_PBRT_FUSED_MAX_RAYS / MAX_NODES must
    not exceed what the VMEM model proves safe on the smallest
    platform."""
    errors: List[str] = []
    d = derived if derived is not None else derive_caps()
    worst_rays = min(p["max_rays"] for p in d["platforms"].values())
    worst_nodes = min(p["max_nodes"] for p in d["platforms"].values())
    cfg_rays = d["configured"]["fused_max_rays"]
    cfg_nodes = d["configured"]["fused_max_nodes"]
    if cfg_rays > worst_rays:
        errors.append(
            f"PC-CAPS: TPU_PBRT_FUSED_MAX_RAYS={cfg_rays} exceeds the "
            f"model-safe maximum {worst_rays} "
            f"(pow2 {_pow2_floor(max(worst_rays, 1))}) — waves at the "
            "cap would overflow VMEM; lower the cap or shrink the flush "
            "kernel"
        )
    if cfg_nodes > worst_nodes:
        errors.append(
            f"PC-CAPS: TPU_PBRT_FUSED_MAX_NODES={cfg_nodes} exceeds the "
            f"model-safe maximum {worst_nodes} "
            f"(pow2 {_pow2_floor(max(worst_nodes, 1))}) at the "
            "configured rays cap — lower the cap or shrink the expand "
            "kernel's node tables"
        )
    return errors


def wave_vmem(R: int, n_nodes: int, motion: bool = False,
              L: Optional[int] = None) -> int:
    """Max per-grid-step VMEM footprint across the fused kernels a wave
    of R rays over an n_nodes top tree (L-triangle leaves) would
    dispatch — the `static_vmem_per_wave` bench field (cost.py
    --bench-wave)."""
    from tpu_pbrt.accel.stream import _ONEHOT_MAX_NODES
    from tpu_pbrt.config import cfg

    R = max(int(R), 1)
    n_nodes = max(int(n_nodes), 1)
    onehot = bool(cfg.onehot) and n_nodes <= _ONEHOT_MAX_NODES
    return max(
        _flush_kernel_info(R, L=L, motion=motion).vmem_bytes,
        _expand_kernel_info(R, n_nodes, onehot, any_hit=False).vmem_bytes,
        _expand_kernel_info(R, n_nodes, onehot, any_hit=True).vmem_bytes,
    )


# --------------------------------------------------------------------------
# suite driver
# --------------------------------------------------------------------------


def run_pallascheck(
    update: bool = False, budgets_path: Optional[Path] = None,
    entries=None, check_caps_too: Optional[bool] = None,
) -> Tuple[List[str], List[str]]:
    """CLI/test driver. Returns (errors, warnings). Caps derivation runs
    by default only for the full registry (tests passing a single entry
    skip the extra synthetic traces unless they opt in)."""
    kernels, findings, crashes = collect_kernels(entries)
    errors: List[str] = list(crashes)
    warnings: List[str] = []
    errors.extend(
        str(f) for f in findings if f.severity == "error" and not f.waived
    )
    warnings.extend(str(f) for f in findings if f.waived)
    errors.extend(check_capacity(kernels))
    if update:
        prev_tol = float(
            load_budgets(budgets_path).get("tolerance", DEFAULT_TOLERANCE)
        )
        save_budgets(kernels, budgets_path, tolerance=prev_tol)
    else:
        e, w = check_budgets(kernels, load_budgets(budgets_path))
        errors.extend(e)
        warnings.extend(w)
    if check_caps_too is None:
        check_caps_too = entries is None
    if check_caps_too:
        try:
            errors.extend(check_caps())
        except Exception as e:  # noqa: BLE001
            errors.append(
                f"PC-CAPS derivation crashed: {type(e).__name__}: {e}"
            )
    return errors, warnings


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_pbrt.analysis.pallascheck"
    )
    ap.add_argument(
        "--derive-caps", action="store_true",
        help="print the maximal safe TPU_PBRT_FUSED_MAX_RAYS/MAX_NODES "
             "per platform VMEM size, derived from the kernel VMEM "
             "model (the source of truth behind the config.py defaults)",
    )
    ap.add_argument("--update-budgets", action="store_true")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)
    if args.derive_caps:
        if args.update_budgets:
            # honor BOTH flags in one shot: refresh the committed
            # budgets first, then print the derived caps — silently
            # ignoring the refresh would leave the gate red after an
            # operator believed they ratified the change
            run_pallascheck(update=True)
            print(f"pallascheck: VMEM budgets refreshed -> {BUDGETS_PATH}")
        d = derive_caps()
        if args.format == "json":
            print(json.dumps(d, indent=2, sort_keys=True))
        else:
            c = d["configured"]
            print(
                f"configured: fused_max_rays={c['fused_max_rays']} "
                f"fused_max_nodes={c['fused_max_nodes']} "
                f"(headroom {d['headroom']:.0%})"
            )
            for platform, p in sorted(d["platforms"].items()):
                dr = p["max_rays_pow2"] - c["fused_max_rays"]
                dn = p["max_nodes_pow2"] - c["fused_max_nodes"]
                print(
                    f"{platform}: VMEM {p['vmem_bytes']} B -> budget "
                    f"{p['budget_bytes']} B; max_rays {p['max_rays']} "
                    f"(pow2 {p['max_rays_pow2']}, delta {dr:+d}), "
                    f"max_nodes {p['max_nodes']} "
                    f"(pow2 {p['max_nodes_pow2']}, delta {dn:+d}); "
                    f"{p['flush_bytes_per_ray']} B/ray flush, "
                    f"{p['expand_bytes_per_node']} B/node expand"
                )
        ok = not check_caps(d)
        return 0 if ok else 1
    errors, warnings = run_pallascheck(update=args.update_budgets)
    for w in warnings:
        print(f"WARN: {w}")
    for e in errors:
        print(f"ERROR: {e}")
    if args.update_budgets:
        print(f"pallascheck: VMEM budgets refreshed -> {BUDGETS_PATH}")
    if not errors:
        print("pallascheck: clean")
    return 1 if errors else 0


if __name__ == "__main__":
    import sys

    from tpu_pbrt.analysis.__main__ import _setup_jax_env

    _setup_jax_env()
    sys.exit(_main())
