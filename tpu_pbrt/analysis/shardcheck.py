"""shardcheck — static replicated-vs-varying analysis over shard_map bodies.

PR 1's `SHARD_MAP_NOCHECK` shim turned OFF jax's own replication checking
(`check_rep`/`check_vma`) on every mesh render — the 0.4.x checker
rejects our while_loop carries — which means nothing verifies that an
output a shard_map CLAIMS is replicated (out_spec `P()`) was actually
reduced over the mesh axis. Deleting the film `psum` from
`sharded_pool_renderer` would silently return device 0's partial film
from every mesh render. This pass restores the check statically, with
real diagnostics:

For every `shard_map` equation found in an entry-point jaxpr, and every
mesh axis, an abstract interpreter walks the body tracking one bit per
value — *replicated* (every device holds the same value) or *varying*:

- inputs sharded over the axis (`in_specs` mentioning it) are varying;
  inputs with `P()` and closed-over constants are replicated;
- `axis_index` over the axis, `ppermute`, `all_to_all` and
  `psum_scatter` produce varying values;
- `psum`/`pmax`/`pmin` and (tiled) `all_gather` over the axis produce
  replicated values (whole-axis reductions only — `axis_index_groups`
  stays varying);
- every other primitive is replicated iff all its operands are;
- control flow recurses: `cond`/`switch` outputs are replicated only if
  every branch agrees AND the predicate is replicated; `while`/`scan`
  carries run to a fixpoint, and a while whose PREDICATE varies over the
  axis (per-device trip counts — the pool drain's designed freedom)
  makes every carry varying.

Rules:

SC-UNREDUCED        an output whose out_spec claims replication but
                    whose computed state is varying — the missing-psum
                    bug class. Error.
SC-LOOP-COLLECTIVE  a collective over the mesh axis inside a while_loop
                    whose trip count is device-varying — mismatched
                    collective counts deadlock the mesh (the reason
                    sharded_pool_renderer's contract bans collectives
                    inside the drain). Error.

Entry points: the pool and chunk mesh renderers (parallel/mesh.py) and
SPPM's three-phase mesh iteration (integrators/sppm.py — the all_gather
photon exchange). MLT's chain shard uses the same psum-at-the-end shape
as the chunk renderer and is exercised by tests/test_mlt.py's mesh leg.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_pbrt.analysis.cost import _is_literal

#: collectives that REPLICATE their output over the named axis
_REDUCING = {"psum", "pmax", "pmin"}
_GATHERING = {"all_gather"}
#: collectives/queries that produce device-VARYING values over the axis
_VARYING_INTRO = {"ppermute", "pshuffle", "all_to_all", "psum_scatter",
                  "reduce_scatter"}

_CALL_LIKE = {"pjit", "closed_call", "core_call", "xla_call", "remat",
              "checkpoint", "custom_jvp_call", "custom_vjp_call",
              "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr"}


@dataclass(frozen=True)
class ShardFinding:
    rule: str
    entry: str
    axis: str
    message: str
    severity: str = "error"

    def __str__(self) -> str:
        return (
            f"{self.entry}: {self.rule} [{self.severity}] "
            f"axis '{self.axis}': {self.message}"
        )


def _eqn_axes(eqn) -> Tuple[str, ...]:
    """Mesh axis names a collective equation operates over."""
    p = eqn.params
    axes = p.get("axes", p.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _whole_axis(eqn) -> bool:
    """Full-axis collective (axis_index_groups would split the axis into
    subgroups, which does NOT replicate over the whole axis)."""
    return eqn.params.get("axis_index_groups") is None


class _Env:
    """var -> replicated? with literal/constvar defaults."""

    def __init__(self) -> None:
        self._m: Dict[int, bool] = {}

    def read(self, v) -> bool:
        if _is_literal(v):
            return True
        return self._m.get(id(v), True)  # constvars/unknowns: replicated

    def write(self, v, rep: bool) -> None:
        self._m[id(v)] = rep


def _has_axis_collective(jaxpr, axis: str) -> bool:
    """Any collective over `axis` anywhere under this jaxpr? Reuses the
    audit layer's sub-jaxpr traversal so a jax version that renames a
    call primitive's jaxpr param needs fixing in exactly one place."""
    from tpu_pbrt.analysis.audit import iter_jaxprs

    return any(
        eqn.primitive.name in (_REDUCING | _GATHERING | _VARYING_INTRO)
        and axis in _eqn_axes(eqn)
        for j in iter_jaxprs(jaxpr)
        for eqn in j.eqns
    )


def _run_body(
    jaxpr, axis: str, in_rep: Sequence[bool], entry: str,
    findings: List[ShardFinding],
) -> List[bool]:
    """Forward replication analysis of one (open) jaxpr. in_rep aligns
    with jaxpr.invars; returns the states of jaxpr.outvars."""
    env = _Env()
    for v, r in zip(jaxpr.invars, in_rep):
        env.write(v, bool(r))
    for v in jaxpr.constvars:
        env.write(v, True)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [env.read(v) for v in eqn.invars]

        if name in _REDUCING or name in _GATHERING:
            rep = axis in _eqn_axes(eqn) and _whole_axis(eqn)
            out = rep or all(ins)
            for v in eqn.outvars:
                env.write(v, out)
            continue
        if name == "axis_index":
            varying = axis in _eqn_axes(eqn)
            for v in eqn.outvars:
                env.write(v, not varying)
            continue
        if name in _VARYING_INTRO:
            touched = axis in _eqn_axes(eqn)
            for v in eqn.outvars:
                env.write(v, all(ins) and not touched)
            continue

        if name == "while":
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            cond_j = eqn.params["cond_jaxpr"].jaxpr
            body_j = eqn.params["body_jaxpr"].jaxpr
            cconsts = ins[:cn]
            bconsts = ins[cn:cn + bn]
            carry = list(ins[cn + bn:])
            for _ in range(len(carry) + 2):
                pred = _run_body(
                    cond_j, axis, cconsts + carry, entry, findings
                )[0]
                new = _run_body(body_j, axis, bconsts + carry, entry, findings)
                if not pred:
                    new = [False] * len(new)
                joined = [a and b for a, b in zip(carry, new)]
                if joined == carry:
                    break
                carry = joined
            pred = _run_body(cond_j, axis, cconsts + carry, entry, findings)[0]
            if not pred and _has_axis_collective(body_j, axis):
                f = ShardFinding(
                    "SC-LOOP-COLLECTIVE", entry, axis,
                    "collective over the mesh axis inside a while_loop "
                    "whose trip count is device-varying — devices would "
                    "issue mismatched collective counts (deadlock); "
                    "hoist the reduction out of the drain loop",
                )
                if f not in findings:
                    findings.append(f)
            for v, r in zip(eqn.outvars, carry):
                env.write(v, r)
            continue

        if name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            body_j = eqn.params["jaxpr"].jaxpr
            consts = ins[:nc]
            carry = list(ins[nc:nc + ncar])
            xs = ins[nc + ncar:]  # per-iteration slices keep their state
            ys: List[bool] = []
            for _ in range(len(carry) + 2):
                out = _run_body(
                    body_j, axis, consts + carry + xs, entry, findings
                )
                new_carry = out[:ncar]
                ys = out[ncar:]
                joined = [a and b for a, b in zip(carry, new_carry)]
                if joined == carry:
                    break
                carry = joined
            for v, r in zip(eqn.outvars, carry + ys):
                env.write(v, r)
            continue

        if name == "cond":
            pred = ins[0]
            ops = ins[1:]
            outs: Optional[List[bool]] = None
            for br in eqn.params["branches"]:
                o = _run_body(br.jaxpr, axis, ops, entry, findings)
                outs = o if outs is None else [a and b for a, b in zip(outs, o)]
            outs = outs or []
            if not pred:
                outs = [False] * len(outs)
            for v, r in zip(eqn.outvars, outs):
                env.write(v, r)
            continue

        if name in _CALL_LIKE:
            sub = None
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    sub = eqn.params[key]
                    break
            if sub is not None:
                from jax import core

                inner = sub.jaxpr if isinstance(sub, core.ClosedJaxpr) else sub
                outs = _run_body(inner, axis, ins, entry, findings)
                for v, r in zip(eqn.outvars, outs):
                    env.write(v, r)
                continue

        if name == "shard_map":
            # nested shard_map: checked on its own when discovered by
            # scan_closed_jaxpr; treat its outputs per its out_names
            for v, names in zip(eqn.outvars, eqn.params["out_names"]):
                claimed = axis not in _flat_names(names)
                env.write(v, claimed and all(ins))
            continue

        # default transfer: replicated iff every operand is
        out = all(ins)
        for v in eqn.outvars:
            env.write(v, out)

    return [env.read(v) for v in jaxpr.outvars]


def _flat_names(names: Dict) -> Tuple[str, ...]:
    out: List[str] = []
    for v in names.values():
        if isinstance(v, str):
            out.append(v)
        else:
            out.extend(v)
    return tuple(out)


def check_shard_map_eqn(eqn, entry: str) -> List[ShardFinding]:
    """Verify one shard_map equation: every output whose out_spec claims
    replication over a mesh axis must be computed replicated."""
    findings: List[ShardFinding] = []
    mesh = eqn.params["mesh"]
    in_names = eqn.params["in_names"]
    out_names = eqn.params["out_names"]
    body = eqn.params["jaxpr"]
    for axis in mesh.axis_names:
        if not isinstance(axis, str):
            continue
        in_rep = [axis not in _flat_names(n) for n in in_names]
        out_rep = _run_body(body, axis, in_rep, entry, findings)
        for i, (names, rep) in enumerate(zip(out_names, out_rep)):
            claimed = axis not in _flat_names(names)
            if claimed and not rep:
                findings.append(
                    ShardFinding(
                        "SC-UNREDUCED", entry, axis,
                        f"shard_map output #{i} is claimed replicated "
                        f"(out_spec P()) but is device-varying — missing "
                        f"psum/all_gather over '{axis}' before return",
                    )
                )
    return findings


def scan_closed_jaxpr(closed_jaxpr, entry: str) -> Tuple[List[ShardFinding], int]:
    """Find every shard_map equation under `closed_jaxpr` (including
    inside pjit bodies) and check each. Returns (findings, n_checked)."""
    from tpu_pbrt.analysis.audit import iter_jaxprs

    findings: List[ShardFinding] = []
    n = 0
    for j in iter_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name == "shard_map":
                n += 1
                findings.extend(check_shard_map_eqn(eqn, entry))
    return findings, n


# --------------------------------------------------------------------------
# entry points (share audit.py's cached tiny scenes)
# --------------------------------------------------------------------------


def chunk_step_jaxpr():
    """Trace a sharded_chunk_renderer step over the stream scene — the
    fixed-batch mesh path (film psum at the end of every chunk)."""
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.analysis.audit import _stream_scene
    from tpu_pbrt.core.film import merge_film
    from tpu_pbrt.parallel.mesh import make_mesh, sharded_chunk_renderer

    scene, integ = _stream_scene("path")
    film = scene.film
    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    n = 64

    def per_device_fn(dev, start):
        # start: this device's (1, 2) shard — feeds the wave so the
        # film contribution is genuinely device-varying pre-psum
        px = (start[0, 0] + jnp.arange(n, dtype=jnp.int32)) % 16
        py = jnp.zeros((n,), jnp.int32)
        o = jnp.zeros((n, 3), jnp.float32)
        d = jnp.tile(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), (n, 1))
        s = jnp.zeros((n,), jnp.int32)
        L, nrays = integ.li(dev, o, d, px, py, s)
        contrib = film.add_samples_pixel(
            film.init_state(), px, py, L, jnp.ones((n,), bool),
            jnp.ones((n,), jnp.float32),
        )
        return contrib, jnp.sum(nrays)

    step = sharded_chunk_renderer(mesh, per_device_fn)

    def fn(fs, starts):
        contrib, nrays = step(scene.dev, starts)
        return merge_film(fs, contrib), nrays

    starts = jnp.zeros((n_dev, 2), jnp.int32)
    return jax.make_jaxpr(fn)(film.init_state(), starts)


def sppm_mesh_jaxpr():
    """Trace one full SPPM mesh iteration (cam/photon/gather shard_maps
    with the ICI all_gather photon exchange)."""
    import jax
    import jax.numpy as jnp

    from tpu_pbrt.analysis.audit import _cornell_scene
    from tpu_pbrt.integrators.sppm import _SPPMState
    from tpu_pbrt.parallel.mesh import make_mesh

    scene, integ = _cornell_scene("sppm")
    film = scene.film
    x0, x1, y0, y1 = film.sample_bounds()
    w, h = x1 - x0, y1 - y0
    P = w * h
    pix = jnp.arange(P, dtype=jnp.int32)
    px = x0 + pix % w
    py = y0 + pix // w
    state = _SPPMState(
        r2=jnp.full((P,), 1.0, jnp.float32),
        n=jnp.zeros((P,), jnp.float32),
        tau=jnp.zeros((P, 3), jnp.float32),
        ld=jnp.zeros((P, 3), jnp.float32),
        dropped=jnp.zeros((), jnp.int32),
    )
    mesh = make_mesh(len(jax.devices()))
    iteration, state, _ = integ._mesh_iteration(
        scene.dev, mesh, state, px, py, P, 64
    )
    return jax.make_jaxpr(lambda st: iteration(st, jnp.int32(0)))(state)


def default_entry_points():
    from tpu_pbrt.analysis import audit

    return {
        "sharded_pool_renderer": audit.mesh_step_jaxpr,
        # the TPU_PBRT_FUSED=1 drain: Pallas wavefront kernels inside
        # the shard_map body (pallas_call is collective-free, so the
        # replication walk treats it like any local equation — this
        # entry proves the fused program keeps the film psum and adds
        # no collective inside the varying-trip drain loop)
        "sharded_pool_renderer_fused": lambda: audit.mesh_step_jaxpr(
            fused=True
        ),
        "sharded_chunk_renderer": chunk_step_jaxpr,
        "sppm.mesh_iteration": sppm_mesh_jaxpr,
    }


def run_shardcheck(entries=None) -> Tuple[List[str], List[str]]:
    """CLI/test driver. Returns (errors, warnings): SC findings and trace
    crashes are errors; an entry point with no shard_map inside would
    mean the mesh path silently stopped being a shard_map program — also
    an error (the check would be vacuous)."""
    entries = entries if entries is not None else default_entry_points()
    errors: List[str] = []
    warnings: List[str] = []
    for name, fn in entries.items():
        try:
            # trace AND check under the same guard: a jax release that
            # renames a shard_map param must degrade to a reported entry
            # error, not a CLI traceback (crashes reported, never raised)
            jx = fn()
            findings, n = scan_closed_jaxpr(jx, name)
        except Exception as e:  # noqa: BLE001
            errors.append(
                f"{name}: shardcheck crashed: {type(e).__name__}: {e}"
            )
            continue
        if n == 0:
            errors.append(
                f"{name}: no shard_map equation found — the mesh entry "
                "point no longer lowers through shard_map; shardcheck "
                "has nothing to verify"
            )
        errors.extend(
            str(f) for f in findings if f.severity == "error"
        )
        warnings.extend(
            str(f) for f in findings if f.severity != "error"
        )
    return errors, warnings
