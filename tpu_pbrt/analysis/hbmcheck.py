"""hbmcheck — analysis layer 7: static HBM residency, liveness &
capacity verification across the serve stack (ISSUE 18).

pallascheck (layer 5) turned the fused kernels' hand-set caps into
checked consequences of a committed VMEM model. hbmcheck is the same
move one memory level up: an aval-level static model of DEVICE memory
across the full serve lifecycle — resident compiled scenes
(`residency.scene_hbm_bytes`), per-job film/counter carries, the
pipeline window's un-donated depth-N slices, the `_prefetch_next`
activation, and develop/preview staging — gated by four rule families:

- **HC-CAP** — the worst-case simultaneous footprint under
  `TPU_PBRT_SERVE_RESIDENT_MB` x `max_active` x `TPU_PBRT_PIPELINE` x
  prefetch must fit a per-platform HBM capacity table with headroom,
  committed to `analysis/hbm_budgets.json` via the shared
  `--update-budgets` workflow. `--derive-hbm-caps` inverts the model
  (mirror of pallascheck's `--derive-caps`): per HBM size it emits the
  largest safe (resident MB, max_active, pipeline depth) triple, and
  the committed serve knob defaults are validated against it.
- **HC-LEAK** — an abstract refcount over the serve code paths: every
  function that drives a job to a terminal status must provably drop
  EVERY device reference that job holds (film carry, in-flight window,
  per-slice counter scalars) AND unpin its resident scene, on every
  exit path — park, cancel, fail, finalize. Residency eviction must
  consult pin counts before dropping an entry.
- **HC-ACCT** — residency's ESTIMATED footprints (what the LRU evicts
  on) must match aval-derived exact bytes within tolerance, checked
  against a deterministic reference scene and the live FilmState
  layout.
- **HC-ALIAS** — donation-aliased carries counted ONCE: the symbolic
  window buffer graph (depth-1 donated in/out alias, the deferred
  checkpoint snapshot reference) deduped over alias edges must
  reproduce the closed-form per-job footprint exactly.

The static pass is cross-validated dynamically by protocheck's
PROTO-HBM invariant (layer 6): the same model evaluated on the LIVE
service after every explorer decision must stay under this module's
static worst case and return to baseline at drain.

Shares the `# jaxlint: disable=HC-*` pragma grammar with the other
layers. Runs without any accelerator; only HC-ACCT touches jax (a
tree-leaves walk over numpy arrays).
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from tpu_pbrt.analysis.lint import Violation
from tpu_pbrt.analysis.protocheck import _pragma_lines, _shallow_walk, repo_root

BUDGETS_PATH = Path(__file__).resolve().parent / "hbm_budgets.json"
DEFAULT_TOLERANCE = 0.10

GiB = 1024 ** 3
#: per-chip HBM by platform — the capacity table HC-CAP gates against
#: (worst case = smallest platform, like pallascheck's VMEM_BYTES)
HBM_BYTES = {"v4": 32 * GiB, "v5e": 16 * GiB, "v5p": 95 * GiB}
#: fraction of HBM the serve model may plan for — the rest is XLA
#: scratch, fragmentation slack, and compiled-program temporaries the
#: static model cannot see
HBM_HEADROOM = 0.80

#: the four per-slice counter scalars a dispatch appends (ray/occ/ctr/
#: nf device int64s on RenderJob's counter lists), 8 B each
COUNTER_BYTES_PER_SLICE = 4 * 8
#: reference film for the worst-case model and the budget entries
REF_FILM = (512, 512)
#: reference concurrent-job load (the serve selftest runs 2; 4 is the
#: planning headroom the derive output is inverted against)
REF_MAX_ACTIVE = 4

HC_RULES = {
    "HC-CAP": "worst-case serve HBM footprint exceeds platform capacity "
              "with headroom, or a configured knob exceeds its derived cap",
    "HC-LEAK": "a serve path drives a job terminal without releasing its "
               "device buffers, or eviction ignores pin counts",
    "HC-ACCT": "residency's estimated footprint drifts from aval-exact "
               "bytes beyond tolerance",
    "HC-ALIAS": "a donation-aliased carry is double counted in the "
                "window model",
    "HC-PARSE": "file does not parse",
}


# --------------------------------------------------------------------------
# the memory model
# --------------------------------------------------------------------------


def film_state_bytes(rx: int, ry: int) -> int:
    """Device bytes of ONE film accumulator carry at rx x ry, derived
    from the LIVE FilmState layout (a 2x2 numpy probe, scaled) — not a
    hardcoded per-pixel constant, so a new film plane shows up here and
    HC-ACCT catches residency drifting from it."""
    import numpy as np

    from tpu_pbrt.core.film import FilmState

    probe = FilmState(
        rgb=np.zeros((2, 2, 3), np.float32),
        weight=np.zeros((2, 2), np.float32),
        splat=np.zeros((2, 2, 3), np.float32),
    )
    per_pixel = sum(int(leaf.nbytes) for leaf in probe) // 4
    return int(rx) * int(ry) * per_pixel


def develop_staging_bytes(rx: int, ry: int) -> int:
    """The develop/preview staging buffer: one RGB f32 image the film
    resolve materializes before the D2H copy."""
    return int(rx) * int(ry) * 3 * 4


def job_hbm_bytes(film_bytes: int, depth: int) -> int:
    """Closed-form worst-case device bytes ONE mid-dispatch job holds:
    live film carries (donation collapses depth 1 to a single buffer;
    depth > 1 keeps every un-donated in-flight input plus the newest
    output — see integrators.common.live_film_carries) plus the
    per-slice counter scalars for a full window."""
    from tpu_pbrt.integrators.common import live_film_carries

    d = max(1, int(depth))
    return live_film_carries(d) * int(film_bytes) + d * COUNTER_BYTES_PER_SLICE


def serve_model(
    rx: Optional[int] = None, ry: Optional[int] = None,
    depth: Optional[int] = None, max_active: Optional[int] = None,
    prefetch: Optional[bool] = None,
    resident_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """The worst-case simultaneous serve footprint, knobs defaulting
    from the live config: resident scenes at the full LRU budget +
    max_active mid-dispatch jobs + the prefetched next activation (one
    freshly-initialized film carry; its first dispatch has not pushed a
    slice yet) + develop staging."""
    from tpu_pbrt.config import cfg

    if rx is None or ry is None:
        rx, ry = REF_FILM
    if depth is None:
        depth = int(cfg.pipeline)
    if max_active is None:
        max_active = REF_MAX_ACTIVE
    if prefetch is None:
        prefetch = bool(cfg.serve_prefetch)
    if resident_bytes is None:
        resident_bytes = (
            int(cfg.serve_resident_mb * 1e6) if cfg.serve_resident_mb else 0
        )
    fb = film_state_bytes(rx, ry)
    jb = job_hbm_bytes(fb, depth)
    pf = fb if prefetch else 0
    st = develop_staging_bytes(rx, ry)
    total = int(resident_bytes) + max_active * jb + pf + st
    return {
        "film": [int(rx), int(ry)],
        "depth": int(depth),
        "max_active": int(max_active),
        "prefetch": bool(prefetch),
        "film_state_bytes": fb,
        "resident_bytes": int(resident_bytes),
        "job_bytes": jb,
        "jobs_bytes": max_active * jb,
        "prefetch_bytes": pf,
        "staging_bytes": st,
        "total_bytes": total,
    }


def check_capacity(
    model: Optional[Dict[str, Any]] = None, headroom: float = HBM_HEADROOM,
) -> List[str]:
    """HC-CAP: the worst-case simultaneous footprint must fit the
    smallest platform's HBM with headroom — statically, before any
    serve process sees a chip."""
    m = model if model is not None else serve_model()
    platform, cap = min(HBM_BYTES.items(), key=lambda kv: kv[1])
    budget = int(cap * headroom)
    if m["total_bytes"] <= budget:
        return []
    return [
        f"HC-CAP: worst-case serve footprint {m['total_bytes']} B "
        f"(resident {m['resident_bytes']} + {m['max_active']} jobs x "
        f"{m['job_bytes']} + prefetch {m['prefetch_bytes']} + staging "
        f"{m['staging_bytes']}) exceeds {budget} B ({headroom:.0%} of "
        f"{platform} HBM {cap} B) — lower TPU_PBRT_SERVE_RESIDENT_MB, "
        "max_active or TPU_PBRT_PIPELINE"
    ]


# --------------------------------------------------------------------------
# HC-ACCT: residency estimates vs aval-exact bytes
# --------------------------------------------------------------------------


class _RefFilm:
    full_resolution = REF_FILM


class _RefScene:
    """A deterministic synthetic compiled-scene stand-in: a mixed-dtype
    nested dev pytree shaped like the real upload (tri soup, stream
    slabs, texture atlas, light CDF, material table) — enough leaf
    variety that an estimator taking dtype or nesting shortcuts drifts
    measurably from the exact walk."""

    def __init__(self):
        import numpy as np

        self.film = _RefFilm()
        self.dev = {
            "tri_verts9T": np.zeros((9, 4096), np.float32),
            "tstream": {
                "slabs48": np.zeros((48, 2048), np.float32),
                "child_idx": np.zeros((8, 2048), np.int32),
            },
            "tex_atlas_u8": np.zeros((256, 256, 3), np.uint8),
            "light_cdf": np.zeros((129,), np.float32),
            "mat_table": np.zeros((64, 16), np.float32),
        }


def reference_scene():
    return _RefScene()


def exact_scene_bytes(scene) -> int:
    """Aval-derived exact device bytes: shape x itemsize per dev leaf —
    deliberately independent of any `nbytes` attribute the estimator
    shortcuts through — plus the film term from the live FilmState
    layout."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(scene.dev):
        shape = getattr(leaf, "shape", None)
        dims = tuple(shape) if shape is not None else (int(np.size(leaf)),)
        n = 1
        for d in dims:
            n *= int(d)
        total += n * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
    rx, ry = scene.film.full_resolution
    return total + film_state_bytes(rx, ry)


def acct_check(
    scene=None, tolerance: float = DEFAULT_TOLERANCE,
) -> List[str]:
    """HC-ACCT: the LRU evicts on `scene_hbm_bytes` estimates — they
    must track aval-exact bytes within tolerance, and residency's
    per-pixel film constant must match the live FilmState layout."""
    from tpu_pbrt.serve import residency

    errors: List[str] = []
    live_px = film_state_bytes(1, 1)
    if residency.FILM_BYTES_PER_PIXEL != live_px:
        errors.append(
            f"HC-ACCT: residency charges {residency.FILM_BYTES_PER_PIXEL} "
            f"B/pixel of film but the live FilmState layout is {live_px} "
            "B/pixel — the LRU would evict on wrong numbers; update "
            "residency.FILM_BYTES_PER_PIXEL"
        )
    sc = scene if scene is not None else reference_scene()
    est = residency.scene_hbm_bytes(sc)
    exact = exact_scene_bytes(sc)
    if exact > 0:
        ratio = est / exact
        if not (1.0 - tolerance <= ratio <= 1.0 + tolerance):
            errors.append(
                f"HC-ACCT: residency estimates {est} B for the reference "
                f"scene but the aval-exact footprint is {exact} B "
                f"({ratio:.2f}x, tolerance {tolerance:.0%}) — the LRU "
                "evicts on wrong numbers"
            )
    return errors


# --------------------------------------------------------------------------
# HC-ALIAS: donation-aliased carries counted once
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Buf:
    """A symbolic device buffer in the window model. `alias_of` names
    another Buf this one shares storage with (donation in/out, the
    deferred checkpoint snapshot); `donated` marks a dispatch output
    that MUST alias its input carry."""

    name: str
    nbytes: int
    alias_of: Optional[str] = None
    donated: bool = False


def job_buffers(
    film_bytes: int, depth: int, cadence: bool = True,
) -> List[Buf]:
    """The symbolic live-buffer set of one job mid-dispatch at `depth`.
    Depth 1 compiles donation into the chunk closure — the dispatch
    output ALIASES the input accumulator, one buffer. Depth > 1
    compiles donation out (deferred checkpoint writes may still read
    superseded carries), so each in-flight slice pins its un-donated
    input carry plus the newest output. The checkpoint cadence snapshot
    is a REFERENCE to an existing carry, never an allocation."""
    d = max(1, int(depth))
    bufs: List[Buf] = [Buf("carry0", int(film_bytes))]
    if d == 1:
        bufs.append(
            Buf("carry_out", int(film_bytes), alias_of="carry0", donated=True)
        )
    else:
        bufs.extend(
            Buf(f"carry{i}", int(film_bytes)) for i in range(1, d + 1)
        )
    if cadence:
        bufs.append(Buf("ckpt_snap", int(film_bytes), alias_of="carry0"))
    bufs.extend(
        Buf(f"counters{i}", COUNTER_BYTES_PER_SLICE) for i in range(d)
    )
    return bufs


def _alias_root(buf: Buf, by_name: Dict[str, Buf]) -> Optional[str]:
    seen = set()
    while buf.alias_of is not None:
        if buf.alias_of in seen or buf.alias_of not in by_name:
            return None
        seen.add(buf.name)
        buf = by_name[buf.alias_of]
    return buf.name


def dedup_bytes(bufs: List[Buf]) -> int:
    """Total bytes counting each alias class ONCE (by its root)."""
    by_name = {b.name: b for b in bufs}
    roots, total = set(), 0
    for b in bufs:
        r = _alias_root(b, by_name)
        if r is None or r in roots:
            continue
        roots.add(r)
        total += by_name[r].nbytes
    return total


def check_alias(bufs: List[Buf]) -> List[str]:
    """HC-ALIAS structural checks on a buffer graph: donated outputs
    must carry an alias edge (else the model double-counts the carry)
    and every alias edge must resolve."""
    errors: List[str] = []
    by_name: Dict[str, Buf] = {}
    for b in bufs:
        if b.name in by_name:
            errors.append(
                f"HC-ALIAS: duplicate buffer name {b.name!r} in the "
                "window model"
            )
        by_name[b.name] = b
    for b in bufs:
        if b.donated and b.alias_of is None:
            errors.append(
                f"HC-ALIAS: {b.name!r} is donation-aliased but carries "
                "no alias edge — the model would double-count the carry"
            )
        if b.alias_of is not None and b.alias_of not in by_name:
            errors.append(
                f"HC-ALIAS: {b.name!r} aliases unknown buffer "
                f"{b.alias_of!r}"
            )
    return errors


def alias_audit(depths: Tuple[int, ...] = (1, 2, 3)) -> List[str]:
    """HC-ALIAS self-consistency: at every depth the symbolic buffer
    graph, deduped over alias edges, must reproduce `job_hbm_bytes`
    exactly — the closed form HC-CAP plans with and the graph HC-ALIAS
    audits are the SAME model."""
    errors: List[str] = []
    fb = film_state_bytes(*REF_FILM)
    for d in depths:
        bufs = job_buffers(fb, d)
        errors.extend(check_alias(bufs))
        got, want = dedup_bytes(bufs), job_hbm_bytes(fb, d)
        if got != want:
            errors.append(
                f"HC-ALIAS: window model at depth {d} counts {got} B "
                f"after alias dedup but the closed-form job footprint is "
                f"{want} B — a donated or snapshot carry is double counted"
            )
    return errors


# --------------------------------------------------------------------------
# HC-LEAK: abstract refcount over the serve code paths
# --------------------------------------------------------------------------

_SERVICE_MOD = "tpu_pbrt/serve/service.py"
_RESIDENCY_MOD = "tpu_pbrt/serve/residency.py"
_TERMINAL_NAMES = frozenset({"FAILED", "CANCELLED", "DONE"})
_COUNTER_LISTS = frozenset(
    {"ray_counts", "occ_counts", "ctr_counts", "nf_counts"}
)


def _leak_service(tree: ast.AST, rel: str) -> List[Violation]:
    """Every function in service.py that assigns a terminal status must
    release the job's device buffers on that path — either by calling
    `_release_device` or by nulling `.state` AND clearing all four
    counter lists inline — and must `unpin` the resident scene."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        terminal_line = None
        has_release = has_unpin = has_state_none = False
        cleared: set = set()
        for n in _shallow_walk(node):
            if isinstance(n, ast.Assign):
                if (
                    isinstance(n.value, ast.Name)
                    and n.value.id in _TERMINAL_NAMES
                    and any(
                        isinstance(t, ast.Attribute) and t.attr == "status"
                        for t in n.targets
                    )
                ):
                    terminal_line = terminal_line or n.lineno
                if (
                    isinstance(n.value, ast.Constant)
                    and n.value.value is None
                    and any(
                        isinstance(t, ast.Attribute) and t.attr == "state"
                        for t in n.targets
                    )
                ):
                    has_state_none = True
            elif isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ):
                if n.func.attr == "_release_device":
                    has_release = True
                elif n.func.attr == "unpin":
                    has_unpin = True
                elif n.func.attr == "clear" and isinstance(
                    n.func.value, ast.Attribute
                ) and n.func.value.attr in _COUNTER_LISTS:
                    cleared.add(n.func.value.attr)
        if terminal_line is None:
            continue
        inline_release = has_state_none and cleared == set(_COUNTER_LISTS)
        if not (has_release or inline_release):
            out.append(Violation(
                "HC-LEAK", rel, terminal_line,
                f"{node.name}() drives a job to a terminal status but "
                "releases no device buffers on that path — call "
                "_release_device(job) (or null .state and clear all four "
                "counter lists) so the film carry, in-flight window and "
                "per-slice counters drop with the job", "error",
            ))
        if not has_unpin:
            out.append(Violation(
                "HC-LEAK", rel, terminal_line,
                f"{node.name}() drives a job to a terminal status without "
                "releasing its residency pin — the scene can never be "
                "evicted and the LRU budget silently shrinks", "error",
            ))
    return out


def _leak_residency(tree: ast.AST, rel: str) -> List[Violation]:
    """Any function that drops a resident entry (`del ..._entries[...]`)
    must consult pin counts in the same function — otherwise a pinned
    scene under a live job could be evicted out from under it."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        del_line = None
        sees_pins = False
        for n in _shallow_walk(node):
            if isinstance(n, ast.Delete):
                for t in n.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "_entries"
                    ):
                        del_line = del_line or n.lineno
            if isinstance(n, ast.Attribute) and n.attr == "pins":
                sees_pins = True
        if del_line is not None and not sees_pins:
            out.append(Violation(
                "HC-LEAK", rel, del_line,
                f"{node.name}() drops a resident entry without consulting "
                "pin counts — a pinned scene under a live job could be "
                "evicted out from under it", "error",
            ))
    return out


def hc_leak_source(src: str, rel: str) -> List[Violation]:
    """HC-LEAK over one source blob. Module scoping is by `rel` (the
    repo-relative path), like the SV-* rules; the shared
    `# jaxlint: disable=HC-LEAK` pragma grammar applies (a pragma on
    the def line covers the whole function)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Violation(
            "HC-PARSE", rel, e.lineno or 0,
            f"does not parse: {e.msg}", "error",
        )]
    found: List[Violation] = []
    if rel.endswith(_SERVICE_MOD.rsplit("/", 1)[-1]) and "serve" in rel:
        found.extend(_leak_service(tree, rel))
    if rel.endswith(_RESIDENCY_MOD.rsplit("/", 1)[-1]) and "serve" in rel:
        found.extend(_leak_residency(tree, rel))
    per_line, file_wide = _pragma_lines(src)
    def_lines = {
        n.lineno: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    kept = []
    for v in found:
        rules = per_line.get(v.line, set()) | file_wide
        # a pragma on the enclosing def line covers the function body
        for ln, fn in def_lines.items():
            if fn.lineno <= v.line <= (fn.end_lineno or fn.lineno):
                rules |= per_line.get(ln, set())
        if v.rule in rules or "all" in rules:
            continue
        kept.append(v)
    return sorted(kept, key=lambda v: (v.line, v.rule))


def hc_leak_tree(root: Optional[str] = None) -> List[Violation]:
    base = Path(root if root else repo_root())
    out: List[Violation] = []
    for rel in (_SERVICE_MOD, _RESIDENCY_MOD):
        p = base / rel
        if p.exists():
            out.extend(hc_leak_source(p.read_text(), rel))
    return out


# --------------------------------------------------------------------------
# budgets: the committed hbm_budgets.json gate
# --------------------------------------------------------------------------


def _fingerprint(detail: Dict[str, Any]) -> str:
    return hashlib.sha1(
        json.dumps(detail, sort_keys=True).encode()
    ).hexdigest()[:12]


def collect_entries(
    model: Optional[Dict[str, Any]] = None,
) -> Dict[str, Dict[str, Any]]:
    """The budget entries the gate tracks: every term of the worst-case
    model plus the reference-scene estimate HC-ACCT audits."""
    from tpu_pbrt.serve.residency import scene_hbm_bytes

    m = model if model is not None else serve_model()
    ref_bytes = int(scene_hbm_bytes(reference_scene()))

    def entry(nbytes: int, **detail) -> Dict[str, Any]:
        return {
            "hbm_bytes": int(nbytes),
            "fingerprint": _fingerprint(detail),
            "detail": detail,
        }

    return {
        "serve.film_state": entry(
            m["film_state_bytes"], film=m["film"],
            per_pixel=film_state_bytes(1, 1),
        ),
        "serve.job": entry(
            m["job_bytes"], depth=m["depth"],
            counter_bytes_per_slice=COUNTER_BYTES_PER_SLICE,
        ),
        "serve.prefetch": entry(m["prefetch_bytes"], enabled=m["prefetch"]),
        "serve.staging": entry(m["staging_bytes"], film=m["film"]),
        "serve.worst_case": entry(
            m["total_bytes"], resident_bytes=m["resident_bytes"],
            max_active=m["max_active"], depth=m["depth"],
        ),
        "scene.reference": entry(ref_bytes, film=list(REF_FILM)),
    }


def load_budgets(path: Optional[Path] = None) -> Dict:
    p = Path(path) if path is not None else BUDGETS_PATH
    if not p.exists():
        return {"tolerance": DEFAULT_TOLERANCE, "entries": {}}
    return json.loads(p.read_text())


def save_budgets(
    entries: Dict[str, Dict[str, Any]], path: Optional[Path] = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Path:
    import jax

    p = Path(path) if path is not None else BUDGETS_PATH
    data = {
        "_comment": (
            "Static HBM footprints of the serve memory model (hbmcheck, "
            "ISSUE 18): film carry, per-job worst case, prefetch slot, "
            "develop staging, the total worst-case watermark, and the "
            "residency estimate of the reference scene. Regenerate with "
            "`python -m tpu_pbrt.analysis --update-budgets` after an "
            "INTENTIONAL serve/film change; CI fails when a footprint "
            "drifts past tolerance or the worst case exceeds platform "
            "HBM with headroom."
        ),
        "tolerance": tolerance,
        "hbm_headroom": HBM_HEADROOM,
        "jax_version": jax.__version__,
        "entries": {k: dict(v) for k, v in sorted(entries.items())},
    }
    p.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return p


def check_budgets(
    entries: Dict[str, Dict[str, Any]], budgets: Dict,
) -> Tuple[List[str], List[str]]:
    errors: List[str] = []
    warnings: List[str] = []
    tol = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    committed = budgets.get("entries", {})
    for key, info in sorted(entries.items()):
        b = committed.get(key)
        if b is None:
            errors.append(
                f"{key}: no committed HBM budget — run "
                "`python -m tpu_pbrt.analysis --update-budgets` and "
                "commit hbm_budgets.json"
            )
            continue
        base = int(b.get("hbm_bytes", 0))
        if base > 0:
            ratio = info["hbm_bytes"] / base
            if ratio > 1.0 + tol:
                errors.append(
                    f"{key}: static HBM footprint regressed {ratio:.2f}x "
                    f"({base} -> {info['hbm_bytes']} B, tolerance "
                    f"{tol:.0%}) — shrink the footprint or, if "
                    "intentional, refresh with --update-budgets"
                )
            elif ratio < 1.0 - tol:
                warnings.append(
                    f"{key}: static HBM footprint improved {ratio:.2f}x "
                    f"({base} -> {info['hbm_bytes']} B) — ratchet with "
                    "--update-budgets"
                )
        if b.get("fingerprint") and b["fingerprint"] != info["fingerprint"]:
            warnings.append(
                f"{key}: model structure fingerprint changed "
                f"({b['fingerprint']} -> {info['fingerprint']}) — refresh "
                "hbm_budgets.json if the footprint above looks right"
            )
    for key in committed:
        if key not in entries and not key.startswith("_"):
            warnings.append(
                f"{key}: committed HBM budget has no live model term — "
                "remove it with --update-budgets"
            )
    return errors, warnings


# --------------------------------------------------------------------------
# cap derivation: invert the model per platform (mirror of PC-CAPS)
# --------------------------------------------------------------------------


def derive_hbm_caps(headroom: float = HBM_HEADROOM) -> Dict:
    """Invert the serve model per platform: with the OTHER knobs at
    their configured values, the largest safe resident-scene budget
    (MB), the largest safe max_active, and the deepest safe pipeline
    window. The hand-set config.py serve knobs are validated against
    these (HC-CAP) — the knobs become consequences of the model, not
    folklore."""
    from tpu_pbrt.config import cfg

    rx, ry = REF_FILM
    fb = film_state_bytes(rx, ry)
    depth = int(cfg.pipeline)
    jb = job_hbm_bytes(fb, depth)
    pf = fb if cfg.serve_prefetch else 0
    st = develop_staging_bytes(rx, ry)
    cfg_res_mb = (
        float(cfg.serve_resident_mb) if cfg.serve_resident_mb else None
    )
    res_bytes = int(cfg_res_mb * 1e6) if cfg_res_mb else 0

    out: Dict[str, Any] = {
        "headroom": headroom,
        "configured": {
            "serve_resident_mb": cfg_res_mb,
            "pipeline_depth": depth,
            "max_active": REF_MAX_ACTIVE,
            "prefetch": bool(cfg.serve_prefetch),
            "film": [rx, ry],
        },
        "platforms": {},
    }
    for platform, cap in sorted(HBM_BYTES.items()):
        budget = int(cap * headroom)
        # resident cap: everything the live jobs need comes first
        resident_raw = budget - REF_MAX_ACTIVE * jb - pf - st
        max_resident_mb = max(resident_raw // 1_000_000, 0)
        free = budget - res_bytes - pf - st
        max_active = max(free // jb, 0)
        # depth cap: a depth-d job (d > 1) costs (d+1) carries + d
        # counter slots = d*(fb + CTR) + fb; invert for the configured
        # active-job load
        per_job = free // max(REF_MAX_ACTIVE, 1)
        max_depth = max(
            int((per_job - fb) // (fb + COUNTER_BYTES_PER_SLICE)), 1,
        )
        out["platforms"][platform] = {
            "hbm_bytes": int(cap),
            "budget_bytes": budget,
            "job_bytes": jb,
            "max_resident_mb": int(max_resident_mb),
            "max_resident_mb_aligned": int(max_resident_mb // 1024 * 1024),
            "max_active": int(max_active),
            "max_pipeline_depth": max_depth,
        }
    return out


def check_hbm_caps(derived: Optional[Dict] = None) -> List[str]:
    """HC-CAP over the derived caps: every CONFIGURED serve knob must
    sit at or under its model-safe maximum on the smallest platform."""
    d = derived if derived is not None else derive_hbm_caps()
    plats = d["platforms"].values()
    worst_res = min(p["max_resident_mb"] for p in plats)
    worst_active = min(p["max_active"] for p in plats)
    worst_depth = min(p["max_pipeline_depth"] for p in plats)
    c = d["configured"]
    errors: List[str] = []
    if c["serve_resident_mb"] is not None and c["serve_resident_mb"] > worst_res:
        errors.append(
            f"HC-CAP: TPU_PBRT_SERVE_RESIDENT_MB="
            f"{c['serve_resident_mb']:g} exceeds the model-safe maximum "
            f"{worst_res} MB on the smallest platform — resident scenes "
            "at the cap would overflow HBM under the live-job load; "
            "lower the budget or the job knobs"
        )
    if c["max_active"] > worst_active:
        errors.append(
            f"HC-CAP: the reference max_active={c['max_active']} exceeds "
            f"the model-safe maximum {worst_active} at the configured "
            "resident budget"
        )
    if c["pipeline_depth"] > worst_depth:
        errors.append(
            f"HC-CAP: TPU_PBRT_PIPELINE={c['pipeline_depth']} exceeds "
            f"the model-safe maximum depth {worst_depth} at the "
            "configured resident budget — un-donated in-flight carries "
            "would overflow HBM"
        )
    return errors


# --------------------------------------------------------------------------
# bench hook: the static HBM half of the bench JSON line
# --------------------------------------------------------------------------


def bench_fields(rx: int = 512, ry: int = 512) -> Dict[str, Any]:
    """`static_hbm_per_job` + `hbm_headroom` for cost.py --bench-wave:
    rides bench.py's schema-stable JSON line (measured AND infra-outage
    paths). `hbm_headroom` is the fraction of the smallest platform's
    HBM budget still free at the current knob settings — negative means
    the configured serve load cannot fit."""
    m = serve_model(rx=rx, ry=ry)
    budget = min(HBM_BYTES.values()) * HBM_HEADROOM
    return {
        "static_hbm_per_job": int(m["job_bytes"]),
        "hbm_headroom": round(1.0 - m["total_bytes"] / budget, 4),
    }


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def run_hbmcheck(
    update: bool = False, budgets_path: Optional[Path] = None,
    root: Optional[str] = None, check_caps_too: bool = True,
) -> Tuple[List[str], List[str]]:
    """The full layer-7 pass: HC-LEAK tree scan, HC-ACCT, HC-ALIAS,
    HC-CAP capacity + budget gate (or refresh), and the derived-caps
    validation. Returns (errors, warnings) like the other layers."""
    errors: List[str] = []
    warnings: List[str] = []
    errors.extend(str(v) for v in hc_leak_tree(root))
    errors.extend(acct_check())
    errors.extend(alias_audit())
    model = serve_model()
    errors.extend(check_capacity(model))
    entries = collect_entries(model)
    if update:
        prev_tol = float(
            load_budgets(budgets_path).get("tolerance", DEFAULT_TOLERANCE)
        )
        save_budgets(entries, budgets_path, tolerance=prev_tol)
    else:
        e, w = check_budgets(entries, load_budgets(budgets_path))
        errors.extend(e)
        warnings.extend(w)
    if check_caps_too:
        try:
            errors.extend(check_hbm_caps())
        except Exception as e:  # noqa: BLE001 — a crashed derivation is a finding
            errors.append(
                f"HC-CAP derivation crashed: {type(e).__name__}: {e}"
            )
    return errors, warnings


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_pbrt.analysis.hbmcheck"
    )
    ap.add_argument(
        "--derive-hbm-caps", action="store_true",
        help="invert the serve HBM model: per platform, the largest "
             "safe (resident MB, max_active, pipeline depth) triple",
    )
    ap.add_argument("--update-budgets", action="store_true")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    if args.derive_hbm_caps:
        if args.update_budgets:
            prev = float(
                load_budgets().get("tolerance", DEFAULT_TOLERANCE)
            )
            save_budgets(collect_entries(), tolerance=prev)
            print(f"hbm budgets refreshed -> {BUDGETS_PATH}")
        derived = derive_hbm_caps()
        if args.format == "json":
            print(json.dumps(derived, indent=2, sort_keys=True))
        else:
            c = derived["configured"]
            res = (
                f"{c['serve_resident_mb']:g}"
                if c["serve_resident_mb"] is not None else "unbounded"
            )
            print(
                f"configured: serve_resident_mb={res} "
                f"pipeline={c['pipeline_depth']} "
                f"max_active={c['max_active']} "
                f"prefetch={c['prefetch']} "
                f"(headroom {derived['headroom']:.0%})"
            )
            for name, p in sorted(derived["platforms"].items()):
                print(
                    f"{name}: HBM {p['hbm_bytes']} B -> budget "
                    f"{p['budget_bytes']} B; max_resident_mb "
                    f"{p['max_resident_mb']} (aligned "
                    f"{p['max_resident_mb_aligned']}), max_active "
                    f"{p['max_active']}, max_pipeline_depth "
                    f"{p['max_pipeline_depth']}; job {p['job_bytes']} B"
                )
        errors = check_hbm_caps(derived)
        for e in errors:
            print(f"ERROR: {e}")
        return 1 if errors else 0

    errors, warnings = run_hbmcheck(update=args.update_budgets)
    if args.format == "json":
        print(json.dumps(
            {"errors": errors, "warnings": warnings,
             "ok": not errors}
        ))
    else:
        for w in warnings:
            print(f"WARN: {w}")
        for e in errors:
            print(f"ERROR: {e}")
        if args.update_budgets:
            print(f"hbm budgets refreshed -> {BUDGETS_PATH}")
    return 1 if errors else 0


if __name__ == "__main__":
    import sys

    from tpu_pbrt.analysis.__main__ import _setup_jax_env

    _setup_jax_env()
    sys.exit(_main())
