"""jaxcost — static roofline budgets over the real entry-point jaxprs.

The latest bench capture (`BENCH_r05.json`) is an accelerator outage with
`value: 0.0`: whenever the TPU tunnel is down, perf regressions are
invisible to the judged metric. This pass closes that gap with a signal
that needs NO hardware: an abstract interpreter walks the closed jaxpr of
every hot entry point (path wave, pool drain, stream traversal, film
deposits, sharded mesh step) and charges each equation a FLOP count and
an HBM bytes-moved count from a per-primitive model. The rollup is a
static roofline per entry point — flops, bytes, arithmetic intensity —
committed to `tpu_pbrt/analysis/budgets.json` and re-checked by
`python -m tpu_pbrt.analysis`: an entry point whose bytes or FLOPs grow
beyond tolerance fails CI even when `jax.devices()` would hang.

The byte model is deliberately the UNFUSED upper bound: every equation
reads its (non-literal) inputs and writes its outputs at HBM. XLA fusion
makes the true traffic lower, but the proxy is deterministic, stable
across runs, and moves in the same direction as the real number — which
is all a regression gate needs. Loop bodies are charged ONCE (a
`while_loop` body is exactly one wave of the drain loop, so the pool
rollup reads as "per wave"); `scan` bodies multiply by their static trip
count.

On top of the rollup, the walk reports anti-pattern findings:

JC-CHURN     dtype round trip (A -> B -> A `convert_element_type` chain
             through elementwise ops) at or above wave width — each
             round trip is two full-array HBM passes that a dtype-stable
             formulation deletes.
JC-RELAYOUT  `transpose` of a buffer >= RELAYOUT_MIN_BYTES inside the
             wave — a relayout copy paid per dispatch that can usually
             be hoisted to scene-compile time.
JC-GATHER    a gather whose slice rows are narrower than
             GATHER_MIN_SLICE_BYTES while the index count exceeds
             GATHER_INDEX_FACTOR x the wave width and the fetched total
             exceeds GATHER_MIN_TOTAL_BYTES — random access far off the
             measured ~bandwidth regime of batched row copies. Gathers
             whose indices provably derive from a `sort` output are
             exempt: nearly-sorted random access measures ~1 ns/element
             on this v5e (accel/stream.py module doc), and sorting
             before gathering is exactly the sanctioned fix.
JC-BCAST     `broadcast_in_dim` materializing >= BCAST_MIN_RATIO x a
             NON-SCALAR input at >= BCAST_MIN_BYTES output — a blowup
             XLA may have to materialize (scalar broadcasts fuse for
             free and are never flagged).
JC-PAD       an output >= PAD_MIN_BYTES whose trailing dims waste more
             than PAD_MIN_WASTE of the (8, 128) f32 vector-memory tile
             (scaled by dtype width) — HBM and VMEM pay the padded shape.

Deliberate violations (the one-hot MXU gather replacement packs i32 ids
through f32 matmul lanes by design) are waived in `WAIVERS` with a
reason, so the finding list stays actionable.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass
from functools import lru_cache
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# per-primitive cost model
# --------------------------------------------------------------------------

#: flops-per-element weight for transcendental / iterative elementwise ops
_TRANSCENDENTAL = {
    "exp", "exp2", "expm1", "log", "log1p", "log2", "sin", "cos", "tan",
    "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh", "asinh",
    "acosh", "atanh", "pow", "rsqrt", "sqrt", "cbrt", "erf", "erfc",
    "erf_inv", "logistic", "lgamma", "digamma", "regularized_incomplete_beta",
}
_TRANSCENDENTAL_WEIGHT = 8

#: pure data-movement primitives: 0 flops, bytes only
_MOVEMENT = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "concatenate", "pad", "slice", "rev", "copy", "convert_element_type",
    "bitcast_convert_type", "iota", "real", "imag", "device_put",
}

#: reductions: flops = input elements
_REDUCTIONS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_precision",
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
}

_SCATTERS = {"scatter", "scatter-add", "scatter_add", "scatter_mul",
             "scatter_min", "scatter_max", "scatter-update"}

#: sub-jaxpr carrying primitives handled structurally in _walk
_CONTROL = {"while", "scan", "cond", "pjit", "closed_call", "remat",
            "checkpoint", "custom_jvp_call", "custom_vjp_call",
            "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "shard_map",
            "core_call", "xla_call"}


def _aval_elems(aval) -> int:
    shape = getattr(aval, "shape", ())
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _aval_bytes(aval) -> int:
    dt = getattr(aval, "dtype", None)
    if dt is None:  # token / abstract unit values
        return 0
    return _aval_elems(aval) * dt.itemsize


def _is_literal(v) -> bool:
    return not hasattr(v, "count")  # core.Var has .count; Literal does not


def _eqn_bytes(eqn) -> int:
    """HBM traffic proxy: read every non-literal input, write every
    output. Gather reads only the fetched slices (not the whole source
    table — a 2-line wave must not be charged the full scene); scatter
    and dynamic_update_slice read AND write their full operand (XLA
    materializes the copy unless it can alias)."""
    name = eqn.primitive.name
    outs = sum(_aval_bytes(v.aval) for v in eqn.outvars)
    if name == "gather":
        idx = _aval_bytes(eqn.invars[1].aval) if len(eqn.invars) > 1 else 0
        return 2 * outs + idx  # slices read + output written + indices
    if name in _SCATTERS:
        operand = _aval_bytes(eqn.invars[0].aval)
        rest = sum(
            _aval_bytes(v.aval)
            for v in eqn.invars[1:]
            if not _is_literal(v)
        )
        return 2 * operand + rest
    if name == "dynamic_update_slice":
        operand = _aval_bytes(eqn.invars[0].aval)
        update = _aval_bytes(eqn.invars[1].aval)
        return 2 * operand + update
    ins = sum(
        _aval_bytes(v.aval) for v in eqn.invars if not _is_literal(v)
    )
    return ins + outs


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    out_elems = sum(_aval_elems(v.aval) for v in eqn.outvars)
    if name in _MOVEMENT:
        return 0
    if name == "dot_general":
        (lhs_c, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        k = 1
        for i in lhs_c:
            k *= int(lhs_shape[i])
        return 2 * k * out_elems
    if name in _REDUCTIONS or name.startswith("reduce_"):
        return sum(
            _aval_elems(v.aval) for v in eqn.invars if not _is_literal(v)
        )
    if name == "sort":
        n = max(_aval_elems(eqn.invars[0].aval), 2)
        return int(n * math.log2(n)) * len(eqn.invars)
    if name == "gather":
        return out_elems
    if name in _SCATTERS:
        return sum(
            _aval_elems(v.aval)
            for v in eqn.invars[2:]
            if not _is_literal(v)
        ) or out_elems
    if name in ("threefry2x32", "random_bits"):
        return 16 * out_elems  # ~13 rounds of ARX per counter pair
    if name in _TRANSCENDENTAL:
        return _TRANSCENDENTAL_WEIGHT * out_elems
    if name == "integer_pow":
        return 2 * out_elems
    if name == "select_n":
        return out_elems
    return out_elems  # default: one op per output element


# --------------------------------------------------------------------------
# rollup + findings containers
# --------------------------------------------------------------------------


@dataclass
class Rollup:
    """Static roofline for one entry point. Loop bodies count once, so
    for the drain/traversal loops this reads as cost per wave."""

    entry: str
    flops: int = 0
    hbm_bytes: int = 0
    eqns: int = 0
    n_dynamic_loops: int = 0
    fingerprint: str = ""

    @property
    def intensity(self) -> float:
        return self.flops / max(self.hbm_bytes, 1)

    def to_json(self) -> Dict:
        return {
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "eqns": self.eqns,
            "fingerprint": self.fingerprint,
        }


@dataclass(frozen=True)
class Finding:
    rule: str
    entry: str
    detail: str
    severity: str = "warning"
    waived: Optional[str] = None  # reason, when waived

    @property
    def finding_id(self) -> str:
        return f"{self.rule}:{self.entry}:{self.detail.split(' @ ')[0]}"

    def __str__(self) -> str:
        w = f" (waived: {self.waived})" if self.waived else ""
        return f"{self.entry}: {self.rule} [{self.severity}] {self.detail}{w}"


# thresholds (module constants so the adversarial tests can reference them)
CHURN_MIN_ELEMS = 64
RELAYOUT_MIN_BYTES = 1 << 16
GATHER_MIN_SLICE_BYTES = 16
GATHER_MIN_TOTAL_BYTES = 1 << 16
GATHER_INDEX_FACTOR = 4
BCAST_MIN_BYTES = 1 << 20
BCAST_MIN_RATIO = 64
PAD_MIN_BYTES = 1 << 20
PAD_MIN_WASTE = 1.0

#: (rule, entry substring, detail substring) -> reason. Deliberate
#: violations stay visible in --format json (waived, severity "info")
#: but do not fail the gate and are excluded from the text summary.
WAIVERS: List[Tuple[str, str, str, str]] = [
    (
        "JC-RELAYOUT", "", "perm=(1, 0, 2)",
        "flush feature build: the (CH, 8, BLOCK) swap feeds phi rows to "
        "the leaf matmul lane-major by design — the profiled layout of "
        "accel/stream.py _flush; hoisting is impossible (per-wave data)",
    ),
]


def _waiver_for(rule: str, entry: str, detail: str) -> Optional[str]:
    for r, e, d, reason in WAIVERS:
        if r == rule and e in entry and d in detail:
            return reason
    return None


# --------------------------------------------------------------------------
# the abstract interpreter
# --------------------------------------------------------------------------


class _Walk:
    def __init__(self, entry: str, wave_width: int):
        self.entry = entry
        self.wave = max(int(wave_width), 1)
        self.flops = 0
        self.bytes = 0
        self.eqns = 0
        self.n_dynamic_loops = 0
        self.findings: List[Finding] = []
        self._fp = hashlib.sha256()
        #: var id -> source dtype string of the convert chain it carries
        self._churn_src: Dict[int, Tuple[str, int]] = {}
        #: var ids that provably derive from a lax.sort output — gathers
        #: at such indices are the sanctioned near-bandwidth pattern
        self._sorted: set = set()

    # -- findings ------------------------------------------------------
    def _emit(self, rule: str, detail: str) -> None:
        waived = _waiver_for(rule, self.entry, detail)
        f = Finding(
            rule, self.entry, detail,
            severity="info" if waived else "warning", waived=waived,
        )
        if f not in self.findings:
            self.findings.append(f)

    def _check_churn(self, eqn) -> None:
        """A -> B -> A convert chain: tag each convert's output with the
        dtype it LEFT, propagate the tag through shape ops and cheap
        elementwise ops whose other operands are literals, and flag when
        a later convert lands back on the tagged source dtype."""
        name = eqn.primitive.name
        if name == "convert_element_type":
            src_v = eqn.invars[0]
            out_v = eqn.outvars[0]
            src_dt = str(src_v.aval.dtype)
            out_dt = str(out_v.aval.dtype)
            if src_dt == out_dt:
                return
            tag = self._churn_src.get(id(src_v))
            elems = _aval_elems(out_v.aval)
            if tag is not None and tag[0] == out_dt and elems >= CHURN_MIN_ELEMS:
                self._emit(
                    "JC-CHURN",
                    f"{out_dt}->{src_dt}->{out_dt} round trip "
                    f"@ {elems} elems — two convert passes over the "
                    "array; keep one dtype through the chain",
                )
            else:
                self._churn_src[id(out_v)] = (src_dt, elems)
            return
        # propagation: shape-preserving movement and cheap arithmetic
        # whose other operands are literals keep the tag alive
        prop = name in (
            "reshape", "transpose", "squeeze", "expand_dims",
            "broadcast_in_dim", "slice", "copy",
        ) or (
            name in ("add", "sub", "mul", "max", "min", "neg", "clamp")
            and sum(0 if _is_literal(v) else 1 for v in eqn.invars) == 1
        )
        if prop:
            for v in eqn.invars:
                if not _is_literal(v) and id(v) in self._churn_src:
                    for ov in eqn.outvars:
                        self._churn_src[id(ov)] = self._churn_src[id(v)]
                    break

    def _track_sorted(self, eqn) -> None:
        name = eqn.primitive.name
        if name == "sort":
            for ov in eqn.outvars:
                self._sorted.add(id(ov))
            return
        # order-preserving-enough propagation: clip/offset/reshape keep
        # a sorted index stream nearly sorted; select_n (jnp.where used
        # to mask lanes) keeps the surviving runs sorted
        prop = name in (
            "reshape", "slice", "squeeze", "expand_dims",
            "broadcast_in_dim", "copy", "convert_element_type",
            "max", "min", "clamp", "select_n",
        ) or (
            name in ("add", "sub")
            and sum(0 if _is_literal(v) else 1 for v in eqn.invars) == 1
        )
        if prop and any(
            not _is_literal(v) and id(v) in self._sorted
            for v in eqn.invars
        ):
            for ov in eqn.outvars:
                self._sorted.add(id(ov))

    def _check_patterns(self, eqn) -> None:
        name = eqn.primitive.name
        self._check_churn(eqn)
        self._track_sorted(eqn)
        if name == "transpose":
            nbytes = _aval_bytes(eqn.invars[0].aval)
            if nbytes >= RELAYOUT_MIN_BYTES:
                shape = tuple(eqn.invars[0].aval.shape)
                self._emit(
                    "JC-RELAYOUT",
                    f"transpose of {nbytes} B buffer {shape} "
                    f"@ perm={eqn.params.get('permutation')} — a relayout "
                    "copy per wave; hoist to build time or keep the "
                    "consumer layout",
                )
        elif name == "gather" and len(eqn.invars) > 1:
            idx_v = eqn.invars[1]
            out_b = _aval_bytes(eqn.outvars[0].aval)
            idx_shape = idx_v.aval.shape
            n_idx = _aval_elems(idx_v.aval) // max(
                idx_shape[-1] if idx_shape else 1, 1
            )
            slice_bytes = out_b // max(n_idx, 1)
            sorted_idx = _is_literal(idx_v) or id(idx_v) in self._sorted
            # only FLAT index streams ((N, d) indices) are candidate
            # random access; a multi-dim index block is a batched
            # take_along_axis whose picks stay local to their own row
            flat_idx = len(idx_shape) <= 2
            if (
                0 < slice_bytes < GATHER_MIN_SLICE_BYTES
                and out_b >= GATHER_MIN_TOTAL_BYTES
                and n_idx > GATHER_INDEX_FACTOR * self.wave
                and flat_idx
                and not sorted_idx
            ):
                self._emit(
                    "JC-GATHER",
                    f"narrow gather: {slice_bytes} B/row over {n_idx} "
                    f"indices (wave width {self.wave}) — random access "
                    "far past wave width; batch rows or sort indices",
                )
        elif name == "broadcast_in_dim":
            out_b = _aval_bytes(eqn.outvars[0].aval)
            in_elems = sum(
                _aval_elems(v.aval)
                for v in eqn.invars
                if not _is_literal(v)
            )
            in_b = max(
                sum(
                    _aval_bytes(v.aval)
                    for v in eqn.invars
                    if not _is_literal(v)
                ),
                1,
            )
            if (
                in_elems > 1  # scalar broadcasts fuse for free
                and out_b >= BCAST_MIN_BYTES
                and out_b // in_b >= BCAST_MIN_RATIO
            ):
                self._emit(
                    "JC-BCAST",
                    f"broadcast blowup {in_b} B -> {out_b} B "
                    f"({out_b // in_b}x) @ {tuple(eqn.outvars[0].aval.shape)}"
                    " — XLA may materialize the expansion",
                )
        for ov in eqn.outvars:
            self._check_pad(ov)

    def _check_pad(self, v) -> None:
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is None or len(aval.shape) < 2:
            return
        nbytes = _aval_bytes(aval)
        if nbytes < PAD_MIN_BYTES:
            return
        # TPU vector memory tiles f32 as (8, 128) over the two minor
        # dims; narrower dtypes pack proportionally more sublanes
        sub = max(8 * 4 // max(dt.itemsize, 1), 8)
        s0, s1 = int(aval.shape[-2]), int(aval.shape[-1])
        padded = -(-s0 // sub) * sub * (-(-s1 // 128) * 128)
        waste = padded / max(s0 * s1, 1) - 1.0
        if waste > PAD_MIN_WASTE:
            self._emit(
                "JC-PAD",
                f"padding waste {waste:.1f}x on {tuple(aval.shape)} "
                f"{dt} ({nbytes} B) @ (8,128)-tile — pad or re-layout "
                "the trailing dims",
            )

    # -- structural walk -----------------------------------------------
    def _charge(self, flops: int, nbytes: int, mult: int) -> None:
        self.flops += flops * mult
        self.bytes += nbytes * mult

    def walk(self, jaxpr, mult: int = 1) -> None:
        from jax import core

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            self.eqns += 1
            self._fp.update(name.encode())
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None:
                    self._fp.update(
                        f"{getattr(aval, 'shape', ())}"
                        f"{getattr(aval, 'dtype', '')}".encode()
                    )
            if name == "while":
                # dynamic trip count: body charged ONCE (one wave)
                self.n_dynamic_loops += 1
                self.walk(eqn.params["cond_jaxpr"].jaxpr, mult)
                self.walk(eqn.params["body_jaxpr"].jaxpr, mult)
                continue
            if name == "scan":
                self.walk(
                    eqn.params["jaxpr"].jaxpr,
                    mult * max(int(eqn.params.get("length", 1)), 1),
                )
                continue
            if name == "cond":
                # one branch executes: charge the most expensive one
                best = None
                for br in eqn.params["branches"]:
                    sub = _Walk(self.entry, self.wave)
                    sub.walk(br.jaxpr, 1)
                    if best is None or sub.bytes > best.bytes:
                        best = sub
                    self._merge_findings(sub)
                    self.eqns += sub.eqns
                    self.n_dynamic_loops += sub.n_dynamic_loops
                    self._fp.update(sub._fp.digest())
                if best is not None:
                    self._charge(best.flops, best.bytes, mult)
                continue
            if name == "pallas_call":
                self._charge_pallas(eqn, mult)
                continue
            if name in _CONTROL:
                sub = None
                for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                    if key in eqn.params:
                        sub = eqn.params[key]
                        break
                if sub is not None:
                    inner = sub.jaxpr if isinstance(
                        sub, core.ClosedJaxpr
                    ) else sub
                    # call-like boundaries (jnp.clip and friends wrap in
                    # pjit) bind fresh inner vars positionally: carry the
                    # sorted/churn tags across, both directions, so a
                    # sort -> clip -> gather chain stays visible
                    for outer, iv in zip(eqn.invars, inner.invars):
                        if _is_literal(outer):
                            continue
                        if id(outer) in self._sorted:
                            self._sorted.add(id(iv))
                        if id(outer) in self._churn_src:
                            self._churn_src[id(iv)] = self._churn_src[
                                id(outer)
                            ]
                    self.walk(inner, mult)
                    for iv, outer in zip(inner.outvars, eqn.outvars):
                        if _is_literal(iv):
                            continue
                        if id(iv) in self._sorted:
                            self._sorted.add(id(outer))
                        if id(iv) in self._churn_src:
                            self._churn_src[id(outer)] = self._churn_src[
                                id(iv)
                            ]
                    continue
            self._charge(_eqn_flops(eqn), _eqn_bytes(eqn), mult)
            self._check_patterns(eqn)

    def _charge_pallas(self, eqn, mult: int) -> None:
        """A Pallas kernel's HBM traffic is its DMA schedule, not its
        operand list: each operand moves min(full array, block bytes x
        grid steps) — a constant index_map fetches its block once, a
        data-dependent one (the fused flush's scalar-prefetch treelet
        row) at most once per grid step, and consecutive steps mapping
        to the SAME block (the treelet-sorted buffer's common case) are
        not re-fetched, which the full-array min also bounds. Kernel-
        internal loads/stores are VMEM, so the body contributes flops
        only, once per grid step. Charging the raw operand list instead
        would bill the fused flush for the whole (C, 16, 4L) feature
        table per chunk — the exact HBM round trip the kernel exists to
        avoid."""
        from jax import core

        gm = eqn.params.get("grid_mapping")
        grid_steps = 1
        for g in getattr(gm, "grid", ()) or ():
            grid_steps *= max(int(g), 1)
        kernel = eqn.params.get("jaxpr")
        if kernel is not None:
            inner = kernel.jaxpr if isinstance(
                kernel, core.ClosedJaxpr
            ) else kernel
            sub = _Walk(self.entry, self.wave)
            sub.walk(inner, 1)
            self.flops += sub.flops * grid_steps * mult
            self.eqns += sub.eqns
            self.n_dynamic_loops += sub.n_dynamic_loops
            # anti-pattern findings inside the kernel body surface like
            # any other code — the budgeted TPU hot path is the last
            # place a flagged gather/churn chain should go invisible
            self._merge_findings(sub)
            self._fp.update(sub._fp.digest())

        def _blk_bytes(bm, aval) -> int:
            shape = getattr(bm, "block_shape", None)
            if shape is None:
                return _aval_bytes(aval)
            n = 1
            for s in shape:
                n *= int(s) if s is not None else 1
            dt = getattr(aval, "dtype", None)
            return n * (dt.itemsize if dt is not None else 4)

        n_idx = int(getattr(gm, "num_index_operands", 0) or 0)
        bms = list(getattr(gm, "block_mappings", ()) or ())
        n_out = len(eqn.outvars)
        in_bms = bms[: max(len(bms) - n_out, 0)]
        out_bms = bms[max(len(bms) - n_out, 0):]
        total = sum(
            _aval_bytes(v.aval)
            for v in eqn.invars[:n_idx]
            if not _is_literal(v)
        )  # scalar-prefetch operands: read whole, once
        for v, bm in zip(eqn.invars[n_idx:], in_bms):
            if _is_literal(v):
                continue
            full = _aval_bytes(v.aval)
            total += min(full, _blk_bytes(bm, v.aval) * grid_steps)
        for v, bm in zip(eqn.outvars, out_bms):
            full = _aval_bytes(v.aval)
            total += min(full, _blk_bytes(bm, v.aval) * grid_steps)
        if not bms:  # no grid mapping info: fall back to operand list
            total = _eqn_bytes(eqn)
        self.bytes += total * mult

    def _merge_findings(self, sub: "_Walk") -> None:
        for f in sub.findings:
            if f not in self.findings:
                self.findings.append(f)


def analyze_jaxpr(
    closed_jaxpr, entry: str, wave_width: int = 1
) -> Tuple[Rollup, List[Finding]]:
    """Roll up (flops, HBM bytes, fingerprint) and anti-pattern findings
    for one entry-point ClosedJaxpr."""
    w = _Walk(entry, wave_width)
    w.walk(closed_jaxpr.jaxpr)
    # constants enter the program once per dispatch
    w.bytes += sum(
        _aval_bytes(v.aval) for v in closed_jaxpr.jaxpr.constvars
    )
    roll = Rollup(
        entry=entry,
        flops=w.flops,
        hbm_bytes=w.bytes,
        eqns=w.eqns,
        n_dynamic_loops=w.n_dynamic_loops,
        fingerprint=w._fp.hexdigest()[:16],
    )
    return roll, w.findings


# --------------------------------------------------------------------------
# entry-point registry (shares audit.py's cached tiny scenes)
# --------------------------------------------------------------------------


def default_entry_points():
    """name -> () -> (ClosedJaxpr, wave_width). Import-deferred: building
    them traces real programs over audit.py's lru-cached scenes."""
    from tpu_pbrt.analysis import audit

    return {
        "path.li": lambda: (audit.integrator_li_jaxpr("path"), 64),
        "pool_chunk": lambda: (audit.pool_chunk_jaxpr(), 64),
        "stream_intersect": lambda: (audit.stream_traversal_jaxpr(), 128),
        # the TPU_PBRT_FUSED=1 programs (ISSUE 9): same waves through
        # the fused Pallas flush/expand kernels. The acceptance bar —
        # fused flush HBM bytes >= 3x below the jnp flush — is pinned
        # against these budget entries by tests/test_fusedwave.py.
        "stream_intersect_fused": lambda: (
            audit.stream_traversal_jaxpr(fused=True), 128,
        ),
        "pool_chunk_fused": lambda: (
            audit.pool_chunk_jaxpr(fused=True), 64,
        ),
        "film.add_samples": lambda: (audit.film_deposit_jaxpr(), 64),
        "film.add_samples_pixel": lambda: (
            audit.film_deposit_jaxpr(pixel_path=True), 64,
        ),
        "mesh_step": lambda: (audit.mesh_step_jaxpr(), 64),
        # the render service's slice dispatch (ISSUE 6): same pool drain,
        # service-shaped slice width — the serving hot path's own budget
        "serve_step": lambda: (audit.serve_step_jaxpr(), 64),
    }


def collect_rollups(
    entries=None,
) -> Tuple[Dict[str, Rollup], List[Finding], List[str]]:
    """Trace every entry point. Returns (rollups, findings, crashes) —
    a crash is reported, never raised (the CLI must print a full report)."""
    entries = entries if entries is not None else default_entry_points()
    rollups: Dict[str, Rollup] = {}
    findings: List[Finding] = []
    crashes: List[str] = []
    for name, fn in entries.items():
        try:
            jx, wave = fn()
            roll, f = analyze_jaxpr(jx, name, wave)
            rollups[name] = roll
            findings.extend(f)
        except Exception as e:  # noqa: BLE001
            crashes.append(f"{name}: cost trace crashed: {type(e).__name__}: {e}")
    return rollups, findings, crashes


# --------------------------------------------------------------------------
# the budget gate
# --------------------------------------------------------------------------

BUDGETS_PATH = Path(__file__).resolve().parent / "budgets.json"
DEFAULT_TOLERANCE = 0.10


def load_budgets(path: Optional[Path] = None) -> Dict:
    p = Path(path) if path is not None else BUDGETS_PATH
    if not p.exists():
        return {"tolerance": DEFAULT_TOLERANCE, "entries": {}}
    return json.loads(p.read_text())


def save_budgets(
    rollups: Dict[str, Rollup], path: Optional[Path] = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Path:
    import jax

    p = Path(path) if path is not None else BUDGETS_PATH
    data = {
        "_comment": (
            "Static per-entry-point roofline budgets (jaxcost, ISSUE 3). "
            "Regenerate with `python -m tpu_pbrt.analysis "
            "--update-budgets` after an INTENTIONAL hot-path change; "
            "CI fails when flops/bytes drift past tolerance."
        ),
        "tolerance": tolerance,
        # the counts depend on how THIS jax version lowers jnp ops to
        # primitives; record it so cross-version drift is diagnosable
        "jax_version": jax.__version__,
        "entries": {k: r.to_json() for k, r in sorted(rollups.items())},
    }
    p.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return p


def check_budgets(
    rollups: Dict[str, Rollup], budgets: Dict
) -> Tuple[List[str], List[str]]:
    """Compare fresh rollups against committed budgets. Returns
    (errors, warnings): regressions beyond tolerance are errors;
    improvements beyond tolerance and fingerprint drift are warnings
    nudging a `--update-budgets` ratchet."""
    errors: List[str] = []
    warnings: List[str] = []
    tol = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    committed = budgets.get("entries", {})
    rec_ver = budgets.get("jax_version")
    if rec_ver:
        import jax

        if jax.__version__ != rec_ver:
            warnings.append(
                f"budgets.json was generated under jax {rec_ver}; this "
                f"process runs jax {jax.__version__} — primitive "
                "lowering differs across versions, so metric drift below "
                "may be the jax upgrade, not your change (refresh with "
                "--update-budgets on the CI jax version)"
            )
    for name, roll in sorted(rollups.items()):
        b = committed.get(name)
        if b is None:
            errors.append(
                f"{name}: no committed budget — run "
                "`python -m tpu_pbrt.analysis --update-budgets` and "
                "commit budgets.json"
            )
            continue
        for metric, fresh in (("flops", roll.flops),
                              ("hbm_bytes", roll.hbm_bytes)):
            base = int(b.get(metric, 0))
            if base <= 0:
                continue
            ratio = fresh / base
            if ratio > 1.0 + tol:
                errors.append(
                    f"{name}: static {metric} regressed {ratio:.2f}x "
                    f"({base} -> {fresh}, tolerance {tol:.0%}) — fix the "
                    "hot path or, if intentional, refresh with "
                    "--update-budgets"
                )
            elif ratio < 1.0 - tol:
                warnings.append(
                    f"{name}: static {metric} improved {ratio:.2f}x "
                    f"({base} -> {fresh}) — ratchet the budget down with "
                    "--update-budgets"
                )
        if b.get("fingerprint") and b["fingerprint"] != roll.fingerprint:
            warnings.append(
                f"{name}: program fingerprint changed "
                f"({b['fingerprint']} -> {roll.fingerprint}) — the "
                "entry-point jaxpr was edited; refresh budgets.json if "
                "the metrics above look right"
            )
    for name in committed:
        if name not in rollups and not name.startswith("_"):
            warnings.append(
                f"{name}: committed budget has no live entry point — "
                "remove it with --update-budgets"
            )
    return errors, warnings


def run_cost(
    update: bool = False, budgets_path: Optional[Path] = None, entries=None,
) -> Tuple[List[str], List[str], Dict[str, Rollup], List[Finding]]:
    """The CLI/test driver: trace, roll up, gate (or refresh) budgets.
    Returns (errors, warnings, rollups, findings)."""
    rollups, findings, crashes = collect_rollups(entries)
    errors: List[str] = list(crashes)
    warnings: List[str] = []
    active = [f for f in findings if f.waived is None]
    warnings.extend(str(f) for f in active)
    if update:
        # refresh the ROLLUPS only — a tolerance someone tightened in
        # the committed file must survive the update
        prev_tol = float(
            load_budgets(budgets_path).get("tolerance", DEFAULT_TOLERANCE)
        )
        save_budgets(rollups, budgets_path, tolerance=prev_tol)
    else:
        e, w = check_budgets(rollups, load_budgets(budgets_path))
        errors.extend(e)
        warnings.extend(w)
    return errors, warnings, rollups, findings


# --------------------------------------------------------------------------
# bench hook: production-shaped wave cost
# --------------------------------------------------------------------------


def _bench_pool(chunk: int) -> int:
    """The bench wave's pool-size default — ONE definition so the
    roofline half and the VMEM half of the same --bench-wave line always
    describe the same wave width."""
    return max(chunk // 4, min(chunk, 4096))


@lru_cache(maxsize=2)
def _bench_scene(res: int, spp: int):
    """The production-shaped killeroo-like scene, compiled once per
    process and shared by the roofline AND VMEM halves of --bench-wave."""
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(
        res=res, spp=spp, integrator="path", maxdepth=5,
        n_theta=24, n_phi=48,
    )
    return compile_api(api)


def bench_wave_rollup(
    res: int = 512, spp: int = 256, chunk: int = 1 << 20,
    pool: Optional[int] = None,
) -> Rollup:
    """Static cost of ONE production-shaped drain wave: traces
    PathIntegrator.pool_chunk at the TPU chunk width over a killeroo-like
    scene with the real film resolution (the mesh is kept small — table
    sizes barely touch the per-wave numbers, the wave/film shapes
    dominate). Pure trace: works with the TPU tunnel down, which is the
    point (BENCH_r05)."""
    import jax
    import jax.numpy as jnp

    scene, integ = _bench_scene(res, spp)
    film = scene.film
    if pool is None:
        pool = _bench_pool(chunk)

    def fn(fs, start_pix, start_s):
        return integ.pool_chunk(
            scene.dev, fs, start_pix, start_s, chunk, pool,
            film=film, cam=scene.camera,
        )

    jx = jax.make_jaxpr(fn)(
        film.init_state(), jnp.int32(0), jnp.int32(0)
    )
    roll, _ = analyze_jaxpr(jx, "bench.pool_chunk", pool)
    return roll


def bench_wave_vmem(
    res: int = 512, spp: int = 256, chunk: int = 1 << 20,
    pool: Optional[int] = None,
) -> Dict:
    """The VMEM half of the static wave signal (ISSUE 11 satellite):
    pallascheck's per-grid-step footprint of the fused kernels this
    bench wave would dispatch on a TPU — camera + pending-shadow rays
    ride ONE 2R fused wave, capped at TPU_PBRT_FUSED_MAX_RAYS (past the
    cap the tracer falls back to jnp, so the capped width is the fused
    operating point). `vmem_headroom` is the fraction of the model's
    VMEM budget (headroom x smallest-platform capacity) still free —
    negative means the wave could not compile within budget. Advisory:
    returns {} when the scene has no stream tracer."""
    from tpu_pbrt.analysis import pallascheck
    from tpu_pbrt.config import cfg

    scene, _ = _bench_scene(res, spp)
    if pool is None:
        pool = _bench_pool(chunk)
    tp = scene.dev.get("tstream")
    if tp is None:
        return {}
    R = min(2 * int(pool), int(cfg.fused_max_rays))
    vmem = pallascheck.wave_vmem(
        R, int(tp.top.child_idx.shape[0]),
        motion=(tp.n_features == 64), L=tp.leaf_tris,
    )
    budget = int(
        min(pallascheck.VMEM_BYTES.values()) * pallascheck.VMEM_HEADROOM
    )
    return {
        "static_vmem_per_wave": vmem,
        "vmem_headroom": round(1.0 - vmem / budget, 4),
    }


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.analysis.cost")
    ap.add_argument("--bench-wave", action="store_true",
                    help="trace the production-shaped pool wave and print "
                         "its static roofline as one JSON line")
    ap.add_argument("--res", type=int, default=512)
    ap.add_argument("--spp", type=int, default=256)
    ap.add_argument("--update-budgets", action="store_true")
    args = ap.parse_args(argv)
    if args.bench_wave:
        roll = bench_wave_rollup(res=args.res, spp=args.spp)
        line = {
            "static_flops_per_wave": roll.flops,
            "static_bytes_per_wave": roll.hbm_bytes,
            "static_intensity": round(roll.intensity, 3),
            "fingerprint": roll.fingerprint,
        }
        try:
            # the VMEM half (pallascheck): advisory — the HBM roofline
            # fields above must survive any pallascheck drift
            line.update(bench_wave_vmem(res=args.res, spp=args.spp))
        except Exception as e:  # noqa: BLE001
            import sys

            print(f"bench-wave vmem model failed: {e}", file=sys.stderr)
        try:
            # the HBM half (hbmcheck, ISSUE 18): the static per-job
            # serve footprint + the fraction of the smallest platform's
            # HBM budget free at current knobs — advisory like the VMEM
            # block, the roofline fields above survive any drift
            from tpu_pbrt.analysis.hbmcheck import bench_fields

            line.update(bench_fields(rx=args.res, ry=args.res))
        except Exception as e:  # noqa: BLE001
            import sys

            print(f"bench-wave hbm model failed: {e}", file=sys.stderr)
        print(json.dumps(line))
        return 0
    errors, warnings, rollups, _ = run_cost(update=args.update_budgets)
    for r in rollups.values():
        print(
            f"{r.entry}: {r.flops} flops, {r.hbm_bytes} B, "
            f"intensity {r.intensity:.2f}, fp {r.fingerprint}"
        )
    for w in warnings:
        print(f"WARN: {w}")
    for e in errors:
        print(f"ERROR: {e}")
    return 1 if errors else 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
