"""Layer 1: AST lint over the tpu_pbrt source tree.

The rule set encodes the invariant bugs that almost sank PR 1 (and that
every rung of the ROADMAP perf ladder will threaten again):

JL-SYNC      host synchronization inside traced code — `.item()`,
             `.tolist()`, `np.asarray`/`np.array` on in-flight values,
             `jax.device_get`, `block_until_ready`, and `float()`/`bool()`
             applied to a local (tracer-shaped) value. Any of these inside
             the bounce loop serializes the dispatch pipe and erases the
             occupancy win.
JL-CALLBACK  `pure_callback` / `debug_callback` / `io_callback` /
             `jax.debug.print` in traced code — a hidden host round-trip
             per wave.
JL-F64       float64 introduction in traced code — `jnp.float64`,
             `np.float64`, `dtype="float64"`, `.astype(float)`. Silent f64
             promotion doubles HBM traffic and falls off the MXU.
JL-DTYPE     dtype-less `jnp.zeros/ones/empty/full/arange/linspace` in
             traced code — the dtype these default to flips with
             JAX_ENABLE_X64, so hot allocations must pin one.
JL-ENV       `os.environ` / `os.getenv` anywhere inside tpu_pbrt/ outside
             tpu_pbrt/config.py — every knob is read once at import by the
             config module (scattered reads made trace-time behavior
             depend on mutation order and defeated the jit cache key).
JL-MUT       in-place subscript mutation (`x[...] = v`, `x[...] += v`)
             inside traced code — jax arrays are immutable, so a store
             that typechecks is mutating a captured numpy buffer: exactly
             the donated-alias heap corruption from PR 1. Use `.at[].set()`.
JL-DONATE    `jax.jit(...)` without `donate_argnums` in the film/pool
             threading modules (integrators/common.py, parallel/mesh.py) —
             an undonated film accumulator doubles its HBM footprint and
             costs a copy per chunk.

Pragmas: `# jaxlint: disable=RULE[,RULE]` suppresses on that line — on a
`def` line it suppresses for the whole function body (for intentional
trace-time host helpers); `# jaxlint: disable-file=RULE[,RULE]` suppresses
file-wide. `python -m tpu_pbrt.analysis` prints every violation and the
pragma budget (the suite's acceptance bar is <= 5 suppressions repo-wide).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

# --------------------------------------------------------------------------
# rule registry + severity / allowlist config
# --------------------------------------------------------------------------

RULES: Dict[str, str] = {
    "JL-PARSE": "file does not parse",
    "JL-SYNC": "host synchronization inside traced code",
    "JL-CALLBACK": "host callback primitive inside traced code",
    "JL-F64": "float64 introduced inside traced code",
    "JL-DTYPE": "dtype-less array constructor inside traced code",
    "JL-ENV": "os.environ read outside tpu_pbrt/config.py",
    "JL-MUT": "in-place subscript mutation inside traced code",
    "JL-DONATE": "jax.jit without donate_argnums in a film/pool module",
}

#: rule -> "error" (exit 1) or "warning" (reported, exit 0)
SEVERITY: Dict[str, str] = {rule: "error" for rule in RULES}

#: repo-wide cap on `# jaxlint: disable` suppressions (ISSUE 2
#: acceptance); the CLI and tests/test_jaxlint.py both enforce it
PRAGMA_BUDGET = 5

#: rule -> path suffixes where the rule is suppressed wholesale. Keep this
#: SHORT — the per-line pragma is the sanctioned escape hatch; the
#: allowlist is for whole files whose job contradicts a rule.
ALLOWLIST: Dict[str, Tuple[str, ...]] = {
    # the config module is the one sanctioned environ reader; the
    # analysis CLI sets XLA_FLAGS for its own audit subprocess; the
    # chaos matrix runner configures backend/device-count env for its
    # own process BEFORE jax imports (the same pattern) and sandboxes
    # per-scenario knobs through config.reload()
    "JL-ENV": (
        "tpu_pbrt/config.py",
        "tpu_pbrt/analysis/__main__.py",
        "tpu_pbrt/chaos/__main__.py",
    ),
}

#: modules whose jax.jit calls thread the film/pool state and must donate
DONATE_MODULES: Tuple[str, ...] = (
    "tpu_pbrt/integrators/common.py",
    "tpu_pbrt/parallel/mesh.py",
)

#: higher-order entry points whose function arguments are traced
_TRACING_HOFS = {
    "jit",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "while_loop",
    "scan",
    "fori_loop",
    "cond",
    "switch",
    "shard_map",
    "checkpoint",
    "remat",
    "custom_jvp",
    "custom_vjp",
    # NOT pallas_call: pallas kernels legitimately store into refs, and
    # their host-sync surface is checked by the pallas lowering itself
}

#: decorator names that mark a function as traced
_TRACING_DECORATORS = {"jit", "vmap", "pmap", "custom_jvp", "custom_vjp"}

_PRAGMA_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Z0-9,\-\s]+)")
_PRAGMA_FILE_RE = re.compile(r"#\s*jaxlint:\s*disable-file=([A-Z0-9,\-\s]+)")

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SYNC_NP_FUNCS = {"asarray", "array", "copyto", "frombuffer", "save", "load"}
_CALLBACK_NAMES = {
    "pure_callback",
    "debug_callback",
    "io_callback",
    "call_tf",
    "host_callback",
}
#: jnp constructors that take dtype as (positional index | None=kwarg only)
_DTYPE_CTORS: Dict[str, Optional[int]] = {
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "arange": None,
    "linspace": None,
}


def _rel(path: Path, repo_root: Path) -> str:
    """Repo-relative posix path; a path outside the repo (explicit CLI
    argument) falls back to its absolute form instead of crashing —
    path-scoped rules (allowlist, DONATE_MODULES) then simply don't
    match it."""
    try:
        return path.resolve().relative_to(repo_root.resolve()).as_posix()
    except ValueError:
        return path.resolve().as_posix()


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str
    severity: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} [{self.severity}] "
            f"{self.message}"
        )


# --------------------------------------------------------------------------
# traced-function discovery: an intra-file call graph seeded at jit/lax
# boundaries, propagated by (qualified-enough) name
# --------------------------------------------------------------------------


def _call_name(func: ast.expr) -> Optional[str]:
    """Trailing name of a call target: `jit` for jax.jit, `while_loop`
    for jax.lax.while_loop, `li` for self.li."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_partial_jit(call: ast.Call) -> bool:
    """partial(jax.jit, ...) / functools.partial(jit, ...)"""
    if _call_name(call.func) != "partial" or not call.args:
        return False
    first = call.args[0]
    return _call_name(first) in _TRACING_DECORATORS if isinstance(
        first, (ast.Name, ast.Attribute)
    ) else False


#: method names too generic to resolve by bare name across the package —
#: `.at[i].add(v)` must not mark ParamSet.add, builtin next() must not
#: mark Lexer.next. Calls through these still propagate when the target
#: is in the SAME module under a specific name.
_GENERIC_NAMES = {
    "add", "get", "set", "copy", "next", "update", "pop", "append",
    "extend", "items", "keys", "values", "shape", "put", "clear",
}


class _FnIndex(ast.NodeVisitor):
    """Collect every function/lambda with a stable key, its parent
    function (lexical nesting), the calls it makes (split into bare-name
    calls and attribute calls), and the module's `from X import y` map."""

    def __init__(self) -> None:
        self.fns: Dict[int, ast.AST] = {}  # id(node) -> node
        self.by_name: Dict[str, List[int]] = {}
        self.parent: Dict[int, Optional[int]] = {}
        self.name_calls: Dict[int, Set[str]] = {}
        self.attr_calls: Dict[int, Set[str]] = {}
        self.imports: Dict[str, str] = {}  # local name -> source module
        self.fn_args: Dict[int, Set[str]] = {}  # names passed to HOFs
        self.roots: Set[int] = set()
        self._stack: List[int] = []

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for a in node.names:
                self.imports[a.asname or a.name] = node.module
        self.generic_visit(node)

    # -- function definitions ------------------------------------------
    def _enter(self, node: ast.AST, name: Optional[str]) -> None:
        key = id(node)
        self.fns[key] = node
        self.parent[key] = self._stack[-1] if self._stack else None
        self.name_calls[key] = set()
        self.attr_calls[key] = set()
        if name:
            self.by_name.setdefault(name, []).append(key)
        self._stack.append(key)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for dec in node.decorator_list:
            dn = None
            if isinstance(dec, (ast.Name, ast.Attribute)):
                dn = _call_name(dec)
            elif isinstance(dec, ast.Call):
                dn = _call_name(dec.func)
                if _is_partial_jit(dec):
                    dn = "jit"
            if dn in _TRACING_DECORATORS:
                self.roots.add(id(node))
        self._enter(node, node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter(node, None)
        self.generic_visit(node)
        self._stack.pop()

    # -- call sites ----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node.func)
        if self._stack and name:
            if isinstance(node.func, ast.Name):
                self.name_calls[self._stack[-1]].add(name)
            else:
                self.attr_calls[self._stack[-1]].add(name)
        if name in _TRACING_HOFS or _is_partial_jit(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    self.roots.add(id(arg))
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    an = _call_name(arg)
                    if an:
                        self.fn_args.setdefault(id(node), set()).add(an)
        self.generic_visit(node)


def _traced_map(trees: Dict[str, ast.AST]) -> Dict[str, Set[int]]:
    """Per-module ids of function nodes considered traced: jit-decorated
    or passed to a tracing HOF, plus everything reachable from a traced
    function through the by-name call graph. The graph is GLOBAL across
    `trees`: `chunk_fn` in common.py is jitted and calls
    `self.pool_chunk`, so `pool_chunk` in path.py is traced — methods
    resolve by bare name across modules, which over-approximates, but
    calls out of traced code are overwhelmingly to other traced helpers
    and a rare false positive is one pragma away."""
    indexes: Dict[str, _FnIndex] = {}
    by_name: Dict[str, List[Tuple[str, int]]] = {}
    #: dotted module name ("tpu_pbrt.core.vecmath") -> tree key
    by_dotted: Dict[str, str] = {}
    traced: Set[Tuple[str, int]] = set()
    for mod, t in trees.items():
        idx = _FnIndex()
        idx.visit(t)
        indexes[mod] = idx
        dotted = mod[:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        by_dotted[dotted] = mod
        for name, keys in idx.by_name.items():
            by_name.setdefault(name, []).extend((mod, k) for k in keys)
        traced |= {(mod, k) for k in idx.roots}
        # names passed to tracing HOFs seed by-name (same module only —
        # a bare function reference handed to jax.jit is a local)
        seeds: Set[str] = set()
        for names in idx.fn_args.values():
            seeds |= names
        for name in seeds:
            traced |= {(mod, k) for k in idx.by_name.get(name, ())}

    def resolve(mod: str, name: str, is_attr: bool) -> List[Tuple[str, int]]:
        """Call targets for `name` called from module `mod`.

        Bare-name calls bind lexically: same-module defs first, then the
        module's explicit `from X import name`; never package-wide (a
        bare `next(...)` is the builtin, not some class's .next method).
        Attribute calls (self.f / obj.f) resolve by name package-wide —
        except _GENERIC_NAMES, whose bare-name matches are coincidences.
        """
        idx = indexes[mod]
        if not is_attr:
            if name in idx.by_name:
                return [(mod, k) for k in idx.by_name[name]]
            src = idx.imports.get(name)
            if src is not None and src in by_dotted:
                smod = by_dotted[src]
                return [(smod, k) for k in indexes[smod].by_name.get(name, ())]
            return []
        if name in _GENERIC_NAMES:
            return [(mod, k) for k in idx.by_name.get(name, ())]
        return by_name.get(name, [])

    frontier: List[Tuple[str, int]] = list(traced)
    while frontier:
        mod, key = frontier.pop()
        idx = indexes[mod]
        # nested defs inside a traced fn execute at trace time
        for other, parent in idx.parent.items():
            if parent == key and (mod, other) not in traced:
                traced.add((mod, other))
                frontier.append((mod, other))
        for is_attr, names in (
            (False, idx.name_calls.get(key, ())),
            (True, idx.attr_calls.get(key, ())),
        ):
            for name in names:
                for target in resolve(mod, name, is_attr):
                    if target not in traced:
                        traced.add(target)
                        frontier.append(target)
    out: Dict[str, Set[int]] = {mod: set() for mod in trees}
    for mod, key in traced:
        out[mod].add(key)
    return out


def _traced_functions(tree: ast.AST) -> Set[int]:
    """Single-file convenience wrapper over _traced_map."""
    return _traced_map({"<target>": tree})["<target>"]


# --------------------------------------------------------------------------
# per-file lint
# --------------------------------------------------------------------------


#: attribute bases whose reads are static in this repo (config snapshot,
#: integrator params on self, numpy/math host constants). An attribute
#: on anything else — `hit.t`, `s.alive`, a NamedTuple tracer field — is
#: tracer-shaped and float()/bool() on it is a host sync.
_STATIC_BASES = {"self", "cls", "cfg", "np", "math", "os"}


def _literalish(node: ast.expr) -> bool:
    """Expressions that cannot be tracers: constants, attribute reads on
    known-static bases (cfg.slab, self.spp), .shape fields, len()/int()
    results."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in ("shape", "ndim", "size", "dtype"):
            return True  # static metadata even on tracers
        base = node.value
        while isinstance(base, ast.Attribute):
            base = base.value
        return isinstance(base, ast.Name) and base.id in _STATIC_BASES
    if isinstance(node, ast.Subscript):
        # x.shape[0], cfg-style table lookups on static bases
        return _literalish(node.value)
    if isinstance(node, ast.Call):
        n = _call_name(node.func)
        return n in {"len", "int", "max", "min", "getattr"}
    if isinstance(node, (ast.BinOp, ast.UnaryOp)):
        kids = [
            c for c in ast.iter_child_nodes(node) if isinstance(c, ast.expr)
        ]
        return all(_literalish(c) for c in kids if not isinstance(c, ast.operator))
    return False


def _np_aliases(tree: ast.AST) -> Set[str]:
    """Module aliases bound to numpy (import numpy as np / _np / onp)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out or {"np"}


class _RuleVisitor(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        traced_nodes: Set[int],
        np_names: Set[str],
        report,
    ) -> None:
        self.path = path
        self.traced_nodes = traced_nodes
        self.np_names = np_names
        self.report = report
        self._fn_stack: List[int] = []
        self._fn_lines: List[int] = []
        #: per enclosing function: local names bound to a fresh python
        #: list/dict/set literal — subscript stores on those are host
        #: container building, not captured-array mutation
        self._containers: List[Set[str]] = []

    # ---- scope tracking ----------------------------------------------
    def _in_traced(self) -> bool:
        return any(k in self.traced_nodes for k in self._fn_stack)

    def visit_FunctionDef(self, node):
        # JL-DONATE, decorator form: @jax.jit in a film/pool module must
        # donate when the function actually takes buffers (a zero-arg
        # staging helper has nothing to donate)
        if (
            not isinstance(node, ast.Lambda)
            and self.path.endswith(DONATE_MODULES)
            and getattr(node, "args", None) is not None
            and (node.args.args or node.args.posonlyargs)
        ):
            for dec in node.decorator_list:
                name = None
                if isinstance(dec, (ast.Name, ast.Attribute)):
                    name = _call_name(dec)
                elif isinstance(dec, ast.Call) and not any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords
                ):
                    name = _call_name(dec.func)
                    if _is_partial_jit(dec):
                        name = "jit"
                if name == "jit":
                    self._report(
                        "JL-DONATE",
                        node.lineno,
                        "@jax.jit in a film/pool-threading module must "
                        "donate the accumulator (donate_argnums=...)",
                    )
        self._fn_stack.append(id(node))
        self._fn_lines.append(node.lineno)
        self._containers.append(set())
        self.generic_visit(node)
        self._containers.pop()
        self._fn_lines.pop()
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _report(self, rule: str, lineno: int, message: str) -> None:
        self.report(rule, lineno, message, tuple(self._fn_lines))

    # ---- JL-ENV (module-wide) ----------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in ("environ", "getenv") and isinstance(
            node.value, ast.Name
        ) and node.value.id in ("os", "_os"):
            self._report(
                "JL-ENV",
                node.lineno,
                "environment read outside tpu_pbrt/config.py — add the "
                "knob to config.Config and read cfg.<name>",
            )
        self.generic_visit(node)

    # ---- JL-MUT ------------------------------------------------------
    def _is_local_container(self, expr: ast.expr) -> bool:
        return (
            isinstance(expr, ast.Name)
            and any(expr.id in s for s in self._containers)
        )

    def _check_mut(self, target: ast.expr, lineno: int) -> None:
        if (
            isinstance(target, ast.Subscript)
            and self._in_traced()
            and not self._is_local_container(target.value)
        ):
            self._report(
                "JL-MUT",
                lineno,
                "subscript store in traced code mutates a captured host "
                "buffer (jax arrays are immutable) — use .at[...].set()",
            )

    def _track_container(self, target: ast.expr, value: ast.expr) -> None:
        if not self._containers or not isinstance(target, ast.Name):
            return
        fresh = isinstance(
            value, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp)
        ) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("list", "dict", "set")
        )
        if fresh:
            self._containers[-1].add(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_mut(t, node.lineno)
            self._track_container(t, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_mut(node.target, node.lineno)
        self.generic_visit(node)

    # ---- call-shaped rules -------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node.func)
        traced = self._in_traced()
        if traced and name:
            self._check_sync(node, name)
            self._check_callback(node, name)
            self._check_dtype(node, name)
        if traced:
            self._check_f64_call(node, name)
        if name == "jit" and self.path.endswith(DONATE_MODULES):
            has_donate = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in node.keywords
            )
            if not has_donate:
                self._report(
                    "JL-DONATE",
                    node.lineno,
                    "jax.jit in a film/pool-threading module must donate "
                    "the accumulator (donate_argnums=...)",
                )
        self.generic_visit(node)

    def _check_sync(self, node: ast.Call, name: str) -> None:
        if name in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            self._report(
                "JL-SYNC",
                node.lineno,
                f".{name}() in traced code forces a host sync",
            )
            return
        if (
            name in _SYNC_NP_FUNCS
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self.np_names
        ):
            self._report(
                "JL-SYNC",
                node.lineno,
                f"numpy.{name} in traced code pulls the operand to host "
                "memory — use jnp",
            )
            return
        if name == "device_get":
            self._report(
                "JL-SYNC", node.lineno, "jax.device_get in traced code"
            )
            return
        if name in ("float", "bool") and isinstance(node.func, ast.Name):
            if node.args and not _literalish(node.args[0]):
                self._report(
                    "JL-SYNC",
                    node.lineno,
                    f"{name}() on a traced value forces a host sync — "
                    "keep it an array or mark the value static",
                )

    def _check_callback(self, node: ast.Call, name: str) -> None:
        if name in _CALLBACK_NAMES:
            self._report(
                "JL-CALLBACK",
                node.lineno,
                f"{name} embeds a host round-trip in the compiled wave",
            )
        elif name == "print" and isinstance(node.func, ast.Attribute):
            # jax.debug.print
            v = node.func.value
            if isinstance(v, ast.Attribute) and v.attr == "debug":
                self._report(
                    "JL-CALLBACK",
                    node.lineno,
                    "jax.debug.print lowers to debug_callback",
                )

    def _check_dtype(self, node: ast.Call, name: str) -> None:
        if name not in _DTYPE_CTORS or not isinstance(
            node.func, ast.Attribute
        ):
            return
        base = node.func.value
        if not (isinstance(base, ast.Name) and base.id in ("jnp", "jax")):
            return
        if any(kw.arg == "dtype" for kw in node.keywords):
            return
        pos = _DTYPE_CTORS[name]
        if pos is not None and len(node.args) > pos:
            return
        self._report(
            "JL-DTYPE",
            node.lineno,
            f"jnp.{name} without an explicit dtype — the default flips "
            "with JAX_ENABLE_X64; pin jnp.float32/int32",
        )

    def _check_f64_call(self, node: ast.Call, name: Optional[str]) -> None:
        # .astype(float) / .astype(np.float64)
        if name == "astype" and node.args:
            a = node.args[0]
            if (isinstance(a, ast.Name) and a.id == "float") or (
                isinstance(a, ast.Attribute) and a.attr == "float64"
            ):
                self._report(
                    "JL-F64",
                    node.lineno,
                    ".astype(float) is float64 under x64 — use jnp.float32",
                )

    # ---- JL-F64 name forms -------------------------------------------
    def visit_Constant(self, node: ast.Constant) -> None:
        if self._in_traced() and node.value in ("float64", "f64"):
            self._report(
                "JL-F64", node.lineno, "float64 dtype string in traced code"
            )
        self.generic_visit(node)


class _F64AttrVisitor(ast.NodeVisitor):
    """float64 attribute reads (np.float64 / jnp.float64) in traced code;
    separate pass so _RuleVisitor's Attribute hook stays JL-ENV-only."""

    def __init__(self, traced_nodes: Set[int], report) -> None:
        self.traced_nodes = traced_nodes
        self.report = report
        self._fn_stack: List[int] = []
        self._fn_lines: List[int] = []

    def _in_traced(self) -> bool:
        return any(k in self.traced_nodes for k in self._fn_stack)

    def visit_FunctionDef(self, node):
        self._fn_stack.append(id(node))
        self._fn_lines.append(node.lineno)
        self.generic_visit(node)
        self._fn_lines.pop()
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._in_traced() and node.attr in ("float64", "complex128"):
            self.report(
                "JL-F64",
                node.lineno,
                f"{node.attr} in traced code doubles HBM/MXU cost",
                tuple(self._fn_lines),
            )
        self.generic_visit(node)


def _pragmas(source: str) -> Tuple[Dict[int, Set[str]], Set[str], int]:
    """(line -> disabled rules, file-wide disabled rules, pragma count).

    Pragmas are recognized only in real COMMENT tokens (tokenize), so a
    docstring describing the pragma syntax is not itself a suppression."""
    import io
    import tokenize

    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    count = 0
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return per_line, per_file, 0
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_FILE_RE.search(tok.string)
        if m:
            per_file |= {r.strip() for r in m.group(1).split(",") if r.strip()}
            count += 1
            continue
        m = _PRAGMA_RE.search(tok.string)
        if m:
            per_line[tok.start[0]] = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
            count += 1
    return per_line, per_file, count


def lint_file(
    path: Path, repo_root: Path, traced: Optional[Set[int]] = None,
    tree: Optional[ast.AST] = None,
) -> Tuple[List[Violation], int]:
    """Lint one file. Returns (violations, pragma_count). `traced`/`tree`
    are supplied by lint_tree's global pass; standalone calls compute a
    file-local traced set."""
    rel = _rel(path, repo_root)
    source = path.read_text()
    if tree is None:
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError as e:  # a file that does not parse is an error
            return (
                [
                    Violation(
                        "JL-PARSE", rel, e.lineno or 0,
                        f"file does not parse: {e.msg}", "error",
                    )
                ],
                0,
            )
    line_pragmas, file_pragmas, n_pragmas = _pragmas(source)
    if traced is None:
        traced = _traced_functions(tree)
    np_names = _np_aliases(tree)
    out: List[Violation] = []

    def report(
        rule: str, lineno: int, message: str,
        scope_lines: Tuple[int, ...] = (),
    ) -> None:
        """scope_lines: def-statement lines of the enclosing functions —
        a pragma on a `def` line suppresses the rule for the whole body."""
        if rule in file_pragmas or rule in line_pragmas.get(lineno, ()):
            return
        if any(rule in line_pragmas.get(ln, ()) for ln in scope_lines):
            return
        if any(rel.endswith(sfx) for sfx in ALLOWLIST.get(rule, ())):
            return
        out.append(
            Violation(rule, rel, lineno, message, SEVERITY.get(rule, "error"))
        )

    _RuleVisitor(rel, traced, np_names, report).visit(tree)
    _F64AttrVisitor(traced, report).visit(tree)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out, n_pragmas


def lint_tree(
    root: Optional[Path] = None, paths: Optional[Iterable[Path]] = None
) -> Tuple[List[Violation], int]:
    """Lint the tpu_pbrt package (or explicit paths). Returns
    (violations, total pragma count)."""
    repo_root = (
        root if root is not None else Path(__file__).resolve().parents[2]
    )
    if paths is None:
        pkg = repo_root / "tpu_pbrt"
        paths = sorted(pkg.rglob("*.py"))
    paths = [Path(p) for p in paths]
    trees: Dict[str, ast.AST] = {}
    parse_errors: List[Violation] = []
    for p in paths:
        rel = _rel(p, repo_root)
        try:
            trees[rel] = ast.parse(p.read_text(), filename=str(p))
        except SyntaxError as e:
            parse_errors.append(
                Violation(
                    "JL-PARSE", rel, e.lineno or 0,
                    f"file does not parse: {e.msg}", "error",
                )
            )
    traced_map = _traced_map(trees)
    all_v: List[Violation] = list(parse_errors)
    pragmas = 0
    for p in paths:
        rel = _rel(p, repo_root)
        if rel not in trees:
            continue
        v, n = lint_file(
            p, repo_root, traced=traced_map[rel], tree=trees[rel]
        )
        all_v.extend(v)
        pragmas += n
    return all_v, pragmas
