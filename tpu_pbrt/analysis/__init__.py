"""jaxlint — repo-specific static analysis + jaxpr audit for TPU hot paths.

Two layers (ISSUE 2):

- **Layer 1 (AST lint, `lint.py`)**: syntactic rules over the source tree.
  A per-module call graph seeded at `jax.jit` / `lax.while_loop` /
  `shard_map` boundaries marks *traced* functions, and the hot-path rules
  (host syncs, f64 leaks, dtype-less constructors, captured-array
  mutation) fire only inside them, so host-side driver/build code stays
  lintable Python. `# jaxlint: disable=RULE` pragmas suppress per line.

- **Layer 2 (jaxpr/compile audit, `audit.py`)**: traces the real render
  entry points (path bounce wave, persistent pool drain, stream
  traversal, film deposit, sharded mesh step) and asserts over the jaxpr
  and the compiled executable: no f64 anywhere, no callback primitives,
  donation materialized as input->output aliasing for the film/pool
  buffers, zero retraces across same-shape waves, and a clean smoke
  render under jax.transfer_guard("disallow").

Run `python -m tpu_pbrt.analysis` (see `__main__.py`), or the pytest
mirrors in tests/test_jaxlint.py and tests/test_jaxpr_audit.py.
"""

from tpu_pbrt.analysis.lint import (  # noqa: F401
    RULES,
    Violation,
    lint_file,
    lint_tree,
)
