"""jaxlint — repo-specific static analysis + jaxpr audit for TPU hot paths.

Six layers (ISSUE 2 + ISSUE 3 + ISSUE 11 + ISSUE 17):

- **Layer 1 (AST lint, `lint.py`)**: syntactic rules over the source tree.
  A per-module call graph seeded at `jax.jit` / `lax.while_loop` /
  `shard_map` boundaries marks *traced* functions, and the hot-path rules
  (host syncs, f64 leaks, dtype-less constructors, captured-array
  mutation) fire only inside them, so host-side driver/build code stays
  lintable Python. `# jaxlint: disable=RULE` pragmas suppress per line.

- **Layer 2 (jaxpr/compile audit, `audit.py`)**: traces the real render
  entry points (path bounce wave, persistent pool drain, stream
  traversal, film deposit, sharded mesh step) and asserts over the jaxpr
  and the compiled executable: no f64 anywhere, no callback primitives,
  donation materialized as input->output aliasing for the film/pool
  buffers, zero retraces across same-shape waves, and a clean smoke
  render under jax.transfer_guard("disallow").

- **Layer 3 (static roofline budgets, `cost.py`)**: an abstract
  interpreter charges every entry-point equation FLOPs and HBM bytes,
  rolls them up per wave, gates against the committed `budgets.json`
  (refresh: `--update-budgets`), and reports anti-pattern findings
  (dtype churn, hot-buffer relayouts, narrow unsorted gathers,
  broadcast blowups, tile-padding waste) — a perf regression signal
  that works with the TPU tunnel down (the BENCH_r05 outage).

- **Layer 4 (shard_map replication analysis, `shardcheck.py`)**: tracks
  replicated-vs-varying values through every shard_map body and errors
  when an output claimed replicated (out_spec P()) was never reduced
  over the mesh axis, or a collective sits inside a varying-trip-count
  loop — restoring (and exceeding) the native check_rep/check_vma that
  SHARD_MAP_NOCHECK disables on jax versions where it is broken.

- **Layer 5 (Pallas VMEM + grid semantics, `pallascheck.py`)**: extracts
  every pallas_call from the fused entry points, computes the exact
  per-grid-step VMEM footprint (double-buffered moving blocks, resident
  constant-index_map blocks, flat scratch), gates it against the
  committed `vmem_budgets.json` and platform VMEM capacity, DERIVES the
  maximal safe TPU_PBRT_FUSED_MAX_RAYS/MAX_NODES from the model
  (`--derive-caps`), and abstract-interprets the kernel bodies with
  intervals over program_id to prove the accumulator pattern sound:
  no parallel-dim revisited output (PC-RACE), no read before the
  grid-step-0 seed (PC-INIT), no unprovable dynamic ref index (PC-OOB).

- **Layer 6 (serve/dispatch protocol verification, `protocheck.py`)**:
  the HOST-side state machine. Static SV-* rules (SV-CLOCK: wall clock
  sampled outside the injected `utils/clock.py` seam or twice in a
  deadline-scoped function; SV-DEFER: deferred checkpoint writes
  without retirement binding; SV-VTIME: fair-share vtime written
  outside the policy API), a seeded mutation-regression corpus of
  three historical bugs, and a bounded exhaustive exploration
  (`tools/explore.py`) of decision sequences — arrival orders x
  pipeline depths x CHAOS fault placements x preempt/resume timings —
  running the REAL RenderService under a VirtualClock and checking the
  PROTO-* invariants (counter reconciliation, deferred-write
  linearity, pin balance, backoff monotonicity, no wedge, schedule
  determinism, film bit-identity) after every decision.

Run `python -m tpu_pbrt.analysis` (see `__main__.py`), or the pytest
mirrors in tests/test_jaxlint.py, test_jaxpr_audit.py, test_cost.py,
test_shardcheck.py, test_pallascheck.py and test_protocheck.py.
"""

from tpu_pbrt.analysis.lint import (  # noqa: F401
    RULES,
    Violation,
    lint_file,
    lint_tree,
)
