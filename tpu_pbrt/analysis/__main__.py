"""`python -m tpu_pbrt.analysis` — run the full analysis suite.

Stages (each skippable):
- layer 1, AST lint (`lint.py`) — always runs;
- layer 2, jaxpr/compile audit (`audit.py`) — `--no-audit` skips (it
  compiles small render programs, a few seconds on CPU);
- jaxcost static roofline + budget gate (`cost.py`) — `--no-cost`
  skips; `--update-budgets` refreshes the committed
  `tpu_pbrt/analysis/budgets.json` instead of gating against it;
- shardcheck replication analysis (`shardcheck.py`) —
  `--no-shardcheck` skips;
- layer 5, pallascheck VMEM-budget + grid-semantics verification of the
  fused Pallas kernels (`pallascheck.py`) — `--no-pallascheck` skips;
  `--update-budgets` also refreshes its `vmem_budgets.json`;
- layer 6, protocheck serve/dispatch protocol verification
  (`protocheck.py`) — the SV-* static rules over the protocol modules,
  the seeded mutation-regression corpus, and a bounded interleaving/
  fault-schedule exploration of the REAL service under a virtual clock
  (`tools/explore.py`); `--no-protocheck` skips;
- layer 7, hbmcheck static HBM residency/liveness/capacity
  verification of the serve stack (`hbmcheck.py`) — the HC-* rules:
  worst-case footprint vs the per-platform capacity table + the
  committed `hbm_budgets.json` (HC-CAP, refreshed by
  `--update-budgets`), terminal-path device-buffer release (HC-LEAK),
  residency-estimate accuracy (HC-ACCT), and donation-alias dedup
  (HC-ALIAS); `--no-hbmcheck` skips.

Exit code 0 iff no error-severity findings in any stage that ran. A
stage that crashes is reported as that stage's failure and the REST of
the stages still run — a multi-stage run always reports every failing
stage before exiting non-zero.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _setup_jax_env() -> None:
    """One-time jax process setup shared by every jaxpr-tracing stage.
    Must happen before jax initializes a backend."""
    import os

    # only when the operator EXPLICITLY selected cpu (tools/ci.sh
    # does): unset JAX_PLATFORMS on a TPU VM means a TPU backend,
    # which must not inherit the unoptimized-CPU pipeline flag
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_backend_optimization_level" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_backend_optimization_level=0"
            ).strip()
    import jax

    repo_root = Path(__file__).resolve().parents[2]
    cache = repo_root / ".jax_cache"
    if cache.is_dir():
        jax.config.update("jax_compilation_cache_dir", str(cache))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.analysis")
    ap.add_argument(
        "paths", nargs="*", help="files to lint (default: all of tpu_pbrt/)"
    )
    ap.add_argument(
        "--no-audit", action="store_true",
        help="skip the jaxpr/compile-time audit layer",
    )
    ap.add_argument(
        "--no-cost", action="store_true",
        help="skip the jaxcost roofline/budget stage",
    )
    ap.add_argument(
        "--no-shardcheck", action="store_true",
        help="skip the shard_map replication analysis",
    )
    ap.add_argument(
        "--no-pallascheck", action="store_true",
        help="skip the Pallas VMEM-budget/grid-semantics verification",
    )
    ap.add_argument(
        "--no-protocheck", action="store_true",
        help="skip the serve/dispatch protocol verification layer",
    )
    ap.add_argument(
        "--no-hbmcheck", action="store_true",
        help="skip the static HBM residency/liveness/capacity layer",
    )
    ap.add_argument(
        "--update-budgets", action="store_true",
        help="refresh tpu_pbrt/analysis/budgets.json, "
             "vmem_budgets.json AND hbm_budgets.json from the current "
             "tree instead of gating against them (commit the result)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    from tpu_pbrt.analysis.lint import PRAGMA_BUDGET, lint_tree

    repo_root = Path(__file__).resolve().parents[2]
    paths = [Path(p).resolve() for p in args.paths] or None
    violations, pragmas = lint_tree(repo_root, paths)
    over_budget = paths is None and pragmas > PRAGMA_BUDGET

    need_jax = not (
        args.no_audit and args.no_cost and args.no_shardcheck
        and args.no_pallascheck and args.no_protocheck
        and args.no_hbmcheck
    )
    if need_jax:
        # CPU audit/cost/shardcheck/pallascheck compile or trace tiny
        # programs; the unoptimized XLA pipeline + the repo compilation
        # cache keep this to seconds.
        _setup_jax_env()

    # every stage runs inside its own guard: a stage that CRASHES is
    # reported as that stage's failure and the remaining stages still
    # run, so one broken layer can't hide findings from the others
    def _stage(fn, sink):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            sink.append(f"stage crashed: {type(e).__name__}: {e}")
            return None

    audit_failures: list = []
    if not args.no_audit:
        def _audit():
            from tpu_pbrt.analysis.audit import run_audit

            return run_audit()

        audit_failures = _stage(_audit, audit_failures) or audit_failures

    cost_errors: list = []
    cost_warnings: list = []
    rollups = {}
    cost_findings: list = []
    if not args.no_cost:
        def _cost():
            from tpu_pbrt.analysis.cost import run_cost

            return run_cost(update=args.update_budgets)

        out = _stage(_cost, cost_errors)
        if out is not None:
            cost_errors, cost_warnings, rollups, cost_findings = out

    shard_errors: list = []
    shard_warnings: list = []
    if not args.no_shardcheck:
        def _shard():
            from tpu_pbrt.analysis.shardcheck import run_shardcheck

            return run_shardcheck()

        out = _stage(_shard, shard_errors)
        if out is not None:
            shard_errors, shard_warnings = out

    pallas_errors: list = []
    pallas_warnings: list = []
    if not args.no_pallascheck:
        def _pallas():
            from tpu_pbrt.analysis.pallascheck import run_pallascheck

            return run_pallascheck(update=args.update_budgets)

        out = _stage(_pallas, pallas_errors)
        if out is not None:
            pallas_errors, pallas_warnings = out

    proto_errors: list = []
    proto_warnings: list = []
    if not args.no_protocheck:
        def _proto():
            from tpu_pbrt.analysis.protocheck import run_protocheck

            return run_protocheck(root=str(repo_root))

        out = _stage(_proto, proto_errors)
        if out is not None:
            proto_errors, proto_warnings = out

    hbm_errors: list = []
    hbm_warnings: list = []
    if not args.no_hbmcheck:
        def _hbm():
            from tpu_pbrt.analysis.hbmcheck import run_hbmcheck

            return run_hbmcheck(
                update=args.update_budgets, root=str(repo_root)
            )

        out = _stage(_hbm, hbm_errors)
        if out is not None:
            hbm_errors, hbm_warnings = out

    errors = [v for v in violations if v.severity == "error"]
    ok = not (
        errors or audit_failures or over_budget or cost_errors
        or shard_errors or pallas_errors or proto_errors or hbm_errors
    )
    if args.format == "json":
        print(
            json.dumps(
                {
                    "lint": [v.__dict__ for v in violations],
                    "audit": audit_failures,
                    "cost": {
                        "rollups": {
                            k: r.to_json() for k, r in rollups.items()
                        },
                        "findings": [
                            {
                                "rule": f.rule, "entry": f.entry,
                                "detail": f.detail,
                                "severity": f.severity,
                                "waived": f.waived,
                            }
                            for f in cost_findings
                        ],
                        "errors": cost_errors,
                        "warnings": cost_warnings,
                    },
                    "shardcheck": {
                        "errors": shard_errors,
                        "warnings": shard_warnings,
                    },
                    "pallascheck": {
                        "errors": pallas_errors,
                        "warnings": pallas_warnings,
                    },
                    "protocheck": {
                        "errors": proto_errors,
                        "warnings": proto_warnings,
                    },
                    "hbmcheck": {
                        "errors": hbm_errors,
                        "warnings": hbm_warnings,
                    },
                    "pragmas": pragmas,
                    "pragma_budget": PRAGMA_BUDGET,
                    "ok": ok,
                }
            )
        )
    else:
        for v in violations:
            print(v)
        for f in audit_failures:
            print(f"AUDIT: {f}")
        for w in cost_warnings:
            print(f"COST [warning]: {w}")
        for e in cost_errors:
            print(f"COST [error]: {e}")
        for w in shard_warnings:
            print(f"SHARDCHECK [warning]: {w}")
        for e in shard_errors:
            print(f"SHARDCHECK [error]: {e}")
        for w in pallas_warnings:
            print(f"PALLASCHECK [warning]: {w}")
        for e in pallas_errors:
            print(f"PALLASCHECK [error]: {e}")
        for w in proto_warnings:
            print(f"PROTOCHECK [warning]: {w}")
        for e in proto_errors:
            print(f"PROTOCHECK [error]: {e}")
        for w in hbm_warnings:
            print(f"HBMCHECK [warning]: {w}")
        for e in hbm_errors:
            print(f"HBMCHECK [error]: {e}")
        if args.update_budgets and not args.no_cost:
            from tpu_pbrt.analysis.cost import BUDGETS_PATH

            print(f"jaxcost: budgets refreshed -> {BUDGETS_PATH}")
        if args.update_budgets and not args.no_pallascheck:
            from tpu_pbrt.analysis.pallascheck import (
                BUDGETS_PATH as VMEM_BUDGETS_PATH,
            )

            print(
                f"pallascheck: VMEM budgets refreshed -> "
                f"{VMEM_BUDGETS_PATH}"
            )
        if args.update_budgets and not args.no_hbmcheck:
            from tpu_pbrt.analysis.hbmcheck import (
                BUDGETS_PATH as HBM_BUDGETS_PATH,
            )

            print(
                f"hbmcheck: HBM budgets refreshed -> {HBM_BUDGETS_PATH}"
            )
        n_warn = len(violations) - len(errors)
        # a SKIPPED stage must not read as a clean one in the summary
        audit_part = (
            "audit skipped" if args.no_audit
            else f"{len(audit_failures)} audit failure(s)"
        )
        cost_part = (
            "cost skipped" if args.no_cost
            else f"{len(cost_errors)} cost error(s)"
        )
        shard_part = (
            "shardcheck skipped" if args.no_shardcheck
            else f"{len(shard_errors)} shardcheck error(s)"
        )
        pallas_part = (
            "pallascheck skipped" if args.no_pallascheck
            else f"{len(pallas_errors)} pallascheck error(s)"
        )
        proto_part = (
            "protocheck skipped" if args.no_protocheck
            else f"{len(proto_errors)} protocheck error(s)"
        )
        hbm_part = (
            "hbmcheck skipped" if args.no_hbmcheck
            else f"{len(hbm_errors)} hbmcheck error(s)"
        )
        print(
            f"jaxlint: {len(errors)} error(s), {n_warn} warning(s), "
            f"{audit_part}, {cost_part}, {shard_part}, {pallas_part}, "
            f"{proto_part}, {hbm_part}, "
            f"{pragmas} pragma suppression(s) (budget {PRAGMA_BUDGET})"
        )
        if over_budget:
            print(
                f"jaxlint: pragma budget exceeded ({pragmas} > "
                f"{PRAGMA_BUDGET}) — fix the code instead of suppressing"
            )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
