"""`python -m tpu_pbrt.analysis` — run the jaxlint suite.

Layer 1 (AST lint) always runs; layer 2 (jaxpr/compile audit) runs unless
--no-audit (it compiles small render programs, a few seconds on CPU).
Exit code 0 iff no error-severity findings.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpu_pbrt.analysis")
    ap.add_argument(
        "paths", nargs="*", help="files to lint (default: all of tpu_pbrt/)"
    )
    ap.add_argument(
        "--no-audit", action="store_true",
        help="skip the jaxpr/compile-time audit layer",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    from tpu_pbrt.analysis.lint import PRAGMA_BUDGET, lint_tree

    repo_root = Path(__file__).resolve().parents[2]
    paths = [Path(p).resolve() for p in args.paths] or None
    violations, pragmas = lint_tree(repo_root, paths)
    over_budget = paths is None and pragmas > PRAGMA_BUDGET

    audit_failures = []
    if not args.no_audit:
        # CPU audit runs compile tiny programs; the unoptimized XLA
        # pipeline + the repo compilation cache keep this to seconds.
        # Must happen before jax initializes a backend.
        import os

        # only when the operator EXPLICITLY selected cpu (tools/ci.sh
        # does): unset JAX_PLATFORMS on a TPU VM means a TPU backend,
        # which must not inherit the unoptimized-CPU pipeline flag
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_backend_optimization_level" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_backend_optimization_level=0"
                ).strip()
        import jax

        cache = repo_root / ".jax_cache"
        if cache.is_dir():
            jax.config.update("jax_compilation_cache_dir", str(cache))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )

        from tpu_pbrt.analysis.audit import run_audit

        audit_failures = run_audit()

    errors = [v for v in violations if v.severity == "error"]
    ok = not errors and not audit_failures and not over_budget
    if args.format == "json":
        print(
            json.dumps(
                {
                    "lint": [v.__dict__ for v in violations],
                    "audit": audit_failures,
                    "pragmas": pragmas,
                    "pragma_budget": PRAGMA_BUDGET,
                    "ok": ok,
                }
            )
        )
    else:
        for v in violations:
            print(v)
        for f in audit_failures:
            print(f"AUDIT: {f}")
        n_warn = len(violations) - len(errors)
        print(
            f"jaxlint: {len(errors)} error(s), {n_warn} warning(s), "
            f"{len(audit_failures)} audit failure(s), "
            f"{pragmas} pragma suppression(s) (budget {PRAGMA_BUDGET})"
        )
        if over_budget:
            print(
                f"jaxlint: pragma budget exceeded ({pragmas} > "
                f"{PRAGMA_BUDGET}) — fix the code instead of suppressing"
            )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
