"""Analysis layer 6: protocheck — serve/dispatch protocol verification.

Layers 1-5 (jaxlint, jaxpr audit, cost model, shardcheck, pallascheck)
verify the COMPILED side of the renderer: traced programs, budgets,
sharding, kernel grids. This layer verifies the HOST side — the
serve/dispatch protocol itself: the state machine formed by
``serve/service.py`` (job lifecycle + recovery ladder),
``serve/queue.py`` (WFQ policy), and ``integrators/common.py``'s
``DispatchWindow`` (pipelined in-flight slices + deferred checkpoint
writes). Four seeded bugs anchor it, each a named mutant in the
regression corpus (``MUTATION_CASES``):

- **PR-13 clock double-sample wedge** — ``step()`` sampled the wall
  clock once for the runnable filter and again for the backoff-wait
  computation; a ``not_before`` deadline falling between the samples was
  excluded from BOTH, so ``step()`` answered None with work still
  pending. SV-CLOCK codifies the fix; the ``clock-double-sample``
  mutant reproduces the wedge deterministically under a VirtualClock.
- **PR-6 WFQ banked credit** — an idle tenant kept its stale low vtime
  and re-entered monopolizing the mesh. ``reenter()``'s busy clamp is
  the fix; the ``wfq-banked-credit`` mutant removes it and the
  PROTO-VTIME invariant catches the regression at the submit boundary.
- **superseded-deferred-write replay** — a cadence checkpoint deferred
  into the dispatch window must land exactly once or be provably
  superseded (a park/finalize write at the same path with a newer
  cursor); replaying it after the park regresses the durable cursor.
  PROTO-DEFER watches ``parallel/checkpoint``'s write-observer seam;
  the ``defer-replay-after-park`` mutant replays a captured deferred
  write and is flagged by cursor regression.
- **park-path HBM leak (ISSUE 18)** — a park that writes the durable
  emergency checkpoint but skips the film release strands one
  film-state carry in HBM per preemption. PROTO-HBM evaluates
  hbmcheck's (layer 7) memory model on the live service after every
  decision: the watermark must stay under the scenario's static worst
  case, parked/terminal jobs must hold no device buffers, and the
  model must return to baseline at drain. The
  ``park-skips-film-release`` mutant reintroduces the leak.

Two halves:

1. **SV static lint** (``sv_lint_source`` / ``sv_lint_tree``) — AST
   rules over the protocol modules, wired into
   ``python -m tpu_pbrt.analysis`` like every other layer (same
   ``Violation`` dataclass, same ``# jaxlint: disable=`` pragma
   grammar):

   - SV-CLOCK: direct wall-clock calls in clock-scoped modules (the
     injected ``Clock`` seam is the only sanctioned time source), and
     — in ``serve/service.py`` — any step-scoped function that reasons
     about runnability/backoff deadlines yet samples the decision
     clock more than once.
   - SV-DEFER: a ``window.defer(...)`` call without its retirement
     cursor binding, or a durable checkpoint write in the same
     function as a non-discarding window flush (the double-write
     shape the replay mutant exploits).
   - SV-VTIME: a write to ``TenantShare.vtime`` anywhere outside
     ``FairScheduler._set_vtime`` (a fair-share policy bypass).

2. **Protocol model** (``ProtocolModel``) — the REAL ``RenderService``
   run against stub chunk dispatches under a ``VirtualClock``
   (``utils/clock.py``), so a whole service run (submit / step /
   preempt / resume / cancel, window launch / retire / defer, backoff
   deadlines, CHAOS fault firings) is a pure deterministic function of
   an explicit decision sequence. ``tools/explore.py`` enumerates
   decision sequences over this model (bounded DPOR-style search) and
   checks the PROTO-* invariants after every decision. Nothing here
   touches the compiled programs: with the explorer unarmed the
   service, the recorders and every analysis budget are byte-identical
   to the pre-layer-6 tree (the seam defaults to the wall clock).
"""

from __future__ import annotations

import ast
import os
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tpu_pbrt.analysis.lint import Violation, _PRAGMA_FILE_RE, _PRAGMA_RE

# --------------------------------------------------------------------------
# SV rules (static half)
# --------------------------------------------------------------------------

SV_RULES: Dict[str, str] = {
    "SV-PARSE": "protocol module does not parse",
    "SV-CLOCK": (
        "wall clock sampled outside the injected Clock seam, or a "
        "deadline-scoped function sampling the decision clock twice"
    ),
    "SV-DEFER": (
        "deferred checkpoint write created without a retirement cursor "
        "binding, or combined with a non-discarding window flush"
    ),
    "SV-VTIME": (
        "tenant vtime written outside FairScheduler._set_vtime"
    ),
}

#: modules where ANY direct `time.*` call is a policy bypass — the
#: service and the queue policy must consume only the injected clock
#: (queue.py consumes none at all: `pick` is clock-free by contract)
_CLOCK_SCOPED = (
    "tpu_pbrt/serve/service.py",
    "tpu_pbrt/serve/queue.py",
    "tpu_pbrt/serve/residency.py",
    "tpu_pbrt/fleet/router.py",
)
#: (module, class) pairs clock-scoped at class granularity — the rest
#: of the module legitimately times host work with the stdlib
_CLOCK_SCOPED_CLASSES = (
    ("tpu_pbrt/integrators/common.py", "DispatchWindow"),
)
#: modules where `.defer(` means DispatchWindow.defer
_DEFER_SCOPED = (
    "tpu_pbrt/serve/service.py",
    "tpu_pbrt/serve/__main__.py",
    "tpu_pbrt/integrators/common.py",
)
_TIME_ATTRS = frozenset(
    ("time", "monotonic", "perf_counter", "sleep", "time_ns",
     "monotonic_ns", "perf_counter_ns")
)
#: attribute names that count as a DECISION sample of the clock
_SAMPLE_ATTRS = frozenset(("_now", "now"))


def _pragma_lines(src: str) -> Tuple[Dict[int, set], set]:
    """(lineno -> disabled rules, file-level disabled rules) — the same
    `# jaxlint: disable=` grammar layer 1 uses, so one suppression
    idiom covers every analysis layer."""
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, line in enumerate(src.splitlines(), 1):
        m = _PRAGMA_FILE_RE.search(line)
        if m:
            file_wide |= {r.strip() for r in m.group(1).split(",")}
        m = _PRAGMA_RE.search(line)
        if m:
            per_line.setdefault(i, set()).update(
                r.strip() for r in m.group(1).split(",")
            )
    return per_line, file_wide


def _shallow_walk(node: ast.AST):
    """Yield `node`'s body nodes without descending into nested
    function/lambda scopes — SV-CLOCK's one-sample-per-scope contract
    is per function, and a deferred `write()` closure is its own
    scope."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _is_time_call(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "time"
        and node.func.attr in _TIME_ATTRS
    ):
        return node.func.attr
    return None


class _SvVisitor(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.out: List[Violation] = []
        self.class_stack: List[str] = []
        self.fn_stack: List[ast.FunctionDef] = []

    # -- helpers -----------------------------------------------------------
    def _emit(self, rule: str, line: int, msg: str) -> None:
        self.out.append(Violation(rule, self.rel, line, msg, "error"))

    def _in_clock_scope(self) -> bool:
        if self.rel in _CLOCK_SCOPED:
            return True
        for mod, cls in _CLOCK_SCOPED_CLASSES:
            if self.rel == mod and cls in self.class_stack:
                return True
        return False

    # -- structure ---------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.fn_stack.append(node)
        if self.rel == "tpu_pbrt/serve/service.py":
            self._check_double_sample(node)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_double_sample(self, fn: ast.FunctionDef) -> None:
        """SV-CLOCK's second aspect: a function that reasons about
        runnability or backoff deadlines (references `not_before` or
        calls `_runnable`) must sample the decision clock at most once
        and thread that value through — the PR-13 wedge was exactly a
        second sample racing a deadline between the two."""
        deadline_scoped = False
        samples: List[int] = []
        for n in _shallow_walk(fn):
            if isinstance(n, ast.Attribute) and n.attr == "not_before":
                deadline_scoped = True
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                if n.func.attr == "_runnable":
                    deadline_scoped = True
                if n.func.attr in _SAMPLE_ATTRS:
                    samples.append(n.lineno)
            if _is_time_call(n):
                samples.append(n.lineno)
        if deadline_scoped and len(samples) > 1:
            self._emit(
                "SV-CLOCK", sorted(samples)[1],
                f"{fn.name}() reasons about backoff deadlines but samples "
                f"the decision clock {len(samples)} times (lines "
                f"{sorted(samples)}); sample once and thread the value",
            )

    # -- leaf rules ---------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        attr = _is_time_call(node)
        if attr is not None and self._in_clock_scope():
            self._emit(
                "SV-CLOCK", node.lineno,
                f"direct wall-clock call time.{attr}() in a clock-scoped "
                "module; route through the injected Clock (utils/clock.py)",
            )
        if (
            self.rel in _DEFER_SCOPED
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "defer"
        ):
            kw = {k.arg for k in node.keywords}
            if len(node.args) < 2 and not ({"cursor", "fn"} <= kw):
                self._emit(
                    "SV-DEFER", node.lineno,
                    "defer() without a retirement cursor binding — a "
                    "deferred write must be tied to the slice whose "
                    "retirement runs it",
                )
        self.generic_visit(node)

    def _check_vtime_target(self, target: ast.AST, line: int) -> None:
        if not (isinstance(target, ast.Attribute) and target.attr == "vtime"):
            return
        sanctioned = (
            self.rel == "tpu_pbrt/serve/queue.py"
            and "FairScheduler" in self.class_stack
            and bool(self.fn_stack)
            and self.fn_stack[-1].name == "_set_vtime"
        )
        if not sanctioned:
            self._emit(
                "SV-VTIME", line,
                "vtime written outside FairScheduler._set_vtime — the "
                "fair-share invariants live in its three sanctioned "
                "callers; use the policy API",
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_vtime_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_vtime_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_vtime_target(node.target, node.lineno)
        self.generic_visit(node)


def _check_flush_after_write(tree: ast.Module, rel: str) -> List[Violation]:
    """SV-DEFER's second aspect (service.py only): a function that both
    writes a durable checkpoint and drains (rather than discards) a
    dispatch window can replay a superseded deferred write — the exact
    regression the `defer-replay-after-park` mutant seeds."""
    if rel != "tpu_pbrt/serve/service.py":
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        saves: List[int] = []
        drains: List[int] = []
        for n in _shallow_walk(node):
            if not isinstance(n, ast.Call):
                continue
            fname = (
                n.func.attr if isinstance(n.func, ast.Attribute)
                else n.func.id if isinstance(n.func, ast.Name) else ""
            )
            if fname == "save_checkpoint":
                saves.append(n.lineno)
            if fname in ("flush", "drain"):
                discard = next(
                    (k.value for k in n.keywords if k.arg == "discard"),
                    None,
                )
                if fname == "drain" or not (
                    isinstance(discard, ast.Constant)
                    and discard.value is True
                ):
                    drains.append(n.lineno)
        if saves and drains:
            out.append(Violation(
                "SV-DEFER", rel, drains[0],
                f"{node.name}() both writes a checkpoint (line {saves[0]}) "
                "and drains a dispatch window without discard=True — the "
                "drained deferred writes would replay a superseded cursor",
                "error",
            ))
    return out


def sv_lint_source(src: str, rel: str) -> List[Violation]:
    """Run the SV rules over one module's source. `rel` is the
    repo-relative posix path (the scoping key)."""
    per_line, file_wide = _pragma_lines(src)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Violation(
            "SV-PARSE", rel, e.lineno or 0, f"does not parse: {e.msg}",
            "error",
        )]
    visitor = _SvVisitor(rel)
    visitor.visit(tree)
    found = visitor.out + _check_flush_after_write(tree, rel)
    # def-line pragmas cover their function body (the per-function
    # SV-CLOCK aspect reports at the offending sample, which may be far
    # from where the waiver is naturally written)
    def_spans: List[Tuple[int, int, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            def_spans.append(
                (node.lineno, getattr(node, "end_lineno", node.lineno),
                 node.lineno)
            )
    out: List[Violation] = []
    for v in found:
        if v.rule in file_wide:
            continue
        if v.rule in per_line.get(v.line, ()):
            continue
        covered = any(
            v.rule in per_line.get(dl, ())
            for lo, hi, dl in def_spans
            if lo <= v.line <= hi
        )
        if not covered:
            out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def sv_lint_file(path: str, rel: str) -> List[Violation]:
    with open(path, encoding="utf-8") as f:
        return sv_lint_source(f.read(), rel)


def sv_lint_tree(root: Optional[str] = None) -> List[Violation]:
    """Lint the whole `tpu_pbrt` package under `root` (default: the
    installed tree this module came from). SV-VTIME is global — a
    policy bypass can hide anywhere — while the clock/defer scopes are
    keyed by the repo-relative path."""
    if root is None:
        root = repo_root()
    pkg = os.path.join(root, "tpu_pbrt")
    out: List[Violation] = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            out.extend(sv_lint_file(path, rel))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def repo_root() -> str:
    """The checkout root (tpu_pbrt/analysis/protocheck.py -> up 3)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


# --------------------------------------------------------------------------
# Stub harness (dynamic half) — real service, stub chunk dispatches
# --------------------------------------------------------------------------

#: every stub chunk reports exactly this many rays — the counter-
#: reconciliation invariant (PROTO-COUNT) is then n_chunks * this
RAYS_PER_CHUNK = 64

_HARNESS: Optional[Dict[str, Any]] = None


def _harness() -> Dict[str, Any]:
    """Build (once) the stub scene/plan/integrator classes. Lazy and
    cached: importing protocheck for the SV lint must not import jax —
    the analysis runner's `need_jax` gating decides when the dynamic
    half may load."""
    global _HARNESS
    if _HARNESS is not None:
        return _HARNESS
    import zlib

    import numpy as np

    from tpu_pbrt.core.film import FilmState
    from tpu_pbrt.integrators.common import WavefrontIntegrator

    class StubFilm:
        """2x2 film with the real FilmState layout; develop() mirrors
        the radiance/weight normalization shape deterministically."""

        full_resolution = (2, 2)

        def init_state(self):
            return FilmState(
                rgb=np.zeros((2, 2, 3), np.float32),
                weight=np.zeros((2, 2), np.float32),
                splat=np.zeros((2, 2, 3), np.float32),
            )

        def develop(self, state, splat_scale: float = 1.0):
            w = np.maximum(np.asarray(state.weight), 1e-9)[..., None]
            return np.asarray(state.rgb) / w + np.asarray(
                state.splat
            ) * np.float32(splat_scale)

    class StubScene:
        def __init__(self):
            self.dev: Dict[str, Any] = {}  # no HBM-resident tables
            self.film = StubFilm()

    def _contrib(c: int) -> Any:
        # distinct deterministic per-chunk deposit: accumulation-order
        # bugs change the film bit pattern even on a 2x2 stub
        val = (zlib.crc32(f"chunk:{c}".encode()) % 1021) / 1021.0
        return np.full((2, 2, 3), np.float32(val), np.float32)

    class StubPlan:
        """Duck-typed ChunkPlan: every field/method the service touches,
        with dispatch() a pure numpy accumulate — idempotent, instant,
        and bit-deterministic, so film identity across interleavings is
        checkable exactly."""

        def __init__(self, n_chunks: int, depth: int):
            self.n_chunks = int(n_chunks)
            self.pipeline_depth = max(1, int(depth))
            self.spp = 1
            self.film = StubFilm()
            self.fingerprint = f"stub:n{n_chunks}:d{depth}"
            self.tracer = "stub"
            self.use_regen = False
            self.pool = 1

        def capacity_audit(self) -> None:
            pass

        def dispatch(self, state, c: int):
            state2 = FilmState(
                rgb=state.rgb + _contrib(c),
                weight=state.weight + np.float32(1.0),
                splat=state.splat,
            )
            return state2, np.int64(RAYS_PER_CHUNK)

        def aux_parts(self, aux):
            return (aux, None, None, None, None)

    class StubIntegrator(WavefrontIntegrator):
        """Subclasses the real base WITHOUT overriding render() — the
        submit-time chunked-loop check must accept it via the real
        entry point — and with its own tiny ctor (no scene plumbing)."""

        def __init__(self, n_chunks: int, depth: int):  # noqa: D107
            self.n_chunks = int(n_chunks)
            self.depth = int(depth)
            self.name = "stub"

        def prepare_chunks(self, scene=None, mesh=None, chunk=None):
            return StubPlan(self.n_chunks, self.depth)

    def reference_state(n_chunks: int):
        """The sequential-schedule film: chunks 0..n-1 accumulated in
        cursor order — the bit-identity baseline PROTO-FILM compares
        every explored interleaving's terminal film against."""
        plan = StubPlan(n_chunks, 1)
        state = plan.film.init_state()
        for c in range(n_chunks):
            state, _ = plan.dispatch(state, c)
        return state

    _HARNESS = {
        "StubFilm": StubFilm,
        "StubScene": StubScene,
        "StubPlan": StubPlan,
        "StubIntegrator": StubIntegrator,
        "reference_state": reference_state,
    }
    return _HARNESS


# --------------------------------------------------------------------------
# Scenarios
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class JobSpec:
    """One job the model may submit."""

    name: str
    tenant: str = "default"
    priority: int = 0
    n_chunks: int = 3
    checkpoint_every: int = 0
    depth: int = 1
    #: scene-affinity routing key for fleet scenarios (defaults to the
    #: job name; two jobs sharing a scene MUST co-locate while their
    #: replica stays healthy — PROTO-ROUTE-AFFINITY)
    scene: str = ""


@dataclass(frozen=True)
class Scenario:
    """A bounded exploration universe: the jobs available to submit,
    the CHAOS fault plan, and which decision kinds the explorer may
    enumerate."""

    name: str
    jobs: Tuple[JobSpec, ...]
    fault: str = ""
    allow: Tuple[str, ...] = ("submit", "step", "advance")
    #: >1 selects the fleet model (a FleetRouter over N LocalReplicas
    #: under one VirtualClock) with the router decision kinds
    #: ("rstep", k) / ("kill", k) / ("drain", k) in the grid
    replicas: int = 1


def smoke_scenarios(n_fault_chunks: int = 2) -> List[Scenario]:
    """The CI exploration grid: two-tenant interleavings at pipeline
    depths 1-3 (arrival orders x retirement orders x preempt/resume
    timings), crossed with every fault plan in the CHAOS protocol
    fault space on a single-job scenario (fault placements x
    recovery-ladder arms)."""
    from tpu_pbrt.chaos import protocol_fault_space

    out: List[Scenario] = []
    for depth in (1, 2, 3):
        out.append(Scenario(
            name=f"duo-d{depth}",
            jobs=(
                JobSpec("a1", tenant="a", n_chunks=3,
                        checkpoint_every=2, depth=depth),
                JobSpec("b1", tenant="b", n_chunks=2,
                        checkpoint_every=2, depth=depth),
            ),
            allow=("submit", "step", "advance", "preempt", "resume"),
        ))
    for i, fault in enumerate(protocol_fault_space(n_fault_chunks)):
        out.append(Scenario(
            name=f"fault-{i}:{fault or 'clean'}",
            jobs=(JobSpec("f1", n_chunks=3, checkpoint_every=2, depth=2),),
            fault=fault,
            allow=("submit", "step", "advance"),
        ))
    # the ISSUE-20 router grid: route / re-route / resume-elsewhere /
    # double-delivery, explored over 2 replicas under one VirtualClock
    out.append(Scenario(
        name="fleet-affine",
        jobs=(
            JobSpec("fa1", scene="sS", n_chunks=2, checkpoint_every=1),
            JobSpec("fa2", scene="sS", n_chunks=2, checkpoint_every=1),
        ),
        allow=("submit", "rstep", "advance"),
        replicas=2,
    ))
    out.append(Scenario(
        name="fleet-kill",
        jobs=(JobSpec("fk", scene="sK", n_chunks=3, checkpoint_every=1),),
        allow=("submit", "rstep", "advance", "kill"),
        replicas=2,
    ))
    out.append(Scenario(
        name="fleet-drain",
        jobs=(
            JobSpec("fd1", scene="sD", n_chunks=2, checkpoint_every=1),
            JobSpec("fd2", scene="sE", n_chunks=2, checkpoint_every=1),
        ),
        allow=("submit", "rstep", "advance", "drain"),
        replicas=2,
    ))
    return out


# --------------------------------------------------------------------------
# The protocol model
# --------------------------------------------------------------------------


class ProtocolModel:
    """The REAL RenderService under a VirtualClock, driven by explicit
    decisions, with the PROTO-* invariants checked after every one.

    Decisions (tuples):

    - ``("submit", i)`` — submit scenario job ``i``
    - ``("step",)``     — one scheduler step (dispatch / wait / idle)
    - ``("advance",)``  — move virtual time to just BEFORE the earliest
      open backoff deadline (epsilon/2 short: the adversarial placement
      that distinguishes one clock sample from two)
    - ``("preempt", name)`` / ``("resume", name)`` / ``("cancel", name)``

    Every decision appends one path-free line to ``self.log`` — the
    schedule-determinism artifact (same decision sequence => byte-
    identical log) — and any invariant breach appends
    ``(invariant, detail)`` to ``self.violations``.
    """

    EPS = 1e-6

    def __init__(self, scenario: Scenario, seed: int = 0):
        import tempfile

        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.trace import TRACE
        from tpu_pbrt.parallel import checkpoint as ckpt
        from tpu_pbrt.serve.service import RenderService
        from tpu_pbrt.utils.clock import VirtualClock

        self.scenario = scenario
        self.seed = int(seed)
        self.clock = VirtualClock(start=0.0, tick=self.EPS)
        self.tmpdir = tempfile.mkdtemp(prefix="protocheck_")
        self.svc = RenderService(
            seed=self.seed, spool_dir=self.tmpdir, clock=self.clock,
        )
        CHAOS.install(scenario.fault, self.seed)
        self._ckpt = ckpt
        self._watermark: Dict[str, int] = {}
        self.ckpt_writes = 0
        self.violations: List[Tuple[str, str]] = []
        self.log: List[str] = []
        self._unsubmitted = set(range(len(scenario.jobs)))
        self._done_checked: set = set()
        # PROTO-HBM (ISSUE 18): the layer-7 memory model evaluated on
        # the live service — peak watermark + cached static worst case
        self.hbm_peak = 0
        self._hbm_worst: Optional[int] = None
        self._obs = self._on_ckpt_write
        ckpt.register_write_observer(self._obs)
        # satellite: the recorders run on the SAME virtual timeline, so
        # flight heartbeats / trace spans emitted during exploration
        # carry monotone virtual timestamps (restored exactly in close)
        self._flight_prev = (FLIGHT._clock, FLIGHT._t0)
        FLIGHT.set_clock(self.clock)
        self._trace_prev = (TRACE._clock, TRACE._t0)
        TRACE.set_clock(self.clock)
        self.closed = False

    # -- observer ----------------------------------------------------------
    def _on_ckpt_write(self, path: str, cursor: int, rays: int) -> None:
        """Deferred-write linearity (PROTO-DEFER): the durable cursor at
        one path must be monotone — a clean publish below the watermark
        means a superseded deferred write replayed after a park or
        terminal supersession."""
        self.ckpt_writes += 1
        prev = self._watermark.get(path)
        if prev is not None and cursor < prev:
            self.violations.append((
                "PROTO-DEFER",
                f"superseded deferred write replayed: durable cursor "
                f"regressed {prev} -> {cursor} at the same checkpoint "
                f"path (write #{self.ckpt_writes})",
            ))
        self._watermark[path] = max(prev or 0, int(cursor))

    # -- decisions ---------------------------------------------------------
    def enabled_decisions(self) -> List[tuple]:
        """The legal decisions at the current state, in a deterministic
        order (the explorer's branching set)."""
        from tpu_pbrt.serve.service import PAUSED, _RUNNABLE, _TERMINAL

        allow = self.scenario.allow
        out: List[tuple] = []
        if "submit" in allow:
            out.extend(("submit", i) for i in sorted(self._unsubmitted))
        jobs = list(self.svc.jobs.values())
        live = [j for j in jobs if j.status not in _TERMINAL]
        if "step" in allow and any(j.status != PAUSED for j in live):
            out.append(("step",))
        if "advance" in allow:
            now = self.clock.peek()
            if any(
                j.status in _RUNNABLE and j.not_before > now for j in jobs
            ):
                out.append(("advance",))
        if "preempt" in allow:
            out.extend(
                ("preempt", j.job_id) for j in jobs
                if j.status in _RUNNABLE
            )
        if "resume" in allow:
            out.extend(
                ("resume", j.job_id) for j in jobs if j.status == PAUSED
            )
        if "cancel" in allow:
            out.extend(
                ("cancel", j.job_id) for j in jobs
                if j.status not in _TERMINAL
            )
        return out

    def apply(self, decision: tuple) -> str:
        """Apply one decision to the real service, then check every
        invariant and append the log line. Returns the outcome token."""
        from tpu_pbrt.serve.service import _RUNNABLE

        kind = decision[0]
        pre_nb = {j.job_id: j.not_before for j in self.svc.jobs.values()}
        pre_sched = len(self.svc.schedule)
        outcome = ""
        try:
            if kind == "submit":
                i = int(decision[1])
                spec = self.scenario.jobs[i]
                self._unsubmitted.discard(i)
                h = _harness()
                self.svc.submit(
                    compiled=(h["StubScene"](),
                              h["StubIntegrator"](spec.n_chunks, spec.depth)),
                    resident_key=f"stub:{spec.name}",
                    job_id=spec.name, tenant=spec.tenant,
                    priority=spec.priority,
                    checkpoint_every=spec.checkpoint_every,
                )
                outcome = f"submitted:{spec.name}"
            elif kind == "step":
                rid = self.svc.step()
                outcome = rid if rid is not None else "idle"
            elif kind == "advance":
                now = self.clock.peek()
                deadlines = [
                    j.not_before for j in self.svc.jobs.values()
                    if j.status in _RUNNABLE and j.not_before > now
                ]
                if deadlines:
                    target = min(deadlines) - self.EPS / 2
                    self.clock.advance_to(target)
                    outcome = f"advanced:{target:.6f}"
                else:
                    outcome = "noop"
            elif kind == "preempt":
                self.svc.preempt(decision[1])
                outcome = f"paused:{decision[1]}"
            elif kind == "resume":
                self.svc.resume(decision[1])
                outcome = f"resumed:{decision[1]}"
            elif kind == "cancel":
                self.svc.cancel(decision[1])
                outcome = f"cancelled:{decision[1]}"
            else:
                raise ValueError(f"unknown decision kind {kind!r}")
        except Exception as e:  # noqa: BLE001 — a crash IS a finding
            detail = str(e).replace(self.tmpdir, "<spool>")
            self.violations.append((
                "PROTO-CRASH",
                f"decision {decision} raised {type(e).__name__}: {detail}",
            ))
            outcome = f"crash:{type(e).__name__}"
        self._check_invariants(decision, kind, outcome, pre_nb, pre_sched)
        self._log_line(decision, outcome)
        return outcome

    def run(self, decisions) -> "ProtocolModel":
        for d in decisions:
            self.apply(tuple(d))
        return self

    # -- invariants ---------------------------------------------------------
    def _check_invariants(
        self, decision: tuple, kind: str, outcome: str,
        pre_nb: Dict[str, float], pre_sched: int,
    ) -> None:
        import numpy as np

        from tpu_pbrt.serve.service import DONE, _RUNNABLE, _TERMINAL

        svc = self.svc
        # PROTO-WEDGE: step answered idle while schedulable work exists
        # (the exact gap obs/health.py's watchdog flags as a wedge)
        if kind == "step" and outcome == "idle":
            stuck = svc._runnable(float("inf"))
            if stuck:
                gap = svc.health_steps - svc.last_progress_step
                self.violations.append((
                    "PROTO-WEDGE",
                    f"step() returned None with runnable work pending "
                    f"({[j.job_id for j in stuck]}); health watchdog gap "
                    f"{gap} step(s) with no cursor progress",
                ))
        # PROTO-VTIME: no banked credit at the submit boundary — the
        # submitter's tenant must sit at/above the busy tenants' floor
        if kind == "submit" and not outcome.startswith("crash"):
            spec = self.scenario.jobs[int(decision[1])]
            sch = svc.scheduler
            ts = sch._tenants.get(spec.tenant)
            floors = [
                sch._tenants[t].vtime
                for t in {
                    j.tenant for j in svc.jobs.values()
                    if j.status in _RUNNABLE and j.tenant != spec.tenant
                }
                if t in sch._tenants
            ]
            if floors:
                floor = min(floors)
                have = ts.vtime if ts is not None else None
                if have is None or have < floor - 1e-9:
                    self.violations.append((
                        "PROTO-VTIME",
                        f"tenant {spec.tenant!r} re-entered below the busy "
                        f"floor: vtime {have} < {floor:.6f} (banked "
                        f"credit — the PR-6 WFQ regression shape)",
                    ))
        # PROTO-PIN: residency pins balance the non-terminal holders
        pins = svc.residency.pin_counts()
        expected: Dict[str, int] = {}
        for j in svc.jobs.values():
            if j.status not in _TERMINAL:
                expected[j.resident_key] = expected.get(j.resident_key, 0) + 1
        for key in sorted(set(pins) | set(expected)):
            if pins.get(key, 0) != expected.get(key, 0):
                self.violations.append((
                    "PROTO-PIN",
                    f"residency pin imbalance for {key!r}: {pins.get(key, 0)}"
                    f" pin(s) vs {expected.get(key, 0)} live holder(s)",
                ))
        # PROTO-BACKOFF: deadlines are monotone per job, and nothing
        # dispatches from inside its pre-decision backoff window
        now = self.clock.peek()
        for j in svc.jobs.values():
            prev = pre_nb.get(j.job_id)
            if prev is not None and j.not_before < prev - 1e-12:
                self.violations.append((
                    "PROTO-BACKOFF",
                    f"job {j.job_id} backoff deadline moved backward: "
                    f"{prev:.6f} -> {j.not_before:.6f}",
                ))
        for job_id, _chunk in svc.schedule[pre_sched:]:
            nb = pre_nb.get(job_id, 0.0)
            if nb > now + 1e-9:
                self.violations.append((
                    "PROTO-BACKOFF",
                    f"job {job_id} dispatched at {now:.6f}, inside its "
                    f"backoff window (not_before {nb:.6f})",
                ))
        # PROTO-COUNT / PROTO-FILM at each terminal DONE
        for j in svc.jobs.values():
            if j.status != DONE or j.job_id in self._done_checked:
                continue
            self._done_checked.add(j.job_id)
            spec = next(
                s for s in self.scenario.jobs if s.name == j.job_id
            )
            res = j.result
            want = spec.n_chunks * RAYS_PER_CHUNK
            if res is None or int(res.rays_traced) != want:
                got = None if res is None else int(res.rays_traced)
                self.violations.append((
                    "PROTO-COUNT",
                    f"job {j.job_id} finished with rays_traced={got}, "
                    f"expected {want} ({spec.n_chunks} x {RAYS_PER_CHUNK}"
                    f") — lost or double-counted across the recovery "
                    f"ladder",
                ))
                continue
            ref = _harness()["reference_state"](spec.n_chunks)
            fs = res.film_state
            if not (
                np.array_equal(np.asarray(fs.rgb), np.asarray(ref.rgb))
                and np.array_equal(
                    np.asarray(fs.weight), np.asarray(ref.weight)
                )
            ):
                self.violations.append((
                    "PROTO-FILM",
                    f"job {j.job_id} terminal film differs bitwise from "
                    f"the sequential schedule's (interleaving or rollback "
                    f"changed the accumulation)",
                ))
        # PROTO-HBM (ISSUE 18): hbmcheck's static memory model,
        # cross-checked dynamically — the modeled watermark must stay
        # under the scenario's static worst case, parked/terminal jobs
        # must hold no device buffers, and the watermark must return to
        # baseline (resident scenes only) once the scenario drains
        from tpu_pbrt.serve.service import CANCELLED, FAILED, PARKED, PAUSED

        held, total = self._modeled_hbm()
        self.hbm_peak = max(self.hbm_peak, total)
        worst = self._static_worst_hbm()
        if total > worst:
            self.violations.append((
                "PROTO-HBM",
                f"modeled HBM watermark {total} B exceeds the static "
                f"worst case {worst} B after {decision!r} — the serve "
                f"stack holds more device memory than layer 7's model "
                f"admits",
            ))
        for j in svc.jobs.values():
            if (
                j.status in (PARKED, PAUSED, CANCELLED, FAILED)
                and j.state is not None
            ):
                self.violations.append((
                    "PROTO-HBM",
                    f"job {j.job_id} ({j.status}) retains its film carry "
                    f"— the park/terminal path must release HBM after "
                    f"the durable write lands",
                ))
            if j.status in _TERMINAL:
                n_ctr = (
                    len(j.ray_counts) + len(j.occ_counts)
                    + len(j.ctr_counts) + len(j.nf_counts)
                )
                if n_ctr or j.window is not None:
                    w = "live" if j.window is not None else "none"
                    self.violations.append((
                        "PROTO-HBM",
                        f"terminal job {j.job_id} ({j.status}) retains "
                        f"{n_ctr} per-slice counter buffer(s), window="
                        f"{w} — terminal paths must drop every device "
                        f"reference",
                    ))
        if (
            svc.jobs and not self._unsubmitted
            and all(j.status in _TERMINAL for j in svc.jobs.values())
            and held != 0
        ):
            self.violations.append((
                "PROTO-HBM",
                f"drained: every job terminal but the modeled job-held "
                f"HBM is {held} B, not 0 — the watermark did not return "
                f"to baseline (resident scenes only)",
            ))

    def _modeled_hbm(self) -> Tuple[int, int]:
        """(job-held bytes, total bytes) of the layer-7 memory model
        evaluated on the LIVE service: film carries (job.state), the
        un-donated in-flight window slices, and the per-slice counter
        scalars, plus resident scene bytes for the total. Terminal
        results (RenderResult.film_state) are intentional retention and
        excluded — the drain baseline is resident scenes only."""
        from tpu_pbrt.analysis.hbmcheck import film_state_bytes

        held = 0
        for j in self.svc.jobs.values():
            fb = 0
            if j.plan is not None:
                rx, ry = j.plan.film.full_resolution
                fb = film_state_bytes(rx, ry)
            if j.state is not None:
                held += fb
            if (
                j.window is not None
                and getattr(j.plan, "pipeline_depth", 1) > 1
            ):
                held += len(j.window) * fb
            held += 8 * (
                len(j.ray_counts) + len(j.occ_counts)
                + len(j.ctr_counts) + len(j.nf_counts)
            )
        return held, held + self.svc.residency.total_bytes()

    def _static_worst_hbm(self) -> int:
        """hbmcheck's static worst case specialized to this scenario —
        the bound PROTO-HBM holds the dynamic watermark to: per job,
        one stub resident scene + the live film carries of its depth +
        a full complement of per-slice counters."""
        if self._hbm_worst is None:
            from tpu_pbrt.analysis.hbmcheck import (
                COUNTER_BYTES_PER_SLICE, film_state_bytes,
            )
            from tpu_pbrt.integrators.common import live_film_carries

            fb = film_state_bytes(2, 2)  # the stub harness film
            total = 0
            for spec in self.scenario.jobs:
                total += fb  # scene_hbm_bytes of a StubScene (dev={})
                total += live_film_carries(spec.depth) * fb
                total += spec.n_chunks * COUNTER_BYTES_PER_SLICE
            self._hbm_worst = total
        return self._hbm_worst

    # -- artifacts ----------------------------------------------------------
    def _log_line(self, decision: tuple, outcome: str) -> None:
        svc = self.svc
        jobs = " ".join(
            f"{j.job_id}:{j.status}:c{j.cursor}:a{j.attempt}"
            f":nb{j.not_before:.6f}"
            for j in sorted(svc.jobs.values(), key=lambda j: j.job_id)
        )
        vt = ",".join(
            f"{t}={ts.vtime:.6f}"
            for t, ts in sorted(svc.scheduler._tenants.items())
        )
        self.log.append(
            f"{len(self.log):03d} {decision!r} -> {outcome} "
            f"@{self.clock.peek():.6f} | {jobs} | vt[{vt}] | "
            f"sched={len(svc.schedule)} ckpt={self.ckpt_writes}"
        )

    def fingerprint(self) -> tuple:
        """Abstract-state key for the explorer's visited-set pruning:
        everything scheduling-relevant, with deadlines made RELATIVE to
        the virtual clock (two states differing only by a time
        translation behave identically)."""
        now = self.clock.peek()
        jobs = tuple(
            (
                j.job_id, j.status, j.cursor, j.attempt, j.state is None,
                round(max(j.not_before - now, 0.0), 9),
                (len(j.window) if j.window is not None else -1),
                (tuple(c for c, _ in j.window.deferred)
                 if j.window is not None else ()),
                self._ckpt.checkpoint_exists(j.checkpoint_path),
            )
            for j in sorted(
                self.svc.jobs.values(), key=lambda j: j.job_id
            )
        )
        vt = tuple(
            (t, round(ts.vtime, 9))
            for t, ts in sorted(self.svc.scheduler._tenants.items())
        )
        return (jobs, vt, tuple(sorted(self._unsubmitted)))

    def close(self) -> None:
        """Restore every process-global the model armed (CHAOS plan,
        checkpoint write observer, recorder clocks) and drop the spool.
        Idempotent."""
        if self.closed:
            return
        self.closed = True
        import shutil

        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.trace import TRACE

        CHAOS.clear()
        self._ckpt.unregister_write_observer(self._obs)
        FLIGHT._clock, FLIGHT._t0 = self._flight_prev
        TRACE._clock, TRACE._t0 = self._trace_prev
        shutil.rmtree(self.tmpdir, ignore_errors=True)

    def __enter__(self) -> "ProtocolModel":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------------
# The fleet model (ISSUE 20): the router/replica handoff protocol
# --------------------------------------------------------------------------


class FleetModel:
    """N real RenderServices behind a real FleetRouter, one shared
    VirtualClock, driven by explicit decisions — the handoff protocol
    (route / re-route / resume-elsewhere / double-delivery) as a pure
    function of the decision sequence, with the PROTO-ROUTE-*
    invariants checked after every one.

    Decisions (tuples; same explorer contract as ProtocolModel):

    - ``("submit", i)``  — submit scenario job ``i`` THROUGH the router
    - ``("rstep", k)``   — one scheduler step on replica ``k``
    - ``("advance",)``   — virtual time to just before the earliest
      open backoff deadline across all alive replicas
    - ``("kill", k)``    — abrupt replica death + spool failover
    - ``("drain", k)``   — graceful drain + spool failover

    Invariants:

    - PROTO-ROUTE-AFFINITY — a submit of a seen scene key routes to
      the same replica while that replica stays healthy
    - PROTO-ROUTE-DUP — no job id has two live instances on alive
      replicas, and no job is DONE on more than one replica (the
      double-render guard the failover-skips-spool-consume mutant
      seeds a regression for)
    - PROTO-ROUTE-LOST — every admitted non-terminal job has exactly
      one live instance somewhere alive; every DONE record a DONE
      instance
    - PROTO-ROUTE-PIN — residency pins balance live holders on every
      alive replica (ProtocolModel's PROTO-PIN, per replica)
    - PROTO-ROUTE-FILM — every DONE film is bit-identical to the
      sequential single-replica schedule's, rays exactly
      ``n_chunks x RAYS_PER_CHUNK`` (failover resumes from the durable
      cursor, never re-accumulates)

    PROTO-DEFER rides along via the checkpoint write observer: the
    durable cursor at one router-owned spool path must stay monotone
    ACROSS replicas — a failover that re-renders retired chunks would
    regress it.
    """

    EPS = 1e-6

    def __init__(self, scenario: Scenario, seed: int = 0):
        import tempfile

        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.fleet.router import FleetRouter, LocalReplica
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.trace import TRACE
        from tpu_pbrt.parallel import checkpoint as ckpt
        from tpu_pbrt.utils.clock import VirtualClock

        self.scenario = scenario
        self.seed = int(seed)
        self.clock = VirtualClock(start=0.0, tick=self.EPS)
        self.tmpdir = tempfile.mkdtemp(prefix="protocheck_fleet_")
        self._rids = [f"r{k}" for k in range(int(scenario.replicas))]
        replicas = [
            LocalReplica(
                rid, clock=self.clock, seed=self.seed,
                spool_dir=os.path.join(self.tmpdir, rid),
            )
            for rid in self._rids
        ]
        self.router = FleetRouter(
            replicas, clock=self.clock,
            spool_dir=os.path.join(self.tmpdir, "fleet"),
        )
        CHAOS.install(scenario.fault, self.seed)
        self._ckpt = ckpt
        self._watermark: Dict[str, int] = {}
        self.ckpt_writes = 0
        self.violations: List[Tuple[str, str]] = []
        self.log: List[str] = []
        self._unsubmitted = set(range(len(scenario.jobs)))
        self._done_checked: set = set()
        #: the model's own affinity expectation: scene key -> the
        #: replica the router last placed it on
        self._affinity: Dict[str, str] = {}
        self._obs = self._on_ckpt_write
        ckpt.register_write_observer(self._obs)
        self._flight_prev = (FLIGHT._clock, FLIGHT._t0)
        FLIGHT.set_clock(self.clock)
        self._trace_prev = (TRACE._clock, TRACE._t0)
        TRACE.set_clock(self.clock)
        self.closed = False

    def _on_ckpt_write(self, path: str, cursor: int, rays: int) -> None:
        """PROTO-DEFER across the fleet: one durable path, one monotone
        cursor — no matter WHICH replica writes it."""
        self.ckpt_writes += 1
        prev = self._watermark.get(path)
        if prev is not None and cursor < prev:
            self.violations.append((
                "PROTO-DEFER",
                f"durable cursor regressed {prev} -> {cursor} at one "
                f"spool path across the fleet (write #{self.ckpt_writes})"
                f" — a failover re-rendered already-durable chunks",
            ))
        self._watermark[path] = max(prev or 0, int(cursor))

    # -- decisions ---------------------------------------------------------
    def _key(self, spec: JobSpec) -> str:
        return f"stub:{spec.scene or spec.name}"

    def enabled_decisions(self) -> List[tuple]:
        from tpu_pbrt.serve.service import PAUSED, _RUNNABLE, _TERMINAL

        allow = self.scenario.allow
        healthy = self.router.healthy()
        out: List[tuple] = []
        if "submit" in allow and healthy:
            out.extend(("submit", i) for i in sorted(self._unsubmitted))
        now = self.clock.peek()
        any_backoff = False
        for k, rid in enumerate(self._rids):
            r = self.router.replicas[rid]
            if not r.alive:
                continue
            jobs = list(r.service.jobs.values())
            live = [j for j in jobs if j.status not in _TERMINAL]
            if "rstep" in allow and any(j.status != PAUSED for j in live):
                out.append(("rstep", k))
            any_backoff = any_backoff or any(
                j.status in _RUNNABLE and j.not_before > now for j in jobs
            )
        if "advance" in allow and any_backoff:
            out.append(("advance",))
        # eviction decisions keep at least one healthy survivor — a
        # fleet with nowhere left to route is outside the protocol
        for k, rid in enumerate(self._rids):
            r = self.router.replicas[rid]
            survivors = [h for h in healthy if h != rid]
            if "kill" in allow and r.alive and survivors:
                out.append(("kill", k))
            if "drain" in allow and r.alive and not r.draining and survivors:
                out.append(("drain", k))
        return out

    def apply(self, decision: tuple) -> str:
        from tpu_pbrt.serve.service import _RUNNABLE

        kind = decision[0]
        outcome = ""
        try:
            if kind == "submit":
                i = int(decision[1])
                spec = self.scenario.jobs[i]
                self._unsubmitted.discard(i)
                h = _harness()
                key = self._key(spec)
                expected = self._affinity.get(key)
                healthy_before = set(self.router.healthy())
                self.router.submit(
                    compiled=(h["StubScene"](),
                              h["StubIntegrator"](spec.n_chunks, spec.depth)),
                    resident_key=key, job_id=spec.name,
                    tenant=spec.tenant, priority=spec.priority,
                    checkpoint_every=spec.checkpoint_every,
                )
                rid = self.router.jobs[spec.name].rid
                if (
                    expected is not None
                    and expected in healthy_before
                    and rid != expected
                ):
                    self.violations.append((
                        "PROTO-ROUTE-AFFINITY",
                        f"scene key {key!r} routed to {rid}, but its "
                        f"compiled scene is resident on the still-"
                        f"healthy {expected} — the warm path lost",
                    ))
                self._affinity[key] = rid
                outcome = f"submitted:{spec.name}@{rid}"
            elif kind == "rstep":
                rid = self._rids[int(decision[1])]
                job = self.router.step_replica(rid)
                outcome = f"{rid}/{job}" if job is not None else f"{rid}/idle"
            elif kind == "advance":
                now = self.clock.peek()
                deadlines = [
                    j.not_before
                    for rid in self._rids
                    if self.router.replicas[rid].alive
                    for j in self.router.replicas[rid].service.jobs.values()
                    if j.status in _RUNNABLE and j.not_before > now
                ]
                if deadlines:
                    target = min(deadlines) - self.EPS / 2
                    self.clock.advance_to(target)
                    outcome = f"advanced:{target:.6f}"
                else:
                    outcome = "noop"
            elif kind in ("kill", "drain"):
                rid = self._rids[int(decision[1])]
                if kind == "kill":
                    moved = self.router.kill_replica(rid)
                else:
                    moved = self.router.drain_replica(rid)
                for job_id in moved:
                    rec = self.router.jobs[job_id]
                    self._affinity[rec.key] = rec.rid
                outcome = f"{kind}ed:{rid}+moved:{','.join(moved) or '-'}"
            else:
                raise ValueError(f"unknown decision kind {kind!r}")
        except Exception as e:  # noqa: BLE001 — a crash IS a finding
            detail = str(e).replace(self.tmpdir, "<spool>")
            self.violations.append((
                "PROTO-CRASH",
                f"decision {decision} raised {type(e).__name__}: {detail}",
            ))
            outcome = f"crash:{type(e).__name__}"
        self._check_invariants(decision)
        self._log_line(decision, outcome)
        return outcome

    def run(self, decisions) -> "FleetModel":
        for d in decisions:
            self.apply(tuple(d))
        return self

    # -- invariants ---------------------------------------------------------
    def _check_invariants(self, decision: tuple) -> None:
        import numpy as np

        from tpu_pbrt.serve.service import DONE, _TERMINAL

        router = self.router
        # instance census per admitted job: DUP / LOST
        for job_id, rec in router.jobs.items():
            live_on: List[str] = []
            done_on: List[str] = []
            for rid in self._rids:
                r = router.replicas[rid]
                j = r.service.jobs.get(job_id)
                if j is None:
                    continue
                if j.status == DONE:
                    done_on.append(rid)
                if r.alive and j.status not in _TERMINAL:
                    live_on.append(rid)
            if len(live_on) > 1:
                self.violations.append((
                    "PROTO-ROUTE-DUP",
                    f"job {job_id} is live on {live_on} simultaneously "
                    f"after {decision!r} — a failover delivered the job "
                    f"without consuming the previous instance",
                ))
            if len(done_on) > 1:
                self.violations.append((
                    "PROTO-ROUTE-DUP",
                    f"job {job_id} rendered to DONE on {done_on} — the "
                    f"same request paid for twice",
                ))
            if not rec.terminal and not live_on:
                self.violations.append((
                    "PROTO-ROUTE-LOST",
                    f"admitted job {job_id} has no live instance on any "
                    f"alive replica after {decision!r} — lost across a "
                    f"failover",
                ))
            if rec.terminal == DONE and not done_on:
                self.violations.append((
                    "PROTO-ROUTE-LOST",
                    f"job {job_id} recorded DONE at the router but no "
                    f"replica holds its result",
                ))
        # PROTO-ROUTE-PIN: ProtocolModel's pin balance, per alive replica
        for rid in self._rids:
            r = router.replicas[rid]
            if not r.alive:
                continue
            pins = r.service.residency.pin_counts()
            expected: Dict[str, int] = {}
            for j in r.service.jobs.values():
                if j.status not in _TERMINAL:
                    expected[j.resident_key] = (
                        expected.get(j.resident_key, 0) + 1
                    )
            for key in sorted(set(pins) | set(expected)):
                if pins.get(key, 0) != expected.get(key, 0):
                    self.violations.append((
                        "PROTO-ROUTE-PIN",
                        f"replica {rid} pin imbalance for {key!r}: "
                        f"{pins.get(key, 0)} pin(s) vs "
                        f"{expected.get(key, 0)} live holder(s)",
                    ))
        # PROTO-ROUTE-FILM at each fleet-terminal DONE
        for job_id, rec in router.jobs.items():
            if rec.terminal != DONE or job_id in self._done_checked:
                continue
            self._done_checked.add(job_id)
            spec = next(
                s for s in self.scenario.jobs if s.name == job_id
            )
            owner = router.replicas.get(rec.rid)
            j = None if owner is None else owner.service.jobs.get(job_id)
            res = None if j is None else j.result
            want = spec.n_chunks * RAYS_PER_CHUNK
            if res is None or int(res.rays_traced) != want:
                got = None if res is None else int(res.rays_traced)
                self.violations.append((
                    "PROTO-ROUTE-FILM",
                    f"job {job_id} finished with rays_traced={got}, "
                    f"expected {want} — chunks lost or re-accumulated "
                    f"across the failover resume",
                ))
                continue
            ref = _harness()["reference_state"](spec.n_chunks)
            fs = res.film_state
            if not (
                np.array_equal(np.asarray(fs.rgb), np.asarray(ref.rgb))
                and np.array_equal(
                    np.asarray(fs.weight), np.asarray(ref.weight)
                )
            ):
                self.violations.append((
                    "PROTO-ROUTE-FILM",
                    f"job {job_id} terminal film differs bitwise from "
                    f"the single-replica sequential schedule's — the "
                    f"re-route/resume changed the accumulation",
                ))

    # -- artifacts ----------------------------------------------------------
    def _log_line(self, decision: tuple, outcome: str) -> None:
        parts = []
        for rid in self._rids:
            r = self.router.replicas[rid]
            flag = ("" if r.alive else "!") + ("~" if r.draining else "")
            jobs = " ".join(
                f"{j.job_id}:{j.status}:c{j.cursor}:a{j.attempt}"
                f":nb{j.not_before:.6f}"
                for j in sorted(
                    r.service.jobs.values(), key=lambda j: j.job_id
                )
            )
            parts.append(f"{flag}{rid}[{jobs}]")
        self.log.append(
            f"{len(self.log):03d} {decision!r} -> {outcome} "
            f"@{self.clock.peek():.6f} | {' '.join(parts)} | "
            f"routes={len(self.router.routes)} "
            f"sheds={self.router.edge_sheds} ckpt={self.ckpt_writes}"
        )

    def fingerprint(self) -> tuple:
        now = self.clock.peek()
        reps = tuple(
            (
                rid, r.alive, r.draining,
                tuple(
                    (
                        j.job_id, j.status, j.cursor, j.attempt,
                        j.state is None,
                        round(max(j.not_before - now, 0.0), 9),
                    )
                    for j in sorted(
                        r.service.jobs.values(), key=lambda j: j.job_id
                    )
                ),
            )
            for rid in self._rids
            for r in (self.router.replicas[rid],)
        )
        recs = tuple(
            (
                job_id, rec.rid, rec.terminal, rec.failovers,
                self._ckpt.checkpoint_exists(rec.checkpoint_path),
            )
            for job_id, rec in sorted(self.router.jobs.items())
        )
        return (reps, recs, tuple(sorted(self._unsubmitted)))

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        import shutil

        from tpu_pbrt.chaos import CHAOS
        from tpu_pbrt.obs.flight import FLIGHT
        from tpu_pbrt.obs.trace import TRACE

        CHAOS.clear()
        self._ckpt.unregister_write_observer(self._obs)
        FLIGHT._clock, FLIGHT._t0 = self._flight_prev
        TRACE._clock, TRACE._t0 = self._trace_prev
        shutil.rmtree(self.tmpdir, ignore_errors=True)

    def __enter__(self) -> "FleetModel":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_model(scenario: Scenario, seed: int = 0):
    """The explorer's model factory: one scenario, one model — the
    fleet shape when the scenario asks for replicas, the single-service
    ProtocolModel otherwise (byte-identical to the pre-fleet grid)."""
    if int(getattr(scenario, "replicas", 1)) > 1:
        return FleetModel(scenario, seed=seed)
    return ProtocolModel(scenario, seed=seed)


# --------------------------------------------------------------------------
# Mutation-regression corpus
# --------------------------------------------------------------------------


@contextmanager
def _mut_clock_double_sample():
    """Reintroduce the PR-13 step() shape: the runnable filter samples
    the clock itself, and the backoff-wait computation samples AGAIN —
    a deadline between the two samples wedges the scheduler."""
    from tpu_pbrt.serve import service as S

    orig = S.RenderService.step

    def step(self):
        self.health_steps += 1
        job = self.scheduler.pick(self._runnable())  # hidden sample #1
        if job is None:
            now = self._now()  # sample #2 — the deadline race window
            waiting = [
                j.not_before for j in self.jobs.values()
                if j.status in S._RUNNABLE and j.not_before > now
            ]
            if not waiting:
                return None
            self.clock.sleep(max(min(waiting) - now, 0.0))
            job = self.scheduler.pick(self._runnable(self._now()))
            if job is None:
                return None
        return self._step_job(job)

    S.RenderService.step = step
    try:
        yield
    finally:
        S.RenderService.step = orig


@contextmanager
def _mut_wfq_banked_credit():
    """Remove reenter()'s busy clamp (the PR-6 fix): an idle tenant
    keeps its stale low vtime and re-enters with banked credit."""
    from tpu_pbrt.serve import queue as Q

    orig = Q.FairScheduler.reenter
    Q.FairScheduler.reenter = (
        lambda self, name, busy_tenants=(): None
    )
    try:
        yield
    finally:
        Q.FairScheduler.reenter = orig


@contextmanager
def _mut_defer_replay():
    """Replay the window's captured deferred writes AFTER the park's
    superseding durable write — the cursor-regression shape SV-DEFER's
    static aspect and PROTO-DEFER's dynamic watermark both target."""
    from tpu_pbrt.serve import service as S

    orig = S.RenderService._park

    def _park(self, job):
        stale = list(job.window.deferred) if job.window is not None else []
        orig(self, job)
        for _cursor, fn in stale:
            fn()

    S.RenderService._park = _park
    try:
        yield
    finally:
        S.RenderService._park = orig


@contextmanager
def _mut_park_leak():
    """Seeded ISSUE-18 leak: the park path writes the durable emergency
    checkpoint but SKIPS the film release — every preemption strands
    one film-state carry in HBM (the 'known suspect' hbmcheck's
    HC-LEAK static rule and PROTO-HBM's dynamic watermark both
    target)."""
    from tpu_pbrt.serve import service as S

    orig = S.RenderService._park

    def _park(self, job):
        carry = job.state
        orig(self, job)
        job.state = carry  # the release the mutant skips

    S.RenderService._park = _park
    try:
        yield
    finally:
        S.RenderService._park = orig


@contextmanager
def _mut_failover_skip_consume():
    """Seeded ISSUE-20 fleet bug: the failover path re-submits the job
    on the surviving replica WITHOUT consuming the old instance first
    (no cancel on the drained-but-alive source). Both replicas now
    consider the job theirs — the drained one holds it PAUSED with a
    durable spool entry, the survivor renders it again from that same
    spool: a double delivery, and a double render once the drain
    lifts. PROTO-ROUTE-DUP's live-instance census flags it at the
    drain decision."""
    from tpu_pbrt.fleet import router as R

    orig = R.FleetRouter._failover_job

    def _failover_job(self, job_id, from_rid, *, cancel_old=True):
        return orig(self, job_id, from_rid, cancel_old=False)

    R.FleetRouter._failover_job = _failover_job
    try:
        yield
    finally:
        R.FleetRouter._failover_job = orig


@dataclass(frozen=True)
class MutationCase:
    """One seeded historical bug: the mutation, the invariant expected
    to flag it, and the (hand-verified) decision sequence that
    deterministically reaches the violating state."""

    name: str
    historical: str
    expect: str
    scenario: Scenario
    decisions: Tuple[tuple, ...]


MUTATIONS = {
    "clock-double-sample": _mut_clock_double_sample,
    "wfq-banked-credit": _mut_wfq_banked_credit,
    "defer-replay-after-park": _mut_defer_replay,
    "park-skips-film-release": _mut_park_leak,
    "failover-skips-spool-consume": _mut_failover_skip_consume,
}

MUTATION_CASES: Tuple[MutationCase, ...] = (
    MutationCase(
        name="clock-double-sample",
        historical=(
            "PR-13 step(): runnable filter and backoff wait sampled the "
            "clock separately; a deadline between the samples wedged "
            "the scheduler"
        ),
        expect="PROTO-WEDGE",
        scenario=Scenario(
            name="mut-clock",
            jobs=(JobSpec("j", n_chunks=2, depth=1),),
            fault="dispatch:fail@chunk=0",
            allow=("submit", "step", "advance"),
        ),
        decisions=(("submit", 0), ("step",), ("advance",), ("step",)),
    ),
    MutationCase(
        name="wfq-banked-credit",
        historical=(
            "PR-6 FairScheduler: an idle tenant re-entered with its "
            "stale low vtime (banked credit) instead of the busy "
            "tenants' floor"
        ),
        expect="PROTO-VTIME",
        scenario=Scenario(
            name="mut-wfq",
            jobs=(
                JobSpec("a1", tenant="a", n_chunks=2),
                JobSpec("b1", tenant="b", n_chunks=3),
                JobSpec("a2", tenant="a", n_chunks=2),
            ),
            allow=("submit", "step", "advance"),
        ),
        decisions=(
            ("submit", 0), ("step",), ("step",),
            ("submit", 1), ("step",), ("step",),
            ("submit", 2),
        ),
    ),
    MutationCase(
        name="defer-replay-after-park",
        historical=(
            "pipelined cadence checkpoints: a deferred write captured "
            "before a park replayed after it, regressing the durable "
            "cursor below the park's superseding write"
        ),
        expect="PROTO-DEFER",
        scenario=Scenario(
            name="mut-defer",
            jobs=(JobSpec("j", n_chunks=6, checkpoint_every=2, depth=3),),
            allow=("submit", "step", "preempt"),
        ),
        decisions=(
            ("submit", 0), ("step",), ("step",), ("step",),
            ("preempt", "j"),
        ),
    ),
    MutationCase(
        name="park-skips-film-release",
        historical=(
            "serve park path: the preempted job's film carry stayed "
            "resident after the durable emergency checkpoint landed — "
            "every preemption leaked one film state (the ISSUE-18 "
            "HBM-liveness suspect hbmcheck gates)"
        ),
        expect="PROTO-HBM",
        scenario=Scenario(
            name="mut-hbm",
            jobs=(JobSpec("j", n_chunks=4, checkpoint_every=2, depth=2),),
            allow=("submit", "step", "preempt"),
        ),
        decisions=(
            ("submit", 0), ("step",), ("step",), ("preempt", "j"),
        ),
    ),
    MutationCase(
        name="failover-skips-spool-consume",
        historical=(
            "ISSUE-20 fleet failover: the drain path re-submitted a "
            "job on the surviving replica without consuming the old "
            "instance — both replicas rendered it (double delivery, "
            "double spend)"
        ),
        expect="PROTO-ROUTE-DUP",
        scenario=Scenario(
            name="mut-route",
            # key "stub:sJ" hashes to r0 on the 2-replica ring — the
            # drain target below is hand-verified like every corpus
            # decision sequence
            jobs=(JobSpec("j", scene="sJ", n_chunks=4,
                          checkpoint_every=2),),
            allow=("submit", "rstep", "advance", "drain"),
            replicas=2,
        ),
        decisions=(
            ("submit", 0), ("rstep", 0), ("rstep", 0), ("drain", 0),
        ),
    ),
)


def mutation_case(name: str) -> MutationCase:
    for case in MUTATION_CASES:
        if case.name == name:
            return case
    raise KeyError(
        f"unknown mutation {name!r} (have: "
        f"{[c.name for c in MUTATION_CASES]})"
    )


def run_mutation_case(
    name: str, seed: int = 0, mutate: bool = True,
) -> Tuple[List[Tuple[str, str]], List[str]]:
    """Run one corpus case's decision sequence against the real service
    — under its mutation (`mutate=True`, the regression check: the
    expected invariant MUST fire) or against the clean tree
    (`mutate=False`, the soundness check: NO invariant may fire).
    Returns (violations, event log)."""
    case = mutation_case(name)
    ctx = MUTATIONS[case.name]() if mutate else _null_ctx()
    with ctx:
        with make_model(case.scenario, seed=seed) as model:
            model.run(case.decisions)
            return list(model.violations), list(model.log)


@contextmanager
def _null_ctx():
    yield


# --------------------------------------------------------------------------
# Analysis-runner entry point
# --------------------------------------------------------------------------


def run_protocheck(
    seed: int = 0,
    root: Optional[str] = None,
    explore: bool = True,
    max_nodes: int = 40,
    max_depth: int = 7,
) -> Tuple[List[str], List[str]]:
    """Layer 6 as `python -m tpu_pbrt.analysis` runs it: the SV static
    lint over the tree, the mutation corpus (each seeded mutant must be
    caught, the clean tree must pass), and — when `explore` — a
    bounded explorer smoke over the CI scenario grid. Returns
    (errors, warnings)."""
    errors: List[str] = []
    warnings: List[str] = []
    if root is None:
        root = repo_root()
    for v in sv_lint_tree(root):
        errors.append(str(v))
    # the mutation corpus is the layer's self-test: a corpus that no
    # longer fires means the invariants rotted, not that the bugs died
    for case in MUTATION_CASES:
        viol, _log = run_mutation_case(case.name, seed=seed, mutate=True)
        if not any(inv == case.expect for inv, _ in viol):
            errors.append(
                f"mutation {case.name!r} not flagged: expected "
                f"{case.expect}, got {[inv for inv, _ in viol]}"
            )
        clean_viol, _log = run_mutation_case(
            case.name, seed=seed, mutate=False
        )
        if clean_viol:
            errors.append(
                f"clean tree violates invariants on corpus case "
                f"{case.name!r}: {clean_viol[:3]}"
            )
    if explore:
        explore_py = os.path.join(root, "tools", "explore.py")
        if not os.path.exists(explore_py):
            warnings.append(
                f"explorer not found at {explore_py}; bounded "
                "interleaving smoke skipped"
            )
        else:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "tpu_pbrt_tools_explore", explore_py
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            errors.extend(mod.run_ci(
                seed=seed, max_nodes=max_nodes, max_depth=max_depth,
            ))
    return errors, warnings
