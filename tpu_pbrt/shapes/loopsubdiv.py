"""Loop subdivision surfaces.

Capability match for pbrt-v3 src/shapes/loopsubdiv.cpp (LoopSubdiv /
CreateLoopSubdiv): subdivides a closed or bounded triangle control mesh
`levels` times with Loop's rules (beta weights for interior vertices, 1/8
boundary rule, odd-vertex edge masks), then pushes vertices to the limit
surface and computes limit normals from the first/second tangent masks.

Host-side numpy (scene-compile step), fully vectorized per level.
"""

from __future__ import annotations

import numpy as np


def _beta(valence: np.ndarray) -> np.ndarray:
    """Loop's beta (pbrt uses 3/16 for valence 3, else 3/(8n))."""
    return np.where(valence == 3, 3.0 / 16.0, 3.0 / (8.0 * np.maximum(valence, 1)))


def _loop_gamma(valence: np.ndarray) -> np.ndarray:
    return 1.0 / (np.maximum(valence, 1) + 3.0 / (8.0 * _beta(valence)))


def _build_edges(faces: np.ndarray):
    """Unique edges + per-face edge ids. Returns (edges (E,2) sorted pairs,
    face_edge (F,3) where edge k is opposite... actually edge k = (v[k], v[k+1]),
    boundary mask, edge->adjacent 'wing' vertices)."""
    f = faces
    e_all = np.concatenate([f[:, [0, 1]], f[:, [1, 2]], f[:, [2, 0]]], axis=0)
    e_sorted = np.sort(e_all, axis=1)
    edges, inv, counts = np.unique(e_sorted, axis=0, return_inverse=True, return_counts=True)
    face_edge = inv.reshape(3, -1).T  # (F,3): edge ids for (01,12,20)
    boundary = counts == 1
    # wing (opposite) vertices per edge: for edge k of face, opposite vertex
    opp = np.concatenate([f[:, 2], f[:, 0], f[:, 1]], axis=0)
    wing1 = np.full(len(edges), -1, np.int64)
    wing2 = np.full(len(edges), -1, np.int64)
    # first occurrence -> wing1, second -> wing2
    order = np.argsort(inv, kind="stable")
    sorted_inv = inv[order]
    sorted_opp = opp[order]
    first_pos = np.searchsorted(sorted_inv, np.arange(len(edges)), side="left")
    wing1 = sorted_opp[first_pos]
    second = counts > 1
    wing2[second] = sorted_opp[first_pos[second] + 1]
    return edges, face_edge, boundary, wing1, wing2


def _subdivide_once(P: np.ndarray, faces: np.ndarray):
    nv = len(P)
    edges, face_edge, boundary, wing1, wing2 = _build_edges(faces)

    # -- even (existing) vertices ----------------------------------------
    # valence + one-ring sums via scatter-add over edges
    valence = np.zeros(nv, np.int64)
    np.add.at(valence, edges[:, 0], 1)
    np.add.at(valence, edges[:, 1], 1)
    ring_sum = np.zeros_like(P)
    np.add.at(ring_sum, edges[:, 0], P[edges[:, 1]])
    np.add.at(ring_sum, edges[:, 1], P[edges[:, 0]])

    # boundary vertices use only boundary-edge neighbors (1/8,3/4,1/8 rule)
    on_boundary = np.zeros(nv, bool)
    on_boundary[edges[boundary].ravel()] = True
    b_sum = np.zeros_like(P)
    b_edges = edges[boundary]
    np.add.at(b_sum, b_edges[:, 0], P[b_edges[:, 1]])
    np.add.at(b_sum, b_edges[:, 1], P[b_edges[:, 0]])

    beta = _beta(valence)[:, None]
    new_interior = P * (1 - valence[:, None] * beta) + beta * ring_sum
    new_boundary = P * (3.0 / 4.0) + b_sum * (1.0 / 8.0)
    P_even = np.where(on_boundary[:, None], new_boundary, new_interior)

    # -- odd (edge) vertices ---------------------------------------------
    interior_e = ~boundary
    mid = 0.5 * (P[edges[:, 0]] + P[edges[:, 1]])
    P_odd = mid.copy()
    ie = np.where(interior_e)[0]
    P_odd[ie] = (
        (3.0 / 8.0) * (P[edges[ie, 0]] + P[edges[ie, 1]])
        + (1.0 / 8.0) * (P[wing1[ie]] + P[wing2[ie]])
    )

    # -- new topology: each face -> 4 faces ------------------------------
    ev = nv + np.arange(len(edges))
    e01 = ev[face_edge[:, 0]]
    e12 = ev[face_edge[:, 1]]
    e20 = ev[face_edge[:, 2]]
    v0, v1, v2 = faces[:, 0], faces[:, 1], faces[:, 2]
    new_faces = np.concatenate(
        [
            np.stack([v0, e01, e20], axis=1),
            np.stack([e01, v1, e12], axis=1),
            np.stack([e20, e12, v2], axis=1),
            np.stack([e01, e12, e20], axis=1),
        ],
        axis=0,
    )
    return np.vstack([P_even, P_odd]), new_faces


def _limit_and_normals(P: np.ndarray, faces: np.ndarray):
    """Push to limit surface + limit normals (pbrt's final step)."""
    nv = len(P)
    edges, _, boundary, _, _ = _build_edges(faces)
    valence = np.zeros(nv, np.int64)
    np.add.at(valence, edges[:, 0], 1)
    np.add.at(valence, edges[:, 1], 1)
    ring_sum = np.zeros_like(P)
    np.add.at(ring_sum, edges[:, 0], P[edges[:, 1]])
    np.add.at(ring_sum, edges[:, 1], P[edges[:, 0]])
    on_boundary = np.zeros(nv, bool)
    on_boundary[edges[boundary].ravel()] = True

    gamma = _loop_gamma(valence)[:, None]
    limit = np.where(
        on_boundary[:, None],
        P,  # boundary limit rule omitted (1/5,3/5,1/5) — boundary kept
        (1 - valence[:, None] * gamma) * P + gamma * ring_sum,
    )

    # normals from area-weighted face normals of the refined mesh (pbrt
    # computes exact tangent masks; area-weighting converges to the same
    # limit normal as levels increase)
    fn = np.cross(limit[faces[:, 1]] - limit[faces[:, 0]], limit[faces[:, 2]] - limit[faces[:, 0]])
    vn = np.zeros_like(limit)
    for k in range(3):
        np.add.at(vn, faces[:, k], fn)
    ln = np.linalg.norm(vn, axis=-1, keepdims=True)
    vn = vn / np.maximum(ln, 1e-20)
    return limit, vn


def loop_subdivide(P: np.ndarray, faces: np.ndarray, levels: int):
    """-> (tri_verts (T,3,3), tri_normals (T,3,3)) after `levels` rounds."""
    P = np.asarray(P, np.float64)
    faces = np.asarray(faces, np.int64)
    for _ in range(max(0, levels)):
        P, faces = _subdivide_once(P, faces)
    limit, vn = _limit_and_normals(P, faces)
    return limit[faces], vn[faces]
