"""RealisticCamera: spherical lens-element tracing + exit-pupil tables.

Capability match for pbrt-v3 src/cameras/realistic.cpp (RealisticCamera:
element stack traced per ray with Snell refraction and aperture clipping,
thick-lens autofocus, precomputed exit-pupil bounds sampled per film
point, cos^4/pupil-area ray weighting). Re-designed for TPU execution:

- the per-ray element loop is a STATIC Python unroll over the (few)
  surfaces — each step is dense vector math (sphere intersect + refract)
  over the whole ray batch, no data-dependent control flow; failed lanes
  carry a weight-0 mask instead of early returns.
- exit-pupil bounds and autofocus run HOST-side in numpy at compile time
  (as pbrt precomputes them in the constructor), producing a (64, 4)
  bounds table the device lerps per film radius.

Geometry convention (differs from realistic.cpp's internal axes, same
physics): film sits on the z=0 plane looking down +z; element surface i
has its vertex at z = z_apex[i] > 0, ordered rear (nearest film) to
front (scene side); the scene lies beyond the front element. A surface
with curvature 0 is the aperture stop (planar). Rays are traced
film -> rear -> front and handed to camera_to_world.

The lens prescription comes from a pbrt-format lens .dat file
("string lensfile": rows of `curvature-radius thickness eta
aperture-diameter` in mm, front-to-rear) or, when the file is missing,
a built-in air-spaced achromat-like doublet derived from the lensmaker
equation (loud fallback) so realistic cameras work without scene data
files.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.utils.error import Warning

#: radial segments of the exit-pupil bounds table (realistic.cpp uses 64)
N_PUPIL_SEGMENTS = 64
#: samples per segment for the host-side pupil bound estimation
_PUPIL_SAMPLES = 1024


class CompiledLens(NamedTuple):
    """Device-side lens stack, rear (film side) to front (scene side)."""

    z_apex: jnp.ndarray       # (N,) surface vertex z (camera space, >0)
    radius: jnp.ndarray       # (N,) curvature radius; 0 = planar stop
    eta_ratio: jnp.ndarray    # (N,) eta_incident / eta_transmitted
    ap2: jnp.ndarray          # (N,) aperture radius squared
    rear_z: float             # z of the rear surface vertex
    rear_ap: float            # rear surface aperture radius
    pupil: jnp.ndarray        # (N_PUPIL_SEGMENTS, 4) [x0, y0, x1, y1]
    film_diag: float          # film diagonal (m) the pupil table spans


# -- prescription ----------------------------------------------------------


def parse_lens_file(path: str) -> np.ndarray:
    """pbrt lens .dat: `radius thickness eta aperture-diameter` per row,
    millimeters, FRONT to REAR. Returns the same rows in meters."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            vals = [float(v) for v in line.split()]
            if len(vals) != 4:
                raise ValueError(f"lens row needs 4 values: {line!r}")
            rows.append(vals)
    if not rows:
        raise ValueError("empty lens file")
    out = np.asarray(rows, np.float64)
    out[:, [0, 1, 3]] *= 1e-3  # mm -> m; eta stays dimensionless
    return out


def builtin_doublet(focal: float = 0.050, ap_diam: float = 0.025) -> np.ndarray:
    """A symmetric biconvex crown singlet + planar stop with the requested
    focal length (lensmaker: 1/f = (n-1)(1/R1 - 1/R2)), used when no
    lensfile is available. Front-to-rear pbrt rows, meters."""
    n = 1.517  # BK7
    r = 2.0 * (n - 1.0) * focal  # symmetric biconvex: R1 = -R2 = r
    thick = 0.006
    return np.asarray(
        [
            # radius   thickness  eta   aperture diameter
            [r, thick, n, ap_diam * 1.4],        # front surface (air->glass)
            [-r, 0.004, 1.0, ap_diam * 1.4],     # rear surface (glass->air)
            [0.0, 0.010, 1.0, ap_diam],          # aperture stop
        ],
        np.float64,
    )


def apply_aperture_diameter(rows: np.ndarray, ap_diam: float) -> np.ndarray:
    """realistic.cpp constructor: the aperture-stop rows (curvature 0)
    take the requested "aperturediameter" unless it exceeds the stop's
    physical bound, in which case the prescription's diameter stands
    (with a warning, as pbrt does). rows are meters (parse_lens_file
    output); ap_diam is meters."""
    rows = np.array(rows, np.float64, copy=True)
    stop = rows[:, 0] == 0.0
    too_big = stop & (rows[:, 3] < ap_diam)
    if too_big.any():
        Warning(
            f"aperture diameter {ap_diam * 1000.0:.3f} mm is greater than "
            f"the lens stop's maximum {rows[too_big, 3].max() * 1000.0:.3f} "
            "mm; clamping to the stop"
        )
    rows[:, 3] = np.where(stop & ~too_big, ap_diam, rows[:, 3])
    return rows


def _stack_from_rows(rows: np.ndarray):
    """pbrt front-to-rear rows -> rear-to-front numpy arrays with
    absolute z apex positions (film at z=0; rear vertex z set later by
    focusing). Returns dict of host arrays (z offsets relative to the
    REAR vertex, which sits at z = film_distance)."""
    rows = np.asarray(rows, np.float64)
    n = len(rows)
    eta_med = np.where(rows[:, 2] > 0.0, rows[:, 2], 1.0)
    # z position of each surface, front surface at the largest z:
    # thickness[i] is the distance from surface i to surface i+1 (next
    # toward the film). Walk front->rear accumulating.
    z_rel = np.zeros(n)
    for i in range(1, n):
        z_rel[i] = z_rel[i - 1] - rows[i - 1, 1]
    # rearmost surface index n-1 has the smallest z; shift so rear = 0
    z_rel = z_rel - z_rel[-1]
    # rear-to-front ordering
    order = np.arange(n)[::-1]
    radius = rows[order, 0]
    ap_r = rows[order, 3] / 2.0
    z_off = z_rel[order]
    # medium eta on the FILM side of each surface (what the ray is in
    # before crossing, tracing film->front): for surface i (rear-to-
    # front), the incident medium is the medium between it and the
    # previous (more rearward) surface = eta listed on the surface
    # behind it in front-to-rear order (rows[order[i]] eta is the
    # medium BEHIND surface order[i], i.e. toward the film — pbrt's
    # convention: row eta is the medium on the z-negative side)
    eta_behind = eta_med[order]  # medium between this surface and film side
    eta_front = np.empty(n)
    # the medium in front of surface i (rear-to-front) is the medium
    # behind surface i+1; in front of the frontmost surface is air
    eta_front[:-1] = eta_behind[1:]
    eta_front[-1] = 1.0
    eta_ratio = eta_behind / eta_front  # incident/transmitted, film->scene
    return {
        "radius": radius,
        "ap_r": ap_r,
        "z_off": z_off,  # relative to rear vertex
        "eta_ratio": eta_ratio,
    }


# -- host-side ray trace (numpy, used for focusing + pupil precompute) -----


def _trace_np(stack, film_dist, o, d):
    """Trace rays (film space: film z=0, +z toward scene) through the
    stack. o: (R,3), d: (R,3) normalized-ish. Returns (ok, o, d)."""
    o = o.copy()
    d = d.copy()
    ok = np.ones(len(o), bool)
    for i in range(len(stack["radius"])):
        z_v = film_dist + stack["z_off"][i]
        R = stack["radius"][i]
        ap2 = stack["ap_r"][i] ** 2
        if R == 0.0:
            t = (z_v - o[:, 2]) / np.where(d[:, 2] == 0, 1e-12, d[:, 2])
            p = o + t[:, None] * d
            ok &= (t > 0) & (p[:, 0] ** 2 + p[:, 1] ** 2 <= ap2)
            o = p
            continue
        c = np.array([0.0, 0.0, z_v + R])
        oc = o - c
        b = np.sum(oc * d, axis=1)
        cc = np.sum(oc * oc, axis=1) - R * R
        disc = b * b - cc
        valid = disc >= 0
        sq = np.sqrt(np.maximum(disc, 0.0))
        # realistic.cpp IntersectSphericalElement root choice: use the
        # CLOSER root when (d.z > 0) ^ (R < 0), the farther one otherwise
        use_closer = (d[:, 2] > 0) ^ (R < 0)
        t = np.where(use_closer, -b - sq, -b + sq)
        valid &= t > 1e-9
        p = o + t[:, None] * d
        valid &= p[:, 0] ** 2 + p[:, 1] ** 2 <= ap2
        n = (p - c) / R  # outward when R>0 — orient against the ray below
        n = np.where(np.sum(n * d, axis=1)[:, None] > 0, -n, n)
        eta = stack["eta_ratio"][i]
        if eta != 1.0:
            cos_i = -np.sum(n * d, axis=1)
            s2 = np.maximum(0.0, 1.0 - cos_i**2) * eta * eta
            tir = s2 > 1.0
            valid &= ~tir
            cos_t = np.sqrt(np.maximum(0.0, 1.0 - s2))
            d_new = eta * d + (eta * cos_i - cos_t)[:, None] * n
            nl = np.linalg.norm(d_new, axis=1, keepdims=True)
            d = np.where(valid[:, None], d_new / np.maximum(nl, 1e-12), d)
        o = np.where(valid[:, None], p, o)
        ok &= valid
    return ok, o, d


def _focus(stack, focus_dist: float) -> float:
    """Film-to-rear-vertex distance that focuses a point at focus_dist
    (measured from the film plane) onto the film: bisection on the axial
    crossing of near-axis rays traced BACK from the object point
    (numerical thick-lens focus — same answer as realistic.cpp's
    FocusThickLens cardinal-point algebra, without needing the paraxial
    matrices)."""

    lens_span = float(stack["z_off"][0] - stack["z_off"][-1]) + 0.0
    lo, hi = 1e-4, max(0.5, 10.0 * lens_span + 0.3)

    # Trace from an on-axis film point forward and find where the exit
    # rays re-cross the axis; bisect film_dist until that conjugate
    # lands at focus_dist.
    def converge_z(film_dist):
        # two rays from the on-axis film point through different pupil
        # heights; after the lens they cross at the conjugate object
        # distance for THIS film_dist
        h1 = stack["ap_r"][0] * 0.15
        h2 = stack["ap_r"][0] * 0.3
        rear_z = film_dist
        o = np.zeros((2, 3))
        d = np.array([[h1, 0.0, rear_z], [h2, 0.0, rear_z]])
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        ok, o2, d2 = _trace_np(stack, film_dist, o, d)
        if not ok.all():
            return None
        # crossing of each exit ray with the axis (x = 0)
        with np.errstate(divide="ignore", invalid="ignore"):
            t = -o2[:, 0] / d2[:, 0]
        z = o2[:, 2] + t * d2[:, 2]
        if not np.all(np.isfinite(z)) or np.any(t <= 0):
            return None
        return float(z.mean())

    best = None
    # bisection on f(film_dist) = converge_z - focus_dist (monotone
    # decreasing in film_dist for a converging lens)
    flo, fhi = None, None
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        z = converge_z(mid)
        if z is None:
            hi = mid  # vignetted/diverged: shrink
            continue
        err = z - focus_dist
        if best is None or abs(err) < best[1]:
            best = (mid, abs(err))
        if err > 0:
            lo = mid
        else:
            hi = mid
    return best[0] if best else 0.05


def _exit_pupil(stack, film_dist: float, film_diag: float) -> np.ndarray:
    """(N_PUPIL_SEGMENTS, 4) bounding boxes (on the rear-element plane)
    of ray directions that make it through the lens, per radial film
    position r in [0, film_diag/2] (realistic.cpp ComputeExitPupilBounds):
    sample the rear aperture square, trace, bound the survivors."""
    rng = np.random.default_rng(7)
    rear_ap = float(stack["ap_r"][0])  # rear-to-front index 0 = rear
    half = rear_ap * 1.5
    bounds = np.zeros((N_PUPIL_SEGMENTS, 4), np.float32)
    for i in range(N_PUPIL_SEGMENTS):
        r = (i + 0.5) / N_PUPIL_SEGMENTS * (film_diag / 2.0)
        px = rng.uniform(-half, half, _PUPIL_SAMPLES)
        py = rng.uniform(-half, half, _PUPIL_SAMPLES)
        o = np.stack([np.full_like(px, r), np.zeros_like(px),
                      np.zeros_like(px)], axis=1)
        tgt = np.stack([px, py, np.full_like(px, film_dist)], axis=1)
        d = tgt - o
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        ok, _, _ = _trace_np(stack, film_dist, o, d)
        if ok.any():
            bounds[i] = [px[ok].min(), py[ok].min(), px[ok].max(), py[ok].max()]
        else:
            # vignetted segment: keep the previous segment's bounds so
            # sampling still draws (weight masks the failures)
            bounds[i] = bounds[i - 1] if i else [-half, -half, half, half]
    # widen by one sample spacing (pbrt expands by the sample diagonal)
    pad = 2.0 * half / np.sqrt(_PUPIL_SAMPLES)
    bounds += np.array([-pad, -pad, pad, pad], np.float32)
    return bounds


def compile_lens(rows: np.ndarray, focus_dist: float, film_diag: float) -> CompiledLens:
    stack = _stack_from_rows(rows)
    film_dist = _focus(stack, focus_dist)
    pupil = _exit_pupil(stack, film_dist, film_diag)
    z_apex = film_dist + stack["z_off"]
    return CompiledLens(
        z_apex=jnp.asarray(z_apex, jnp.float32),
        radius=jnp.asarray(stack["radius"], jnp.float32),
        eta_ratio=jnp.asarray(stack["eta_ratio"], jnp.float32),
        ap2=jnp.asarray(stack["ap_r"] ** 2, jnp.float32),
        rear_z=float(film_dist),
        rear_ap=float(stack["ap_r"][0]),
        pupil=jnp.asarray(pupil),
        film_diag=float(film_diag),
    )


# -- device-side -----------------------------------------------------------


def trace_lenses(lens: CompiledLens, o, d):
    """Batched film->scene trace in camera space. o/d: (..., 3).
    Returns (ok, o', d') with failed lanes masked (their o/d are junk).
    Static unroll over the few surfaces — each step dense vector math."""
    ok = jnp.ones(o.shape[:-1], bool)
    n = lens.radius.shape[0]
    for i in range(n):
        z_v = lens.z_apex[i]
        R = lens.radius[i]
        ap2 = lens.ap2[i]
        planar = R == 0.0
        dz = jnp.where(d[..., 2] == 0.0, 1e-12, d[..., 2])
        t_plane = (z_v - o[..., 2]) / dz
        c = jnp.stack([jnp.zeros_like(z_v), jnp.zeros_like(z_v), z_v + R])
        oc = o - c
        b = jnp.sum(oc * d, axis=-1)
        cc = jnp.sum(oc * oc, axis=-1) - R * R
        disc = b * b - cc
        sq = jnp.sqrt(jnp.maximum(disc, 0.0))
        # realistic.cpp root choice: CLOSER root when (d.z > 0) ^ (R < 0)
        use_closer = (d[..., 2] > 0.0) ^ (R < 0.0)
        t_sph = jnp.where(use_closer, -b - sq, -b + sq)
        t = jnp.where(planar, t_plane, t_sph)
        valid = (t > 1e-9) & jnp.where(planar, True, disc >= 0.0)
        p = o + t[..., None] * d
        valid = valid & (p[..., 0] ** 2 + p[..., 1] ** 2 <= ap2)
        # refraction (skip on the planar stop: eta_ratio is 1 there)
        nrm = (p - c) / jnp.where(R == 0.0, 1.0, R)
        nrm = jnp.where(
            (jnp.sum(nrm * d, axis=-1) > 0.0)[..., None], -nrm, nrm
        )
        eta = lens.eta_ratio[i]
        cos_i = -jnp.sum(nrm * d, axis=-1)
        s2 = jnp.maximum(0.0, 1.0 - cos_i * cos_i) * eta * eta
        tir = s2 > 1.0
        cos_t = jnp.sqrt(jnp.maximum(0.0, 1.0 - s2))
        d_ref = eta * d + (eta * cos_i - cos_t)[..., None] * nrm
        d_ref = d_ref / jnp.maximum(
            jnp.linalg.norm(d_ref, axis=-1, keepdims=True), 1e-12
        )
        refracting = jnp.abs(eta - 1.0) > 1e-6
        valid = valid & jnp.where(refracting & ~planar, ~tir, True)
        d = jnp.where(
            (refracting & ~planar & valid)[..., None], d_ref, d
        )
        o = jnp.where(valid[..., None], p, o)
        ok = ok & valid
    return ok, o, d


def sample_pupil(lens: CompiledLens, p_film_cam, u_lens):
    """Sample the exit-pupil bounds for film point (x, y, 0) in camera
    space (realistic.cpp SampleExitPupil): pick the radial segment's
    box, sample it, rotate by the film azimuth. Returns (p_rear (..,3),
    area (..,) of the sampled bounds)."""
    r = jnp.sqrt(p_film_cam[..., 0] ** 2 + p_film_cam[..., 1] ** 2)
    fi = jnp.clip(
        r / (lens.film_diag / 2.0) * N_PUPIL_SEGMENTS, 0.0,
        N_PUPIL_SEGMENTS - 1.0,
    )
    i0 = fi.astype(jnp.int32)
    box = lens.pupil[i0]  # (..., 4)
    x = box[..., 0] + u_lens[..., 0] * (box[..., 2] - box[..., 0])
    y = box[..., 1] + u_lens[..., 1] * (box[..., 3] - box[..., 1])
    area = (box[..., 2] - box[..., 0]) * (box[..., 3] - box[..., 1])
    # rotate from the +x reference azimuth to the film point's azimuth
    sin_a = jnp.where(r > 1e-12, p_film_cam[..., 1] / jnp.maximum(r, 1e-12), 0.0)
    cos_a = jnp.where(r > 1e-12, p_film_cam[..., 0] / jnp.maximum(r, 1e-12), 1.0)
    px = cos_a * x - sin_a * y
    py = sin_a * x + cos_a * y
    pz = jnp.full_like(px, lens.rear_z)
    return jnp.stack([px, py, pz], axis=-1), area
