"""Cameras: host-side construction + device-side batched ray generation.

Capability match for pbrt-v3 src/cameras/ (perspective, orthographic,
environment, realistic) and src/core/camera.{h,cpp}. The projective
transform chain (screen window -> raster -> camera) is built on the host
exactly as in ProjectiveCamera's constructor; the device side is a single
vectorized ray-gen over a batch of CameraSamples (film + lens points), with
depth of field via concentric lens sampling.

The realistic camera's lens-element tracing is approximated by the thin-lens
model (same params: aperture + focus); full element tables are a later
extension (SURVEY.md §7 stage 9).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.core import transform as xf
from tpu_pbrt.core.sampling import concentric_sample_disk
from tpu_pbrt.core.vecmath import normalize
from tpu_pbrt.utils.error import Error, Warning

CAM_PERSPECTIVE = 0
CAM_ORTHOGRAPHIC = 1
CAM_ENVIRONMENT = 2
CAM_REALISTIC = 3


class CompiledCamera(NamedTuple):
    """Device-ready camera. Matrices are float32 (4,4); row-vector math is
    done explicitly in generate_rays. For CAM_REALISTIC, `lens` carries
    the compiled element stack (cameras/realistic.py) and the projective
    matrices hold a thin-lens PROXY (fov from the focused film distance)
    used only by the pinhole-approximated seams (ray differentials,
    BDPT t=1 / light-tracing We — pbrt's realistic camera does not
    implement We/Sample_Wi at all; the proxy is our loud stand-in)."""

    cam_type: int  # static python int — selects the trace path
    raster_to_camera: jnp.ndarray  # (4,4)
    camera_to_world: jnp.ndarray  # (4,4)
    lens_radius: jnp.ndarray  # scalar
    focal_distance: jnp.ndarray  # scalar
    shutter_open: float
    shutter_close: float
    full_res: tuple  # (x, y)
    lens: object = None  # CompiledLens for CAM_REALISTIC


def _screen_window(aspect: float, params) -> tuple:
    sw = params.find_float("screenwindow")
    if aspect > 1.0:
        screen = [-aspect, aspect, -1.0, 1.0]
    else:
        screen = [-1.0, 1.0, -1.0 / aspect, 1.0 / aspect]
    if sw is not None:
        if len(sw) == 4:
            screen = [sw[0], sw[1], sw[2], sw[3]]
        else:
            Error('"screenwindow" should have four values')
    return screen


def make_camera(name: str, params, cam_to_world: xf.Transform, full_res,
                shutter=(0.0, 1.0), film_diag: float = 0.035,
                scene_dir: str = "."):
    """api.cpp MakeCamera: string-dispatched factory -> CompiledCamera."""
    res_x, res_y = full_res
    aspect = params.find_one_float("frameaspectratio", res_x / res_y)
    lens_radius = params.find_one_float("lensradius", 0.0)
    focal = params.find_one_float("focaldistance", 1e6)
    lens = None

    if name in ("perspective", "realistic"):
        if name == "realistic":
            # real lens-element tracing (cameras/realistic.py). The
            # projective matrices built below become the thin-lens PROXY
            # for the pinhole-approximated seams (see CompiledCamera).
            import math as _math

            from tpu_pbrt.cameras.realistic import (
                apply_aperture_diameter,
                builtin_doublet,
                compile_lens,
                parse_lens_file,
            )
            from tpu_pbrt.utils.fileutil import resolve_filename

            ap_diam = params.find_one_float("aperturediameter", 1.0) / 1000.0
            focal = params.find_one_float("focusdistance", 10.0)
            lens_file = params.find_one_string("lensfile", "")
            rows = None
            if lens_file:
                try:
                    rows = parse_lens_file(
                        resolve_filename(lens_file, scene_dir)
                    )
                    # realistic.cpp: "aperturediameter" rescales the
                    # prescription's aperture-stop element (clamped to
                    # the stop's physical bound)
                    rows = apply_aperture_diameter(rows, ap_diam)
                except Exception as e:  # noqa: BLE001
                    Warning(
                        f'realistic: could not read lensfile "{lens_file}" '
                        f"({e}); using the built-in doublet"
                    )
            if rows is None:
                rows = builtin_doublet(ap_diam=max(ap_diam, 1e-4))
            lens = compile_lens(rows, focal, film_diag)
            ctype = CAM_REALISTIC
            # proxy fov from the focused film distance (2 atan(diag/2z))
            fov = _math.degrees(
                2.0 * _math.atan(0.5 * film_diag / max(lens.rear_z, 1e-4))
            )
            lens_radius = ap_diam / 2.0
        else:
            fov = params.find_one_float("fov", 90.0)
            halffov = params.find_one_float("halffov", -1.0)
            if halffov > 0:
                fov = 2.0 * halffov
            ctype = CAM_PERSPECTIVE
        screen = _screen_window(aspect, params)
        cam_to_screen = xf.perspective(fov, 1e-2, 1000.0)
    elif name == "orthographic":
        screen = _screen_window(aspect, params)
        cam_to_screen = xf.orthographic(0.0, 1.0)
        ctype = CAM_ORTHOGRAPHIC
    elif name == "environment":
        screen = [-1.0, 1.0, -1.0, 1.0]
        cam_to_screen = xf.Transform()
        ctype = CAM_ENVIRONMENT
    else:
        Warning(f'Camera "{name}" unknown; using "perspective".')
        return make_camera("perspective", params, cam_to_world, full_res, shutter)

    x0, x1, y0, y1 = screen
    screen_to_raster = (
        xf.scale(res_x, res_y, 1.0)
        * xf.scale(1.0 / (x1 - x0), 1.0 / (y0 - y1), 1.0)
        * xf.translate([-x0, -y1, 0.0])
    )
    raster_to_screen = screen_to_raster.inverse()
    raster_to_camera = cam_to_screen.inverse() * raster_to_screen

    return CompiledCamera(
        cam_type=ctype,
        raster_to_camera=jnp.asarray(raster_to_camera.m, jnp.float32),
        camera_to_world=jnp.asarray(cam_to_world.m, jnp.float32),
        lens_radius=jnp.float32(lens_radius),
        focal_distance=jnp.float32(focal),
        shutter_open=shutter[0],
        shutter_close=shutter[1],
        full_res=(res_x, res_y),
        lens=lens,
    )


def _xform_point(m, p):
    r = p @ m[:3, :3].T + m[:3, 3]
    w = p @ m[3, :3].T + m[3, 3]
    return r / jnp.where(w == 0.0, 1.0, w)[..., None]


def _xform_vector(m, v):
    return v @ m[:3, :3].T


def _screen_area_z1(cam: CompiledCamera):
    """Area of the perspective screen window projected to the z=1 plane in
    camera space (perspective.cpp PerspectiveCamera constructor's A)."""
    rx, ry = cam.full_res
    corners = jnp.asarray([[0.0, 0.0, 0.0], [rx, ry, 0.0]], jnp.float32)
    p = _xform_point(cam.raster_to_camera, corners)
    p = p / p[:, 2:3]
    return jnp.abs((p[1, 0] - p[0, 0]) * (p[1, 1] - p[0, 1]))


def camera_world_frame(cam: CompiledCamera):
    """(origin, forward) of the camera in world space."""
    o = _xform_point(cam.camera_to_world, jnp.zeros((1, 3), jnp.float32))[0]
    fwd = normalize(
        _xform_vector(cam.camera_to_world, jnp.asarray([[0.0, 0.0, 1.0]], jnp.float32))
    )[0]
    return o, fwd


def project_to_raster(cam: CompiledCamera, p_world):
    """World point -> raster coordinates + in-front/in-bounds mask (the
    inverse of generate_rays for the pinhole perspective camera; used by
    BDPT's t=1 camera connections and by light tracing)."""
    w2c = jnp.linalg.inv(cam.camera_to_world)
    c2r = jnp.linalg.inv(cam.raster_to_camera)
    p_cam = _xform_point(w2c, p_world)
    in_front = p_cam[..., 2] > 1e-6
    p_safe = jnp.where(in_front[..., None], p_cam, jnp.ones_like(p_cam))
    p_ras = _xform_point(c2r, p_safe)
    rx, ry = cam.full_res
    in_b = (
        in_front
        & (p_ras[..., 0] >= 0.0)
        & (p_ras[..., 0] < rx)
        & (p_ras[..., 1] >= 0.0)
        & (p_ras[..., 1] < ry)
    )
    return p_ras[..., :2], in_b


def camera_pdf_we(cam: CompiledCamera, d_world):
    """PerspectiveCamera::Pdf_We: (pdf_pos, pdf_dir) of generating a ray
    in direction d_world. Delta pinhole position -> pdf_pos = 1."""
    _, fwd = camera_world_frame(cam)
    a = _screen_area_z1(cam)
    cos_t = jnp.maximum(jnp.sum(d_world * fwd, axis=-1), 0.0)
    pdf_dir = jnp.where(
        cos_t > 1e-6, 1.0 / (a * jnp.maximum(cos_t, 1e-9) ** 3), 0.0
    )
    return jnp.ones_like(pdf_dir), pdf_dir


def camera_sample_wi(cam: CompiledCamera, ref_p):
    """PerspectiveCamera::Sample_Wi for a pinhole lens: direction to the
    camera, distance, solid-angle pdf, and the importance We carried by
    that connection (perspective.cpp:260). Returns
    (wi, dist, pdf, we (R,), raster_xy, in_bounds)."""
    cam_p, fwd = camera_world_frame(cam)
    a = _screen_area_z1(cam)
    to_cam = cam_p - ref_p
    dist = jnp.maximum(jnp.linalg.norm(to_cam, axis=-1), 1e-12)
    wi = to_cam / dist[..., None]
    cos_t = jnp.maximum(jnp.sum(-wi * fwd, axis=-1), 0.0)  # ray cam->ref
    # pinhole: lensArea treated as 1 (delta), pdf in solid angle at ref
    pdf = dist * dist / jnp.maximum(cos_t, 1e-9)
    we = jnp.where(cos_t > 1e-6, 1.0 / (a * jnp.maximum(cos_t, 1e-9) ** 4), 0.0)
    raster, in_b = project_to_raster(cam, ref_p)
    we = jnp.where(in_b, we, 0.0)
    return wi, dist, pdf, we, raster, in_b


def generate_rays(cam: CompiledCamera, p_film, u_lens):
    """Batched Camera::GenerateRay.

    p_film: (...,2) raster-space sample points; u_lens: (...,2) in [0,1).
    Returns (o, d, weight): world-space origins/directions + ray weight."""
    p_raster = jnp.concatenate([p_film, jnp.zeros_like(p_film[..., :1])], axis=-1)
    p_cam = _xform_point(cam.raster_to_camera, p_raster)

    if cam.cam_type == CAM_REALISTIC:
        # realistic.cpp GenerateRay: raster -> physical film point
        # (x negated, pbrt's film orientation), exit-pupil sample,
        # element-stack trace; vignetted lanes carry weight 0.
        from tpu_pbrt.cameras.realistic import sample_pupil, trace_lenses

        lens = cam.lens
        rx, ry = cam.full_res
        a = ry / rx
        fx = np.float32(np.sqrt(lens.film_diag**2 / (1.0 + a * a)))
        fy = np.float32(a * fx)
        sx = p_film[..., 0] / rx
        sy = p_film[..., 1] / ry
        pf = jnp.stack(
            [-(sx - 0.5) * fx, (sy - 0.5) * fy,
             jnp.zeros_like(sx)], axis=-1,
        )
        p_rear, area = sample_pupil(lens, pf, u_lens)
        d0 = normalize(p_rear - pf)
        ok, o_c, d_c = trace_lenses(lens, pf, d0)
        cos4 = jnp.maximum(d0[..., 2], 0.0) ** 4
        # exposure-normalized simple weighting (realistic.cpp's
        # simpleWeighting, divided by the on-axis reference so a stopped
        # -down lens meters like the thin-lens camera): cos^4 * A(r)/A(0)
        area0 = (lens.pupil[0, 2] - lens.pupil[0, 0]) * (
            lens.pupil[0, 3] - lens.pupil[0, 1]
        )
        weight = jnp.where(
            ok, cos4 * area / jnp.maximum(area0, 1e-20), 0.0
        )
        o_w = _xform_point(cam.camera_to_world, o_c)
        d_w = normalize(_xform_vector(cam.camera_to_world, d_c))
        return o_w, d_w, weight

    if cam.cam_type == CAM_PERSPECTIVE:
        o = jnp.zeros_like(p_cam)
        d = normalize(p_cam)
    elif cam.cam_type == CAM_ORTHOGRAPHIC:
        o = p_cam
        d = jnp.broadcast_to(jnp.asarray([0.0, 0.0, 1.0], jnp.float32), p_cam.shape)
    else:  # environment: lat-long over the full sphere (pbrt environment.cpp)
        x, y = p_film[..., 0], p_film[..., 1]
        theta = jnp.pi * y / cam.full_res[1]
        phi = 2.0 * jnp.pi * x / cam.full_res[0]
        d = jnp.stack(
            [jnp.sin(theta) * jnp.cos(phi), jnp.cos(theta), jnp.sin(theta) * jnp.sin(phi)],
            axis=-1,
        )
        o = jnp.zeros_like(d)

    if cam.cam_type != CAM_ENVIRONMENT:
        # thin-lens depth of field (ProjectiveCamera lens code)
        def with_lens(o, d):
            lx, ly = concentric_sample_disk(u_lens[..., 0], u_lens[..., 1])
            p_lens = cam.lens_radius * jnp.stack([lx, ly], axis=-1)
            ft = cam.focal_distance / jnp.where(d[..., 2] == 0.0, 1.0, d[..., 2])
            p_focus = o + ft[..., None] * d
            o_new = jnp.concatenate([p_lens, jnp.zeros_like(p_lens[..., :1])], axis=-1)
            # orthographic keeps its z origin
            o_new = o_new + o * jnp.asarray([0.0, 0.0, 1.0], jnp.float32)
            d_new = normalize(p_focus - o_new)
            return o_new, d_new

        o_l, d_l = with_lens(o, d)
        use_lens = cam.lens_radius > 0.0
        o = jnp.where(use_lens, o_l, o)
        d = jnp.where(use_lens, d_l, d)

    o_w = _xform_point(cam.camera_to_world, o)
    d_w = normalize(_xform_vector(cam.camera_to_world, d))
    weight = jnp.ones(p_film.shape[:-1], jnp.float32)
    return o_w, d_w, weight


def ray_differentials(cam: CompiledCamera, p_film):
    """Camera::GenerateRayDifferential's offset-ray deltas (camera.cpp):
    world-space (d_origin/dx, d_dir/dx, d_origin/dy, d_dir/dy) for a
    +1-raster-pixel step. Pinhole-analytic; the thin-lens origin jitter
    is ignored exactly as pbrt's differentials assume the primary ray."""
    zero = jnp.zeros(p_film.shape[:-1] + (3,), jnp.float32)
    if cam.cam_type == CAM_ENVIRONMENT:
        x, y = p_film[..., 0], p_film[..., 1]

        def dir_at(xx, yy):
            theta = jnp.pi * yy / cam.full_res[1]
            phi = 2.0 * jnp.pi * xx / cam.full_res[0]
            d = jnp.stack(
                [jnp.sin(theta) * jnp.cos(phi), jnp.cos(theta),
                 jnp.sin(theta) * jnp.sin(phi)], axis=-1)
            return normalize(_xform_vector(cam.camera_to_world, d))

        base = dir_at(x, y)
        return (zero, dir_at(x + 1.0, y) - base,
                zero, dir_at(x, y + 1.0) - base)

    p_raster = jnp.concatenate(
        [p_film, jnp.zeros_like(p_film[..., :1])], axis=-1)
    p_cam = _xform_point(cam.raster_to_camera, p_raster)
    # raster steps as PROJECTED POINT DIFFERENCES (camera.cpp shifts the
    # CameraSample by one pixel): raster_to_camera is projective, so
    # pushing the step through the linear part alone mis-scales it
    step_x = jnp.asarray([1.0, 0.0, 0.0], jnp.float32)
    step_y = jnp.asarray([0.0, 1.0, 0.0], jnp.float32)
    dx_cam = _xform_point(cam.raster_to_camera, p_raster + step_x) - p_cam
    dy_cam = _xform_point(cam.raster_to_camera, p_raster + step_y) - p_cam
    # realistic: the thin-lens proxy matrices stand in for the primary
    # ray's differentials (pbrt likewise assumes the unperturbed ray)
    if cam.cam_type in (CAM_PERSPECTIVE, CAM_REALISTIC):
        d0 = normalize(p_cam)
        ddx = _xform_vector(cam.camera_to_world, normalize(p_cam + dx_cam) - d0)
        ddy = _xform_vector(cam.camera_to_world, normalize(p_cam + dy_cam) - d0)
        return zero, ddx, zero, ddy
    # orthographic: direction constant, origin shifts
    dox = _xform_vector(cam.camera_to_world, dx_cam)
    doy = _xform_vector(cam.camera_to_world, dy_cam)
    return dox, zero, doy, zero
