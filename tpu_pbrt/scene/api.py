"""The pbrt scene-description API state machine.

Capability match for pbrt-v3 src/core/api.{h,cpp}: pbrtInit/pbrtCleanup,
the CTM stack (Translate/Rotate/.../LookAt/CoordinateSystem), attribute and
transform stacks, object instancing, named materials/media, texture
registration, and the Make* plugin-factory seam (string-dispatched plugin
registries) through which the `tpupath` integrator is selected by unmodified
.pbrt scene files.

State-machine rules (matching pbrt's APISTATE checks): directives are only
legal in the Options block (before WorldBegin) or the World block, and this
is enforced with pbrt's error messages.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from tpu_pbrt.core import transform as xf
from tpu_pbrt.core.transform import Transform
from tpu_pbrt.scene.paramset import ParamSet, TextureParams
from tpu_pbrt.utils.error import Error, Warning, set_quiet

# -- active-transform bits (pbrt api.cpp) ---------------------------------
MAX_TRANSFORMS = 2
START_TRANSFORM_BITS = 1 << 0
END_TRANSFORM_BITS = 1 << 1
ALL_TRANSFORMS_BITS = (1 << MAX_TRANSFORMS) - 1

_STATE_UNINIT, _STATE_OPTIONS, _STATE_WORLD = 0, 1, 2


class TransformSet:
    """Pair of CTMs (start/end time) for animated transforms."""

    __slots__ = ("t",)

    def __init__(self, t=None):
        self.t = t if t is not None else [Transform(), Transform()]

    def copy(self):
        return TransformSet([Transform(x.m, x.m_inv) for x in self.t])

    def __getitem__(self, i):
        return self.t[i]

    def __setitem__(self, i, v):
        self.t[i] = v

    def is_animated(self):
        return not np.allclose(self.t[0].m, self.t[1].m)

    def inverse(self):
        return TransformSet([x.inverse() for x in self.t])


@dataclass
class MaterialRecord:
    """A material captured at directive time with textures resolved
    against the then-active texture scope (pbrt MakeMaterial)."""

    type: str
    params: Dict[str, Any] = field(default_factory=dict)
    name: str = ""  # for named materials


@dataclass
class ShapeRecord:
    type: str
    params: ParamSet
    object_to_world: TransformSet
    reverse_orientation: bool
    material: Optional[MaterialRecord]
    area_light: Optional[ParamSet]
    area_light_to_world: Optional[Transform]
    inside_medium: str
    outside_medium: str
    scene_dir: str


@dataclass
class LightRecord:
    type: str
    params: ParamSet
    light_to_world: Transform
    medium: str
    scene_dir: str


@dataclass
class InstanceUse:
    name: str
    instance_to_world: TransformSet


@dataclass
class MediumRecord:
    type: str
    params: ParamSet
    medium_to_world: Transform


@dataclass
class GraphicsState:
    float_textures: Dict[str, Any] = field(default_factory=dict)
    spectrum_textures: Dict[str, Any] = field(default_factory=dict)
    named_materials: Dict[str, MaterialRecord] = field(default_factory=dict)
    current_material: MaterialRecord = field(
        default_factory=lambda: MaterialRecord("matte", {"Kd": ("const", np.array([0.5, 0.5, 0.5]))})
    )
    area_light: Optional[ParamSet] = None
    area_light_name: str = ""
    reverse_orientation: bool = False
    current_inside_medium: str = ""
    current_outside_medium: str = ""

    def copy(self):
        g = GraphicsState(
            float_textures=dict(self.float_textures),
            spectrum_textures=dict(self.spectrum_textures),
            named_materials=dict(self.named_materials),
            current_material=self.current_material,
            area_light=self.area_light,
            area_light_name=self.area_light_name,
            reverse_orientation=self.reverse_orientation,
            current_inside_medium=self.current_inside_medium,
            current_outside_medium=self.current_outside_medium,
        )
        return g


@dataclass
class RenderOptions:
    """Everything accumulated before/within the world block
    (pbrt api.cpp RenderOptions)."""

    transform_start_time: float = 0.0
    transform_end_time: float = 1.0
    filter_name: str = "box"
    filter_params: ParamSet = field(default_factory=ParamSet)
    film_name: str = "image"
    film_params: ParamSet = field(default_factory=ParamSet)
    sampler_name: str = "halton"
    sampler_params: ParamSet = field(default_factory=ParamSet)
    accelerator_name: str = "bvh"
    accelerator_params: ParamSet = field(default_factory=ParamSet)
    integrator_name: str = "path"
    integrator_params: ParamSet = field(default_factory=ParamSet)
    camera_name: str = "perspective"
    camera_params: ParamSet = field(default_factory=ParamSet)
    camera_to_world: TransformSet = field(default_factory=TransformSet)
    named_media: Dict[str, MediumRecord] = field(default_factory=dict)
    camera_medium: str = ""
    shapes: List[ShapeRecord] = field(default_factory=list)
    lights: List[LightRecord] = field(default_factory=list)
    instances: Dict[str, List[ShapeRecord]] = field(default_factory=dict)
    instance_uses: List[InstanceUse] = field(default_factory=list)
    have_scattering_media: bool = False


@dataclass
class Options:
    """CLI options (pbrt core/pbrt.h Options struct)."""

    n_threads: int = 0
    quick_render: bool = False
    quiet: bool = False
    verbose: bool = False
    image_file: str = ""
    crop_window: Optional[tuple] = None  # (x0,x1,y0,y1)
    mesh_shape: Optional[tuple] = None  # TPU-specific: device mesh shape
    spp_chunk: int = 0  # TPU-specific: samples per chunk (0 = auto)
    checkpoint_path: str = ""  # TPU-specific: film checkpoint for resume
    checkpoint_every: int = 0  # chunks between checkpoint writes (0 = off)
    multihost: bool = False  # bring up jax.distributed (multi-host DCN)


class PbrtAPI:
    """The directive state machine. One instance per parse
    (pbrt uses globals; we keep it instantiable for tests)."""

    def __init__(self, options: Optional[Options] = None):
        self.options = options or Options()
        self.state = _STATE_UNINIT
        self.cur_transform = TransformSet()
        self.active_transform_bits = ALL_TRANSFORMS_BITS
        self.named_coordinate_systems: Dict[str, TransformSet] = {}
        self.render_options = RenderOptions()
        self.graphics_state = GraphicsState()
        self.pushed_graphics_states: List[GraphicsState] = []
        self.pushed_transforms: List[TransformSet] = []
        self.pushed_active_transform_bits: List[int] = []
        self.current_instance: Optional[List[ShapeRecord]] = None
        self.scene_dir = "."
        self.scene: Any = None  # set by world_end
        #: submit/step seam (tpu_pbrt/serve): when True, WorldEnd compiles
        #: the scene and builds the integrator but does NOT run the
        #: render-to-completion loop — the pair lands in `self.compiled`
        #: for a scheduler (the render service) to drive chunk by chunk
        self.defer_render = False
        self.compiled: Any = None  # (CompiledScene, integrator) when deferred

    # -- state checks -----------------------------------------------------
    def _verify_initialized(self, func):
        if self.state == _STATE_UNINIT:
            Error(f"pbrtInit() must be before calling \"{func}()\". Ignoring.")

    def _verify_options(self, func):
        self._verify_initialized(func)
        if self.state == _STATE_WORLD:
            Error(f"Options cannot be set inside world block; \"{func}\" not allowed. Ignoring.")

    def _verify_world(self, func):
        self._verify_initialized(func)
        if self.state == _STATE_OPTIONS:
            Error(f"Scene description must be inside world block; \"{func}\" not allowed. Ignoring.")

    def _for_active_transforms(self, fn: Callable[[Transform], Transform]):
        for i in range(MAX_TRANSFORMS):
            if self.active_transform_bits & (1 << i):
                self.cur_transform[i] = fn(self.cur_transform[i])

    # -- init/cleanup -----------------------------------------------------
    def init(self):
        if self.state != _STATE_UNINIT:
            Error("pbrtInit() has already been called.")
        self.state = _STATE_OPTIONS
        set_quiet(self.options.quiet)

    def cleanup(self):
        if self.state == _STATE_UNINIT:
            Error("pbrtCleanup() called without pbrtInit().")
        elif self.state == _STATE_WORLD:
            Error("pbrtCleanup() called while inside world block.")
        self.state = _STATE_UNINIT

    # -- transforms -------------------------------------------------------
    def identity(self):
        self._verify_initialized("Identity")
        self._for_active_transforms(lambda t: Transform())

    def translate(self, dx, dy, dz):
        self._verify_initialized("Translate")
        self._for_active_transforms(lambda t: t * xf.translate([dx, dy, dz]))

    def rotate(self, angle, ax, ay, az):
        self._verify_initialized("Rotate")
        self._for_active_transforms(lambda t: t * xf.rotate(angle, [ax, ay, az]))

    def scale(self, sx, sy, sz):
        self._verify_initialized("Scale")
        self._for_active_transforms(lambda t: t * xf.scale(sx, sy, sz))

    def look_at(self, ex, ey, ez, lx, ly, lz, ux, uy, uz):
        self._verify_initialized("LookAt")
        # LookAt gives camera-to-world; CTM becomes world-to-camera
        self._for_active_transforms(lambda t: t * xf.look_at([ex, ey, ez], [lx, ly, lz], [ux, uy, uz]).inverse())

    def concat_transform(self, m16):
        self._verify_initialized("ConcatTransform")
        m = np.asarray(m16, dtype=np.float64).reshape(4, 4).T  # column-major in file
        self._for_active_transforms(lambda t: t * Transform(m))

    def transform(self, m16):
        self._verify_initialized("Transform")
        m = np.asarray(m16, dtype=np.float64).reshape(4, 4).T
        self._for_active_transforms(lambda t: Transform(m))

    def coordinate_system(self, name):
        self._verify_initialized("CoordinateSystem")
        self.named_coordinate_systems[name] = self.cur_transform.copy()

    def coord_sys_transform(self, name):
        self._verify_initialized("CoordSysTransform")
        if name in self.named_coordinate_systems:
            self.cur_transform = self.named_coordinate_systems[name].copy()
        else:
            Warning(f'Couldn\'t find named coordinate system "{name}"')

    def active_transform_all(self):
        self.active_transform_bits = ALL_TRANSFORMS_BITS

    def active_transform_start(self):
        self.active_transform_bits = START_TRANSFORM_BITS

    def active_transform_end(self):
        self.active_transform_bits = END_TRANSFORM_BITS

    def transform_times(self, start, end):
        self._verify_options("TransformTimes")
        self.render_options.transform_start_time = start
        self.render_options.transform_end_time = end

    # -- options ----------------------------------------------------------
    def pixel_filter(self, name, params):
        self._verify_options("PixelFilter")
        self.render_options.filter_name = name
        self.render_options.filter_params = params

    def film(self, name, params):
        self._verify_options("Film")
        self.render_options.film_name = name
        self.render_options.film_params = params

    def sampler(self, name, params):
        self._verify_options("Sampler")
        self.render_options.sampler_name = name
        self.render_options.sampler_params = params

    def accelerator(self, name, params):
        self._verify_options("Accelerator")
        self.render_options.accelerator_name = name
        self.render_options.accelerator_params = params

    def integrator(self, name, params):
        self._verify_options("Integrator")
        self.render_options.integrator_name = name
        self.render_options.integrator_params = params

    def camera(self, name, params):
        self._verify_options("Camera")
        self.render_options.camera_name = name
        self.render_options.camera_params = params
        self.render_options.camera_to_world = self.cur_transform.inverse()
        self.named_coordinate_systems["camera"] = self.render_options.camera_to_world.copy()
        self.render_options.camera_medium = self.graphics_state.current_outside_medium

    def make_named_medium(self, name, params):
        self._verify_initialized("MakeNamedMedium")
        mtype = params.find_one_string("type", "")
        if not mtype:
            Error('No parameter string "type" found in MakeNamedMedium')
        self.render_options.named_media[name] = MediumRecord(mtype, params, self.cur_transform[0])
        self.render_options.have_scattering_media = True

    def medium_interface(self, inside, outside):
        self._verify_initialized("MediumInterface")
        self.graphics_state.current_inside_medium = inside
        self.graphics_state.current_outside_medium = outside
        self.render_options.have_scattering_media = True

    # -- world block ------------------------------------------------------
    def world_begin(self):
        self._verify_options("WorldBegin")
        self.state = _STATE_WORLD
        self.cur_transform = TransformSet()
        self.active_transform_bits = ALL_TRANSFORMS_BITS
        self.named_coordinate_systems["world"] = self.cur_transform.copy()

    def attribute_begin(self):
        self._verify_world("AttributeBegin")
        self.pushed_graphics_states.append(self.graphics_state.copy())
        self.pushed_transforms.append(self.cur_transform.copy())
        self.pushed_active_transform_bits.append(self.active_transform_bits)

    def attribute_end(self):
        self._verify_world("AttributeEnd")
        if not self.pushed_graphics_states:
            Error("Unmatched AttributeEnd encountered.")
        self.graphics_state = self.pushed_graphics_states.pop()
        self.cur_transform = self.pushed_transforms.pop()
        self.active_transform_bits = self.pushed_active_transform_bits.pop()

    def transform_begin(self):
        self._verify_world("TransformBegin")
        self.pushed_transforms.append(self.cur_transform.copy())
        self.pushed_active_transform_bits.append(self.active_transform_bits)

    def transform_end(self):
        self._verify_world("TransformEnd")
        if not self.pushed_transforms:
            Error("Unmatched TransformEnd encountered.")
        self.cur_transform = self.pushed_transforms.pop()
        self.active_transform_bits = self.pushed_active_transform_bits.pop()

    def texture(self, name, type_name, tex_name, params):
        self._verify_world("Texture")
        from tpu_pbrt.scene import textures as tex_mod

        tp = TextureParams(params, ParamSet(), self.graphics_state.float_textures, self.graphics_state.spectrum_textures)
        if type_name == "float":
            if name in self.graphics_state.float_textures:
                Warning(f'Texture "{name}" being redefined')
            t = tex_mod.make_float_texture(tex_name, self.cur_transform[0], tp, self.scene_dir)
            if t is not None:
                self.graphics_state.float_textures[name] = t
        elif type_name in ("color", "spectrum"):
            if name in self.graphics_state.spectrum_textures:
                Warning(f'Texture "{name}" being redefined')
            t = tex_mod.make_spectrum_texture(tex_name, self.cur_transform[0], tp, self.scene_dir)
            if t is not None:
                self.graphics_state.spectrum_textures[name] = t
        else:
            Error(f'Texture type "{type_name}" unknown.')

    def material(self, name, params):
        self._verify_world("Material")
        from tpu_pbrt.scene import materials as mat_mod

        tp = TextureParams(ParamSet(), params, self.graphics_state.float_textures, self.graphics_state.spectrum_textures)
        self.graphics_state.current_material = mat_mod.make_material(name, tp, self, self.scene_dir)

    def make_named_material(self, name, params):
        self._verify_world("MakeNamedMaterial")
        from tpu_pbrt.scene import materials as mat_mod

        mat_type = params.find_one_string("type", "")
        if not mat_type:
            Error('No parameter string "type" found in MakeNamedMaterial')
        tp = TextureParams(ParamSet(), params, self.graphics_state.float_textures, self.graphics_state.spectrum_textures)
        if name in self.graphics_state.named_materials:
            Warning(f'Named material "{name}" redefined.')
        rec = mat_mod.make_material(mat_type, tp, self, self.scene_dir)
        rec.name = name
        self.graphics_state.named_materials[name] = rec

    def named_material(self, name):
        self._verify_world("NamedMaterial")
        if name not in self.graphics_state.named_materials:
            Error(f'NamedMaterial "{name}" unknown.')
        self.graphics_state.current_material = self.graphics_state.named_materials[name]

    def light_source(self, name, params):
        self._verify_world("LightSource")
        self.render_options.lights.append(
            LightRecord(name, params, self.cur_transform[0], self.graphics_state.current_outside_medium, self.scene_dir)
        )

    def area_light_source(self, name, params):
        self._verify_world("AreaLightSource")
        self.graphics_state.area_light = params
        self.graphics_state.area_light_name = name

    def shape(self, name, params):
        self._verify_world("Shape")
        rec = ShapeRecord(
            type=name,
            params=params,
            object_to_world=self.cur_transform.copy(),
            reverse_orientation=self.graphics_state.reverse_orientation,
            material=self.graphics_state.current_material,
            area_light=self.graphics_state.area_light,
            area_light_to_world=self.cur_transform[0] if self.graphics_state.area_light is not None else None,
            inside_medium=self.graphics_state.current_inside_medium,
            outside_medium=self.graphics_state.current_outside_medium,
            scene_dir=self.scene_dir,
        )
        if self.current_instance is not None:
            if self.graphics_state.area_light is not None:
                Warning("Area lights not supported with object instancing; ignoring.")
                rec.area_light = None
            self.current_instance.append(rec)
        else:
            self.render_options.shapes.append(rec)

    def reverse_orientation(self):
        self._verify_world("ReverseOrientation")
        self.graphics_state.reverse_orientation = not self.graphics_state.reverse_orientation

    def object_begin(self, name):
        self._verify_world("ObjectBegin")
        self.attribute_begin()
        if self.current_instance is not None:
            Error("ObjectBegin called inside of instance definition")
        self.render_options.instances[name] = []
        self.current_instance = self.render_options.instances[name]

    def object_end(self):
        self._verify_world("ObjectEnd")
        if self.current_instance is None:
            Error("ObjectEnd called outside of instance definition")
        self.current_instance = None
        self.attribute_end()

    def object_instance(self, name):
        self._verify_world("ObjectInstance")
        if self.current_instance is not None:
            Error("ObjectInstance can't be called inside instance definition")
        if name not in self.render_options.instances:
            Error(f'Unable to find instance named "{name}"')
        self.render_options.instance_uses.append(InstanceUse(name, self.cur_transform.copy()))

    def world_end(self, render: bool = True):
        self._verify_world("WorldEnd")
        while self.pushed_graphics_states:
            Warning("Missing end to AttributeBegin")
            self.pushed_graphics_states.pop()
            self.pushed_transforms.pop()
            self.pushed_active_transform_bits.pop()
        while self.pushed_transforms:
            Warning("Missing end to TransformBegin")
            self.pushed_transforms.pop()
            self.pushed_active_transform_bits.pop()
        self.state = _STATE_OPTIONS
        result = None
        if render:
            from tpu_pbrt.scene.compiler import compile_scene
            from tpu_pbrt.integrators import make_integrator

            self.scene = compile_scene(self)
            integrator = make_integrator(self.render_options.integrator_name,
                                         self.render_options.integrator_params, self.scene, self.options)
            if self.defer_render:
                # serve seam: hand the compiled pair to the caller's
                # scheduler instead of running to completion here
                self.compiled = result = (self.scene, integrator)
            else:
                self.result = result = integrator.render(self.scene)
        # reset world state for a possible next frame (pbrt api.cpp WorldEnd:
        # fresh RenderOptions, identity CTM, default graphics state); the
        # completed frame stays inspectable via last_render_options
        prev = self.last_render_options = self.render_options
        self.render_options = RenderOptions(
            transform_start_time=prev.transform_start_time,
            transform_end_time=prev.transform_end_time,
        )
        self.graphics_state = GraphicsState()
        self.cur_transform = TransformSet()
        self.active_transform_bits = ALL_TRANSFORMS_BITS
        self.named_coordinate_systems.clear()
        return result


# -- module-level convenience entry points --------------------------------

def pbrt_init(options: Optional[Options] = None) -> PbrtAPI:
    api = PbrtAPI(options)
    api.init()
    return api


def pbrt_cleanup(api: PbrtAPI):
    api.cleanup()


def parse_string(contents: str, api: Optional[PbrtAPI] = None, render: bool = False) -> PbrtAPI:
    from tpu_pbrt.scene.parser import parse_tokens
    from tpu_pbrt.scene.lexer import Tokenizer

    if api is None:
        api = pbrt_init()
    parse_tokens(Tokenizer(contents), api, render=render)
    return api


def parse_file(path: str, api: Optional[PbrtAPI] = None, render: bool = False) -> PbrtAPI:
    from tpu_pbrt.scene.parser import parse_tokens
    from tpu_pbrt.scene.lexer import Tokenizer

    if api is None:
        api = pbrt_init()
    api.scene_dir = os.path.dirname(os.path.abspath(path))
    parse_tokens(Tokenizer.from_file(path), api, render=render)
    return api


def render_file(path: str, options: Optional[Options] = None):
    """pbrt main(): parse + render, returns the integrator result."""
    api = pbrt_init(options)
    parse_file(path, api, render=True)
    return getattr(api, "result", None)


def compile_file(path: str, options: Optional[Options] = None):
    """Parse + compile a .pbrt scene file WITHOUT rendering it: returns
    (CompiledScene, integrator) — the resident-scene unit the render
    service caches and schedules (submit/step instead of
    run-to-completion)."""
    api = pbrt_init(options)
    api.defer_render = True
    parse_file(path, api, render=True)
    if api.compiled is None:
        from tpu_pbrt.utils.error import Error

        Error(f"scene file {path!r} has no WorldEnd; nothing to compile")
    return api.compiled


def compile_string(contents: str, options: Optional[Options] = None):
    """compile_file for in-memory scene text (the JSONL daemon's inline
    submit payload)."""
    api = pbrt_init(options)
    api.defer_render = True
    parse_string(contents, api, render=True)
    if api.compiled is None:
        from tpu_pbrt.utils.error import Error

        Error("scene text has no WorldEnd; nothing to compile")
    return api.compiled
