"""Texture plugin factories.

Capability match for pbrt-v3 src/textures/ (constant, scale, mix, bilerp,
imagemap, checkerboard, dots, fbm, wrinkled, marble, windy, uv) and the
Create*Texture factories in api.cpp's MakeFloatTexture/MakeSpectrumTexture.

Textures are captured as declarative nodes (nested tuples/dicts) at parse
time; the scene compiler lowers them to device-evaluable forms: constants
fold into material parameter slots, image maps go into a mip-mapped texture
atlas, procedural nodes are evaluated by jitted noise code at shade time.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from tpu_pbrt.core.transform import Transform
from tpu_pbrt.scene.paramset import TextureParams
from tpu_pbrt.utils.error import Error, Warning
from tpu_pbrt.utils.fileutil import resolve_filename


def _mapping2d(tp: TextureParams, tex_to_world: Transform) -> dict:
    """pbrt TextureMapping2D factory (texture.cpp GetMapping2D)."""
    m = {"type": tp.find_one_string("mapping", "uv")}
    if m["type"] == "uv":
        m.update(
            su=tp.find_one_float("uscale", 1.0),
            sv=tp.find_one_float("vscale", 1.0),
            du=tp.find_one_float("udelta", 0.0),
            dv=tp.find_one_float("vdelta", 0.0),
        )
    elif m["type"] == "planar":
        m.update(
            v1=np.asarray(tp.geom.find_one_vector3("v1", [1, 0, 0])),
            v2=np.asarray(tp.geom.find_one_vector3("v2", [0, 1, 0])),
            du=tp.find_one_float("udelta", 0.0),
            dv=tp.find_one_float("vdelta", 0.0),
        )
    elif m["type"] in ("spherical", "cylindrical"):
        m["world_to_texture"] = tex_to_world.inverse()
    else:
        Error(f'2D texture mapping "{m["type"]}" unknown')
    return m


def _mapping3d(tp: TextureParams, tex_to_world: Transform) -> dict:
    return {"world_to_texture": tex_to_world.inverse()}


def _imagemap(kind: str, tex_to_world, tp: TextureParams, scene_dir: str) -> tuple:
    filename = tp.find_one_string("filename", "")
    path = resolve_filename(filename, scene_dir)
    return (
        "imagemap",
        {
            "kind": kind,
            "filename": path,
            "mapping": _mapping2d(tp, tex_to_world),
            "trilerp": tp.find_one_bool("trilinear", False),
            "max_aniso": tp.find_one_float("maxanisotropy", 8.0),
            "wrap": tp.find_one_string("wrap", "repeat"),
            "scale": tp.find_one_float("scale", 1.0),
            "gamma": tp.find_one_bool(
                "gamma", filename.lower().endswith((".tga", ".png", ".jpg", ".jpeg"))
            ),
        },
    )


def _noise_common(name, kind, tex_to_world, tp):
    d = {
        "kind": kind,
        "mapping": _mapping3d(tp, tex_to_world),
        "octaves": tp.find_one_int("octaves", 8),
        "roughness": tp.find_one_float("roughness", 0.5),
    }
    if name == "marble":
        d["scale"] = tp.find_one_float("scale", 1.0)
        d["variation"] = tp.find_one_float("variation", 0.2)
    return (name, d)


def _make_texture(name: str, kind: str, tex_to_world: Transform, tp: TextureParams, scene_dir: str):
    get = tp.get_float_texture if kind == "float" else tp.get_spectrum_texture
    one = 1.0 if kind == "float" else np.ones(3)
    zero = 0.0 if kind == "float" else np.zeros(3)
    if name == "constant":
        v = tp.find_one_float("value", 1.0) if kind == "float" else tp.find_one_spectrum("value", 1.0)
        return ("constf", v) if kind == "float" else ("const", v)
    if name == "scale":
        return ("scale", get("tex1", one), get("tex2", one))
    if name == "mix":
        return ("mix", get("tex1", zero), get("tex2", one), tp.get_float_texture("amount", 0.5))
    if name == "bilerp":
        return (
            "bilerp",
            {
                "v00": get("v00", zero),
                "v01": get("v01", one),
                "v10": get("v10", zero),
                "v11": get("v11", one),
                "mapping": _mapping2d(tp, tex_to_world),
            },
        )
    if name == "imagemap":
        return _imagemap(kind, tex_to_world, tp, scene_dir)
    if name == "uv":
        return ("uv", {"mapping": _mapping2d(tp, tex_to_world)})
    if name == "checkerboard":
        dim = tp.find_one_int("dimension", 2)
        if dim not in (2, 3):
            Error(f"{dim} dimensional checkerboard texture not supported")
        d = {
            "dim": dim,
            "tex1": get("tex1", one),
            "tex2": get("tex2", zero),
            "aamode": tp.find_one_string("aamode", "closedform"),
        }
        d["mapping"] = _mapping2d(tp, tex_to_world) if dim == 2 else _mapping3d(tp, tex_to_world)
        return ("checkerboard", d)
    if name == "dots":
        return (
            "dots",
            {
                "inside": get("inside", one),
                "outside": get("outside", zero),
                "mapping": _mapping2d(tp, tex_to_world),
            },
        )
    if name in ("fbm", "wrinkled", "windy", "marble"):
        return _noise_common(name, kind, tex_to_world, tp)
    if name == "ptex":
        Warning('ptex textures are approximated as constant gray (convert to imagemap for full fidelity)')
        return ("constf", 0.5) if kind == "float" else ("const", np.full(3, 0.5))
    Warning(f'{kind} texture "{name}" unknown; using constant')
    return ("constf", 0.5) if kind == "float" else ("const", np.full(3, 0.5))


def make_float_texture(name: str, tex_to_world: Transform, tp: TextureParams, scene_dir: str = "."):
    return _make_texture(name, "float", tex_to_world, tp, scene_dir)


def make_spectrum_texture(name: str, tex_to_world: Transform, tp: TextureParams, scene_dir: str = "."):
    return _make_texture(name, "spectrum", tex_to_world, tp, scene_dir)
