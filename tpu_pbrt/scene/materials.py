"""Material plugin factories.

Capability match for pbrt-v3 src/materials/ and api.cpp MakeMaterial: every
material type resolves its parameters (textures included) at directive time
against the then-active texture scope, producing a MaterialRecord whose
params dict holds texture nodes. The scene compiler lowers records into the
SoA material table (type enum + parameter/texture-id slots) consumed by the
wavefront shading kernel.

Parameter names and defaults follow the corresponding Create*Material
factories (e.g. matte: Kd=0.5, sigma=0; glass: Kr=1 Kt=1 eta=1.5; metal:
copper eta/k, roughness=0.01; uber/substrate/plastic/translucent/mix/
mirror/fourier/hair/disney/subsurface/kdsubsurface per upstream).
"""

from __future__ import annotations

import numpy as np

from tpu_pbrt.core.spectrum import NAMED_SPECTRA_RGB
from tpu_pbrt.scene.paramset import TextureParams
from tpu_pbrt.utils.error import Warning


def make_material(name: str, tp: TextureParams, api=None, scene_dir: str = "."):
    from tpu_pbrt.scene.api import MaterialRecord

    p = {}
    if name in ("", "none"):
        return MaterialRecord("none", {})
    if name == "matte":
        p["Kd"] = tp.get_spectrum_texture("Kd", 0.5)
        p["sigma"] = tp.get_float_texture("sigma", 0.0)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "plastic":
        p["Kd"] = tp.get_spectrum_texture("Kd", 0.25)
        p["Ks"] = tp.get_spectrum_texture("Ks", 0.25)
        p["roughness"] = tp.get_float_texture("roughness", 0.1)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "metal":
        p["eta"] = tp.get_spectrum_texture("eta", NAMED_SPECTRA_RGB["metal-cu-eta"])
        p["k"] = tp.get_spectrum_texture("k", NAMED_SPECTRA_RGB["metal-cu-k"])
        p["roughness"] = tp.get_float_texture("roughness", 0.01)
        p["uroughness"] = tp.get_float_texture_or_none("uroughness")
        p["vroughness"] = tp.get_float_texture_or_none("vroughness")
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "glass":
        p["Kr"] = tp.get_spectrum_texture("Kr", 1.0)
        p["Kt"] = tp.get_spectrum_texture("Kt", 1.0)
        p["eta"] = tp.get_float_texture("eta", tp.find_one_float("index", 1.5))
        p["uroughness"] = tp.get_float_texture("uroughness", 0.0)
        p["vroughness"] = tp.get_float_texture("vroughness", 0.0)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "mirror":
        p["Kr"] = tp.get_spectrum_texture("Kr", 0.9)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "translucent":
        p["Kd"] = tp.get_spectrum_texture("Kd", 0.25)
        p["Ks"] = tp.get_spectrum_texture("Ks", 0.25)
        p["reflect"] = tp.get_spectrum_texture("reflect", 0.5)
        p["transmit"] = tp.get_spectrum_texture("transmit", 0.5)
        p["roughness"] = tp.get_float_texture("roughness", 0.1)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "uber":
        p["Kd"] = tp.get_spectrum_texture("Kd", 0.25)
        p["Ks"] = tp.get_spectrum_texture("Ks", 0.25)
        p["Kr"] = tp.get_spectrum_texture("Kr", 0.0)
        p["Kt"] = tp.get_spectrum_texture("Kt", 0.0)
        p["roughness"] = tp.get_float_texture("roughness", 0.1)
        p["uroughness"] = tp.get_float_texture_or_none("uroughness")
        p["vroughness"] = tp.get_float_texture_or_none("vroughness")
        p["eta"] = tp.get_float_texture("eta", tp.find_one_float("index", 1.5))
        p["opacity"] = tp.get_spectrum_texture("opacity", 1.0)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "substrate":
        p["Kd"] = tp.get_spectrum_texture("Kd", 0.5)
        p["Ks"] = tp.get_spectrum_texture("Ks", 0.5)
        p["uroughness"] = tp.get_float_texture("uroughness", 0.1)
        p["vroughness"] = tp.get_float_texture("vroughness", 0.1)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "mix":
        p["amount"] = tp.get_spectrum_texture("amount", 0.5)
        m1 = tp.find_one_string("namedmaterial1", "")
        m2 = tp.find_one_string("namedmaterial2", "")
        named = api.graphics_state.named_materials if api is not None else {}
        if m1 not in named or m2 not in named:
            Warning(f'Named material(s) "{m1}"/"{m2}" for mix material not found; using matte')
            return make_material("matte", tp, api, scene_dir)
        p["material1"] = named[m1]
        p["material2"] = named[m2]
    elif name == "fourier":
        p["bsdffile"] = tp.find_one_string("bsdffile", "")
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name == "hair":
        p["sigma_a"] = tp.get_spectrum_texture_or_none("sigma_a")
        p["color"] = tp.get_spectrum_texture_or_none("color")
        p["eumelanin"] = tp.get_float_texture_or_none("eumelanin")
        p["pheomelanin"] = tp.get_float_texture_or_none("pheomelanin")
        p["eta"] = tp.get_float_texture("eta", 1.55)
        p["beta_m"] = tp.get_float_texture("beta_m", 0.3)
        p["beta_n"] = tp.get_float_texture("beta_n", 0.3)
        p["alpha"] = tp.get_float_texture("alpha", 2.0)
    elif name == "disney":
        p["color"] = tp.get_spectrum_texture("color", 0.5)
        for fname, dflt in [
            ("metallic", 0.0), ("eta", 1.5), ("roughness", 0.5), ("speculartint", 0.0),
            ("anisotropic", 0.0), ("sheen", 0.0), ("sheentint", 0.5), ("clearcoat", 0.0),
            ("clearcoatgloss", 1.0), ("spectrans", 0.0), ("flatness", 0.0), ("difftrans", 1.0),
        ]:
            p[fname] = tp.get_float_texture(fname, dflt)
        p["scatterdistance"] = tp.get_spectrum_texture("scatterdistance", 0.0)
        p["thin"] = tp.find_one_bool("thin", False)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    elif name in ("subsurface", "kdsubsurface"):
        if name == "subsurface":
            p["preset"] = tp.find_one_string("name", "")
            p["sigma_a"] = tp.get_spectrum_texture("sigma_a", np.array([0.0011, 0.0024, 0.014]))
            p["sigma_s"] = tp.get_spectrum_texture("sigma_prime_s", np.array([2.55, 3.21, 3.77]))
            p["scale"] = tp.find_one_float("scale", 1.0)
            p["g"] = tp.find_one_float("g", 0.0)
        else:
            p["Kd"] = tp.get_spectrum_texture("Kd", 0.5)
            p["mfp"] = tp.get_spectrum_texture("mfp", 1.0)
        p["eta"] = tp.get_float_texture("eta", 1.33)
        p["Kr"] = tp.get_spectrum_texture("Kr", 1.0)
        p["Kt"] = tp.get_spectrum_texture("Kt", 1.0)
        p["uroughness"] = tp.get_float_texture("uroughness", 0.0)
        p["vroughness"] = tp.get_float_texture("vroughness", 0.0)
        p["remaproughness"] = tp.find_one_bool("remaproughness", True)
        p["bumpmap"] = tp.get_float_texture_or_none("bumpmap")
    else:
        Warning(f'Material "{name}" unknown. Using "matte".')
        return make_material("matte", tp, api, scene_dir)
    from tpu_pbrt.scene.api import MaterialRecord as MR

    return MR(name, p)
