"""PLY mesh reader (ascii + binary little/big endian).

Capability match for pbrt-v3's src/ext/rply + shapes/plymesh.cpp
CreatePLYMesh: reads vertex positions, normals, uvs (u,v / s,t /
texture_u,texture_v aliases) and face indices (triangulating polygon fans),
returning numpy arrays for the TriangleMesh compiler.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_pbrt.utils.error import Error, Warning

_PLY_TYPES = {
    "char": ("i1", 1), "int8": ("i1", 1),
    "uchar": ("u1", 1), "uint8": ("u1", 1),
    "short": ("i2", 2), "int16": ("i2", 2),
    "ushort": ("u2", 2), "uint16": ("u2", 2),
    "int": ("i4", 4), "int32": ("i4", 4),
    "uint": ("u4", 4), "uint32": ("u4", 4),
    "float": ("f4", 4), "float32": ("f4", 4),
    "double": ("f8", 8), "float64": ("f8", 8),
}


def read_ply(path: str) -> Dict[str, Optional[np.ndarray]]:
    """Returns dict with 'vertices' (V,3) f64, 'indices' (T,3) i64, and
    optional 'normals' (V,3), 'uvs' (V,2), 'face_indices' (per-face int)."""
    with open(path, "rb") as f:
        data = f.read()

    # ---- header ----
    end = data.find(b"end_header")
    if not data.startswith(b"ply") or end < 0:
        Error(f"{path}: not a PLY file")
    end = data.find(b"\n", end) + 1
    header = data[:end].decode("ascii", errors="replace")
    body = data[end:]

    fmt = None
    elements: List[Tuple[str, int, list]] = []  # (name, count, [(prop, type, list_count_type|None)])
    for line in header.splitlines():
        parts = line.strip().split()
        if not parts:
            continue
        if parts[0] == "format":
            fmt = parts[1]
        elif parts[0] == "element":
            elements.append((parts[1], int(parts[2]), []))
        elif parts[0] == "property":
            if not elements:
                continue
            if parts[1] == "list":
                elements[-1][2].append((parts[4], parts[3], parts[2]))
            else:
                elements[-1][2].append((parts[2], parts[1], None))

    if fmt is None:
        Error(f"{path}: PLY missing format line")

    out: Dict[str, Optional[np.ndarray]] = {"vertices": None, "indices": None, "normals": None, "uvs": None, "face_indices": None}

    if fmt == "ascii":
        _read_ascii(body, elements, out, path)
    else:
        endian = "<" if fmt == "binary_little_endian" else ">"
        _read_binary(body, elements, out, path, endian)

    if out["vertices"] is None or out["indices"] is None:
        Error(f"{path}: PLY file missing vertices or faces")
    return out


def _collect_vertex(props: list, rows: np.ndarray, out, path):
    names = [p[0] for p in props]

    def col(*cands):
        for c in cands:
            if c in names:
                return rows[:, names.index(c)]
        return None

    x, y, z = col("x"), col("y"), col("z")
    if x is None or y is None or z is None:
        Error(f"{path}: PLY vertex element missing x/y/z")
    out["vertices"] = np.stack([x, y, z], axis=1).astype(np.float64)
    nx, ny, nz = col("nx"), col("ny"), col("nz")
    if nx is not None and ny is not None and nz is not None:
        out["normals"] = np.stack([nx, ny, nz], axis=1).astype(np.float64)
    u = col("u", "s", "texture_u", "texture_s")
    v = col("v", "t", "texture_v", "texture_t")
    if u is not None and v is not None:
        out["uvs"] = np.stack([u, v], axis=1).astype(np.float64)


def _triangulate(faces: List[List[int]], face_idx_vals: Optional[List[int]], out):
    tris = []
    fidx = []
    for i, fc in enumerate(faces):
        if len(fc) < 3:
            continue
        for k in range(1, len(fc) - 1):  # fan triangulation (rply/pbrt behavior)
            tris.append((fc[0], fc[k], fc[k + 1]))
            if face_idx_vals is not None:
                fidx.append(face_idx_vals[i])
    out["indices"] = np.asarray(tris, dtype=np.int64).reshape(-1, 3)
    if face_idx_vals is not None:
        out["face_indices"] = np.asarray(fidx, dtype=np.int64)


def _read_ascii(body: bytes, elements, out, path):
    toks = body.decode("ascii", errors="replace").split()
    pos = 0

    def take(n):
        nonlocal pos
        v = toks[pos : pos + n]
        pos += n
        return v

    for name, count, props in elements:
        if name == "vertex":
            rows = np.empty((count, len(props)), dtype=np.float64)
            for i in range(count):
                vals = []
                for pname, ptype, list_ct in props:
                    if list_ct is None:
                        vals.append(float(take(1)[0]))
                    else:
                        n = int(float(take(1)[0]))
                        take(n)
                        vals.append(0.0)
                rows[i] = vals
            _collect_vertex(props, rows, out, path)
        elif name == "face":
            faces = []
            fvals: List[int] = []
            has_fi = any(p[0] == "face_indices" for p in props)
            for i in range(count):
                fc = None
                fi = 0
                for pname, ptype, list_ct in props:
                    if list_ct is not None:
                        n = int(float(take(1)[0]))
                        idx = [int(float(t)) for t in take(n)]
                        if pname in ("vertex_indices", "vertex_index"):
                            fc = idx
                    else:
                        v = float(take(1)[0])
                        if pname == "face_indices":
                            fi = int(v)
                if fc is not None:
                    faces.append(fc)
                    fvals.append(fi)
            _triangulate(faces, fvals if has_fi else None, out)
        else:
            for i in range(count):  # skip unknown elements
                for pname, ptype, list_ct in props:
                    if list_ct is None:
                        take(1)
                    else:
                        n = int(float(take(1)[0]))
                        take(n)


def _read_binary(body: bytes, elements, out, path, endian):
    off = 0
    for name, count, props in elements:
        all_scalar = all(p[2] is None for p in props)
        if name == "vertex":
            if all_scalar:
                # fast path: fixed-stride struct
                dtype = np.dtype([(p[0], endian + _PLY_TYPES[p[1]][0]) for p in props])
                arr = np.frombuffer(body, dtype=dtype, count=count, offset=off)
                off += dtype.itemsize * count
                rows = np.stack([arr[p[0]].astype(np.float64) for p in props], axis=1)
                _collect_vertex(props, rows, out, path)
            else:
                # slow path: vertex element carrying list properties
                rows = np.empty((count, len(props)), dtype=np.float64)
                for i in range(count):
                    for j, (pname, ptype, ct_type) in enumerate(props):
                        if ct_type is None:
                            it_fmt, it_sz = _PLY_TYPES[ptype]
                            rows[i, j] = np.frombuffer(body, dtype=endian + it_fmt, count=1, offset=off)[0]
                            off += it_sz
                        else:
                            ct_fmt, ct_sz = _PLY_TYPES[ct_type]
                            n = int(np.frombuffer(body, dtype=endian + ct_fmt, count=1, offset=off)[0])
                            off += ct_sz + n * _PLY_TYPES[ptype][1]
                            rows[i, j] = 0.0
                _collect_vertex(props, rows, out, path)
        elif name == "face":
            faces = []
            fvals: List[int] = []
            has_fi = any(p[0] == "face_indices" for p in props)
            # fast path: single list property with uniform arity 3
            if len(props) == 1 and props[0][2] is not None:
                pname, ptype, ct_type = props[0]
                ct_fmt, ct_sz = _PLY_TYPES[ct_type]
                it_fmt, it_sz = _PLY_TYPES[ptype]
                first_n = int(np.frombuffer(body, dtype=endian + ct_fmt, count=1, offset=off)[0])
                stride = ct_sz + first_n * it_sz
                if count * stride <= len(body) - off:
                    raw = np.frombuffer(body, dtype=np.uint8, count=count * stride, offset=off)
                    counts = raw.reshape(count, stride)[:, :ct_sz].copy().view(endian + ct_fmt).ravel()
                    if np.all(counts == first_n):
                        idx = (
                            raw.reshape(count, stride)[:, ct_sz:]
                            .copy()
                            .view(endian + it_fmt)
                            .reshape(count, first_n)
                            .astype(np.int64)
                        )
                        off += count * stride
                        if first_n == 3:
                            out["indices"] = idx
                        else:
                            _triangulate([list(r) for r in idx], None, out)
                        continue
            # slow path: per-face parse
            for i in range(count):
                fc = None
                fi = 0
                for pname, ptype, ct_type in props:
                    if ct_type is not None:
                        ct_fmt, ct_sz = _PLY_TYPES[ct_type]
                        n = int(np.frombuffer(body, dtype=endian + ct_fmt, count=1, offset=off)[0])
                        off += ct_sz
                        it_fmt, it_sz = _PLY_TYPES[ptype]
                        idx = np.frombuffer(body, dtype=endian + it_fmt, count=n, offset=off).astype(np.int64)
                        off += n * it_sz
                        if pname in ("vertex_indices", "vertex_index"):
                            fc = list(idx)
                    else:
                        it_fmt, it_sz = _PLY_TYPES[ptype]
                        v = np.frombuffer(body, dtype=endian + it_fmt, count=1, offset=off)[0]
                        off += it_sz
                        if pname == "face_indices":
                            fi = int(v)
                if fc is not None:
                    faces.append(fc)
                    fvals.append(fi)
            if faces:
                _triangulate(faces, fvals if has_fi else None, out)
        else:
            # skip unknown fixed-stride elements; lists are walked
            for i in range(count):
                for pname, ptype, ct_type in props:
                    if ct_type is None:
                        off += _PLY_TYPES[ptype][1]
                    else:
                        ct_fmt, ct_sz = _PLY_TYPES[ct_type]
                        n = int(np.frombuffer(body, dtype=endian + ct_fmt, count=1, offset=off)[0])
                        off += ct_sz + n * _PLY_TYPES[ptype][1]


def write_ply(path: str, vertices: np.ndarray, indices: np.ndarray, normals: Optional[np.ndarray] = None):
    """Binary-little-endian PLY writer (used by scene generators/tests)."""
    v = np.asarray(vertices, dtype=np.float32)
    f = np.asarray(indices, dtype=np.int32)
    with open(path, "wb") as fh:
        props = "property float x\nproperty float y\nproperty float z\n"
        if normals is not None:
            props += "property float nx\nproperty float ny\nproperty float nz\n"
        fh.write(
            (
                "ply\nformat binary_little_endian 1.0\n"
                f"element vertex {len(v)}\n{props}"
                f"element face {len(f)}\n"
                "property list uchar int vertex_indices\nend_header\n"
            ).encode("ascii")
        )
        if normals is not None:
            n = np.asarray(normals, dtype=np.float32)
            fh.write(np.hstack([v, n]).astype("<f4").tobytes())
        else:
            fh.write(v.astype("<f4").tobytes())
        rec = np.empty((len(f), 13), dtype=np.uint8)
        rec[:, 0] = 3
        rec[:, 1:] = f.astype("<i4").view(np.uint8).reshape(len(f), 12)
        fh.write(rec.tobytes())
