""".pbrt tokenizer.

Capability match for pbrt-v3 src/core/parser.cpp's hand-written Tokenizer:
produces directive identifiers, quoted strings, numbers and brackets;
'#' comments to end of line; tracks file/line for error reporting; Include
is handled by the parser pushing a nested Tokenizer.
"""

from __future__ import annotations

import os
from typing import Iterator, NamedTuple, Optional

from tpu_pbrt.utils.error import Error


class Token(NamedTuple):
    kind: str  # 'ident' | 'string' | 'number' | 'lbrack' | 'rbrack'
    value: object
    filename: str
    line: int


class Tokenizer:
    def __init__(self, contents: str, filename: str = "<string>"):
        self.s = contents
        self.filename = filename
        self.pos = 0
        self.line = 1
        self.n = len(contents)

    @classmethod
    def from_file(cls, path: str) -> "Tokenizer":
        with open(path, "r", errors="replace") as f:
            return cls(f.read(), path)

    def __iter__(self) -> Iterator[Token]:
        while True:
            t = self.next()
            if t is None:
                return
            yield t

    def next(self) -> Optional[Token]:
        s, n = self.s, self.n
        # skip whitespace + comments
        while self.pos < n:
            c = s[self.pos]
            if c == "\n":
                self.line += 1
                self.pos += 1
            elif c in " \t\r":
                self.pos += 1
            elif c == "#":
                while self.pos < n and s[self.pos] != "\n":
                    self.pos += 1
            else:
                break
        if self.pos >= n:
            return None
        c = s[self.pos]
        if c == "[":
            self.pos += 1
            return Token("lbrack", "[", self.filename, self.line)
        if c == "]":
            self.pos += 1
            return Token("rbrack", "]", self.filename, self.line)
        if c == '"':
            start_line = self.line
            self.pos += 1
            out = []
            while self.pos < n and s[self.pos] != '"':
                ch = s[self.pos]
                if ch == "\n":
                    Error(f"{self.filename}:{self.line}: newline in quoted string")
                if ch == "\\" and self.pos + 1 < n:
                    self.pos += 1
                    esc = s[self.pos]
                    out.append({"n": "\n", "t": "\t", "\\": "\\", '"': '"', "r": "\r", "b": "\b", "f": "\f", "'": "'"}.get(esc, esc))
                else:
                    out.append(ch)
                self.pos += 1
            if self.pos >= n:
                Error(f"{self.filename}:{start_line}: unterminated string")
            self.pos += 1
            return Token("string", "".join(out), self.filename, start_line)
        # number or identifier: read until delimiter
        start = self.pos
        while self.pos < n and s[self.pos] not in ' \t\r\n"[]#':
            self.pos += 1
        word = s[start : self.pos]
        try:
            v = float(word)
            return Token("number", v, self.filename, self.line)
        except ValueError:
            return Token("ident", word, self.filename, self.line)


def resolve_include(path: str, current_file: str) -> str:
    """pbrt resolves Include paths relative to the including file's dir."""
    if os.path.isabs(path):
        return path
    base = os.path.dirname(os.path.abspath(current_file))
    return os.path.join(base, path)
