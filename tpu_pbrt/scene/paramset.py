"""Typed parameter lists for scene directives.

Capability match for pbrt-v3 src/core/paramset.{h,cpp}: ParamSet holds typed
name->value lists declared as "type name" strings in .pbrt files
(bool/integer/float/point2/vector2/point3/vector3/normal/spectrum/rgb/color/
xyz/blackbody/string/texture), with Find*/FindOne* lookups and defaults, and
TextureParams which layers texture lookup over material+geometry param sets.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from tpu_pbrt.core import spectrum as spec
from tpu_pbrt.utils.error import Warning as warn
from tpu_pbrt.utils.fileutil import resolve_filename

# declared-type -> canonical storage kind
_TYPE_KINDS = {
    "bool": "bool",
    "integer": "int",
    "float": "float",
    "point2": "point2",
    "vector2": "vector2",
    "point3": "point3",
    "point": "point3",
    "vector3": "vector3",
    "vector": "vector3",
    "normal": "normal",
    "normal3": "normal",
    "string": "string",
    "texture": "texture",
    "rgb": "spectrum",
    "color": "spectrum",
    "xyz": "spectrum",
    "blackbody": "spectrum",
    "spectrum": "spectrum",
}


class ParamSet:
    """Typed name->values container with pbrt lookup semantics."""

    def __init__(self):
        self._params: Dict[str, tuple] = {}  # name -> (kind, values)
        self._looked_up: set = set()

    # -- construction -----------------------------------------------------
    def add(self, decl: str, values: Sequence, scene_dir: str = "."):
        """Add a parameter from its '.pbrt' declaration string, e.g.
        add("float radius", [1.0])."""
        from tpu_pbrt.utils.error import Error

        parts = decl.strip().split()
        if len(parts) != 2:
            Error(f"malformed parameter declaration {decl!r}")
        type_name, name = parts
        kind = _TYPE_KINDS.get(type_name)
        if kind is None:
            Error(f"unknown parameter type {type_name!r} in {decl!r}")
        vals = self._convert(type_name, kind, name, list(values), scene_dir)
        self._params[name] = (kind, vals)

    def _convert(self, type_name, kind, name, values, scene_dir):
        if kind == "bool":
            out = []
            for v in values:
                if isinstance(v, str):
                    out.append(v == "true")
                else:
                    out.append(bool(v))
            return out
        if kind == "int":
            return [int(v) for v in values]
        if kind == "float":
            return [float(v) for v in values]
        from tpu_pbrt.utils.error import Error

        if kind in ("point2", "vector2"):
            a = np.asarray([float(v) for v in values], dtype=np.float64)
            if a.size % 2:
                Error(f"parameter {name!r}: odd value count for {kind}")
            return a.reshape(-1, 2)
        if kind in ("point3", "vector3", "normal"):
            a = np.asarray([float(v) for v in values], dtype=np.float64)
            if a.size % 3:
                Error(f"parameter {name!r}: value count not multiple of 3")
            return a.reshape(-1, 3)
        if kind in ("string", "texture"):
            return [str(v) for v in values]
        if kind == "spectrum":
            return self._convert_spectrum(type_name, name, values, scene_dir)
        raise AssertionError(kind)

    @staticmethod
    def _convert_spectrum(type_name, name, values, scene_dir):
        """All spectral inputs canonicalize to linear RGB rows (n,3)."""
        from tpu_pbrt.utils.error import Error

        if type_name in ("rgb", "color"):
            a = np.asarray([float(v) for v in values], dtype=np.float64)
            if a.size % 3:
                Error(f"parameter {name!r}: rgb value count not multiple of 3")
            return a.reshape(-1, 3)
        if type_name == "xyz":
            a = np.asarray([float(v) for v in values], dtype=np.float64).reshape(-1, 3)
            return np.stack([spec.xyz_to_rgb(x) for x in a])
        if type_name == "blackbody":
            # pbrt-v3: pairs of (temperature, scale)
            a = [float(v) for v in values]
            out = []
            for i in range(0, len(a), 2):
                t = a[i]
                sc = a[i + 1] if i + 1 < len(a) else 1.0
                out.append(spec.blackbody_rgb_normalized(t) * sc)
            return np.asarray(out)
        if type_name == "spectrum":
            if values and isinstance(values[0], str):
                # .spd file(s): lines of "wavelength value"
                out = []
                for fn in values:
                    lam_v = np.loadtxt(resolve_filename(fn, scene_dir)).reshape(-1, 2)
                    out.append(spec.spd_to_rgb(lam_v[:, 0], lam_v[:, 1]))
                return np.asarray(out)
            a = [float(v) for v in values]
            if len(a) < 2 or len(a) % 2:
                Error(f"parameter {name!r}: spectrum needs (wavelength, value) pairs")
            lam = np.asarray(a[0::2])
            val = np.asarray(a[1::2])
            return spec.spd_to_rgb(lam, val)[None, :]
        raise AssertionError(type_name)

    # -- typed lookups (pbrt FindOne* / Find* surface) --------------------
    def _get(self, name, kinds):
        e = self._params.get(name)
        if e is not None and e[0] in kinds:
            self._looked_up.add(name)
            return e[1]
        return None

    def find_one_float(self, name, default: float) -> float:
        v = self._get(name, ("float", "int"))
        return float(v[0]) if v is not None and len(v) else default

    def find_one_int(self, name, default: int) -> int:
        v = self._get(name, ("int", "float"))
        return int(v[0]) if v is not None and len(v) else default

    def find_one_bool(self, name, default: bool) -> bool:
        v = self._get(name, ("bool",))
        return bool(v[0]) if v is not None and len(v) else default

    def find_one_string(self, name, default: str) -> str:
        v = self._get(name, ("string",))
        return str(v[0]) if v is not None and len(v) else default

    def find_one_filename(self, name, default: str, scene_dir: str = ".") -> str:
        v = self.find_one_string(name, "")
        return resolve_filename(v, scene_dir) if v else default

    def find_texture(self, name) -> Optional[str]:
        v = self._get(name, ("texture",))
        return str(v[0]) if v is not None and len(v) else None

    def find_one_point3(self, name, default) -> np.ndarray:
        v = self._get(name, ("point3",))
        return np.asarray(v[0], dtype=np.float64) if v is not None and len(v) else np.asarray(default, dtype=np.float64)

    def find_one_vector3(self, name, default) -> np.ndarray:
        v = self._get(name, ("vector3", "point3", "normal"))
        return np.asarray(v[0], dtype=np.float64) if v is not None and len(v) else np.asarray(default, dtype=np.float64)

    def find_one_normal(self, name, default) -> np.ndarray:
        return self.find_one_vector3(name, default)

    def find_one_point2(self, name, default) -> np.ndarray:
        v = self._get(name, ("point2", "vector2"))
        return np.asarray(v[0], dtype=np.float64) if v is not None and len(v) else np.asarray(default, dtype=np.float64)

    def find_one_spectrum(self, name, default) -> np.ndarray:
        v = self._get(name, ("spectrum",))
        if v is not None and len(v):
            return np.asarray(v[0], dtype=np.float64)
        d = np.asarray(default, dtype=np.float64)
        return np.full(3, float(d)) if d.ndim == 0 else d

    # vector (multi-value) lookups
    def find_float(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("float", "int"))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    def find_int(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("int", "float"))
        return np.asarray(v, dtype=np.int64) if v is not None else None

    def find_point3(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("point3",))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    def find_vector3(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("vector3", "point3"))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    def find_normal(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("normal", "vector3", "point3"))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    def find_point2(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("point2", "vector2"))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    def find_string(self, name) -> Optional[List[str]]:
        v = self._get(name, ("string",))
        return list(v) if v is not None else None

    def find_bool(self, name) -> Optional[List[bool]]:
        v = self._get(name, ("bool",))
        return list(v) if v is not None else None

    def find_spectrum(self, name) -> Optional[np.ndarray]:
        v = self._get(name, ("spectrum",))
        return np.asarray(v, dtype=np.float64) if v is not None else None

    # -- bookkeeping ------------------------------------------------------
    def report_unused(self, context: str = ""):
        for name in self._params:
            if name not in self._looked_up:
                warn(f'parameter "{name}" not used {context}'.strip())

    def names(self):
        return list(self._params)

    def has(self, name) -> bool:
        return name in self._params

    def __repr__(self):
        return f"ParamSet({ {k: v[0] for k, v in self._params.items()} })"


class TextureParams:
    """Layered lookup: geometry params shadow material params; texture
    lookups resolve named Texture plugins (pbrt-v3 paramset.h TextureParams)."""

    def __init__(self, geom: ParamSet, material: ParamSet,
                 float_textures: Dict[str, Any], spectrum_textures: Dict[str, Any]):
        self.geom = geom
        self.material = material
        self.float_textures = float_textures
        self.spectrum_textures = spectrum_textures

    def _tex_name(self, name):
        t = self.geom.find_texture(name)
        if t is None:
            t = self.material.find_texture(name)
        return t

    def get_spectrum_texture(self, name, default):
        """Returns a texture node: ('const', rgb) or a named texture object."""
        t = self._tex_name(name)
        if t is not None:
            if t in self.spectrum_textures:
                return self.spectrum_textures[t]
            warn(f'spectrum texture "{t}" not found; using default for "{name}"')
        if self.geom.has(name):
            return ("const", self.geom.find_one_spectrum(name, default))
        if self.material.has(name):
            return ("const", self.material.find_one_spectrum(name, default))
        return ("const", np.asarray(default, dtype=np.float64) * np.ones(3))

    def get_spectrum_texture_or_none(self, name):
        t = self._tex_name(name)
        if t is not None and t in self.spectrum_textures:
            return self.spectrum_textures[t]
        if self.geom.has(name) or self.material.has(name):
            return ("const", self.find_one_spectrum(name, 0.0))
        return None

    def get_float_texture(self, name, default):
        t = self._tex_name(name)
        if t is not None:
            if t in self.float_textures:
                return self.float_textures[t]
            warn(f'float texture "{t}" not found; using default for "{name}"')
        if self.geom.has(name):
            return ("constf", self.geom.find_one_float(name, default))
        if self.material.has(name):
            return ("constf", self.material.find_one_float(name, default))
        return ("constf", float(default))

    def get_float_texture_or_none(self, name):
        t = self._tex_name(name)
        if t is not None and t in self.float_textures:
            return self.float_textures[t]
        if self.geom.has(name) or self.material.has(name):
            return ("constf", self.find_one_float(name, 0.0))
        return None

    # scalar lookups fall through geometry -> material
    def find_one_float(self, name, default):
        return self.geom.find_one_float(name, self.material.find_one_float(name, default))

    def find_one_int(self, name, default):
        return self.geom.find_one_int(name, self.material.find_one_int(name, default))

    def find_one_bool(self, name, default):
        return self.geom.find_one_bool(name, self.material.find_one_bool(name, default))

    def find_one_string(self, name, default):
        return self.geom.find_one_string(name, self.material.find_one_string(name, default))

    def find_one_spectrum(self, name, default):
        return self.geom.find_one_spectrum(name, self.material.find_one_spectrum(name, default))
