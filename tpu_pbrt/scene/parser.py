""".pbrt directive parser.

Capability match for pbrt-v3 src/core/parser.cpp: pulls tokens from the
Tokenizer, dispatches each directive to the PbrtAPI state machine, parses
'"type name" [values]' parameter lists into ParamSets, and handles Include
by pushing a nested tokenizer.
"""

from __future__ import annotations

from typing import List, Optional

from tpu_pbrt.scene.lexer import Token, Tokenizer, resolve_include
from tpu_pbrt.scene.paramset import ParamSet
from tpu_pbrt.utils.error import Error, pop_loc, push_loc, set_line


class _TokenStream:
    def __init__(self, tok: Tokenizer):
        self.stack: List[Tokenizer] = [tok]
        self.pushed: Optional[Token] = None

    def next(self) -> Optional[Token]:
        if self.pushed is not None:
            t, self.pushed = self.pushed, None
            return t
        while self.stack:
            t = self.stack[-1].next()
            if t is not None:
                set_line(t.line)
                return t
            self.stack.pop()
            pop_loc()
        return None

    def push_back(self, t: Token):
        assert self.pushed is None
        self.pushed = t

    def include(self, path: str):
        try:
            tok = Tokenizer.from_file(path)
        except OSError as e:
            Error(f"Include: couldn't open {path!r}: {e.strerror}")
            return
        self.stack.append(tok)
        push_loc(path)


def _expect_numbers(ts: _TokenStream, n: int, directive: str) -> List[float]:
    out = []
    brack = False
    while len(out) < n:
        t = ts.next()
        if t is None:
            Error(f"Premature EOF reading arguments of {directive}")
        if t.kind == "lbrack":
            brack = True
            continue
        if t.kind != "number":
            Error(f"{directive}: expected number, got {t.value!r}")
        out.append(float(t.value))
    if brack:
        t = ts.next()
        if t is None or t.kind != "rbrack":
            if t is not None:
                ts.push_back(t)
    return out


def _expect_string(ts: _TokenStream, directive: str) -> str:
    t = ts.next()
    if t is None or t.kind != "string":
        Error(f"{directive}: expected quoted string" + (f", got {t.value!r}" if t else " (EOF)"))
    return t.value


def _parse_params(ts: _TokenStream, scene_dir: str) -> ParamSet:
    """Parse zero or more '"type name" value-or-[values]' entries."""
    ps = ParamSet()
    while True:
        t = ts.next()
        if t is None:
            return ps
        if t.kind != "string":
            ts.push_back(t)
            return ps
        decl = t.value
        values: list = []
        t2 = ts.next()
        if t2 is None:
            Error(f"Premature EOF after parameter declaration {decl!r}")
        if t2.kind == "lbrack":
            while True:
                t3 = ts.next()
                if t3 is None:
                    Error(f"Premature EOF in value list of {decl!r}")
                if t3.kind == "rbrack":
                    break
                if t3.kind in ("number", "string"):
                    values.append(t3.value)
                elif t3.kind == "ident" and t3.value in ("true", "false"):
                    values.append(t3.value)
                else:
                    Error(f"Unexpected token {t3.value!r} in value list of {decl!r}")
        elif t2.kind in ("number", "string"):
            values.append(t2.value)
        elif t2.kind == "ident" and t2.value in ("true", "false"):
            values.append(t2.value)
        else:
            Error(f"Expected value after parameter declaration {decl!r}")
        ps.add(decl, values, scene_dir)
    return ps


def parse_tokens(tok: Tokenizer, api, render: bool = False):
    ts = _TokenStream(tok)
    push_loc(tok.filename)
    try:
        _parse_loop(ts, api, render)
    finally:
        while ts.stack:
            ts.stack.pop()
            pop_loc()


def _parse_loop(ts: _TokenStream, api, render: bool):
    sd = lambda: api.scene_dir  # noqa: E731
    while True:
        t = ts.next()
        if t is None:
            return
        if t.kind != "ident":
            Error(f"Unexpected token at top level: {t.value!r}")
            continue
        d = t.value
        if d == "Include":
            path = _expect_string(ts, d)
            ts.include(resolve_include(path, t.filename))
        elif d == "Identity":
            api.identity()
        elif d == "Translate":
            api.translate(*_expect_numbers(ts, 3, d))
        elif d == "Scale":
            api.scale(*_expect_numbers(ts, 3, d))
        elif d == "Rotate":
            api.rotate(*_expect_numbers(ts, 4, d))
        elif d == "LookAt":
            api.look_at(*_expect_numbers(ts, 9, d))
        elif d == "Transform":
            api.transform(_expect_numbers(ts, 16, d))
        elif d == "ConcatTransform":
            api.concat_transform(_expect_numbers(ts, 16, d))
        elif d == "CoordinateSystem":
            api.coordinate_system(_expect_string(ts, d))
        elif d == "CoordSysTransform":
            api.coord_sys_transform(_expect_string(ts, d))
        elif d == "ActiveTransform":
            t2 = ts.next()
            if t2 is None or t2.kind != "ident":
                Error("ActiveTransform: expected All/StartTime/EndTime")
            if t2.value == "All":
                api.active_transform_all()
            elif t2.value == "StartTime":
                api.active_transform_start()
            elif t2.value == "EndTime":
                api.active_transform_end()
            else:
                Error(f"ActiveTransform: unknown time {t2.value!r}")
        elif d == "TransformTimes":
            api.transform_times(*_expect_numbers(ts, 2, d))
        elif d == "PixelFilter":
            name = _expect_string(ts, d)
            api.pixel_filter(name, _parse_params(ts, sd()))
        elif d == "Film":
            name = _expect_string(ts, d)
            api.film(name, _parse_params(ts, sd()))
        elif d == "Sampler":
            name = _expect_string(ts, d)
            api.sampler(name, _parse_params(ts, sd()))
        elif d == "Accelerator":
            name = _expect_string(ts, d)
            api.accelerator(name, _parse_params(ts, sd()))
        elif d == "Integrator":
            name = _expect_string(ts, d)
            api.integrator(name, _parse_params(ts, sd()))
        elif d == "Camera":
            name = _expect_string(ts, d)
            api.camera(name, _parse_params(ts, sd()))
        elif d == "MakeNamedMedium":
            name = _expect_string(ts, d)
            api.make_named_medium(name, _parse_params(ts, sd()))
        elif d == "MediumInterface":
            inside = _expect_string(ts, d)
            t2 = ts.next()
            outside = ""
            if t2 is not None and t2.kind == "string":
                outside = t2.value
            elif t2 is not None:
                ts.push_back(t2)
            api.medium_interface(inside, outside)
        elif d == "WorldBegin":
            api.world_begin()
        elif d == "WorldEnd":
            api.world_end(render=render)
        elif d == "AttributeBegin":
            api.attribute_begin()
        elif d == "AttributeEnd":
            api.attribute_end()
        elif d == "TransformBegin":
            api.transform_begin()
        elif d == "TransformEnd":
            api.transform_end()
        elif d == "Texture":
            name = _expect_string(ts, d)
            type_name = _expect_string(ts, d)
            tex_class = _expect_string(ts, d)
            api.texture(name, type_name, tex_class, _parse_params(ts, sd()))
        elif d == "Material":
            name = _expect_string(ts, d)
            api.material(name, _parse_params(ts, sd()))
        elif d == "MakeNamedMaterial":
            name = _expect_string(ts, d)
            api.make_named_material(name, _parse_params(ts, sd()))
        elif d == "NamedMaterial":
            api.named_material(_expect_string(ts, d))
        elif d == "LightSource":
            name = _expect_string(ts, d)
            api.light_source(name, _parse_params(ts, sd()))
        elif d == "AreaLightSource":
            name = _expect_string(ts, d)
            api.area_light_source(name, _parse_params(ts, sd()))
        elif d == "Shape":
            name = _expect_string(ts, d)
            api.shape(name, _parse_params(ts, sd()))
        elif d == "ReverseOrientation":
            api.reverse_orientation()
        elif d == "ObjectBegin":
            api.object_begin(_expect_string(ts, d))
        elif d == "ObjectEnd":
            api.object_end()
        elif d == "ObjectInstance":
            api.object_instance(_expect_string(ts, d))
        else:
            Error(f"Unknown directive: {d}")
