"""Scene compiler: parsed scene records -> flat SoA device arrays.

This is the TPU-first replacement for pbrt-v3's object graph. Where pbrt
builds a tree of virtual-dispatch objects (GeometricPrimitive wrapping
Shape/Material/AreaLight; src/core/primitive.h, api.cpp MakeShapes), the
compiler lowers everything ONCE on the host into flat arrays in HBM:

- all shapes tessellated/collected into one world-space triangle soup
  (src/shapes/* capability; quadrics are tessellated, meshes are native),
- object instances (TransformedPrimitive, api.cpp pbrtObjectInstance)
  expanded by baking instance transforms,
- materials lowered to a type-enum + parameter-slot table
  (src/materials/*::ComputeScatteringFunctions capability),
- lights lowered to a type-enum SoA table; emissive shapes become one
  area-light row per triangle exactly as pbrt makes one DiffuseAreaLight
  per Triangle (api.cpp MakeShapes + diffuse.cpp),
- a BVH built over the soup and flattened to LinearBVHNode SoA
  (accelerators/bvh.cpp), with triangle arrays permuted to leaf order so
  leaf prims are contiguous in HBM,
- film/camera/sampler/integrator configs resolved via the Make* factories.

Tagged-union dispatch over the type enums replaces virtual calls inside the
wavefront kernels (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from tpu_pbrt.accel.build import build_bvh, triangle_bounds
from tpu_pbrt.accel.traverse import bvh_as_device_dict
from tpu_pbrt.cameras import make_camera
from tpu_pbrt.core.film import Film, make_film
from tpu_pbrt.core.filters import make_filter
from tpu_pbrt.core.sampling import Distribution1D, Distribution2D
from tpu_pbrt.core.spectrum import luminance
from tpu_pbrt.scene.plyreader import read_ply
from tpu_pbrt.utils.error import Error, Warning
from tpu_pbrt.utils.fileutil import resolve_filename

# material type enum (device tagged union)
MAT_NONE = 0
MAT_MATTE = 1
MAT_PLASTIC = 2
MAT_METAL = 3
MAT_GLASS = 4
MAT_MIRROR = 5
MAT_UBER = 6
MAT_SUBSTRATE = 7
MAT_TRANSLUCENT = 8
MAT_DISNEY = 9
MAT_HAIR = 10
MAT_FOURIER = 11
MAT_SUBSURFACE = 12

_MAT_ENUM = {
    "none": MAT_NONE,
    "matte": MAT_MATTE,
    "plastic": MAT_PLASTIC,
    "metal": MAT_METAL,
    "glass": MAT_GLASS,
    "mirror": MAT_MIRROR,
    "uber": MAT_UBER,
    "substrate": MAT_SUBSTRATE,
    "translucent": MAT_TRANSLUCENT,
    "disney": MAT_DISNEY,
    "hair": MAT_HAIR,
    "fourier": MAT_FOURIER,
    "subsurface": MAT_SUBSURFACE,
    "kdsubsurface": MAT_SUBSURFACE,
}

# light type enum
LIGHT_POINT = 0
LIGHT_SPOT = 1
LIGHT_DISTANT = 2
LIGHT_AREA = 3
LIGHT_INFINITE = 4
LIGHT_GONIO = 5
LIGHT_PROJECTION = 6


@dataclass
class SamplerSpec:
    name: str
    spp: int
    params: Any


@dataclass
class CompiledScene:
    """Host handle + the device pytree every kernel consumes."""

    dev: Dict[str, Any]  # device arrays (see compile_scene for schema)
    film: Film
    camera: Any  # CompiledCamera
    sampler: SamplerSpec
    integrator_name: str
    integrator_params: Any
    n_tris: int
    n_lights: int
    world_min: np.ndarray
    world_max: np.ndarray
    world_center: np.ndarray
    world_radius: float
    has_envmap: bool = False
    env_distribution: Optional[Distribution2D] = None
    light_distribution_name: str = "spatial"
    light_distr: Optional[Distribution1D] = None
    media: Dict[str, Any] = field(default_factory=dict)
    camera_medium_id: int = -1
    #: scene contains MAT_NONE (interface/container) surfaces — integrators
    #: then pay for the null-passthrough visibility walk (unoccluded_tr)
    has_null_materials: bool = False
    #: compiled texture evaluator (core/texture_eval.py) or None when every
    #: texture constant-folded; signature eval(atlas, tid, uv, p, lod=None)
    tex_eval: Any = None
    #: static set of material tex slots actually used ("kd", "ks", ...) so
    #: integrators skip evaluation entirely for untextured slots
    tex_used: frozenset = frozenset()
    #: dense per-voxel light CDFs (lights_dev.SpatialLightDistribution) or
    #: None for single-light scenes
    spatial_distr: Any = None


# -------------------------------------------------------------------------
# Shape tessellation (host). Each returns (verts (T,3,3) f64 in OBJECT
# space, normals (T,3,3) or None, uvs (T,3,2) or None).
# -------------------------------------------------------------------------

def _tess_mesh(params, scene_dir):
    idx = params.find_int("indices")
    P = params.find_point3("P")
    if idx is None or P is None:
        Error("Vertex indices and positions \"P\" must be provided with triangle mesh.")
        return None
    idx = np.asarray(idx, np.int64).reshape(-1, 3)
    P = np.asarray(P, np.float64).reshape(-1, 3)
    N = params.find_normal("N")
    uv = params.find_point2("uv")
    if uv is None:
        uv = params.find_point2("st")
        if uv is None:
            fuv = params.find_float("uv")
            if fuv is None:
                fuv = params.find_float("st")
            uv = np.asarray(fuv, np.float64).reshape(-1, 2) if fuv is not None else None
    verts = P[idx]
    normals = np.asarray(N, np.float64).reshape(-1, 3)[idx] if N is not None else None
    uvs = np.asarray(uv, np.float64).reshape(-1, 2)[idx] if uv is not None else None
    return verts, normals, uvs


def _tess_ply(params, scene_dir):
    fn = params.find_one_string("filename", "")
    path = resolve_filename(fn, scene_dir)
    if not os.path.exists(path):
        Error(f"PLY file \"{path}\" not found.")
        return None
    mesh = read_ply(path)
    idx = mesh["indices"].reshape(-1, 3)
    verts = mesh["P"][idx]
    normals = mesh["N"][idx] if mesh.get("N") is not None else None
    uvs = mesh["uv"][idx] if mesh.get("uv") is not None else None
    return verts, normals, uvs


def _grid_to_tris(px, n_u, n_v, wrap_u=False):
    """(n_v+1, n_u+1, 3) grid of points -> triangle list + uv + normals via
    finite differences left to caller. Returns vertex index triples."""
    tris = []
    for v in range(n_v):
        for u in range(n_u):
            u1 = (u + 1) % (n_u + 1) if wrap_u else u + 1
            a = v * (n_u + 1) + u
            b = v * (n_u + 1) + u1
            c = (v + 1) * (n_u + 1) + u1
            d = (v + 1) * (n_u + 1) + u
            tris.append((a, b, c))
            tris.append((a, c, d))
    return np.asarray(tris, np.int64)


def _tess_param_surface(point_fn, normal_fn, u_max, v_range, n_u, n_v):
    """Tessellate a parametric surface. point_fn(u, v) -> (3,), u in
    [0, u_max] (phi), v in v_range."""
    us = np.linspace(0.0, u_max, n_u + 1)
    vs = np.linspace(v_range[0], v_range[1], n_v + 1)
    uu, vv = np.meshgrid(us, vs)  # (n_v+1, n_u+1)
    pts = point_fn(uu, vv)  # (n_v+1, n_u+1, 3)
    nrm = normal_fn(uu, vv) if normal_fn is not None else None
    idx = _grid_to_tris(pts, n_u, n_v)
    flat_p = pts.reshape(-1, 3)
    verts = flat_p[idx]
    normals = nrm.reshape(-1, 3)[idx] if nrm is not None else None
    v_den = v_range[1] - v_range[0]
    if abs(v_den) < 1e-9:
        v_den = 1e-9
    uvn = np.stack([uu / max(u_max, 1e-9), (vv - v_range[0]) / v_den], axis=-1)
    uvs = uvn.reshape(-1, 2)[idx]
    return verts, normals, uvs


def _tess_sphere(params, scene_dir):
    r = params.find_one_float("radius", 1.0)
    zmin = params.find_one_float("zmin", -r)
    zmax = params.find_one_float("zmax", r)
    phimax = math.radians(params.find_one_float("phimax", 360.0))
    theta_min = math.acos(np.clip(zmin / r, -1, 1))
    theta_max = math.acos(np.clip(zmax / r, -1, 1))
    n_u, n_v = 64, 32

    def pt(u, v):
        # v: theta from theta_min(at zmin)→theta_max; pbrt params z from zmin..zmax
        theta = v
        return np.stack(
            [r * np.sin(theta) * np.cos(u), r * np.sin(theta) * np.sin(u), r * np.cos(theta)],
            axis=-1,
        )

    def nrm(u, v):
        p = pt(u, v)
        return p / r

    return _tess_param_surface(pt, nrm, phimax, (theta_min, theta_max), n_u, n_v)


def _tess_disk(params, scene_dir):
    h = params.find_one_float("height", 0.0)
    r = params.find_one_float("radius", 1.0)
    ri = params.find_one_float("innerradius", 0.0)
    phimax = math.radians(params.find_one_float("phimax", 360.0))
    n_u, n_v = 64, 1

    def pt(u, v):
        rad = ri + (r - ri) * v
        return np.stack([rad * np.cos(u), rad * np.sin(u), np.full_like(u, h)], axis=-1)

    def nrm(u, v):
        return np.broadcast_to(np.array([0.0, 0.0, 1.0]), u.shape + (3,))

    return _tess_param_surface(pt, nrm, phimax, (0.0, 1.0), n_u, n_v)


def _tess_cylinder(params, scene_dir):
    r = params.find_one_float("radius", 1.0)
    zmin = params.find_one_float("zmin", -1.0)
    zmax = params.find_one_float("zmax", 1.0)
    phimax = math.radians(params.find_one_float("phimax", 360.0))

    def pt(u, v):
        return np.stack([r * np.cos(u), r * np.sin(u), v], axis=-1)

    def nrm(u, v):
        return np.stack([np.cos(u), np.sin(u), np.zeros_like(u)], axis=-1)

    return _tess_param_surface(pt, nrm, phimax, (zmin, zmax), 64, 8)


def _tess_cone(params, scene_dir):
    r = params.find_one_float("radius", 1.0)
    h = params.find_one_float("height", 1.0)
    phimax = math.radians(params.find_one_float("phimax", 360.0))

    def pt(u, v):
        rad = r * (1.0 - v / h)
        return np.stack([rad * np.cos(u), rad * np.sin(u), v], axis=-1)

    return _tess_param_surface(pt, None, phimax, (0.0, h * (1 - 1e-6)), 64, 16)


def _tess_paraboloid(params, scene_dir):
    r = params.find_one_float("radius", 1.0)
    zmin = params.find_one_float("zmin", 0.0)
    zmax = params.find_one_float("zmax", 1.0)
    phimax = math.radians(params.find_one_float("phimax", 360.0))

    def pt(u, v):
        rad = r * np.sqrt(np.maximum(v, 0.0) / zmax)
        return np.stack([rad * np.cos(u), rad * np.sin(u), v], axis=-1)

    return _tess_param_surface(pt, None, phimax, (zmin, zmax), 64, 16)


def _tess_hyperboloid(params, scene_dir):
    p1 = np.asarray(params.find_one_point3("p1", [0.0, 0.0, 0.0]), np.float64)
    p2 = np.asarray(params.find_one_point3("p2", [1.0, 1.0, 1.0]), np.float64)
    phimax = math.radians(params.find_one_float("phimax", 360.0))

    def pt(u, v):
        p = p1[None, None] * (1 - v[..., None]) + p2[None, None] * v[..., None]
        xr = np.cos(u) * p[..., 0] - np.sin(u) * p[..., 1]
        yr = np.sin(u) * p[..., 0] + np.cos(u) * p[..., 1]
        return np.stack([xr, yr, p[..., 2]], axis=-1)

    return _tess_param_surface(pt, None, phimax, (0.0, 1.0), 64, 16)


def _tess_heightfield(params, scene_dir):
    nu = params.find_one_int("nu", -1)
    nv = params.find_one_int("nv", -1)
    z = params.find_float("Pz")
    if nu <= 0 or nv <= 0 or z is None:
        Error("heightfield2 requires nu, nv, Pz")
        return None
    z = np.asarray(z, np.float64).reshape(nv, nu)
    xs = np.linspace(0, 1, nu)
    ys = np.linspace(0, 1, nv)
    xx, yy = np.meshgrid(xs, ys)
    pts = np.stack([xx, yy, z], axis=-1)
    idx = _grid_to_tris(pts, nu - 1, nv - 1)
    flat = pts.reshape(-1, 3)
    uv = np.stack([xx, yy], axis=-1).reshape(-1, 2)
    return flat[idx], None, uv[idx]


def _tess_loopsubdiv(params, scene_dir):
    from tpu_pbrt.shapes.loopsubdiv import loop_subdivide

    levels = params.find_one_int("levels", params.find_one_int("nlevels", 3))
    idx = params.find_int("indices")
    P = params.find_point3("P")
    if idx is None or P is None:
        Error("loopsubdiv requires indices and P")
        return None
    verts, normals = loop_subdivide(
        np.asarray(P, np.float64).reshape(-1, 3), np.asarray(idx, np.int64).reshape(-1, 3), levels
    )
    return verts, normals, None


def _tess_curve(params, scene_dir):
    """shapes/curve.cpp capability: cubic Bezier hair/fur segments.

    pbrt intersects the curve analytically by recursive subdivision; the
    TPU-first mapping TESSELLATES each segment into a camera-independent
    flat ribbon strip (the same geometric model pbrt's "flat" curves use —
    ribbons that ignore orientation render identically under the width
    interpolation; "cylinder" curves approximate to the same ribbon). uv:
    u along the curve, v across the width."""
    cps = params.find_point3("P")
    if cps is None:
        Error("curve requires control points P")
        return None
    cps = np.asarray(cps, np.float64).reshape(-1, 3)
    if len(cps) < 4:
        Error("curve requires at least 4 control points")
        return None
    w0 = params.find_one_float("width0", params.find_one_float("width", 1.0))
    w1 = params.find_one_float("width1", params.find_one_float("width", 1.0))
    n_seg_pts = 16  # subdivisions per cubic segment
    verts_all, uvs_all = [], []
    n_curves = (len(cps) - 1) // 3  # chained cubic segments share endpoints
    for ci in range(max(n_curves, 1)):
        p0, p1, p2, p3 = cps[3 * ci : 3 * ci + 4]
        t = np.linspace(0.0, 1.0, n_seg_pts + 1)[:, None]
        b = (
            (1 - t) ** 3 * p0
            + 3 * (1 - t) ** 2 * t * p1
            + 3 * (1 - t) * t * t * p2
            + t ** 3 * p3
        )  # (n+1, 3)
        tan = (
            3 * (1 - t) ** 2 * (p1 - p0)
            + 6 * (1 - t) * t * (p2 - p1)
            + 3 * t * t * (p3 - p2)
        )
        tan /= np.maximum(np.linalg.norm(tan, axis=-1, keepdims=True), 1e-12)
        # ribbon frame: side = tangent x reference, with a per-point
        # fallback axis where the tangent turns parallel to the primary
        # reference (a single t=0-derived axis degenerates there)
        ref = np.eye(3)[np.argmin(np.abs(tan[0]))]
        side = np.cross(tan, ref)
        nrm = np.linalg.norm(side, axis=-1, keepdims=True)
        alt = np.eye(3)[(np.argmin(np.abs(tan[0])) + 1) % 3]
        side_alt = np.cross(tan, alt)
        bad = nrm < 1e-6
        side = np.where(bad, side_alt, side)
        side /= np.maximum(np.linalg.norm(side, axis=-1, keepdims=True), 1e-12)
        u_glob = (ci + t[:, 0]) / max(n_curves, 1)
        half_w = 0.5 * ((1 - u_glob) * w0 + u_glob * w1)[:, None]
        left = b - side * half_w
        right = b + side * half_w
        pts = np.stack([left, right], axis=1)  # (n+1, 2, 3)
        for k in range(n_seg_pts):
            a0, a1 = pts[k, 0], pts[k, 1]
            b0_, b1_ = pts[k + 1, 0], pts[k + 1, 1]
            verts_all += [[a0, a1, b1_], [a0, b1_, b0_]]
            ua, ub = u_glob[k], u_glob[k + 1]
            uvs_all += [
                [[ua, 0], [ua, 1], [ub, 1]],
                [[ua, 0], [ub, 1], [ub, 0]],
            ]
    return (
        np.asarray(verts_all, np.float64),
        None,
        np.asarray(uvs_all, np.float64),
    )


_TESSELATORS = {
    "trianglemesh": _tess_mesh,
    "plymesh": _tess_ply,
    "curve": _tess_curve,
    "sphere": _tess_sphere,
    "disk": _tess_disk,
    "cylinder": _tess_cylinder,
    "cone": _tess_cone,
    "paraboloid": _tess_paraboloid,
    "hyperboloid": _tess_hyperboloid,
    "heightfield2": _tess_heightfield,
    "loopsubdiv": _tess_loopsubdiv,
}


def tessellate_shape(rec) -> Optional[tuple]:
    fn = _TESSELATORS.get(rec.type)
    if fn is None:
        Warning(f'Shape "{rec.type}" unknown or not yet tessellatable; skipping.')
        return None
    return fn(rec.params, rec.scene_dir)


# -------------------------------------------------------------------------
# Texture folding: declarative texture nodes -> constant RGB/float for the
# material table; non-constant nodes get a texture id (imagemap atlas /
# procedural eval at shade time — compiled in textures_dev).
# -------------------------------------------------------------------------

def _fold_const(node, default):
    """Try to reduce a texture node to a constant; returns (value, folded)."""
    if node is None:
        return default, True
    if isinstance(node, tuple):
        tag = node[0]
        if tag in ("const", "constf"):
            return node[1], True
        if tag == "scale":
            a, fa = _fold_const(node[1], 1.0)
            b, fb = _fold_const(node[2], 1.0)
            if fa and fb:
                return np.asarray(a) * np.asarray(b), True
        if tag == "mix":
            a, fa = _fold_const(node[1], 0.0)
            b, fb = _fold_const(node[2], 1.0)
            t, ft = _fold_const(node[3], 0.5)
            if fa and fb and ft:
                return np.asarray(a) * (1 - np.asarray(t)) + np.asarray(b) * np.asarray(t), True
        return default, False
    # plain value (float or rgb array) captured directly by TextureParams
    return node, True


def _rgb(v) -> np.ndarray:
    a = np.asarray(v, np.float64).reshape(-1)
    if a.size == 1:
        return np.full(3, float(a[0]))
    return a[:3]


# -------------------------------------------------------------------------
# Material lowering
# -------------------------------------------------------------------------

_ROUGH_SLOTS = ("roughness", "uroughness", "vroughness")

#: Disney parameter slots, added to the material table only when a scene
#: actually uses the disney material (keeps every other scene's gather
#: and compile cost unchanged)
_DISNEY_SLOTS = (
    "d_metallic", "d_spectint", "d_aniso", "d_sheen", "d_sheentint",
    "d_clearcoat", "d_ccgloss", "d_strans", "d_flat", "d_dtrans",
)


def _ensure_disney_slots(tab, m):
    if "d_metallic" not in tab:
        for s in _DISNEY_SLOTS:
            tab[s] = np.zeros(m, np.float32)
        tab["d_thin"] = np.zeros(m, np.int32)


def _ensure_hair_slots(tab, m):
    if "h_beta_m" not in tab:
        tab["h_sigma_a"] = np.zeros((m, 3), np.float32)
        tab["h_beta_m"] = np.full(m, 0.3, np.float32)
        tab["h_beta_n"] = np.full(m, 0.3, np.float32)
        tab["h_alpha"] = np.full(m, 2.0, np.float32)


def _hair_sigma_a_from_reflectance(c, beta_n):
    """HairBSDF::SigmaAFromReflectance (hair.cpp)."""
    denom = (
        5.969
        - 0.215 * beta_n
        + 2.532 * beta_n**2
        - 10.73 * beta_n**3
        + 5.574 * beta_n**4
        + 0.245 * beta_n**5
    )
    return (np.log(np.maximum(np.asarray(c, np.float64), 1e-4)) / denom) ** 2


#: classic measured subsurface media (Jensen, Marschner, Levoy &
#: Hanrahan, "A Practical Model for Subsurface Light Transport",
#: SIGGRAPH 2001, table 1): name -> (sigma_prime_s, sigma_a) in 1/mm —
#: the most-used rows of pbrt's GetMediumScatteringProperties catalog
#: (src/core/medium.cpp). Others fall back to explicit parameters.
_SSS_PRESETS = {
    "Skimmilk": ([0.70, 1.22, 1.90], [0.0014, 0.0025, 0.0142]),
    "Wholemilk": ([2.55, 3.21, 3.77], [0.0011, 0.0024, 0.014]),
    "Skin1": ([0.74, 0.88, 1.01], [0.032, 0.17, 0.48]),
    "Skin2": ([1.09, 1.59, 1.79], [0.013, 0.070, 0.145]),
    "Marble": ([2.19, 2.62, 3.00], [0.0021, 0.0041, 0.0071]),
    "Ketchup": ([0.18, 0.07, 0.03], [0.061, 0.97, 1.45]),
    "Cream": ([7.38, 5.47, 3.15], [0.0002, 0.0028, 0.0163]),
    "Spectralon": ([11.6, 20.4, 14.9], [0.00, 0.00, 0.00]),
}


def lower_materials(mat_records: List, tex_registry,
                    scene_dir: str = ".") -> Dict[str, np.ndarray]:
    """MaterialRecords -> SoA table. tex_registry assigns ids to
    non-constant textures (returns -1 for constants).

    Mix materials (mixmat.cpp) expand here: each mix row's two
    sub-materials are appended as REAL rows of the same table and the
    mix row records (mix_a, mix_b, mix_amt). Shading resolves a mix
    lane to ONE sub-row by a sampler draw before the parameter gather
    (bxdf.resolve_mix) — the one-sample estimator of the scaled BSDF
    union, exact for scalar `amount` (see resolve_mix docstring).
    Nested mixes expand recursively (resolution loops a static 4 deep)."""
    mat_records = list(mat_records)
    mix_sub: Dict[int, Tuple[int, int]] = {}
    i_scan = 0
    while i_scan < len(mat_records):
        rec = mat_records[i_scan]
        if rec.type == "mix":
            m1 = rec.params.get("material1")
            m2 = rec.params.get("material2")
            if m1 is not None and m2 is not None:
                ia = len(mat_records)
                mat_records.append(m1)
                ib = len(mat_records)
                mat_records.append(m2)
                mix_sub[i_scan] = (ia, ib)
        i_scan += 1
    m = len(mat_records)
    tab = {
        "type": np.zeros(m, np.int32),
        "kd": np.zeros((m, 3), np.float32),
        "ks": np.zeros((m, 3), np.float32),
        "kr": np.zeros((m, 3), np.float32),
        "kt": np.zeros((m, 3), np.float32),
        "eta": np.ones((m, 3), np.float32),
        "k": np.zeros((m, 3), np.float32),
        "rough_u": np.zeros(m, np.float32),
        "rough_v": np.zeros(m, np.float32),
        "sigma": np.zeros(m, np.float32),
        "opacity": np.ones((m, 3), np.float32),
        "remap": np.ones(m, np.int32),
        "mix_a": np.full(m, -1, np.int32),
        "mix_b": np.full(m, -1, np.int32),
        "mix_amt": np.full(m, 0.5, np.float32),
        "sub_id": np.full(m, -1, np.int32),
        "kd_tex": np.full(m, -1, np.int32),
        "ks_tex": np.full(m, -1, np.int32),
        "sigma_tex": np.full(m, -1, np.int32),
        "rough_tex": np.full(m, -1, np.int32),
        "opacity_tex": np.full(m, -1, np.int32),
        "bump_tex": np.full(m, -1, np.int32),
    }

    #: (sigma_s, sigma_a, g, eta) per subsurface material, in sub_id
    #: order; compile_scene bakes these into the device BSSRDF table
    sss_rows: List[tuple] = []

    def fold_spec(rec, key, default, slot, tex_slot=None, i=0):
        node = rec.params.get(key)
        val, folded = _fold_const(node, default)
        if not folded:
            tid = tex_registry(node)
            if tex_slot is not None:
                tab[tex_slot][i] = tid
            val, _ = _fold_const(None, default)  # fall back to default under texture
            # average color as fallback beneath the texture lookup
            if tid < 0:
                Warning(f"texture for {key} not representable; using default")
        tab[slot][i] = _rgb(val)
        return folded

    def fold_f(rec, key, default, slot, tex_slot=None, i=0):
        node = rec.params.get(key)
        val, folded = _fold_const(node, default)
        if not folded:
            tid = tex_registry(node)
            if tex_slot is not None:
                tab[tex_slot][i] = tid
            val = default
        arr = np.asarray(val, np.float64).reshape(-1)
        tab[slot][i] = float(arr.mean())
        return folded

    for i, rec in enumerate(mat_records):
        t = rec.type
        tab["type"][i] = _MAT_ENUM.get(t, MAT_MATTE)
        p = rec.params
        if t == "matte":
            fold_spec(rec, "Kd", 0.5, "kd", "kd_tex", i)
            fold_f(rec, "sigma", 0.0, "sigma", "sigma_tex", i)
        elif t == "plastic":
            fold_spec(rec, "Kd", 0.25, "kd", "kd_tex", i)
            fold_spec(rec, "Ks", 0.25, "ks", "ks_tex", i)
            fold_f(rec, "roughness", 0.1, "rough_u", "rough_tex", i)
            tab["rough_v"][i] = tab["rough_u"][i]
            tab["remap"][i] = int(p.get("remaproughness", True))
        elif t == "metal":
            fold_spec(rec, "eta", 1.0, "eta", None, i)
            fold_spec(rec, "k", 1.0, "k", None, i)
            fold_f(rec, "roughness", 0.01, "rough_u", "rough_tex", i)
            tab["rough_v"][i] = tab["rough_u"][i]
            if p.get("uroughness") is not None:
                fold_f(rec, "uroughness", 0.01, "rough_u", None, i)
            if p.get("vroughness") is not None:
                fold_f(rec, "vroughness", 0.01, "rough_v", None, i)
            tab["remap"][i] = int(p.get("remaproughness", True))
        elif t == "glass":
            fold_spec(rec, "Kr", 1.0, "kr", None, i)
            fold_spec(rec, "Kt", 1.0, "kt", None, i)
            fold_f(rec, "eta", 1.5, "eta", None, i)
            # glass.cpp: nonzero uroughness/vroughness selects the
            # microfacet reflection/transmission lobes (rough glass).
            # vroughness defaults to 0 INDEPENDENTLY of uroughness (a
            # scene giving only uroughness is anisotropic under pbrt)
            fold_f(rec, "uroughness", 0.0, "rough_u", "rough_tex", i)
            fold_f(rec, "vroughness", 0.0, "rough_v", None, i)
            tab["remap"][i] = int(p.get("remaproughness", True))
            tab["eta"][i] = tab["eta"][i][:1].repeat(3)
        elif t == "mirror":
            fold_spec(rec, "Kr", 0.9, "kr", None, i)
        elif t == "uber":
            fold_spec(rec, "Kd", 0.25, "kd", "kd_tex", i)
            fold_spec(rec, "Ks", 0.25, "ks", "ks_tex", i)
            fold_spec(rec, "Kr", 0.0, "kr", None, i)
            fold_spec(rec, "Kt", 0.0, "kt", None, i)
            fold_f(rec, "roughness", 0.1, "rough_u", "rough_tex", i)
            tab["rough_v"][i] = tab["rough_u"][i]
            if p.get("uroughness") is not None:
                fold_f(rec, "uroughness", 0.1, "rough_u", None, i)
            if p.get("vroughness") is not None:
                fold_f(rec, "vroughness", 0.1, "rough_v", None, i)
            fold_f(rec, "eta", 1.5, "eta", None, i)
            tab["eta"][i] = tab["eta"][i][:1].repeat(3)
            fold_spec(rec, "opacity", 1.0, "opacity", "opacity_tex", i)
            tab["remap"][i] = int(p.get("remaproughness", True))
        elif t == "substrate":
            fold_spec(rec, "Kd", 0.5, "kd", "kd_tex", i)
            fold_spec(rec, "Ks", 0.5, "ks", "ks_tex", i)
            fold_f(rec, "uroughness", 0.1, "rough_u", "rough_tex", i)
            fold_f(rec, "vroughness", 0.1, "rough_v", None, i)
            tab["remap"][i] = int(p.get("remaproughness", True))
        elif t == "translucent":
            fold_spec(rec, "Kd", 0.25, "kd", "kd_tex", i)
            fold_spec(rec, "Ks", 0.25, "ks", "ks_tex", i)
            fold_spec(rec, "reflect", 0.5, "kr", None, i)
            fold_spec(rec, "transmit", 0.5, "kt", None, i)
            fold_f(rec, "roughness", 0.1, "rough_u", "rough_tex", i)
            tab["rough_v"][i] = tab["rough_u"][i]
            tab["remap"][i] = int(p.get("remaproughness", True))
        elif t == "disney":
            # full Disney 2015 lobe set (disney.cpp): parameters land in
            # dedicated d_* slots added lazily below; the shared slots
            # carry color/rough/eta for the generic machinery
            _ensure_disney_slots(tab, m)
            fold_spec(rec, "color", 0.5, "kd", "kd_tex", i)
            fold_f(rec, "roughness", 0.5, "rough_u", "rough_tex", i)
            tab["rough_v"][i] = tab["rough_u"][i]
            fold_f(rec, "eta", 1.5, "eta", None, i)
            tab["eta"][i] = tab["eta"][i][:1].repeat(3)
            tab["remap"][i] = 0
            for key, slot, dflt in (
                ("metallic", "d_metallic", 0.0),
                ("speculartint", "d_spectint", 0.0),
                ("anisotropic", "d_aniso", 0.0),
                ("sheen", "d_sheen", 0.0),
                ("sheentint", "d_sheentint", 0.5),
                ("clearcoat", "d_clearcoat", 0.0),
                ("clearcoatgloss", "d_ccgloss", 1.0),
                ("spectrans", "d_strans", 0.0),
                ("flatness", "d_flat", 0.0),
                ("difftrans", "d_dtrans", 1.0),
            ):
                fold_f(rec, key, dflt, slot, None, i)
            thin, _ = _fold_const(p.get("thin"), False)
            tab["d_thin"][i] = 1 if thin else 0
            sd, _ = _fold_const(p.get("scatterdistance"), 0.0)
            if np.any(np.asarray(sd, np.float64) > 0):
                Warning(
                    "disney scatterdistance > 0 (subsurface) is not "
                    "supported; shading as the solid Disney BSDF"
                )
        elif t == "hair":
            # full Chiang/pbrt HairBSDF (hair.cpp): sigma_a resolution
            # order matches HairMaterial::ComputeScatteringFunctions
            _ensure_hair_slots(tab, m)
            bn, _ = _fold_const(p.get("beta_n"), 0.3)
            bn = float(np.asarray(bn, np.float64).reshape(-1).mean())
            if p.get("sigma_a") is not None:
                sa, _ = _fold_const(p.get("sigma_a"), 1.3)
                sa = _rgb(sa)
            elif p.get("color") is not None:
                col, _ = _fold_const(p.get("color"), 0.5)
                sa = _hair_sigma_a_from_reflectance(_rgb(col), bn)
            else:
                eu, _ = _fold_const(p.get("eumelanin"), 1.3)
                ph, _ = _fold_const(p.get("pheomelanin"), 0.0)
                eu = float(np.asarray(eu, np.float64).reshape(-1).mean())
                ph = float(np.asarray(ph, np.float64).reshape(-1).mean())
                # HairMaterial: eumelanin/pheomelanin absorption spectra
                sa = eu * np.array([0.419, 0.697, 1.37]) + ph * np.array(
                    [0.187, 0.4, 1.05]
                )
            tab["h_sigma_a"][i] = np.asarray(sa, np.float32)
            fold_f(rec, "beta_m", 0.3, "h_beta_m", None, i)
            tab["h_beta_n"][i] = bn
            fold_f(rec, "alpha", 2.0, "h_alpha", None, i)
            fold_f(rec, "eta", 1.55, "eta", None, i)
            tab["eta"][i] = tab["eta"][i][:1].repeat(3)
            # fallback color for integrators that only store diffuse
            tab["kd"][i] = np.exp(-np.asarray(sa, np.float64) * 0.5)
        elif t == "fourier":
            # real tabulated FourierBSDF when the .bsdf file loads
            # (core/fourierbsdf.py); loud diffuse fallback otherwise
            fn, _ = _fold_const(p.get("bsdffile"), "")
            prev = tab.get("_fourier")
            tab_obj = None
            if fn and prev is not None and prev[1] == str(fn):
                tab_obj = prev[0]  # same file: reuse, skip the re-read
            elif fn and prev is not None:
                Warning(
                    "multiple distinct fourier bsdffiles in one scene "
                    "are not supported; reusing the first table"
                )
                tab_obj = prev[0]
            elif fn:
                from tpu_pbrt.core.fourierbsdf import read_bsdf_file
                from tpu_pbrt.utils.fileutil import resolve_filename

                try:
                    tab_obj = read_bsdf_file(resolve_filename(str(fn), scene_dir))
                    tab["_fourier"] = (tab_obj, str(fn))
                except Exception as e:  # noqa: BLE001
                    Warning(f'fourier: could not read "{fn}" ({e}); '
                            "SUBSTITUTING a 0.5 diffuse BSDF")
            else:
                Warning('fourier material without "bsdffile"; '
                        "SUBSTITUTING a 0.5 diffuse BSDF")
            if tab_obj is None:
                tab["type"][i] = MAT_MATTE
            tab["kd"][i] = 0.5
        elif t in ("subsurface", "kdsubsurface"):
            # real BSSRDF transport (core/bssrdf.py): the surface BSDF
            # is the smooth Fresnel interface (glass kr/kt — gather_mat
            # remaps the type); the medium's beam-diffusion profile is
            # baked per channel below and the path integrator runs the
            # Sample_Sp probe wave (subsurface.cpp / bssrdf.cpp)
            fold_spec(rec, "Kr", 1.0, "kr", None, i)
            fold_spec(rec, "Kt", 1.0, "kt", None, i)
            fold_f(rec, "eta", 1.33, "eta", None, i)
            tab["eta"][i] = tab["eta"][i][:1].repeat(3)
            eta_v = float(tab["eta"][i][0])
            g_v = 0.0
            if t == "subsurface":
                g_v = float(_fold_const(p.get("g"), 0.0)[0])
                preset = str(p.get("preset") or "")
                if preset and preset in _SSS_PRESETS:
                    sig_sp, sig_a = (
                        np.asarray(v, np.float64)
                        for v in _SSS_PRESETS[preset]
                    )
                elif preset:
                    Warning(
                        f'subsurface: unknown medium preset "{preset}"; '
                        "using the sigma_a/sigma_prime_s parameters"
                    )
                    preset = ""
                if not preset:
                    sa, fold_a = _fold_const(
                        p.get("sigma_a"), np.array([0.0011, 0.0024, 0.014])
                    )
                    ss_, fold_s = _fold_const(
                        p.get("sigma_s"), np.array([2.55, 3.21, 3.77])
                    )
                    if not (fold_a and fold_s):
                        Warning(
                            "subsurface: textured sigma_a/sigma_prime_s "
                            "are not supported (the diffusion profile "
                            "bakes per material); using constants"
                        )
                    sig_a = _rgb(sa).astype(np.float64)
                    sig_sp = _rgb(ss_).astype(np.float64)
                scale = float(_fold_const(p.get("scale"), 1.0)[0])
                sig_a = sig_a * scale
                sigma_s = sig_sp * scale / max(1.0 - g_v, 1e-3)
            else:
                from tpu_pbrt.core.bssrdf import subsurface_from_diffuse

                kd_v, _ = _fold_const(p.get("Kd"), 0.5)
                mfp_v, _ = _fold_const(p.get("mfp"), 1.0)
                sigma_s, sig_a = subsurface_from_diffuse(
                    _rgb(kd_v), _rgb(mfp_v), g_v, eta_v
                )
            ur, _ = _fold_const(p.get("uroughness"), 0.0)
            if np.max(np.asarray(ur, np.float64)) > 0:
                Warning(
                    "subsurface: rough interface not supported; using "
                    "the smooth specular interface"
                )
            tab["sub_id"][i] = len(sss_rows)
            sss_rows.append((sigma_s, sig_a, g_v, eta_v))
            # fallback albedo for integrators without the probe wave
            # (bdpt/sppm/mlt shade the interface only — warned at render)
            tab["kd"][i] = 0.5
        elif t == "mix":
            # true MixMaterial (mixmat.cpp): sub-materials are rows
            # ia/ib of this same table (expanded in the pre-pass);
            # shading resolves the lane stochastically by `amount`
            # before the gather (bxdf.resolve_mix). The row's own
            # shading params are a diffuse blend FALLBACK used only
            # past the static nesting-depth limit.
            amt, folded = _fold_const(p.get("amount"), 0.5)
            a = _rgb(amt)
            if not folded:
                Warning(
                    "mix: textured `amount` is not supported; using "
                    "its constant fallback for the selection probability"
                )
            if a.min() != a.max():
                Warning(
                    "mix: colored `amount` selects by its channel MEAN "
                    "(per-channel mix weights are approximated)"
                )
            if i in mix_sub:
                ia, ib = mix_sub[i]
                tab["mix_a"][i] = ia
                tab["mix_b"][i] = ib
                tab["mix_amt"][i] = float(np.clip(a.mean(), 0.0, 1.0))
            tab["type"][i] = MAT_MATTE
            m1 = p.get("material1")
            m2 = p.get("material2")
            kd1, _ = _fold_const(m1.params.get("Kd") if m1 else None, 0.5)
            kd2, _ = _fold_const(m2.params.get("Kd") if m2 else None, 0.5)
            tab["kd"][i] = _rgb(kd1) * a + _rgb(kd2) * (1 - a)
        # "none" keeps zeros (passthrough)
    if not (tab["mix_a"] >= 0).any():
        # mix-free scene: drop the columns so resolve_mix is a static
        # no-op in every traced program (key presence IS the flag)
        del tab["mix_a"], tab["mix_b"], tab["mix_amt"]
    if sss_rows:
        tab["_sss_rows"] = sss_rows
    else:
        del tab["sub_id"]
    return tab


# -------------------------------------------------------------------------
# The compile pass
# -------------------------------------------------------------------------

def _geometric_normals(verts: np.ndarray) -> np.ndarray:
    e1 = verts[:, 1] - verts[:, 0]
    e2 = verts[:, 2] - verts[:, 0]
    n = np.cross(e1, e2)
    ln = np.linalg.norm(n, axis=-1, keepdims=True)
    n = n / np.maximum(ln, 1e-20)
    return np.repeat(n[:, None, :], 3, axis=1)


def compile_scene(api) -> CompiledScene:
    ro = api.render_options
    opts = api.options

    # -- film / filter / camera / sampler --------------------------------
    filt = make_filter(ro.filter_name, ro.filter_params)
    film = make_film(ro.film_name, ro.film_params, filt, opts)
    camera = make_camera(
        ro.camera_name,
        ro.camera_params,
        ro.camera_to_world[0],
        film.full_resolution,
        (
            ro.camera_params.find_one_float("shutteropen", 0.0),
            ro.camera_params.find_one_float("shutterclose", 1.0),
        ),
        film_diag=film.diagonal,
        scene_dir=getattr(api, "scene_dir", "."),
    )
    spp = ro.sampler_params.find_one_int("pixelsamples", 16)
    if getattr(opts, "quick_render", False):
        spp = max(1, spp // 4)
    sampler = SamplerSpec(ro.sampler_name, spp, ro.sampler_params)

    # -- gather shapes (instances expanded) ------------------------------
    shape_list = list(ro.shapes)
    for use in ro.instance_uses:
        for rec in ro.instances.get(use.name, []):
            import copy as _copy

            r2 = _copy.copy(rec)
            r2.object_to_world = type(rec.object_to_world)(
                [use.instance_to_world[i] * rec.object_to_world[i] for i in range(2)]
            )
            shape_list.append(r2)

    all_verts, all_normals, all_uvs = [], [], []
    all_verts1 = []
    any_motion = False
    all_mat, all_light = [], []
    mat_records: List = []
    mat_index: Dict[int, int] = {}
    light_rows: List[dict] = []
    #: shared image atlas for goniometric/projection light maps
    light_atlas_chunks: List[np.ndarray] = []
    shape_tri_counts: List = []  # (ShapeRecord, n_tris) for medium interfaces

    def mat_id_for(mrec):
        if mrec is None:
            from tpu_pbrt.scene.api import MaterialRecord

            mrec = MaterialRecord("none", {})
        key = id(mrec)
        if key not in mat_index:
            mat_index[key] = len(mat_records)
            mat_records.append(mrec)
        return mat_index[key]

    for rec in shape_list:
        tess = tessellate_shape(rec)
        if tess is None:
            continue
        verts, normals, uvs = tess
        o2w = rec.object_to_world[0]
        o2w1 = rec.object_to_world[1]
        wverts = o2w.apply_point(verts.reshape(-1, 3)).reshape(-1, 3, 3)
        # shutter-end keyframe (AnimatedTransform endpoint baking: verts
        # interpolate LINEARLY per ray time — transform.cpp's decompose+
        # slerp differs for large rotations; documented deviation)
        if not np.allclose(o2w.m, o2w1.m):
            wverts1 = o2w1.apply_point(verts.reshape(-1, 3)).reshape(-1, 3, 3)
            any_motion = True
        else:
            wverts1 = wverts
        if normals is not None:
            wn = o2w.apply_normal(normals.reshape(-1, 3)).reshape(-1, 3, 3)
            ln = np.linalg.norm(wn, axis=-1, keepdims=True)
            wn = wn / np.maximum(ln, 1e-20)
        else:
            wn = _geometric_normals(wverts)
        if rec.reverse_orientation ^ o2w.swaps_handedness():
            wn = -wn
        if uvs is None:
            uvs = np.zeros((len(wverts), 3, 2))
            uvs[:, 1, 0] = 1.0
            uvs[:, 2] = [1.0, 1.0]
        mid = mat_id_for(rec.material)
        n_t = len(wverts)
        base = sum(len(v) for v in all_verts)
        shape_tri_counts.append((rec, n_t))
        all_verts.append(wverts)
        all_verts1.append(wverts1)
        all_normals.append(wn)
        all_uvs.append(uvs)
        all_mat.append(np.full(n_t, mid, np.int32))
        lids = np.full(n_t, -1, np.int32)
        if rec.area_light is not None:
            # one DiffuseAreaLight per triangle (pbrt MakeShapes semantics)
            L = _rgb(rec.area_light.find_one_spectrum("L", np.array([1.0, 1.0, 1.0])))
            sc = _rgb(rec.area_light.find_one_spectrum("scale", np.array([1.0, 1.0, 1.0])))
            two = rec.area_light.find_one_bool("twosided", False)
            e1 = wverts[:, 1] - wverts[:, 0]
            e2 = wverts[:, 2] - wverts[:, 0]
            areas = 0.5 * np.linalg.norm(np.cross(e1, e2), axis=-1)
            for k in range(n_t):
                lids[k] = len(light_rows)
                light_rows.append(
                    dict(
                        type=LIGHT_AREA,
                        p=np.zeros(3),
                        L=L * sc,
                        dir=np.zeros(3),
                        cos0=0.0,
                        cos1=0.0,
                        tri=base + k,
                        twosided=int(two),
                        area=float(areas[k]),
                    )
                )
        all_light.append(lids)

    # motion blur is active only when something moves AND the camera
    # shutter is open for a nonzero interval
    shutter = (
        ro.camera_params.find_one_float("shutteropen", 0.0),
        ro.camera_params.find_one_float("shutterclose", 1.0),
    )
    any_motion = any_motion and shutter[1] > shutter[0]
    if all_verts:
        verts = np.concatenate(all_verts).astype(np.float64)
        verts1 = np.concatenate(all_verts1).astype(np.float64) if any_motion else None
        normals = np.concatenate(all_normals).astype(np.float32)
        uvs = np.concatenate(all_uvs).astype(np.float32)
        mat_ids = np.concatenate(all_mat)
        light_ids = np.concatenate(all_light)
    else:
        # no geometry: a degenerate far-away triangle keeps shapes static
        verts = np.full((1, 3, 3), 1e30)
        verts1 = None
        any_motion = False
        normals = np.zeros((1, 3, 3), np.float32)
        normals[:, :, 2] = 1.0
        uvs = np.zeros((1, 3, 2), np.float32)
        mat_ids = np.zeros(1, np.int32)
        light_ids = np.full(1, -1, np.int32)
        from tpu_pbrt.scene.api import MaterialRecord

        mat_records.append(MaterialRecord("none", {}))

    # -- world bounds (union over the shutter when anything moves) -------
    vb = verts if verts1 is None else np.concatenate([verts, verts1])
    finite = np.abs(vb).max(axis=(1, 2)) < 1e29
    if finite.any():
        wmin = vb[finite].min(axis=(0, 1))
        wmax = vb[finite].max(axis=(0, 1))
    else:
        wmin = np.full(3, -1.0)
        wmax = np.full(3, 1.0)
    wcenter = 0.5 * (wmin + wmax)
    wradius = float(np.linalg.norm(wmax - wcenter)) + 1e-6

    # -- BVH (per-tri bounds = union over the two keyframes) -------------
    bmin, bmax = triangle_bounds(verts)
    if verts1 is not None:
        bmin1, bmax1 = triangle_bounds(verts1)
        bmin = np.minimum(bmin, bmin1)
        bmax = np.maximum(bmax, bmax1)
    bvh = build_bvh(bmin, bmax, method=ro.accelerator_params.find_one_string("splitmethod", "auto")
                    if ro.accelerator_name == "bvh" else "auto")
    order = bvh.prim_order
    verts = verts[order]
    if verts1 is not None:
        verts1 = verts1[order]
    normals = normals[order]
    uvs = uvs[order]
    mat_ids = mat_ids[order]
    light_ids = light_ids[order]
    # area-light rows reference triangle ids -> remap to leaf order
    inv_order = np.empty_like(order)
    inv_order[order] = np.arange(len(order))
    for row in light_rows:
        if row["type"] == LIGHT_AREA:
            row["tri"] = int(inv_order[row["tri"]])

    # -- non-area lights -------------------------------------------------
    envmap = None
    env_distr = None
    has_envmap = False
    env_w2l = np.eye(4, dtype=np.float32)
    for lrec in ro.lights:
        l2w = lrec.light_to_world
        p = lrec.params
        sc = _rgb(p.find_one_spectrum("scale", np.array([1.0, 1.0, 1.0])))
        if lrec.type == "point":
            I = _rgb(p.find_one_spectrum("I", np.array([1.0, 1.0, 1.0]))) * sc
            pos = l2w.apply_point(p.find_one_point3("from", [0.0, 0.0, 0.0]))
            light_rows.append(dict(type=LIGHT_POINT, p=pos, L=I, dir=np.zeros(3), cos0=0, cos1=0, tri=-1, twosided=0, area=0.0))
        elif lrec.type == "spot":
            I = _rgb(p.find_one_spectrum("I", np.array([1.0, 1.0, 1.0]))) * sc
            cone = p.find_one_float("coneangle", 30.0)
            delta = p.find_one_float("conedeltaangle", 5.0)
            frm = np.asarray(p.find_one_point3("from", [0, 0, 0]), np.float64)
            to = np.asarray(p.find_one_point3("to", [0, 0, 1]), np.float64)
            pos = l2w.apply_point(frm)
            d = l2w.apply_point(to) - pos
            d = d / max(np.linalg.norm(d), 1e-20)
            light_rows.append(
                dict(type=LIGHT_SPOT, p=pos, L=I, dir=d,
                     cos0=math.cos(math.radians(cone - delta)),  # falloff start
                     cos1=math.cos(math.radians(cone)),  # total width
                     tri=-1, twosided=0, area=0.0)
            )
        elif lrec.type == "distant":
            L = _rgb(p.find_one_spectrum("L", np.array([1.0, 1.0, 1.0]))) * sc
            frm = np.asarray(p.find_one_point3("from", [0, 0, 0]), np.float64)
            to = np.asarray(p.find_one_point3("to", [0, 0, 1]), np.float64)
            d = l2w.apply_vector(frm - to)
            d = d / max(np.linalg.norm(d), 1e-20)  # direction TOWARD light
            light_rows.append(dict(type=LIGHT_DISTANT, p=np.zeros(3), L=L, dir=d, cos0=0, cos1=0, tri=-1, twosided=0, area=0.0))
        elif lrec.type in ("infinite", "exinfinite"):
            L = _rgb(p.find_one_spectrum("L", np.array([1.0, 1.0, 1.0]))) * sc
            fn = p.find_one_string("mapname", "")
            w2l = np.asarray(l2w.inverse().m, np.float32)
            if fn:
                from tpu_pbrt.utils import imageio

                path = resolve_filename(fn, lrec.scene_dir)
                try:
                    img = imageio.read_image(path) * L[None, None]
                    envmap = img.astype(np.float32)
                    has_envmap = True
                except Exception as e:  # noqa: BLE001
                    Warning(f'could not read environment map "{path}": {e}; using constant')
                    envmap = np.full((4, 8, 3), L, np.float32)
                    has_envmap = True
            else:
                envmap = np.full((4, 8, 3), L, np.float32)
                has_envmap = True
            # importance distribution over luminance * sin(theta)
            hgt, wdt = envmap.shape[:2]
            lum = luminance(envmap)
            theta = (np.arange(hgt) + 0.5) / hgt * np.pi
            env_distr = Distribution2D.build(lum * np.sin(theta)[:, None])
            light_rows.append(dict(type=LIGHT_INFINITE, p=wcenter, L=np.ones(3), dir=np.zeros(3), cos0=0, cos1=0, tri=-1, twosided=0, area=0.0))
            # store world-to-light for map lookups
            env_w2l = w2l
        elif lrec.type in ("projection", "goniometric"):
            # goniometric.cpp / projection.cpp: a delta-position light whose
            # angular intensity is modulated by an image (goniophotometric
            # diagram in spherical coords / projected texture inside a fov
            # frustum). The image goes into the shared light atlas; the
            # world-to-light rotation rides the row.
            I = _rgb(p.find_one_spectrum("I", np.array([1.0, 1.0, 1.0]))) * sc
            pos = l2w.apply_point([0.0, 0.0, 0.0])
            fn = p.find_one_string("mapname", "")
            img = None
            if fn:
                from tpu_pbrt.utils import imageio as _iio

                try:
                    img = np.asarray(
                        _iio.read_image(resolve_filename(fn, lrec.scene_dir)),
                        np.float32,
                    )
                except Exception as e:  # noqa: BLE001
                    Warning(f'could not read light map "{fn}": {e}; using constant')
            if img is None:
                img = np.ones((1, 1, 3), np.float32)
            if img.ndim == 2:
                img = np.repeat(img[..., None], 3, -1)
            img = np.ascontiguousarray(img[..., :3], np.float32)
            off = sum(ch.shape[0] for ch in light_atlas_chunks)
            light_atlas_chunks.append(img.reshape(-1, 3))
            w2l_rot = np.asarray(l2w.inverse().m, np.float64)[:3, :3]
            if lrec.type == "goniometric":
                light_rows.append(dict(
                    type=LIGHT_GONIO, p=pos, L=I, dir=np.zeros(3),
                    cos0=0, cos1=0, tri=-1, twosided=0, area=0.0,
                    w2l=w2l_rot.reshape(-1),
                    img=np.array([off, img.shape[1], img.shape[0]], np.int64),
                ))
            else:
                fov = p.find_one_float("fov", 45.0)
                # projection.cpp: screen window from aspect; the map covers
                # the [-1,1] (short axis) frustum at tan(fov/2)
                aspect = img.shape[1] / img.shape[0]
                tan_half = math.tan(math.radians(fov) / 2.0)
                light_rows.append(dict(
                    type=LIGHT_PROJECTION, p=pos, L=I, dir=np.zeros(3),
                    cos0=tan_half, cos1=aspect, tri=-1, twosided=0, area=0.0,
                    w2l=w2l_rot.reshape(-1),
                    img=np.array([off, img.shape[1], img.shape[0]], np.int64),
                ))
        else:
            Warning(f'LightSource "{lrec.type}" unknown.')

    # -- media (medium.cpp / media/{homogeneous,grid}.cpp lowering) ------
    from tpu_pbrt.core.media import (
        MEDIUM_GRID,
        MEDIUM_HOMOGENEOUS,
        MEDIUM_PRESETS,
        MediumTable,
        empty_medium_table,
    )

    medium_ids: Dict[str, int] = {"": -1}
    med_rows = []
    grid_density_arr = None
    grid_w2m = np.eye(4, dtype=np.float32)
    sigma_t_max = 0.0
    for mname, mrec in ro.named_media.items():
        p = mrec.params
        scale_m = p.find_one_float("scale", 1.0)
        g_m = p.find_one_float("g", 0.0)
        preset = p.find_one_string("preset", "")
        sig_a_d = np.array([0.0011, 0.0024, 0.014])
        sig_s_d = np.array([2.55, 3.21, 3.77])
        if preset:
            if preset in MEDIUM_PRESETS:
                sig_s_d, sig_a_d = MEDIUM_PRESETS[preset]
            else:
                Warning(f'Material preset "{preset}" not found; using defaults')
        sig_a = _rgb(p.find_one_spectrum("sigma_a", sig_a_d)) * scale_m
        sig_s = _rgb(p.find_one_spectrum("sigma_s", sig_s_d)) * scale_m
        if mrec.type == "homogeneous":
            med_rows.append(dict(type=MEDIUM_HOMOGENEOUS, sa=sig_a, ss=sig_s, g=g_m, grid=-1))
        elif mrec.type == "heterogeneous" or mrec.type == "grid":
            nx = p.find_one_int("nx", 1)
            ny = p.find_one_int("ny", 1)
            nz = p.find_one_int("nz", 1)
            dvals = p.find_float("density")
            if dvals is None or len(dvals) != nx * ny * nz:
                Error(f'GridDensityMedium requires nx*ny*nz "density" values')
            if grid_density_arr is not None:
                Warning("multiple grid media: only one density grid supported; last wins")
            grid_density_arr = np.asarray(dvals, np.float32).reshape(nz, ny, nx)
            # pbrt maps medium space [0,1]^3 through p0/p2 bounds if given
            p0 = np.asarray(p.find_one_point3("p0", [0.0, 0.0, 0.0]))
            p1 = np.asarray(p.find_one_point3("p1", [1.0, 1.0, 1.0]))
            m2w = mrec.medium_to_world.m @ np.block(
                [[np.diag(p1 - p0), (p0)[:, None]], [np.zeros((1, 3)), np.ones((1, 1))]]
            )
            grid_w2m = np.linalg.inv(m2w).astype(np.float32)
            sigma_t_max = float((sig_a + sig_s).max() * grid_density_arr.max())
            med_rows.append(dict(type=MEDIUM_GRID, sa=sig_a, ss=sig_s, g=g_m, grid=0))
        else:
            Warning(f'Medium "{mrec.type}" unknown; ignored.')
            med_rows.append(dict(type=MEDIUM_HOMOGENEOUS, sa=sig_a * 0, ss=sig_s * 0, g=0.0, grid=-1))
        medium_ids[mname] = len(med_rows) - 1

    if med_rows:
        medium_table = MediumTable(
            mtype=jnp.asarray([r["type"] for r in med_rows], jnp.int32),
            sigma_a=jnp.asarray(np.array([r["sa"] for r in med_rows]), jnp.float32),
            sigma_s=jnp.asarray(np.array([r["ss"] for r in med_rows]), jnp.float32),
            g=jnp.asarray([r["g"] for r in med_rows], jnp.float32),
            grid_id=jnp.asarray([r["grid"] for r in med_rows], jnp.int32),
            density=jnp.asarray(
                grid_density_arr if grid_density_arr is not None else np.zeros((1, 1, 1), np.float32)
            ),
            world_to_medium=jnp.asarray(grid_w2m, jnp.float32),
            sigma_t_max=jnp.float32(sigma_t_max),
        )
    else:
        medium_table = empty_medium_table()

    # per-triangle medium interface ids (primitive.h MediumInterface)
    med_in = np.full(len(verts), -1, np.int32)
    med_out = np.full(len(verts), -1, np.int32)
    tri_base = 0
    for rec, n_t in shape_tri_counts:
        med_in[tri_base : tri_base + n_t] = medium_ids.get(rec.inside_medium, -1)
        med_out[tri_base : tri_base + n_t] = medium_ids.get(rec.outside_medium, -1)
        tri_base += n_t
    if len(order) == len(med_in):
        med_in = med_in[order]
        med_out = med_out[order]
    camera_medium_id = medium_ids.get(ro.camera_medium, -1)

    n_lights = len(light_rows)
    if n_lights == 0:
        Warning("No light sources defined in scene; rendering a black image.")
        light_rows.append(dict(type=LIGHT_POINT, p=np.zeros(3), L=np.zeros(3), dir=np.zeros(3), cos0=0, cos1=0, tri=-1, twosided=0, area=0.0))

    for r in light_rows:
        r.setdefault("w2l", np.eye(3).reshape(-1))
        r.setdefault("img", np.array([-1, 0, 0], np.int64))
    lt = {
        "type": np.array([r["type"] for r in light_rows], np.int32),
        "p": np.array([r["p"] for r in light_rows], np.float32),
        "L": np.array([r["L"] for r in light_rows], np.float32),
        "dir": np.array([r["dir"] for r in light_rows], np.float32),
        "cos0": np.array([r["cos0"] for r in light_rows], np.float32),
        "cos1": np.array([r["cos1"] for r in light_rows], np.float32),
        "tri": np.array([r["tri"] for r in light_rows], np.int32),
        "twosided": np.array([r["twosided"] for r in light_rows], np.int32),
        "area": np.array([r["area"] for r in light_rows], np.float32),
        "w2l": np.array([r["w2l"] for r in light_rows], np.float32),
        "img": np.array([r["img"] for r in light_rows], np.int32),
    }
    light_atlas = (
        np.concatenate(light_atlas_chunks, 0)
        if light_atlas_chunks
        else np.zeros((1, 3), np.float32)
    )

    # power-weighted light selection distribution (lightdistrib.cpp
    # PowerLightDistribution); used when integrator asks for "power"
    power = np.zeros(max(n_lights, 1))
    for i, r in enumerate(light_rows[: max(n_lights, 1)]):
        lum_v = float(luminance(np.asarray(r["L"], np.float64)))
        if r["type"] == LIGHT_AREA:
            power[i] = lum_v * r["area"] * np.pi * (2.0 if r["twosided"] else 1.0)
        elif r["type"] == LIGHT_INFINITE:
            # the row carries L=1 (radiance lives in the envmap, already
            # scaled by L); power must reflect the map's mean luminance
            env_lum = float(np.mean(luminance(envmap.astype(np.float64)))) if envmap is not None else lum_v
            power[i] = env_lum * np.pi * wradius * wradius * 4
        elif r["type"] == LIGHT_DISTANT:
            power[i] = lum_v * np.pi * wradius * wradius
        elif r["type"] in (LIGHT_GONIO, LIGHT_PROJECTION):
            off, iw, ih = (int(v) for v in r["img"])
            mean_lum = float(
                np.mean(luminance(light_atlas[off : off + iw * ih].astype(np.float64)))
            )
            power[i] = lum_v * mean_lum * 4 * np.pi
        else:
            power[i] = lum_v * 4 * np.pi
    light_distr = Distribution1D.build(power if power.sum() > 0 else np.ones_like(power))

    # -- spatial light distribution (lightdistrib.cpp
    # SpatialLightDistribution): dense per-voxel CDFs, importance estimated
    # at voxel centers (center-point simplification of pbrt's 128-sample MC)
    spatial_distr = None
    _strategy = ro.integrator_params.find_one_string("lightsamplestrategy", "spatial")
    # dense tables scale O(voxels * light rows): build only when the scene
    # asks for the spatial strategy and the row count is sane (mesh area
    # lights emit one row per triangle; pbrt's lazy hash exists to avoid
    # exactly this blowup — past the cap we fall back to power)
    if n_lights > 1 and _strategy == "spatial" and n_lights <= 4096:
        res = (8, 8, 8)
        lo_g = wmin - 1e-3
        hi_g = wmax + 1e-3
        cs_g = np.maximum((hi_g - lo_g) / np.asarray(res), 1e-6)
        gx, gy, gz = res
        ii, jj, kk = np.meshgrid(
            np.arange(gx), np.arange(gy), np.arange(gz), indexing="ij"
        )
        centers = lo_g + (np.stack([ii, jj, kk], -1).reshape(-1, 3, order="F") + 0.5) * cs_g
        V = centers.shape[0]
        L = len(light_rows)
        imp = np.zeros((V, L), np.float64)
        for i, r in enumerate(light_rows):
            lum_v = float(luminance(np.asarray(r["L"], np.float64)))
            t = r["type"]
            if t in (LIGHT_POINT, LIGHT_SPOT, LIGHT_GONIO, LIGHT_PROJECTION):
                d2 = np.maximum(((centers - r["p"]) ** 2).sum(-1), 1e-6)
                base = lum_v / d2
                if t == LIGHT_SPOT:
                    toc = centers - r["p"]
                    toc /= np.maximum(np.linalg.norm(toc, axis=-1, keepdims=True), 1e-12)
                    cosw = toc @ np.asarray(r["dir"])
                    base = base * np.clip(
                        (cosw - r["cos1"]) / max(r["cos0"] - r["cos1"], 1e-6), 0.05, 1.0
                    )
                imp[:, i] = base
            elif t != LIGHT_AREA:  # distant / infinite: position-independent
                imp[:, i] = power[i] / max(power.sum(), 1e-12)
        # area lights vectorized: centroid distance falloff x luminance x
        # area (rows carry LEAF-ORDER tri ids; verts is leaf-ordered here)
        area_rows = [i for i, r in enumerate(light_rows) if r["type"] == LIGHT_AREA]
        if area_rows:
            tri_ids = np.asarray([light_rows[i]["tri"] for i in area_rows])
            cent = np.asarray(verts, np.float64).mean(axis=1)[tri_ids]  # (A,3)
            lum_a = np.asarray(
                [float(luminance(np.asarray(light_rows[i]["L"], np.float64))) for i in area_rows]
            )
            area_a = np.asarray([light_rows[i]["area"] for i in area_rows])
            d2 = np.maximum(
                ((centers[:, None, :] - cent[None, :, :]) ** 2).sum(-1), 1e-6
            )  # (V, A)
            imp[:, area_rows] = lum_a * area_a / d2
        row_sum = imp.sum(-1, keepdims=True)
        imp = np.where(row_sum > 0, imp / np.maximum(row_sum, 1e-30), 1.0 / L)
        cdf = np.cumsum(imp, -1).astype(np.float32)
        cdf[:, -1] = 1.0
        from tpu_pbrt.core.lights_dev import SpatialLightDistribution

        spatial_distr = SpatialLightDistribution(
            cdf=jnp.asarray(cdf),
            mean_pmf=jnp.asarray(imp.mean(0).astype(np.float32)),
            lo=jnp.asarray(lo_g, jnp.float32),
            inv_cs=jnp.asarray(1.0 / cs_g, jnp.float32),
            res=res,
        )

    # -- materials -------------------------------------------------------
    # non-constant textures lower to real device evaluators (VERDICT r3
    # #6): nodes are deduped by structure, compiled into per-texture jax
    # closures + one flat mip atlas by core/texture_eval.py
    deferred_textures: List = []
    _tex_ids: Dict[str, int] = {}

    def tex_registry(node):
        key = repr(node)
        tid = _tex_ids.get(key)
        if tid is None:
            tid = len(deferred_textures)
            _tex_ids[key] = tid
            deferred_textures.append(node)
        return tid

    mtab = lower_materials(mat_records, tex_registry,
                           getattr(api, "scene_dir", "."))

    tex_eval = None
    tex_atlas = None
    tex_used = set()
    if deferred_textures:
        from tpu_pbrt.core.texture_eval import build_texture_table

        tex_atlas, tex_eval = build_texture_table(deferred_textures)
        for slot, name in (
            ("kd_tex", "kd"), ("ks_tex", "ks"), ("sigma_tex", "sigma"),
            ("rough_tex", "rough"), ("opacity_tex", "opacity"),
        ):
            if (mtab[slot] >= 0).any():
                tex_used.add(name)
        if (mtab["bump_tex"] >= 0).any():
            Warning("bump textures are parsed but not applied (no shading-"
                    "normal perturbation yet)")

    # -- device upload ---------------------------------------------------
    # One acceleration structure only (VERDICT r1 weak #4: no duplicate
    # geometry in HBM). The stream (sort/compaction wavefront) tracer over
    # the two-level treelet BVH is the TPU-shaped default (accel/stream.py
    # — coherence-independent, sized for incoherent bounce waves); scenes
    # at or below BRUTE_MAX_TRIS skip the hierarchy and brute-force all
    # triangles in one feature matmul. TPU_PBRT_BVH=packet|wide|binary
    # selects the other walkers for A/B comparison. tri_verts is padded
    # (degenerate rows) so fixed-size leaf slices stay in bounds;
    # interaction gathers never index the padding (prim < n_tris).
    import os as _os

    from tpu_pbrt.accel.wide import build_wide, pad_tri_verts

    sss_rows = mtab.pop("_sss_rows", None)
    dev_bssrdf = None
    if sss_rows:
        # bake each subsurface material's per-channel beam-diffusion
        # profile (core/bssrdf.py module doc: albedo is constant per
        # material, so the (rho, r) spline table of bssrdf.cpp
        # collapses to one radial profile per (material, channel))
        from tpu_pbrt.core.bssrdf import N_RADII, BakedBSSRDF, bake_profile

        M = len(sss_rows)
        b_radii = np.zeros((M, 3, N_RADII), np.float32)
        b_prof = np.zeros((M, 3, N_RADII), np.float32)
        b_cdf = np.zeros((M, 3, N_RADII), np.float32)
        b_rho = np.zeros((M, 3), np.float32)
        b_rmax = np.zeros((M, 3), np.float32)
        b_eta = np.zeros((M,), np.float32)
        for mrow, (sigma_s, sigma_a, g_v, eta_v) in enumerate(sss_rows):
            b_eta[mrow] = eta_v
            for c in range(3):
                ra, pr, cd, re, rm = bake_profile(
                    float(np.asarray(sigma_s).reshape(-1)[c]),
                    float(np.asarray(sigma_a).reshape(-1)[c]),
                    g_v, eta_v,
                )
                b_radii[mrow, c], b_prof[mrow, c], b_cdf[mrow, c] = ra, pr, cd
                b_rho[mrow, c], b_rmax[mrow, c] = re, rm
        dev_bssrdf = BakedBSSRDF(
            radii=jnp.asarray(b_radii), profile=jnp.asarray(b_prof),
            cdf=jnp.asarray(b_cdf), rho_eff=jnp.asarray(b_rho),
            r_max=jnp.asarray(b_rmax), eta=jnp.asarray(b_eta),
        )

    dev = {
        "tri_verts": jnp.asarray(pad_tri_verts(verts), jnp.float32),
        **({"tri_verts1": jnp.asarray(pad_tri_verts(verts1), jnp.float32)}
           if verts1 is not None else {}),
        "tri_normals": jnp.asarray(normals, jnp.float32),
        "tri_uvs": jnp.asarray(uvs, jnp.float32),
        "tri_mat": jnp.asarray(mat_ids, jnp.int32),
        "tri_light": jnp.asarray(light_ids, jnp.int32),
        "mat": {
            k: (v[0] if k == "_fourier" else jnp.asarray(v))
            for k, v in mtab.items()
        },
        "light": {k: jnp.asarray(v) for k, v in lt.items()},
        "tri_med_in": jnp.asarray(med_in, jnp.int32),
        "tri_med_out": jnp.asarray(med_out, jnp.int32),
        "media": medium_table,
        "world_center": jnp.asarray(wcenter, jnp.float32),
        "world_radius": jnp.float32(wradius),
        "n_lights": jnp.int32(n_lights if light_rows else 0),
        **({"bssrdf": dev_bssrdf} if dev_bssrdf is not None else {}),
    }
    # Consolidated (T, 16) per-triangle shading row [n0 n1 n2 (9) |
    # uv0 uv1 uv2 (6) | mat*4096 + light+1 as exact f32]: one
    # row-friendly gather replaces four awkward-layout gathers in
    # make_interaction (profiled ~15 vs ~2.6 ns per fetched element on
    # the v5e). Only built when the ids fit the exact-f32 packing.
    n_mats_tab = len(mtab["type"]) if mtab else 0
    if n_mats_tab < 4096 and (n_lights if light_rows else 0) < 4095:
        pack = (
            np.asarray(mat_ids, np.int64) * 4096
            + np.asarray(light_ids, np.int64)
            + 1
        ).astype(np.float32)[:, None]
        # stored LANE-MAJOR (16, T): axis-1 takes gather at ~2.6 ns per
        # fetched element on the v5e where row-major (T, 16) row gathers
        # cost ~33
        dev["tri_sh16"] = jnp.asarray(
            np.concatenate(
                [
                    np.asarray(normals, np.float32).reshape(len(normals), 9),
                    np.asarray(uvs, np.float32).reshape(len(uvs), 6),
                    pack,
                ],
                axis=1,
            ).T.copy()
        )
    if "h_beta_m" in mtab or tex_atlas is not None:
        # uv-parameterization derivatives per triangle (triangle.cpp
        # dpdu/dpdv): hair needs the normalized dpdu as the shading
        # tangent; textured scenes need BOTH raw vectors for ray-
        # differential footprints (interaction.cpp ComputeDifferentials).
        # Stored lane-major; built only when something consumes them.
        duv02 = uvs[:, 0] - uvs[:, 2]
        duv12 = uvs[:, 1] - uvs[:, 2]
        dp02 = verts[:, 0] - verts[:, 2]
        dp12 = verts[:, 1] - verts[:, 2]
        det = duv02[:, 0] * duv12[:, 1] - duv02[:, 1] * duv12[:, 0]
        safe = np.abs(det) > 1e-12
        inv = 1.0 / np.where(safe, det, 1.0)
        dpdu_raw = (duv12[:, 1:2] * dp02 - duv02[:, 1:2] * dp12) * inv[:, None]
        dpdv_raw = (-duv12[:, 0:1] * dp02 + duv02[:, 0:1] * dp12) * inv[:, None]
        dpdu_raw = np.where(safe[:, None], dpdu_raw, 0.0)
        dpdv_raw = np.where(safe[:, None], dpdv_raw, 0.0)
        ln = np.linalg.norm(dpdu_raw, axis=-1, keepdims=True)
        dpdu_n = np.where(ln > 1e-12, dpdu_raw / np.maximum(ln, 1e-20), 0.0)
        if "h_beta_m" in mtab:
            dev["tri_tanT"] = jnp.asarray(dpdu_n.T.copy(), jnp.float32)
        if tex_atlas is not None:
            dev["tri_difT"] = jnp.asarray(
                np.concatenate(
                    [dpdu_raw.T, dpdv_raw.T, np.zeros((2, len(verts)))],
                    axis=0,
                ),
                jnp.float32,
            )  # (8, T): dpdu(3), dpdv(3), pad
    if light_rows:
        # per-light triangle vertices (area lights; zeros elsewhere) so
        # light sampling never gathers the big tri_verts array by the
        # per-ray picked light id
        lt_tri = np.asarray([r["tri"] for r in light_rows], np.int64)
        lv = np.asarray(verts, np.float32)[np.clip(lt_tri, 0, len(verts) - 1)]
        lv[lt_tri < 0] = 0.0
        dev["light"]["tri_v"] = jnp.asarray(lv)
        if verts1 is not None:
            # NEE/MIS light tables are built from the shutter-START
            # keyframe only; intersections lerp by ray time, so an
            # ANIMATED emissive shape gets statically-positioned light
            # sampling (pbrt samples lights at ref.time). Loud until the
            # light vertex table is time-lerped like Hit.tv.
            lv1 = np.asarray(verts1, np.float32)[
                np.clip(lt_tri, 0, len(verts) - 1)
            ]
            moving = (lt_tri >= 0) & (
                np.abs(lv1 - lv).max(axis=(1, 2)) > 1e-7
            )
            if np.any(moving):
                Warning(
                    f"{int(moving.sum())} area light(s) sit on ANIMATED "
                    "shapes: direct-light sampling uses the shutter-start "
                    "keyframe (approximation; MIS pdfs likewise)"
                )
    if tex_atlas is not None:
        dev["tex_atlas"] = jnp.asarray(tex_atlas, jnp.float32)
    if light_atlas_chunks:
        dev["light_atlas"] = jnp.asarray(light_atlas, jnp.float32)
    from tpu_pbrt.config import cfg

    accel_kind = cfg.bvh
    if verts1 is not None and accel_kind in ("binary", "wide"):
        Warning(
            "motion blur is only supported on the stream/brute accel "
            f"paths; this {accel_kind}-walker render is STATIC at "
            "shutter start"
        )
    if accel_kind == "binary":
        dev["bvh"] = bvh_as_device_dict(bvh)
    elif accel_kind == "wide":
        dev["wbvh"] = build_wide(bvh)
    else:
        from tpu_pbrt.accel.mxu import BRUTE_MAX_TRIS, tri_feature_weights
        from tpu_pbrt.accel.treelet import build_treelet_pack

        if len(verts) <= BRUTE_MAX_TRIS:
            if verts1 is not None:
                from tpu_pbrt.accel.mxu import tri_feature_weights_motion

                dev["bfeat"] = {
                    "feat": jnp.asarray(
                        tri_feature_weights_motion(verts, verts1, wcenter)
                    ),
                    "center": jnp.asarray(wcenter, jnp.float32),
                }
            else:
                dev["bfeat"] = {
                    "feat": jnp.asarray(tri_feature_weights(verts, wcenter)),
                    "center": jnp.asarray(wcenter, jnp.float32),
                }
        elif accel_kind == "packet":
            if verts1 is not None:
                Warning(
                    "motion blur is only supported on the stream/brute "
                    "accel paths; this packet-walker render is STATIC at "
                    "shutter start"
                )
            dev["tpack"] = build_treelet_pack(verts, bvh)
        else:
            from tpu_pbrt.accel.stream import STREAM_LEAF_TRIS

            leaf_tris = int(
                cfg.leaf_tris if cfg.leaf_tris is not None
                else STREAM_LEAF_TRIS
            )
            dev["tstream"] = build_treelet_pack(
                verts, bvh, leaf_tris=leaf_tris, tri_verts1=verts1
            )
            # lane-major (9, T) vertex table for _finalize_hits' winner
            # refetch, baked ONCE here: recomputing reshape(T, 9).T
            # inside the wave relayout-copied the whole triangle table
            # per dispatch (cost-pass finding
            # JC-RELAYOUT:stream_intersect:"transpose of (T, 9) buffer")
            T9 = dev["tri_verts"].shape[0]
            dev["tri_verts9T"] = dev["tri_verts"].reshape(T9, 9).T
            if verts1 is not None:
                dev["tri_verts1_9T"] = dev["tri_verts1"].reshape(T9, 9).T
    if has_envmap:
        dev["envmap"] = jnp.asarray(envmap, jnp.float32)
        dev["env_distr"] = env_distr
        dev["env_w2l"] = jnp.asarray(env_w2l[:3, :3], jnp.float32)

    distrib_name = ro.integrator_params.find_one_string("lightsamplestrategy", "spatial")

    return CompiledScene(
        dev=dev,
        film=film,
        camera=camera,
        sampler=sampler,
        integrator_name=ro.integrator_name,
        integrator_params=ro.integrator_params,
        n_tris=len(verts),
        n_lights=n_lights,
        world_min=wmin,
        world_max=wmax,
        world_center=wcenter,
        world_radius=wradius,
        has_envmap=has_envmap,
        env_distribution=env_distr,
        light_distribution_name=distrib_name,
        light_distr=light_distr,
        media=dict(ro.named_media),
        camera_medium_id=camera_medium_id,
        has_null_materials=bool(np.any(np.asarray(mtab["type"])[np.asarray(mat_ids)] == MAT_NONE)),
        tex_eval=tex_eval,
        tex_used=frozenset(tex_used),
        spatial_distr=spatial_distr,
    )
