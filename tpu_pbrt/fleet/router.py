"""The fleet front door: scene-affinity routing, edge shedding, and
checkpoint-spool failover across N serve replicas.

Protocol shape (modeled decision-by-decision in protocheck's
``FleetModel`` before this module existed — the invariants came first):

- **route** — a submit hashes its scene key onto a consistent-hash
  ring of healthy replicas. Affinity is the point: the same key routes
  to the same replica while that replica stays healthy, so a warm
  resubmit finds its compiled scene resident (PROTO-ROUTE-AFFINITY).
- **edge shed** — before anything compiles, the offered arrival rate
  over a sliding window is compared against the fleet's capacity
  (``knee_req_s x healthy replicas`` — the measured ``--capacity``
  knee). Over-capacity submits are answered with the same
  deterministic ``ShedError`` contract the per-replica SLO uses.
- **failover** — the router polls each replica's health verdict; a
  wedged or backoff-storming replica is drained (its runnable jobs
  park through the emergency-checkpoint path) and each of its live
  jobs is re-submitted on another replica with the SAME router-owned
  spool checkpoint path, so the new replica's activation resumes from
  the durable cursor. Chunks are idempotent pure functions and film
  accumulation from the cursor is sequential, so the resumed film is
  BIT-identical to an undisturbed render (PROTO-ROUTE-FILM).
- **consume-the-spool dedup** — a failover terminates the old
  instance before the new one exists (cancel on drain; the replica is
  dead on kill), and the router's job table plus a bounded dedup
  window refuse a second delivery of a job id that was already
  admitted. A job never renders twice (PROTO-ROUTE-DUP); the
  ``failover-skips-spool-consume`` mutant seeds the regression.

Trace contract (the cross-process satellite): the router mints
``t:<job>`` and owns the root ``serve/job`` async span; replicas get
the id as a caller-supplied trace context and never open or close the
root, so one request — including a failover's re-route/resume — is a
single ``tools/scope.py --check``-clean timeline.
"""

from __future__ import annotations

import hashlib
import os
from bisect import bisect_right
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from tpu_pbrt.serve.service import (
    _RUNNABLE,
    _TERMINAL,
    PAUSED,
    RenderService,
    ShedError,
)
from tpu_pbrt.utils.clock import WALL

#: the measured steady-scenario capacity knee (req/s one replica
#: sustains at the p99 queue-wait SLO) from
#: ``python -m tpu_pbrt.load --capacity steady`` — the edge-shedding
#: threshold and the sizing formula's denominator
KNEE_REQ_S = 159.5


def fleet_size(offered_req_s: float, knee_req_s: float = KNEE_REQ_S) -> int:
    """The capacity-derived sizing formula:
    ``replicas = ceil(offered / knee)`` (README "Fleet serving")."""
    import math

    return max(1, math.ceil(float(offered_req_s) / float(knee_req_s)))


@dataclass(frozen=True)
class FleetPolicy:
    """Router knobs — all deterministic inputs, no hidden state."""

    #: per-replica sustainable req/s (the --capacity knee); the edge
    #: admits while offered <= knee x healthy replicas
    knee_req_s: float = KNEE_REQ_S
    #: sliding window (seconds) the offered arrival rate is measured
    #: over at the edge
    rate_window_s: float = 1.0
    #: virtual nodes per replica on the hash ring — enough to spread
    #: keys evenly at small N without making the ring expensive
    vnodes: int = 16
    #: admitted job ids remembered after they leave the job table —
    #: the double-delivery refusal horizon
    dedup_window: int = 256


@dataclass
class _JobRecord:
    """The router's view of one admitted job: where it lives, how to
    re-submit it on failover, and the trace/spool handles it owns."""

    job_id: str
    key: str  # scene-affinity routing key (== the residency key)
    rid: str  # owning replica id
    trace_id: str
    checkpoint_path: str  # router-owned durable spool entry
    #: submit kwargs replayed verbatim on failover (None after a
    #: router restart: the rebuilt table can route/poll/cancel but a
    #: job whose source is unknown cannot be re-submitted)
    resubmit: Optional[Dict[str, Any]] = None
    terminal: str = ""  # fleet-wide terminal outcome, "" while live
    failovers: int = 0
    root_open: bool = True  # the root serve/job span awaits its end


class LocalReplica:
    """One in-process replica: a real RenderService under the shared
    (usually virtual) clock. The deterministic-testing backend — the
    whole fleet is then a pure function of the decision sequence."""

    kind = "local"

    def __init__(
        self,
        rid: str,
        *,
        clock=None,
        spool_dir: Optional[str] = None,
        seed: int = 0,
        slo=None,
        max_active: Optional[int] = None,
        chunk: Optional[int] = None,
        mesh=None,
    ):
        self.rid = rid
        self.alive = True
        self.draining = False
        if spool_dir is not None:
            os.makedirs(spool_dir, exist_ok=True)
        self.service = RenderService(
            mesh=mesh, chunk=chunk, max_active=max_active, seed=seed,
            spool_dir=spool_dir, quiet=True, slo=slo, clock=clock,
        )

    # -- submit/lifecycle forwarding ---------------------------------------
    def submit(self, **kw) -> str:
        return self.service.submit(**kw)

    def poll(self, job_id: str) -> Dict[str, Any]:
        return self.service.poll(job_id)

    def status(self, job_id: str) -> Optional[str]:
        j = self.service.jobs.get(job_id)
        return None if j is None else j.status

    def result(self, job_id: str):
        return self.service.result(job_id)

    def cancel(self, job_id: str) -> None:
        self.service.cancel(job_id)

    def stats(self) -> Dict[str, Any]:
        return self.service.stats()

    def health(self) -> Dict[str, Any]:
        from tpu_pbrt.obs.health import evaluate

        rep = evaluate(self.service)
        return {"ok": rep.ok, "firing": rep.firing()}

    # -- scheduling (local-only: daemons step themselves) ------------------
    def step(self) -> Optional[str]:
        return self.service.step()

    def has_ready(self, now: float) -> bool:
        """Dispatchable work as of `now` — a pure observation (the
        shared `now` threads through, so checking N replicas never
        perturbs the decision clock)."""
        return bool(self.service._runnable(now))

    def backoff_deadlines(self, now: float) -> List[float]:
        return [
            j.not_before for j in self.service.jobs.values()
            if j.status in _RUNNABLE and j.not_before > now
        ]

    # -- handoff -----------------------------------------------------------
    def drain(self) -> Dict[str, Any]:
        self.draining = True
        return self.service.begin_drain()

    def kill(self) -> None:
        """Abrupt death. A real process would just vanish — its device
        memory and its trace file with it. In-process the recorders are
        shared, so the equivalent is: drop every device reference and
        close the open wait/slice spans (aborted), writing NOTHING
        durable — the spool keeps exactly what was already
        checkpointed, which is all a restarted peer could ever see."""
        self.alive = False
        svc = self.service
        for j in svc.jobs.values():
            if j.status not in _TERMINAL:
                svc._release_device(j)
                j.plan = None
                svc._trace_wait_end(j)


class FleetRouter:
    """The front door. Deterministic given (replica set, policy, clock,
    decision sequence): routing is a pure hash, edge shedding a pure
    function of the arrival window, and failover an explicit decision
    — which is what lets protocheck's FleetModel explore the whole
    route/re-route/resume-elsewhere/double-delivery grid exhaustively.
    """

    def __init__(
        self,
        replicas,
        *,
        clock=None,
        policy: Optional[FleetPolicy] = None,
        spool_dir: Optional[str] = None,
    ):
        self.clock = clock if clock is not None else WALL
        self.policy = policy if policy is not None else FleetPolicy()
        if spool_dir is None:
            import tempfile

            spool_dir = tempfile.mkdtemp(prefix="tpu_pbrt_fleet_")
        os.makedirs(spool_dir, exist_ok=True)
        self.spool_dir = spool_dir
        self.replicas: "OrderedDict[str, Any]" = OrderedDict(
            (r.rid, r) for r in replicas
        )
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        # the consistent-hash ring: policy.vnodes points per replica,
        # content-hashed (sha256 — stable across processes and
        # PYTHONHASHSEED) so the key->replica map is a pure function
        # of the replica-id set
        self._ring: List[Tuple[int, str]] = sorted(
            (self._hash(f"{rid}#{v}"), rid)
            for rid in self.replicas
            for v in range(self.policy.vnodes)
        )
        self.jobs: Dict[str, _JobRecord] = {}
        #: admitted ids remembered past the job table (bounded) — the
        #: double-delivery refusal window
        self._dedup: "OrderedDict[str, str]" = OrderedDict()
        self._arrivals: deque = deque()
        self._seq = 0
        self._rr = 0  # step() rotation cursor
        self.edge_sheds = 0
        #: routing decisions [(job_id, key, rid)] — the affinity
        #: evidence protocheck and the tests assert on
        self.routes: List[Tuple[str, str, str]] = []

    # -- ring --------------------------------------------------------------
    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.sha256(s.encode()).digest()[:8], "big"
        )

    def healthy(self) -> List[str]:
        return [
            rid for rid, r in self.replicas.items()
            if r.alive and not r.draining
        ]

    def route_key(self, key: str) -> str:
        """The ring walk: first healthy replica at/after the key's
        point, clockwise. Removing one replica re-routes ONLY the keys
        that pointed at it — every other key keeps its affinity."""
        healthy = set(self.healthy())
        if not healthy:
            raise RuntimeError(
                "no healthy replica to route to (all drained or dead)"
            )
        h = self._hash(key)
        n = len(self._ring)
        i = bisect_right(self._ring, (h, ""))
        for off in range(n):
            _, rid = self._ring[(i + off) % n]
            if rid in healthy:
                return rid
        raise RuntimeError("unreachable: healthy set non-empty")

    # -- edge admission ----------------------------------------------------
    def _edge_admit(self, now: float, tenant: str, priority: int) -> None:
        """Fleet-level SLO shedding BEFORE any replica compiles: the
        offered rate over the sliding arrival window (this arrival
        included) against knee x healthy. Deterministic — same arrival
        times, same healthy set, same sheds."""
        w = self.policy.rate_window_s
        arr = self._arrivals
        while arr and arr[0] <= now - w:
            arr.popleft()
        offered = (len(arr) + 1) / w
        cap = self.policy.knee_req_s * max(len(self.healthy()), 1)
        if offered > cap:
            self.edge_sheds += 1
            reason = (
                f"fleet-edge: offered {offered:g} req/s > capacity "
                f"{cap:g} (knee {self.policy.knee_req_s:g} x "
                f"{len(self.healthy())} replica(s))"
            )
            from tpu_pbrt.obs.flight import FLIGHT
            from tpu_pbrt.obs.metrics import METRICS
            from tpu_pbrt.obs.trace import TRACE

            METRICS.counter(
                "fleet_edge_shed_total",
                "submits refused at the fleet edge (offered > knee x "
                "healthy)",
            ).inc(tenant=tenant, priority=priority)
            FLIGHT.heartbeat(
                "fleet_shed", tenant=tenant, priority=priority,
                reason=reason,
            )
            # same zero-length pseudo-trace the per-replica shed path
            # emits: the refusal is part of the fleet timeline
            tid = TRACE.trace_id(f"fshed{self.edge_sheds}")
            TRACE.async_begin(
                "serve/job", id=tid, cat="job", outcome="shed",
                tenant=tenant, priority=priority, reason=reason,
                trace_id=tid,
            )
            TRACE.async_end(
                "serve/job", id=tid, cat="job", outcome="shed"
            )
            raise ShedError(
                f"submit shed: {reason}", tenant=tenant,
                priority=priority, reason=reason,
            )
        arr.append(now)

    # -- submit ------------------------------------------------------------
    def _spool_path(self, job_id: str) -> str:
        return os.path.join(self.spool_dir, f"{job_id}.ckpt.npz")

    def submit(
        self,
        path: Optional[str] = None,
        *,
        text: Optional[str] = None,
        compiled=None,
        resident_key: Optional[str] = None,
        options=None,
        job_id: Optional[str] = None,
        tenant: str = "default",
        priority: int = 0,
        weight: Optional[float] = None,
        chunk: Optional[int] = None,
        checkpoint_every: int = 0,
        preview_every: int = 0,
        preview_path: str = "",
        outfile: str = "",
    ) -> str:
        """Route one submit. Returns the job id; a duplicate id (still
        tracked, or inside the dedup window) returns the EXISTING
        assignment without touching any replica — the double-delivery
        guard. Raises ShedError at the fleet edge (over capacity) or
        from the routed replica's own SLO admission."""
        if job_id is not None and (
            job_id in self.jobs or job_id in self._dedup
        ):
            return job_id  # already delivered once; never render twice
        now = self.clock.peek()
        self._edge_admit(now, tenant, int(priority))
        key = self._routing_key(
            path=path, text=text, compiled=compiled,
            resident_key=resident_key, options=options,
        )
        rid = self.route_key(key)
        self._seq += 1
        if job_id is None:
            job_id = f"f{self._seq}"
        from tpu_pbrt.obs.trace import TRACE

        trace_id = TRACE.trace_id(job_id)
        resubmit = dict(
            path=path, text=text, compiled=compiled, resident_key=key,
            options=options, tenant=tenant, priority=int(priority),
            weight=weight, chunk=chunk,
            checkpoint_every=int(checkpoint_every),
            preview_every=int(preview_every), preview_path=preview_path,
            outfile=outfile,
        )
        # the root span opens at the ROUTER — the replicas see a
        # caller-supplied trace context and never re-open it, so a
        # failover's second submit continues this same timeline
        TRACE.async_begin(
            "serve/job", id=trace_id, cat="job", job=job_id,
            tenant=tenant, priority=int(priority), trace_id=trace_id,
            replica=rid,
        )
        try:
            self.replicas[rid].submit(
                job_id=job_id, trace_id=trace_id,
                checkpoint_path=self._spool_path(job_id), **resubmit,
            )
        except ShedError:
            TRACE.async_end(
                "serve/job", id=trace_id, cat="job", outcome="shed",
            )
            raise
        except Exception:
            TRACE.async_end(
                "serve/job", id=trace_id, cat="job", outcome="failed",
            )
            raise
        self.jobs[job_id] = _JobRecord(
            job_id=job_id, key=key, rid=rid, trace_id=trace_id,
            checkpoint_path=self._spool_path(job_id), resubmit=resubmit,
        )
        self._remember(job_id, rid)
        self.routes.append((job_id, key, rid))
        return job_id

    def _routing_key(
        self, *, path, text, compiled, resident_key, options,
    ) -> str:
        """The affinity key — the same residency key the replica will
        compute, so routing affinity IS residency affinity."""
        if resident_key:
            return resident_key
        from tpu_pbrt.serve.residency import scene_source_key

        opt_extra = (
            getattr(options, "crop_window", None),
            getattr(options, "quick_render", False),
            getattr(options, "image_file", ""),
        )
        if path is not None:
            return scene_source_key(path=path, extra=opt_extra)
        if text is not None:
            return scene_source_key(text=text, extra=opt_extra)
        if compiled is not None:
            raise ValueError(
                "routing a precompiled pair needs an explicit "
                "resident_key (affinity must be content-derived)"
            )
        raise ValueError("submit needs a path, text, or compiled pair")

    def _remember(self, job_id: str, rid: str) -> None:
        self._dedup[job_id] = rid
        self._dedup.move_to_end(job_id)
        while len(self._dedup) > self.policy.dedup_window:
            self._dedup.popitem(last=False)

    # -- scheduling (local replicas) ---------------------------------------
    def step(self) -> Optional[Tuple[str, str]]:
        """Dispatch one chunk-slice somewhere in the fleet: rotate over
        the alive replicas that have dispatchable work at one shared
        observation of the clock; when nothing is dispatchable but
        backoff windows are open, wait out the earliest fleet-wide
        deadline and retry once. Returns (replica id, job id), or None
        when the whole fleet is idle. Local replicas only — daemon
        replicas run their own loops."""
        now = self.clock.peek()
        picked = self._pick(now)
        if picked is None:
            deadlines = [
                d for r in self.replicas.values() if r.alive
                for d in r.backoff_deadlines(now)
            ]
            if not deadlines:
                return None
            self.clock.sleep(max(min(deadlines) - now, 0.0))
            picked = self._pick(self.clock.peek())
            if picked is None:
                return None
        rid = picked
        job = self.replicas[rid].step()
        self._note_progress(rid)
        if job is None:
            return None
        return (rid, job)

    def _pick(self, now: float) -> Optional[str]:
        rids = [
            rid for rid, r in self.replicas.items()
            if r.alive and r.kind == "local" and r.has_ready(now)
        ]
        if not rids:
            return None
        order = list(self.replicas)
        # rotation: continue after the last-stepped replica, so equal
        # backlogs share the dispatch budget deterministically
        rids.sort(key=lambda rid: (
            (order.index(rid) - self._rr - 1) % len(order)
        ))
        self._rr = list(self.replicas).index(rids[0])
        return rids[0]

    def step_replica(self, rid: str) -> Optional[str]:
        """Step one NAMED replica (the explorer's interleaving
        decision) and run the terminal bookkeeping."""
        r = self.replicas[rid]
        if not r.alive:
            raise ValueError(f"replica {rid} is dead")
        job = r.step()
        self._note_progress(rid)
        return job

    def drain_fleet(self, max_steps: int = 1_000_000) -> None:
        """step() until the whole fleet is idle."""
        for _ in range(max_steps):
            if self.step() is None:
                return
        raise RuntimeError("fleet drain exceeded max_steps")

    def _note_progress(self, rid: str) -> None:
        """Scan the stepped replica for newly-terminal jobs: close
        their root spans with the fleet-wide outcome and consume their
        spool entries (a prefetch failure can terminate a job other
        than the stepped one, so the scan covers every record there)."""
        r = self.replicas[rid]
        for rec in self.jobs.values():
            if rec.terminal or rec.rid != rid:
                continue
            st = r.status(rec.job_id)
            if st in _TERMINAL:
                self._note_terminal(rec, st)

    def _note_terminal(self, rec: _JobRecord, status: str) -> None:
        from tpu_pbrt.obs.trace import TRACE
        from tpu_pbrt.parallel.checkpoint import delete_checkpoint

        rec.terminal = status
        if rec.root_open:
            rec.root_open = False
            r = self.replicas.get(rec.rid)
            chunks = 0
            if r is not None and r.alive:
                try:
                    chunks = int(r.poll(rec.job_id).get("chunks_done", 0))
                except Exception:  # noqa: BLE001 — daemon race at exit
                    chunks = 0
            TRACE.async_end(
                "serve/job", id=rec.trace_id, cat="job", outcome=status,
                chunks=chunks,
            )
        if status != "failed":
            # consume the spool: the durable entry exists for resume;
            # a done/cancelled job must not leave a stale cursor a
            # later failover could resurrect. Failed jobs keep theirs
            # for post-mortem.
            delete_checkpoint(rec.checkpoint_path)

    # -- verbs forwarded by ownership --------------------------------------
    def _rec(self, job_id: str) -> _JobRecord:
        rec = self.jobs.get(job_id)
        if rec is None:
            raise KeyError(f"unknown fleet job {job_id!r}")
        return rec

    def owner(self, job_id: str) -> str:
        return self._rec(job_id).rid

    def poll(self, job_id: str) -> Dict[str, Any]:
        rec = self._rec(job_id)
        out = self.replicas[rec.rid].poll(job_id)
        out["replica"] = rec.rid
        out["failovers"] = rec.failovers
        return out

    def result(self, job_id: str):
        rec = self._rec(job_id)
        return self.replicas[rec.rid].result(job_id)

    def cancel(self, job_id: str) -> None:
        rec = self._rec(job_id)
        r = self.replicas.get(rec.rid)
        if r is not None and r.alive:
            r.cancel(job_id)
        if not rec.terminal:
            self._note_terminal(rec, "cancelled")

    def stats(self) -> Dict[str, Any]:
        live = [r for r in self.jobs.values() if not r.terminal]
        return {
            "replicas": {
                rid: {
                    "alive": r.alive,
                    "draining": r.draining,
                    "jobs": sum(1 for j in live if j.rid == rid),
                }
                for rid, r in self.replicas.items()
            },
            "jobs": len(self.jobs),
            "live": len(live),
            "edge_sheds": self.edge_sheds,
            "routes": len(self.routes),
        }

    # -- health-driven drain & failover ------------------------------------
    def check_health(self) -> Dict[str, List[str]]:
        """Poll every routable replica's health verdict; drain any
        whose wedge or backoff_storm condition fires (the two verdicts
        that mean the replica is no longer making progress — slo_burn
        and nonfinite_spike are load/content signals the router answers
        with shedding, not eviction). Returns {rid: firing}."""
        firing: Dict[str, List[str]] = {}
        for rid in self.healthy():
            verdict = self.replicas[rid].health()
            flags = list(verdict.get("firing", []))
            if flags:
                firing[rid] = flags
            if {"wedge", "backoff_storm"} & set(flags):
                self.drain_replica(rid)
        return firing

    def drain_replica(self, rid: str) -> List[str]:
        """Graceful eviction: the replica sheds new submits and parks
        its runnable jobs (durable spool writes), then every live job
        it owned fails over to a surviving replica. Returns the moved
        job ids."""
        from tpu_pbrt.obs.trace import TRACE

        r = self.replicas[rid]
        if not r.alive or r.draining:
            return []
        r.draining = True
        TRACE.instant("fleet/drain", replica=rid)
        r.drain()
        return self._failover_all(rid, cancel_old=True)

    def kill_replica(self, rid: str) -> List[str]:
        """Abrupt replica death (the chaos row): no goodbye, no final
        checkpoint — survivors adopt its jobs from whatever the spool
        already holds (possibly nothing: then the job restarts from
        chunk 0, which is still bit-identical)."""
        from tpu_pbrt.obs.trace import TRACE

        r = self.replicas[rid]
        if not r.alive:
            return []
        TRACE.instant("fleet/replica_kill", replica=rid)
        r.kill()
        return self._failover_all(rid, cancel_old=False)

    def _failover_all(self, rid: str, *, cancel_old: bool) -> List[str]:
        moved = []
        for rec in list(self.jobs.values()):
            if rec.rid == rid and not rec.terminal:
                self._failover_job(rec.job_id, rid, cancel_old=cancel_old)
                moved.append(rec.job_id)
        return moved

    def _failover_job(
        self, job_id: str, from_rid: str, *, cancel_old: bool = True,
    ) -> str:
        """Move one live job: CONSUME the old instance (cancel it on a
        drained-but-alive replica — a dead one consumed itself), then
        re-submit on a surviving replica with the same spool checkpoint
        path, so activation resumes from the durable cursor. The order
        is the dedup guarantee: at no point do two replicas both
        consider the job theirs — the seeded mutant that skips the
        consume is exactly a double render."""
        from tpu_pbrt.obs.trace import TRACE

        rec = self._rec(job_id)
        if rec.resubmit is None:
            raise RuntimeError(
                f"job {job_id} cannot fail over: its submit source was "
                "lost across a router restart"
            )
        old = self.replicas.get(from_rid)
        if cancel_old and old is not None and old.alive:
            old.cancel(job_id)  # explicit checkpoint_path: spool survives
        to_rid = self.route_key(rec.key)
        TRACE.instant(
            "fleet/failover", job=job_id, src=from_rid, dst=to_rid,
            trace_id=rec.trace_id,
        )
        self.replicas[to_rid].submit(
            job_id=job_id, trace_id=rec.trace_id,
            checkpoint_path=rec.checkpoint_path, **rec.resubmit,
        )
        rec.rid = to_rid
        rec.failovers += 1
        self._remember(job_id, to_rid)
        self.routes.append((job_id, rec.key, to_rid))
        return to_rid

    # -- restart recovery --------------------------------------------------
    @classmethod
    def adopt(
        cls,
        replicas,
        *,
        clock=None,
        policy: Optional[FleetPolicy] = None,
        spool_dir: str,
    ) -> "FleetRouter":
        """Router restart: build a fresh router over the SAME replicas
        and rebuild the routing table from each replica's `stats` verb
        — ownership, scene keys, and open root spans are recovered, so
        no job is lost and every in-flight trace still gets exactly one
        terminal close. (Jobs recovered this way can be polled,
        stepped, cancelled — but not failed over: the submit source
        died with the old router.)"""
        router = cls(
            replicas, clock=clock, policy=policy, spool_dir=spool_dir,
        )
        for rid, r in router.replicas.items():
            if not r.alive:
                continue
            st = r.stats()
            for job_id, p in sorted(st.get("jobs", {}).items()):
                if job_id in router.jobs:
                    continue  # first-seen owner wins (dup = defect)
                from tpu_pbrt.obs.trace import TRACE

                rec = _JobRecord(
                    job_id=job_id, key=p.get("scene", job_id), rid=rid,
                    trace_id=TRACE.trace_id(job_id),
                    checkpoint_path=router._spool_path(job_id),
                    resubmit=None,
                )
                status = p.get("status", "")
                if status in _TERMINAL:
                    rec.terminal = status
                    rec.root_open = False  # closed by the old router
                router.jobs[job_id] = rec
                router._remember(job_id, rid)
        return router

    # -- idleness ----------------------------------------------------------
    def idle(self) -> bool:
        return all(
            rec.terminal or self._paused(rec) for rec in self.jobs.values()
        )

    def _paused(self, rec: _JobRecord) -> bool:
        r = self.replicas.get(rec.rid)
        return (
            r is not None and r.alive
            and r.status(rec.job_id) == PAUSED
        )
