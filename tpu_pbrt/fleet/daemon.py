"""DaemonReplica: a fleet replica backed by a child
``python -m tpu_pbrt.serve`` JSONL daemon — the real-deployment shape
behind the same handle interface ``LocalReplica`` gives the
deterministic tests.

The wire protocol is the daemon's documented one (serve/__main__.py):
one JSON object per line each way, asynchronous ``{"event": ...}``
completion lines interleaved with responses. The router's verbs map
1:1 — submit carries the router-minted trace id in the ``trace`` field
and the router-owned spool path in ``checkpoint``, drain is the
``drain`` verb, health the ``health`` verb. Two deliberate
asymmetries vs LocalReplica:

- the router never steps a daemon (``has_ready`` is always False;
  the child's own loop renders between commands), so ``FleetRouter.
  step()`` only drives in-process replicas;
- job terminality is observed via ``poll``/collected events rather
  than shared objects, and ``kill()`` is a real SIGKILL — process
  death, not a simulation of one.
"""

from __future__ import annotations

import json
import subprocess
import sys
from typing import Any, Dict, List, Optional


class DaemonReplica:
    """Handle on one child serve daemon."""

    kind = "daemon"

    def __init__(
        self,
        rid: str,
        *,
        spool_dir: Optional[str] = None,
        seed: int = 0,
        chunk: Optional[int] = None,
        extra_args: Optional[List[str]] = None,
    ):
        self.rid = rid
        self.alive = True
        self.draining = False
        #: asynchronous {"event": ...} lines collected while waiting
        #: for responses — done/failed completions land here
        self.events: List[Dict[str, Any]] = []
        argv = [sys.executable, "-m", "tpu_pbrt.serve",
                "--seed", str(int(seed))]
        if spool_dir:
            argv += ["--spool", spool_dir]
        if chunk:
            argv += ["--chunk", str(int(chunk))]
        argv += list(extra_args or [])
        self.proc = subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1,
        )

    # -- wire --------------------------------------------------------------
    def _rpc(self, req: Dict[str, Any]) -> Dict[str, Any]:
        if not self.alive or self.proc.poll() is not None:
            raise RuntimeError(f"daemon replica {self.rid} is not running")
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()
        while True:
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"daemon replica {self.rid} closed its pipe "
                    f"mid-request ({req.get('op')})"
                )
            msg = json.loads(line)
            if "event" in msg:
                self.events.append(msg)
                continue
            return msg

    # -- submit/lifecycle --------------------------------------------------
    def submit(
        self,
        path: Optional[str] = None,
        *,
        text: Optional[str] = None,
        compiled=None,
        resident_key: Optional[str] = None,
        options=None,
        job_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        checkpoint_path: str = "",
        tenant: str = "default",
        priority: int = 0,
        weight: Optional[float] = None,
        chunk: Optional[int] = None,
        checkpoint_every: int = 0,
        preview_every: int = 0,
        preview_path: str = "",
        outfile: str = "",
    ) -> str:
        if compiled is not None:
            raise ValueError(
                "a compiled (scene, integrator) pair cannot cross a "
                "process boundary — submit a path or inline text"
            )
        req: Dict[str, Any] = {"op": "submit"}
        if path is not None:
            req["scene"] = path
        if text is not None:
            req["text"] = text
        if job_id:
            req["job"] = job_id
        if trace_id:
            req["trace"] = trace_id
        if checkpoint_path:
            req["checkpoint"] = checkpoint_path
        if tenant != "default":
            req["tenant"] = tenant
        if priority:
            req["priority"] = int(priority)
        if weight is not None:
            req["weight"] = weight
        if chunk:
            req["chunk"] = int(chunk)
        if checkpoint_every:
            req["checkpoint_every"] = int(checkpoint_every)
        if preview_every:
            req["preview_every"] = int(preview_every)
        if preview_path:
            req["preview"] = preview_path
        if outfile:
            req["outfile"] = outfile
        if options is not None:
            crop = getattr(options, "crop_window", None)
            if crop:
                req["crop"] = list(crop)
            if getattr(options, "quick_render", False):
                req["quick"] = True
        ans = self._rpc(req)
        if ans.get("shed"):
            from tpu_pbrt.serve.service import ShedError

            raise ShedError(
                f"submit shed: {ans.get('reason', '')}",
                tenant=ans.get("tenant", tenant),
                priority=int(ans.get("priority", priority)),
                reason=ans.get("reason", ""),
            )
        if not ans.get("ok"):
            raise RuntimeError(
                f"daemon replica {self.rid} refused submit: {ans}"
            )
        return ans["job"]

    def poll(self, job_id: str) -> Dict[str, Any]:
        ans = self._rpc({"op": "poll", "job": job_id})
        if not ans.get("ok"):
            raise KeyError(f"unknown job {job_id!r} on {self.rid}: {ans}")
        return ans

    def status(self, job_id: str) -> Optional[str]:
        try:
            return self.poll(job_id).get("status")
        except (KeyError, RuntimeError):
            return None

    def result(self, job_id: str, out: str = "") -> Dict[str, Any]:
        """The daemon's result answer (rays/seconds/mean/stats); `out`
        additionally writes the image file daemon-side."""
        req = {"op": "result", "job": job_id}
        if out:
            req["out"] = out
        ans = self._rpc(req)
        if not ans.get("ok"):
            raise RuntimeError(f"result for {job_id!r} failed: {ans}")
        return ans

    def cancel(self, job_id: str) -> None:
        self._rpc({"op": "cancel", "job": job_id})

    def stats(self) -> Dict[str, Any]:
        ans = self._rpc({"op": "stats"})
        ans.pop("ok", None)
        ans.pop("op", None)
        return ans

    def health(self) -> Dict[str, Any]:
        ans = self._rpc({"op": "health"})
        return {
            "ok": bool(ans.get("ok")) and not ans.get("firing"),
            "firing": list(ans.get("firing", [])),
        }

    # -- scheduling: the child steps itself --------------------------------
    def step(self) -> Optional[str]:
        return None

    def has_ready(self, now: float) -> bool:
        return False

    def backoff_deadlines(self, now: float) -> List[float]:
        return []

    # -- handoff -----------------------------------------------------------
    def drain(self) -> Dict[str, Any]:
        self.draining = True
        return self._rpc({"op": "drain"})

    def kill(self) -> None:
        """SIGKILL — the abrupt-death failover path. The spool keeps
        exactly what the child already checkpointed."""
        self.alive = False
        self.proc.kill()
        self.proc.wait(timeout=10)

    def shutdown(self, drain: bool = True) -> int:
        """Graceful exit: the daemon finishes (drain=True) or abandons
        its queue, then the process ends. Returns the exit code."""
        self.alive = False
        try:
            self.proc.stdin.write(
                json.dumps({"op": "shutdown", "drain": drain}) + "\n"
            )
            self.proc.stdin.flush()
            self.proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass
        return self.proc.wait(timeout=120)
