"""`python -m tpu_pbrt.fleet` — the fleet-router frontend.

`--selftest` is the CI smoke (ISSUE 20): two REAL in-process replicas
under one VirtualClock behind a FleetRouter, exercising the whole
handoff protocol on a real (small) cornell scene:

- scene-affinity: a resubmit of the same scene routes to the same
  replica and pays zero scene compiles (residency warm hit);
- fleet-edge shedding: with the capacity knee clamped down, an
  over-offered burst is refused at the edge before any compile;
- kill-one failover: a replica is killed mid-job past a durable
  checkpoint; the job resumes on the survivor from the spool and the
  final film is BIT-identical to the undisturbed solo render;
- cross-replica trace: when tracing is armed (TPU_PBRT_TRACE_PATH),
  the exported timeline carries ONE root span per job across the
  re-route — `tools/scope.py --check` validates it in CI.

`--daemon-smoke` additionally round-trips one job through a real
child JSONL daemon (DaemonReplica): submit with a router trace id,
drain verb, graceful shutdown. Slower (a process spawn + jax import);
not part of the default smoke.

Exit 0 = pass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_pbrt.fleet",
        description="tpu-pbrt fleet router over N serve replicas",
    )
    p.add_argument(
        "--selftest", action="store_true",
        help="run the fleet smoke (2 in-process replicas, affinity + "
        "edge shed + kill-one failover bit-identity) and exit",
    )
    p.add_argument(
        "--daemon-smoke", action="store_true",
        help="also round-trip one job through a child JSONL daemon "
        "(slow: process spawn + jax import)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--chunk", type=int, default=256,
        help="slice width in camera rays (preemption quantum)",
    )
    return p


def selftest(args) -> int:
    import numpy as np

    from tpu_pbrt.fleet import FleetPolicy, FleetRouter, LocalReplica
    from tpu_pbrt.obs.flight import FLIGHT
    from tpu_pbrt.obs.trace import TRACE
    from tpu_pbrt.scene.api import Options, compile_string
    from tpu_pbrt.scenes import cornell_box_text
    from tpu_pbrt.serve.service import DONE, ShedError
    from tpu_pbrt.utils.clock import VirtualClock

    def say(msg):
        print(f"fleet-selftest: {msg}", file=sys.stderr)

    fails = []
    text = cornell_box_text(res=32, spp=1, integrator="path", maxdepth=3)

    say("rendering solo reference")
    scene, integ = compile_string(text, Options(quiet=True))
    ref = np.asarray(integ.render(scene).image, np.float32)

    clock = VirtualClock(start=0.0, tick=1e-6)
    tmp = tempfile.mkdtemp(prefix="tpu_pbrt_fleet_selftest_")
    # the recorders share the virtual timeline (restored at exit), so
    # the exported trace is internally consistent for scope --check
    flight_prev = (FLIGHT._clock, FLIGHT._t0)
    FLIGHT.set_clock(clock)
    trace_prev = (TRACE._clock, TRACE._t0)
    TRACE.set_clock(clock)
    try:
        replicas = [
            LocalReplica(
                rid, clock=clock, seed=args.seed, chunk=args.chunk,
                spool_dir=os.path.join(tmp, rid),
            )
            for rid in ("r0", "r1")
        ]
        router = FleetRouter(
            replicas, clock=clock, spool_dir=os.path.join(tmp, "fleet"),
        )

        # -- scene affinity + residency warm hit ---------------------------
        j1 = router.submit(text=text, checkpoint_every=1, tenant="alice")
        rid1 = router.owner(j1)
        say(f"submitted {j1} -> {rid1}")
        router.drain_fleet()
        if router.poll(j1)["status"] != DONE:
            fails.append(f"{j1} did not finish: {router.poll(j1)}")
        j2 = router.submit(text=text, tenant="bob")
        rid2 = router.owner(j2)
        if rid2 != rid1:
            fails.append(
                f"affinity broken: same scene routed {rid1} then {rid2}"
            )
        router.drain_fleet()
        warm = router.replicas[rid1].service.residency.stats()
        if warm["scene_compiles"] != 1 or warm["hits"] < 1:
            fails.append(
                f"warm resubmit was not a residency hit on {rid1}: {warm}"
            )
        for j in (j1, j2):
            img = np.asarray(
                router.result(j).image, np.float32
            )
            if not np.array_equal(img, ref):
                fails.append(f"{j}: routed film differs from solo render")

        # -- fleet-edge shedding (knee clamped to force it) ----------------
        tight = FleetRouter(
            replicas, clock=clock,
            policy=FleetPolicy(knee_req_s=0.5, rate_window_s=2.0),
            spool_dir=os.path.join(tmp, "edge"),
        )
        admitted, shed = 0, 0
        for i in range(4):
            try:
                tight.submit(text=text, tenant="burst",
                             job_id=f"edge{i}")
                admitted += 1
            except ShedError as e:
                shed += 1
                if "fleet-edge" not in e.reason:
                    fails.append(f"edge shed carries wrong reason: {e.reason}")
        # knee 0.5 x 2 replicas x 2 s window = 2 admitted, then refusal
        if admitted != 2 or shed != 2 or tight.edge_sheds != 2:
            fails.append(
                f"edge shedding not deterministic: {admitted} admitted, "
                f"{shed} shed (counted {tight.edge_sheds})"
            )
        say(f"edge shed {shed}/4 over-knee submits")
        tight.drain_fleet()

        # -- kill-one failover: bit-identity from the spool ----------------
        jk = router.submit(text=text, checkpoint_every=1, tenant="alice")
        victim = router.owner(jk)
        survivor = "r1" if victim == "r0" else "r0"
        stepped = 0
        while router.poll(jk)["chunks_done"] < 2:
            if router.step() is None or stepped > 200:
                fails.append(f"{jk} made no progress pre-kill")
                break
            stepped += 1
        say(
            f"killing {victim} with {jk} at chunk "
            f"{router.poll(jk)['chunks_done']}"
        )
        moved = router.kill_replica(victim)
        if moved != [jk]:
            fails.append(f"failover moved {moved}, expected [{jk!r}]")
        if router.owner(jk) != survivor:
            fails.append(
                f"{jk} failed over to {router.owner(jk)}, "
                f"expected {survivor}"
            )
        router.drain_fleet()
        pk = router.poll(jk)
        if pk["status"] != DONE:
            fails.append(f"{jk} did not finish after failover: {pk}")
        else:
            img = np.asarray(router.result(jk).image, np.float32)
            if not np.array_equal(img, ref):
                fails.append(
                    "failover film differs bitwise from the undisturbed "
                    "solo render"
                )
            if pk["failovers"] != 1:
                fails.append(f"{jk} records {pk['failovers']} failovers")
        say(f"failover film bit-identical: {pk['status']}")

        if args.daemon_smoke:
            fails += _daemon_smoke(say, text, tmp)

        traced = TRACE.maybe_export()
        if traced:
            say(f"trace exported to {traced}")
    finally:
        FLIGHT._clock, FLIGHT._t0 = flight_prev
        TRACE._clock, TRACE._t0 = trace_prev

    line = {
        "selftest": "tpu_pbrt.fleet",
        "ok": not fails,
        "jobs": len(router.jobs),
        "routes": len(router.routes),
        "edge_sheds": tight.edge_sheds,
        "failovers": sum(r.failovers for r in router.jobs.values()),
        "clock_samples": clock.samples,
    }
    if fails:
        line["failures"] = fails
        for f in fails:
            say(f"FAIL: {f}")
    print(json.dumps(line))
    return 0 if not fails else 1


def _daemon_smoke(say, text, tmp) -> list:
    """One job through a real child JSONL daemon: submit with a router
    trace id, poll to done, drain verb, graceful shutdown."""
    from tpu_pbrt.fleet.daemon import DaemonReplica

    fails = []
    say("daemon smoke: spawning child serve daemon")
    rep = DaemonReplica(
        "d0", spool_dir=os.path.join(tmp, "d0"), chunk=256,
    )
    try:
        job = rep.submit(text=text, job_id="dj1", trace_id="t:dj1")
        deadline = 240
        import time

        t0 = time.monotonic()
        while rep.status(job) not in ("done", "failed", None):
            if time.monotonic() - t0 > deadline:
                fails.append("daemon job did not finish in time")
                break
            time.sleep(0.2)
        if rep.status(job) != "done":
            fails.append(f"daemon job ended {rep.status(job)!r}")
        ans = rep.drain()
        if not (ans.get("ok") and ans.get("draining")
                and ans.get("quiescent")):
            fails.append(f"daemon drain answered {ans}")
        code = rep.shutdown()
        if code != 0:
            fails.append(f"daemon exited {code}")
    finally:
        if rep.proc.poll() is None:
            rep.proc.kill()
    return fails


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.selftest or args.daemon_smoke:
        return selftest(args)
    build_arg_parser().print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
