"""tpu-fleet: replicated serve daemons behind a deterministic
failover router (ISSUE 20 tentpole — ROADMAP new direction #1).

One `RenderService` on one mesh cannot be the millions-of-users north
star. This package is the layer above it: a front-door **router**
spreading jobs across N serve **replicas** with

- **scene-affinity consistent hashing** — a resubmit of the same scene
  lands on the replica where the compiled scene is already resident
  (zero scene compiles, zero jit retraces on the warm path);
- **fleet-level SLO shedding at the edge** — the offered arrival rate
  is compared against `knee_req_s x healthy replicas` (the `--capacity`
  sweep's measured knee, PR 19) BEFORE any replica compiles anything;
- **drain/failover** — a replica whose `health` verb fires wedge or
  backoff-storm is drained; its jobs resume on another replica through
  the durable checkpoint-v4 spool, with a double-delivery dedup window
  so a job never renders twice.

Replicas come in two flavors behind one handle interface:
`LocalReplica` (a real in-process RenderService under an injected
clock — the deterministic-testing shape protocheck's FleetModel and
the load harness's `--replicas N` mode drive) and `DaemonReplica`
(a child `python -m tpu_pbrt.serve` JSONL daemon — real deployment).

Frontends: this library API and `python -m tpu_pbrt.fleet --selftest`.
"""

from tpu_pbrt.fleet.router import (
    KNEE_REQ_S,
    FleetPolicy,
    FleetRouter,
    LocalReplica,
    fleet_size,
)

__all__ = [
    "KNEE_REQ_S", "FleetPolicy", "FleetRouter", "LocalReplica",
    "fleet_size",
]
