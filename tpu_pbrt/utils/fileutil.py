"""File path utilities.

Capability match for pbrt-v3 src/core/fileutil.{h,cpp}: ResolveFilename
(scene-relative path resolution) and ReadFloatFile (whitespace/comment
tolerant float lists, used by RealisticCamera lens files and .spd spectra).
"""

from __future__ import annotations

import os
from typing import List


def resolve_filename(filename: str, scene_dir: str = ".") -> str:
    """Resolve a scene-file-relative path (pbrt ResolveFilename)."""
    if not filename or os.path.isabs(filename):
        return filename
    return os.path.join(scene_dir, filename)


def read_float_file(path: str) -> List[float]:
    """pbrt ReadFloatFile: all whitespace-separated floats, '#' comments."""
    out: List[float] = []
    with open(path) as f:
        for line in f:
            body = line.split("#", 1)[0]
            out.extend(float(t) for t in body.split())
    return out
